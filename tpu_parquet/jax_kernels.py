"""Device (JAX/XLA) decode kernels — the TPU compute path.

Each kernel is the device twin of a NumPy host kernel in ``tpu_parquet/kernels``;
the host versions are the correctness reference, these are what runs under ``jit``
on TPU.  The split follows SURVEY.md §7.2-P2: the *structure* of a stream (run
headers, delta block headers — metadata-sized, sequential varints) is parsed on the
host; the *bulk* transform (bit extraction, run expansion, prefix sums, gathers) is
a shape-static XLA program over the raw page bytes shipped to HBM.

Key trick shared by the RLE-hybrid and DELTA_BINARY_PACKED kernels: a vectorized
"extract w bits at bit-position p" primitive (`extract_bits`) where both p and w may
be per-value *arrays*.  Each value gathers the ≤5/≤9 bytes that can cover it,
combines them into a wide integer, shifts and masks.  This replaces the reference's
98 width-specialized unrolled functions (bitbacking32.go / bitpacking64.go) and its
value-at-a-time run loops (hybrid_decoder.go:81-113) with gathers the VPU executes
8x128 lanes at a time.

All functions here are jit-compatible with static output shapes: ``count`` and
padded run-table sizes are Python ints at trace time, so XLA sees fixed shapes and
the per-(page-geometry) executable is cached.  int64 work uses 32-bit lane pairs
where possible; full-width paths need 64-bit lanes, which every public entry
point enables for the duration of the call via ``scoped_x64`` (the global
``jax_enable_x64`` setting of the importing application is never modified).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# The device decode path needs 64-bit lanes (INT64 columns, byte offsets), but
# flipping ``jax_enable_x64`` process-wide at import time would change dtype
# semantics for any co-resident JAX program (a training pipeline importing this
# library).  Instead every public kernel and reader entry point is wrapped in
# ``scoped_x64`` below, which enters ``jax.enable_x64()`` only for the duration
# of the call: traces happen with 64-bit lanes on, returned arrays keep their
# 64-bit dtypes, and the caller's global x64 setting is never touched.

# the scoped x64 context manager moved between jax releases: newer jax exposes
# it as ``jax.enable_x64``, older releases only as ``jax.experimental.enable_x64``
# (same signature; accepts an optional bool).  Resolve once at import.
enable_x64 = getattr(jax, "enable_x64", None)
if enable_x64 is None:  # pragma: no cover - depends on installed jax
    from jax.experimental import enable_x64  # noqa: F401


def scoped_x64(fn):
    """Run ``fn`` with ``jax_enable_x64`` active, without touching global state.

    Applied to every public device-path entry point so that jit traces see
    64-bit dtypes while the importing application keeps its own x64 setting
    (the reference's int64 columns are not optional — hybrid_decoder.go,
    deltabp_decoder.go:176-333 are 64-bit paths).  Re-entrant: nesting under an
    already-active context (an outer decorated caller) is a cheap no-op flip.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with enable_x64():
            return fn(*args, **kwargs)

    return wrapper


def named_kernel(family):
    """Wrap a kernel so its traced ops carry a ``tpq.<family>`` name scope.

    The names land in the XLA HLO metadata, so a ``TPQ_XPROF`` device
    profile's op timeline is attributable to the SAME kernel families the
    completion-timing lane reports (snappy_resolve / unpack / gather /
    narrow / levels — device_reader._KERNEL_FAMILIES).  Pure trace-time
    metadata: zero runtime cost in the compiled executable.
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with jax.named_scope(f"tpq.{family}"):
                return fn(*args, **kwargs)

        return wrapper

    return deco


__all__ = [
    "scoped_x64",
    "named_kernel",
    "extract_bits",
    "unpack_bits",
    "expand_rle_hybrid",
    "expand_rle_hybrid_vw",
    "delta_reconstruct",
    "dict_gather",
    "dict_gather_bytes",
    "ragged_take",
    "levels_to_validity",
    "scatter_defined",
    "row_starts_from_rep",
    "plain_decode_fixed",
    "byte_stream_split_decode",
    "snappy_resolve",
]


# ---------------------------------------------------------------------------
# Bit extraction primitive
# ---------------------------------------------------------------------------

@scoped_x64
def extract_bits(buf: jax.Array, bit_pos: jax.Array, width: jax.Array, max_width: int):
    """Extract unsigned bit fields from an LSB-first byte stream.

    ``buf``      uint8[n] — must be padded with >= (max_width+14)//8 slack bytes
                 so the trailing gathers stay in bounds (host pads; see
                 ``jax_decode.pad_buffer``).
    ``bit_pos``  int32/int64[count] — starting bit of each field.
    ``width``    scalar or per-value array — field width in bits (<= max_width).
    ``max_width`` static upper bound on width; selects the gather footprint.

    Returns uint32[count] when max_width <= 32, else uint64[count].
    """
    bit_pos = bit_pos.astype(jnp.int64)
    byte0 = bit_pos >> 3
    shift = (bit_pos & 7).astype(jnp.uint32)
    nbytes = (max_width + 7 + 7) // 8  # widest field + worst-case 7-bit shift
    # bucketed decode shapes may carry tail positions past the real stream;
    # clamp the gather base so every lane stays in bounds (tail lanes read
    # garbage that callers mask or slice away)
    byte0 = jnp.minimum(byte0, max(buf.shape[0] - 9, 0))
    if max_width <= 25:
        # fits in one uint32 accumulation (25 + 7 = 32)
        acc = jnp.zeros(bit_pos.shape, dtype=jnp.uint32)
        for k in range(nbytes):
            b = buf[byte0 + k].astype(jnp.uint32)
            acc = acc | (b << jnp.uint32(8 * k))
        out = acc >> shift
        w = jnp.asarray(width, dtype=jnp.uint32)
        mask = jnp.where(
            w >= 32, jnp.uint32(0xFFFFFFFF), (jnp.uint32(1) << w) - jnp.uint32(1)
        )
        return out & mask
    if max_width <= 57:
        acc = jnp.zeros(bit_pos.shape, dtype=jnp.uint64)
        for k in range(nbytes):
            b = buf[byte0 + k].astype(jnp.uint64)
            acc = acc | (b << jnp.uint64(8 * k))
        out = acc >> shift.astype(jnp.uint64)
        w = jnp.asarray(width, dtype=jnp.uint64)
        mask = jnp.where(
            w >= 64,
            jnp.uint64(0xFFFFFFFFFFFFFFFF),
            (jnp.uint64(1) << w) - jnp.uint64(1),
        )
        out = out & mask
        return out if max_width > 32 else out.astype(jnp.uint32)
    # 58..64: the field may span 9 bytes; accumulate low 8 bytes then OR the
    # straggler's bits above (64 - shift).
    acc = jnp.zeros(bit_pos.shape, dtype=jnp.uint64)
    for k in range(8):
        b = buf[byte0 + k].astype(jnp.uint64)
        acc = acc | (b << jnp.uint64(8 * k))
    sh = shift.astype(jnp.uint64)
    out = acc >> sh
    b8 = buf[byte0 + 8].astype(jnp.uint64)
    # when shift == 0 the straggler contributes nothing (and << 64 is UB-ish);
    # mask it out explicitly.
    high = jnp.where(sh > 0, b8 << (jnp.uint64(64) - sh), jnp.uint64(0))
    out = out | high
    w = jnp.asarray(width, dtype=jnp.uint64)
    mask = jnp.where(
        w >= 64, jnp.uint64(0xFFFFFFFFFFFFFFFF), (jnp.uint64(1) << w) - jnp.uint64(1)
    )
    return out & mask


@named_kernel("unpack")
@scoped_x64
def unpack_bits(buf: jax.Array, width: int, count: int):
    """Device twin of kernels.bitpack.unpack: fixed-width LSB-first unpack."""
    if width == 0:
        dt = jnp.uint32 if width <= 32 else jnp.uint64
        return jnp.zeros(count, dtype=dt)
    pos = jnp.arange(count, dtype=jnp.int64) * width
    return extract_bits(buf, pos, width, width)


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid expansion
# ---------------------------------------------------------------------------

@named_kernel("unpack")
@scoped_x64
def expand_rle_hybrid(
    buf: jax.Array,
    run_ends: jax.Array,
    run_is_rle: jax.Array,
    run_values: jax.Array,
    run_bit_starts: jax.Array,
    width: int,
    count: int,
    n_valid=None,
):
    """Expand a parsed RLE/bit-packed hybrid stream to ``count`` values.

    ``count`` may be a *bucketed* static size larger than the stream's real
    value count; pass the real count as the traced scalar ``n_valid`` and the
    tail lanes come back zeroed (so e.g. deferred dictionary-index range
    checks never see tail garbage).  One executable then serves every stream
    whose count lands in the same bucket.

    Host side (jax_decode.parse_hybrid_device) walks the run headers — a few bytes
    per run — and hands over per-run metadata (padded to a static run count):

    ``run_ends``       int64[R] cumulative value count at the end of each run
                       (padding runs repeat the final end).
    ``run_is_rle``     bool[R]
    ``run_values``     uint32[R] the repeated value for RLE runs (0 for BP).
    ``run_bit_starts`` int64[R] bit offset of the run's packed payload in ``buf``,
                       minus run_start*width so position math is uniform (0 for RLE).
    ``width``          static bit width of the stream.

    Replaces hybridDecoder.next (hybrid_decoder.go:81-113): every output position
    finds its run with one searchsorted, then either broadcasts the RLE value or
    bit-extracts its element — no sequential state.
    """
    pos = jnp.arange(count, dtype=jnp.int64)
    r = jnp.searchsorted(run_ends, pos, side="right").astype(jnp.int32)
    r = jnp.minimum(r, run_ends.shape[0] - 1)
    is_rle = run_is_rle[r]
    rle_val = run_values[r]
    if width == 0:
        return jnp.zeros(count, dtype=jnp.uint32)
    bit_pos = run_bit_starts[r] + pos * width
    # clamp BP gathers for RLE positions to 0 so they stay in bounds
    bit_pos = jnp.where(is_rle, 0, bit_pos)
    bp_val = extract_bits(buf, bit_pos, width, width)
    out = jnp.where(is_rle, rle_val.astype(bp_val.dtype), bp_val)
    if n_valid is not None:
        out = jnp.where(pos < n_valid, out, jnp.zeros((), dtype=out.dtype))
    return out


@named_kernel("unpack")
@scoped_x64
def expand_rle_hybrid_vw(
    buf: jax.Array,
    run_ends: jax.Array,
    run_is_rle: jax.Array,
    run_values: jax.Array,
    run_bit_starts: jax.Array,
    run_widths: jax.Array,
    max_width: int,
    count: int,
    n_valid=None,
):
    """Variable-width :func:`expand_rle_hybrid`: each run carries its own bit
    width (``run_widths`` uint32[R], 0 for RLE runs).

    A dictionary-encoded column chunk is one hybrid stream per page, and the
    index width legally GROWS page to page as the dictionary fills (pyarrow
    writes exactly that).  Treating the width as per-run data instead of a
    static lets one executable decode the whole chunk's merged run table —
    per-value dynamic widths are what :func:`extract_bits` is built for.
    ``max_width`` is the static gather-footprint bound (round it to a
    multiple of 8 to share executables).
    """
    pos = jnp.arange(count, dtype=jnp.int64)
    r = jnp.searchsorted(run_ends, pos, side="right").astype(jnp.int32)
    r = jnp.minimum(r, run_ends.shape[0] - 1)
    is_rle = run_is_rle[r]
    rle_val = run_values[r]
    w = run_widths[r].astype(jnp.int64)
    bit_pos = run_bit_starts[r] + pos * w
    bit_pos = jnp.where(is_rle, 0, bit_pos)
    bp_val = extract_bits(buf, bit_pos, w.astype(jnp.uint32), max_width)
    out = jnp.where(is_rle, rle_val.astype(bp_val.dtype), bp_val)
    if n_valid is not None:
        out = jnp.where(pos < n_valid, out, jnp.zeros((), dtype=out.dtype))
    return out


# ---------------------------------------------------------------------------
# DELTA_BINARY_PACKED reconstruction
# ---------------------------------------------------------------------------

@named_kernel("unpack")
@scoped_x64
def delta_reconstruct(
    buf: jax.Array,
    first_value: jax.Array,
    mini_bit_starts: jax.Array,
    mini_widths: jax.Array,
    mini_min_delta: jax.Array,
    values_per_mini: int,
    count: int,
    bits: int,
    max_width: int | None = None,
):
    """Reconstruct a DELTA_BINARY_PACKED column from packed miniblock bytes.

    Host (jax_decode.parse_delta_device) reads the block/miniblock headers — a
    handful of varints per 128 values — and passes per-*miniblock* tables:

    ``mini_bit_starts`` int64[M] bit offset of each miniblock's packed deltas.
    ``mini_widths``     int32[M] per-miniblock delta bit width (<= bits).
    ``mini_min_delta``  int64/uint64[M] the block's min_delta (repeated per mini).

    Device does: per-delta dynamic-width bit extract → + min_delta → cumsum with
    the zigzag first value as seed.  Arithmetic wraps modulo 2**bits via unsigned
    lanes, matching the Go reference's overflow semantics (deltabp_decoder.go).
    Replaces the value-at-a-time loops of deltabp_decoder.go:13-333.

    ``max_width`` (static) bounds the per-delta gather footprint: passing the
    stream's real max miniblock width cuts the byte gathers from 9 to
    ceil((w+14)/8) for typical small-delta data.
    """
    n_deltas = count - 1
    out_u = jnp.uint32 if bits == 32 else jnp.uint64
    out_s = jnp.int32 if bits == 32 else jnp.int64
    first_u = jnp.asarray(first_value).astype(jnp.int64).astype(out_u)
    if n_deltas <= 0:
        return jnp.full((count,), first_u, dtype=out_u).astype(out_s)
    i = jnp.arange(n_deltas, dtype=jnp.int64)
    m = i // values_per_mini
    within = i % values_per_mini
    w = mini_widths[m]
    bit_pos = mini_bit_starts[m] + within * w.astype(jnp.int64)
    mw = bits if max_width is None else max(int(max_width), 1)
    raw = extract_bits(buf, bit_pos, w, mw).astype(out_u)
    deltas = raw + mini_min_delta[m].astype(out_u)
    acc = jnp.cumsum(deltas, dtype=out_u)
    vals = jnp.concatenate([first_u[None], first_u + acc])
    return vals.astype(out_s)


# ---------------------------------------------------------------------------
# Dictionary / ragged gathers
# ---------------------------------------------------------------------------

@named_kernel("gather")
@scoped_x64
def dict_gather(dictionary: jax.Array, indices: jax.Array):
    """Fixed-width dictionary expansion (type_dict.go:10-60 read path).

    Use only for integer dictionaries; float dictionaries must go through
    :func:`dict_gather_bytes` — TPU emulates f64 as float32 pairs, f64-typed
    gathers can round, and XLA's X64-elimination pass implements bitcasts *into*
    wide types from u8 rows but not out of them.
    """
    return jnp.take(dictionary, indices.astype(jnp.int32), axis=0)


@named_kernel("gather")
@scoped_x64
def dict_gather_bytes(dict_u8_rows: jax.Array, indices: jax.Array, dtype: str):
    """Gather dictionary rows as raw bytes, then bitcast into ``dtype``.

    ``dict_u8_rows`` is uint8[K, itemsize] (a free numpy view host-side).  The
    byte gather moves bits verbatim — NaN payloads, -0.0, subnormals survive —
    and the final u8[...,itemsize]→dtype bitcast is the pattern the TPU X64
    rewriter supports (same as plain_decode_fixed).
    """
    rows = jnp.take(dict_u8_rows, indices.astype(jnp.int32), axis=0)
    n, total = rows.shape
    if dtype == "float64":
        # uint32 word pairs, not f64 — see plain_decode_fixed
        return jax.lax.bitcast_convert_type(
            rows.reshape(n, 2, 4), jnp.uint32
        ).reshape(n, 2)
    dt = _PLAIN_DTYPES[dtype]
    itemsize = jnp.dtype(dt).itemsize
    if total == itemsize:
        return jax.lax.bitcast_convert_type(rows, dt).reshape(n)
    # multi-word values (e.g. INT96 as 3×uint32): keep the trailing word axis
    return jax.lax.bitcast_convert_type(
        rows.reshape(n, total // itemsize, itemsize), dt
    ).reshape(n, total // itemsize)


@named_kernel("gather")
@scoped_x64
def ragged_take(
    offsets: jax.Array, heap: jax.Array, indices: jax.Array, out_heap_size: int
):
    """Gather rows of a ragged (offsets, heap) byte column — string dict decode.

    ``out_heap_size`` is static (host computes sum of selected lengths).  Returns
    (new_offsets int64[m+1], new_heap uint8[out_heap_size]).  Output byte j maps to
    output row r = searchsorted(new_offsets, j) and source byte
    src_start[r] + (j - new_start[r]) — two gathers, no per-row loop.
    """
    idx = indices.astype(jnp.int64)
    lens = offsets[idx + 1] - offsets[idx]
    new_off = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int64), jnp.cumsum(lens, dtype=jnp.int64)]
    )
    j = jnp.arange(out_heap_size, dtype=jnp.int64)
    r = jnp.searchsorted(new_off, j, side="right") - 1
    r = jnp.clip(r, 0, idx.shape[0] - 1)
    src = offsets[idx[r]] + (j - new_off[r])
    src = jnp.clip(src, 0, heap.shape[0] - 1) if heap.shape[0] else src * 0
    new_heap = heap[src] if heap.shape[0] else jnp.zeros(0, dtype=jnp.uint8)
    return new_off, new_heap


# ---------------------------------------------------------------------------
# Dremel level reconstruction (prefix scans)
# ---------------------------------------------------------------------------

@named_kernel("levels")
@scoped_x64
def levels_to_validity(def_levels: jax.Array, max_def: int):
    """validity[i] = slot i holds a real leaf value (def == max_def)."""
    return def_levels == max_def


@scoped_x64
def scatter_defined(values: jax.Array, validity: jax.Array, fill):
    """Expand dense defined values to one-per-slot with ``fill`` at null slots.

    The data-parallel replacement for the reference's assembly loop
    (data_store.go:262-309): position of slot i inside ``values`` is the exclusive
    prefix count of validity — one cumsum + one gather.
    """
    vidx = jnp.cumsum(validity.astype(jnp.int32)) - 1
    vidx = jnp.clip(vidx, 0, max(values.shape[0] - 1, 0))
    if values.shape[0] == 0:
        return jnp.full(validity.shape, fill, dtype=values.dtype)
    expanded = jnp.take(values, vidx, axis=0)
    fill_arr = jnp.asarray(fill, dtype=values.dtype)
    return jnp.where(
        validity.reshape(validity.shape + (1,) * (values.ndim - 1)),
        expanded,
        fill_arr,
    )


@named_kernel("levels")
@scoped_x64
def row_starts_from_rep(rep_levels: jax.Array):
    """Row-boundary mask from repetition levels: a slot with rep==0 starts a row.

    row_index = inclusive prefix count of starts - 1; the scan that replaces the
    reference's getNextData row walk (schema.go:216-312).
    """
    starts = rep_levels == 0
    row_index = jnp.cumsum(starts.astype(jnp.int64)) - 1
    return starts, row_index


# ---------------------------------------------------------------------------
# PLAIN / BYTE_STREAM_SPLIT
# ---------------------------------------------------------------------------

_PLAIN_DTYPES = {
    "int32": jnp.int32,
    "int64": jnp.int64,
    "uint32": jnp.uint32,
    "float32": jnp.float32,
    "float64": jnp.float64,
}


@named_kernel("plain")
@scoped_x64
def plain_decode_fixed(buf: jax.Array, dtype: str, count: int):
    """PLAIN decode of a fixed-width type: reshape + bitcast, zero compute.

    (type_int32.go / type_int64.go / type_float.go / type_double.go read paths.)

    DOUBLE columns return uint32[count, 2] little-endian word pairs, NOT f64:
    TPU emulates f64 as float32 pairs (~48 mantissa bits), so a materialized f64
    array silently rounds the low bits of real data.  int64 emulation is exact
    (true 32-bit word pairs), so INT64 stays native.  Host-side view back to f64
    is free (DeviceColumnData.to_host).
    """
    if dtype == "float64":
        raw = buf[: count * 8].reshape(count, 2, 4)
        return jax.lax.bitcast_convert_type(raw, jnp.uint32).reshape(count, 2)
    dt = _PLAIN_DTYPES[dtype]
    nbytes = jnp.dtype(dt).itemsize
    raw = buf[: count * nbytes].reshape(count, nbytes)
    return jax.lax.bitcast_convert_type(raw, dt).reshape(count)


@named_kernel("plain")
@scoped_x64
def byte_stream_split_decode(buf: jax.Array, dtype: str, count: int):
    """BYTE_STREAM_SPLIT: de-interleave K byte streams then bitcast.

    DOUBLE returns uint32[count, 2] word pairs (see plain_decode_fixed).
    """
    if dtype == "float64":
        mat = buf[: count * 8].reshape(8, count).T.reshape(count, 2, 4)
        return jax.lax.bitcast_convert_type(mat, jnp.uint32).reshape(count, 2)
    dt = _PLAIN_DTYPES[dtype]
    nbytes = jnp.dtype(dt).itemsize
    mat = buf[: count * nbytes].reshape(nbytes, count).T
    return jax.lax.bitcast_convert_type(mat, dt).reshape(count)


@named_kernel("snappy_resolve")
def snappy_resolve(ends, asrc, offs, islit, *, out_pad: int, iters: int):
    """Resolve snappy op tables into a per-output-byte SOURCE MAP.

    The shared device half of every compressed-shipping route (PLAIN
    fixed-width, narrow+snappy, byte-array heaps, dictionary tables — see
    ``ship.py``): the host's tag walk (``native.snappy_plan``, packed by
    ``device_reader._plan_snappy_ops``) describes each op's output extent;
    this maps every position of the decompressed OUTPUT SPACE to the staged
    buffer index holding its byte, without materializing the output:

    1. per output byte, find its op (one searchsorted over ``ends``) and
       compute a source: literal bytes point into the staged compressed
       stream (>= 0); copy bytes encode their output-space source as
       ``-(pos)-1`` using the periodic form
       ``dst_start - offset + (i mod offset)``, which maps overlapping
       (RLE-style) copies straight past their own op;
    2. resolve copy chains by pointer doubling: ``iters`` rounds of
       ``S = where(S >= 0, S, S[-S-1])`` — after ceil(log2(depth)) rounds
       every byte points at a literal (the host computed the exact max
       chain depth during the tag walk, so ``iters`` is a static bound,
       no syncs).

    All math is int32 (planners enforce the 2 GiB ceiling); positions past
    the real output resolve through padded literal ops (source 0).  Returns
    int32[out_pad] of staged-buffer byte indices.  Traced inside consuming
    jits — not jitted here.
    """
    n_ops = ends.shape[0]
    j = jnp.arange(out_pad, dtype=jnp.int32)
    op = jnp.clip(jnp.searchsorted(ends, j, side="right").astype(jnp.int32),
                  0, n_ops - 1)
    start = jnp.where(op > 0, ends[jnp.maximum(op - 1, 0)], 0)
    within = j - start
    S = jnp.where(
        islit[op] != 0,
        asrc[op] + within,
        -(asrc[op] + within % jnp.maximum(offs[op], 1)) - 1,
    )
    for _ in range(iters):
        t = jnp.clip(-S - 1, 0, out_pad - 1)
        S = jnp.where(S >= 0, S, S[t])
    return S
