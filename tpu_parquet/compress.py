"""Page-block compression codec registry.

Equivalent of the reference's compress.go:16-187: built-in codecs
{UNCOMPRESSED, SNAPPY, GZIP, ZSTD} plus a thread-safe, user-pluggable registry
(`register_codec`, the extension hook compress.go exposes as
``RegisterBlockCompressor``).  Decompression validates the declared uncompressed
size, which is the first line of defense against decompression bombs (mirrors
``newBlockReader``, compress.go:131-152).

SNAPPY uses the native C++ codec (tpu_parquet/native/snappy.cpp) with a pure-Python
raw-snappy implementation as fallback; GZIP uses stdlib zlib; ZSTD uses the
``zstandard`` module when present.

Thread-safety contract: ``decompress_block``/``compress_block`` on a
registered codec instance may be called CONCURRENTLY from the prefetch
pipeline's worker threads (tpu_parquet/pipeline.py).  The built-ins satisfy
it (stateless, or per-thread contexts — see ZstdCompressor); codecs plugged
in via ``register_codec`` must too.
"""

from __future__ import annotations

from .errors import ParquetError

import gzip as _gzip
import io
import threading
import zlib
from typing import Callable, Optional

from .format import CompressionCodec

try:
    import zstandard as _zstd
except ImportError:  # pragma: no cover - present in target image
    _zstd = None

from . import native as _native


class CompressionError(ParquetError):
    pass


class BlockCompressor:
    """Interface for page-block codecs (compress.go:24-27)."""

    def compress_block(self, block: bytes) -> bytes:
        raise NotImplementedError

    def decompress_block(self, block: bytes, uncompressed_size: int) -> bytes:
        raise NotImplementedError


class PlainCompressor(BlockCompressor):
    def compress_block(self, block: bytes) -> bytes:
        return bytes(block)

    def decompress_block(self, block: bytes, uncompressed_size: int) -> bytes:
        return bytes(block)


# ---------------------------------------------------------------------------
# Snappy (raw format) — native C++ preferred, pure-Python fallback
# ---------------------------------------------------------------------------

def _py_snappy_decompress(data: bytes, max_size: int = -1) -> bytes:
    """Pure-Python raw-snappy decoder (same format as native/snappy.cpp)."""
    pos = 0
    n = len(data)
    # uvarint header
    expect = 0
    shift = 0
    while True:
        if pos >= n:
            raise CompressionError("snappy: truncated length header")
        b = data[pos]
        pos += 1
        expect |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 28:
            raise CompressionError("snappy: length varint too long")
    if 0 <= max_size < expect:
        # bomb guard: stream claims more than the page header declared
        raise CompressionError(
            f"snappy stream claims {expect} bytes, page declared {max_size}"
        )
    out = bytearray()
    while pos < n:
        if len(out) > expect:
            # bomb guard inside the loop: copy ops amplify ~21x per input
            # byte, so waiting for the post-hoc length check would allocate
            # the whole bomb first
            raise CompressionError(
                f"snappy: output exceeds declared {expect} bytes"
            )
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                extra = ln - 59
                if pos + extra > n:
                    raise CompressionError("snappy: truncated literal length")
                ln = int.from_bytes(data[pos : pos + extra], "little")
                pos += extra
            ln += 1
            if pos + ln > n:
                raise CompressionError("snappy: truncated literal")
            out += data[pos : pos + ln]
            pos += ln
        else:
            if kind == 1:
                if pos >= n:
                    raise CompressionError("snappy: truncated copy")
                ln = ((tag >> 2) & 7) + 4
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:
                if pos + 2 > n:
                    raise CompressionError("snappy: truncated copy")
                ln = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 2], "little")
                pos += 2
            else:
                if pos + 4 > n:
                    raise CompressionError("snappy: truncated copy")
                ln = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise CompressionError("snappy: copy offset out of range")
            if offset >= ln:
                start = len(out) - offset
                out += out[start : start + ln]
            else:
                for _ in range(ln):
                    out.append(out[-offset])
    if len(out) != expect:
        raise CompressionError(
            f"snappy: declared {expect} bytes, produced {len(out)}"
        )
    return bytes(out)


def _py_snappy_compress(data: bytes) -> bytes:
    """Literal-only raw snappy (valid but uncompressed; fallback path only)."""
    out = bytearray()
    n = len(data)
    v = n
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    pos = 0
    while pos < n or (n == 0 and pos == 0 and False):
        ln = min(n - pos, 1 << 16)
        if ln == 0:
            break
        m = ln - 1
        if m < 60:
            out.append(m << 2)
        else:
            out.append(62 << 2)
            out += m.to_bytes(3, "little")
        out += data[pos : pos + ln]
        pos += ln
    return bytes(out)


class SnappyCompressor(BlockCompressor):
    def compress_block(self, block: bytes) -> bytes:
        if _native.available():
            return _native.snappy_compress(block)  # input: any buffer
        return _py_snappy_compress(bytes(block))

    def decompress_block(self, block: bytes, uncompressed_size: int):
        # returns bytes OR a uint8 numpy array (bytes-like, zero-copy native
        # path) — consumers must compare/concatenate by content, not type
        try:
            if _native.available():
                # no bytes() copy: the native wrapper takes any contiguous
                # buffer, and returns a uint8 array (not bytes) so the
                # output isn't copied either
                return _native.snappy_decompress(
                    block, max_size=max(uncompressed_size, 0)
                )
            return _py_snappy_decompress(
                bytes(block), max_size=max(uncompressed_size, 0)
            )
        except ValueError as e:
            raise CompressionError(str(e)) from e


class GzipCompressor(BlockCompressor):
    def compress_block(self, block: bytes) -> bytes:
        buf = io.BytesIO()
        with _gzip.GzipFile(fileobj=buf, mode="wb", mtime=0) as g:
            g.write(block)
        return buf.getvalue()

    def decompress_block(self, block: bytes, uncompressed_size: int) -> bytes:
        try:
            # wbits=47 accepts both gzip and zlib wrappers
            d = zlib.decompressobj(wbits=47)
            out = d.decompress(bytes(block), max(uncompressed_size, 0) + 1)
            # bomb guard: if output already exceeds the declared size, or input
            # remains unconsumed, fail *before* inflating the rest via flush()
            if len(out) > uncompressed_size or d.unconsumed_tail:
                raise CompressionError(
                    f"gzip page inflates past declared {uncompressed_size} bytes"
                )
            out += d.flush()
            return out
        except zlib.error as e:
            raise CompressionError(f"gzip: {e}") from e


class ZstdCompressor(BlockCompressor):
    """zstd codec with PER-THREAD compressor/decompressor objects.

    zstandard's context objects are explicitly not safe for concurrent use
    of the same method from multiple threads, and the prefetch pipeline
    (tpu_parquet/pipeline.py) decompresses several chunks' pages on a pool
    against ONE registered codec instance — so each thread lazily builds its
    own pair.  The other built-ins are audited stateless: Plain copies,
    Snappy calls a pure function (native or python), Gzip constructs a fresh
    decompressobj per call.
    """

    def __init__(self, level: int = 3):
        if _zstd is None:
            raise CompressionError("zstandard module not available")
        self._level = level
        self._tls = threading.local()

    def _ctx(self):
        t = self._tls
        if not hasattr(t, "c"):
            t.c = _zstd.ZstdCompressor(level=self._level)
            t.d = _zstd.ZstdDecompressor()
        return t

    def compress_block(self, block: bytes) -> bytes:
        return self._ctx().c.compress(bytes(block))

    def decompress_block(self, block: bytes, uncompressed_size: int) -> bytes:
        try:
            return self._ctx().d.decompress(
                bytes(block), max_output_size=max(uncompressed_size, 1)
            )
        except _zstd.ZstdError as e:
            raise CompressionError(f"zstd: {e}") from e


# ---------------------------------------------------------------------------
# Registry (compress.go:16-27, 160-187)
# ---------------------------------------------------------------------------

_registry_lock = threading.RLock()
_registry: dict[int, BlockCompressor] = {}


def register_codec(codec: int, compressor: BlockCompressor) -> None:
    """Public extension hook, mirroring ``RegisterBlockCompressor``."""
    with _registry_lock:
        _registry[int(codec)] = compressor


def get_codec(codec: int) -> BlockCompressor:
    if codec is None:  # absent thrift field (fuzz: file_reader-7c7d4874355f)
        raise CompressionError("column chunk missing compression codec")
    with _registry_lock:
        c = _registry.get(int(codec))
    if c is None:
        try:
            name = CompressionCodec(codec).name
        except ValueError:
            name = str(codec)
        raise CompressionError(f"unsupported compression codec {name}")
    return c


def registered_codecs() -> list[int]:
    with _registry_lock:
        return sorted(_registry)


def compress_block(block: bytes, codec: int) -> bytes:
    return get_codec(codec).compress_block(block)


def decompress_block(block: bytes, codec: int, uncompressed_size: int):
    """Decompress and validate the size declared in the page header.

    Returns a bytes-LIKE buffer: ``bytes`` from most codecs, a uint8 numpy
    array from the zero-copy native snappy path.  All in-tree consumers
    slice/view via the buffer protocol.

    Mirrors newBlockReader (compress.go:131-152): a mismatch between the header's
    uncompressed_page_size and actual output is corruption, not a warning.
    """
    if uncompressed_size < 0:
        raise CompressionError(f"negative uncompressed size {uncompressed_size}")
    out = get_codec(codec).decompress_block(block, uncompressed_size)
    if len(out) != uncompressed_size:
        raise CompressionError(
            f"page declared {uncompressed_size} uncompressed bytes, got {len(out)}"
        )
    return out


register_codec(CompressionCodec.UNCOMPRESSED, PlainCompressor())
register_codec(CompressionCodec.SNAPPY, SnappyCompressor())
register_codec(CompressionCodec.GZIP, GzipCompressor())
if _zstd is not None:
    register_codec(CompressionCodec.ZSTD, ZstdCompressor())
