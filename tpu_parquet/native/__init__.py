"""Native (C++) runtime pieces, loaded via ctypes.

Built lazily with g++ the first time they're needed (no pip/cmake dependency at
import time); the shared object is cached next to the sources and rebuilt when any
source file changes (content-hash stamp).  Everything here is optional: each consumer
has a pure-Python fallback, so the framework still works — slower — without a C++
toolchain.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["snappy.cpp", "meta_parse.cpp"]
_LIB_BASENAME = "_libtpq_native.so"

_lock = threading.Lock()
_lib = None
_load_failed = False


def _source_hash() -> str:
    h = hashlib.sha256()
    for src in _SOURCES:
        with open(os.path.join(_DIR, src), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _build(lib_path: str) -> None:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-march=native",
        "-o", lib_path,
    ] + [os.path.join(_DIR, s) for s in _SOURCES]
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)


def load():
    """Return the ctypes native library, building it if needed; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            stamp = _source_hash()
            lib_path = os.path.join(_DIR, f"{_LIB_BASENAME}.{stamp}")
            if not os.path.exists(lib_path):
                _build(lib_path)
                # drop stale builds
                for f in os.listdir(_DIR):
                    if f.startswith(_LIB_BASENAME) and not f.endswith(stamp):
                        try:
                            os.unlink(os.path.join(_DIR, f))
                        except OSError:
                            pass
            lib = ctypes.CDLL(lib_path)
            lib.tpq_snappy_uncompressed_length.restype = ctypes.c_longlong
            lib.tpq_snappy_uncompressed_length.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.tpq_snappy_decompress.restype = ctypes.c_int
            lib.tpq_snappy_decompress.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.tpq_snappy_max_compressed_length.restype = ctypes.c_size_t
            lib.tpq_snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
            lib.tpq_snappy_compress.restype = ctypes.c_longlong
            lib.tpq_snappy_compress.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ]
            c_ll = ctypes.c_longlong
            p = ctypes.POINTER
            lib.tpq_delta_ba_stitch.restype = c_ll
            lib.tpq_delta_ba_stitch.argtypes = [
                p(ctypes.c_longlong), p(ctypes.c_longlong), p(ctypes.c_uint8),
                p(ctypes.c_longlong), p(ctypes.c_uint8), c_ll,
            ]
            lib.tpq_bytearray_walk.restype = c_ll
            lib.tpq_bytearray_walk.argtypes = [
                ctypes.c_char_p, c_ll, c_ll, p(ctypes.c_longlong),
                p(ctypes.c_uint8),
            ]
            lib.tpq_bytearray_lengths.restype = c_ll
            lib.tpq_bytearray_lengths.argtypes = [
                ctypes.c_char_p, c_ll, c_ll, c_ll, p(ctypes.c_uint32),
            ]
            lib.tpq_page_header.restype = c_ll
            lib.tpq_page_header.argtypes = [
                ctypes.c_char_p, c_ll, c_ll, p(ctypes.c_longlong),
            ]
            lib.tpq_delta_meta.restype = c_ll
            lib.tpq_delta_meta.argtypes = [
                ctypes.c_char_p, c_ll, c_ll, p(ctypes.c_longlong),
                p(ctypes.c_longlong), p(ctypes.c_int32), p(ctypes.c_uint64),
                c_ll,
            ]
            lib.tpq_snappy_plan.restype = c_ll
            lib.tpq_snappy_plan.argtypes = [
                ctypes.c_char_p, c_ll, c_ll,
                p(c_ll), p(c_ll), p(ctypes.c_uint8), c_ll,
                p(c_ll), c_ll, p(c_ll),
            ]
            lib.tpq_dict_build_bytes.restype = c_ll
            lib.tpq_dict_build_bytes.argtypes = [
                p(c_ll), ctypes.c_char_p, c_ll, c_ll,
                p(ctypes.c_int32), c_ll, p(ctypes.c_uint32), p(c_ll),
            ]
            lib.tpq_dict_build_fixed.restype = c_ll
            lib.tpq_dict_build_fixed.argtypes = [
                ctypes.c_char_p, c_ll, c_ll, c_ll,
                p(ctypes.c_int32), c_ll, p(ctypes.c_uint32), p(c_ll),
            ]
            lib.tpq_int_minmax.restype = None
            lib.tpq_int_minmax.argtypes = [
                ctypes.c_char_p, c_ll, c_ll, ctypes.c_int, p(c_ll),
            ]
            lib.tpq_int_truncate.restype = None
            lib.tpq_int_truncate.argtypes = [
                ctypes.c_char_p, c_ll, c_ll, ctypes.c_int, ctypes.c_uint64,
                ctypes.c_int, ctypes.c_void_p,
            ]
            lib.tpq_hybrid_meta.restype = c_ll
            # output pointers as c_void_p: the wrapper passes raw addresses
            # into ONE arena allocation — per-call POINTER() casts on the
            # hottest wrapper (once per page per stream) cost as much as the
            # C walk itself
            lib.tpq_bp_pack.restype = None
            lib.tpq_bp_pack.argtypes = [
                p(ctypes.c_uint64), c_ll, c_ll, ctypes.c_void_p,
            ]
            lib.tpq_hybrid_meta.argtypes = [
                ctypes.c_char_p, c_ll, c_ll, c_ll, c_ll,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, c_ll, ctypes.c_void_p,
                c_ll, ctypes.c_void_p,
                c_ll, ctypes.c_uint64, ctypes.c_void_p,
            ]
            lib.tpq_ragged_take.restype = None
            lib.tpq_ragged_take.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, c_ll,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
            lib.tpq_hybrid_expand.restype = None
            lib.tpq_hybrid_expand.argtypes = [
                ctypes.c_char_p, c_ll,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, c_ll, ctypes.c_int, c_ll, ctypes.c_void_p,
            ]
            _lib = lib
        except Exception:
            _load_failed = True
    return _lib


def _buf_arg(buf):
    """ctypes argument for a read-only byte buffer: ``bytes`` passes through
    (fast path, no conversion); any other contiguous buffer-protocol object
    (numpy views of decompressed pages, memoryviews of mmap'd chunks) passes
    as a raw pointer with ZERO copies.  The caller's reference keeps the
    memory alive for the duration of the call."""
    if type(buf) is bytes:
        return buf
    import numpy as np

    a = np.frombuffer(buf, np.uint8)
    return ctypes.c_char_p(a.ctypes.data)


def snappy_decompress(data, max_size: int = -1):
    """Raw-snappy decompress; returns a uint8 numpy array (NOT bytes — the
    extra ``tobytes`` copy was ~1 s of a 100M-row scan's host phase; every
    downstream consumer slices/views, so the buffer-protocol array is a
    drop-in)."""
    import numpy as np

    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    dptr = _buf_arg(data)
    n = lib.tpq_snappy_uncompressed_length(dptr, len(data))
    if n < 0:
        raise ValueError("malformed snappy data: bad length header")
    if 0 <= max_size < n:
        # bomb guard: the stream's own varint claims more than the page header
        # declared — reject BEFORE allocating the output buffer
        raise ValueError(
            f"snappy stream claims {n} bytes, page declared {max_size}"
        )
    # np.empty skips create_string_buffer's zero-init memset (decompress
    # overwrites every byte on success; failures discard the buffer).
    # +16 slack bytes: tpq_snappy_decompress's short-op fast paths do blind
    # 16-byte stores (see its contract); the logical output is out[:n].
    out = np.empty(n + 16, dtype=np.uint8)
    rc = lib.tpq_snappy_decompress(
        dptr, len(data), out.ctypes.data_as(ctypes.c_char_p), n
    )
    if rc != 0:
        raise ValueError(f"malformed snappy data (error {rc})")
    return out[:n]


def snappy_compress(data) -> bytes:
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    import numpy as np

    cap = lib.tpq_snappy_max_compressed_length(len(data))
    # np.empty (no zero-init) + _buf_arg input: the create_string_buffer
    # memset and the callers' bytes() copies were ~15% of a plain-int64
    # page write
    out = np.empty(cap, dtype=np.uint8)
    n = lib.tpq_snappy_compress(_buf_arg(data), len(data),
                                out.ctypes.data_as(ctypes.c_char_p))
    if n < 0:
        raise ValueError("snappy compression failed")
    # uint8-array out: the parts-based page writer appends buffers and
    # never concatenates, so the tobytes copy (was ~10% of a plain page
    # write) is pure waste.  NOTE for consumers: never += this into a
    # bytearray via fallback paths — numpy broadcasting hazard.
    return out[:n]


def delta_meta(buf: bytes, pos: int, cap: int):
    """Walk DELTA_BINARY_PACKED headers natively (meta_parse.cpp).

    Returns (header, starts, widths, mins) on success where header is
    int64[6] = [block_size, minis_per_block, total, first_value, consumed,
    n_minis] and the arrays are trimmed to n_minis — or a negative error code
    (int) the caller maps to its DeltaError messages.  Returns None when the
    native library is unavailable (caller falls back to the Python walk).
    """
    import numpy as np

    lib = load()
    if lib is None:
        return None
    header = np.zeros(6, dtype=np.int64)
    starts = np.empty(cap, dtype=np.int64)
    widths = np.empty(cap, dtype=np.int32)
    mins = np.empty(cap, dtype=np.uint64)
    pll = ctypes.POINTER(ctypes.c_longlong)
    rc = lib.tpq_delta_meta(
        _buf_arg(buf), len(buf), pos,
        header.ctypes.data_as(pll),
        starts.ctypes.data_as(pll),
        widths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        mins.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        cap,
    )
    if rc < 0:
        return int(rc)
    n = int(header[5])
    return header, starts[:n], widths[:n], mins[:n]


def hybrid_meta(buf: bytes, n: int, pos: int, width: int, count: int, cap: int,
                want_max: bool = False, eq_target: "int | None" = None):
    """Walk RLE/bit-packed hybrid run headers natively (meta_parse.cpp).

    Returns (n_runs, consumed, ends, kinds, vals, starts, max_value,
    eq_count) trimmed to n_runs (max_value is None unless want_max; eq_count
    — the number of stream values equal to ``eq_target`` — is None unless
    eq_target is given), a negative error code (int; -10 = cap exceeded,
    retry bigger), or None when the native library is unavailable.
    """
    import numpy as np

    lib = load()
    if lib is None:
        return None
    # ONE arena for every output (header scalars + 4 run tables), addressed
    # by raw pointer arithmetic: the previous 7 allocations + 7 POINTER()
    # casts cost ~as much as the C walk on run-light pages, and this wrapper
    # runs once per page per stream.  Layout (8-aligned: np.empty data is
    # 16-aligned, all offsets multiples of 8 until the u32/u8 tails):
    #   [consumed i64 | max u64 | eq i64 | ends i64*cap | starts i64*cap
    #    | vals u32*cap | kinds u8*cap]
    o_ends, o_starts = 24, 24 + 8 * cap
    o_vals, o_kinds = 24 + 16 * cap, 24 + 20 * cap
    arena = np.empty(24 + 21 * cap, dtype=np.uint8)
    arena[:24] = 0  # scalar slots must read 0 when not requested
    base = arena.ctypes.data
    rc = lib.tpq_hybrid_meta(
        _buf_arg(buf), n, pos, width, count,
        base + o_ends, base + o_kinds, base + o_vals, base + o_starts, cap,
        base,
        1 if want_max else 0,
        base + 8,
        0 if eq_target is None else 1,
        0 if eq_target is None else int(eq_target),
        base + 16,
    )
    if rc < 0:
        return int(rc)
    r = int(rc)
    head = np.frombuffer(arena, np.int64, 3, 0)
    # the max slot is u64 in C — an i64 view would return >=2^63 values
    # (width-64 RLE runs) as negative
    mx = int(np.frombuffer(arena, np.uint64, 1, 8)[0]) if want_max else None
    eq = int(head[2]) if eq_target is not None else None
    return (
        r, int(head[0]),
        np.frombuffer(arena, np.int64, r, o_ends),
        np.frombuffer(arena, np.uint8, r, o_kinds),
        np.frombuffer(arena, np.uint32, r, o_vals),
        np.frombuffer(arena, np.int64, r, o_starts),
        mx, eq,
    )


# meta_parse.cpp error codes → messages (kept aligned with the C enum);
# shared by every native-walk caller so diagnostics don't depend on which
# wrapper surfaced the failure
NATIVE_ERRORS = {
    -1: "truncated varint in stream header",
    -2: "varint too long in stream header",
    -3: "invalid delta block size",
    -4: "invalid miniblock count",
    -5: "miniblock size not multiple of 32",
    -6: "implausible delta value count",
    -7: "truncated miniblock bit widths",
    -8: "invalid miniblock bit width",
    -9: "truncated miniblock data",
    -11: "truncated bit-packed run",
    -12: "truncated RLE run value",
    -13: "hybrid stream exhausted",
}


def hybrid_meta_retry(buf: bytes, n: int, pos: int, width: int, count: int,
                      want_max: bool = False, eq_target: "int | None" = None):
    """hybrid_meta with the standard cap-retry policy.

    Starts with a small run-table cap and retries once with the provable
    worst case (one run per value/byte) on ERR_CAP.  Returns the result
    tuple, a negative error code, or None when unavailable.
    """
    cap = min(count, max(n - pos, 0) + 1, 4096)
    full_cap = min(count, max(n - pos, 0) + 1)
    while True:
        res = hybrid_meta(buf, n, pos, width, count, cap, want_max=want_max,
                          eq_target=eq_target)
        if isinstance(res, int) and res == -10 and cap < full_cap:
            cap = full_cap
            continue
        return res


def bytearray_walk(buf: bytes, count: int):
    """Walk PLAIN BYTE_ARRAY length prefixes natively (meta_parse.cpp).

    Returns (offsets int64[count+1], heap uint8[total]) with prefixes
    stripped, a negative error code (int), or None when the native library is
    unavailable.
    """
    import numpy as np

    lib = load()
    if lib is None:
        return None
    n = len(buf)
    offsets = np.empty(count + 1, dtype=np.int64)
    # upper bound is n, NOT n - 4*count: a malformed stream can run out of
    # records midway, after legitimately copying up to ~n payload bytes
    # (found by fuzz_plain — the tighter bound corrupted the heap allocation)
    heap = np.empty(n, dtype=np.uint8)
    rc = lib.tpq_bytearray_walk(
        _buf_arg(buf), n, count,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        heap.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if rc < 0:
        return int(rc)
    return offsets, heap[: int(rc)]


def bytearray_lengths(buf: bytes, count: int, pos: int = 0):
    """Validate PLAIN BYTE_ARRAY prefixes from ``pos`` and return the u32
    lengths only (no copies anywhere: the caller passes the whole page
    buffer + offset, and the device compacts the heap from the raw stream).

    Returns (lens uint32[count], consumed_end int — the stream position
    after the last value), a negative error code (int), or None when the
    native library is unavailable.
    """
    import numpy as np

    lib = load()
    if lib is None:
        return None
    lens = np.empty(count, dtype=np.uint32)
    rc = lib.tpq_bytearray_lengths(
        _buf_arg(buf), len(buf), pos, count,
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    if rc < 0:
        return int(rc)
    return lens, int(rc)


def snappy_plan(payload: bytes, expect: int):
    """Parse a raw snappy stream's TAG STRUCTURE only (no byte movement).

    Returns (dst_end int64[nops], op_src int64[nops], is_lit uint8[nops],
    max_chain_depth int) where dst_end is each op's cumulative output end,
    op_src is a literal run's payload offset in the COMPRESSED stream or a
    copy's back-reference offset, and max_chain_depth bounds the
    pointer-doubling rounds the device resolver needs
    (device_reader._plan_device_snappy).  Validates the whole stream with the
    same reject set as tpq_snappy_decompress.  Returns a negative error code
    on malformed input, or None when the native library is unavailable.
    """
    import numpy as np

    lib = load()
    if lib is None:
        return None
    n = len(payload)
    full_cap = n // 2 + 2  # provable worst case: every op >= 2 stream bytes
    # normal streams carry one op per ~60 bytes; start small and retry on
    # ERR_CAP — allocating (and zeroing the depth tree for) the worst case
    # up front costs more than the walk itself on multi-MB pages
    cap = min(full_cap, max(n // 32, 64))
    pll = ctypes.POINTER(ctypes.c_longlong)
    while True:
        cap2 = 1
        while cap2 < cap:
            cap2 <<= 1
        dst_end = np.empty(cap, dtype=np.int64)
        op_src = np.empty(cap, dtype=np.int64)
        is_lit = np.empty(cap, dtype=np.uint8)
        seg = np.zeros(2 * cap2, dtype=np.int64)  # zeroed: depth maxima
        out = np.zeros(2, dtype=np.int64)
        rc = lib.tpq_snappy_plan(
            _buf_arg(payload), n, expect,
            dst_end.ctypes.data_as(pll), op_src.ctypes.data_as(pll),
            is_lit.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
            seg.ctypes.data_as(pll), cap2, out.ctypes.data_as(pll),
        )
        if rc == -10 and cap < full_cap:
            cap = min(full_cap, cap * 8)
            continue
        if rc < 0:
            return int(rc)
        r = int(rc)
        return dst_end[:r], op_src[:r], is_lit[:r], int(out[1])


def dict_build(n: int, max_dict: int, *, offsets=None, heap=None,
               data=None, width: int = 0):
    """First-appearance dictionary build (writer side) — ragged when
    ``offsets``/``heap`` given, fixed-width rows when ``data``/``width``.

    Returns (firsts int64[k], inverse uint32[n]) in first-appearance order,
    -50 when the distinct count exceeds ``max_dict`` (caller falls back to
    plain), or None when the native library is unavailable."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    nslots = 16
    while nslots < 2 * n:
        nslots <<= 1
    slots = np.full(nslots, -1, dtype=np.int32)
    inverse = np.empty(n, dtype=np.uint32)
    firsts = np.empty(max_dict, dtype=np.int64)
    pll = ctypes.POINTER(ctypes.c_longlong)
    pi32 = ctypes.POINTER(ctypes.c_int32)
    pu32 = ctypes.POINTER(ctypes.c_uint32)
    if offsets is not None:
        rc = lib.tpq_dict_build_bytes(
            offsets.ctypes.data_as(pll),
            heap.ctypes.data_as(ctypes.c_char_p), n, max_dict,
            slots.ctypes.data_as(pi32), nslots,
            inverse.ctypes.data_as(pu32), firsts.ctypes.data_as(pll),
        )
    else:
        rc = lib.tpq_dict_build_fixed(
            data.ctypes.data_as(ctypes.c_char_p), n, width, max_dict,
            slots.ctypes.data_as(pi32), nslots,
            inverse.ctypes.data_as(pu32), firsts.ctypes.data_as(pll),
        )
    if rc < 0:
        return int(rc)
    return firsts[: int(rc)], inverse


def bp_pack(vals, width: int):
    """LSB-first bit-pack of a contiguous uint64 array (widths 1..56);
    returns a uint8 array of ceil(n*width/8) bytes, or None when the native
    library is unavailable."""
    import numpy as np

    lib = load()
    if lib is None or not 1 <= width <= 56:
        return None
    v = np.ascontiguousarray(vals, dtype=np.uint64)
    out = np.empty((len(v) * width + 7) // 8, dtype=np.uint8)
    lib.tpq_bp_pack(
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(v), width,
        out.ctypes.data,
    )
    return out


def int_minmax(buf: bytes, pos: int, n: int, width: int):
    """Min/max of ``n`` little-endian signed ``width``-byte ints at buf+pos.

    Returns (min, max) as python ints, or None when the native library is
    unavailable (caller falls back to numpy)."""
    import numpy as np

    lib = load()
    if lib is None or n <= 0:
        return None
    out = np.empty(2, dtype=np.int64)
    lib.tpq_int_minmax(
        _buf_arg(buf), pos, n, width,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
    )
    return int(out[0]), int(out[1])


def int_truncate(buf: bytes, pos: int, n: int, width: int, bias: int, k: int,
                 dst) -> bool:
    """Write ``(v - bias) mod 2**(8*width)`` truncated to k bytes per value
    into ``dst`` (uint8 numpy array, >= n*k bytes).  Returns False when the
    native library is unavailable."""
    lib = load()
    if lib is None:
        return False
    lib.tpq_int_truncate(_buf_arg(buf), pos, n, width,
                         ctypes.c_uint64(bias % (1 << 64)), k,
                         dst.ctypes.data)
    return True


def page_header(buf: bytes, pos: int = 0):
    """Parse one thrift compact PageHeader natively (meta_parse.cpp).

    Returns (PageHeader, end_pos), a negative error code (int — TERR_*
    values, same accept/reject set as the Python engine), or None when the
    native library is unavailable.  Everything the format defines is
    populated, including each data page header's Statistics (min/max bytes,
    null/distinct counts — consumed by page-level predicate pruning).
    """
    lib = load()
    if lib is None:
        return None
    # stack-local ctypes array: per-page numpy allocation + data_as cast
    # would eat a few percent of the win this parser exists for
    out = (ctypes.c_longlong * 40)()
    rc = lib.tpq_page_header(_buf_arg(buf), len(buf), pos, out)
    if rc < 0:
        return int(rc)
    from ..format import (
        DataPageHeader, DataPageHeaderV2, DictionaryPageHeader,
        IndexPageHeader, PageHeader, Statistics,
    )

    mask = int(out[18])

    def g(i):
        return int(out[i]) if mask >> i & 1 else None

    def stats(base, struct_bit):
        if not (mask >> struct_bit & 1):
            return None
        st = Statistics(null_count=g(base), distinct_count=g(base + 1))

        def b(slot):
            if not (mask >> slot & 1):
                return None
            p, ln = int(out[slot]), int(out[slot + 1])
            return buf[p : p + ln]

        st.max, st.min = b(base + 2), b(base + 4)
        st.max_value, st.min_value = b(base + 6), b(base + 8)
        return st

    h = PageHeader(
        type=g(0), uncompressed_page_size=g(1),
        compressed_page_size=g(2), crc=g(3),
    )
    if mask >> 60 & 1:
        h.data_page_header = DataPageHeader(
            num_values=g(4), encoding=g(5),
            definition_level_encoding=g(6), repetition_level_encoding=g(7),
            statistics=stats(20, 58),
        )
    if mask >> 59 & 1:
        h.index_page_header = IndexPageHeader()
    if mask >> 61 & 1:
        dph = DictionaryPageHeader(num_values=g(8), encoding=g(9))
        if mask >> 10 & 1:
            dph.is_sorted = bool(out[10])
        h.dictionary_page_header = dph
    if mask >> 62 & 1:
        v2 = DataPageHeaderV2(
            num_values=g(11), num_nulls=g(12), num_rows=g(13),
            encoding=g(14), definition_levels_byte_length=g(15),
            repetition_levels_byte_length=g(16),
            statistics=stats(30, 57),
        )
        if mask >> 17 & 1:
            v2.is_compressed = bool(out[17])
        h.data_page_header_v2 = v2
    return h, int(out[19])


def delta_ba_stitch(prefix_lens, suf_off, suf_heap, out_off, heap) -> "int | None":
    """Run the DELTA_BYTE_ARRAY prefix chain natively (meta_parse.cpp).

    All arguments are numpy arrays (int64 offsets, uint8 heaps); ``heap`` is
    written in place.  Returns 0, -30 (prefix exceeds previous value), or
    None when the native library is unavailable.
    """
    lib = load()
    if lib is None:
        return None
    pll = ctypes.POINTER(ctypes.c_longlong)
    pu8 = ctypes.POINTER(ctypes.c_uint8)
    return int(lib.tpq_delta_ba_stitch(
        prefix_lens.ctypes.data_as(pll),
        suf_off.ctypes.data_as(pll),
        suf_heap.ctypes.data_as(pu8),
        out_off.ctypes.data_as(pll),
        heap.ctypes.data_as(pu8),
        len(prefix_lens),
    ))


def ragged_take(offsets, heap, idx, out_off, out_heap) -> bool:
    """Gather ragged rows: out_heap[out_off[i]:out_off[i+1]] =
    heap[offsets[idx[i]]:offsets[idx[i]+1]] (dictionary expansion).

    All arrays are caller-allocated, contiguous numpy (offsets/idx/out_off
    int64, heaps uint8); the caller computed ``out_off`` and bounds-checked
    ``idx``.  Returns False when the native library is unavailable (caller
    keeps the numpy gather).  Runs with the GIL released — the prefetch
    pipeline's worker threads overlap here.
    """
    lib = load()
    if lib is None:
        return False
    lib.tpq_ragged_take(
        offsets.ctypes.data, heap.ctypes.data, idx.ctypes.data, len(idx),
        out_off.ctypes.data, out_heap.ctypes.data,
    )
    return True


def hybrid_expand(buf, ends, kinds, vals, starts, width: int, count: int):
    """Expand hybrid run tables (hybrid_meta output) to uint32[count].

    Same value contract as the numpy sweep in kernels/rle.py:_decode_native
    (bit-packed fields at starts[r] + i*width, RLE broadcasting vals[r]).
    Returns the array, or None when the native library is unavailable.
    GIL-free like ragged_take.
    """
    import numpy as np

    lib = load()
    if lib is None:
        return None
    out = np.empty(count, dtype=np.uint32)
    # locals keep the (possibly converted) tables alive across the C call
    e = np.ascontiguousarray(ends, np.int64)
    k = np.ascontiguousarray(kinds, np.uint8)
    v = np.ascontiguousarray(vals, np.uint32)
    s = np.ascontiguousarray(starts, np.int64)
    lib.tpq_hybrid_expand(
        _buf_arg(buf), len(buf),
        e.ctypes.data, k.ctypes.data, v.ctypes.data, s.ctypes.data,
        len(e), width, count, out.ctypes.data,
    )
    return out


def available() -> bool:
    return load() is not None
