"""Native (C++) runtime pieces, loaded via ctypes.

Built lazily with g++ the first time they're needed (no pip/cmake dependency at
import time); the shared object is cached next to the sources and rebuilt when any
source file changes (content-hash stamp).  Everything here is optional: each consumer
has a pure-Python fallback, so the framework still works — slower — without a C++
toolchain.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["snappy.cpp"]
_LIB_BASENAME = "_libtpq_native.so"

_lock = threading.Lock()
_lib = None
_load_failed = False


def _source_hash() -> str:
    h = hashlib.sha256()
    for src in _SOURCES:
        with open(os.path.join(_DIR, src), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _build(lib_path: str) -> None:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-march=native",
        "-o", lib_path,
    ] + [os.path.join(_DIR, s) for s in _SOURCES]
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)


def load():
    """Return the ctypes native library, building it if needed; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            stamp = _source_hash()
            lib_path = os.path.join(_DIR, f"{_LIB_BASENAME}.{stamp}")
            if not os.path.exists(lib_path):
                _build(lib_path)
                # drop stale builds
                for f in os.listdir(_DIR):
                    if f.startswith(_LIB_BASENAME) and not f.endswith(stamp):
                        try:
                            os.unlink(os.path.join(_DIR, f))
                        except OSError:
                            pass
            lib = ctypes.CDLL(lib_path)
            lib.tpq_snappy_uncompressed_length.restype = ctypes.c_longlong
            lib.tpq_snappy_uncompressed_length.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.tpq_snappy_decompress.restype = ctypes.c_int
            lib.tpq_snappy_decompress.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.tpq_snappy_max_compressed_length.restype = ctypes.c_size_t
            lib.tpq_snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
            lib.tpq_snappy_compress.restype = ctypes.c_longlong
            lib.tpq_snappy_compress.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ]
            _lib = lib
        except Exception:
            _load_failed = True
    return _lib


def snappy_decompress(data: bytes) -> bytes:
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = lib.tpq_snappy_uncompressed_length(data, len(data))
    if n < 0:
        raise ValueError("malformed snappy data: bad length header")
    out = ctypes.create_string_buffer(n)
    rc = lib.tpq_snappy_decompress(data, len(data), out, n)
    if rc != 0:
        raise ValueError(f"malformed snappy data (error {rc})")
    return out.raw


def snappy_compress(data: bytes) -> bytes:
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    cap = lib.tpq_snappy_max_compressed_length(len(data))
    out = ctypes.create_string_buffer(cap)
    n = lib.tpq_snappy_compress(data, len(data), out)
    if n < 0:
        raise ValueError("snappy compression failed")
    return out.raw[:n]


def available() -> bool:
    return load() is not None
