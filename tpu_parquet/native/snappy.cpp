// Native runtime: Snappy raw-block codec (C++17, no external deps).
//
// Parquet's default page codec is Snappy's *raw* (non-framed) format.  The Go
// reference pulls in github.com/golang/snappy (compress.go:182-187); the Python
// snappy binding is not available in this image, so the codec is implemented here
// from the format spec and exposed to Python via ctypes
// (tpu_parquet/native/__init__.py).  A pure-Python fallback lives in
// tpu_parquet/compress.py for environments without a C++ toolchain.
//
// Raw snappy format:
//   [uvarint uncompressed_length] then a sequence of elements:
//     tag & 3 == 0: literal.  len-1 in tag>>2 if < 60, else (tag>>2)-59 extra
//                   little-endian length bytes follow; then the literal bytes.
//     tag & 3 == 1: copy, 1-byte offset: len = ((tag>>2)&7)+4,
//                   offset = ((tag>>5)<<8) | next byte.   (4..11 bytes, off<2048)
//     tag & 3 == 2: copy, 2-byte LE offset: len = (tag>>2)+1.
//     tag & 3 == 3: copy, 4-byte LE offset: len = (tag>>2)+1.
// Matches only ever reach back < 65536 bytes because compression operates on
// 64 KiB fragments.

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

constexpr size_t kBlockSize = 1 << 16;   // compression fragment size
constexpr int kHashBits = 14;
constexpr size_t kHashTableSize = 1 << kHashBits;

inline uint32_t load32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint64_t load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

inline uint32_t hash32(uint32_t v) {
  return (v * 0x1e35a7bdu) >> (32 - kHashBits);
}

// --- varint ----------------------------------------------------------------

int read_uvarint32(const uint8_t* src, size_t n, size_t* pos, uint32_t* out) {
  uint32_t result = 0;
  int shift = 0;
  while (*pos < n) {
    uint8_t b = src[(*pos)++];
    if (shift == 28 && (b & 0xf0) != 0) return -1;  // overflow past 32 bits
    result |= uint32_t(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return 0;
    }
    shift += 7;
    if (shift > 28) return -1;
  }
  return -1;  // truncated
}

size_t write_uvarint32(uint8_t* dst, uint32_t v) {
  size_t i = 0;
  while (v >= 0x80) {
    dst[i++] = uint8_t(v) | 0x80;
    v >>= 7;
  }
  dst[i++] = uint8_t(v);
  return i;
}

// --- emit helpers for the compressor --------------------------------------

inline uint8_t* emit_literal(uint8_t* dst, const uint8_t* src, size_t len) {
  if (len == 0) return dst;
  size_t n = len - 1;
  if (n < 60) {
    *dst++ = uint8_t(n << 2);
  } else if (n < (1u << 8)) {
    *dst++ = 60 << 2;
    *dst++ = uint8_t(n);
  } else if (n < (1u << 16)) {
    *dst++ = 61 << 2;
    *dst++ = uint8_t(n);
    *dst++ = uint8_t(n >> 8);
  } else if (n < (1u << 24)) {
    *dst++ = 62 << 2;
    *dst++ = uint8_t(n);
    *dst++ = uint8_t(n >> 8);
    *dst++ = uint8_t(n >> 16);
  } else {
    *dst++ = 63 << 2;
    *dst++ = uint8_t(n);
    *dst++ = uint8_t(n >> 8);
    *dst++ = uint8_t(n >> 16);
    *dst++ = uint8_t(n >> 24);
  }
  std::memcpy(dst, src, len);
  return dst + len;
}

// Emit one copy element of length 4..64 (caller splits longer matches).
inline uint8_t* emit_copy_chunk(uint8_t* dst, size_t offset, size_t len) {
  if (len < 12 && offset < 2048) {
    *dst++ = uint8_t(((offset >> 8) << 5) | ((len - 4) << 2) | 1);
    *dst++ = uint8_t(offset);
  } else {
    *dst++ = uint8_t(((len - 1) << 2) | 2);
    *dst++ = uint8_t(offset);
    *dst++ = uint8_t(offset >> 8);
  }
  return dst;
}

inline uint8_t* emit_copy(uint8_t* dst, size_t offset, size_t len) {
  // Prefer 64-byte chunks; keep the tail >= 4.
  while (len >= 68) {
    dst = emit_copy_chunk(dst, offset, 64);
    len -= 64;
  }
  if (len > 64) {
    dst = emit_copy_chunk(dst, offset, 60);
    len -= 60;
  }
  return emit_copy_chunk(dst, offset, len);
}

}  // namespace

extern "C" {

// Parse the uncompressed-length header. Returns length, or -1 on malformed input.
long long tpq_snappy_uncompressed_length(const uint8_t* src, size_t n) {
  size_t pos = 0;
  uint32_t len;
  if (read_uvarint32(src, n, &pos, &len) != 0) return -1;
  return (long long)len;
}

// Decompress src (raw snappy) into dst of exactly dst_len bytes.
// Returns 0 on success, negative error codes on malformed input.
// Contract: dst must have >= 16 writable SLACK bytes past dst_len (the
// Python wrapper over-allocates) — the short-op fast paths below do blind
// 16-byte stores and the slack keeps them in-bounds without per-op length
// branches.  Bytes past dst_len are scratch; the logical output is
// dst[0:dst_len].
int tpq_snappy_decompress(const uint8_t* src, size_t n, uint8_t* dst,
                          size_t dst_len) {
  size_t pos = 0;
  uint32_t expect;
  if (read_uvarint32(src, n, &pos, &expect) != 0) return -2;
  if (expect != dst_len) return -3;
  size_t out = 0;
  while (pos < n) {
    uint8_t tag = src[pos++];
    uint32_t kind = tag & 3;
    if (kind == 0) {  // literal
      size_t len = tag >> 2;
      if (len < 60) {
        len += 1;
        if (pos + len > n || out + len > dst_len) return -5;
        if (len <= 16 && pos + 16 <= n) {
          // blind 16-byte copy (slack covers the overshoot); the typical
          // literal is short and a memcpy call dominated it
          std::memcpy(dst + out, src + pos, 16);
        } else {
          std::memcpy(dst + out, src + pos, len);
        }
        pos += len;
        out += len;
        continue;
      }
      size_t extra = len - 59;
      if (pos + extra > n) return -4;
      len = 0;
      for (size_t i = 0; i < extra; i++) len |= size_t(src[pos + i]) << (8 * i);
      pos += extra;
      len += 1;
      if (pos + len > n || out + len > dst_len) return -5;
      std::memcpy(dst + out, src + pos, len);
      pos += len;
      out += len;
    } else {  // copy
      size_t len, offset;
      if (kind == 1) {
        if (pos >= n) return -6;
        len = ((tag >> 2) & 7) + 4;
        offset = (size_t(tag >> 5) << 8) | src[pos];
        pos += 1;
      } else if (kind == 2) {
        if (pos + 2 > n) return -6;
        len = (tag >> 2) + 1;
        offset = size_t(src[pos]) | (size_t(src[pos + 1]) << 8);
        pos += 2;
      } else {
        if (pos + 4 > n) return -6;
        len = (tag >> 2) + 1;
        offset = size_t(src[pos]) | (size_t(src[pos + 1]) << 8) |
                 (size_t(src[pos + 2]) << 16) | (size_t(src[pos + 3]) << 24);
        pos += 4;
      }
      if (offset == 0 || offset > out) return -7;
      if (out + len > dst_len) return -8;
      uint8_t* d = dst + out;
      const uint8_t* s = d - offset;
      if (offset >= 8) {
        // 8-byte stride blind copy into the slack (format caps copy len at
        // 64, so this is at most 8 wide stores, usually 1-2)
        for (size_t i = 0; i < len; i += 8) std::memcpy(d + i, s + i, 8);
      } else {
        // overlapping copy: byte-wise (RLE-style repetition)
        for (size_t i = 0; i < len; i++) d[i] = s[i];
      }
      out += len;
    }
  }
  return out == dst_len ? 0 : -9;
}

size_t tpq_snappy_max_compressed_length(size_t n) {
  return 32 + n + n / 6;
}

// Compress src into dst (capacity >= max_compressed_length). Returns output size.
long long tpq_snappy_compress(const uint8_t* src, size_t n, uint8_t* dst) {
  uint8_t* out = dst + write_uvarint32(dst, uint32_t(n));
  static thread_local uint16_t table[kHashTableSize];

  for (size_t block = 0; block < n || block == 0; block += kBlockSize) {
    size_t block_len = n - block < kBlockSize ? n - block : kBlockSize;
    const uint8_t* base = src + block;
    if (block_len < 16) {
      out = emit_literal(out, base, block_len);
      if (n == 0) break;
      continue;
    }
    std::memset(table, 0, sizeof(table));
    size_t ip = 0;
    size_t lit_start = 0;
    const size_t margin = block_len - 15;  // room for fast 8-byte loads
    // skip acceleration (the snappy format's standard incompressible-input
    // heuristic): after 32 consecutive hash misses, probe every 2nd byte,
    // then every 3rd, ... — random data costs O(n/step) instead of one
    // probe per byte (measured 0.44 -> ~3 GB/s on random int64 pages)
    size_t skip = 32;
    while (ip + 4 <= margin) {
      uint32_t h = hash32(load32(base + ip));
      size_t cand = table[h];
      table[h] = uint16_t(ip);
      if (cand < ip && load32(base + cand) == load32(base + ip)) {
        skip = 32;
        // extend match forward
        size_t len = 4;
        while (ip + len + 8 <= block_len &&
               load64(base + cand + len) == load64(base + ip + len)) {
          len += 8;
        }
        while (ip + len < block_len && base[cand + len] == base[ip + len]) len++;
        out = emit_literal(out, base + lit_start, ip - lit_start);
        out = emit_copy(out, ip - cand, len);
        ip += len;
        lit_start = ip;
        if (ip + 4 <= margin) {
          // re-prime the table at the new position - 1
          table[hash32(load32(base + ip - 1))] = uint16_t(ip - 1);
        }
      } else {
        ip += (skip++ >> 5);
      }
    }
    out = emit_literal(out, base + lit_start, block_len - lit_start);
    if (n == 0) break;
  }
  return out - dst;
}

// --- ragged gather / hybrid expansion (host decode hot paths) ---------------
//
// These two transforms dominated the host decode profile as numpy
// (repeat+arange gather for dictionary take, searchsorted + byte-window
// sweep for hybrid expansion) and — unlike numpy's non-ufunc kernels — run
// here with the GIL released (ctypes), so the chunk-prefetch pipeline's
// worker threads genuinely overlap.

// Dictionary expansion for ragged BYTE_ARRAY rows: output row i is
// heap[offsets[idx[i]] : offsets[idx[i]+1]], landing at out_off[i].  The
// caller computes out_off (cumsum of the selected lengths) and has already
// bounds-checked idx against the dictionary.
void tpq_ragged_take(const int64_t* offsets, const uint8_t* heap,
                     const int64_t* idx, long long n,
                     const int64_t* out_off, uint8_t* out_heap) {
  for (long long i = 0; i < n; ++i) {
    const int64_t j = idx[i];
    const int64_t start = offsets[j];
    const int64_t len = offsets[j + 1] - start;
    if (len > 0) std::memcpy(out_heap + out_off[i], heap + start, size_t(len));
  }
}

// Expand parsed hybrid run tables (tpq_hybrid_meta's output, meta_parse.cpp)
// to `count` uint32 values.  kinds[r] == 0 is a bit-packed run whose value
// at global position i sits at bit starts[r] + i*width (starts are
// pre-normalized by -run_start*width, exactly the contract the numpy sweep
// in kernels/rle.py consumes); nonzero kinds are RLE runs filling vals[r].
// width 1..32.  Reads never pass nbuf (tail fields assemble byte-wise).
void tpq_hybrid_expand(const uint8_t* buf, long long nbuf,
                       const int64_t* ends, const uint8_t* kinds,
                       const uint32_t* vals, const int64_t* starts,
                       long long n_runs, int width, long long count,
                       uint32_t* out) {
  const uint64_t mask =
      (width >= 32) ? 0xffffffffull : ((1ull << width) - 1ull);
  int64_t pos = 0;
  for (long long r = 0; r < n_runs && pos < count; ++r) {
    int64_t end = ends[r];
    if (end > count) end = count;
    if (end <= pos) continue;
    if (kinds[r] != 0) {  // RLE: broadcast the run value
      const uint32_t v = vals[r];
      for (; pos < end; ++pos) out[pos] = v;
    } else {  // bit-packed: extract width-bit fields at affine positions
      const int64_t sbit = starts[r];
      for (; pos < end; ++pos) {
        const int64_t bit = sbit + pos * int64_t(width);
        const int64_t byte0 = bit >> 3;
        uint64_t acc = 0;
        if (byte0 + 8 <= nbuf) {
          std::memcpy(&acc, buf + byte0, 8);
        } else {
          for (int k = 0; k < 8 && byte0 + k < nbuf; ++k)
            acc |= uint64_t(buf[byte0 + k]) << (8 * k);
        }
        out[pos] = uint32_t((acc >> (bit & 7)) & mask);
      }
    }
  }
  for (; pos < count; ++pos) out[pos] = 0;  // defensive: runs short of count
}

}  // extern "C"
