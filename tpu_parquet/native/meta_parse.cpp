// Host-side stream-structure parsers: DELTA_BINARY_PACKED block headers and
// RLE/bit-packed hybrid run headers.
//
// The device decode path splits every encoded stream into (structure, bulk):
// structure — varint headers, a few bytes per block/run — is walked on the
// host; bulk bytes go to the TPU untouched (jax_decode.parse_delta_meta /
// parse_hybrid_meta docstrings).  The structure walk is sequential byte
// chasing, the one shape Python is worst at: on a 10M-row DELTA column the
// pure-Python walk costs ~10x the actual XLA decode.  These C functions do the
// identical walk at memory speed; the Python versions remain as the reference
// implementation and the fallback when no C++ toolchain is available.
//
// Semantics mirror the reference decoders' header validation:
// deltabp_decoder.go:38-103 (block geometry + bit-width bounds) and
// hybrid_decoder.go:115-165 (run headers, truncation checks).  Varints follow
// helpers.go readUVariant64: at most 10 bytes (continuation past shift 63 is
// an error); values may exceed 64 bits transiently, so accumulation is 128-bit
// to match the Python parser bit for bit on hostile inputs.

#include <cstdint>

typedef uint8_t u8;
typedef int32_t i32;
typedef uint32_t u32;
typedef int64_t i64;
typedef uint64_t u64;
typedef unsigned __int128 u128;

namespace {

// error codes shared with the ctypes wrapper (tpu_parquet/native/__init__.py)
enum {
    ERR_TRUNC_VARINT = -1,
    ERR_VARINT_LONG = -2,
    ERR_BLOCK_SIZE = -3,
    ERR_MINI_COUNT = -4,
    ERR_MINI_MULT = -5,
    ERR_COUNT_BOMB = -6,
    ERR_TRUNC_WIDTHS = -7,
    ERR_BAD_WIDTH = -8,
    ERR_TRUNC_MINI = -9,
    ERR_CAP = -10,
    ERR_TRUNC_RUN = -11,
    ERR_TRUNC_RLE_VALUE = -12,
    ERR_EXHAUSTED = -13,
};

int read_uvarint(const u8 *buf, i64 n, i64 *pos, u128 *out) {
    u128 result = 0;
    int shift = 0;
    for (;;) {
        if (*pos >= n) return ERR_TRUNC_VARINT;
        u8 b = buf[(*pos)++];
        result |= (u128)(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            *out = result;
            return 0;
        }
        shift += 7;
        if (shift > 63) return ERR_VARINT_LONG;
    }
}

int read_zigzag(const u8 *buf, i64 n, i64 *pos, u128 *out) {
    u128 v;
    int rc = read_uvarint(buf, n, pos, &v);
    if (rc) return rc;
    // (v >> 1) ^ -(v & 1) in 128-bit, exactly like the Python reference
    *out = (v >> 1) ^ (~(u128)0 * (v & 1));
    return 0;
}

}  // namespace

extern "C" {

// Parse a DELTA_BINARY_PACKED stream's headers starting at buf[pos].
//
// header_out[6]: block_size, minis_per_block, total, first_value (two's
// complement low 64 bits), consumed byte position, n_minis written.
// starts/widths/mins: per-miniblock payload bit offset, bit width, and block
// min-delta (low 64 bits), cap entries each.  Returns 0 or a negative error.
i64 tpq_delta_meta(const u8 *buf, i64 len, i64 pos, i64 *header_out,
                   i64 *starts, i32 *widths, u64 *mins, i64 cap) {
    u128 block_size, minis_per_block, total, first;
    int rc;
    if ((rc = read_uvarint(buf, len, &pos, &block_size))) return rc;
    if ((rc = read_uvarint(buf, len, &pos, &minis_per_block))) return rc;
    if ((rc = read_uvarint(buf, len, &pos, &total))) return rc;
    if ((rc = read_zigzag(buf, len, &pos, &first))) return rc;
    if (block_size == 0 || block_size % 128 != 0) return ERR_BLOCK_SIZE;
    if (block_size > ((u128)1 << 30)) return ERR_BLOCK_SIZE;  // decompression-bomb guard
    if (minis_per_block == 0 || block_size % minis_per_block != 0)
        return ERR_MINI_COUNT;
    u128 values_per_mini = block_size / minis_per_block;
    if (values_per_mini % 32 != 0) return ERR_MINI_MULT;
    if (total > ((u128)1 << 40)) return ERR_COUNT_BOMB;

    i64 n_deltas = total > 0 ? (i64)total - 1 : 0;
    i64 got = 0, n_minis = 0;
    // values_per_mini/minis_per_block stay 128-bit through the size math:
    // hostile headers can make them exceed i64, and a narrowing cast would
    // turn the bound checks below into out-of-bounds reads (the Python
    // reference walk does this arithmetic in unbounded ints)
    u128 vpm128 = values_per_mini;
    // width vectors are only read when there are deltas to decode: a
    // total<=1 stream legally ends right after the header (the Go reference
    // reads blocks lazily and never touches one for a single value), so the
    // truncation pre-check must not fire for it.  minis_per_block <=
    // block_size <= 2^30 here (the %-check above), so the cast is safe.
    if (n_deltas > 0 && minis_per_block > (u128)len + 1)
        return ERR_TRUNC_WIDTHS;
    i64 mpb = (i64)minis_per_block;
    while (got < n_deltas) {
        u128 min_delta;
        if ((rc = read_zigzag(buf, len, &pos, &min_delta))) return rc;
        if (pos + mpb > len) return ERR_TRUNC_WIDTHS;
        const u8 *wvec = buf + pos;
        pos += mpb;
        for (i64 m = 0; m < mpb && got < n_deltas; m++) {
            i64 w = wvec[m];
            if (w > 64) return ERR_BAD_WIDTH;
            u128 nbytes128 = (vpm128 * (u128)w + 7) / 8;
            if ((u128)pos + nbytes128 > (u128)len) return ERR_TRUNC_MINI;
            if (n_minis >= cap) return ERR_CAP;
            starts[n_minis] = pos * 8;
            widths[n_minis] = (i32)w;
            mins[n_minis] = (u64)min_delta;
            n_minis++;
            pos += (i64)nbytes128;
            u128 take = (u128)(n_deltas - got);
            got += (i64)(take < vpm128 ? take : vpm128);
        }
    }
    header_out[0] = (i64)block_size;
    header_out[1] = mpb;
    header_out[2] = (i64)total;
    header_out[3] = (i64)(u64)first;
    header_out[4] = pos;
    header_out[5] = n_minis;
    return 0;
}

// Parse RLE/bit-packed hybrid run headers for `count` values starting at
// buf[pos], bounded by n (the v1 length prefix, or the buffer end).
//
// ends/kinds/vals/starts: per-run cumulative value count, is-RLE flag, RLE
// value, and bit-packed payload bit offset minus run_start*width (the uniform
// position form expand_rle_hybrid consumes), cap entries each.
// consumed_out[0] receives the final byte position.  When want_max is nonzero
// the stream's maximum value (RLE run values + a scan of every bit-packed
// field up to each run's real extent) is written to max_out[0] — this lets
// dictionary-index range validation happen entirely on the host, so the
// device decode path needs zero device→host syncs.  When want_eq is nonzero
// the number of stream values equal to eq_target is written to eq_out[0]:
// for definition-level streams with eq_target = max_def this is the page's
// defined-value count, which gates every static decode shape — so the host
// never needs to materialize the decoded level array at all.  Returns
// n_runs >= 0, or a negative error (ERR_CAP: caller retries with a larger
// cap).
i64 tpq_hybrid_meta(const u8 *buf, i64 n, i64 pos, i64 width, i64 count,
                    i64 *ends, u8 *kinds, u32 *vals, i64 *starts, i64 cap,
                    i64 *consumed_out, i64 want_max, u64 *max_out,
                    i64 want_eq, u64 eq_target, i64 *eq_out) {
    i64 value_bytes = (width + 7) / 8;
    i64 total = 0, n_runs = 0;
    u64 max_val = 0;
    i64 eq_count = 0;
    const u64 mask = width >= 64 ? ~(u64)0 : (((u64)1 << width) - 1);
    const int scan_bp = (want_max || want_eq);
    while (total < count) {
        if (pos >= n) return ERR_EXHAUSTED;
        u128 h;
        int rc = read_uvarint(buf, n, &pos, &h);
        if (rc) return rc;
        if (h & 1) {
            u128 groups = h >> 1;
            if (groups == 0) continue;
            u128 nbytes128 = groups * (u128)width;
            if ((u128)pos + nbytes128 > (u128)n) return ERR_TRUNC_RUN;
            // nvals in 128-bit: for width 0 the byte bound above doesn't cap
            // groups, and (i64)(groups*8) could truncate to 0 and stall the
            // walk where the Python reference accepts the run
            u128 nvals128 = groups * 8;
            i64 take = count - total;
            if (nvals128 < (u128)take) take = (i64)nvals128;
            if (n_runs >= cap) return ERR_CAP;
            kinds[n_runs] = 0;
            vals[n_runs] = 0;
            starts[n_runs] = pos * 8 - total * width;
            if (scan_bp && width > 0) {
                // scan the run's real extent (padding past `take` is ignored,
                // matching the device expansion's idx[:count] semantics).
                // Block-lane form: a bit-packed run is whole 8-value groups
                // of `width` bytes, and within every group lane j sits at
                // the FIXED (byte, shift) = ((j*width)>>3, (j*width)&7) —
                // so the inner 8-lane loop has compile-time-hoistable
                // offsets and 8 independent max/eq accumulator chains the
                // superscalar units run in parallel.  Measured ~2x over the
                // per-value u64-load walk (itself ~4x the byte walk); this
                // scan is the hottest host cost on dictionary-heavy files.
                i64 k = 0;
                if (width <= 56) {
                    i64 blocks = take >> 3;
                    // every lane load reads 8 bytes: bound the last block's
                    // highest load (lane 7) inside the buffer
                    i64 lane7 = ((i64)7 * width) >> 3;
                    while (blocks > 0 &&
                           pos + (blocks - 1) * width + lane7 + 8 > n)
                        blocks--;
                    u64 mx[8] = {0, 0, 0, 0, 0, 0, 0, 0};
                    i64 eqc[8] = {0, 0, 0, 0, 0, 0, 0, 0};
                    const u8 *bp = buf + pos;
                    for (i64 b = 0; b < blocks; b++, bp += width) {
                        for (int j = 0; j < 8; j++) {
                            u64 acc;
                            __builtin_memcpy(&acc, bp + (((i64)j * width) >> 3), 8);
                            u64 v = (acc >> (((i64)j * width) & 7)) & mask;
                            if (v > mx[j]) mx[j] = v;
                            eqc[j] += (i64)(v == eq_target);
                        }
                    }
                    for (int j = 0; j < 8; j++) {
                        if (mx[j] > max_val) max_val = mx[j];
                        eq_count += eqc[j];
                    }
                    k = blocks * 8;
                }
                i64 safe_end = n - 8;
                for (; k < take; k++) {
                    i64 bit = pos * 8 + k * width;
                    i64 byte0 = bit >> 3;
                    int sh = (int)(bit & 7);
                    u64 v;
                    if (width <= 56 && byte0 <= safe_end) {
                        u64 acc;
                        __builtin_memcpy(&acc, buf + byte0, 8);
                        v = (acc >> sh) & mask;
                    } else {
                        u64 acc = 0;
                        i64 nb = (width + sh + 7) / 8;
                        for (i64 b = 0; b < nb && byte0 + b < n; b++)
                            acc |= (u64)buf[byte0 + b] << (8 * b);
                        v = (acc >> sh) & mask;
                    }
                    if (v > max_val) max_val = v;
                    if (v == eq_target) eq_count++;
                }
            } else if (want_eq && width == 0 && eq_target == 0) {
                eq_count += take;  // width-0 stream: every value is 0
            }
            pos += (i64)nbytes128;
            total += take;
        } else {
            u128 repeats128 = h >> 1;
            if (repeats128 == 0) continue;
            i64 repeats = repeats128 > (u128)(count - total)
                              ? count - total
                              : (i64)repeats128;
            if (pos + value_bytes > n) return ERR_TRUNC_RLE_VALUE;
            u64 v = 0;
            for (i64 k = 0; k < value_bytes; k++)
                v |= (u64)buf[pos + k] << (8 * k);
            pos += value_bytes;
            if (n_runs >= cap) return ERR_CAP;
            kinds[n_runs] = 1;
            vals[n_runs] = (u32)v;
            starts[n_runs] = 0;
            // RLE run values are NOT masked to the stream width: the Python
            // decoder, the run table (vals above), and the device expansion
            // all broadcast the raw little-endian bytes, so max/eq must see
            // the same value or a malformed file's defined-count diverges
            // between the host and batched-device paths (found by the
            // device_reader differential fuzzer).
            if (want_max && v > max_val) max_val = v;
            if (want_eq && v == eq_target) eq_count += repeats;
            total += repeats;
        }
        ends[n_runs] = total;
        n_runs++;
    }
    consumed_out[0] = pos;
    if (want_max) max_out[0] = max_val;
    if (want_eq) eq_out[0] = eq_count;
    return n_runs;
}

// Walk `count` PLAIN BYTE_ARRAY values (uint32 LE length prefix + bytes,
// type_bytearray.go:13-96 wire shape) starting at buf[0]: validate prefixes,
// write offsets[count+1] (cumulative value lengths) and compact the value
// bytes into heap (prefixes stripped).  heap must hold >= n - 4*count bytes
// (the caller allocates the upper bound).  Returns total heap bytes, or a
// negative error (ERR_TRUNC_PREFIX / ERR_LEN_RANGE).
i64 tpq_bytearray_walk(const u8 *buf, i64 n, i64 count, i64 *offsets,
                       u8 *heap) {
    i64 pos = 0, total = 0;
    offsets[0] = 0;
    for (i64 i = 0; i < count; i++) {
        if (pos + 4 > n) return -20;  // truncated length prefix
        u32 ln = (u32)buf[pos] | ((u32)buf[pos + 1] << 8) |
                 ((u32)buf[pos + 2] << 16) | ((u32)buf[pos + 3] << 24);
        if ((u128)pos + 4 + ln > (u128)n) return -21;  // length exceeds buffer
        pos += 4;
        __builtin_memcpy(heap + total, buf + pos, ln);
        pos += ln;
        total += ln;
        offsets[i + 1] = total;
    }
    return total;
}

// Lengths-only variant of tpq_bytearray_walk: validate the same prefix walk
// (starting at `pos` of the page buffer — callers never slice/copy the
// stream) but write only the u32 value lengths — no heap copy.  The batched
// device reader stages the RAW stream and compacts the heap on device
// (offsets = cumsum of these lengths there), so the host never touches the
// value bytes.  Returns the position after the last value, or
// ERR_TRUNC_PREFIX / ERR_LEN_RANGE.
i64 tpq_bytearray_lengths(const u8 *buf, i64 n, i64 pos, i64 count,
                          u32 *lens) {
    for (i64 i = 0; i < count; i++) {
        if (pos + 4 > n) return -20;  // truncated length prefix
        u32 ln = (u32)buf[pos] | ((u32)buf[pos + 1] << 8) |
                 ((u32)buf[pos + 2] << 16) | ((u32)buf[pos + 3] << 24);
        if ((u128)pos + 4 + ln > (u128)n) return -21;  // length exceeds buffer
        lens[i] = ln;
        pos += 4 + (i64)ln;
    }
    return pos;
}

// ---------------------------------------------------------------------------
// Thrift compact-protocol PageHeader parse (the per-page host hot path).
//
// Semantics mirror tpu_parquet/thrift.py's CompactReader EXACTLY (that engine
// is the reference and the fuzz-parity oracle): varints reject >10 bytes and
// 64-bit overflow, field ids arrive as header deltas or zigzag varints, bool
// field values ride the header ctype, containers are capped at 2^24, nesting
// at depth 32, and a known field id carrying the wrong wire type is skipped
// by its wire type (leaving the field absent).  Only the fields the readers
// consume are extracted; everything else (incl. page Statistics, which no
// consumer reads — predicate pushdown uses chunk metadata stats) is skipped
// by wire type.
// ---------------------------------------------------------------------------

enum {
    TERR_TRUNC = -40,      // truncated input
    TERR_VARLONG = -41,    // varint too long / exceeds 64 bits
    TERR_CONTAINER = -42,  // container exceeds sanity cap
    TERR_DEPTH = -43,      // nesting too deep
    TERR_CTYPE = -44,      // unknown thrift wire type (13-15)
};

static const i64 T_MAX_CONTAINER = (i64)1 << 24;

static int t_varint(const u8 *buf, i64 n, i64 *pos, u64 *out) {
    u64 result = 0;
    int shift = 0;
    i64 p = *pos;
    while (1) {
        if (p >= n) return TERR_TRUNC;
        u8 b = buf[p++];
        result |= (u64)(b & 0x7F) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
        if (shift > 63) return TERR_VARLONG;
    }
    // shift==63 with a 2-bit payload would exceed 64 bits; the python engine
    // rejects via `result >> 64`, which the shift cap above already covers
    // except for the final byte's high bits — replicate the exact check:
    if (shift == 63 && (buf[p - 1] & 0x7E)) return TERR_VARLONG;
    *pos = p;
    *out = result;
    return 0;
}

static int t_zigzag(const u8 *buf, i64 n, i64 *pos, i64 *out) {
    u64 v;
    int rc = t_varint(buf, n, pos, &v);
    if (rc) return rc;
    *out = (i64)(v >> 1) ^ -(i64)(v & 1);
    return 0;
}

static int t_skip(const u8 *buf, i64 n, i64 *pos, int ctype, int depth);

static int t_skip_struct(const u8 *buf, i64 n, i64 *pos, int depth) {
    if (depth > 32) return TERR_DEPTH;
    u64 last = 0;  // wraps like a machine int; python's unbounded ids only miss lookups
    while (1) {
        if (*pos >= n) return TERR_TRUNC;
        u8 b = buf[(*pos)++];
        // the python engine masks ctype BEFORE its STOP comparison, so any
        // zero-ctype-nibble byte terminates the struct (0x00 consumes
        // nothing further; nonzero deltas were already folded into fid)
        if ((b & 0x0F) == 0x00) return 0;  // CT_STOP
        int ctype = b & 0x0F;
        int delta = (b >> 4) & 0x0F;
        if (delta) {
            last += (u64)delta;
        } else {
            i64 fid;
            int rc = t_zigzag(buf, n, pos, &fid);
            if (rc) return rc;
            last = (u64)fid;
        }
        if (ctype != 0x01 && ctype != 0x02) {  // bools carry no payload
            int rc = t_skip(buf, n, pos, ctype, depth + 1);
            if (rc) return rc;
        }
    }
}

static int t_skip(const u8 *buf, i64 n, i64 *pos, int ctype, int depth) {
    if (depth > 32) return TERR_DEPTH;
    u64 v;
    int rc;
    switch (ctype) {
        case 0x01: case 0x02: return 0;            // bool in field header
        case 0x03:                                  // byte
            if (*pos + 1 > n) return TERR_TRUNC;
            (*pos)++;
            return 0;
        case 0x04: case 0x05: case 0x06:            // i16/i32/i64 varints
            return t_varint(buf, n, pos, &v);
        case 0x07:                                  // double
            if (*pos + 8 > n) return TERR_TRUNC;
            *pos += 8;
            return 0;
        case 0x08:                                  // binary
            rc = t_varint(buf, n, pos, &v);
            if (rc) return rc;
            if (v > (u64)T_MAX_CONTAINER) return TERR_CONTAINER;
            if (*pos + (i64)v > n) return TERR_TRUNC;
            *pos += (i64)v;
            return 0;
        case 0x09: case 0x0A: {                     // list/set
            if (*pos >= n) return TERR_TRUNC;
            u8 b = buf[(*pos)++];
            i64 size = (b >> 4) & 0x0F;
            int etype = b & 0x0F;
            if (size == 15) {
                rc = t_varint(buf, n, pos, &v);
                if (rc) return rc;
                if (v > (u64)T_MAX_CONTAINER) return TERR_CONTAINER;
                size = (i64)v;
            }
            if (size > T_MAX_CONTAINER) return TERR_CONTAINER;
            if (etype == 0x01 || etype == 0x02) {   // bool elems are one byte
                if (*pos + size > n) return TERR_TRUNC;
                *pos += size;
                return 0;
            }
            for (i64 i = 0; i < size; i++) {
                rc = t_skip(buf, n, pos, etype, depth + 1);
                if (rc) return rc;
            }
            return 0;
        }
        case 0x0B: {                                // map
            rc = t_varint(buf, n, pos, &v);
            if (rc) return rc;
            if (v > (u64)T_MAX_CONTAINER) return TERR_CONTAINER;
            if (v) {
                if (*pos >= n) return TERR_TRUNC;
                u8 kv = buf[(*pos)++];
                int kt = (kv >> 4) & 0x0F, vt = kv & 0x0F;
                for (u64 i = 0; i < v; i++) {
                    rc = t_skip(buf, n, pos, kt, depth + 1);
                    if (rc) return rc;
                    rc = t_skip(buf, n, pos, vt, depth + 1);
                    if (rc) return rc;
                }
            }
            return 0;
        }
        case 0x0C:                                  // struct
            // python's skip() checks depth at entry (done above) and walks
            // inner fields at depth+1 — the walker continues at THIS depth
            return t_skip_struct(buf, n, pos, depth);
        default:
            // unknown wire type (13-15): the python engine's skip() raises
            // "cannot skip unknown thrift ctype" — distinct code, same reject
            return TERR_CTYPE;
    }
}

// Statistics sub-struct (format field ids: 1 max binary, 2 min binary,
// 3 null_count i64, 4 distinct_count i64, 5 max_value binary, 6 min_value
// binary).  Binary values are recorded as (pos, len) into the source buffer.
// Slot bank at `base` (base+0 null_count, +1 distinct_count, +2/+3 max,
// +4/+5 min, +6/+7 max_value, +8/+9 min_value); presence bits are the value
// slots' indices, and `struct_bit` marks the sub-struct itself.  v1 and v2
// data page headers get SEPARATE banks (20/bit 58 and 30/bit 57): both may
// appear in one PageHeader and each python object carries its own stats.
static int t_stats(const u8 *buf, i64 n, i64 *pos, i64 *out, u64 *mask,
                   int base, int struct_bit) {
    for (int i = base; i < base + 10; i++) {
        out[i] = 0;
        *mask &= ~((u64)1 << i);
    }
    u64 last = 0;
    while (1) {
        if (*pos >= n) return TERR_TRUNC;
        u8 b = buf[(*pos)++];
        if ((b & 0x0F) == 0x00) break;  // masked-STOP (python parity)
        int ctype = b & 0x0F;
        int delta = (b >> 4) & 0x0F;
        if (delta) {
            last += (u64)delta;
        } else {
            i64 fid;
            int rc = t_zigzag(buf, n, pos, &fid);
            if (rc) return rc;
            last = (u64)fid;
        }
        int rc = 0;
        if ((last == 3 || last == 4) && ctype == 0x06) {
            i64 v;
            rc = t_zigzag(buf, n, pos, &v);
            if (!rc) {
                int slot = base + (last == 3 ? 0 : 1);
                out[slot] = v;
                *mask |= (u64)1 << slot;
            }
        } else if ((last == 1 || last == 2 || last == 5 || last == 6)
                   && ctype == 0x08) {
            u64 blen;
            rc = t_varint(buf, n, pos, &blen);
            if (!rc) {
                if (blen > (u64)T_MAX_CONTAINER) return TERR_CONTAINER;
                if (*pos + (i64)blen > n) return TERR_TRUNC;
                int slot = base + (last == 1 ? 2 : last == 2 ? 4
                                   : last == 5 ? 6 : 8);
                out[slot] = *pos;
                out[slot + 1] = (i64)blen;
                *mask |= (u64)1 << slot;
                *pos += (i64)blen;
            }
        } else if (ctype != 0x01 && ctype != 0x02) {
            rc = t_skip(buf, n, pos, ctype, 2);
        }
        if (rc) return rc;
    }
    *mask |= (u64)1 << struct_bit;
    return 0;
}

// Parse the sub-struct `fids` maps into out slots: for each field id fid in
// [1, nf], if fid maps to slot s >= 0 and the wire type matches `want`
// (varint ints) or is a bool (want < 0), record the value + presence bit.
// wants[fid-1]: 5/6 = zigzag varint of that wire type, -1 = bool, 0 = skip.
// `stats_fid` != 0 routes that struct-typed field into t_stats (the
// Statistics carried by DataPageHeader field 5 / DataPageHeaderV2 field 8).
static int t_sub_struct(const u8 *buf, i64 n, i64 *pos, const int8_t *wants,
                        const int8_t *slots, int nf, i64 *out, u64 *mask,
                        int stats_fid, int stats_base, int stats_bit) {
    u64 last = 0;  // wrap-safe; range tests below bound all uses
    while (1) {
        if (*pos >= n) return TERR_TRUNC;
        u8 b = buf[(*pos)++];
        if ((b & 0x0F) == 0x00) return 0;  // masked-STOP (python parity)
        int ctype = b & 0x0F;
        int delta = (b >> 4) & 0x0F;
        if (delta) {
            last += (u64)delta;
        } else {
            i64 fid;
            int rc = t_zigzag(buf, n, pos, &fid);
            if (rc) return rc;
            last = (u64)fid;
        }
        int want = (last >= 1 && last <= (u64)nf) ? wants[last - 1] : 0;
        int slot = (last >= 1 && last <= (u64)nf) ? slots[last - 1] : -1;
        if (stats_fid && last == (u64)stats_fid && ctype == 0x0C) {
            int rc = t_stats(buf, n, pos, out, mask, stats_base, stats_bit);
            if (rc) return rc;
        } else if (want == -1 && (ctype == 0x01 || ctype == 0x02)) {
            out[slot] = (ctype == 0x01);
            *mask |= (u64)1 << slot;
        } else if (want > 0 && ctype == want) {
            i64 v;
            int rc = t_zigzag(buf, n, pos, &v);
            if (rc) return rc;
            out[slot] = v;
            *mask |= (u64)1 << slot;
        } else if (ctype != 0x01 && ctype != 0x02) {
            int rc = t_skip(buf, n, pos, ctype, 1);
            if (rc) return rc;
        }
    }
}

// Slot layout (out i64[40]):
//   0 type  1 uncompressed_page_size  2 compressed_page_size  3 crc
//   4 dph.num_values  5 dph.encoding  6 dph.def_level_enc  7 dph.rep_level_enc
//   8 dict.num_values  9 dict.encoding  10 dict.is_sorted
//   11 v2.num_values  12 v2.num_nulls  13 v2.num_rows  14 v2.encoding
//   15 v2.def_levels_byte_length  16 v2.rep_levels_byte_length
//   17 v2.is_compressed
//   18 presence mask (bits 0-17/20-39 as slot indices; bits 59/60/61/62 =
//      index/dph/dict/v2 sub-struct present; 58/57 = dph/v2 Statistics
//      present)  19 end position
//   20-29 dph.statistics bank, 30-39 v2.statistics bank (see t_stats)
// Returns 0 or a TERR_* code.
i64 tpq_page_header(const u8 *buf, i64 n, i64 pos, i64 *out) {
    u64 mask = 0;
    for (int i = 0; i < 18; i++) out[i] = 0;
    for (int i = 20; i < 40; i++) out[i] = 0;
    static const int8_t dph_w[5] = {5, 5, 5, 5, 0};
    static const int8_t dph_s[5] = {4, 5, 6, 7, -1};
    static const int8_t dict_w[3] = {5, 5, -1};
    static const int8_t dict_s[3] = {8, 9, 10};
    static const int8_t v2_w[8] = {5, 5, 5, 5, 5, 5, -1, 0};
    static const int8_t v2_s[8] = {11, 12, 13, 14, 15, 16, 17, -1};
    u64 last = 0;  // wrap-safe field-id accumulator (see t_sub_struct)
    while (1) {
        if (pos >= n) return TERR_TRUNC;
        u8 b = buf[pos++];
        if ((b & 0x0F) == 0x00) break;  // masked-STOP (python parity)
        int ctype = b & 0x0F;
        int delta = (b >> 4) & 0x0F;
        if (delta) {
            last += delta;
        } else {
            i64 fid;
            int rc = t_zigzag(buf, n, &pos, &fid);
            if (rc) return rc;
            last = fid;
        }
        int rc = 0;
        if (last >= 1 && last <= 4 && ctype == 0x05) {
            i64 v;
            rc = t_zigzag(buf, n, &pos, &v);
            if (!rc) {
                out[last - 1] = v;
                mask |= (u64)1 << (last - 1);
            }
        } else if (last == 5 && ctype == 0x0C) {
            // last occurrence wins (python setattr replaces the object) —
            // including the sub-struct's statistics bank
            for (int i = 4; i <= 7; i++) { out[i] = 0; mask &= ~((u64)1 << i); }
            for (int i = 20; i <= 29; i++) { out[i] = 0; mask &= ~((u64)1 << i); }
            mask &= ~((u64)1 << 58);
            rc = t_sub_struct(buf, n, &pos, dph_w, dph_s, 5, out, &mask,
                              5, 20, 58);
            if (!rc) mask |= (u64)1 << 60;
        } else if (last == 6 && ctype == 0x0C) {
            // IndexPageHeader is an empty struct: walk it, record presence
            rc = t_skip_struct(buf, n, &pos, 0);
            if (!rc) mask |= (u64)1 << 59;
        } else if (last == 7 && ctype == 0x0C) {
            for (int i = 8; i <= 10; i++) { out[i] = 0; mask &= ~((u64)1 << i); }
            rc = t_sub_struct(buf, n, &pos, dict_w, dict_s, 3, out, &mask,
                              0, 0, 0);
            if (!rc) mask |= (u64)1 << 61;
        } else if (last == 8 && ctype == 0x0C) {
            for (int i = 11; i <= 17; i++) { out[i] = 0; mask &= ~((u64)1 << i); }
            for (int i = 30; i <= 39; i++) { out[i] = 0; mask &= ~((u64)1 << i); }
            mask &= ~((u64)1 << 57);
            rc = t_sub_struct(buf, n, &pos, v2_w, v2_s, 8, out, &mask,
                              8, 30, 57);
            if (!rc) mask |= (u64)1 << 62;
        } else if (ctype != 0x01 && ctype != 0x02) {
            rc = t_skip(buf, n, &pos, ctype, 0);
        }
        if (rc) return rc;
    }
    out[18] = (i64)mask;
    out[19] = pos;
    return 0;
}

// DELTA_BYTE_ARRAY prefix stitching (type_bytearray.go:189-292 semantics):
// value i = previous value's first prefix_lens[i] bytes + suffix i.  The
// chain is inherently sequential (SURVEY.md §7.4.4) — this runs it at memcpy
// speed.  All offset arrays are caller-validated cumulative sums; the only
// data-dependent check is the prefix-vs-previous-length bound.
// Returns 0, or -30 when value i's prefix exceeds the previous value's length.
i64 tpq_delta_ba_stitch(const i64 *prefix_lens, const i64 *suf_off,
                        const u8 *suf_heap, const i64 *out_off, u8 *heap,
                        i64 count) {
    i64 prev_start = 0, prev_len = 0;
    for (i64 i = 0; i < count; i++) {
        i64 p = prefix_lens[i];
        if (p > prev_len) return -30;
        i64 start = out_off[i];
        if (p) __builtin_memmove(heap + start, heap + prev_start, p);
        i64 sl = suf_off[i + 1] - suf_off[i];
        if (sl) __builtin_memcpy(heap + start + p, suf_heap + suf_off[i], sl);
        prev_start = start;
        prev_len = p + sl;
    }
    return 0;
}

// Narrow-int transcode support (device_reader._plan_narrow_ints): the host
// link is the scarce resource, so PLAIN INT columns whose value span fits in
// k < width bytes ship as (v - min) truncated to k little-endian bytes.
// These two passes replace a 4-temp numpy pipeline (min, max, subtract,
// strided copy) with two streaming loops gcc auto-vectorizes; unaligned
// sources are handled with memcpy loads (pages start at arbitrary offsets).

// min/max of n little-endian signed width-byte ints at buf+pos; width 4 or 8.
// Writes out[0]=min, out[1]=max.  n==0 leaves out untouched (caller guards).
void tpq_int_minmax(const u8 *buf, i64 pos, i64 n, int width, i64 *out) {
    const u8 *src = buf + pos;
    if (n <= 0) return;
    // ternary (branchless) reductions: -O3 vectorizes these into packed
    // min/max, ~4-8x the branchy compare on span probes over whole chunks
    if (width == 8) {
        i64 mn = INT64_MAX, mx = INT64_MIN;
        for (i64 i = 0; i < n; i++) {
            i64 v;
            __builtin_memcpy(&v, src + i * 8, 8);
            mn = v < mn ? v : mn;
            mx = v > mx ? v : mx;
        }
        out[0] = mn;
        out[1] = mx;
    } else {
        int32_t mn = INT32_MAX, mx = INT32_MIN;
        for (i64 i = 0; i < n; i++) {
            int32_t v;
            __builtin_memcpy(&v, src + i * 4, 4);
            mn = v < mn ? v : mn;
            mx = v > mx ? v : mx;
        }
        out[0] = mn;
        out[1] = mx;
    }
}

// Write (v - bias) mod 2^(8*width) truncated to its k low bytes, for each of
// n width-byte values at buf+pos, densely into dst (n*k bytes).  The caller
// guarantees the span fits k bytes, so truncation is lossless.
// k-specialized loops: a fixed-size store compiles to a plain mov (and the
// w8 cases vectorize); the generic memcpy-with-runtime-k form cost ~2.5
// ns/value on the 100M-row transcode path.
#define TPQ_TRUNC_LOOP(W, K)                                      \
    for (i64 i = 0; i < n; i++) {                                 \
        u64 v = 0;                                                \
        __builtin_memcpy(&v, src + i * (W), (W));                 \
        u64 d = v - bias;                                         \
        __builtin_memcpy(dst + i * (K), &d, (K));                 \
    }
void tpq_int_truncate(const u8 *buf, i64 pos, i64 n, int width, u64 bias,
                      int k, u8 *dst) {
    const u8 *src = buf + pos;
    if (width == 8) {
        switch (k) {
        case 1: TPQ_TRUNC_LOOP(8, 1); return;
        case 2: TPQ_TRUNC_LOOP(8, 2); return;
        case 3: TPQ_TRUNC_LOOP(8, 3); return;
        case 4: TPQ_TRUNC_LOOP(8, 4); return;
        case 5: TPQ_TRUNC_LOOP(8, 5); return;
        }
    } else if (width == 4) {
        switch (k) {
        case 1: TPQ_TRUNC_LOOP(4, 1); return;
        case 2: TPQ_TRUNC_LOOP(4, 2); return;
        }
    }
    TPQ_TRUNC_LOOP(width, k);
}
#undef TPQ_TRUNC_LOOP

// ---------------------------------------------------------------------------
// Device-side snappy expansion: the host parses ONLY the tag structure of a
// raw snappy stream into op tables; the actual byte movement (literal
// stitching + back-reference resolution) runs on the TPU as gathers
// (device_reader._plan_device_snappy).  This walk touches ~1 tag byte per
// ~60 payload bytes, so eligible pages skip host decompression entirely and
// ship compressed.
//
// Per op i (in stream order, output-contiguous):
//   dst_end[i]  cumulative output end of op i (within this stream)
//   src[i]      literal: byte offset of the run's payload in the COMPRESSED
//               stream; copy: the back-reference offset
//   is_lit[i]   1 literal / 0 copy
// Copy semantics for the device: output byte dst_start+j of a copy op reads
// output position dst_start - offset + (j mod offset) — the mod form makes
// overlapping (RLE-style) copies jump straight past the op, so every chain
// hop crosses an op boundary and pointer-doubling converges in
// log2(max_chain_depth) rounds.  The exact max depth is computed here with
// an incremental segment-tree max over op slots.
//
// Returns n_ops >= 0, or a negative TERR-style code on malformed input
// (same reject set as tpq_snappy_decompress).  out[0] = uncompressed size,
// out[1] = max chain depth.  cap is the op-table capacity; -10 = cap
// exceeded (callers size cap = n/2+2, the provable worst case, so -10 is
// unreachable from that sizing).

static inline i64 seg_query(const i64 *tree, i64 cap2, i64 lo, i64 hi) {
    // max over [lo, hi) of the segment tree (iterative, 0-based leaves)
    i64 best = 0;
    for (lo += cap2, hi += cap2; lo < hi; lo >>= 1, hi >>= 1) {
        if (lo & 1) { if (tree[lo] > best) best = tree[lo]; lo++; }
        if (hi & 1) { hi--; if (tree[hi] > best) best = tree[hi]; }
    }
    return best;
}

static inline void seg_update(i64 *tree, i64 cap2, i64 i, i64 v) {
    i += cap2;
    tree[i] = v;
    for (i >>= 1; i >= 1; i >>= 1) {
        i64 m = tree[2 * i] > tree[2 * i + 1] ? tree[2 * i] : tree[2 * i + 1];
        if (tree[i] == m) break;
        tree[i] = m;
    }
}

i64 tpq_snappy_plan(const u8 *src, i64 n, i64 expect,
                    i64 *dst_end, i64 *op_src, u8 *is_lit, i64 cap,
                    i64 *seg_tree, i64 cap2, i64 *out) {
    i64 pos = 0;
    // uncompressed-length uvarint
    u64 ulen = 0;
    int shift = 0;
    while (1) {
        if (pos >= n) return -2;
        u8 b = src[pos++];
        if (shift == 28 && (b & 0xf0)) return -2;
        ulen |= (u64)(b & 0x7f) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
        if (shift > 28) return -2;
    }
    if ((i64)ulen != expect) return -3;
    i64 outp = 0, nops = 0, maxdepth = 0;
    while (pos < n) {
        u8 tag = src[pos++];
        u32 kind = tag & 3;
        i64 len, offset = 0;
        if (kind == 0) {  // literal
            len = tag >> 2;
            if (len >= 60) {
                i64 extra = len - 59;
                if (pos + extra > n) return -4;
                len = 0;
                for (i64 i = 0; i < extra; i++)
                    len |= (i64)src[pos + i] << (8 * i);
                pos += extra;
            }
            len += 1;
            if (pos + len > n || outp + len > (i64)ulen) return -5;
            if (nops >= cap) return -10;
            dst_end[nops] = outp + len;
            op_src[nops] = pos;
            is_lit[nops] = 1;
            seg_update(seg_tree, cap2, nops, 0);
            nops++;
            pos += len;
            outp += len;
        } else {
            if (kind == 1) {
                if (pos >= n) return -6;
                len = ((tag >> 2) & 7) + 4;
                offset = ((i64)(tag >> 5) << 8) | src[pos];
                pos += 1;
            } else if (kind == 2) {
                if (pos + 2 > n) return -6;
                len = (tag >> 2) + 1;
                offset = (i64)src[pos] | ((i64)src[pos + 1] << 8);
                pos += 2;
            } else {
                if (pos + 4 > n) return -6;
                len = (tag >> 2) + 1;
                offset = (i64)src[pos] | ((i64)src[pos + 1] << 8) |
                         ((i64)src[pos + 2] << 16) | ((i64)src[pos + 3] << 24);
                pos += 4;
            }
            if (offset == 0 || offset > outp) return -7;
            if (outp + len > (i64)ulen) return -8;
            if (nops >= cap) return -10;
            // chain depth: 1 + max depth of ops covering the source range
            // [outp-offset, min(outp, outp-offset+len)) — the mod form never
            // reads at/after outp
            i64 s = outp - offset;
            i64 e = s + len < outp ? s + len : outp;
            // ops covering [s, e): first op with dst_end > s .. first with
            // dst_end >= e (inclusive) — binary search over dst_end[0..nops)
            i64 lo = 0, hi = nops;
            while (lo < hi) {
                i64 mid = (lo + hi) >> 1;
                if (dst_end[mid] > s) hi = mid; else lo = mid + 1;
            }
            i64 j1 = lo;
            lo = 0; hi = nops;
            while (lo < hi) {
                i64 mid = (lo + hi) >> 1;
                if (dst_end[mid] >= e) hi = mid; else lo = mid + 1;
            }
            i64 j2 = lo < nops ? lo + 1 : nops;
            i64 d = 1 + seg_query(seg_tree, cap2, j1, j2);
            if (d > maxdepth) maxdepth = d;
            dst_end[nops] = outp + len;
            op_src[nops] = offset;
            is_lit[nops] = 0;
            seg_update(seg_tree, cap2, nops, d);
            nops++;
            outp += len;
        }
    }
    if (outp != (i64)ulen) return -9;
    out[0] = outp;
    out[1] = maxdepth;
    return nops;
}

// ---------------------------------------------------------------------------
// Writer-side dictionary build: first-appearance uniquing with an open-
// addressing hash table (FNV-1a + linear probe).  Replaces the numpy
// unique-on-hashes path (argsort-bound, ~80% of dict-encode time on string
// columns) with one O(n) pass at memory speed.  `slots` (caller-allocated,
// nslots = power of two >= 2n, pre-filled with -1) maps hash slot -> dict
// id; `firsts` records the value index of each dict id's first occurrence
// (ascending by construction = first-appearance order).  Returns the
// distinct count k, or -50 once it would exceed max_dict (the caller falls
// back to plain encoding, chunk_writer.go:188-207 MaxInt16 semantics).

// 8-bytes-at-a-time mix (multiply + xor-shift per word, splitmix-style
// finalizer): the per-byte FNV loop was ~half the whole dict-string write
// (~20 ops per typical value vs ~4 here); collision quality only affects
// probe counts — equality is always decided by memcmp.
static inline u64 tpq_hash_span(const u8 *p, i64 len) {
    u64 h = 0x9E3779B97F4A7C15ull ^ (u64)len;
    while (len >= 8) {
        u64 w;
        __builtin_memcpy(&w, p, 8);
        h = (h ^ w) * 0xFF51AFD7ED558CCDull;
        h ^= h >> 29;
        p += 8;
        len -= 8;
    }
    if (len) {
        u64 w = 0;
        for (i64 j = 0; j < len; j++) w |= (u64)p[j] << (8 * j);
        h = (h ^ w) * 0xFF51AFD7ED558CCDull;
        h ^= h >> 29;
    }
    return h ^ (h >> 32);
}

i64 tpq_dict_build_bytes(const i64 *offsets, const u8 *heap, i64 n,
                         i64 max_dict, i32 *slots, i64 nslots,
                         u32 *inverse, i64 *firsts) {
    i64 k = 0;
    u64 mask = (u64)nslots - 1;
    for (i64 i = 0; i < n; i++) {
        i64 a = offsets[i], len = offsets[i + 1] - a;
        u64 s = tpq_hash_span(heap + a, len) & mask;
        for (;;) {
            i32 v = slots[s];
            if (v < 0) {
                if (k >= max_dict) return -50;
                slots[s] = (i32)k;
                firsts[k] = i;
                inverse[i] = (u32)k;
                k++;
                break;
            }
            i64 fa = offsets[firsts[v]];
            if (offsets[firsts[v] + 1] - fa == len &&
                __builtin_memcmp(heap + fa, heap + a, (u64)len) == 0) {
                inverse[i] = (u32)v;
                break;
            }
            s = (s + 1) & mask;
        }
    }
    return k;
}

i64 tpq_dict_build_fixed(const u8 *data, i64 n, i64 w, i64 max_dict,
                         i32 *slots, i64 nslots, u32 *inverse, i64 *firsts) {
    i64 k = 0;
    u64 mask = (u64)nslots - 1;
    for (i64 i = 0; i < n; i++) {
        const u8 *p = data + i * w;
        u64 s = tpq_hash_span(p, w) & mask;
        for (;;) {
            i32 v = slots[s];
            if (v < 0) {
                if (k >= max_dict) return -50;
                slots[s] = (i32)k;
                firsts[k] = i;
                inverse[i] = (u32)k;
                k++;
                break;
            }
            if (__builtin_memcmp(data + firsts[v] * w, p, (u64)w) == 0) {
                inverse[i] = (u32)v;
                break;
            }
            s = (s + 1) & mask;
        }
    }
    return k;
}

}  // extern "C"

// Pack n unsigned values (u64, already < 2^width) into the LSB-first
// continuous bit stream the RLE/bit-packed hybrid and DELTA_BINARY_PACKED
// formats share.  out must hold ceil(n*width/8) bytes; widths 1..56 (the
// accumulator holds width+7 pending bits).  The numpy encoder expanded a
// (n, width) bit matrix — ~25 ns/value; this loop is ~1 ns/value.
extern "C" void tpq_bp_pack(const uint64_t* vals, i64 n, i64 width, u8* out) {
    const u64 mask = width >= 64 ? ~(u64)0 : (((u64)1 << width) - 1);
    u64 acc = 0;
    int nb = 0;
    u8* o = out;
    for (i64 i = 0; i < n; i++) {
        acc |= (vals[i] & mask) << nb;
        nb += (int)width;
        while (nb >= 8) {
            *o++ = (u8)acc;
            acc >>= 8;
            nb -= 8;
        }
    }
    if (nb) *o++ = (u8)acc;
}
