"""Batched device reader: one staged buffer + one fused dispatch per chunk.

The page-at-a-time DeviceChunkDecoder (jax_decode.py) is correct but transfer-
latency-bound: every page pays several host→device staging calls, and over a
tunneled TPU each blocking transfer costs milliseconds regardless of size.
This reader restructures the decode around the transfer economics
(SURVEY.md §7.4.7 — pipelining beats any single kernel):

- per chunk, ALL pages' decompressed value bytes are assembled into ONE host
  buffer and staged with ONE async transfer;
- per-page stream structure is folded into chunk-global metadata tables
  (hybrid run tables with global bit offsets; per-page delta miniblock tables
  stacked for vmap), so each column decodes with ONE fused XLA dispatch;
- nothing blocks until ``finalize()``: staging and dispatches are async, the
  deferred dictionary-index range checks sync once at the end;
- dictionary string columns stay dictionary-encoded on device — (dict bytes,
  indices) like an Arrow DictionaryArray — and materialize lazily, because the
  gather output size is data-dependent and forcing it would sync per chunk.

Encoding coverage matches DeviceChunkDecoder; byte-array value streams decode
on host (inherently sequential, SURVEY.md §7.4.2/§7.4.4) and stage their
(offsets, heap) result in two async transfers.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from contextlib import nullcontext as _noop_ctx
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import jax_kernels as K
from .jax_kernels import scoped_x64
from .chunk_decode import _check_crc, walk_pages
from .column import ByteArrayData
from .kernels import bitpack
from .compress import decompress_block
from .footer import ParquetError
from .format import Encoding, PageType, Type, parse_encoding
from .iostore import require_full
from .scanplan import int_stats_span as _int_stats_span, row_group_chunks
from .jax_decode import (
    DeviceColumnData, ParsedDataPage, _bucket, _bucket_bytes, _bucket_count,
    _SLACK, _concat_jit, _concat_ragged_jit, _dict_gather_bytes_jit,
    _dict_rows_jit, _hybrid_jit, _hybrid_vw_jit, _max_jit, _plain_flba_jit,
    _plain_jit, _plain_rows_jit, _PTYPE_TO_NAME, _stack_jit,
    host_decode_dictionary, parse_data_page, parse_hybrid_meta, parse_delta_meta,
)
from .schema.core import SchemaNode
from .ship import (
    ChunkFacts, FUSED_ROUTES, ROUTE_DEVICE_SNAPPY, ROUTE_FUSED_NARROW_SNAPPY,
    ROUTE_FUSED_PLAIN, ROUTE_NARROW, ROUTE_NARROW_SNAPPY, ROUTE_PLAIN,
    ROUTE_RECOMPRESS, SNAPPY_WORTH_RATIO, ShipPlanner, default_planner,
)

__all__ = ["DeviceFileReader", "DeviceStats", "ReaderStats",
           "decode_chunk_batched", "DeviceDictColumn", "scan_files"]


@dataclass
class DeviceDictColumn(DeviceColumnData):
    """A dictionary-encoded device column: values stay as (dictionary, indices).

    ``indices`` uint32[n_defined]; the dictionary is either fixed-width byte
    rows (``dict_u8`` + ``dict_dtype``) or ragged (``dict_offsets``/``dict_heap``).
    ``materialize()`` gathers on device (fixed-width) or host (ragged).
    """

    indices: Optional[jax.Array] = None
    dict_u8: Optional[jax.Array] = None
    dict_dtype: Optional[str] = None
    dict_offsets: Optional[jax.Array] = None
    dict_heap: Optional[jax.Array] = None

    @scoped_x64
    def materialize(self) -> DeviceColumnData:
        if self.dict_u8 is not None:
            # padded tail indices are zeros (expand_rle_hybrid n_valid mask),
            # so the gather stays in bounds; n_values carries the real count
            vals = _dict_gather_bytes_jit(self.dict_u8, self.indices, dtype=self.dict_dtype)
            return DeviceColumnData(
                values=vals, def_levels=self.def_levels, rep_levels=self.rep_levels,
                max_def=self.max_def, max_rep=self.max_rep,
                num_leaf_slots=self.num_leaf_slots, value_dtype=self.value_dtype,
                n_values=self.n_values,
            )
        off = np.asarray(self.dict_offsets)
        heap = np.asarray(self.dict_heap)
        idx = np.asarray(self.indices, dtype=np.int64)[: self.num_values]
        host = ByteArrayData(offsets=off, heap=heap).take(idx)
        return DeviceColumnData(
            offsets=jnp.asarray(host.offsets), heap=jnp.asarray(host.heap),
            def_levels=self.def_levels, rep_levels=self.rep_levels,
            max_def=self.max_def, max_rep=self.max_rep,
            num_leaf_slots=self.num_leaf_slots,
        )

    @property
    def num_values(self) -> int:
        if self.n_values is not None:
            return self.n_values
        return int(self.indices.shape[0]) if self.indices is not None else 0

    def to_host(self):
        off_or_none = self.dict_offsets
        idx = np.asarray(self.indices, dtype=np.int64)[: self.num_values]
        if self.dict_u8 is not None:
            rows = np.asarray(self.dict_u8)
            n, nb = rows.shape
            if self.dict_dtype == "uint32":  # INT96
                return rows.view("<u4").reshape(n, -1)[idx]
            return rows[idx].copy().view(f"<{np.dtype(self.dict_dtype).str[1:]}").reshape(len(idx))
        return ByteArrayData(
            offsets=np.asarray(off_or_none), heap=np.asarray(self.dict_heap)
        ).take(idx)


@functools.partial(
    jax.jit,
    static_argnames=("values_per_mini", "mb", "count", "bits", "max_width",
                     "total", "n_pages", "m_max"),
)
def _delta_pages_staged_jit(buf, tbase, *, values_per_mini, mb, count, bits,
                            max_width, total, n_pages, m_max):
    """_delta_pages_jit with COMPACT metadata tables read from the staged
    buffer at ``tbase``.

    The format carries one min-delta varint and one payload position per
    BLOCK (``mb`` miniblocks), and miniblock payloads are contiguous within
    a block — so the tables ship per-block starts/mins plus one width BYTE
    per mini (layout: firsts i64[P] | block_starts i32[P,B] | widths u8[P,M]
    | block_mins u64[P,B] | page_starts i64[P+1], B = M/mb), ~4 bytes per
    mini instead of the 20 of the round-3 per-mini tables — the tables were
    rivaling the payload bytes on 32-value-mini streams.  Per-mini starts
    and mins expand here in-graph (a within-block exclusive cumsum of the
    widths and a repeat)."""
    P, M = n_pages, m_max
    B = M // mb
    o = 0
    firsts = _tslice(buf, tbase, o, P, jnp.int64); o += P * 8
    bstarts = _tslice(buf, tbase, o, P * B, jnp.int32).reshape(P, B); o += P * B * 4
    widths_u8 = _tslice(buf, tbase, o, P * M, jnp.uint8).reshape(P, M); o += P * M
    bmins = _tslice(buf, tbase, o, P * B, jnp.uint64).reshape(P, B); o += P * B * 8
    page_starts = _tslice(buf, tbase, o, P + 1, jnp.int64)
    widths = widths_u8.astype(jnp.int32)
    bpm = (widths * (values_per_mini // 8)).reshape(P, B, mb)
    excl = jnp.cumsum(bpm, axis=-1) - bpm  # within-block byte offsets
    starts = ((bstarts.astype(jnp.int64)[:, :, None] + excl)
              .reshape(P, M)) * 8  # bit starts (minis are byte-aligned)
    mins = jnp.repeat(bmins, mb, axis=1)
    return _delta_pages_jit(
        buf, firsts, starts, widths, mins, page_starts,
        values_per_mini=values_per_mini, count=count, bits=bits,
        max_width=max_width, total=total,
    )


@functools.partial(
    jax.jit,
    static_argnames=("values_per_mini", "count", "bits", "max_width", "total"),
)
def _delta_pages_jit(buf, firsts, starts, widths, mins, page_starts, *,
                     values_per_mini, count, bits, max_width, total):
    """Decode P delta pages; flatten to the per-page real extents in-graph.

    Every shape here is *bucketed* static (page count, per-page value count,
    total output), and the real per-page extents arrive as the traced
    ``page_starts`` (int64[P+1], cumulative defined counts, last = real
    total).  One executable therefore serves every delta chunk whose geometry
    lands in the same buckets — per-page exact counts as static args would
    compile a fresh program per chunk, which over a tunneled backend costs
    tens of seconds each.  Tail lanes (pad pages, output past the real total)
    gather clamped garbage that callers slice off via ``n_values``.
    """
    vals = jax.vmap(
        lambda f, s, w, m: K.delta_reconstruct(
            buf, f, s, w, m, values_per_mini, count, bits, max_width
        )
    )(firsts, starts, widths, mins)
    i = jnp.arange(total, dtype=jnp.int64)
    p = jnp.searchsorted(page_starts, i, side="right") - 1
    p = jnp.clip(p, 0, vals.shape[0] - 1)
    within = jnp.clip(i - page_starts[p], 0, count - 1)
    return vals[p, within]


@functools.partial(jax.jit, static_argnames=("count_pad", "heap_pad",
                                             "n_pages"))
def _plain_bytes_staged_jit(buf, lens_base, tbase, *, count_pad, heap_pad,
                            n_pages):
    """_plain_bytes_pages_jit with the page tables read from the staged
    buffer (layout: page_byte_base i64[P] | page_val_start i32[P+1])."""
    page_byte_base = _tslice(buf, tbase, 0, n_pages, jnp.int64)
    page_val_start = _tslice(buf, tbase, n_pages * 8, n_pages + 1, jnp.int32)
    return _plain_bytes_pages_jit(
        buf, lens_base, page_byte_base, page_val_start,
        count_pad=count_pad, heap_pad=heap_pad,
    )


def _bytes_heap_src(buf, lens_base, page_base, page_val_start, *, count_pad,
                    heap_pad):
    """Shared front half of the BYTE_ARRAY routes: staged lengths → offsets
    and each heap byte's source position in PAGE-STREAM coordinates.

      offsets  = cumsum(lens)                              (int64[count+1])
      value r of heap byte j via a scatter-of-run-ends + cumsum
      src[j]   = page_base[p] + within-page data offset + 4*(prefixes so far)

    ``page_base`` is staged-buffer coords on the plain route and
    OUTPUT-SPACE coords on the compressed-shipping routes (the caller picks
    the final indirection).  Returns (offsets, src)."""
    lens_raw = jax.lax.dynamic_slice(buf, (lens_base,), (count_pad * 4,))
    lens = jax.lax.bitcast_convert_type(
        lens_raw.reshape(count_pad, 4), jnp.uint32
    ).reshape(count_pad)
    offsets = jnp.concatenate([
        jnp.zeros(1, dtype=jnp.int64),
        jnp.cumsum(lens.astype(jnp.int64)),
    ])
    ends = jnp.clip(offsets[1:], 0, heap_pad)
    marks = jnp.zeros(heap_pad + 1, dtype=jnp.int32).at[ends].add(
        jnp.ones(count_pad, dtype=jnp.int32)
    )
    r = jnp.cumsum(marks[:heap_pad])  # value index of each heap byte
    r = jnp.clip(r, 0, count_pad - 1)
    p = jnp.searchsorted(page_val_start, r, side="right").astype(jnp.int32) - 1
    p = jnp.clip(p, 0, page_base.shape[0] - 1)
    pvs = page_val_start[p].astype(jnp.int64)
    j = jnp.arange(heap_pad, dtype=jnp.int64)
    src = (page_base[p]
           + (offsets[r] - offsets[pvs])        # data bytes before r in page
           + 4 * (r.astype(jnp.int64) - pvs + 1)  # prefixes up to & incl. r
           + (j - offsets[r]))                  # byte within value r
    return offsets, src


@functools.partial(jax.jit, static_argnames=("count_pad", "heap_pad"))
def _plain_bytes_pages_jit(buf, lens_base, page_byte_base, page_val_start,
                           *, count_pad, heap_pad):
    """PLAIN BYTE_ARRAY decode on device: lengths → offsets → heap compaction.

    The host walks ONLY the u32 length prefixes (native
    tpq_bytearray_lengths — O(values), no copies) and stages the RAW value
    streams plus the lengths; this kernel does everything that touches the
    value bytes (SURVEY §7.4.2's "sequential" length walk is sequential only
    in *finding* the lengths — once they are known, offsets are one cumsum
    and the heap compaction is data-parallel; see _bytes_heap_src).

    ``lens_base`` points at the staged uint32 lengths (zero-filled past the
    real count, so pad values are empty).  ``page_val_start`` int32[P+1]
    cumulative value counts; ``page_byte_base`` int64[P] staged byte base of
    each page's raw stream.  Returns (offsets int64[count_pad+1],
    heap uint8[heap_pad]) — callers slice by the real counts.
    """
    offsets, src = _bytes_heap_src(
        buf, lens_base, page_byte_base, page_val_start,
        count_pad=count_pad, heap_pad=heap_pad,
    )
    heap = buf[jnp.clip(src, 0, buf.shape[0] - 1)]
    return offsets, heap


@functools.partial(
    jax.jit,
    static_argnames=("count_pad", "heap_pad", "n_ops", "out_pad", "iters",
                     "n_pages"),
)
def _snappy_bytes_staged_jit(buf, lens_base, tbase, *, count_pad, heap_pad,
                             n_ops, out_pad, iters, n_pages):
    """BYTE_ARRAY heap compaction with the value streams shipped COMPRESSED
    (ship.py ROUTE_DEVICE_SNAPPY / ROUTE_RECOMPRESS — byte-array heaps are
    the lineitem16 byte mover the round-5 VERDICT named).  Identical to
    _plain_bytes_pages_jit except each heap byte's page-stream position is
    an OUTPUT-SPACE coordinate resolved through the snappy source map — one
    extra gather composes the two routes.

    Layout at ``tbase``: op tables (_SNAPPY_OPS_BYTES * n_ops) |
    page_out_base i64[P] | page_val_start i32[P+1].
    """
    S = _resolve_snappy_staged(buf, tbase, n_ops=n_ops, out_pad=out_pad,
                               iters=iters)
    o = _SNAPPY_OPS_BYTES * n_ops
    page_out = _tslice(buf, tbase, o, n_pages, jnp.int64); o += 8 * n_pages
    pvs = _tslice(buf, tbase, o, n_pages + 1, jnp.int32)
    offsets, src = _bytes_heap_src(
        buf, lens_base, page_out, pvs, count_pad=count_pad, heap_pad=heap_pad,
    )
    src32 = jnp.clip(src, 0, out_pad - 1).astype(jnp.int32)
    heap = buf[jnp.clip(S[src32], 0, buf.shape[0] - 1)]
    return offsets, heap


def _fused_words_cast(words, dtype: str):
    """Finished little-endian u32 words from a fused megakernel -> the
    value array (same dtype conventions as plain_decode_fixed: DOUBLE
    stays u32 word pairs — TPU f64 emulation rounds real data).  Runs in
    the plan fn's ambient x64 trace; the kernels themselves are x64-free."""
    if dtype == "float64":
        return words
    if dtype == "int64":
        return jax.lax.bitcast_convert_type(words, jnp.int64)
    return jax.lax.bitcast_convert_type(
        words.reshape(-1), jnp.int32 if dtype == "int32" else jnp.float32)


def _narrow_widen(raw, bias, *, k, dtype, count):
    """Widen ``k``-byte little-endian rows and re-bias: ``v = min +
    zero_extend(bytes)`` (the shared back half of both narrow routes).  All
    arithmetic is modular, so the reconstruction is exact for any int range
    whose *span* fits ``k`` bytes, including negative minima."""
    lo = jnp.zeros((count,), jnp.uint32)
    for i in range(min(k, 4)):
        lo = lo | (raw[:, i].astype(jnp.uint32) << (8 * i))
    if dtype == "int32":
        return jax.lax.bitcast_convert_type(
            bias.astype(jnp.uint32) + lo, jnp.int32
        )
    hi = jnp.zeros((count,), jnp.uint32)
    for i in range(4, k):
        hi = hi | (raw[:, i].astype(jnp.uint32) << (8 * (i - 4)))
    u = lo.astype(jnp.uint64) | (hi.astype(jnp.uint64) << 32)
    return jax.lax.bitcast_convert_type(bias.astype(jnp.uint64) + u, jnp.int64)


@functools.partial(jax.jit, static_argnames=("k", "dtype", "count"))
def _plain_narrow_jit(buf, base, bias, *, k, dtype, count):
    """Reconstruct a narrow-transcoded PLAIN INT column.

    The host shipped ``(v - min)`` truncated to ``k`` little-endian bytes per
    value (see _ChunkAssembler._plan_narrow_ints); this widens and re-biases
    (_narrow_widen).  ``bias`` is traced (per-chunk data); only
    (k, dtype, count) key the executable.
    """
    raw = jax.lax.dynamic_slice(buf, (base,), (count * k,)).reshape(count, k)
    return _narrow_widen(raw, bias, k=k, dtype=dtype, count=count)


# packed op-table bytes per op slot: ends/asrc/offs int32 + islit uint8.
# Route tables packed behind the ops start at tbase + _SNAPPY_OPS_BYTES*n_ops.
_SNAPPY_OPS_BYTES = 13


def _resolve_snappy_staged(buf, tbase, *, n_ops, out_pad, iters):
    """Slice the packed op tables at ``tbase`` back out of the staged buffer
    and resolve the output-space source map (jax_kernels.snappy_resolve —
    the shared device half of every compressed-shipping route).  Trace-time
    helper; statics (n_ops, out_pad, iters) ride the consuming jit's key."""
    o = 0
    ends = _tslice(buf, tbase, o, n_ops, jnp.int32); o += 4 * n_ops
    asrc = _tslice(buf, tbase, o, n_ops, jnp.int32); o += 4 * n_ops
    offs = _tslice(buf, tbase, o, n_ops, jnp.int32); o += 4 * n_ops
    islit = _tslice(buf, tbase, o, n_ops, jnp.uint8)
    return K.snappy_resolve(ends, asrc, offs, islit, out_pad=out_pad,
                            iters=iters)


@functools.partial(
    jax.jit,
    static_argnames=("n_ops", "out_pad", "iters", "dtype", "count", "n_pages"),
)
def _snappy_plain_staged_jit(buf, tbase, *, n_ops, out_pad, iters, dtype,
                             count, n_pages):
    """Decompress snappy PLAIN pages ON DEVICE and decode their values.

    The host shipped the COMPRESSED page payloads plus tag-walk op tables
    (native tpq_snappy_plan; see _plan_device_snappy).  Byte movement — the
    actual decompression — happens in ``snappy_resolve`` as gathers; this
    kernel then gathers each value's bytes through the source map and
    bitcasts (plain_decode_fixed).

    Output positions past the real total resolve through padded literal ops
    (src 0) and are never selected by the value gather.  All math is int32 —
    the planner falls back to host decompression beyond 2 GiB arenas.
    """
    S = _resolve_snappy_staged(buf, tbase, n_ops=n_ops, out_pad=out_pad,
                               iters=iters)
    o = _SNAPPY_OPS_BYTES * n_ops
    vbase = _tslice(buf, tbase, o, n_pages, jnp.int32); o += 4 * n_pages
    vstart = _tslice(buf, tbase, o, n_pages + 1, jnp.int32)
    width = 8 if dtype in ("int64", "float64") else 4
    i = jnp.arange(count, dtype=jnp.int32)
    p = jnp.clip(
        jnp.searchsorted(vstart, i, side="right").astype(jnp.int32) - 1,
        0, n_pages - 1,
    )
    vpos = vbase[p] + (i - vstart[p]) * width
    byte_idx = (vpos[:, None]
                + jnp.arange(width, dtype=jnp.int32)[None, :]).reshape(-1)
    src = S[jnp.clip(byte_idx, 0, out_pad - 1)]
    bts = buf[jnp.clip(src, 0, buf.shape[0] - 1)]
    return K.plain_decode_fixed(bts, dtype, count)


@functools.partial(
    jax.jit, static_argnames=("n_ops", "out_pad", "iters", "k", "dtype",
                              "count"),
)
def _snappy_narrow_staged_jit(buf, tbase, bias, *, n_ops, out_pad, iters, k,
                              dtype, count):
    """The narrow+snappy composition: the host shipped SNAPPY over the
    ``k``-byte narrow transcode (ship.py ROUTE_NARROW_SNAPPY), so the two
    transfer cuts multiply — narrow residuals are low-entropy and compress
    far below their already-truncated width.  Resolve the stream's output
    space, gather the rows, widen and re-bias (_narrow_widen).  Rows past
    the real count resolve through padded ops — callers slice by
    ``n_values``."""
    S = _resolve_snappy_staged(buf, tbase, n_ops=n_ops, out_pad=out_pad,
                               iters=iters)
    idx = jnp.arange(count * k, dtype=jnp.int32)
    src = S[jnp.clip(idx, 0, out_pad - 1)]
    raw = buf[jnp.clip(src, 0, buf.shape[0] - 1)].reshape(count, k)
    return _narrow_widen(raw, bias, k=k, dtype=dtype, count=count)


@functools.partial(
    jax.jit, static_argnames=("n_ops", "out_pad", "iters", "nbytes"),
)
def _snappy_gather_staged_jit(buf, tbase, *, n_ops, out_pad, iters, nbytes):
    """Materialize the first ``nbytes`` of a snappy stream's output space
    (dictionary value tables, ragged dictionary heaps).  Positions past the
    real output resolve through padded literal ops to staged byte 0 —
    consumers never index them (every valid dictionary index is <
    dict_len; the deferred-check path raises at finalize before clamped
    garbage can escape)."""
    S = _resolve_snappy_staged(buf, tbase, n_ops=n_ops, out_pad=out_pad,
                               iters=iters)
    idx = jnp.arange(nbytes, dtype=jnp.int32)
    src = S[jnp.clip(idx, 0, out_pad - 1)]
    return buf[jnp.clip(src, 0, buf.shape[0] - 1)]


# pointer-doubling round buckets (static arg: executable sharing); 24 covers
# chains of 2^24 ops — more ops than a 16 MiB page can encode
_SNAPPY_ITER_BUCKETS = (2, 4, 8, 16, 24)
# op-table cap: a stream shattered into more ops than this ships decompressed
# (the table would rival the payload)
_SNAPPY_MAX_OPS = 1 << 20
# ratio~1 chunks larger than this take the host-decompress path: the device
# resolve (searchsorted + doubling gathers over the output space) costs more
# than host snappy at ~1.4 GB/s once the chunk spans multiple strips
_SNAPPY_SMALL_OUT = 8 << 20


# transcode only when it saves >= 3 bytes/value: below that the extra host
# pass (min/max + truncating copy) buys too little transfer
_NARROW_SAVE_BYTES = 3
# probe the first page's head before scanning the whole chunk: full-range
# data (8-byte spans) must not pay a full min/max pass just to bail
_NARROW_PROBE = 65536


def _check_plain_sizes(pages, width: int) -> None:
    """Reject PLAIN pages whose value stream is shorter than defined*width
    (shared by every fixed-width staging/transcode/expansion planner)."""
    for p in pages:
        nbytes = (p.comp[2] if p.comp is not None
                  else len(p.raw) - p.value_pos)
        if nbytes < p.defined * width:
            raise ParquetError(
                f"PLAIN data truncated: {nbytes} < {p.defined * width}"
            )


def _span_bytes(lo: int, hi: int) -> int:
    """Bytes needed for the unsigned span hi - lo (>= 1)."""
    return max((int(hi) - int(lo)).bit_length() + 7, 8) // 8


def _narrow_max_k(width: int) -> int:
    """Largest transcoded byte width still worth the host pass.

    Shared by the narrow planner AND _plan_device_snappy's stats-hint
    routing: the two must agree bit for bit, or a chunk each side expects
    the other to claim would silently pay host decompression and full-width
    staging.
    """
    return width - (_NARROW_SAVE_BYTES if width == 8 else 2)


@functools.partial(jax.jit, static_argnames=("count",))
def _bool_pages_jit(buf, page_byte_base, page_val_start, *, count):
    """PLAIN booleans across pages: bit position restarts at each page base."""
    i = jnp.arange(count, dtype=jnp.int64)
    p = jnp.searchsorted(page_val_start, i, side="right") - 1
    p = jnp.clip(p, 0, page_val_start.shape[0] - 1)
    bit_pos = page_byte_base[p] * 8 + (i - page_val_start[p])
    return K.extract_bits(buf, bit_pos, 1, 1).astype(jnp.bool_)


@functools.partial(jax.jit, static_argnames=("size",))
def _fit_rows_jit(x, *, size):
    """Zero-pad leading-axis rows up to ``size`` (batch-carry capacity)."""
    pad = size - x.shape[0]
    return jnp.concatenate(
        [x, jnp.zeros((pad,) + x.shape[1:], dtype=x.dtype)]
    )


@jax.jit
def _roll_rows_jit(x, shift):
    """Roll rows by a traced shift (batch-carry compaction)."""
    return jnp.roll(x, shift, axis=0)


@jax.jit
def _update_rows_jit(x, update, pos):
    """Write ``update`` rows at a traced offset (batch-carry append)."""
    return jax.lax.dynamic_update_slice(
        x, update, (pos,) + (0,) * (x.ndim - 1)
    )


@functools.partial(jax.jit, static_argnames=("size",))
def _dynslice_jit(buf, start, *, size):
    """Slice ``size`` leading rows at a traced offset (static size, bucketed
    by the caller so executables are shared across chunks/batches)."""
    return jax.lax.dynamic_slice(
        buf, (start,) + (0,) * (buf.ndim - 1), (size,) + buf.shape[1:]
    )


class _RowGroupStager:
    """One staged host→device transfer for a whole row group.

    The tunneled TPU backend charges a fixed ~50-100ms round trip per
    transfer, so per-chunk staging (~8 MB each) runs at a fraction of link
    bandwidth.  Every chunk registers its host byte regions here (value
    streams, level arrays, byte-array heaps); ``stage()`` ships ONE buffer and
    each chunk's kernels address into it by base offset — the transfer
    granularity and the executable granularity are decoupled.

    With an ``executor`` (the reader's staging worker), registration also
    *streams*: every time the arena grows past a 16 MiB strip boundary the
    completed strip is copied and device_put on the worker while the main
    thread is still decompressing the row group's remaining chunks, and
    ``stage()`` concatenates the strips on device.  That overlaps host parse
    with transfer *within* a row group — the single-large-row-group case
    (one 128 MB group per file) that the cross-row-group pipeline cannot
    overlap at all.  Row groups smaller than one strip take the original
    single-buffer path byte for byte, so small-file executable shapes (and
    the warm compile cache) are untouched.
    """

    STRIP = 16 << 20

    def __init__(self, executor=None):
        # ("arr", u8, base, nbytes) | ("segs", segments, base, nbytes)
        self._parts: list[tuple] = []
        self.total = 0
        self._max_read_end = 0
        self._ex = executor
        self._strip_futs: list = []
        self._flushed = 0  # arena bytes handed to strip jobs (STRIP multiple)

    def _reserve(self, nbytes: int, reserve: int | None) -> int:
        base = self.total
        room = max(reserve or 0, nbytes)
        # keep every region 64-byte aligned for clean device layouts
        self.total = base + room + (-(base + room)) % 64
        return base

    def add(self, arr: np.ndarray, reserve: int | None = None) -> int:
        """Register a host array; returns its byte offset in the staged buffer.

        ``reserve`` rounds the region up (tail zero-filled) so callers can
        device-slice a bucketed size without reading past the arena.
        """
        u8 = arr.reshape(-1).view(np.uint8) if arr.dtype != np.uint8 else arr.reshape(-1)
        base = self._reserve(u8.nbytes, reserve)
        self._parts.append(("arr", u8, base, u8.nbytes))
        self._flush_ready()
        return base

    def _copy_range(self, buf: np.ndarray, lo: int, hi: int) -> None:
        """Copy every registered byte in [lo, hi) into ``buf``, zeroing only
        the GAPS (alignment padding + zero-filled reserves) — a full 16 MiB
        memset per strip re-wrote the whole scan's staged volume once over
        (~1 s of a 100M-row rep).  Parts are appended in ascending base
        order and never mutated, so a worker thread may scan the list while
        the main thread appends."""
        pos = lo
        for kind, payload, base, nbytes in self._parts:
            if base >= hi:
                break
            if base + nbytes <= lo:
                continue
            s = max(lo, base)
            if s > pos:
                buf[pos - lo : s - lo] = 0  # reserve tail / alignment gap
            if kind == "arr":
                e = min(hi, base + nbytes)
                buf[s - lo : e - lo] = payload[s - base : e - base]
                pos = e
            else:
                off = base
                for raw, start, size in payload:
                    if off >= hi:
                        break
                    if off + size > lo:
                        s = max(lo, off)
                        e = min(hi, off + size)
                        buf[s - lo : e - lo] = np.frombuffer(
                            raw, np.uint8, e - s, start + (s - off)
                        )
                        pos = e
                    off += size
        if pos < hi:
            buf[pos - lo :] = 0

    def _flush_ready(self) -> None:
        """Hand every newly completed strip to the worker (copy + device_put
        run there, overlapping the main thread's decompress/parse)."""
        if self._ex is None:
            return
        while self.total - self._flushed >= self.STRIP:
            lo = self._flushed
            self._flushed += self.STRIP

            def job(lo=lo, hi=self._flushed):
                buf = np.empty(self.STRIP, dtype=np.uint8)
                self._copy_range(buf, lo, hi)
                return jnp.asarray(buf)

            self._strip_futs.append(self._ex.submit(job))

    def add_segments(self, segments: list[tuple[bytes, int, int]]) -> np.ndarray:
        """Register byte slices (buf, offset, size) laid back to back.

        The slices are copied straight from their source buffers (decompressed
        page bytes) into the staged buffer during ``stage()`` — no per-chunk
        intermediate assembly copy.  Returns each slice's absolute byte base.
        """
        bases = np.empty(len(segments), dtype=np.int64)
        nbytes = 0
        for i, (_, _, size) in enumerate(segments):
            bases[i] = nbytes
            nbytes += size
        base = self._reserve(nbytes, None)
        self._parts.append(("segs", segments, base, nbytes))
        self._flush_ready()
        return bases + base

    def note_read_extent(self, base: int, nbytes: int) -> None:
        """Declare that a kernel will read ``nbytes`` from ``base`` — possibly
        past the registered region (bucketed static-size reads overlap the
        next chunk's bytes harmlessly; only the END of the staged buffer must
        cover the overhang).  ``stage()`` sizes the buffer to the maximum
        declared extent, so dynamic_slice reads never clamp/misalign."""
        self._max_read_end = max(self._max_read_end, base + nbytes)

    def stage(self) -> jax.Array:
        need = max(self.total, self._max_read_end)
        if not self._strip_futs:
            # single-transfer path (row group under one strip, or no worker)
            buf = np.empty(_bucket_bytes(need + _SLACK, 64), dtype=np.uint8)
            pos = 0
            for kind, payload, base, nbytes in self._parts:
                if base > pos:
                    buf[pos:base] = 0
                if kind == "arr":
                    buf[base : base + nbytes] = payload
                else:
                    off = base
                    for raw, start, size in payload:
                        buf[off : off + size] = np.frombuffer(raw, np.uint8,
                                                              size, start)
                        off += size
                pos = base + nbytes
            buf[pos:] = 0
            return jnp.asarray(buf)
        # streaming path: strips are already in flight; copy+ship the tail,
        # then assemble on device (HBM-bandwidth concat, one executable per
        # (strip count, tail bucket) shape set)
        tail_len = _bucket_bytes(need + _SLACK - self._flushed, 64)
        tail = np.empty(tail_len, dtype=np.uint8)
        self._copy_range(tail, self._flushed, self._flushed + tail_len)
        parts = [f.result() for f in self._strip_futs] + [jnp.asarray(tail)]
        self._strip_futs.clear()  # release strip buffers once concat owns them
        return _concat_jit(parts)


_CACHE_ENABLED = False


def _enable_compile_cache() -> None:
    """Enable jax's persistent compilation cache on first reader use.

    The decode executables are keyed by bucketed chunk geometry; on the
    tunneled backend each remote compile costs 10-30 s, and a fresh process
    re-opening the same file pays them all again (~180 s measured on the
    5M-row lineitem shapes).  With the persistent cache, re-opens are
    near-free across processes (measured 107 s → 5 s).

    Defers to the host application: a cache dir already configured (by the
    embedding program or via JAX_COMPILATION_CACHE_DIR, which jax reads
    itself) is left untouched.  The default path is per-user (world-shared
    /tmp paths are a collision/poisoning hazard on multi-user hosts).
    TPQ_COMPILE_CACHE=0 disables; any other value overrides the directory.
    """
    global _CACHE_ENABLED
    if _CACHE_ENABLED:
        return
    _CACHE_ENABLED = True
    env = os.environ.get("TPQ_COMPILE_CACHE", "")
    if env == "0":
        return
    try:
        if jax.config.jax_compilation_cache_dir:
            return  # application (or JAX_COMPILATION_CACHE_DIR) already chose
        # per-backend dir: CPU AOT entries compiled by one process flavor
        # can trip machine-feature mismatches when another loads them.
        # User-owned location (NOT world-writable /tmp, where another local
        # user could pre-create the path and poison the serialized
        # executables jax would then load); created 0700.
        cache_root = os.environ.get(
            "XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")
        )
        cache_dir = env or os.path.join(
            cache_root, f"tpq_jax_cache_{jax.default_backend()}"
        )
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        st = os.stat(cache_dir)
        if st.st_uid != os.getuid():
            return  # refuse a squatted directory; run uncached
        if st.st_mode & 0o022:
            # pre-existing dir with group/other write (permissive umask):
            # jax deserializes executables from here, so it cannot be
            # trusted as-is.  Only the DEFAULT XDG-derived path is ours to
            # tighten; a user-chosen TPQ_COMPILE_CACHE dir may be
            # group-writable on purpose (a shared team cache) — warn and
            # run uncached instead of silently stripping its permissions.
            if env:
                import warnings

                warnings.warn(
                    f"TPQ_COMPILE_CACHE directory {cache_dir!r} is "
                    f"group/other-writable; refusing to use it for "
                    f"deserialized executables (chmod it 0700, or accept "
                    f"uncached compiles)", RuntimeWarning, stacklevel=2,
                )
                return
            os.chmod(cache_dir, 0o700)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 — the cache is an optimization only
        pass


def _pallas_interpret_mode():
    """Whether hybrid decode routes through the Pallas unpack kernel.

    Returns None (off — use the XLA extract path), False (native Mosaic, the
    TPU default), or True (Pallas interpreter — CPU test parity).  Default-on
    for TPU backends per the round-3 directive: the plane kernel is the
    fastest unpack primitive in the repo and BP staging also drops the RLE
    bytes from the transfer.  ``TPQ_PALLAS=0`` forces the XLA path
    everywhere; ``TPQ_PALLAS=1`` forces the interpreter on non-TPU backends
    (tests A/B the two paths with it).
    """
    env = os.environ.get("TPQ_PALLAS", "").strip()
    if env == "0":
        return None
    from .pallas_kernels import pallas_available

    if pallas_available():
        return False
    return True if env == "1" else None


# BP payloads are staged as one host-side segment copy per bit-packed run;
# streams shattered into very many tiny runs (adversarial or ultra-alternating
# data) would make that copy loop the bottleneck, so they keep the XLA
# extract path whose staging is one segment per page.
_PALLAS_MAX_SEGS = 4096


def _pack_tables(stager: _RowGroupStager, arrays) -> int:
    """Pack np arrays into ONE staged region; returns its byte base.

    Every per-chunk metadata table shipped as its own ``jnp.asarray`` costs a
    full tunnel round trip (~2.5 ms measured) — at 800 chunks × 4 tables that
    is the dominant wall-clock at multi-GB scale, dwarfing the decode.
    Packing the tables into the row-group buffer makes them part of the ONE
    staged transfer; consuming jits slice them back out at static offsets
    (shapes are bucketed, so offsets are static relative to a traced base).
    Arrays are staged back to back in call order; callers compute the same
    static layout at trace time.
    """
    cat = np.concatenate([np.ascontiguousarray(a).reshape(-1).view(np.uint8)
                          for a in arrays])
    return stager.add(cat)


def _tslice(buf, base, off: int, n: int, dtype):
    """Slice a packed table back out of the staged buffer (trace-time
    helper; ``off``/``n`` static, ``base`` traced)."""
    nbytes = np.dtype(dtype).itemsize
    raw = jax.lax.dynamic_slice(buf, (base + off,), (n * nbytes,))
    if nbytes == 1:
        return raw
    return jax.lax.bitcast_convert_type(
        raw.reshape(n, nbytes), dtype
    ).reshape(n)


@functools.partial(jax.jit, static_argnames=("count", "rp"))
def _hybrid_combine_staged_jit(vals, buf, tbase, n_valid, *, count, rp):
    """Combine Pallas-unpacked BP values with RLE runs into stream order.

    ``vals`` uint32[8 * groups_pad] — BP groups unpacked from the contiguous
    staged payload.  Every output position finds its run with one
    searchsorted (same structure as expand_rle_hybrid), then either
    broadcasts the RLE value or picks its BP element at
    ``bp_idx_base[run] + pos`` — one u32 gather instead of per-value
    multi-byte extraction.  Run tables ride the staged buffer at ``tbase``
    (layout [ends i32 | is_rle u8 | values u32 | bp_idx_base i32] × rp —
    see _pack_tables); all index math is int32, so the trace is
    x64-agnostic."""
    ends = _tslice(buf, tbase, 0, rp, np.int32)
    isr = _tslice(buf, tbase, rp * 4, rp, np.uint8) != 0
    rvals = _tslice(buf, tbase, rp * 5, rp, np.uint32)
    bib = _tslice(buf, tbase, rp * 9, rp, np.int32)
    pos = jnp.arange(count, dtype=jnp.int32)
    r = jnp.searchsorted(ends, pos, side="right").astype(jnp.int32)
    r = jnp.minimum(r, rp - 1)
    bp_idx = jnp.clip(bib[r] + pos, 0, vals.shape[0] - 1)
    out = jnp.where(isr[r], rvals[r], vals[bp_idx])
    return jnp.where(pos < n_valid, out, jnp.zeros((), dtype=out.dtype))


def _plan_hybrid_pallas(stager: _RowGroupStager, pages_info, width: int,
                        total: int, count_pad: int, interpret: bool):
    """Plan a hybrid expansion through the Pallas BP-group kernel.

    ``pages_info``: [(HybridMeta, source_buffer, page_value_count)] in stream
    order.  Registers each bit-packed run's payload with the stager so the
    staged buffer holds ALL BP groups contiguously (RLE headers/values never
    ship — they live in the run table), then returns
    ``fn(buf_dev) -> uint32[count_pad]``.  Returns None when the stream has
    no Pallas-eligible shape (width 0, no BP groups, or a pathological run
    count) — callers fall back to the XLA extract path.
    """
    if width <= 0 or width > 32 or total > np.iinfo(np.int32).max:
        # i32 combine math covers byte bases AND value positions; >=2^31
        # value chunks keep the XLA path (int64 throughout)
        return None
    # one vectorized pass over the concatenated run tables (a per-page
    # Python loop here was ~30% of the nested config's host phase)
    ks = np.array([m.n_runs for m, _, _ in pages_info], dtype=np.int64)
    nr = int(ks.sum())
    if nr == 0:
        return None
    ends_c = np.concatenate([m.run_ends[: m.n_runs] for m, _, _ in pages_info])
    isr = np.concatenate([m.run_is_rle[: m.n_runs] for m, _, _ in pages_info])
    rvals = np.concatenate([m.run_values[: m.n_runs] for m, _, _ in pages_info])
    bst = np.concatenate(
        [m.run_bit_starts[: m.n_runs] for m, _, _ in pages_info]
    )
    run_page_start = np.repeat(np.cumsum(ks) - ks, ks)  # first run idx of page
    page_of = np.repeat(np.arange(len(ks)), ks)
    pcounts = np.array([c for _, _, c in pages_info], dtype=np.int64)
    prefix = np.concatenate([[0], np.cumsum(pcounts)[:-1]])
    # within-page run start = previous run's end (0 for a page's first run)
    rstart = np.empty(nr, np.int64)
    rstart[0] = 0
    rstart[1:] = ends_c[:-1]
    first = np.arange(nr) == run_page_start
    rstart[first] = 0
    # payload byte position in src coords: run_bit_starts stores
    # pos*8 - run_start*width (see parse_hybrid_meta)
    pay = (bst + rstart * width) >> 3
    groups = np.where(isr, 0, -(-(ends_c - rstart) // 8))
    sel = np.flatnonzero(groups > 0)
    if len(sel) > _PALLAS_MAX_SEGS or not len(sel):
        return None
    cumg = int(groups.sum())
    gbase = np.cumsum(groups) - groups  # exclusive prefix (global group base)
    ends = (ends_c + prefix[page_of]).astype(np.int32)
    bib = np.where(isr, 0,
                   gbase * 8 - (rstart + prefix[page_of])).astype(np.int32)
    srcs = [s for _, s, _ in pages_info]
    segs = [(srcs[p], int(b), int(g) * width)
            for p, b, g in zip(page_of[sel], pay[sel], groups[sel])]
    from .pallas_kernels import bp_groups_pad, unpack_bp_groups

    rp = _bucket(max(nr, 1))
    if rp > nr:
        pad = rp - nr
        ends = np.concatenate([ends, np.full(pad, total, np.int32)])
        isr = np.concatenate([isr, np.zeros(pad, bool)])
        rvals = np.concatenate([rvals, np.zeros(pad, np.uint32)])
        bib = np.concatenate([bib, np.zeros(pad, np.int32)])
    gpad = bp_groups_pad(cumg)
    if stager.total + gpad * width > np.iinfo(np.int32).max:
        # the kernel's x64-free trace addresses the staged buffer with i32;
        # a >=2 GiB stager region can't — the XLA extract path handles it
        # (checked before ANY stager mutation so fallback leaves no dead bytes)
        return None
    tbase = _pack_tables(stager, [ends, isr.astype(np.uint8), rvals, bib])
    bases = stager.add_segments(segs)
    bp_base = int(bases[0])
    # the unpack reads gpad*width bytes from bp_base: past the real payload
    # it sees later regions' bytes — garbage values the combine never
    # selects (positions past `total` are masked, real positions always map
    # into real groups)
    stager.note_read_extent(bp_base, gpad * width)

    def fn(buf_dev, bp_base_d, tbase_d, total_d):
        vals = unpack_bp_groups(buf_dev, bp_base_d, width, gpad,
                                interpret=interpret)
        return _hybrid_combine_staged_jit(
            vals, buf_dev, tbase_d, total_d, count=count_pad, rp=rp,
        )

    return _Plan(
        ("lvlp", width, gpad, rp, count_pad, bool(interpret)), fn,
        (np.int32(bp_base), np.int64(tbase), np.int32(total)), None,
        stages=2,  # pallas unpack pass + run-table combine pass
    )


def _merge_run_tables(ends_l, rle_l, vals_l, starts_l, fill_end,
                      widths_l=None):
    """Pad per-page hybrid run lists into one bucketed chunk-global table.

    Padding slots get ``run_ends = fill_end`` (so searchsorted clamps past
    the real runs) and zeros elsewhere.  Returns (ends, is_rle, values,
    starts[, widths]) — the argument set of expand_rle_hybrid(_vw).
    """
    rp = _bucket(max(sum(len(e) for e in ends_l), 1))
    ends = np.full(rp, fill_end, dtype=np.int64)
    is_rle = np.zeros(rp, dtype=bool)
    rvals = np.zeros(rp, dtype=np.uint32)
    starts = np.zeros(rp, dtype=np.int64)
    rwidths = np.zeros(rp, dtype=np.uint32) if widths_l is not None else None
    k = 0
    for i, e in enumerate(ends_l):
        ends[k : k + len(e)] = e
        is_rle[k : k + len(e)] = rle_l[i]
        rvals[k : k + len(e)] = vals_l[i]
        starts[k : k + len(e)] = starts_l[i]
        if rwidths is not None:
            rwidths[k : k + len(e)] = widths_l[i]
        k += len(e)
    if rwidths is not None:
        return ends, is_rle, rvals, starts, rwidths
    return ends, is_rle, rvals, starts


class _SnappyShipInfo:
    """Statics + staged table base of one planned compressed shipment."""

    __slots__ = ("tbase", "n_ops", "out_pad", "iters", "shipped", "total_out")

    def __init__(self, tbase, n_ops, out_pad, iters, shipped, total_out):
        self.tbase = tbase
        self.n_ops = n_ops
        self.out_pad = out_pad
        self.iters = iters
        self.shipped = shipped
        self.total_out = total_out


def _plan_snappy_ops(stager: _RowGroupStager, specs, extra_tables=()):
    """Register snappy/raw payloads and pack the op tables the device
    resolver (jax_kernels.snappy_resolve) consumes — the shared host half
    of every compressed-shipping route (ship.py).

    ``specs``: per stream, ``('comp', payload, out_len[, plan])`` — a
    raw-snappy payload whose uncompressed length is ``out_len`` (``plan``
    optionally carries a pre-run ``native.snappy_plan`` result) — or
    ``('raw', buf, pos, out_len)`` — host bytes shipped as one synthetic
    literal op.  Output spaces concatenate in spec order; callers compute
    out-space bases as the exclusive cumsum of out_lens.  ``extra_tables``
    pack behind the op tables at the same ``tbase`` (consuming jits slice
    them at ``_SNAPPY_OPS_BYTES * n_ops_pad``).

    Returns ``_SnappyShipInfo`` or None when infeasible (native library
    absent, stream rejected by the tag walk, op-table cap, i32 arena
    ceiling).  Infeasibility leaves the stager UNTOUCHED, so callers fall
    through to another route with no dead staged bytes.
    """
    from . import native

    if not native.available():
        return None
    plans = []
    n_ops_total = 0
    total_out = 0
    for spec in specs:
        if spec[0] == "comp":
            payload, out_len = spec[1], spec[2]
            r = spec[3] if len(spec) > 3 and spec[3] is not None else (
                native.snappy_plan(payload, out_len))
            if r is None or isinstance(r, int):
                return None
            plans.append((spec, r, out_len))
            n_ops_total += len(r[0])
        else:
            out_len = spec[3]
            plans.append((spec, None, out_len))
            n_ops_total += 1
        total_out += out_len
    if n_ops_total == 0 or n_ops_total > _SNAPPY_MAX_OPS:
        return None
    out_pad = _bucket_bytes(total_out + 8, 8)
    segs = [
        (spec[1], 0, len(spec[1])) if r is not None
        else (spec[1], spec[2], out_len)
        for spec, r, out_len in plans
    ]
    shipped = sum(s[2] for s in segs)
    n_ops_pad = _bucket(n_ops_total)
    extra_bytes = sum(np.ascontiguousarray(t).nbytes for t in extra_tables)
    if (stager.total + shipped + _SNAPPY_OPS_BYTES * n_ops_pad + extra_bytes
            + out_pad > (np.iinfo(np.int32).max >> 1)):
        return None  # i32 source/table math would overflow
    bases = stager.add_segments(segs)
    ends = np.empty(n_ops_total, np.int64)
    asrc = np.empty(n_ops_total, np.int64)
    offs = np.zeros(n_ops_total, np.int32)
    islit = np.empty(n_ops_total, np.uint8)
    at = 0
    out_base = 0
    max_depth = 0
    for (spec, r, out_len), base in zip(plans, bases):
        if r is None:
            ends[at] = out_base + out_len
            asrc[at] = base
            islit[at] = 1
            at += 1
        else:
            dst_end, op_src, is_lit_p, depth = r
            n = len(dst_end)
            if n:
                ends[at : at + n] = dst_end + out_base
                # literal: absolute staged position of the run's payload;
                # copy: chunk-out source base  dst_start - offset
                starts = np.empty(n, np.int64)
                starts[0] = 0
                starts[1:] = dst_end[:-1]
                asrc[at : at + n] = np.where(
                    is_lit_p != 0, op_src + base,
                    out_base + starts - op_src,
                )
                offs[at : at + n] = np.where(is_lit_p != 0, 1, op_src)
                islit[at : at + n] = is_lit_p
                at += n
                max_depth = max(max_depth, depth)
        out_base += out_len
    # `at` always lands on n_ops_total: raw specs write one op each and
    # comp specs exactly len(plan) (counted above)
    assert at == n_ops_total, (at, n_ops_total)
    iters = next(
        (b for b in _SNAPPY_ITER_BUCKETS
         if (1 << b) >= max_depth + 1), _SNAPPY_ITER_BUCKETS[-1]
    ) if max_depth > 0 else 0
    ends_t = np.full(n_ops_pad, out_pad, np.int32)
    ends_t[:n_ops_total] = ends
    asrc_t = np.zeros(n_ops_pad, np.int32)
    asrc_t[:n_ops_total] = asrc
    offs_t = np.ones(n_ops_pad, np.int32)
    offs_t[:n_ops_total] = offs
    islit_t = np.ones(n_ops_pad, np.uint8)
    islit_t[:n_ops_total] = islit
    tbase = _pack_tables(
        stager, [ends_t, asrc_t, offs_t, islit_t, *extra_tables]
    )
    return _SnappyShipInfo(tbase, n_ops_pad, out_pad, iters, shipped,
                           total_out)


def _fixed_value_tables(sizes, counts):
    """Bucket-padded (vbase, vstart) page tables for the fixed-width snappy
    routes: per-page OUT-SPACE byte bases (exclusive cumsum of ``sizes``)
    and cumulative defined ``counts``.  Layout twin of what
    _snappy_plain_staged_jit slices back out — one builder so its call
    sites (_plan_device_snappy, _plan_recompress_fixed) can never
    desynchronize.  Returns (vbase_t, vstart_t, pages_pad, defined)."""
    out_bases = np.concatenate([[0], np.cumsum(sizes)[:-1]]).astype(np.int64)
    vstart = np.concatenate([[0], np.cumsum(counts)])
    pages_pad = _bucket(len(sizes))
    vbase_t = np.zeros(pages_pad, np.int32)
    vbase_t[: len(sizes)] = out_bases
    vstart_t = np.full(pages_pad + 1, vstart[-1], np.int32)
    vstart_t[: len(sizes) + 1] = vstart
    return vbase_t, vstart_t, pages_pad, int(vstart[-1])


class _Plan:
    """A planned device computation: ``fn(buf_dev, *dyn) -> pytree``.

    The fused row-group dispatch (``_run_plans``) traces every chunk's plan
    into ONE jitted call per row group, so all per-chunk dynamic arguments
    ride a single batched transfer and the tunneled backend pays ONE
    dispatch per row group instead of one per chunk (the per-call
    scalar-argument `device_put`s were 4.9 s of a 27 s warm 100M-row rep).

    Contract — the correctness of the executable cache rests on it:

    - ``key`` must capture EVERY static the traced body closes over.  The
      fused executable for a row group is cached by the tuple of plan keys;
      a later row group with an equal key tuple reuses the FIRST row
      group's traced closures, so any per-row-group value not in ``dyn``
      and not in ``key`` silently decodes with stale state.
    - ``dyn`` carries all per-row-group values (numpy scalars/arrays).
      Shape changes are safe (jit respecializes); value changes through
      closures are not.
    - ``build(res)`` runs host-side with the jit outputs and the CURRENT
      row group's metadata (it is never cached).
    - ``fn=None`` marks a pass-through plan whose result was already
      materialized at prepare time (`_finish_host`); ``build(None)``
      returns it.
    - ``stages`` is the STRUCTURAL count of separate device passes the
      traced graph contains — XLA fusions with an HBM-materialized
      intermediate between them (slice → decode → validity is 3; the
      snappy chains add their pointer-doubling rounds; a fused Pallas
      megakernel is exactly 1).  It rides the completion timer into the
      registry ``device`` section as ``device_passes``: fused routes
      prove structurally (passes == dispatches) that the round trips are
      gone, where the unfused twins show ≥3 passes per dispatch.
    """

    __slots__ = ("key", "fn", "dyn", "build", "route", "bytes_in",
                 "bytes_staged", "stages")

    def __init__(self, key, fn, dyn, build, stages: "int | None" = None):
        self.key = key
        self.fn = fn
        self.dyn = tuple(dyn)
        self.build = build
        # device-timing attribution (set by _prepare_row_group from the
        # chunk's ship records): the dominant ship route plus the column's
        # logical/shipped byte totals — never part of the executable key
        self.route = None
        self.bytes_in = 0
        self.bytes_staged = 0
        self.stages = (stages if stages is not None
                       else (3 if fn is not None else 0))


_FUSED_CACHE: dict = {}
_FUSED_LOCK = threading.Lock()
# NOTE: whole-row-group fusion (one jit over every chunk's plan) was built
# and measured first: any per-row-group static flip (a narrow-transcode k,
# a snappy iter bucket) changes the FUSED signature and recompiles the
# entire 16-column graph — minutes per signature on the tunneled backend.
# Per-plan executables keep the round-4 cache granularity; the per-call
# transfer cost is killed by _memo_dev instead.
_FUSE_RG = os.environ.get("TPQ_FUSE_RG", "") == "1"

_DEV_MEMO: dict = {}
_DEV_MEMO_LOCK = threading.Lock()
_DEV_MEMO_MAX_ARRAY = 4096  # bytes; tables above this ride the staged buffer


def _memo_scope() -> tuple:
    """The (platform, device id) a bare device_put commits to right now.

    Keys are scoped by it so a default-device change mid-process (or a
    multi-backend embedder) never hands a plan an array committed to the
    wrong device."""
    d = jax.config.jax_default_device
    if isinstance(d, str):  # the config also accepts a platform string
        d = jax.devices(d)[0]
    elif d is None:
        d = jax.devices()[0]
    return (d.platform, d.id)


def _memo_dev(x):
    """Device-resident memo for small dynamic plan arguments.

    The staged-buffer layout of a uniform file is identical across row
    groups, so per-chunk scalar args (byte bases, table offsets, value
    counts) repeat with the SAME VALUES every row group.  Shipping each
    distinct value once and handing jit an already-committed device array
    makes later row groups' dispatches transfer-free — the per-call scalar
    `device_put`s were 4.9 s of a 27 s warm 100M-row rep on the tunneled
    backend (BENCH_SCALE20.md).

    Thread-safe (dispatches may come from pipeline threads) and
    self-healing: entries whose buffers were deleted out from under the
    memo (jax.clear_caches, backend teardown) are dropped and re-put rather
    than handed to a plan as dead arrays.  A racing double put is benign —
    both arrays are valid, last one stays cached."""
    if isinstance(x, np.generic):
        key = ("s", x.dtype.str, x.item())
    elif isinstance(x, np.ndarray):
        if x.ndim == 0:
            key = ("s", x.dtype.str, x.item())
        elif x.nbytes <= _DEV_MEMO_MAX_ARRAY:
            key = ("a", x.dtype.str, x.shape, x.tobytes())
        else:
            return x
    else:
        return x
    key = _memo_scope() + key
    with _DEV_MEMO_LOCK:
        hit = _DEV_MEMO.get(key)
    if hit is not None:
        try:
            if not hit.is_deleted():
                return hit
        except Exception:  # noqa: BLE001 — treat unknowable as dead
            pass
    fresh = jax.device_put(x)
    with _DEV_MEMO_LOCK:
        if len(_DEV_MEMO) > 8192:
            _DEV_MEMO.clear()
        _DEV_MEMO[key] = fresh
    return fresh


def _single_for(key, fn):
    """Per-plan jitted runner, cached by the plan's static key (so the
    executable set has exactly the round-4 granularity: one per
    (kernel-family, bucket) combination, never per row group)."""
    with _FUSED_LOCK:
        hit = _FUSED_CACHE.get(key)
        if hit is None:
            hit = jax.jit(fn)
            _FUSED_CACHE[key] = hit
        return hit


def _fused_for(key, fns, arities):
    """The jitted all-plans runner for a row-group signature (cached)."""
    with _FUSED_LOCK:
        hit = _FUSED_CACHE.get(key)
        if hit is not None:
            return hit

        def run_all(buf, dyn):
            outs, i = [], 0
            for fn, k in zip(fns, arities):
                outs.append(fn(buf, *dyn[i : i + k]))
                i += k
            return tuple(outs)

        jitted = jax.jit(run_all)
        _FUSED_CACHE[key] = jitted
        return jitted


def _run_plans(plans, buf_dev, timer: "_DeviceTimer | None" = None):
    """Execute ``[(name, _Plan)]`` against the staged buffer: pass-throughs
    directly, everything else through per-plan cached jits with
    device-memoized arguments (or one fused call under TPQ_FUSE_RG=1).

    With a ``timer`` (the reader's completion-timing lane), each traced
    plan's raw jit outputs are handed to the worker with the dispatch
    timestamp and the plan's ship-route attribution — the per-route device
    seconds in the registry's ``device`` section."""
    out = {}
    traced = []
    for name, p in plans:
        if p.fn is None:
            out[name] = p.build(None)
        else:
            traced.append((name, p))
    if not traced:
        return out
    timing = timer is not None and timer.enabled
    if _FUSE_RG:
        key = tuple(p.key for _, p in traced)
        fused = _fused_for(
            key,
            tuple(p.fn for _, p in traced),
            tuple(len(p.dyn) for _, p in traced),
        )
        dyn = tuple(_memo_dev(x) for _, p in traced for x in p.dyn)
        t0 = time.perf_counter() if timing else 0.0
        results = fused(buf_dev, dyn)
        if timing:
            # ONE executable ran: one timing entry, attributed to the
            # dominant (most-bytes-in) plan — per-plan submissions with
            # the shared t0 would each bank the whole fused wall and sum
            # to ~N_plans x the real device time
            dom = max((p for _, p in traced), key=lambda p: p.bytes_in)
            timer.submit("dispatch", dom.route or ROUTE_PLAIN,
                         _kernel_family(dom.key), results, t0,
                         bytes_in=sum(p.bytes_in for _, p in traced),
                         bytes_staged=sum(p.bytes_staged
                                          for _, p in traced),
                         passes=sum(p.stages for _, p in traced))
        for (name, p), res in zip(traced, results):
            out[name] = p.build(res)
        return out
    for name, p in traced:
        jfn = _single_for(p.key, p.fn)
        t0 = time.perf_counter() if timing else 0.0
        res = jfn(buf_dev, *(_memo_dev(x) for x in p.dyn))
        if timing:
            timer.submit("dispatch", p.route or ROUTE_PLAIN,
                         _kernel_family(p.key), res, t0,
                         bytes_in=p.bytes_in, bytes_staged=p.bytes_staged,
                         passes=p.stages)
        out[name] = p.build(res)
    return out


def _compose_column(value_plan: "_Plan", d_plan, r_plan) -> "_Plan":
    """Fuse a chunk's value plan with its def/rep level plans into one
    _Plan producing the finished DeviceColumnData."""
    if value_plan.fn is None and d_plan is None and r_plan is None:
        return value_plan
    nv = len(value_plan.dyn)
    nd = len(d_plan.dyn) if d_plan is not None else 0
    v_fn, d_fn = value_plan.fn, d_plan.fn if d_plan is not None else None
    r_fn = r_plan.fn if r_plan is not None else None
    key = ("col", value_plan.key,
           d_plan.key if d_plan is not None else None,
           r_plan.key if r_plan is not None else None)

    def fn(buf, *dyn):
        vres = v_fn(buf, *dyn[:nv]) if v_fn is not None else None
        dres = d_fn(buf, *dyn[nv : nv + nd]) if d_fn is not None else None
        rres = r_fn(buf, *dyn[nv + nd :]) if r_fn is not None else None
        return (vres, dres, rres)

    dyn = (value_plan.dyn
           + (d_plan.dyn if d_plan is not None else ())
           + (r_plan.dyn if r_plan is not None else ()))
    stages = (value_plan.stages
              + (d_plan.stages if d_plan is not None else 0)
              + (r_plan.stages if r_plan is not None else 0))

    def build(res):
        vres, dres, rres = res
        col = value_plan.build(vres)
        if d_plan is not None:
            col.def_levels = dres
        if r_plan is not None:
            col.rep_levels = rres
        return col

    return _Plan(key, fn, dyn, build, stages=stages)


class _ChunkAssembler:
    """Collects a chunk's pages, then emits one fused device decode."""

    def __init__(self, leaf: SchemaNode, deferred_checks: list):
        self.leaf = leaf
        self.pages: list[ParsedDataPage] = []
        self.dict_u8: Optional[np.ndarray] = None
        self.dict_dtype: Optional[str] = None
        self.dict_ragged: Optional[ByteArrayData] = None
        self.dict_len = 0
        self._deferred = deferred_checks  # (maxima_device_scalar, dict_len, path)
        # (min, max) int hint from chunk-level Statistics — routes the
        # device-snappy vs narrow-transcode choice; never trusted for
        # correctness (see _plan_device_snappy)
        self.stats_span: "tuple[int, int] | None" = None
        self.pages_kept_compressed = 0
        self.pages_pruned = 0  # page-level predicate pushdown skips
        # ship planner state (see preship / tpu_parquet.ship): the ordered
        # route preference, host-built artifacts keyed by route family, and
        # the per-stream (route, logical, shipped) decisions for stats
        self.dict_comp: "tuple | None" = None  # (snappy payload, ulen)
        self.alloc = None  # AllocTracker: recompression copies count too
        self._ship_pref: "list | None" = None
        self._ship: dict = {}
        self._ship_costs: dict = {}  # route -> planner's modeled seconds
        self._ship_dev_costs: dict = {}  # route -> modeled DEVICE seconds
        # fused route -> the UNFUSED chain's modeled device seconds
        # (ship.ShipPlanner.unfused_device_costs) — recorded on fused ship
        # records so the doctor's fusion-win verdict has the prediction
        # the measured fused lane must beat
        self._ship_unfused_dev: dict = {}
        # fused routes that degraded to their unfused twin (caps, level
        # lanes, i32 ceilings) — a counter, never a crash
        self.fused_fallbacks = 0
        self._dict_costs: dict = {}  # same, for the dictionary value table
        self._dict_dev_costs: dict = {}
        self._dict_ship: "tuple | None" = None  # (route, payload, out_len)
        self._bytes_walk: "tuple | None" = None  # (lens_l, span_l)
        self._narrow_compress = False
        self.ship_records: list = []
        # memoized route from a replayed ScanPlan (scanplan.py): preship
        # puts it first in the preference order, so a plain memo skips the
        # failed narrow/recompress probes a first pass already paid
        self._route_hint: "str | None" = None

    def _record_ship(self, route: str, logical: int, shipped: int,
                     predicted: "float | None" = None,
                     predicted_device: "float | None" = None) -> None:
        # the planner's modeled seconds for the route that actually ran —
        # obs.StatsRegistry.ship_feedback puts it next to the measured link
        # lane (TPQ_LINK_MBPS calibration); value-stream records default to
        # the preship plan's cost table, dict-table records pass their own.
        # The device-lane prediction rides the same record so the measured
        # per-route completion timing has a model to calibrate against.
        # Fused records additionally carry the UNFUSED chain's modeled
        # device seconds (0.0 elsewhere) — the fusion-win comparison.
        if predicted is None:
            predicted = self._ship_costs.get(route, 0.0)
        if predicted_device is None:
            predicted_device = self._ship_dev_costs.get(route, 0.0)
        self.ship_records.append(
            (route, int(logical), int(shipped), float(predicted),
             float(predicted_device),
             float(self._ship_unfused_dev.get(route, 0.0))))

    def _apply_route_hint(self) -> None:
        """Reorder the planner's preference behind a replayed route memo.

        Only a route the model priced FEASIBLE for this chunk moves up (a
        hint recorded for different data never forces an impossible
        build); everything else of the ranked order stays as fallback.
        A forced route (``TPQ_FORCE_ROUTE``) wins over any memo."""
        h = self._route_hint
        if (h and self._ship_pref and h in (self._ship_costs or {})
                and self._ship_pref[0] != h):
            self._ship_pref = [h] + [r for r in self._ship_pref if r != h]

    def _route_enabled(self, route: str) -> bool:
        """Whether the planner ranked ``route`` ahead of the plain tail
        (True when no preship ran — legacy chain semantics)."""
        if self._ship_pref is None:
            return True
        for r in self._ship_pref:
            if r == route:
                return True
            if r == ROUTE_PLAIN:
                return False
        return False

    # -- dictionary ----------------------------------------------------------

    @scoped_x64
    def set_dictionary(self, raw: bytes, encoding: int, count: int) -> None:
        decoded = host_decode_dictionary(raw, self.leaf, encoding, count)
        if isinstance(decoded, ByteArrayData):
            self.dict_ragged = decoded
            self.dict_len = len(decoded)
        else:
            self.dict_u8, self.dict_dtype, self.dict_len = decoded

    def dict_cache_entry(self) -> "dict | None":
        """This chunk's decoded dictionary as a read-through cache entry
        (serve.PlanCache): the decoded table, its compressed ship payload
        when the file's own snappy page covers the rows, and a byte size
        for cache accounting.  None when the chunk has no dictionary."""
        if self.dict_len == 0:
            return None
        if self.dict_u8 is not None:
            nbytes = int(self.dict_u8.nbytes)
        elif self.dict_ragged is not None:
            nbytes = int(self.dict_ragged.offsets.nbytes
                         + self.dict_ragged.heap.nbytes)
        else:
            return None
        if self.dict_comp is not None:
            nbytes += len(self.dict_comp[0])
        return {
            "u8": self.dict_u8, "dtype": self.dict_dtype,
            "ragged": self.dict_ragged, "len": self.dict_len,
            "comp": self.dict_comp, "nbytes": nbytes,
        }

    def adopt_dictionary(self, entry: dict) -> None:
        """Adopt a cached decoded dictionary (inverse of
        :meth:`dict_cache_entry`) — shared READ-ONLY across assemblers;
        every consumer gathers/copies, never mutates the tables."""
        self.dict_u8 = entry.get("u8")
        self.dict_dtype = entry.get("dtype")
        self.dict_ragged = entry.get("ragged")
        self.dict_len = int(entry.get("len") or 0)
        self.dict_comp = entry.get("comp")

    # -- ship planning (host half; see tpu_parquet.ship) ----------------------

    def _try_snappy(self, stream, pipe_stats=None):
        """snappy over one host stream (buffer-protocol, no copies); returns
        the payload only when it beats SNAPPY_WORTH_RATIO — thin wins lose
        to the op tables + device resolve."""
        from . import native

        if not native.available():
            return None
        nbytes = len(stream) if isinstance(stream, (bytes, bytearray)) \
            else stream.nbytes
        if nbytes == 0:
            return None
        if self.alloc is not None:
            # register the worst-case compressed size BEFORE materializing
            # it (raise-don't-OOM: the guard must fire before the peak)
            self.alloc.register_transient(nbytes + nbytes // 6 + 32)
        ctx = (pipe_stats.timed("recompress") if pipe_stats is not None
               else _noop_ctx())
        with ctx:
            comp = native.snappy_compress(stream)
        if len(comp) > SNAPPY_WORTH_RATIO * nbytes:
            return None
        return comp

    def _recompress_streams(self, streams, pipe_stats=None):
        """Link recompression (ship.py ROUTE_RECOMPRESS): snappy over each
        page's value stream.  ``streams``: [(buf, pos, size)].  Returns the
        per-page payloads, or None when the whole chunk didn't compress
        past SNAPPY_WORTH_RATIO (the builder then falls through)."""
        from . import native

        if not native.available():
            return None
        total = sum(s[2] for s in streams)
        if total == 0:
            return None
        if self.alloc is not None:
            # the compressed copies coexist with the decompressed originals
            # at their peak — register the worst-case bound BEFORE the
            # copies exist (raise-don't-OOM), per-stream snappy worst case
            # being n + n/6 + 32
            self.alloc.register_transient(
                total + total // 6 + 32 * len(streams))
        ctx = (pipe_stats.timed("recompress") if pipe_stats is not None
               else _noop_ctx())
        payloads = []
        with ctx:
            for buf, pos, size in streams:
                payloads.append(native.snappy_compress(
                    np.frombuffer(buf, np.uint8, size, pos)))
        if sum(len(c) for c in payloads) > SNAPPY_WORTH_RATIO * total:
            return None
        return payloads

    def _narrow_host_transcode(self, width: int):
        """Host half of the narrow routes: span probe, exact min/max, and
        the k-byte truncating transcode into one dense buffer.  Returns
        (k, min, uint8 buffer) or None when the span is too wide (full-range
        data pays only a 64k-value probe, never a full scan).  Pages are
        peeked, not materialized, so a later route can still ship the
        file's compressed payload."""
        from . import native

        if not native.available():
            return None
        max_k = _narrow_max_k(width)
        defined = sum(p.defined for p in self.pages)
        if defined == 0:
            return None
        for p in self.pages:
            p.peek()
        if any(len(p.raw) - p.value_pos < p.defined * width
               for p in self.pages):
            return None  # truncated: the plain path raises with diagnostics
        probe = next(p for p in self.pages if p.defined)
        head = native.int_minmax(
            probe.raw, probe.value_pos, min(probe.defined, _NARROW_PROBE),
            width,
        )
        if _span_bytes(*head) > max_k:
            return None
        mms = [native.int_minmax(p.raw, p.value_pos, p.defined, width)
               for p in self.pages if p.defined]
        mn = min(m[0] for m in mms)
        mx = max(m[1] for m in mms)
        k = _span_bytes(mn, mx)
        if k > max_k:
            return None
        # one truncating pass per page, written straight into a single dense
        # buffer: (v - min) mod 2^width wraps to a value that fits k bytes by
        # construction (negative minima included)
        out = np.empty(defined * k, dtype=np.uint8)
        at = 0
        for p in self.pages:
            native.int_truncate(p.raw, p.value_pos, p.defined, width, mn, k,
                                out[at:])
            at += p.defined * k
        return k, mn, out

    def preship(self, planner: "ShipPlanner | None" = None,
                pipe_stats=None, route_hint: "str | None" = None) -> None:
        """Route choice + link-byte host work for this chunk (ship.py).

        Runs on the prefetch pool's worker threads when prefetch > 0 — the
        same threads that decompress, so ROUTE_RECOMPRESS's snappy pass and
        the narrow transcode overlap the consumer thread's stage/dispatch —
        and inline on the sequential path.  Stores the ordered route
        preference plus any host-built artifacts; ``finish`` executes the
        routes in order, falling through on infeasibility.  Compression
        seconds land in PipelineStats' ``recompress`` stage.

        ``route_hint`` (a replayed ScanPlan's memoized route) moves that
        route to the head of the preference order when the model still
        prices it feasible — the builders' fall-through keeps correctness
        if the replay turns out infeasible on this chunk.
        """
        if planner is None:
            planner = default_planner()
        # a forced route (TPQ_FORCE_ROUTE) wins over any replayed memo
        self._route_hint = route_hint if planner.force is None else None
        self._preship_dict(planner, pipe_stats)
        if not self.pages:
            return
        encs = {parse_encoding(p.encoding) for p in self.pages}
        if encs != {Encoding.PLAIN}:
            return
        leaf = self.leaf
        if leaf.physical_type in _PTYPE_TO_NAME:
            self._preship_fixed(planner, pipe_stats)
        elif leaf.physical_type == Type.BYTE_ARRAY:
            self._preship_bytes(planner, pipe_stats)

    def _preship_fixed(self, planner, pipe_stats) -> None:
        from . import native

        leaf = self.leaf
        name = _PTYPE_TO_NAME[leaf.physical_type]
        width = np.dtype(name).itemsize
        defined = sum(p.defined for p in self.pages)
        logical = defined * width
        comp_bytes = sum(len(p.comp[0]) for p in self.pages
                         if p.comp is not None)
        is_int = leaf.physical_type in (Type.INT32, Type.INT64)
        narrow_k = 0
        if is_int and self.stats_span is not None:
            k = _span_bytes(*self.stats_span)
            if k <= _narrow_max_k(width):
                narrow_k = k
        facts = ChunkFacts(
            logical=logical, width=width, narrow_k=narrow_k,
            narrow_possible=is_int and native.available(),
            comp_bytes=comp_bytes, native=native.available(),
            flat=leaf.max_def == 0 and leaf.max_rep == 0,
        )
        self._ship_pref, self._ship_costs = planner.plan(facts)
        self._ship_dev_costs = planner.device_costs(
            facts, routes=self._ship_costs)
        self._ship_unfused_dev = planner.unfused_device_costs(
            facts, routes=self._ship_costs)
        self._apply_route_hint()
        # failed host work is memoized as a None sentinel so the finish
        # builders (and a later pref entry naming the same family) never
        # repeat a full-chunk scan that already failed — preship exists to
        # keep that work OFF the consumer thread
        for route in self._ship_pref:
            if route in (ROUTE_NARROW, ROUTE_NARROW_SNAPPY,
                         ROUTE_FUSED_NARROW_SNAPPY):
                if not is_int or defined == 0:
                    continue
                if "narrow" in self._ship:  # earlier pref entry failed
                    continue
                art = self._narrow_host_transcode(width)
                if art is None:
                    self._ship["narrow"] = None
                    continue
                k, mn, out = art
                comp = (self._try_snappy(out, pipe_stats)
                        if route in (ROUTE_NARROW_SNAPPY,
                                     ROUTE_FUSED_NARROW_SNAPPY) else None)
                self._ship["narrow"] = (k, mn, out, comp)
                return
            if route == ROUTE_DEVICE_SNAPPY:
                if comp_bytes:
                    return  # planned at finish (needs the stager)
                continue
            if route == ROUTE_RECOMPRESS:
                if comp_bytes or defined == 0:
                    continue
                if any(len(p.raw) - p.value_pos < p.defined * width
                       for p in self.pages):
                    continue  # truncated: plain path raises diagnostics
                payloads = self._recompress_streams(
                    [(p.raw, p.value_pos, p.defined * width)
                     for p in self.pages], pipe_stats)
                if payloads is None:
                    self._ship["recompress"] = None
                    continue
                self._ship["recompress"] = payloads
                return
            if route in (ROUTE_PLAIN, ROUTE_FUSED_PLAIN):
                return  # no host artifacts to prepare for either

    def _preship_bytes(self, planner, pipe_stats) -> None:
        from . import native

        if not native.available():
            return
        lens_l, span_l = [], []
        for p in self.pages:
            p.peek()
            res = native.bytearray_lengths(p.raw, p.defined, pos=p.value_pos)
            if res is None or isinstance(res, int):
                return  # finish raises (or falls back) with diagnostics
            lens, end = res
            lens_l.append(lens)
            span_l.append(end - p.value_pos)
        self._bytes_walk = (lens_l, span_l)
        logical = sum(span_l)
        comp_bytes = sum(len(p.comp[0]) for p in self.pages
                         if p.comp is not None)
        facts = ChunkFacts(
            logical=logical, width=0, comp_bytes=comp_bytes, native=True,
        )
        self._ship_pref, self._ship_costs = planner.plan(facts)
        self._ship_dev_costs = planner.device_costs(
            facts, routes=self._ship_costs)
        self._apply_route_hint()
        for route in self._ship_pref:
            if route == ROUTE_DEVICE_SNAPPY:
                if comp_bytes:
                    return  # planned at finish
                continue
            if route == ROUTE_RECOMPRESS:
                if comp_bytes or logical == 0:
                    continue
                payloads = self._recompress_streams(
                    [(p.raw, p.value_pos, s)
                     for p, s in zip(self.pages, span_l)], pipe_stats)
                # failure memoized (None): _plan_snappy_bytes must not
                # repeat the compression on the consumer thread
                self._ship["recompress_bytes"] = payloads
                if payloads is None:
                    continue
                return
            if route == ROUTE_PLAIN:
                return

    def _preship_dict(self, planner, pipe_stats) -> None:
        """Dictionary VALUE TABLE shipping: fixed-width dictionaries whose
        page payload is exactly the rows (PLAIN) can keep the file's snappy
        payload; ragged heaps (and non-snappy files) recompress.  The
        decoded host copy is dropped after staging either way — only the
        link bytes change."""
        from . import native

        if self.dict_len == 0:
            return
        if self.dict_u8 is not None:
            nbytes = self.dict_u8.nbytes
            src = self.dict_u8
        elif self.dict_ragged is not None:
            nbytes = int(self.dict_ragged.heap.nbytes)
            src = self.dict_ragged.heap
        else:
            return
        # the snappy page payload covers the rows only for fixed-width
        # dictionaries (ragged payloads interleave u32 length prefixes)
        comp0 = None
        if (self.dict_u8 is not None and self.dict_comp is not None
                and self.dict_comp[1] >= nbytes):
            comp0 = self.dict_comp
        facts = ChunkFacts(
            logical=nbytes, width=0,
            comp_bytes=len(comp0[0]) if comp0 is not None else 0,
            native=native.available(),
            host_bytes_ready=True,  # dict pages always decompress on host
        )
        dict_routes, self._dict_costs = planner.plan(facts)
        self._dict_dev_costs = planner.device_costs(
            facts, routes=self._dict_costs)
        for route in dict_routes:
            if route == ROUTE_DEVICE_SNAPPY and comp0 is not None:
                self._dict_ship = (route, comp0[0], comp0[1])
                return
            if route == ROUTE_RECOMPRESS and comp0 is None:
                comp = self._try_snappy(np.ascontiguousarray(src),
                                        pipe_stats)
                if comp is None:
                    continue
                self._dict_ship = (route, comp, nbytes)
                return
            if route == ROUTE_PLAIN:
                return

    # -- finish: fused decode -------------------------------------------------

    @scoped_x64
    def finish(self, stager: _RowGroupStager):
        """Phase A (host): parse structure, register bytes with the stager.

        Returns a closure ``fn(buf_dev) -> DeviceColumnData`` that dispatches
        the chunk's kernels against the staged row-group buffer.
        """
        leaf = self.leaf
        slots = sum(p.num_values for p in self.pages)
        encs = {parse_encoding(p.encoding) for p in self.pages}
        encs = {
            Encoding.RLE_DICTIONARY if e == Encoding.PLAIN_DICTIONARY else e
            for e in encs
        }
        # lazily-compressed pages are only consumed by the compressed-ship
        # routes (PLAIN fixed-width and PLAIN BYTE_ARRAY — see ship.py);
        # every other route gets host bytes
        lazy_ok = encs == {Encoding.PLAIN} and (
            leaf.physical_type in _PTYPE_TO_NAME
            or leaf.physical_type == Type.BYTE_ARRAY
        )
        if any(p.comp is not None for p in self.pages) and not lazy_ok:
            for p in self.pages:
                p.materialize()
        slots_pad = _bucket_count(slots)
        d_plan = r_plan = None
        if leaf.max_def > 0:
            d_plan = self._plan_levels(
                stager, [p.def_stream for p in self.pages],
                bitpack.bit_width(leaf.max_def), slots, slots_pad,
                metas=[p.def_meta for p in self.pages],
            )
        if leaf.max_rep > 0:
            r_plan = self._plan_levels(
                stager, [p.rep_stream for p in self.pages],
                bitpack.bit_width(leaf.max_rep), slots, slots_pad,
            )

        common = dict(
            max_def=leaf.max_def, max_rep=leaf.max_rep, num_leaf_slots=slots,
            value_dtype=(
                "float64" if leaf.physical_type == Type.DOUBLE else None
            ),
        )

        if len(encs) == 1:
            enc = next(iter(encs))
            if enc == Encoding.RLE_DICTIONARY:
                value_fn = self._finish_dict(common, stager)
            elif enc == Encoding.PLAIN and leaf.physical_type in _PTYPE_TO_NAME:
                value_fn = self._finish_plain_fixed(common, stager)
            elif enc == Encoding.PLAIN and leaf.physical_type == Type.BOOLEAN:
                value_fn = self._finish_plain_bool(common, stager)
            elif enc == Encoding.PLAIN and leaf.physical_type == Type.BYTE_ARRAY:
                value_fn = self._finish_plain_bytes(common, stager)
            elif (enc == Encoding.PLAIN and leaf.physical_type == Type.INT96):
                value_fn = self._finish_plain_rows(common, stager, 12)
            elif (enc == Encoding.PLAIN
                  and leaf.physical_type == Type.FIXED_LEN_BYTE_ARRAY
                  and (leaf.type_length or 0) > 0):
                value_fn = self._finish_plain_rows(common, stager,
                                                   leaf.type_length,
                                                   flba=True)
            elif enc == Encoding.DELTA_BINARY_PACKED:
                value_fn = self._finish_delta(common, stager)
            else:
                value_fn = self._finish_host(common)
        elif (encs == {Encoding.RLE_DICTIONARY, Encoding.PLAIN}
              and leaf.physical_type in _PTYPE_TO_NAME
              and self.dict_u8 is not None):
            # dictionary-overflow fallback: early pages dict-encoded, later
            # pages PLAIN (type_dict.go:101-103 semantics on the write side)
            value_fn = self._finish_mixed_dict_plain(common, stager)
        else:
            # other mixed encodings, BSS, INT96, FLBA, delta byte arrays,
            # boolean RLE: host decode per page, stage per chunk
            value_fn = self._finish_host(common)

        # every plan has captured what it needs; dropping the parsed pages
        # here releases all raw decompressed page bytes before dispatch (the
        # iter_row_groups pipeline otherwise pins a whole extra row group)
        self.pages = []
        # level arrays expand on device from the staged RLE streams at the
        # bucketed slot count (tail zeros past num_leaf_slots)
        return _compose_column(value_fn, d_plan, r_plan)

    def _plan_levels(self, stager: _RowGroupStager, streams, width: int,
                     slots: int, slots_pad: int, metas=None):
        """Stage the pages' raw RLE level streams and expand them on device.

        Levels are run-dominated: the encoded stream is a fraction of the
        4-bytes-per-slot decoded array, so staging the stream + run tables
        instead of host-decoded uint32 arrays cuts the dominant transfer on
        nested files (~2/3 of staged bytes on the LIST/MAP bench config).
        Returns ``fn(buf_dev) -> uint32[slots_pad]`` (tail past ``slots``
        zeroed).  Every decode_levels=False parse records the stream span
        whenever max_def/max_rep > 0, so a missing span is a caller bug.
        """
        if metas is None:
            metas = [None] * len(self.pages)
        if any(s is None for s in streams):
            raise ParquetError(
                "internal: level stream span missing on the batched path"
            )
        metas = [
            m if m is not None else parse_hybrid_meta(
                src, width, p.num_values, pos=start, end=start + size
            )
            for (src, start, size), p, m in zip(streams, self.pages, metas)
        ]
        interp = _pallas_interpret_mode()
        if interp is not None:
            plan = _plan_hybrid_pallas(
                stager,
                [(m, src, p.num_values)
                 for (src, _, _), p, m in zip(streams, self.pages, metas)],
                width, slots, slots_pad, interp,
            )
            if plan is not None:
                return plan
        bases = stager.add_segments(list(streams))
        ends_l, rle_l, vals_l, starts_l = [], [], [], []
        prefix = 0
        for (src, start, size), base, p, meta in zip(streams, bases,
                                                     self.pages, metas):
            n = meta.n_runs
            ends_l.append(meta.run_ends[:n] + prefix)
            rle_l.append(meta.run_is_rle[:n])
            vals_l.append(meta.run_values[:n])
            # source byte b lands at staged (b - start + base); rebase bit
            # starts for the copy and for the global value position
            starts_l.append(
                meta.run_bit_starts[:n] + (int(base) - start) * 8
                - prefix * width
            )
            prefix += p.num_values
        ends, is_rle, rvals, starts = _merge_run_tables(
            ends_l, rle_l, vals_l, starts_l, fill_end=slots
        )

        def fn(buf_dev, ends_d, isr_d, rvals_d, starts_d, slots_d):
            return _hybrid_jit(buf_dev, ends_d, isr_d, rvals_d, starts_d,
                               slots_d, width=width, count=slots_pad)

        return _Plan(("lvlx", width, slots_pad), fn,
                     (ends, is_rle, rvals, starts, np.int64(slots)), None,
                     stages=2)  # run-table expand pass + tail-mask pass

    def _value_segments(self, stager: _RowGroupStager) -> np.ndarray:
        """Register all pages' value streams back-to-back; returns byte bases
        (absolute offsets in the staged buffer), int64[P].  The page bytes are
        copied exactly once, by ``stage()``, straight into the row-group
        buffer."""
        return stager.add_segments([
            (p.raw, p.value_pos, len(p.raw) - p.value_pos) for p in self.pages
        ])

    def _stage_fixed_width(self, stager, width: int):
        """Register exactly the pages' value bytes back-to-back for a
        ``width``-bytes-per-value PLAIN stream.

        Returns (base, defined, count): the staged byte base, the real value
        count, and the bucketed static count the kernel decodes — it reads
        past the segments into whatever follows in the staged buffer
        (harmless garbage past n_values, in-bounds by note_read_extent), so
        one executable is shared across chunks.
        """
        defined = sum(p.defined for p in self.pages)
        _check_plain_sizes(self.pages, width)
        segs = [(p.raw, p.value_pos, p.defined * width) for p in self.pages]
        base = (int(stager.add_segments(segs)[0]) if segs
                else stager._reserve(0, None))
        count = _bucket_count(defined)
        stager.note_read_extent(base, count * width)
        return base, defined, count

    def _finish_plain_fixed(self, common, stager):
        """PLAIN fixed-width dispatcher: execute the ship planner's route
        preference in order (ship.py), falling through on infeasibility —
        the ``plain`` tail can never fail.  Without a preship pass (direct
        decode_chunk_batched callers) the legacy chain applies:
        device-snappy, then narrow, then plain."""
        name = _PTYPE_TO_NAME[self.leaf.physical_type]
        pref = self._ship_pref
        if pref is None:
            pref = [ROUTE_DEVICE_SNAPPY, ROUTE_NARROW, ROUTE_PLAIN]
        for route in pref:
            plan = None
            if route == ROUTE_PLAIN:
                break  # the infallible tail below; later entries are dead
            if route == ROUTE_DEVICE_SNAPPY:
                if any(p.comp is not None for p in self.pages):
                    plan = self._plan_device_snappy(common, stager, name)
            elif route == ROUTE_FUSED_PLAIN:
                plan = self._plan_fused_plain(common, stager, name)
            elif route in (ROUTE_NARROW, ROUTE_NARROW_SNAPPY,
                           ROUTE_FUSED_NARROW_SNAPPY):
                if name in ("int32", "int64"):
                    self._narrow_compress = route in (
                        ROUTE_NARROW_SNAPPY, ROUTE_FUSED_NARROW_SNAPPY)
                    plan = self._plan_narrow_ints(
                        common, stager, name,
                        fused=route == ROUTE_FUSED_NARROW_SNAPPY)
            elif route == ROUTE_RECOMPRESS:
                plan = self._plan_recompress_fixed(common, stager, name)
            if plan is None and route in FUSED_ROUTES:
                # forced/planned fused on a stream the megakernel cannot
                # claim (levels, op/depth/payload caps, i32 ceilings):
                # degrade to the next-ranked route with a COUNTER, never a
                # crash — the fuzz target's invariant
                self.fused_fallbacks += 1
            if plan is not None:
                return plan
        for p in self.pages:
            p.materialize()
        base, defined, count = self._stage_fixed_width(
            stager, np.dtype(name).itemsize
        )
        logical = defined * np.dtype(name).itemsize
        self._record_ship(ROUTE_PLAIN, logical, logical)
        return _Plan(
            ("plain", name, count),
            lambda buf, base_d: _plain_jit(buf, base_d, dtype=name,
                                           count=count),
            (np.int64(base),),
            lambda v: DeviceColumnData(values=v, n_values=defined, **common),
        )

    def _plan_recompress_fixed(self, common, stager, name: str):
        """Link recompression for PLAIN fixed-width chunks stored GZIP/ZSTD/
        uncompressed (ship.py ROUTE_RECOMPRESS): the host decompressed these
        bytes anyway, so one more snappy pass trades cheap host cycles for
        link bytes, and the device expands through the same resolver as
        native snappy files.  Normally prepared by preship on the prefetch
        pool; compresses inline when reached without one."""
        width = np.dtype(name).itemsize
        if any(p.comp is not None for p in self.pages):
            return None  # the file's own payload is the better ship
        defined = sum(p.defined for p in self.pages)
        if defined == 0:
            return None
        _check_plain_sizes(self.pages, width)
        if "recompress" in self._ship:
            payloads = self._ship["recompress"]  # None: preship declined
        else:
            payloads = self._recompress_streams(
                [(p.raw, p.value_pos, p.defined * width) for p in self.pages])
        if payloads is None:
            return None
        sizes = [p.defined * width for p in self.pages]
        specs = [("comp", c, n, None) for c, n in zip(payloads, sizes)]
        vbase_t, vstart_t, pages_pad, _ = _fixed_value_tables(
            sizes, [p.defined for p in self.pages])
        count = _bucket_count(defined)
        info = _plan_snappy_ops(stager, specs,
                                extra_tables=[vbase_t, vstart_t])
        if info is None:
            return None
        self.pages_kept_compressed = len(specs)
        self._record_ship(ROUTE_RECOMPRESS, defined * width, info.shipped)
        n_ops, out_pad, iters = info.n_ops, info.out_pad, info.iters
        return _Plan(
            ("snappy", n_ops, out_pad, iters, name, count, pages_pad),
            lambda buf, tbase_d: _snappy_plain_staged_jit(
                buf, tbase_d, n_ops=n_ops, out_pad=out_pad,
                iters=iters, dtype=name, count=count, n_pages=pages_pad,
            ),
            (np.int64(info.tbase),),
            lambda v: DeviceColumnData(values=v, n_values=defined, **common),
            # op-map pass + `iters` doubling rounds + byte gather + decode
            stages=3 + iters,
        )

    def _plan_device_snappy(self, common, stager, name: str):
        """Ship COMPRESSED snappy PLAIN pages; decompress + decode on device.

        Host work per page collapses to the native tag walk (~1 byte touched
        per ~60 payload bytes) — no decompression, no value copies; the
        staged transfer carries the compressed stream.  See
        _snappy_plain_staged_jit for the device side.  Returns None when the
        chunk should fall back (narrow-int stats hint, 2 GiB i32 ceiling,
        shattered op tables, native library absent) — the caller then
        materializes and takes the standard host paths.
        """
        from . import native

        width = np.dtype(name).itemsize
        # legacy stats hint (pre-planner chain only): a narrow int span
        # means host decompress + narrow transcode ships FEWER bytes than
        # the compressed stream — decline so the chain's next step claims
        # it.  With a planner preference the hint already routed via
        # ChunkFacts.narrow_k, and declining HERE would fight it: narrow
        # may rank after plain, have already failed (lying stats), or be
        # absent entirely under TPQ_FORCE_ROUTE=device_snappy.
        if (self._ship_pref is None and name in ("int32", "int64")
                and self.stats_span is not None):
            lo, hi = self.stats_span
            if _span_bytes(lo, hi) <= _narrow_max_k(width):
                return None
        _check_plain_sizes(self.pages, width)
        specs = []
        sizes = []
        lazy_out = comp_bytes = 0
        for p in self.pages:
            if p.comp is not None:
                payload, _codec, ulen = p.comp
                r = native.snappy_plan(payload, ulen)
                if r is None:
                    return None
                if isinstance(r, int):
                    # malformed stream: materialize so the standard codec
                    # diagnostics raise (same reject set as the planner)
                    p.materialize()
                    return None
                specs.append(("comp", payload, ulen, r))
                sizes.append(ulen)
                lazy_out += ulen
                comp_bytes += len(payload)
            else:
                nbytes = len(p.raw) - p.value_pos
                # staged segment: the raw value bytes for already-
                # materialized pages (one synthetic literal op each)
                specs.append(("raw", p.raw, p.value_pos, nbytes))
                sizes.append(nbytes)
        # worth-it gate (measured on v5e): shipping compressed pays for the
        # device-side resolve whenever the stream actually compressed; at
        # ratio ~1 the only win is the skipped host decompress, which beats
        # the resolve cost on small chunks but loses on multi-strip ones
        if (lazy_out > 0 and comp_bytes > SNAPPY_WORTH_RATIO * lazy_out
                and lazy_out > _SNAPPY_SMALL_OUT):
            return None
        # out-space bases: value_pos == 0 on lazy pages (parse contract)
        vbase_t, vstart_t, pages_pad, defined = _fixed_value_tables(
            sizes, [p.defined for p in self.pages])
        info = _plan_snappy_ops(stager, specs,
                                extra_tables=[vbase_t, vstart_t])
        if info is None:
            return None
        count = _bucket_count(defined)
        self.pages_kept_compressed = len(
            [1 for s in specs if s[0] == "comp"])
        self._record_ship(ROUTE_DEVICE_SNAPPY, defined * width, info.shipped)
        n_ops, out_pad, iters = info.n_ops, info.out_pad, info.iters
        return _Plan(
            ("snappy", n_ops, out_pad, iters, name, count, pages_pad),
            lambda buf, tbase_d: _snappy_plain_staged_jit(
                buf, tbase_d, n_ops=n_ops, out_pad=out_pad,
                iters=iters, dtype=name, count=count, n_pages=pages_pad,
            ),
            (np.int64(info.tbase),),
            lambda v: DeviceColumnData(values=v, n_values=defined, **common),
            # op-map pass + `iters` doubling rounds + byte gather + decode
            stages=3 + iters,
        )

    def _plan_narrow_ints(self, common, stager, name: str,
                          fused: bool = False):
        """Narrow transcode for PLAIN INT columns: ship ``v - min`` truncated
        to the minimal byte width instead of full-width values.

        Real-world int64 columns are overwhelmingly narrow-ranged (ids,
        dates, quantities — TPC-H l_partkey spans 18 bits, shipped 8 bytes
        wide by PLAIN), and the tunneled host→device link is the scarce
        resource the whole reader is engineered around.  The host is already
        touching these bytes (decompress), so one extra vectorized pass
        (min/max + truncating copy) buys a (width-k)/width transfer cut; the
        device widens and re-biases in one fused kernel (_plain_narrow_jit).
        Under ship.py's ROUTE_NARROW_SNAPPY the truncated buffer is
        additionally snappy-compressed — narrow residuals are low-entropy,
        so the two transfer cuts multiply (_snappy_narrow_staged_jit).
        Returns None (caller takes the next route) when the span probe shows
        < _NARROW_SAVE_BYTES savings, so full-range data pays only a 64k-value
        probe, not a full scan.
        """
        from . import native

        width = np.dtype(name).itemsize
        _check_plain_sizes(self.pages, width)
        defined = sum(p.defined for p in self.pages)
        if defined == 0 or not native.available():
            return None
        if "narrow" in self._ship:
            art = self._ship["narrow"]
            if art is None:
                return None  # preship already scanned and declined
            k, mn, out, comp = art
        else:
            trans = self._narrow_host_transcode(width)
            if trans is None:
                return None
            k, mn, out = trans
            comp = (self._try_snappy(out) if self._narrow_compress else None)
        if fused:
            plan = (self._plan_fused_narrow(common, stager, name, k, mn,
                                            out, comp)
                    if comp is not None else None)
            if plan is not None:
                return plan
            # megakernel ineligible (no compressed payload, or the
            # op/depth/payload caps): degrade to the unfused narrow chain
            # with a counter — same bytes, staged resolve instead
            self.fused_fallbacks += 1
        count = _bucket_count(defined)
        bias = np.int32(mn) if name == "int32" else np.int64(mn)
        if comp is not None:
            info = _plan_snappy_ops(
                stager, [("comp", comp, out.nbytes, None)])
            if info is not None:
                self.pages_kept_compressed = len(self.pages)
                self._record_ship(ROUTE_NARROW_SNAPPY, defined * width,
                                  info.shipped)
                n_ops, out_pad, iters = info.n_ops, info.out_pad, info.iters
                return _Plan(
                    ("narrows", k, name, count, n_ops, out_pad, iters),
                    lambda buf, tb_d, bias_d: _snappy_narrow_staged_jit(
                        buf, tb_d, bias_d, n_ops=n_ops, out_pad=out_pad,
                        iters=iters, k=k, dtype=name, count=count),
                    (np.int64(info.tbase), bias),
                    lambda v: DeviceColumnData(values=v, n_values=defined,
                                               **common),
                    # the chain the fused twin collapses: op-map pass +
                    # `iters` doubling rounds + byte gather + widen/re-bias
                    stages=3 + iters,
                )
            # op planning fell through: ship the narrow bytes uncompressed
        base = stager.add(out)
        stager.note_read_extent(base, count * k)
        self._record_ship(ROUTE_NARROW, defined * width, out.nbytes)
        return _Plan(
            ("narrow", k, name, count),
            lambda buf, base_d, bias_d: _plain_narrow_jit(
                buf, base_d, bias_d, k=k, dtype=name, count=count),
            (np.int64(base), bias),
            lambda v: DeviceColumnData(values=v, n_values=defined, **common),
        )

    def _plan_fused_plain(self, common, stager, name: str):
        """ONE Pallas pass for a PLAIN fixed-width chunk (ship.py
        ROUTE_FUSED_PLAIN): byte-plane assembly of the staged value stream
        plus the validity tail mask in a single device dispatch, replacing
        the unfused slice → bitcast → tail chain and its HBM round trips.
        Same link bytes as ``plain`` — the win is the device lane and the
        dispatch count, which the registry ``device`` section proves
        structurally (``device_passes`` == ``dispatches``).  Returns None
        (degrade to the next route, counted by the caller) when the column
        carries level lanes or the staged arena exceeds the kernel's i32
        addressing."""
        from .pallas_kernels import (
            fused_count_pad, fused_plain_words, resolve_interpret,
        )

        leaf = self.leaf
        if leaf.max_def > 0 or leaf.max_rep > 0:
            return None  # fused claims flat streams only (ship.fused_eligible)
        width = np.dtype(name).itemsize
        if width not in (4, 8):
            return None
        _check_plain_sizes(self.pages, width)
        defined = sum(p.defined for p in self.pages)
        count = fused_count_pad(defined)
        if stager.total + count * width > np.iinfo(np.int32).max:
            return None  # x64-free pallas trace addresses the arena with i32
        for p in self.pages:
            p.materialize()
        segs = [(p.raw, p.value_pos, p.defined * width) for p in self.pages]
        base = (int(stager.add_segments(segs)[0]) if segs
                else stager._reserve(0, None))
        stager.note_read_extent(base, count * width)
        interp = resolve_interpret()
        logical = defined * width
        self._record_ship(ROUTE_FUSED_PLAIN, logical, logical)

        def fn(buf, base_d, nv_d):
            words = fused_plain_words(buf, base_d, nv_d, width=width,
                                      count_pad=count, interpret=interp)
            return _fused_words_cast(words, name)

        return _Plan(
            ("fusedp", name, count, bool(interp)), fn,
            (np.int32(base), np.int32(defined)),
            lambda v: DeviceColumnData(values=v, n_values=defined, **common),
            stages=1,
        )

    def _plan_fused_narrow(self, common, stager, name: str, k: int, mn,
                           out: np.ndarray, comp):
        """ONE Pallas pass for the narrow+snappy composition (ship.py
        ROUTE_FUSED_NARROW_SNAPPY): decompress-resolve, gather, widen,
        re-bias, and validity fused — the staged chain's HBM-materialized
        source map never exists.  The op tables and compressed payload are
        VMEM-resident per tile, so the kernel caps bound eligibility
        (FUSED_MAX_OPS / FUSED_MAX_DEPTH / FUSED_MAX_PAYLOAD); beyond them
        the caller degrades to the pointer-doubling chain.  Literal op
        sources are packed PAYLOAD-RELATIVE — the staged chain's absolute
        coordinates would tie the executable to the arena layout."""
        from . import native
        from .pallas_kernels import (
            FUSED_MAX_DEPTH, FUSED_MAX_OPS, FUSED_MAX_PAYLOAD,
            fused_narrow_count_pad, fused_narrow_words, resolve_interpret,
        )

        leaf = self.leaf
        if leaf.max_def > 0 or leaf.max_rep > 0:
            return None
        width = np.dtype(name).itemsize
        defined = sum(p.defined for p in self.pages)
        if defined == 0 or len(comp) > FUSED_MAX_PAYLOAD:
            return None
        r = native.snappy_plan(comp, out.nbytes)
        if r is None or isinstance(r, int):
            return None
        dst_end, op_src, is_lit, depth = r
        n_ops = len(dst_end)
        if n_ops == 0 or depth > FUSED_MAX_DEPTH:
            return None
        n_ops_pad = _bucket(n_ops)
        if n_ops_pad > FUSED_MAX_OPS:
            return None
        count = fused_narrow_count_pad(defined)
        out_pad = _bucket_bytes(out.nbytes + 8, 8)
        ppad = _bucket_bytes(max(len(comp), 1), 64)
        if (stager.total + len(comp) + 13 * n_ops_pad + ppad + out_pad
                > (np.iinfo(np.int32).max >> 1)):
            return None  # i32 table/source math (checked before mutation)
        ends_t = np.full(n_ops_pad, out_pad, np.int32)
        ends_t[:n_ops] = dst_end
        starts = np.empty(n_ops, np.int64)
        starts[0] = 0
        starts[1:] = dst_end[:-1]
        asrc_t = np.zeros(n_ops_pad, np.int32)
        asrc_t[:n_ops] = np.where(is_lit != 0, op_src, starts - op_src)
        offs_t = np.ones(n_ops_pad, np.int32)
        offs_t[:n_ops] = np.where(is_lit != 0, 1, op_src)
        islit_t = np.ones(n_ops_pad, np.uint8)
        islit_t[:n_ops] = is_lit
        tbase = _pack_tables(stager, [ends_t, asrc_t, offs_t, islit_t])
        pbase = stager.add(np.frombuffer(comp, np.uint8))
        stager.note_read_extent(pbase, ppad)
        if width == 8:
            bu = np.uint64(np.int64(mn).astype(np.uint64))
            bias2 = np.array([[bu & np.uint64(0xFFFFFFFF),
                               bu >> np.uint64(32)]], dtype=np.uint32)
        else:
            bias2 = np.array([[np.int32(mn).astype(np.uint32), 0]],
                             dtype=np.uint32)
        interp = resolve_interpret()
        depth = int(depth)
        self.pages_kept_compressed = len(self.pages)
        self._record_ship(ROUTE_FUSED_NARROW_SNAPPY, defined * width,
                          len(comp))

        def fn(buf, tb_d, pb_d, bias_d, nv_d):
            ends = _tslice(buf, tb_d, 0, n_ops_pad, np.int32)
            asrc = _tslice(buf, tb_d, 4 * n_ops_pad, n_ops_pad, np.int32)
            offs = _tslice(buf, tb_d, 8 * n_ops_pad, n_ops_pad, np.int32)
            islit = _tslice(buf, tb_d, 12 * n_ops_pad, n_ops_pad, np.uint8)
            payload = jax.lax.dynamic_slice(buf, (pb_d,), (ppad,))
            words = fused_narrow_words(
                payload, ends, asrc, offs, islit, bias_d, nv_d, k=k,
                width=width, depth=depth, count_pad=count, out_pad=out_pad,
                interpret=interp)
            return _fused_words_cast(words, name)

        return _Plan(
            ("fusedns", k, name, count, n_ops_pad, out_pad, ppad, depth,
             bool(interp)), fn,
            (np.int64(tbase), np.int64(pbase), bias2, np.int32(defined)),
            lambda v: DeviceColumnData(values=v, n_values=defined, **common),
            stages=1,
        )

    def _finish_plain_rows(self, common, stager, k: int, flba: bool = False):
        """PLAIN fixed-length rows: exactly the value bytes back-to-back, one
        bucketed slice — INT96 as u32[n,3] values, FLBA as the uniform
        (offsets, heap) ragged form (matching the host decoder)."""
        base, defined, count = self._stage_fixed_width(stager, k)

        def fn(buf, base_d):
            if flba:
                return _plain_flba_jit(buf, base_d, k=k, count=count)
            return _plain_rows_jit(buf, base_d, k=k, count=count)

        def build(res):
            col = DeviceColumnData(n_values=defined, **common)
            if flba:
                col.offsets, col.heap = res
            else:
                col.values = res
            return col

        return _Plan(("rows", k, bool(flba), count), fn, (np.int64(base),),
                     build)

    def _finish_plain_bool(self, common, stager):
        defined = sum(p.defined for p in self.pages)
        for p in self.pages:
            need = (p.defined + 7) // 8
            if len(p.raw) - p.value_pos < need:
                raise ParquetError(
                    f"PLAIN BOOLEAN truncated: {len(p.raw) - p.value_pos} < {need}"
                )
        bases = self._value_segments(stager)
        n_pages = _bucket(len(self.pages))
        byte_base = np.zeros(n_pages, dtype=np.int64)
        byte_base[: len(self.pages)] = bases
        byte_base[len(self.pages):] = bases[-1] if len(self.pages) else 0
        starts = np.full(n_pages, defined, dtype=np.int64)
        acc = 0
        for i, p in enumerate(self.pages):
            starts[i] = acc
            acc += p.defined
        count = _bucket_count(defined)
        return _Plan(
            ("bool", count, n_pages),
            lambda buf, bb_d, st_d: _bool_pages_jit(buf, bb_d, st_d,
                                                    count=count),
            (byte_base, starts),
            lambda v: DeviceColumnData(values=v, n_values=defined, **common),
        )

    def _finish_plain_bytes(self, common, stager):
        """PLAIN BYTE_ARRAY chunk: host walks only the length prefixes
        (native, no copies); the streams + lengths stage and the heap
        compaction/offset cumsum run on device (_plain_bytes_pages_jit).

        Value streams ship by the planner's route (ship.py): the file's own
        snappy payloads (ROUTE_DEVICE_SNAPPY), a host snappy re-compression
        of the walked spans (ROUTE_RECOMPRESS, prepared by preship on the
        decompress pool), or the raw spans (plain).  Byte-array heaps are
        the dominant mover on string-heavy schemas (lineitem16), so this is
        where compressed shipping pays most.  Falls back to the round-2
        host-decode staging when the native library is unavailable."""
        from . import native

        if self._bytes_walk is not None:
            lens_l, span_l = self._bytes_walk
        else:
            lens_l, span_l = [], []
            for p in self.pages:
                # whole page buffer + offset: no host copy of the stream
                p.peek()
                res = native.bytearray_lengths(p.raw, p.defined,
                                               pos=p.value_pos)
                if res is None:
                    return self._finish_plain_bytes_host(common, stager)
                if isinstance(res, int):
                    if res == -20:
                        raise ParquetError(
                            "byte array: truncated length prefix")
                    raise ParquetError("byte array: length exceeds buffer")
                lens, end = res
                lens_l.append(lens)
                span_l.append(end - p.value_pos)
        n = sum(p.defined for p in self.pages)
        logical = sum(span_l)
        count_pad = _bucket_count(n)
        lens_all = (np.concatenate(lens_l) if lens_l
                    else np.zeros(0, np.uint32))
        total_heap = int(lens_all.astype(np.int64).sum())
        heap_pad = _bucket_bytes(max(total_heap, 1), 64)
        n_pages = _bucket(len(self.pages))
        pvs = np.full(n_pages + 1, n, dtype=np.int32)
        pvs[0] = 0
        np.cumsum([p.defined for p in self.pages],
                  out=pvs[1 : len(self.pages) + 1])

        def build(res):
            offsets, heap = res
            return DeviceColumnData(offsets=offsets, heap=heap, n_values=n,
                                    **common)

        plan = self._plan_snappy_bytes(
            stager, span_l, pvs, count_pad, heap_pad, n_pages, lens_all,
            logical, build)
        if plan is not None:
            return plan
        # plain route: stage exactly the walked stream spans, back to back
        for p in self.pages:
            p.materialize()
        bases = stager.add_segments([
            (p.raw, p.value_pos, c) for p, c in zip(self.pages, span_l)
        ])
        # zero-filled reserve: pad values past n must read length 0
        lens_base = stager.add(lens_all, reserve=count_pad * 4)
        page_base = np.zeros(n_pages, dtype=np.int64)
        page_base[: len(bases)] = bases
        tbase = _pack_tables(stager, [page_base, pvs])
        self._record_ship(ROUTE_PLAIN, logical, logical)
        return _Plan(
            ("bytes", count_pad, heap_pad, n_pages),
            lambda buf, lb_d, tb_d: _plain_bytes_staged_jit(
                buf, lb_d, tb_d, count_pad=count_pad, heap_pad=heap_pad,
                n_pages=n_pages),
            (np.int64(lens_base), np.int64(tbase)),
            build,
        )

    def _plan_snappy_bytes(self, stager, span_l, pvs, count_pad, heap_pad,
                           n_pages, lens_all, logical, build):
        """Compressed-shipping half of _finish_plain_bytes: build the op
        tables for whichever compressed payloads exist (the file's own, or
        preship's re-compression) and wire _snappy_bytes_staged_jit.
        Returns None when no compressed route applies or planning falls
        through — the caller stages the raw spans."""
        route = None
        specs = None
        if (any(p.comp is not None for p in self.pages)
                and self._route_enabled(ROUTE_DEVICE_SNAPPY)):
            comp_total = sum(len(p.comp[0]) for p in self.pages
                             if p.comp is not None)
            # ratio ~1: the op tables + resolve buy nothing — ship raw
            if comp_total <= SNAPPY_WORTH_RATIO * max(logical, 1):
                route = ROUTE_DEVICE_SNAPPY
                specs = [
                    ("comp", p.comp[0], p.comp[2], None)
                    if p.comp is not None
                    else ("raw", p.raw, p.value_pos, span)
                    for p, span in zip(self.pages, span_l)
                ]
        elif self._ship.get("recompress_bytes") is not None:
            route = ROUTE_RECOMPRESS
            specs = [
                ("comp", c, span, None)
                for c, span in zip(self._ship["recompress_bytes"], span_l)
            ]
        if specs is None:
            return None
        out_lens = [s[2] if s[0] == "comp" else s[3] for s in specs]
        page_out = np.zeros(n_pages, dtype=np.int64)
        page_out[: len(specs)] = np.concatenate(
            [[0], np.cumsum(out_lens)[:-1]])
        info = _plan_snappy_ops(stager, specs,
                                extra_tables=[page_out, pvs])
        if info is None:
            return None
        # zero-filled reserve: pad values past n must read length 0
        lens_base = stager.add(lens_all, reserve=count_pad * 4)
        self.pages_kept_compressed = len(
            [1 for s in specs if s[0] == "comp"])
        self._record_ship(route, logical, info.shipped)
        n_ops, out_pad, iters = info.n_ops, info.out_pad, info.iters
        return _Plan(
            ("bytess", count_pad, heap_pad, n_pages, n_ops, out_pad, iters),
            lambda buf, lb_d, tb_d: _snappy_bytes_staged_jit(
                buf, lb_d, tb_d, count_pad=count_pad, heap_pad=heap_pad,
                n_ops=n_ops, out_pad=out_pad, iters=iters, n_pages=n_pages),
            (np.int64(lens_base), np.int64(info.tbase)),
            build,
            stages=3 + iters,
        )

    def _finish_plain_bytes_host(self, common, stager):
        """PLAIN BYTE_ARRAY chunk: native host walk per page, merged offsets,
        heap shipped in the row-group buffer (no per-page transfers)."""
        from .kernels import plain as plain_host

        offs_parts, heap_parts = [], []
        for p in self.pages:
            ba = plain_host.decode_byte_array(
                p.raw[p.value_pos :], p.defined
            )
            offs_parts.append(ba.offsets)
            heap_parts.append(ba.heap)
        counts = np.array([len(o) - 1 for o in offs_parts], dtype=np.int64)
        heap_sizes = np.array([h.nbytes for h in heap_parts], dtype=np.int64)
        n = int(counts.sum())
        offsets = np.empty(n + 1, dtype=np.int64)
        offsets[0] = 0
        pos = 0
        hbase = 0
        for o, hs in zip(offs_parts, heap_sizes):
            k = len(o) - 1
            offsets[pos + 1 : pos + 1 + k] = o[1:] + hbase
            pos += k
            hbase += int(hs)
        heap = (np.concatenate(heap_parts) if len(heap_parts) > 1
                else heap_parts[0])
        heap_len = heap.nbytes
        heap_room = _bucket_bytes(max(heap_len, 1), 64)
        heap_base = stager.add(heap, reserve=heap_room)
        off_base = stager.add(offsets)
        n_off = _bucket_count(n + 1)
        stager.note_read_extent(off_base, n_off * 8)

        def fn(buf, off_d, heap_d):
            # bucketed offset count (tail garbage past n+1, sliced by
            # to_host); bucketed heap slice (zero padding past offsets[-1],
            # trimmed on host) keeps executables shared
            return (_plain_jit(buf, off_d, dtype="int64", count=n_off),
                    _dynslice_jit(buf, heap_d, size=heap_room))

        def build(res):
            col = DeviceColumnData(n_values=n, **common)
            col.offsets, col.heap = res
            return col

        return _Plan(("bytesh", n_off, heap_room), fn,
                     (np.int64(off_base), np.int64(heap_base)), build)

    def _parse_dict_index_page(self, p, host_max):
        """Parse one RLE_DICTIONARY page's index stream; folds the host-side
        max when it is FREE (None = unknown, defer to device check).  Shared
        by the pure-dict and mixed dict+PLAIN finish paths.  Returns the
        sliced stream too so callers staging payload segments reference the
        parsed coords.

        When the dictionary covers the index stream's whole bit-width value
        range (dict_len >= 2^width), NO encodable index can be out of range,
        so the exact-max request is skipped — that upgrade turns the
        O(runs) header walk into an O(values) scan, the single hottest host
        cost on dictionary-heavy files (~4 s of a 100-row-group 22 s scan).
        The deferred device-side max stays OPT-IN (TPQ_DEFER_DICT_CHECK=1)
        even though the _Plan refactor folds the ``jnp.max`` into the
        chunk's one fused executable with all maxima synced once at
        finalize: measured round 5 on the tunneled backend, a 100M-row scan
        holding ~700 live tiny max buffers degraded warm reps 24 s → 514 s
        (and round 4's separate-dispatch variant lost 20× before that).
        The host walk's O(values) scan is the cheaper evil at every scale
        measured, and it reports corruption at the exact page.
        """
        stream = p.raw[p.value_pos :]
        if len(stream) < 1:
            raise ParquetError("dictionary page data truncated (missing width)")
        width = int(stream[0])
        if width > 32:
            raise ParquetError(f"dictionary index width {width} invalid")
        covered = width < 31 and self.dict_len >= (1 << width)
        defer = os.environ.get("TPQ_DEFER_DICT_CHECK", "") == "1"
        meta = parse_hybrid_meta(stream, width, p.defined, pos=1,
                                 compute_max=not covered and not defer)
        if p.defined == 0:
            pass  # no indices: nothing to fold into the max
        elif covered:
            # bit-packed values are masked to `width`, hence < 2^width <=
            # dict_len — in range by construction.  RLE run values are RAW
            # unmasked bytes (see meta_parse.cpp note) and can exceed the
            # width's range, so fold them from the run table — O(runs).
            n = meta.n_runs
            rle_mask = meta.run_is_rle[:n]
            if host_max is not None and rle_mask.any():
                host_max = max(host_max,
                               int(meta.run_values[:n][rle_mask].max()))
        elif host_max is not None and meta.max_value is not None:
            host_max = max(host_max, meta.max_value)
        else:
            host_max = None  # Python fallback walk: defer check to device
        return meta, width, stream, host_max

    def _check_dict_range(self, prefix, host_max):
        if prefix and self.dict_len == 0:
            raise ParquetError("dictionary indices with empty dictionary")
        if prefix and host_max is not None and host_max >= self.dict_len:
            raise ParquetError(
                f"dictionary index {host_max} out of range ({self.dict_len}) "
                f"in column {'.'.join(self.leaf.path)}"
            )

    def _finish_dict(self, common, stager):
        if self.dict_u8 is None and self.dict_ragged is None:
            raise ParquetError("dictionary-encoded page but no dictionary page seen")
        # parse every page's index stream once (host_max folds the native
        # walk's per-page maxima; None defers the range check to device)
        parsed = []  # (page, stream, meta)
        page_widths = []
        host_max = 0 if self.pages else None
        for p in self.pages:
            meta, pw, stream, host_max = self._parse_dict_index_page(p, host_max)
            parsed.append((p, stream, meta))
            page_widths.append(pw)
        uniform = len(set(page_widths)) <= 1
        width = page_widths[0] if page_widths else 0
        prefix = sum(p.defined for p in self.pages)
        interp = _pallas_interpret_mode()
        plan = None
        if uniform and prefix and interp is not None:
            plan = _plan_hybrid_pallas(
                stager, [(m, s, p.defined) for p, s, m in parsed],
                width, prefix, _bucket_count(prefix), interp,
            )
        if plan is None:
            bases = self._value_segments(stager)
            ends_l, rle_l, vals_l, starts_l, widths_l = [], [], [], [], []
            pos0 = 0
            for (p, stream, meta), base, pw in zip(parsed, bases, page_widths):
                n = meta.n_runs
                ends_l.append(meta.run_ends[:n] + pos0)
                rle_l.append(meta.run_is_rle[:n])
                vals_l.append(meta.run_values[:n])
                # global bit base: page byte base within buf, re-zeroed for
                # the global value position (see jax_kernels.expand_rle_hybrid)
                starts_l.append(
                    meta.run_bit_starts[:n] + base * 8 - pos0 * pw
                )
                widths_l.append(np.full(n, pw, dtype=np.uint32))
                pos0 += p.defined
            ends, is_rle, rvals, starts, rwidths = _merge_run_tables(
                ends_l, rle_l, vals_l, starts_l, fill_end=prefix,
                widths_l=widths_l,
            )
        self._check_dict_range(prefix, host_max)
        dict_u8 = self.dict_u8
        has_u8 = dict_u8 is not None
        cp = _bucket_count(prefix)
        dyn: list = []
        if plan is not None:
            idx_key, idx_fn, idx_arity = plan.key, plan.fn, len(plan.dyn)
            dyn.extend(plan.dyn)
        elif uniform:
            idx_key = ("hyb", width, cp)
            idx_arity = 5

            def idx_fn(buf, e, r, v, s, nv):
                return _hybrid_jit(buf, e, r, v, s, nv, width=width, count=cp)

            dyn.extend((ends, is_rle, rvals, starts, np.int64(prefix)))
        else:
            # per-page index widths differ (dictionary grew page to page):
            # same fused expansion with per-run widths
            mw = min(max(8, (max(page_widths) + 7) // 8 * 8), 32)
            idx_key = ("hybvw", mw, cp)
            idx_arity = 6

            def idx_fn(buf, e, r, v, s, w, nv):
                return _hybrid_vw_jit(buf, e, r, v, s, w, nv, max_width=mw,
                                      count=cp)

            dyn.extend((ends, is_rle, rvals, starts, rwidths,
                        np.int64(prefix)))
        # no native walk: deferred on-device range check (max rides the
        # fused call's outputs, one sync at finalize); bucketing tail lanes
        # are zeroed by n_valid, so the max reflects only real indices
        need_max = bool(prefix) and host_max is None
        ship = self._dict_ship  # (route, payload, out_len) or None: ship.py
        if has_u8:
            # dictionary bytes ride the row-group buffer (no extra transfer);
            # the row count is bucketed so the slice/gather executables are
            # shared across chunks with different dict sizes
            dict_kp = _bucket(max(self.dict_len, 1))
            dict_itemsize = int(dict_u8.shape[1])
            du8_fn = None
            if ship is not None:
                info = _plan_snappy_ops(
                    stager, [("comp", ship[1], ship[2], None)])
                if info is not None:
                    # value table shipped compressed; the device gathers the
                    # bucketed rows out of the stream's output space.  Rows
                    # past dict_len resolve through padded ops (staged byte
                    # 0) — unlike the plain route's zero reserve they are
                    # garbage, but the deferred range check raises at
                    # finalize before a clamped gather can escape.
                    self._record_ship(
                        ship[0], dict_u8.nbytes, info.shipped,
                        predicted=self._dict_costs.get(ship[0], 0.0),
                        predicted_device=self._dict_dev_costs.get(
                            ship[0], 0.0))
                    dyn.append(np.int64(info.tbase))
                    dkey = ("du8s", dict_kp, dict_itemsize, info.n_ops,
                            info.out_pad, info.iters)
                    _i = info

                    def du8_fn(buf, tb):
                        return _snappy_gather_staged_jit(
                            buf, tb, n_ops=_i.n_ops, out_pad=_i.out_pad,
                            iters=_i.iters,
                            nbytes=dict_kp * dict_itemsize,
                        ).reshape(dict_kp, dict_itemsize)
            if du8_fn is None:
                # zero-filled reserve (NOT a read-extent overlap): clamped
                # out-of-range gathers on the deferred-check path must see
                # zeros, never a neighboring chunk's staged bytes
                dict_base = stager.add(np.ascontiguousarray(dict_u8),
                                       reserve=dict_kp * dict_itemsize)
                dyn.append(np.int64(dict_base))
                dkey = ("du8", dict_kp, dict_itemsize)

                def du8_fn(buf, tb):
                    return _dict_rows_jit(buf, tb, k=dict_kp,
                                          itemsize=dict_itemsize)
        else:
            # ragged (string) dictionaries ride the buffer too — two
            # jnp.asarray transfers per chunk otherwise dominate dict-heavy
            # scans at many-row-group scale (~2.5 ms per transfer)
            roff = np.ascontiguousarray(self.dict_ragged.offsets,
                                        dtype=np.int64)
            roff_n = _bucket_count(len(roff))
            roff_base = stager.add(roff, reserve=roff_n * 8)
            rheap = np.ascontiguousarray(self.dict_ragged.heap)
            rheap_room = _bucket_bytes(max(rheap.nbytes, 1), 64)
            dheap_fn = None
            if ship is not None:
                info = _plan_snappy_ops(
                    stager, [("comp", ship[1], ship[2], None)])
                if info is not None:
                    # heap shipped compressed (offsets stay plain — tiny);
                    # bytes past the real heap resolve through padded ops,
                    # same garbage contract as the plain route's padding
                    self._record_ship(
                        ship[0], rheap.nbytes, info.shipped,
                        predicted=self._dict_costs.get(ship[0], 0.0),
                        predicted_device=self._dict_dev_costs.get(
                            ship[0], 0.0))
                    dyn.extend((np.int64(roff_base), np.int64(info.tbase)))
                    dkey = ("drags", roff_n, rheap_room, info.n_ops,
                            info.out_pad, info.iters)
                    _i = info

                    def dheap_fn(buf, hb):
                        return _snappy_gather_staged_jit(
                            buf, hb, n_ops=_i.n_ops, out_pad=_i.out_pad,
                            iters=_i.iters, nbytes=rheap_room,
                        )
            if dheap_fn is None:
                rheap_base = stager.add(rheap, reserve=rheap_room)
                dyn.extend((np.int64(roff_base), np.int64(rheap_base)))
                dkey = ("drag", roff_n, rheap_room)

                def dheap_fn(buf, hb):
                    return _dynslice_jit(buf, hb, size=rheap_room)

        def fn(buf, *d):
            idx = idx_fn(buf, *d[:idx_arity])
            outs = {"idx": idx}
            if has_u8:
                outs["du8"] = du8_fn(buf, d[idx_arity])
            else:
                # device slices of the staged ragged dictionary (padding
                # past the real offsets is garbage consumers never index:
                # every valid dict index is < dict_len)
                outs["doff"] = _plain_jit(buf, d[idx_arity], dtype="int64",
                                          count=roff_n)
                outs["dheap"] = dheap_fn(buf, d[idx_arity + 1])
            if need_max:
                outs["max"] = _max_jit(idx)
            return outs

        deferred = self._deferred
        dict_len = self.dict_len
        path_name = ".".join(self.leaf.path)
        dict_dtype = self.dict_dtype

        def build(res):
            col = DeviceDictColumn(indices=res["idx"], n_values=prefix,
                                   **common)
            if has_u8:
                col.dict_u8 = res["du8"]
                col.dict_dtype = dict_dtype
            else:
                col.dict_offsets = res["doff"]
                col.dict_heap = res["dheap"]
            if need_max:
                deferred.append((res["max"], dict_len, path_name))
            return col

        return _Plan(("dict", idx_key, dkey, need_max), fn, tuple(dyn), build)

    def _finish_delta(self, common, stager):
        ptype = self.leaf.physical_type
        if ptype not in (Type.INT32, Type.INT64):
            raise ParquetError(f"DELTA_BINARY_PACKED invalid for {ptype!r}")
        bits = 32 if ptype == Type.INT32 else 64
        metas = []
        for p in self.pages:
            m = parse_delta_meta(p.raw[p.value_pos :], bits)
            if m.count < p.defined:
                raise ParquetError(
                    f"delta stream yielded {m.count} of {p.defined} values"
                )
            metas.append(m)
        if any(m.values_per_mini != metas[0].values_per_mini for m in metas):
            # spec-legal but rare: block geometry differs across pages;
            # page-at-a-time fallback rather than a per-page-geometry kernel
            return self._finish_host(common)
        # minis-per-block from the stream's own header varints (the walker's
        # return contract carries only values_per_mini); geometry is constant
        # per stream and already validated by the walk
        from .kernels.delta import _read_uvarint

        mbs = set()
        for p in self.pages:
            bsz, p2 = _read_uvarint(p.raw, p.value_pos)
            mpb, _ = _read_uvarint(p.raw, p2)
            mbs.add(mpb)
        if len(mbs) != 1:
            return self._finish_host(common)
        mb = mbs.pop()
        if any((m.mini_bit_starts & 7).any() for m in metas):
            # miniblocks are byte-aligned by construction; anything else
            # means a walker change this compact path no longer matches
            return self._finish_host(common)
        if (stager.total + sum(len(p.raw) - p.value_pos for p in self.pages)
                > np.iinfo(np.int32).max):
            # block byte starts are staged as i32 (checked before any stager
            # mutation so the fallback leaves no dead bytes)
            return self._finish_host(common)
        bases = self._value_segments(stager)
        # every static shape bucketed; real geometry rides the traced tables.
        # Tables are COMPACT (see _delta_pages_staged_jit): per-BLOCK byte
        # starts + mins, one width byte per mini.
        n_pages = _bucket(len(metas))
        count = _bucket_count(max(m.count for m in metas))
        m_max = _bucket(max(m.mini_bit_starts.shape[0] for m in metas))
        m_max = -(-m_max // mb) * mb  # multiple of mb for the block reshape
        n_blocks = m_max // mb
        bstarts = np.zeros((n_pages, n_blocks), dtype=np.int32)
        widths = np.zeros((n_pages, m_max), dtype=np.uint8)
        bmins = np.zeros((n_pages, n_blocks), dtype=np.uint64)
        firsts = np.zeros(n_pages, dtype=np.int64)
        for i, (m, base) in enumerate(zip(metas, bases)):
            kk = m.mini_bit_starts.shape[0]
            kb = -(-kk // mb)
            bs = (m.mini_bit_starts[::mb] >> 3) + base
            bstarts[i, :kb] = bs
            bstarts[i, kb:] = bs[-1] if kb else 0
            widths[i, :kk] = m.mini_widths
            bmins[i, :kb] = m.mini_min_delta[::mb]
            firsts[i] = m.first_value
        total_real = sum(p.defined for p in self.pages)
        page_starts = np.full(n_pages + 1, total_real, dtype=np.int64)
        page_starts[0] = 0
        np.cumsum([p.defined for p in self.pages],
                  out=page_starts[1 : len(metas) + 1])
        max_width = max(1, int(widths.max(initial=0)))
        max_width = min((max_width + 7) // 8 * 8, 64)  # byte-rounded: 8 shapes
        tbase = _pack_tables(stager, [firsts, bstarts, widths, bmins,
                                      page_starts])
        vpm = metas[0].values_per_mini
        total_b = _bucket_count(total_real)
        return _Plan(
            ("delta", vpm, mb, count, bits, max_width, total_b, n_pages,
             m_max),
            lambda buf, tb_d: _delta_pages_staged_jit(
                buf, tb_d, values_per_mini=vpm, mb=mb, count=count,
                bits=bits, max_width=max_width, total=total_b,
                n_pages=n_pages, m_max=m_max),
            (np.int64(tbase),),
            lambda v: DeviceColumnData(values=v, n_values=total_real,
                                       **common),
        )

    def _finish_mixed_dict_plain(self, common, stager):
        """Fixed-width chunk whose pages mix RLE_DICTIONARY and PLAIN.

        The write-side dictionary-overflow fallback (type_dict.go:101-103)
        always produces a dict-encoded PREFIX of pages followed by a PLAIN
        suffix.  The prefix decodes exactly like _finish_dict (one fused
        expansion + gather over merged run tables); the suffix is one
        contiguous bitcast when the staged segments are exactly the value
        bytes (always true for the overflow shape), else one dispatch per
        page.  Two or three executables per chunk total — per-page dispatch
        diversity is what the tunneled backend punishes.
        """
        name = _PTYPE_TO_NAME[self.leaf.physical_type]
        itemsize = np.dtype(name).itemsize
        kinds = []
        for p in self.pages:
            enc = Encoding(p.encoding)
            kinds.append(Encoding.RLE_DICTIONARY if enc == Encoding.PLAIN_DICTIONARY
                         else enc)
        n_dict = 0
        for k in kinds:
            if k != Encoding.RLE_DICTIONARY:
                break
            n_dict += 1
        if any(k == Encoding.RLE_DICTIONARY for k in kinds[n_dict:]):
            # dict pages after plain pages: not the overflow shape
            return self._finish_host(common)

        bases = self._value_segments(stager)
        dict_pages = self.pages[:n_dict]
        plain_pages = self.pages[n_dict:]

        # --- dict prefix: per-page expansion (widths GROW page to page as
        # the dictionary fills — a merged single-width kernel would corrupt),
        # one concat, ONE gather --------------------------------------------
        dict_calls = []  # (tables..., width, count)
        prefix = 0
        host_max = 0
        for p, base in zip(dict_pages, bases[:n_dict]):
            meta, width, _, host_max = self._parse_dict_index_page(p, host_max)
            dict_calls.append((
                meta.run_ends, meta.run_is_rle, meta.run_values,
                meta.run_bit_starts + int(base) * 8, int(width), p.defined,
            ))
            prefix += p.defined
        self._check_dict_range(prefix, host_max)

        # --- plain suffix: contiguous bitcast when segments are exact -------
        plain_total = sum(p.defined for p in plain_pages)
        _check_plain_sizes(plain_pages, itemsize)
        contiguous = True
        for p, base, nxt in zip(plain_pages, bases[n_dict:],
                                list(bases[n_dict + 1 :]) + [None]):
            seg = len(p.raw) - p.value_pos
            if seg != p.defined * itemsize or (
                nxt is not None and int(nxt) != int(base) + seg
            ):
                contiguous = False
                break
        plain_base = int(bases[n_dict]) if plain_pages else 0
        plain_calls = None
        if not contiguous:
            plain_calls = [
                (int(base), p.defined) for p, base in
                zip(plain_pages, bases[n_dict:])
            ]

        dict_u8 = self.dict_u8
        dict_dtype = self.dict_dtype
        deferred = self._deferred
        dict_len = self.dict_len
        path_name = ".".join(self.leaf.path)

        # dynamic layout: per live dict call (ends, is_rle, values, starts,
        # i64 count) · dict rows array · per plain call i64 base — statics
        # (widths, counts, contiguity) all ride the key
        live_calls = [c for c in dict_calls if c[5]]
        wc = tuple((w, c) for _, _, _, _, w, c in live_calls)
        need_max = bool(prefix) and host_max is None
        plain_desc = (("contig", plain_total) if plain_calls is None
                      else tuple(c for _, c in plain_calls))
        dyn: list = []
        for e, r, v, s, _w, c in live_calls:
            dyn.extend((e, r, v, s, np.int64(c)))
        if prefix:
            dyn.append(np.ascontiguousarray(dict_u8))
        if plain_total:
            if plain_calls is None:
                dyn.append(np.int64(plain_base))
            else:
                dyn.extend(np.int64(b) for b, _ in plain_calls)

        def fn(buf, *d):
            parts = []
            outs = {}
            j = 0
            if prefix:
                idx_parts = []
                for w, c in wc:
                    e, r, v, s, nv = d[j : j + 5]
                    j += 5
                    idx_parts.append(
                        _hybrid_jit(buf, e, r, v, s, nv, width=w, count=c))
                idx = (idx_parts[0] if len(idx_parts) == 1
                       else _concat_jit(idx_parts))
                if need_max:
                    outs["max"] = _max_jit(idx)
                parts.append(_dict_gather_bytes_jit(d[j], idx,
                                                    dtype=dict_dtype))
                j += 1
            if plain_total:
                if plain_calls is None:
                    parts.append(_plain_jit(buf, d[j], dtype=name,
                                            count=plain_total))
                else:
                    for _, c in plain_calls:
                        parts.append(_plain_jit(buf, d[j], dtype=name,
                                                count=c))
                        j += 1
            outs["vals"] = parts[0] if len(parts) == 1 else _concat_jit(parts)
            return outs

        def build(res):
            if need_max:
                deferred.append((res["max"], dict_len, path_name))
            return DeviceColumnData(values=res["vals"], **common)

        return _Plan(
            ("mixed", name, dict_dtype, wc, bool(prefix), plain_desc,
             need_max),
            fn, tuple(dyn), build,
        )

    def _finish_host(self, common):
        """Host decode per page (byte arrays, INT96, BSS, boolean RLE, mixed);
        per-chunk staging, independent of the row-group buffer."""
        from .jax_decode import DeviceChunkDecoder

        helper = DeviceChunkDecoder(self.leaf)
        helper.dict_u8 = (
            jnp.asarray(self.dict_u8) if self.dict_u8 is not None else None
        )
        helper.dict_dtype = self.dict_dtype
        helper.dict_len = self.dict_len
        if self.dict_ragged is not None:
            helper._dict_host_offsets = self.dict_ragged.offsets
            helper.dict_offsets = jnp.asarray(self.dict_ragged.offsets)
            helper.dict_heap = jnp.asarray(self.dict_ragged.heap)
        vals_parts, off_parts, heap_parts = [], [], []
        for p in self.pages:
            v, off, heap = helper._decode_values_device(
                p.encoding, p.raw, p.value_pos, p.defined
            )
            if v is not None:
                vals_parts.append(v)
            else:
                off_parts.append(off)
                heap_parts.append(heap)
        for mx in helper._idx_maxima:
            self._deferred.append((mx, self.dict_len, ".".join(self.leaf.path)))
        out = DeviceColumnData(**common)
        if off_parts:
            if len(off_parts) == 1:
                out.offsets, out.heap = off_parts[0], heap_parts[0]
            else:
                out.offsets, out.heap = _concat_ragged_jit(off_parts, heap_parts)
        elif vals_parts:
            out.values = (
                vals_parts[0] if len(vals_parts) == 1 else _concat_jit(vals_parts)
            )
        else:
            out.values = jnp.asarray(np.zeros(0, dtype=np.int64))
        # transfers already happened above: pass-through plan
        return _Plan(None, None, (), lambda _res: out)


@scoped_x64
def _collect_chunk(
    buf: bytes, codec: int, total_values: int, leaf: SchemaNode,
    deferred_checks: list, validate_crc: bool = False, alloc=None,
    statistics=None, skip_pages=None, context=None, dict_cache=None,
) -> Optional[_ChunkAssembler]:
    """Walk a chunk's pages into an assembler (host phase); None if no data.

    ``skip_pages``: data-page ordinals pruned by page-level predicate
    pushdown — their payloads are never decompressed, parsed, or staged.
    ``context``: decode-site coordinates ({file, column, row_group,
    chunk_offset}) stamped onto every raise (quarantine.error_context),
    plus the failing page's ordinal and byte offset.
    ``dict_cache`` (serve.BoundDictCache duck type): read-through cache of
    DECODED dictionaries keyed by this context's (row_group, column) — a
    hit adopts the decoded value table (and its compressed ship payload)
    without decompressing or parsing the dictionary page again."""
    from .format import CompressionCodec
    from .quarantine import error_context

    ctx = dict(context or {})
    if "column" not in ctx and leaf.path:
        ctx["column"] = ".".join(leaf.path)
    chunk_offset = ctx.pop("chunk_offset", 0) or 0
    asm = _ChunkAssembler(leaf, deferred_checks)
    asm.stats_span = _int_stats_span(statistics, leaf)
    asm.alloc = alloc
    data_ordinal = 0
    # PLAIN SNAPPY chunks (fixed-width AND byte-array) can skip host
    # decompression entirely (device-side expansion — _plan_device_snappy /
    # _plan_snappy_bytes); parse_data_page applies the per-page structural
    # conditions (PLAIN encoding, levels outside the compressed region)
    lazy = (codec == CompressionCodec.SNAPPY
            and (leaf.physical_type in _PTYPE_TO_NAME
                 or leaf.physical_type == Type.BYTE_ARRAY)
            and os.environ.get("TPQ_DEVICE_SNAPPY", "1") != "0")
    if lazy:
        from . import native

        lazy = native.available()
    with error_context(**ctx):
        pages = walk_pages(buf, total_values)
    for ps in pages:
        header = ps.header
        pt = header.type
        if pt == PageType.DICTIONARY_PAGE:
            dk = (ctx.get("row_group"), ctx.get("column"),
                  # CRC tier in the key (chunk_decode._dict_cache_key
                  # contract): a validating request never adopts an
                  # unvalidated decode
                  f"dev:v{1 if validate_crc else 0}")
            if (dict_cache is not None and dk[0] is not None
                    and dk[1] is not None):
                hit = dict_cache.get(dk[0], dk[1], dk[2])
                if hit is not None:
                    asm.adopt_dictionary(hit)
                    continue
            with error_context(offset=chunk_offset + ps.payload_start, **ctx):
                payload = buf[ps.payload_start : ps.payload_end]
                _check_crc(header, payload, validate_crc)
                if alloc is not None:
                    alloc.register(max(header.uncompressed_page_size or 0, 0))
                raw = decompress_block(payload, codec,
                                       header.uncompressed_page_size)
                dh = header.dictionary_page_header
                asm.set_dictionary(raw, dh.encoding, dh.num_values or 0)
            if codec == CompressionCodec.SNAPPY:
                # keep the compressed payload: the ship planner may send the
                # dictionary VALUE TABLE over the link compressed and expand
                # it on device (_preship_dict / _finish_dict)
                asm.dict_comp = (payload,
                                 max(header.uncompressed_page_size or 0, 0))
            if (dict_cache is not None and dk[0] is not None
                    and dk[1] is not None):
                entry = asm.dict_cache_entry()
                if entry is not None:
                    dict_cache.put(dk[0], dk[1], dk[2], entry,
                                   entry["nbytes"])
            continue
        if pt in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2):
            if skip_pages and data_ordinal in skip_pages:
                asm.pages_pruned += 1
                data_ordinal += 1
                continue
            with error_context(page=data_ordinal,
                               offset=chunk_offset + ps.payload_start, **ctx):
                asm.pages.append(
                    parse_data_page(ps, buf, codec, leaf,
                                    validate_crc=validate_crc,
                                    alloc=alloc, decode_levels=False,
                                    lazy_decompress=lazy)
                )
            data_ordinal += 1
            continue
        # index/unknown pages: skip
    # returned even with zero pages: a fully-pruned chunk still carries its
    # pages_pruned count (callers emit a placeholder column for it)
    return asm


@scoped_x64
def decode_chunk_batched(
    buf: bytes, codec: int, total_values: int, leaf: SchemaNode,
    deferred_checks: list, validate_crc: bool = False,
) -> DeviceColumnData:
    """Decode one chunk with per-chunk fused dispatch (no blocking syncs).

    Dictionary-index range checks land in ``deferred_checks`` as
    (device_max, dict_len, column) tuples — the caller MUST drain them
    (``DeviceFileReader.finalize`` / ``_finalize_many`` semantics) or the
    clamped on-device gather silently tolerates corrupt indices.  Callers
    that decode a single chunk and cannot batch the sync should pass a list
    and check it immediately."""
    asm = _collect_chunk(buf, codec, total_values, leaf, deferred_checks,
                         validate_crc)
    if asm is None or not asm.pages:
        return DeviceColumnData(
            values=jnp.asarray(np.zeros(0, dtype=np.int64)),
            max_def=leaf.max_def, max_rep=leaf.max_rep, num_leaf_slots=0,
        )
    asm.preship()
    stager = _RowGroupStager()
    plan = asm.finish(stager)
    return _run_plans([("c", plan)], stager.stage())["c"]


@dataclass
class ReaderStats:
    """Decode observability counters (SURVEY.md §5.5 — the subsystem the
    reference lacks entirely).  Accumulated per DeviceFileReader; throughput
    properties divide by wall time from first host parse to last dispatch."""

    row_groups: int = 0
    chunks: int = 0
    pages: int = 0
    pages_device_expanded: int = 0  # pages shipped compressed (device snappy)
    pages_pruned: int = 0           # pages skipped by page-level pushdown
    rows: int = 0
    compressed_bytes: int = 0      # chunk bytes read from the file
    staged_bytes: int = 0          # HBM bytes shipped (row-group buffers)
    host_seconds: float = 0.0      # decompress + structure parse + assembly
    # the round-13 `device_seconds` scalar double-counted wall time: the
    # staging worker and the dispatching thread both added their (possibly
    # CONCURRENT) intervals to it, so the sum could exceed the device lane's
    # wall.  Split lanes — on a serial (prefetch=0) run host + stage +
    # dispatch sums back to ~wall (regression-tested); on a pipelined run
    # the lanes overlap and each is honest on its own.
    stage_seconds: float = 0.0     # host->device staging (worker or inline)
    dispatch_seconds: float = 0.0  # issuing fused XLA calls (not queue drain)
    wall_seconds: float = 0.0
    # ship-planner accounting (ship.py): per-route stream counts and byte
    # totals.  `logical` is what plain shipping would have moved; `shipped`
    # what the chosen route actually registered for transfer — the
    # difference IS the link-byte win the round-5 VERDICT prescribed.
    route_streams: dict = field(default_factory=dict)
    route_bytes_logical: dict = field(default_factory=dict)
    route_bytes_shipped: dict = field(default_factory=dict)
    # the cost model's modeled seconds for the routes that RAN, summed per
    # route — obs.StatsRegistry.ship_feedback compares them to the measured
    # link lane (staged bytes / stage seconds) for TPQ_LINK_MBPS calibration
    route_pred_seconds: dict = field(default_factory=dict)
    # the model's DEVICE-lane seconds per route (ship.ShipPlanner
    # .device_costs) — ship_feedback compares them to the measured per-route
    # completion timing (DeviceStats) for TPQ_DEVICE_MBPS calibration
    route_pred_device_seconds: dict = field(default_factory=dict)
    # for FUSED routes: the unfused chain's modeled device seconds
    # (ship.ShipPlanner.unfused_device_costs) — the prediction the doctor's
    # fusion-win verdict compares the measured fused lane against
    route_pred_unfused_device_seconds: dict = field(default_factory=dict)
    # fused routes that degraded to their unfused twin (kernel caps, level
    # lanes, i32 ceilings) — forced-fused on an ineligible stream counts
    # here instead of crashing
    fused_fallbacks: int = 0
    # the link rate the planner ASSUMED (TPQ_LINK_MBPS or the default
    # planning point) — pq_tool doctor prints it next to the measured rate
    # so a recalibration names both sides
    planner_link_mbps: float = 0.0

    def count_route(self, route: str, logical: int, shipped: int,
                    predicted: float = 0.0,
                    predicted_device: float = 0.0,
                    predicted_unfused_device: float = 0.0) -> None:
        self.route_streams[route] = self.route_streams.get(route, 0) + 1
        self.route_bytes_logical[route] = (
            self.route_bytes_logical.get(route, 0) + logical)
        self.route_bytes_shipped[route] = (
            self.route_bytes_shipped.get(route, 0) + shipped)
        self.route_pred_seconds[route] = (
            self.route_pred_seconds.get(route, 0.0) + predicted)
        self.route_pred_device_seconds[route] = (
            self.route_pred_device_seconds.get(route, 0.0) + predicted_device)
        if predicted_unfused_device:
            self.route_pred_unfused_device_seconds[route] = (
                self.route_pred_unfused_device_seconds.get(route, 0.0)
                + predicted_unfused_device)

    @property
    def link_bytes_logical(self) -> int:
        return sum(self.route_bytes_logical.values())

    @property
    def link_bytes_shipped(self) -> int:
        return sum(self.route_bytes_shipped.values())

    @property
    def rows_per_sec(self) -> float:
        return self.rows / self.wall_seconds if self.wall_seconds else 0.0

    @property
    def bytes_per_sec(self) -> float:
        return (self.compressed_bytes / self.wall_seconds
                if self.wall_seconds else 0.0)

    @property
    def pages_per_chunk(self) -> float:
        return self.pages / self.chunks if self.chunks else 0.0

    def as_dict(self) -> dict:
        return {
            "row_groups": self.row_groups, "chunks": self.chunks,
            "pages": self.pages,
            "pages_device_expanded": self.pages_device_expanded,
            "pages_pruned": self.pages_pruned,
            "rows": self.rows,
            "compressed_bytes": self.compressed_bytes,
            "staged_bytes": self.staged_bytes,
            "link_bytes_logical": self.link_bytes_logical,
            "link_bytes_shipped": self.link_bytes_shipped,
            "ship_routes": {
                r: {"streams": self.route_streams[r],
                    "logical": self.route_bytes_logical.get(r, 0),
                    "shipped": self.route_bytes_shipped.get(r, 0),
                    # 9 decimals: a tiny stream's sub-µs prediction must
                    # not round to a 0.0 that ship_feedback would read as
                    # "no prediction" (nulling the error ratio)
                    "predicted_s": round(
                        self.route_pred_seconds.get(r, 0.0), 9),
                    "predicted_device_s": round(
                        self.route_pred_device_seconds.get(r, 0.0), 9),
                    # nonzero only on fused routes: the unfused chain's
                    # modeled device seconds (fusion-win's bar)
                    "predicted_unfused_device_s": round(
                        self.route_pred_unfused_device_seconds.get(r, 0.0),
                        9)}
                for r in sorted(self.route_streams)
            },
            "fused_fallbacks": self.fused_fallbacks,
            "planner_link_mbps": round(self.planner_link_mbps, 1),
            "host_seconds": round(self.host_seconds, 6),
            "stage_seconds": round(self.stage_seconds, 6),
            "dispatch_seconds": round(self.dispatch_seconds, 6),
            "wall_seconds": round(self.wall_seconds, 6),
            "rows_per_sec": round(self.rows_per_sec, 1),
            "bytes_per_sec": round(self.bytes_per_sec, 1),
            "pages_per_chunk": round(self.pages_per_chunk, 3),
        }


# ---------------------------------------------------------------------------
# per-route device timing (the completion-side lane, TPQ_DEVICE_TIMING)
# ---------------------------------------------------------------------------

# plan-key leading token -> kernel family, the granularity the device lane
# is attributed at (doctor names "the gather family of the dict route", not
# an opaque executable hash).  Families follow the decode pipeline's device
# passes: snappy_resolve (op-table source-map resolves), unpack (bitpack /
# delta reconstruction), gather (dictionary index gathers), narrow
# (widen/re-bias of truncated ints), levels (RLE-hybrid level expansion),
# plain (reshape/bitcast-only decodes and host pass-throughs).
_KERNEL_FAMILIES = {
    "snappy": "snappy_resolve", "bytess": "snappy_resolve",
    "narrows": "narrow", "narrow": "narrow",
    "lvlx": "levels", "lvlp": "levels",
    "dict": "gather", "mixed": "gather",
    "hyb": "unpack", "hybvw": "unpack", "delta": "unpack",
    "plain": "plain", "rows": "plain", "bytes": "plain", "bytesh": "plain",
    "bool": "plain",
    # the fused megakernels are their OWN family: one pallas pass running
    # what the families above do as a staged chain (ISSUE 13) — the doctor
    # names it directly when it dominates, and the fusion-win verdict
    # compares it against the unfused chain's prediction
    "fusedp": "fused", "fusedns": "fused",
}


def _kernel_family(key) -> str:
    """Kernel family of a plan key (a ``("col", value_key, ...)`` composite
    classifies by its VALUE plan — levels ride every column)."""
    if isinstance(key, tuple) and key:
        if key[0] == "col":
            return _kernel_family(key[1])
        return _KERNEL_FAMILIES.get(key[0], "plain")
    return "plain"


def _device_timing_enabled() -> bool:
    """Whether the completion-timing lane may run: ``TPQ_DEVICE_TIMING``
    (default on) AND a live jax backend to time against.  A host with no
    usable device (mis-set JAX_PLATFORMS, driverless box) drops the lane
    with ONE warning instead of failing every reader construction — the
    CPU backend counts as a device (block_until_ready is its clock)."""
    from .obs import env_int, warn_env_once

    if env_int("TPQ_DEVICE_TIMING", 1, lo=0) == 0:
        return False
    try:
        ok = bool(jax.devices())
    except Exception:  # noqa: BLE001 — no backend is a disable, not a raise
        ok = False
    if not ok:
        warn_env_once("TPQ_DEVICE_TIMING", "<no jax device>",
                      "disabled (no device clock)")
        return False
    return True


class DeviceStats:
    """Per-route / per-kernel-family device completion timing counters.

    The device half of :class:`~tpu_parquet.pipeline.PipelineStats`: where
    the pipeline's ``dispatch_seconds`` is the HOST wall of issuing async
    XLA calls (microseconds), these are the seconds until the dispatched
    work actually COMPLETED on device (``block_until_ready``), keyed by
    ship route and kernel family — the attribution the plain_int64 gap and
    the fused-megakernel work need (ROADMAP direction 2).

    Per route: ``dispatches`` (fused column dispatches timed),
    ``device_seconds`` (dispatch→completion), ``bytes_in`` (logical output
    bytes the kernels produce — the planner's per-OUTPUT-byte device charge,
    so ``bytes_in / device_seconds`` IS the measured ``TPQ_DEVICE_MBPS``),
    and ``bytes_staged`` (link bytes staged for the route's columns).
    ``h2d`` times the staged row-group buffer transfers the same way.
    Thread-safe: the timing worker accumulates while readers snapshot.

    Caveat — completion semantics: the worker serializes each interval
    against the previous completion (see ``_devtimer_worker``), so the
    per-route seconds partition ONE device timeline — route shares of
    the serialized device lane, never a sum that can exceed it.  Per-op
    exclusive kernel time is ``TPQ_XPROF``'s job, not this lane's.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # route -> [dispatches, s, b_in, b_staged, device_passes]
        self._routes: dict = {}
        self._kernels: dict = {}  # family -> [dispatches, s]
        self._h2d = [0, 0.0, 0]   # transfers, seconds, bytes

    def note_dispatch(self, route: str, family: str, seconds: float,
                      bytes_in: int = 0, bytes_staged: int = 0,
                      passes: int = 1) -> None:
        with self._lock:
            r = self._routes.setdefault(route, [0, 0.0, 0, 0, 0])
            r[0] += 1
            r[1] += seconds
            r[2] += int(bytes_in)
            r[3] += int(bytes_staged)
            r[4] += int(passes)
            k = self._kernels.setdefault(family, [0, 0.0])
            k[0] += 1
            k[1] += seconds

    def note_h2d(self, seconds: float, nbytes: int = 0) -> None:
        with self._lock:
            self._h2d[0] += 1
            self._h2d[1] += seconds
            self._h2d[2] += int(nbytes)

    def progress(self) -> dict:
        """Cumulative counters for the sampler's ``device`` track and the
        watchdog heartbeat (their slope is live device throughput)."""
        with self._lock:
            return {
                "dispatches": sum(r[0] for r in self._routes.values()),
                "device_seconds": round(
                    sum(r[1] for r in self._routes.values()), 6),
                "h2d_transfers": self._h2d[0],
                "h2d_seconds": round(self._h2d[1], 6),
            }

    def as_dict(self) -> dict:
        # 9 decimals on seconds: a tiny run's sub-µs kernel must not round
        # to a 0.0 that ship_feedback would read as "unmeasured" (same
        # contract as ReaderStats.predicted_s)
        with self._lock:
            return {
                "dispatches": sum(r[0] for r in self._routes.values()),
                "device_seconds": round(
                    sum(r[1] for r in self._routes.values()), 9),
                "routes": {
                    # device_passes: STRUCTURAL separate-device-pass count
                    # (see _Plan.stages) — passes == dispatches is the
                    # registry-level proof a route ran fused (no HBM
                    # round trips between stages)
                    route: {"dispatches": r[0],
                            "device_seconds": round(r[1], 9),
                            "bytes_in": r[2], "bytes_staged": r[3],
                            "device_passes": r[4]}
                    for route, r in sorted(self._routes.items())
                },
                "kernels": {
                    fam: {"dispatches": k[0],
                          "device_seconds": round(k[1], 9)}
                    for fam, k in sorted(self._kernels.items())
                },
                "h2d": {"transfers": self._h2d[0],
                        "device_seconds": round(self._h2d[1], 9),
                        "bytes": self._h2d[2]},
            }


class _DeviceTimer:
    """Completion-side timing worker for the device lane.

    Dispatches (and staged transfers) are ASYNC — blocking the dispatching
    thread on ``block_until_ready`` would serialize the very pipeline the
    timing is meant to attribute.  Instead each dispatch hands its output
    arrays (plus route/family/bytes and its dispatch timestamp) to one
    daemon worker (``tpq-devtimer``, covered by bench.py's zero-leaked-
    daemon-threads gate) that blocks until the work completes and folds
    ``t_complete - t_dispatch`` into :class:`DeviceStats` — and, when a
    tracer is listening, emits a ``device.<route>`` span so ``pq_tool
    trace`` prints device lanes in the same p50/p95 table as the host
    stages.

    Disabled (``TPQ_DEVICE_TIMING=0`` or no backend): ``submit`` is one
    attribute check, guarded <3% by the tier-1 overhead test.  The worker
    starts lazily on first submit and ``stop()`` joins it (idempotent;
    submits after stop are dropped, so a closed reader can never respawn
    the thread).
    """

    def __init__(self, stats: DeviceStats, tracer=None,
                 enabled: "bool | None" = None):
        self.stats = stats
        self.tracer = tracer
        self.enabled = (_device_timing_enabled() if enabled is None
                        else bool(enabled))
        self._lock = threading.Lock()
        self._q = None
        self._thread = None
        self._closed = False

    def submit(self, kind: str, route: str, family: str, arrays, t0: float,
               bytes_in: int = 0, bytes_staged: int = 0,
               passes: int = 1) -> None:
        if not self.enabled:
            return
        q = self._q
        if q is None:
            q = self._start()
            if q is None:
                return  # closed
        q.put((kind, route, family, arrays, t0, bytes_in, bytes_staged,
               passes))

    def _start(self):
        import queue
        import weakref

        with self._lock:
            if self._closed:
                return None
            if self._q is None:
                self._q = queue.Queue()
                # the worker references only (queue, stats, tracer) — never
                # this timer — so an abandoned reader (no close()) lets the
                # timer become unreachable and the finalizer below delivers
                # the shutdown sentinel: no thread outlives its reader's
                # collection, even without the explicit stop()
                self._thread = threading.Thread(
                    target=_devtimer_worker,
                    args=(self._q, self.stats, self.tracer),
                    name="tpq-devtimer", daemon=True)
                self._thread.start()
                weakref.finalize(self, self._q.put, None)
            return self._q

    def drain(self, timeout: float = 2.0) -> None:
        """Wait (bounded) until every submitted dispatch has been timed —
        a mid-session stats read must not observe 1 of a group's 3
        dispatches just because the worker is still blocking on the other
        two.  Bounded: a wedged device must not also wedge a flight dump
        whose registry provider calls this."""
        import time as _time

        q = self._q
        if q is None or not self.enabled:
            return
        deadline = _time.monotonic() + timeout
        while q.unfinished_tasks and _time.monotonic() < deadline:
            _time.sleep(0.002)

    def stop(self) -> None:
        """Drain and join the worker (idempotent, thread-leak-safe: every
        already-submitted dispatch is still timed before the join)."""
        with self._lock:
            self._closed = True
            q, t = self._q, self._thread
            self._q = self._thread = None
        if t is None:
            return
        q.put(None)
        t.join(timeout=10.0)


def _devtimer_worker(q, stats: DeviceStats, tracer) -> None:
    """The completion worker's loop (module-level on purpose: it must not
    reference the :class:`_DeviceTimer`, or the timer could never be
    collected and its shutdown finalizer could never fire).

    Intervals are SERIALIZED against the previous completion: dispatches
    ride one async device queue, so an interval anchored at its own
    dispatch time would also contain every earlier dispatch's device time
    and the per-route sums would overcount the device wall several-fold
    (K columns back-to-back → ~K/2x).  Anchoring each entry at
    ``max(own dispatch, previous completion)`` partitions the busy lane:
    the sums are route shares of one serialized device timeline, directly
    comparable to the wall-clock host lanes the doctor weighs them
    against."""
    import time as _time

    prev_done = 0.0
    while True:
        item = q.get()
        if item is None:
            return
        try:
            kind, route, family, arrays, t0, b_in, b_staged, passes = item
            try:
                jax.block_until_ready(arrays)
            except Exception:  # noqa: BLE001 — a failed dispatch
                continue       # reports through the consumer
            t1 = _time.perf_counter()
            start = max(t0, prev_done)
            prev_done = t1
            dt = max(t1 - start, 0.0)
            if kind == "h2d":
                stats.note_h2d(dt, b_staged)
                name = "device.h2d"
            else:
                stats.note_dispatch(route, family, dt, b_in, b_staged,
                                    passes)
                name = f"device.{route}"
            if tracer is not None and tracer.active:
                tracer.complete(name, start, t1, kernel=family,
                                bytes=int(b_staged or b_in))
        finally:
            q.task_done()


# ---------------------------------------------------------------------------
# aligned device profiles (TPQ_XPROF): one bounded-window jax.profiler
# capture per process whose TraceAnnotations carry the SAME names as the
# span tracer's stages, so the host Perfetto artifact and the XLA device
# timeline line up one-to-one
# ---------------------------------------------------------------------------

_XPROF_LOCK = threading.Lock()
_XPROF_DONE = False      # one capture per process: xprof dirs are heavy
_XPROF_ACTIVE = False    # cheap hot-path gate for TraceAnnotations


def _xprof_active() -> bool:
    return _XPROF_ACTIVE


def _xprof_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` matching a span-tracer stage name
    while an xprof window is capturing; a no-op context otherwise (the
    annotation objects are only built inside a live capture)."""
    if not _XPROF_ACTIVE:
        return _noop_ctx()
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — profiling never takes the run down
        return _noop_ctx()


class _XprofWindow:
    """Bounded-window device profile capture (``TPQ_XPROF=<dir>``).

    Starts a ``jax.profiler`` trace at scan start and stops it after
    ``TPQ_XPROF_S`` seconds (default 10; checked at row-group granularity)
    or at scan end, whichever comes first — an unbounded xprof over a 1B-row
    scan is gigabytes, a window is what the alignment needs.  One capture
    per process; every later scan is a no-op.  All profiler calls are
    guarded: a backend without profiler support degrades silently.
    """

    def __init__(self):
        from .obs import env_float

        self.dir = os.environ.get("TPQ_XPROF", "")
        self.window_s = env_float("TPQ_XPROF_S", 10.0, lo=0.1)
        self._t0 = None
        self._started = False

    def start(self) -> None:
        global _XPROF_DONE, _XPROF_ACTIVE
        if not self.dir:
            return
        with _XPROF_LOCK:
            if _XPROF_DONE:
                return
            _XPROF_DONE = True
            try:
                import time as _time

                jax.profiler.start_trace(self.dir)
                self._t0 = _time.perf_counter()
                self._started = True
                _XPROF_ACTIVE = True
            except Exception:  # noqa: BLE001
                self._started = False

    def tick(self) -> None:
        """Row-group boundary check: close the window once it has run
        ``window_s`` (the profiler flushes its own buffers on stop)."""
        import time as _time

        if self._started and _time.perf_counter() - self._t0 >= self.window_s:
            self.stop()

    def stop(self) -> None:
        global _XPROF_ACTIVE
        if not self._started:
            return
        self._started = False
        with _XPROF_LOCK:
            _XPROF_ACTIVE = False
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass


class DeviceFileReader:
    """Columnar file reader decoding straight to device arrays.

    The device twin of reader.FileReader: same options (projection, CRC), row
    groups as the work unit, nothing blocks until ``finalize()`` (called by
    ``read_row_group``; pass ``finalize=False`` to pipeline several row groups
    and call it once).

    With ``row_filter`` set, pruning is two-level: row groups whose chunk
    stats prove no match are skipped whole (prune_row_groups), and within
    surviving FLAT row groups, page-header Statistics drop maximal
    provably-false row runs aligned to whole-page boundaries of every
    selected column (prune_pages — skipped pages are never decompressed,
    staged, or decoded; ReaderStats.pages_pruned counts them).  Yielded rows
    are always a SUPERSET of matching rows, identical across columns;
    columns with differing page grids share no interior edges, in which
    case the pruner soundly declines rather than misalign.

    Zero-decode-work policy: a PLAIN fixed-width chunk has no device compute
    — decoding it here is a pure host→HBM transfer, so against a host decode
    + async upload pipeline the information-theoretic ceiling on a
    transfer-bound link is ~1× (both paths move the same bytes; encoded
    columns — dict/RLE/delta — are where the device path wins by shipping
    FEWER bytes and expanding on device).  ``iter_row_groups`` reaches that
    ceiling by streaming staged strips during host decompress (see
    _RowGroupStager) rather than serializing parse→transfer; callers that
    want host-resident arrays for such columns should read them with the
    host FileReader (project them out of the device reader) and skip the
    transfer entirely.
    """

    def __init__(self, source, columns=None, validate_crc=None,
                 profile_dir: "str | None" = None, max_memory: int = 0,
                 row_filter=None, prefetch: int = 0, trace=None,
                 sample_ms=None, hang_s=None, hang_policy=None,
                 store=None, on_data_error=None, quarantine=None,
                 metadata=None, plan=None, dict_cache=None,
                 result_cache=None, cancel=None):
        from .obs import (Sampler, Watchdog, register_flight_registry,
                          resolve_hang_s, resolve_sample_ms, resolve_tracer)
        from .pipeline import PipelineStats
        from .quarantine import resolve_validate
        from .reader import FileReader

        _enable_compile_cache()

        # span tracer (obs.py): None = the TPQ_TRACE process tracer (a
        # disabled no-op without the env); a path = per-reader tracer whose
        # trace file (+ embedded registry) is written at close()
        self._tracer, self._owns_tracer = resolve_tracer(trace)
        validate_crc = resolve_validate(validate_crc)
        self._host = FileReader(source, columns=columns,
                                validate_crc=validate_crc,
                                max_memory=max_memory,
                                row_filter=row_filter,
                                trace=self._tracer, store=store,
                                on_data_error=on_data_error,
                                quarantine=quarantine,
                                metadata=metadata, plan=plan,
                                dict_cache=dict_cache, cancel=cancel)
        # the plan IR (scanplan.py): the footer slice + pruning verdicts +
        # ship-route memo this scan consumes.  A caller-supplied plan (the
        # serve.ScanService cache) is REPLAYED — group pruning is adopted
        # from it (via the host reader), page-pruning header walks are
        # skipped where memoized, and preship starts from the memoized
        # route.  Without one, the reader builds its own, so plan
        # construction always lives in scanplan.py.
        self._plan = self._host._plan
        # decoded-dictionary read-through cache (serve.BoundDictCache duck
        # type: get(rg, column, kind) / put(rg, column, kind, value, nbytes))
        self._dict_cache = dict_cache
        # decoded device-result cache (serve.BoundResultCache bound to the
        # DEVICE decode signature — deliberately NOT forwarded to the host
        # FileReader above: host ColumnData and device arrays are
        # different decode shapes and must never share entries).  An
        # adapter whose signature doesn't match THIS reader's shape, CRC
        # tier, or predicate fingerprint is dropped, not adopted — a
        # validate_crc=True request must never adopt an unvalidated
        # decode, and page-pruned output is only shared under the exact
        # same fingerprint.  A row group whose every selected column is
        # cached skips IO, staging, and every device kernel; misses
        # populate at finalize — the one point that proves the deferred
        # validity checks passed.
        if result_cache is not None:
            from .scanplan import predicate_fingerprint

            sig = getattr(result_cache, "sig", None) or ()
            want = ("dev", "v1" if validate_crc else "v0",
                    predicate_fingerprint(self._host.row_filter))
            if tuple(sig[:3]) != want:
                result_cache = None
        self._result_cache = result_cache
        # rc-pending ledger: id(out dict) -> [rg index, out, dispatched,
        # nbytes]; flushed to the cache by _flush_result_cache (via
        # _finalize_many).  BOUNDED by the cache tier's capacity: a
        # deferred-finalize multi-file scan must not pin every group's
        # decoded output until the end — beyond the bound the OLDEST
        # pending group is simply dropped (a forgone cache fill, never a
        # correctness or memory cost).
        self._rc_pending: dict = {}
        self._rc_pending_bytes = 0
        # data-error containment engine, SHARED with the host half so the
        # budget and quarantine ledger span both decode paths
        self.quarantine = self._host.quarantine
        # the IO backend all chunk bytes enter through (iostore.py) —
        # shared with the host reader so both paths see one retry budget
        self._store = self._host._store
        # chunk-granular host prefetch depth (IO + CRC + decompress + parse
        # of upcoming chunks on a bounded pool, spanning row-group
        # boundaries); 0 = the sequential host phase
        self._prefetch = int(prefetch)
        self._pipe_stats = PipelineStats(prefetch=self._prefetch,
                                         budget_bytes=int(max_memory),
                                         tracer=self._tracer)
        self.metadata = self._host.metadata
        self.schema = self._host.schema
        self.validate_crc = validate_crc
        self.profile_dir = profile_dir  # JAX profiler trace dir (SURVEY §5.1)
        # HBM/host staging budget (SURVEY §5.3): ONE tracker shared with the
        # host FileReader, registered against each page's REAL decompressed
        # size (chunk-level metadata totals are attacker-controlled), so a
        # decompression bomb raises instead of exhausting memory
        self.alloc = self._host.alloc
        self._deferred: list = []
        self._stats = ReaderStats()
        self._stats_lock = __import__("threading").Lock()
        self._t0: float | None = None
        # per-route device completion timing (TPQ_DEVICE_TIMING, default
        # on): one lazy daemon worker times each staged dispatch to
        # block_until_ready, keyed by ship route and kernel family
        self._device_stats = DeviceStats()
        self._device_timer = _DeviceTimer(self._device_stats, self._tracer)
        # HBM residency ledger: staged buffers register at staging
        # (`_device_staged_pending`), move to `_device_outstanding` at
        # dispatch, and release at finalize — the one point that proves
        # every kernel reading the DISPATCHED buffers has completed (the
        # pipelined path stages group N before group N-1 finalizes, so a
        # single counter would release N's live buffer early)
        self._device_staged_pending = 0
        self._device_outstanding = 0
        # bounded-window aligned device profile (TPQ_XPROF)
        self._xprof = _XprofWindow()
        # link-byte ship planner (ship.py): per-reader so env overrides
        # (TPQ_FORCE_ROUTE, TPQ_LINK_MBPS) bind at open time
        self._ship_planner = ShipPlanner()
        self._stats.planner_link_mbps = self._ship_planner.link_mbps
        # live counter sampler (obs.Sampler, TPQ_SAMPLE_MS / sample_ms=):
        # throughput + backpressure curves on the trace; inert (no thread)
        # unless the tracer is enabled AND an interval is set
        # track_id ties each reader's curves to its pipeline's `pipe=` wall
        # counter — scan_files opens several readers on ONE shared tracer,
        # and same-named id-less tracks would interleave into one sawtooth
        self._sampler = Sampler(self._tracer, resolve_sample_ms(sample_ms),
                                track_id=self._pipe_stats._obs_id)
        # the chunk feed's in-flight budget, once a scan creates one — the
        # sampler's budget_waiters track and the watchdog's abort hook both
        # late-bind through it (_chunk_feed sets it)
        self._live_budget = None
        if self._sampler.enabled:
            self._sampler.add_source("reader_progress", self._sample_progress)
            # late-bound like the watchdog lanes below: iter_row_groups
            # replaces _pipe_stats per scan and the sampled track must
            # follow the live object, not the constructor-time one
            self._sampler.add_source("pipeline_lanes",
                                     lambda: self._pipe_stats.sample())
            self._sampler.add_source("alloc_bytes", self._sample_alloc)
            self._sampler.add_source("budget_waiters", self._sample_budget)
            if self._store.stats is not None:
                # retry/backoff curves next to the lanes they stall
                self._sampler.add_source("io_retries",
                                         self._store.stats.progress)
            # quarantined-unit accounting as a live curve: a corruption
            # burst is visible next to the lane it degraded
            self._sampler.add_source("data_errors", self.quarantine.progress)
            if self._result_cache is not None:
                # result-cache hit/miss/eviction flows as a live curve
                # next to the decode lanes they spare
                self._sampler.add_source("result_cache",
                                         self._result_cache.cache.progress)
            if self._device_timer.enabled:
                # the device lane as a curve (slope = live device
                # throughput); on hosts where the timing lane dropped
                # (no backend) the track simply never registers
                self._sampler.add_source("device",
                                         self._device_stats.progress)
            self._sampler.start()
        # hang watchdog (obs.Watchdog, TPQ_HANG_S / hang_s=): fires a
        # flight dump (and, policy "raise", aborts the chunk feed's budget
        # so the submitter raises HangError) when no lane below advances.
        # Lambdas late-bind self._pipe_stats: iter_row_groups replaces it
        # per scan and the heartbeats must follow the live object.
        self._watchdog = Watchdog(resolve_hang_s(hang_s), policy=hang_policy)
        if self._watchdog.enabled:
            self._watchdog.watch("pipeline",
                                 lambda: self._pipe_stats.sample())
            self._watchdog.watch("reader", self._sample_progress)
            if self._store.stats is not None:
                # store heartbeat: the counters FREEZE while a fetch is
                # stalled (a retrying store keeps advancing) — so a
                # network stall fires the dog and the flight dump names
                # the in-flight range (pq_tool autopsy: network-stall)
                self._watchdog.watch("iostore", self._store.stats.progress)
            if getattr(self._store, "supports_async", False):
                # async-routed stores get an engine heartbeat lane too:
                # submissions/completions freeze when every in-flight
                # fetch is stuck on the loop (the dog still only fires
                # when ALL lanes freeze)
                from .iostore_async import engine_for_store

                eng = engine_for_store(self._store)
                if eng is not None:
                    self._watchdog.watch("fetch_engine", eng.stats.progress)
            # raise-policy exit from a stalled fetch: poisoning the store
            # wakes the worker pinned inside the transport, so the HangError
            # (not a belated transport error) reaches the consumer
            self._watchdog.add_abort_hook(self._store.abort)
            # idle consumer gate until the first scan replaces it: both
            # counter lanes above are frozen at 0 while the reader sits
            # un-iterated, and a reader built long before its first
            # iter_row_groups must not read as a hang
            self._watchdog.watch_consumer()
            self._watchdog.start()
        # a wedged process's dump should embed the same registry tree a
        # clean close would have written (weakly held — see obs)
        register_flight_registry(self, "obs_registry")

    def _sample_progress(self) -> dict:
        st = self._stats
        return {"rows": st.rows, "chunks": st.chunks,
                "staged_bytes": st.staged_bytes,
                "compressed_bytes": st.compressed_bytes}

    def _sample_alloc(self) -> dict:
        in_use, peak = self.alloc.snapshot()
        dev_in_use, dev_peak = self.alloc.device_snapshot()
        return {"in_use": in_use, "peak": peak,
                "device_in_use": dev_in_use, "device_peak": dev_peak}

    def _sample_budget(self) -> dict:
        b = self._live_budget
        return b.snapshot() if b is not None else {}

    def close(self):
        self._watchdog.stop()  # before the sampler: no dump mid-teardown
        # before the sampler's final tick and the trace write: every
        # in-flight dispatch must land in the device section first
        self._device_timer.stop()
        self._xprof.stop()
        # deferred-finalize scans (scan_files) release residency here
        self._release_device_outstanding(all_bytes=True)
        self._sampler.stop()  # before the write: the final tick must land
        self._host.close()
        if self._owns_tracer:
            self._tracer.write(registry=self.obs_registry())
            self._owns_tracer = False  # idempotent: scan_files double-closes

    def obs_registry(self):
        """This reader's unified metrics tree (obs.StatsRegistry): decode
        counters + per-route ship decisions with the planner's predictions,
        the pipeline's per-stage histograms, and the alloc high-water mark."""
        from .obs import StatsRegistry

        reg = StatsRegistry()
        reg.add_reader(self._stats)
        reg.add_pipeline(self._pipe_stats)
        reg.note_alloc_peak(self.alloc)
        if self._device_timer.enabled:
            # the versioned `device` section (golden-keyed like io/
            # data_errors); absent entirely when the timing lane dropped,
            # so consumers see "n/a", never zeros masquerading as
            # measures.  Drain first: a mid-session read must not miss
            # dispatches still queued behind the completion worker.
            self._device_timer.drain()
            reg.add_device(self._device_stats)
        if self._store.stats is not None:
            reg.add_io(self._store.stats)
        if len(self.quarantine.log) or self.quarantine.units_skipped:
            reg.add_data_errors(self.quarantine)
        return reg

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def num_row_groups(self) -> int:
        return self._host.num_row_groups

    @staticmethod
    def _walk_headers_file(f, offset: int, size: int, num_values: int):
        """Page headers of a chunk read via seeks (moved to
        scanplan.walk_header_pages — kept as a delegate for callers/tests
        addressing the reader)."""
        from .scanplan import walk_header_pages

        return walk_header_pages(f, offset, size, num_values)

    def _plan_page_pruning(self, rg, leaves, f=None, index=None):
        """Page-level predicate pushdown planning, via the plan IR
        (scanplan.plan_page_pruning) with a per-row-group memo: a replayed
        ScanPlan (serve's PlanCache, or a second scan over one reader)
        skips the header walks entirely and adopts the recorded skip sets.
        The memoized replay returns no filter-chunk buffers — the decode
        loop then reads those chunks itself, exactly as without a filter.
        """
        pred = self._host.row_filter
        if pred is None:
            return None, 0, {}
        from . import scanplan as _sp

        plan = self._plan
        memo_ok = (plan is not None and index is not None
                   and plan.filter_fp is not None
                   and plan.filter_fp == _sp.predicate_fingerprint(pred))
        if memo_ok:
            hint = plan.pruning_hint(index)
            if hint is not None:
                skip, rows_dropped = hint
                return skip, rows_dropped, {}
        if f is None:  # the chunk feed passes a thread-safe pread view
            f = self._host._sr.as_file()  # store-backed, like every read
        skip, rows_dropped, bufs = _sp.plan_page_pruning(
            rg, leaves, self.schema, pred, f)
        if memo_ok:
            plan.note_pruning(index, skip, rows_dropped)
        return skip, rows_dropped, bufs

    @scoped_x64
    def _prepare_row_group(self, index: int, executor=None, collected=None):
        """Host phase: decompress + parse every chunk of the row group,
        registering all byte regions with ONE stager.

        With ``executor`` (the iter_row_groups staging worker) the stager
        streams completed 16 MiB strips to the device while this thread is
        still decompressing later chunks — see _RowGroupStager.

        With ``collected`` (the chunk feed's output — IO + CRC + decompress
        + structure parse already done on the prefetch pool, possibly while
        an EARLIER row group was dispatching) the host phase here collapses
        to stager registration and plan construction.

        No device calls on the common paths (plain/bool/bytes/dict/delta);
        the _finish_host fallback (mixed encodings, FLBA, INT96, delta byte
        arrays) still stages per chunk eagerly here and is therefore NOT
        overlapped by the iter_row_groups pipeline.
        """
        rg = self.metadata.row_groups[index]
        if self._result_cache is not None:
            fed_cached = collected is not None and collected.get("cached")
            if fed_cached or collected is None:
                hit = self._cached_group(index)
                if hit is not None:
                    # warm group: no IO, no staging, no device dispatch —
                    # _dispatch_row_group sees zero plans and passes
                    # straight through
                    return hit, [], None
                if fed_cached:
                    # evicted between the feed's probe and here: decode
                    # fresh on the sequential path (the feed read nothing)
                    collected = None
        import time as _time

        t0 = _time.perf_counter()
        if self._t0 is None:
            self._t0 = t0
        leaves = {l.path: l for l in self.schema.selected_leaves()}
        out: dict[str, DeviceColumnData] = {}
        # store-backed view: the sequential path's bytes enter through the
        # same fault-tolerant backend as the prefetch pool's
        f = self._host._sr.as_file()
        self.alloc.reset()
        if collected is None:
            skip_pages, rows_dropped, planned_bufs = self._plan_page_pruning(
                rg, leaves, index=index)
        else:
            skip_pages, planned_bufs = None, {}
            rows_dropped = collected["rows_dropped"]
        stager = _RowGroupStager(executor)
        plans: list[tuple[str, object]] = []
        for path, leaf, chunk, md, offset in row_group_chunks(rg, leaves):
            if collected is not None:
                entry = collected["chunks"].get(path)
                if entry is None:
                    # selection changed between feed and prepare (both run
                    # in the consumer thread, so this is a caller bug)
                    raise ParquetError(
                        f"prefetched row group {index} missing chunk "
                        f"{'.'.join(path)}"
                    )
                md, asm = entry
                if isinstance(asm, _FailedChunk):
                    # a quarantined chunk from the prefetch feed: re-raise
                    # its (already annotated + recorded) error here so the
                    # consumer-side containment in _scan_pipeline handles
                    # the sequential and pipelined paths identically
                    raise asm.exc
                self._stats.chunks += 1
                self._stats.compressed_bytes += md.total_compressed_size
                self.alloc.register(md.total_compressed_size)
            else:
                ctx = {"file": self._host._source_name, "row_group": index,
                       "column": ".".join(path), "chunk_offset": offset}
                buf = planned_bufs.get(path)
                if buf is None:
                    f.seek(offset)
                    buf = f.read(md.total_compressed_size)
                require_full(buf, offset, md.total_compressed_size,
                             context=f"column {'.'.join(path)}")
                self._stats.chunks += 1
                self._stats.compressed_bytes += md.total_compressed_size
                self.alloc.register(md.total_compressed_size)
                asm = _collect_chunk(
                    buf, md.codec, md.num_values, leaf, self._deferred,
                    validate_crc=self.validate_crc, alloc=self.alloc,
                    statistics=md.statistics,
                    skip_pages=(skip_pages or {}).get(path),
                    context=ctx, dict_cache=self._dict_cache,
                )
                if asm is not None:
                    # replay the plan IR's memoized route (scanplan.py):
                    # preship starts from the recorded choice instead of
                    # re-ranking — and, on a plain memo, skips the failed
                    # narrow/recompress probes a first pass already paid
                    asm.preship(self._ship_planner, self._pipe_stats,
                                route_hint=(
                                    self._plan.route_hint(index,
                                                          ".".join(path))
                                    if self._plan is not None else None))
            if asm is not None:
                self._stats.pages += len(asm.pages)
                self._stats.pages_pruned += asm.pages_pruned
            name = ".".join(path)
            if asm is None or not asm.pages:
                # empty chunk OR fully pruned: placeholder column (still
                # count the pruned pages — a fully-pruned chunk is the
                # pushdown's best case, not a zero)
                out[name] = DeviceColumnData(
                    values=jnp.asarray(np.zeros(0, dtype=np.int64)),
                    max_def=leaf.max_def, max_rep=leaf.max_rep,
                    num_leaf_slots=0,
                )
                continue
            plan = asm.finish(stager)
            plans.append((name, plan))
            self._stats.pages_device_expanded += asm.pages_kept_compressed
            tr = self._pipe_stats.tracer
            self._stats.fused_fallbacks += asm.fused_fallbacks
            logical_sum = shipped_sum = 0
            best_route, best_bytes = None, -1
            for (route, logical, shipped, predicted, predicted_dev,
                 predicted_unfused_dev) in asm.ship_records:
                self._stats.count_route(route, logical, shipped, predicted,
                                        predicted_dev,
                                        predicted_unfused_dev)
                logical_sum += logical
                shipped_sum += shipped
                if shipped > best_bytes:
                    best_route, best_bytes = route, shipped
                if tr is not None and tr.active:
                    # one instant per shipped stream: pq_tool trace folds
                    # these into the per-route predicted-vs-measured table
                    tr.instant("ship", route=route, column=name,
                               logical=logical, shipped=shipped,
                               predicted_s=round(predicted, 9),
                               predicted_device_s=round(predicted_dev, 9))
            # device-timing attribution: the column's dispatch is timed
            # under its dominant (most-shipped-bytes) ship route
            plan.route = best_route or ROUTE_PLAIN
            plan.bytes_in = logical_sum
            plan.bytes_staged = shipped_sum
            if self._plan is not None:
                # memoize the decision into the plan IR: a replay (this
                # reader's next scan, or the serve cache's next request
                # over the same plan) starts preship from it
                self._plan.note_route(index, name, plan.route,
                                      _kernel_family(plan.key))
        # every selected leaf must have a chunk in the row group (host
        # FileReader parity — reader.py read_row_group's missing check)
        seen = set(out) | {name for name, _ in plans}
        missing = {".".join(p) for p in leaves} - seen
        if missing:
            raise ParquetError(
                f"row group {index} missing columns {sorted(missing)}"
            )
        self._stats.row_groups += 1
        self._stats.rows += (rg.num_rows or 0) - rows_dropped
        self._stats.staged_bytes += stager.total
        now = _time.perf_counter()
        self._stats.host_seconds += now - t0
        self._stats.wall_seconds = now - self._t0
        tr = self._pipe_stats.tracer
        if tr is not None and tr.active:
            tr.complete("prepare", t0, now, rg=index, bytes=stager.total)
        if self._result_cache is not None:
            # miss path: remember this group's output dict (dispatch fills
            # it in place); _flush_result_cache publishes it only after
            # finalize proves the deferred checks passed AND the group was
            # actually dispatched (a prepared-but-never-dispatched dict
            # still holds placeholders, not results)
            self._rc_pending[id(out)] = [index, out, False, 0]
        return out, plans, stager

    def _cached_group(self, index: int) -> "dict | None":
        """All-or-nothing decoded-result probe for row group ``index``:
        every selected column cached under this reader's decode signature,
        or None.  A hit counts into the reader's row/group accounting
        (rows from the widest column's leaf-slot count — accounting only)
        so throughput math keeps describing what was SERVED."""
        rc = self._result_cache
        names = [".".join(l.path) for l in self.schema.selected_leaves()]
        if not names:
            return None
        got = rc.lookup_group(index, names)
        if got is None:
            return None
        import time as _time

        now = _time.perf_counter()
        if self._t0 is None:
            self._t0 = now
        self._stats.row_groups += 1
        self._stats.rows += max(
            (int(getattr(c, "num_leaf_slots", 0) or 0) for c in got.values()),
            default=0)
        self._stats.wall_seconds = now - self._t0
        tr = self._pipe_stats.tracer
        if tr is not None and tr.active:
            tr.instant("result_cache_hit", rg=index, columns=len(got))
        return dict(got)

    def _flush_result_cache(self) -> None:
        """Publish dispatched groups' decoded columns to the result cache.
        Called after the deferred validity checks pass (finalize /
        _finalize_many) — never before: a value that would fail
        finalization must never be servable."""
        rc = self._result_cache
        if rc is None or not self._rc_pending:
            return
        pending, self._rc_pending = self._rc_pending, {}
        self._rc_pending_bytes = 0
        from .serve.result_cache import device_column_nbytes

        for index, out, dispatched, _nbytes in pending.values():
            if not dispatched:
                continue
            for name, col in out.items():
                rc.put(index, name, col, device_column_nbytes(col))

    def _note_staged(self, stager, buf_dev, t0: float) -> None:
        """One staged row-group buffer just shipped: account its HBM
        residency and hand it to the completion timer as an ``h2d``
        transfer.  ``t0`` must be the POST-stage timestamp — ``stage()``
        is host-blocking, so an interval anchored before it would contain
        the whole host staging wall and the ``h2d`` lane would
        structurally dominate the link lane it is meant to sit next to.
        The bytes land in ``_device_staged_pending`` (not yet dispatched)
        and move to ``_device_outstanding`` at dispatch — finalize proves
        completion only for DISPATCHED groups, and the pipelined path
        stages group N before group N-1 finalizes."""
        n = int(stager.total)
        if n:
            self.alloc.register_device(n)
            with self._stats_lock:
                self._device_staged_pending += n
        self._device_timer.submit("h2d", "h2d", "h2d", buf_dev, t0,
                                  bytes_staged=n)

    def _note_dispatched(self, stager) -> None:
        """The group's staged bytes are now consumed by in-flight kernels:
        eligible for release at the next finalize."""
        n = int(stager.total)
        if n:
            with self._stats_lock:
                self._device_staged_pending -= n
                self._device_outstanding += n

    def _release_device_outstanding(self, all_bytes: bool = False) -> None:
        """Release the HBM ledger for groups whose kernels finalize just
        proved complete; ``all_bytes`` (close) also drops still-pending
        staged buffers — the scan is over either way."""
        with self._stats_lock:
            n, self._device_outstanding = self._device_outstanding, 0
            if all_bytes:
                n += self._device_staged_pending
                self._device_staged_pending = 0
        if n:
            self.alloc.release_device(n)

    @scoped_x64
    def _dispatch_row_group(self, prepared, buf_dev=None):
        import time as _time

        out, plans, stager = prepared
        # the request trace rides the reader's cancel token (the serve tier
        # sets it); the device pass is one span per dispatched group
        _cancel = getattr(self._host, "_cancel", None)
        _rtrace = getattr(_cancel, "trace", None) if _cancel is not None \
            else None
        if _rtrace is None:
            from .obs import current_request_trace

            _rtrace = current_request_trace()
        if plans:
            _tr0 = _time.perf_counter() if _rtrace is not None else 0.0
            if buf_dev is None:
                t0 = _time.perf_counter()
                with self._pipe_stats.timed("stage", bytes=stager.total), \
                        _xprof_annotation("stage"):
                    buf_dev = stager.stage()
                t_staged = _time.perf_counter()
                with self._stats_lock:
                    self._stats.stage_seconds += t_staged - t0
                self._note_staged(stager, buf_dev, t_staged)
            t1 = _time.perf_counter()
            with self._pipe_stats.timed("dispatch"), \
                    _xprof_annotation("dispatch"):
                out.update(_run_plans(plans, buf_dev, self._device_timer))
            with self._stats_lock:
                self._stats.dispatch_seconds += _time.perf_counter() - t1
            self._note_dispatched(stager)
            if _rtrace is not None:
                _rtrace.add_timed("device", _tr0, _time.perf_counter(),
                                  plans=len(plans),
                                  staged_bytes=int(stager.total))
        if self._result_cache is not None:
            ent = self._rc_pending.get(id(out))
            if ent is not None:
                # the group's columns are now real decoded results (or it
                # had no device work at all) — eligible to publish once
                # finalize proves the deferred checks.  Pending residency
                # is bounded by the tier's capacity: past it the oldest
                # pending group is dropped unpublished, so a streaming
                # consumer's memory profile stays within cache-budget of
                # the cache-off scan even when finalize is deferred to
                # the end of a multi-file sweep.
                from .serve.result_cache import device_column_nbytes

                ent[2] = True
                ent[3] = sum(device_column_nbytes(c) for c in out.values())
                self._rc_pending_bytes += ent[3]
                # 2x the tier capacity: bounded pinning, while the flush
                # can still OVERFILL the tier enough to exercise eviction
                # (a bound at exactly the capacity would starve it)
                cap = 2 * self._result_cache.cache.tier_capacity(
                    self._result_cache.tier)
                while (self._rc_pending_bytes > cap
                       and len(self._rc_pending) > 1):
                    oldest = next(iter(self._rc_pending))
                    if oldest == id(out):
                        break
                    dropped = self._rc_pending.pop(oldest)
                    self._rc_pending_bytes -= dropped[3]
        now = _time.perf_counter()
        if self._t0 is not None:
            self._stats.wall_seconds = now - self._t0
        self._pipe_stats.count_row_group()
        self._pipe_stats.touch_wall()
        return out

    def stats(self) -> ReaderStats:
        """Decode counters so far (rows/s, bytes/s, pages/chunk, HBM staged)."""
        return self._stats

    def pipeline_stats(self):
        """Per-stage pipeline timing (io / decompress / stage / dispatch /
        finalize) plus stall time and the in-flight high-water mark — see
        pipeline.PipelineStats.  The io/decompress stages are only populated
        when ``prefetch`` > 0 routed the host phase through the chunk pool;
        stage/dispatch/finalize accumulate on every path."""
        return self._pipe_stats

    @scoped_x64
    def read_row_group(self, index: int, finalize: bool = True):
        collected = None
        if self._prefetch > 0:
            feed = _chunk_feed(iter([(self, None, index)]), self._prefetch,
                               self.alloc.max_size,
                               cancel=self._host._cancel)
            try:
                _r, _p, _i, collected = next(feed)
            finally:
                feed.close()
        out = self._dispatch_row_group(
            self._prepare_row_group(index, collected=collected))
        if finalize:
            self.finalize()
        return out

    @scoped_x64
    def finalize(self) -> None:
        """Run deferred validity checks (one device sync for all chunks).
        The sync also proves every kernel reading the staged buffers has
        completed, so the HBM residency ledger releases them here."""
        with self._pipe_stats.timed("finalize"), \
                _xprof_annotation("finalize"):
            _finalize_many([self])
        self._release_device_outstanding()
        self._pipe_stats.touch_wall()

    def iter_batches(self, batch_size: int, columns=None):
        """Yield fixed-size device batches {column: jax.Array[batch_size, ...]}.

        The training-pipeline view: every yielded batch has the SAME static
        shape, so a consuming jitted step compiles once.  Rows flow across row
        group boundaries through fixed-capacity device buffers (power-of-two
        capacity, rows appended with dynamic_update_slice, batches cut with
        dynamic_slice at traced offsets), so the executable set is bounded by
        {capacity} x {row-group size} x {batch_size} — no per-remainder
        recompiles.  The final short remainder is NOT yielded (classic
        drop_remainder semantics; the row count is known from the footer).

        Fixed-width, null-free, non-repeated columns only: ragged byte arrays
        have no static row shape.  Dictionary columns are materialized to
        values on device.
        """
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        want = None if columns is None else set(columns)
        bufs: dict[str, jax.Array] = {}
        cap = 0
        start = end = 0  # valid rows [start, end), shared by all columns
        first = True
        for cols in self.iter_row_groups():
            ready: list[dict] = []
            # trace everything under a scoped x64 context; yields happen
            # outside it so the consumer's dtype semantics are untouched
            # (a decorator on a generator would only scope its construction)
            with K.enable_x64():
                arrays = {}
                for name, col in cols.items():
                    if want is not None and name not in want:
                        continue
                    if isinstance(col, DeviceDictColumn):
                        col = col.materialize()
                    if col.values is None:
                        raise TypeError(
                            f"iter_batches needs fixed-width columns; "
                            f"{name!r} is ragged (offsets/heap)"
                        )
                    if col.max_rep > 0:
                        raise TypeError(
                            f"iter_batches needs flat columns; {name!r} is "
                            f"repeated"
                        )
                    if col.num_values != col.num_leaf_slots:
                        raise TypeError(
                            f"iter_batches needs null-free columns; {name!r} "
                            f"has "
                            f"{col.num_leaf_slots - col.num_values} "
                            f"nulls"
                        )
                    arrays[name] = (col.values, col.num_values)
                if want is not None:
                    missing = want - set(arrays)
                    if missing:
                        raise KeyError(
                            f"iter_batches: no such column(s) {sorted(missing)}"
                        )
                if not arrays:
                    continue
                ns = {n for _, n in arrays.values()}
                if len(ns) != 1:
                    raise ParquetError(
                        f"iter_batches: column row counts differ: {sorted(ns)}"
                    )
                n_new = ns.pop()
                if n_new == 0:
                    continue  # zero-row group: placeholder columns, skip
                # arrays may be bucket-padded past n_new; appends write the
                # full padded rows (tail garbage lands past `end`, where the
                # next append or the drop_remainder tail covers it), so all
                # capacity math uses the padded length
                pad_len = max(int(v.shape[0]) for v, _ in arrays.values())
                if first:
                    cap = _bucket(pad_len + batch_size)
                    bufs = {k: _fit_rows_jit(v, size=cap)
                            for k, (v, _) in arrays.items()}
                    start, end = 0, n_new
                    first = False
                else:
                    if end + pad_len > cap and start:  # compact [start, end) to 0
                        bufs = {k: _roll_rows_jit(v, np.int64(-start))
                                for k, v in bufs.items()}
                        end -= start
                        start = 0
                    if end + pad_len > cap:  # still short: grow capacity
                        cap = _bucket(end + pad_len + batch_size)
                        bufs = {k: _fit_rows_jit(v, size=cap)
                                for k, v in bufs.items()}
                    bufs = {
                        k: _update_rows_jit(bufs[k], v, np.int64(end))
                        for k, (v, _) in arrays.items()
                    }
                    end += n_new
                # the carry is device memory held across row groups: count it
                # against this row group's budget window (alloc resets per
                # group in _prepare_row_group)
                self.alloc.register(
                    sum(int(np.prod(v.shape)) * v.dtype.itemsize
                        for v in bufs.values())
                )
                while end - start >= batch_size:
                    ready.append({
                        k: _dynslice_jit(v, np.int64(start), size=batch_size)
                        for k, v in bufs.items()
                    })
                    start += batch_size
            yield from ready

    def iter_row_groups(self, finalize_each: bool = False):
        """Iterate row groups with a one-deep transfer pipeline.

        Staging (host→device transfer) of row group N runs on a worker thread
        while the main thread decompresses and parses row group N+1 — the
        tunneled backend serializes transfers with its queue, so overlapping
        them with host work is the difference between sum and max of the two
        phases.  The stager buffers are plain uint8, so the worker thread
        needs no x64 scope.
        """
        from concurrent.futures import ThreadPoolExecutor
        import contextlib

        from .pipeline import PipelineStats

        # fresh counters per scan: the wall clock anchors at the scan's
        # first touch, so overlap_efficiency never absorbs idle time
        # between two scans on one reader (pipeline_stats() reports the
        # current/most recent scan)
        self._pipe_stats = PipelineStats(prefetch=self._prefetch,
                                         budget_bytes=self.alloc.max_size,
                                         tracer=self._tracer)
        # fresh per-scan retry budget / coalescing state / abort poison on
        # BOTH paths (the prefetch feed also calls this — idempotent at
        # scan start; the prefetch=0 path has no other reset point), with
        # the request's deadline/cancel riding the scan token
        self._host._sr.set_scan(
            self._store.begin_scan(cancel=self._host._cancel))
        indices = [i for i in range(self.num_row_groups)
                   if self._host.row_group_selected(i)]
        self.quarantine.begin_scan(len(indices))
        if not indices:
            self.finalize()
            return
        trace = (jax.profiler.trace(self.profile_dir) if self.profile_dir
                 else contextlib.nullcontext())
        # aligned device profile (TPQ_XPROF): a bounded window of the XLA
        # timeline whose TraceAnnotations match the span tracer's stage
        # names; profile_dir (the explicit kwarg) takes precedence — the
        # two capture APIs must not nest
        xprof = None if self.profile_dir else self._xprof
        if xprof is not None:
            xprof.start()
        try:
            with trace, ThreadPoolExecutor(1) as ex:
                for _, out in _scan_pipeline(
                    ((self, None, i) for i in indices), ex,
                    finalize_each=finalize_each,
                    prefetch=self._prefetch,
                    budget_bytes=self.alloc.max_size,
                    watchdog=self._watchdog,
                    quarantine=self.quarantine,
                    cancel=self._host._cancel,
                ):
                    yield out
                    if xprof is not None:
                        xprof.tick()
        finally:
            if xprof is not None:
                xprof.stop()


def _finalize_many(readers) -> None:
    """Run every reader's deferred validity checks with ONE device sync.

    The tunneled backend charges ~100ms per device->host transfer regardless
    of size — and worse, a D2H sync of computed results mid-pipeline stalls
    the async queue behind it.  Stacking every deferred scalar across all
    readers costs one round trip total, and callers place it after the last
    dispatch so nothing downstream is poisoned."""
    deferred = [d for r in readers for d in r._deferred]
    if deferred:
        host_max = np.asarray(_stack_jit([m for m, _, _ in deferred]))
        for mx, (_, dict_len, path) in zip(host_max, deferred):
            if int(mx) >= dict_len:
                raise ParquetError(
                    f"dictionary index {int(mx)} out of range ({dict_len}) "
                    f"in column {path}"
                )
        for r in readers:
            r._deferred = []
    # the checks passed (or there were none): dispatched groups' decoded
    # columns are now provably valid — publish them to the result cache
    for r in readers:
        r._flush_result_cache()


def _timed_stage(reader: DeviceFileReader, stager: _RowGroupStager):
    """Stage on the worker, attributing wall time to the owning reader's
    ``stage_seconds`` lane (the worker and dispatching threads write
    concurrently; += is not atomic across bytecodes, hence the lock.
    Distinct lanes — not the old shared ``device_seconds`` scalar — so the
    two threads' concurrent intervals can never double-count wall time)."""
    import time as _time

    t0 = _time.perf_counter()
    with reader._pipe_stats.timed("stage", bytes=stager.total), \
            _xprof_annotation("stage"):
        buf_dev = stager.stage()
    t_staged = _time.perf_counter()
    with reader._stats_lock:
        reader._stats.stage_seconds += t_staged - t0
    # post-stage timestamp: the h2d lane times the ASYNC transfer tail,
    # never the host staging wall the `stage` lane already measured
    reader._note_staged(stager, buf_dev, t_staged)
    return buf_dev


class _FailedChunk:
    """In-band marker for a quarantined chunk riding the ordered chunk
    feed (a worker raise would kill the whole multi-file pool).  Carries
    the annotated exception; ``_prepare_row_group`` re-raises it so the
    consumer-side containment in ``_scan_pipeline`` records exactly one
    quarantine entry per failed unit on every path."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def _chunk_feed(work, prefetch: int, budget_bytes: int = 0, watchdog=None,
                cancel=None):
    """Chunk-granular prefetch over the ``(reader, path, index)`` stream.

    The host half of the overlapped pipeline (ISSUE 1 tentpole): IO + CRC +
    decompression + structure parse of upcoming chunks runs on a bounded
    pool of ``prefetch`` threads — work items FLATTENED across row-group
    and file boundaries, so the pool never drains while the main thread
    registers/stages/dispatches the current group.  Yields
    ``(reader, path, index, collected)`` in work order, where ``collected``
    is the dict ``_prepare_row_group(collected=...)`` consumes
    ({column_path: (md, _ChunkAssembler)} plus the pruning row count).

    Structurally this mirrors FileReader._decode_row_groups (reader.py) —
    same flatten/regroup protocol, sentinel convention, and cost formula;
    a change to one should be checked against the other.  They stay
    separate because the payloads differ (parsed assemblers + pruning
    plans + per-reader stats attribution here, finished ColumnData there).

    Page-pruning planning runs in the CONSUMER thread as items are pulled
    (it must precede its group's reads); its header walks go through the
    SharedReader's pread view, so they never race the pool's reads on the
    shared descriptor.  In-flight decompressed bytes are bounded by an
    InFlightBudget over ``budget_bytes`` — backpressure, not OOM.  Worker
    chunks register against fresh per-chunk AllocTrackers (the
    decompression-bomb guard keeps its teeth without sharing the reader's
    per-row-group counter across threads).
    """
    from .alloc import AllocTracker, InFlightBudget
    from .iostore import CoalescedFetcher
    from .iostore_async import engine_for_store
    from .pipeline import prefetch_map

    budget = InFlightBudget(budget_bytes)
    if watchdog is not None and watchdog.enabled:
        # the raise-policy exit from a wedge: aborting the budget wakes the
        # submitter blocked in acquire() with HangError (obs.Watchdog)
        watchdog.add_abort_hook(budget.abort)
    fed: set = set()  # readers whose _live_budget points at this feed
    srs: dict = {}  # reader id -> its host's store-backed SharedReader
    pending: dict[tuple, dict] = {}
    current = {"stats": None}  # stats of the reader whose item is submitting
    depth_owner = {"stats": None}  # last stats whose queue_depth gauge we set
    feedbox = {"eng": None}  # the async fetch engine, once any store routes

    class _Feed:
        """Late-binding feed gate for prefetch_map: a multi-file work
        stream mixes engine-routed and plain stores, and the engine only
        becomes known when gen_items first plans a routed group — until
        then the feed reports no lookahead appetite (plain threaded
        behavior), after which in-flight IO is bounded by the engine cap
        instead of the decode window."""

        @property
        def max_inflight(self):
            eng = feedbox["eng"]
            return eng.max_inflight if eng is not None else 0

        @staticmethod
        def want_more():
            eng = feedbox["eng"]
            return eng is not None and eng.want_more()

    class _StatsFwd:
        """Route prefetch_map's stall/peak accounting to the owning reader.

        Submission happens in the consumer thread right after gen_items
        yields an item, so ``current`` always names the reader whose chunk
        is paying the budget wait.  The queue-depth gauge is point-in-time
        state, not a flow: when the window's ownership moves to the next
        reader, the previous owner's gauge must drop to 0 — otherwise its
        sampler (and the final stop() tick at close) records a phantom
        backlog frozen at whatever depth it last saw, and prefetch_map's
        end-of-run reset only ever reaches the LAST reader."""

        @staticmethod
        def add_stall(seconds, t0=None):
            st = current["stats"]
            if st is not None:
                st.add_stall(seconds, t0)

        @staticmethod
        def set_queue_depth(n):
            st = current["stats"]
            prev = depth_owner["stats"]
            if prev is not None and prev is not st:
                prev.set_queue_depth(0)
            depth_owner["stats"] = st
            if st is not None:
                st.set_queue_depth(n)

        @staticmethod
        def note_peak(b):
            st = current["stats"]
            if st is not None:
                st.note_peak(b)

    def gen_items():
        for r, path, i in work:
            current["stats"] = r._pipe_stats
            r._live_budget = budget  # sampler budget_waiters track late-binds
            fed.add(r)
            sr = srs.get(id(r))
            if sr is None:
                # the host reader's own store-backed view — one wrapper
                # per (file, store) pair, never a divergent copy
                sr = srs[id(r)] = r._host._sr
                # fresh per-scan retry budget + coalescing state, scoped
                # to this scan's token (the reader's request deadline/
                # cancel rides it into every store read)
                sr.set_scan(sr.store.begin_scan(cancel=r._host._cancel))
            rc = r._result_cache
            if rc is not None and rc.has_group(
                    i, [".".join(l.path) for l in r.schema.selected_leaves()],
                    count_misses=True):
                # decoded-result hit: the feed reads NOTHING for this
                # group (no pruning walk, no chunk IO) — prepare re-probes
                # authoritatively and falls back to a sequential decode in
                # the rare evicted-in-between race
                pending[(id(r), i)] = {"r": r, "path": path, "i": i,
                                       "todo": 1, "chunks": {},
                                       "rows_dropped": 0, "cached": True}
                yield (r, None, i, None, None, None, None, None, None, None)
                continue
            rg = r.metadata.row_groups[i]
            leaves = {l.path: l for l in r.schema.selected_leaves()}
            skip_pages, rows_dropped, planned_bufs = r._plan_page_pruning(
                rg, leaves, f=sr.as_file(), index=i)
            items = []
            ranges = []
            for p, leaf, _chunk, md, offset in row_group_chunks(rg, leaves):
                items.append([r, sr, i, p, leaf, md, offset,
                              (skip_pages or {}).get(p),
                              planned_bufs.get(p), None])
                if planned_bufs.get(p) is None:
                    # chunks the pruning planner already read never join a
                    # coalesced span (their bytes are in hand)
                    ranges.append((offset, md.total_compressed_size))
            # range coalescing (iostore.py): this group's chunk reads merge
            # into fewer, larger, individually-retryable fetches, fanned
            # out on the prefetch pool (the first worker to touch a span
            # fetches it) — only for stores that ask for it
            st = sr.store
            tok = sr._scan
            eng = engine_for_store(st)
            if eng is not None:
                feedbox["eng"] = eng
            use_coalesce = (st.prefers_coalescing
                            and not (tok.coalesce_disabled if tok is not None
                                     else st.coalesce_disabled)
                            and len(ranges) > 1)
            if ranges and (use_coalesce or eng is not None):
                # engine mode submits the group's fetches NOW (merged
                # spans, or singles once the ladder disables merging)
                fetcher = CoalescedFetcher(st, ranges, scan=tok, engine=eng,
                                           coalesce=use_coalesce)
                for it in items:
                    if it[8] is None:
                        it[9] = fetcher
            key = (id(r), i)
            pending[key] = {"r": r, "path": path, "i": i,
                            "todo": max(len(items), 1), "chunks": {},
                            "rows_dropped": rows_dropped}
            if not items:
                items.append([r, None, i, None, None, None, None, None,
                              None, None])
            yield from map(tuple, items)

    def cost(item):
        md = item[5]
        if md is None:
            return 0
        comp = max(md.total_compressed_size or 0, 0)
        return comp + max(md.total_uncompressed_size or 0, comp)

    def collect(item):
        r, sr, i, p, leaf, md, offset, skip, buf0, fetcher = item
        if md is None:
            return (id(r), i), None, None
        stats = r._pipe_stats
        ctx = {"file": r._host._source_name, "row_group": i,
               "column": ".".join(p), "chunk_offset": offset}
        try:
            tracker = AllocTracker(r.alloc.max_size)
            tracker.register(md.total_compressed_size)
            if buf0 is not None:
                buf = buf0  # the pruning planner already paid this chunk's IO
            else:
                with stats.timed("io"):
                    buf = (fetcher.read(offset, md.total_compressed_size)
                           if fetcher is not None
                           else sr.pread(offset, md.total_compressed_size))
            require_full(buf, offset, md.total_compressed_size,
                         context=f"column {'.'.join(p)}")
            with stats.timed("decompress"):
                asm = _collect_chunk(
                    buf, md.codec, md.num_values, leaf, r._deferred,
                    validate_crc=r.validate_crc, alloc=tracker,
                    statistics=md.statistics, skip_pages=skip,
                    context=ctx, dict_cache=r._dict_cache,
                )
        except ParquetError as e:
            # containment seam (quarantine.py): wrap instead of raise so
            # the feed keeps flowing; the consumer notes the record
            q = r.quarantine
            from .errors import DataIntegrityError
            from .quarantine import annotate_data_error

            if not q.contains or isinstance(e, DataIntegrityError):
                raise
            return (id(r), i), p, (md, _FailedChunk(
                annotate_data_error(e, **{k: v for k, v in ctx.items()
                                          if k != "chunk_offset"})))
        # ship planning on the SAME worker thread (outside the decompress
        # timer: its compression seconds land in the `recompress` stage) —
        # the link-recompression work overlaps the consumer's stage/dispatch
        if asm is not None:
            asm.preship(r._ship_planner, stats,
                        route_hint=(r._plan.route_hint(i, ".".join(p))
                                    if r._plan is not None else None))
        stats.count_chunk()
        return (id(r), i), p, (md, asm)

    try:
        for key, p, payload in prefetch_map(gen_items(), collect, prefetch,
                                            budget=budget, cost=cost,
                                            stats=_StatsFwd(),
                                            cancel=cancel, feed=_Feed()):
            slot = pending[key]
            if p is not None:
                slot["chunks"][p] = payload
            slot["todo"] -= 1
            if slot["todo"] == 0:
                del pending[key]
                r = slot["r"]
                r._pipe_stats.note_peak(budget)
                r._pipe_stats.touch_wall()
                yield r, slot["path"], slot["i"], {
                    "chunks": slot["chunks"],
                    "rows_dropped": slot["rows_dropped"],
                    "cached": slot.get("cached", False),
                }
    finally:
        # un-bind the dead feed's budget: a later flight dump (or a reused
        # reader's sampler) must not report this scan's stale zero-waiter
        # budget as live state — and the reader-lifetime watchdog must not
        # pin (or abort) this scan's budget after the feed is gone
        if watchdog is not None and watchdog.enabled:
            watchdog.remove_abort_hook(budget.abort)
        for r in fed:
            if r._live_budget is budget:
                r._live_budget = None


def _scan_pipeline(work, ex, finalize_each: bool = False,
                   close_finished: bool = False,
                   defer_finalize: bool = False,
                   prefetch: int = 0, budget_bytes: int = 0,
                   watchdog=None, quarantine=None, cancel=None):
    """The one-deep prepare/stage/dispatch pipeline shared by
    ``DeviceFileReader.iter_row_groups`` (one reader) and :func:`scan_files`
    (many).  ``work`` yields ``(reader, path, row_group_index)``; this yields
    ``(path, columns)`` per row group.

    With ``prefetch`` > 0 the host phase (chunk IO + decompress + parse) is
    pulled out of ``_prepare_row_group`` onto :func:`_chunk_feed`'s pool:
    chunks of row group N+1 (and beyond, budget permitting) decompress on
    worker threads while group N stages and dispatches — the chunk-granular
    overlap on top of the existing group-granular stage/dispatch overlap.
    An eager error from a prefetched chunk may then preempt the preceding
    yield by up to the feed's depth (the sequential path's by exactly one).

    Ordering contract: a row group is always YIELDED before its reader's
    deferred checks can raise (finalize runs after the yield, either at a
    file boundary or at the end), matching iter_row_groups' yield-then-raise
    semantics.  With ``close_finished`` a reader is closed as soon as its
    last row group is delivered, bounding open file descriptors to one (all
    of a reader's chunk reads precede its last group's yield, so the feed
    never touches a closed descriptor).
    """
    if prefetch > 0:
        stream = _chunk_feed(work, prefetch, budget_bytes, watchdog=watchdog,
                             cancel=cancel)
    else:
        stream = ((r, path, i, None) for r, path, i in work)
    # consumer gate: the watchdog may only fire while the consumer is
    # genuinely blocked in here producing — a consumer pausing between row
    # groups freezes every other lane (full prefetch window) and must not
    # read as a hang (obs.ConsumerLane)
    lane = (watchdog.watch_consumer()
            if watchdog is not None and watchdog.enabled else None)
    from .errors import DataIntegrityError

    dead: set = set()  # readers quarantined whole (policy skip_file)
    try:
        if lane is not None:
            lane.producing()
        prev = None  # (reader, path, prepared, staging future)
        for r, path, i, collected in stream:
            if watchdog is not None:
                watchdog.check()  # surface a fired raise-policy HangError
                # even when no budget wait existed to interrupt (prefetch=0)
            q = quarantine if quarantine is not None else r.quarantine
            if id(r) in dead:
                # collateral skip: a later unit of a skip_file-quarantined
                # file — accounted, never decoded, never a new record
                q.note_unit_skipped(
                    int(r.metadata.row_groups[i].num_rows or 0))
                continue
            try:
                prepared = r._prepare_row_group(i, executor=ex,
                                                collected=collected)
            except ParquetError as e:
                # containment seam (quarantine.py): record + skip the unit
                # instead of aborting the scan; DataIntegrityError (budget
                # exhausted) always propagates
                if not q.contains or isinstance(e, DataIntegrityError):
                    raise
                q.note(e, file=r._host._source_name, row_group=i)
                q.note_unit_skipped(
                    int(r.metadata.row_groups[i].num_rows or 0))
                if q.policy == "skip_file":
                    q.note_file_skipped()
                    dead.add(id(r))
                continue
            fut = (ex.submit(_timed_stage, r, prepared[2])
                   if prepared[1] else None)
            if prev is not None:
                pr, pp, pprep, pfut = prev
                out = pr._dispatch_row_group(
                    pprep, pfut.result() if pfut else None
                )
                if lane is not None:
                    lane.idle()
                yield pp, out
                if lane is not None:
                    lane.producing()
                if finalize_each or pr is not r:
                    if not defer_finalize:
                        # a mid-pipeline finalize is a D2H sync that stalls
                        # the async queue; multi-file scans defer it to one
                        # combined end-of-scan check (_finalize_many)
                        pr.finalize()
                    if close_finished and pr is not r:
                        pr.close()
            prev = (r, path, prepared, fut)
        if prev is not None:
            pr, pp, pprep, pfut = prev
            out = pr._dispatch_row_group(
                pprep, pfut.result() if pfut else None
            )
            if lane is not None:
                lane.idle()
            yield pp, out
            if lane is not None:
                lane.producing()
            if not defer_finalize:
                pr.finalize()
    finally:
        # the scan is over (or dead): leave the lane advancing so a
        # reader's long-lived watchdog never mistakes post-scan idleness
        # (or a consumer that abandoned us) for a wedge
        if lane is not None:
            lane.idle()


def scan_files(paths, columns=None, validate_crc=None,
               max_memory: int = 0, row_filter=None, with_path: bool = False,
               prefetch: int = 0, trace=None, sample_ms=None, hang_s=None,
               hang_policy=None, store=None, on_data_error=None,
               quarantine=None, plan_cache=None):
    """Scan several files' row groups through ONE continuous transfer pipeline.

    ``prefetch=K`` additionally runs chunk IO + decompression K-deep on a
    worker pool spanning row-group AND file boundaries (see _chunk_feed), so
    the host phase of file N+1's first group overlaps file N's tail
    transfers — the same lookahead the group-granular pipeline below already
    provides for staging, extended to the host's half of the work.  The
    feed's lookahead opens upcoming files a little earlier, so the open-fd
    bound becomes O(prefetch) instead of one.

    ``store=`` selects the IO backend per file (iostore.py): pass a
    FACTORY callable (``lambda f: MyRangeStore(...)``) so each file gets
    its own store — a single shared instance would mix files' bytes.

    ``plan_cache=`` (a :class:`tpu_parquet.serve.PlanCache`) makes every
    file's footer, ScanPlan IR, and decoded dictionaries read through
    shared cached state — a re-scanned file re-parses nothing, and route/
    pruning memos accumulate across scans.

    The multi-file dataset form of ``DeviceFileReader.iter_row_groups``
    (BASELINE config 5 is a multi-file row-group scan): per-file iteration
    drains the transfer pipeline at every file boundary — the last row
    group's staging ships with nothing overlapping it, and the next file's
    footer parse waits for it.  Here one staging worker spans the whole
    dataset, so file N+1's footer/decompress overlaps file N's tail
    transfers exactly like adjacent row groups within a file.

    Yields one ``{column: DeviceColumnData}`` dict per row group (in file
    order); ``with_path=True`` yields ``(path, cols)`` pairs.  Deferred
    dictionary range checks run ONCE, after the last file's last group is
    yielded (a per-file-boundary check would be a mid-pipeline D2H sync
    that stalls the async queue — measured ~50ms per boundary); eager
    per-chunk errors raise from the pipelined prepare and may preempt the
    preceding group's yield by one (the pipeline's depth), exactly as
    within one file.  Finished files close at the boundary (open
    descriptors stay bounded for arbitrarily many shards — the deferred
    scalars are device arrays, not file state), and every reader is closed
    on exit even on error.

    .. warning:: Consumers that abandon the scan early (``break``,
       ``islice``) and let the generator be closed by GC lose the deferred
       range-check exception (``GeneratorExit`` semantics swallow it); the
       corruption is still reported via ``logging.error`` on the
       ``tpu_parquet.device_reader`` logger.  Close the generator
       explicitly (or iterate to exhaustion) to get the ``ParquetError``.
    """
    from concurrent.futures import ThreadPoolExecutor

    from .obs import Watchdog, resolve_hang_s, resolve_tracer
    from .quarantine import Quarantine
    from .write.manifest import expand_dataset

    # a manifest path (or a directory holding tpq_manifest.json — the
    # sharded writer's multi-file layout) expands to its member list, so
    # a written-then-compacted dataset scans as ONE dataset
    paths, _manifest = expand_dataset(paths)

    # one tracer spans the whole scan (per-file tracers would shred the
    # timeline Perfetto is supposed to show); with a path, the trace + the
    # merged registry of every reader are written when the scan ends
    tracer, owns_tracer = resolve_tracer(trace)
    # ONE containment engine spans the whole scan: the error budget and the
    # quarantine ledger are per-SCAN facts, not per-file ones (the unit
    # total is unknown up front, so only the absolute budget binds)
    q = quarantine if quarantine is not None else Quarantine(on_data_error)
    q.begin_scan()
    readers: list[DeviceFileReader] = []

    # ONE watchdog spans the whole scan (per-reader watchdogs would call a
    # reader idle just because its neighbor has the pipeline's turn);
    # child readers are armed with an explicit hang_s=0 below so the env
    # cannot raise N redundant watchdog threads for one scan
    watchdog = Watchdog(resolve_hang_s(hang_s), policy=hang_policy)
    if watchdog.enabled:
        def _lanes():
            out: dict = {}
            for r in list(readers):
                for k, v in r._pipe_stats.sample().items():
                    out[k] = out.get(k, 0) + v
            return out

        watchdog.watch("pipeline", _lanes)
        watchdog.watch("reader", lambda: {
            "rows": sum(r._stats.rows for r in list(readers)),
            "chunks": sum(r._stats.chunks for r in list(readers)),
            "staged_bytes": sum(r._stats.staged_bytes
                                for r in list(readers)),
        })

        def _io_lanes():
            out: dict = {}
            for r in list(readers):
                st = r._store.stats
                if st is None:
                    continue
                for k, v in st.progress().items():
                    out[k] = out.get(k, 0) + v
            return out

        # store heartbeat across every file's store: frozen fetch counters
        # + frozen pipeline = a network stall the dump can name
        watchdog.watch("iostore", _io_lanes)
        watchdog.start()

    def work():
        for path in paths:
            # with a serve.PlanCache, the footer, the ScanPlan IR, and the
            # decoded-dictionary cache all read through shared state — a
            # re-scanned file re-parses nothing (ROADMAP item 4's owed
            # footer cache, generalized)
            kw = (plan_cache.reader_kwargs(path, columns=columns,
                                           row_filter=row_filter,
                                           device=True,
                                           validate_crc=validate_crc)
                  if plan_cache is not None else {})
            r = DeviceFileReader(
                path, columns=columns, validate_crc=validate_crc,
                max_memory=max_memory, row_filter=row_filter, trace=tracer,
                sample_ms=sample_ms, hang_s=0, store=store, quarantine=q,
                **kw,
            )
            readers.append(r)
            if watchdog.enabled:
                # like the per-reader wiring: a fired watchdog must wake
                # fetches stalled inside any file's store (no-op for local)
                watchdog.add_abort_hook(r._store.abort)
            for i in range(r.num_row_groups):
                if r._host.row_group_selected(i):
                    yield r, path, i

    # aligned device profile (TPQ_XPROF): the multi-file scan owns ONE
    # bounded window spanning file boundaries — per-reader windows would
    # never start (scan_files drives _scan_pipeline directly, not
    # iter_row_groups)
    xprof = _XprofWindow()
    xprof.start()
    try:
        with ThreadPoolExecutor(1) as ex:
            for pp, out in _scan_pipeline(work(), ex, close_finished=True,
                                          defer_finalize=True,
                                          prefetch=int(prefetch),
                                          budget_bytes=int(max_memory),
                                          watchdog=watchdog, quarantine=q):
                yield (pp, out) if with_path else out
                xprof.tick()
        _finalize_many(readers)
    finally:
        xprof.stop()
        watchdog.stop()
        try:
            # idempotent re-check: covers consumers that abandon the scan
            # early (break/islice) — their consumed-but-unchecked files
            # still validate when the generator closes.  (A GC-time close
            # swallows exceptions by Python semantics — see the docstring
            # warning — so corrupt indices are ALSO logged before raising.)
            try:
                _finalize_many(readers)
            except ParquetError as e:
                import logging

                logging.getLogger(__name__).error(
                    "scan_files deferred validation failed "
                    "(swallowed if this close is GC-driven): %s", e)
                raise
        finally:
            for r in readers:
                r.close()
            if owns_tracer and readers:
                reg = readers[0].obs_registry()
                for r in readers[1:]:
                    reg.merge_from(r.obs_registry())
                tracer.write(registry=reg)
