"""Parquet file footer open/close discipline.

Equivalent of the reference's file_meta.go:14-74 (`ReadFileMetaData`): validate the
4-byte ``PAR1`` magic at both ends of the file, read the little-endian uint32 footer
length from the last 8 bytes, and thrift-decode the ``FileMetaData`` struct.  Footer-only
open — no data pages are touched — which is what makes metadata inspection, row-group
seeking, and column projection cheap (SURVEY.md §5.4).
"""

from __future__ import annotations

import io
import os
import struct
from typing import BinaryIO, Union

from .format import FileMetaData
from .thrift import ThriftError, deserialize, serialize

MAGIC = b"PAR1"
MAGIC_ENCRYPTED = b"PARE"
FOOTER_TAIL = 8  # uint32 footer length + 4-byte magic


from .errors import ParquetError  # noqa: F401  (canonical home: errors.py)


def read_file_metadata(
    source: Union[str, os.PathLike, BinaryIO, bytes], validate_head_magic: bool = True
) -> FileMetaData:
    """Read the ``FileMetaData`` footer from a path, file object, or bytes.

    Mirrors file_meta.go:18-74: head-magic check (optional, as in the reference's
    ``readHeader`` gate), seek to end, tail magic + footer-length validation, thrift
    decode.
    """
    if isinstance(source, (str, os.PathLike)):
        with open(source, "rb") as f:
            return read_file_metadata(f, validate_head_magic)
    if isinstance(source, (bytes, bytearray, memoryview)):
        return read_file_metadata(io.BytesIO(bytes(source)), validate_head_magic)

    f = source
    if validate_head_magic:
        f.seek(0)
        head = f.read(4)
        if head != MAGIC:
            if head == MAGIC_ENCRYPTED:
                raise ParquetError("encrypted parquet files are not supported")
            raise ParquetError(f"invalid parquet file: bad head magic {head!r}")

    size = f.seek(0, os.SEEK_END)
    if size < len(MAGIC) * 2 + FOOTER_TAIL - 4:
        raise ParquetError(f"file too small to be parquet ({size} bytes)")

    f.seek(size - FOOTER_TAIL)
    tail = f.read(FOOTER_TAIL)
    if tail[4:] != MAGIC:
        raise ParquetError(f"invalid parquet file: bad tail magic {tail[4:]!r}")
    footer_len = struct.unpack("<I", tail[:4])[0]
    if footer_len == 0 or footer_len > size - FOOTER_TAIL:
        raise ParquetError(
            f"invalid footer length {footer_len} (file size {size})"
        )

    f.seek(size - FOOTER_TAIL - footer_len)
    buf = f.read(footer_len)
    if len(buf) != footer_len:
        raise ParquetError("truncated footer")
    try:
        meta = deserialize(FileMetaData, buf)
    except ThriftError as e:
        raise ParquetError(f"corrupt footer thrift: {e}") from e

    if meta.schema is None or len(meta.schema) == 0:
        raise ParquetError("footer has no schema elements")
    if meta.num_rows is None or meta.num_rows < 0:
        raise ParquetError(f"footer has invalid num_rows {meta.num_rows}")
    if meta.row_groups is None:
        meta.row_groups = []
    return meta


def serialize_footer(meta: FileMetaData) -> bytes:
    """Footer bytes as written at Close: thrift body + uint32 length + magic.

    Mirrors file_writer.go:336-347.
    """
    body = serialize(meta)
    return body + struct.pack("<I", len(body)) + MAGIC
