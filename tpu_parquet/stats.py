"""Column statistics (min/max/null_count/distinct_count).

Equivalent of the reference's stats.go:9-224: per-physical-type min/max trackers
serialized as little-endian bytes (or raw bytes for BYTE_ARRAY).  Batch-oriented:
stats are computed over whole value arrays with numpy reductions, not per value.
Booleans get no min/max (nilStats parity); byte arrays use unsigned lexicographic
order (the reference's byte-wise compare).
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

from .column import ByteArrayData
from .format import Statistics, Type


def _le_bytes(v, fmt: str) -> bytes:
    return struct.pack(fmt, v)


def compute_statistics(
    values, ptype: Type, null_count: int, distinct_count: Optional[int] = None
) -> Statistics:
    """Stats over the defined values of one page/chunk."""
    st = Statistics(null_count=null_count)
    if distinct_count is not None:
        st.distinct_count = distinct_count
    n = len(values)
    if n == 0:
        return st
    if ptype == Type.BOOLEAN:
        return st  # nilStats: no min/max for booleans (stats.go:9-24)
    if ptype in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
        items = values.to_list() if isinstance(values, ByteArrayData) else [bytes(v) for v in values]
        mn = min(items)
        mx = max(items)
        st.min, st.max = mn, mx
        st.min_value, st.max_value = mn, mx
        return st
    if ptype == Type.INT96:
        return st  # no meaningful order; reference tracks none for int96 pages
    arr = np.asarray(values)
    if ptype == Type.INT32:
        mn, mx = int(arr.min()), int(arr.max())
        st.min = st.min_value = _le_bytes(mn, "<i")
        st.max = st.max_value = _le_bytes(mx, "<i")
    elif ptype == Type.INT64:
        mn, mx = int(arr.min()), int(arr.max())
        st.min = st.min_value = _le_bytes(mn, "<q")
        st.max = st.max_value = _le_bytes(mx, "<q")
    elif ptype == Type.FLOAT:
        finite = arr[~np.isnan(arr)]
        if len(finite) == 0:
            return st
        st.min = st.min_value = _le_bytes(float(finite.min()), "<f")
        st.max = st.max_value = _le_bytes(float(finite.max()), "<f")
    elif ptype == Type.DOUBLE:
        finite = arr[~np.isnan(arr)]
        if len(finite) == 0:
            return st
        st.min = st.min_value = _le_bytes(float(finite.min()), "<d")
        st.max = st.max_value = _le_bytes(float(finite.max()), "<d")
    return st


def merge_statistics(a: Optional[Statistics], b: Statistics, ptype: Type) -> Statistics:
    """Fold page stats into chunk stats."""
    if a is None:
        return Statistics(
            min=b.min, max=b.max, min_value=b.min_value, max_value=b.max_value,
            null_count=b.null_count, distinct_count=b.distinct_count,
        )
    out = Statistics()
    if a.null_count is not None or b.null_count is not None:
        out.null_count = (a.null_count or 0) + (b.null_count or 0)
    # distinct counts don't merge additively; drop at chunk level unless equal
    key = _compare_key(ptype)
    for lo_attr, hi_attr in (("min", "max"), ("min_value", "max_value")):
        alo, blo = getattr(a, lo_attr), getattr(b, lo_attr)
        ahi, bhi = getattr(a, hi_attr), getattr(b, hi_attr)
        setattr(out, lo_attr, _pick(alo, blo, key, lambda x, y: x <= y))
        setattr(out, hi_attr, _pick(ahi, bhi, key, lambda x, y: x >= y))
    return out


def _compare_key(ptype: Type):
    if ptype == Type.INT32:
        return lambda b: struct.unpack("<i", b)[0]
    if ptype == Type.INT64:
        return lambda b: struct.unpack("<q", b)[0]
    if ptype == Type.FLOAT:
        return lambda b: struct.unpack("<f", b)[0]
    if ptype == Type.DOUBLE:
        return lambda b: struct.unpack("<d", b)[0]
    return lambda b: b  # byte-wise


def _pick(a, b, key, better):
    if a is None:
        return b
    if b is None:
        return a
    return a if better(key(a), key(b)) else b
