"""Column statistics (min/max/null_count/distinct_count).

Equivalent of the reference's stats.go:9-224: per-physical-type min/max trackers
serialized as little-endian bytes (or raw bytes for BYTE_ARRAY).  Batch-oriented:
stats are computed over whole value arrays with numpy reductions, not per value.
Booleans get no min/max (nilStats parity); byte arrays use unsigned lexicographic
order (the reference's byte-wise compare).
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

from .column import ByteArrayData
from .format import Statistics, Type


def _le_bytes(v, fmt: str) -> bytes:
    return struct.pack(fmt, v)


def _int_minmax(arr: "np.ndarray", width: int) -> tuple:
    """(min, max) in ONE pass via the native vectorized scan when available
    (numpy needs two reduces; the writer computes stats per chunk AND per
    page)."""
    from . import native

    a = np.ascontiguousarray(arr)
    if (a.dtype.itemsize == width and a.dtype.kind == "i"
            and a.dtype.isnative):  # the C scan reads little-endian
        mm = native.int_minmax(a, 0, len(a), width)
        if mm is not None:
            return mm
    return int(arr.min()), int(arr.max())


def _lex_minmax(ba) -> tuple[bytes, bytes]:
    """Lexicographic (min, max) over a ragged byte column, vectorized.

    Candidate filtering one byte position at a time: every survivor shares
    the same prefix, so a candidate exhausted at position k IS the min (and
    loses the max unless all are exhausted, i.e. identical).  Candidate sets
    shrink geometrically on real data (2-4 rounds typical); the worst case —
    all values identical — is one vector pass per byte of the value, still
    O(total bytes).  Replaces a to_list() + Python min/max that materialized
    every value as a bytes object (the writer's hottest path after uniquing
    on string columns; byte-wise unsigned order matches stats.go).
    """
    off = np.asarray(ba.offsets)
    heap = np.asarray(ba.heap)
    lens = np.diff(off)

    def pick(want_max: bool) -> int:
        cands = np.arange(len(ba))
        k = 0
        while len(cands) > 1:
            exhausted = lens[cands] == k
            if want_max:
                alive = cands[~exhausted]
                if len(alive) == 0:
                    return int(cands[0])  # all identical
                cands = alive
            elif exhausted.any():
                return int(cands[exhausted][0])  # a prefix beats extensions
            b = heap[off[cands] + k]
            target = b.max() if want_max else b.min()
            cands = cands[b == target]
            k += 1
        return int(cands[0])

    i_mn, i_mx = pick(False), pick(True)
    return (bytes(heap[off[i_mn] : off[i_mn] + lens[i_mn]]),
            bytes(heap[off[i_mx] : off[i_mx] + lens[i_mx]]))


def compute_statistics(
    values, ptype: Type, null_count: int, distinct_count: Optional[int] = None
) -> Statistics:
    """Stats over the defined values of one page/chunk."""
    st = Statistics(null_count=null_count)
    if distinct_count is not None:
        st.distinct_count = distinct_count
    n = len(values)
    if n == 0:
        return st
    if ptype == Type.BOOLEAN:
        return st  # nilStats: no min/max for booleans (stats.go:9-24)
    if ptype in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
        if isinstance(values, ByteArrayData):
            mn, mx = _lex_minmax(values)
        else:
            items = [bytes(v) for v in values]
            mn, mx = min(items), max(items)
        st.min, st.max = mn, mx
        st.min_value, st.max_value = mn, mx
        return st
    if ptype == Type.INT96:
        return st  # no meaningful order; reference tracks none for int96 pages
    arr = np.asarray(values)
    if ptype in (Type.INT32, Type.INT64):
        mn, mx = _int_minmax(arr, 4 if ptype == Type.INT32 else 8)
        fmt = "<i" if ptype == Type.INT32 else "<q"
        st.min = st.min_value = _le_bytes(mn, fmt)
        st.max = st.max_value = _le_bytes(mx, fmt)
    elif ptype == Type.FLOAT:
        finite = arr[~np.isnan(arr)]
        if len(finite) == 0:
            return st
        st.min = st.min_value = _le_bytes(float(finite.min()), "<f")
        st.max = st.max_value = _le_bytes(float(finite.max()), "<f")
    elif ptype == Type.DOUBLE:
        finite = arr[~np.isnan(arr)]
        if len(finite) == 0:
            return st
        st.min = st.min_value = _le_bytes(float(finite.min()), "<d")
        st.max = st.max_value = _le_bytes(float(finite.max()), "<d")
    return st
