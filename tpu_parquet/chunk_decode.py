"""Column-chunk decoding: page walk + (type × encoding) dispatch → ColumnData.

Equivalent of the reference's chunk_reader.go (readChunk/readPages/readPageBlock +
getValuesDecoder dispatch :106-159) and page_v1.go/page_v2.go/page_dict.go — but
columnar: the whole chunk's byte range is read in one IO (that is also the unit
shipped to TPU HBM), pages are sliced out of the buffer, and every decode step is a
bulk array transform rather than a value-at-a-time interface call.

Encoding support matrix mirrors chunk_reader.go:106-159 exactly, plus
BYTE_STREAM_SPLIT (in the format since 2.8; the Go reference lacks it).
PLAIN_DICTIONARY is aliased to RLE_DICTIONARY on read (chunk_reader.go:108-110).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import BinaryIO, Optional

import numpy as np

from .alloc import AllocTracker
from .column import ByteArrayData, ColumnData
from .compress import decompress_block
from .footer import ParquetError
from .format import parse_encoding, Encoding, PageHeader, PageType, Type
from .kernels import bitpack, bytearray as ba_codec, delta, plain, rle
from .schema.core import SchemaNode
from .thrift import ThriftError, read_struct


@dataclass
class PageSlice:
    """One page located inside a chunk buffer (header + payload span)."""

    header: PageHeader
    payload_start: int
    payload_end: int


# native page-header parse error codes → the python engine's diagnostics
_NATIVE_THRIFT_ERRORS = {
    -40: "truncated thrift input",
    -41: "varint too long",
    -42: "thrift container exceeds sanity cap",
    -43: "thrift nesting too deep",
    -44: "cannot skip unknown thrift ctype",
}


def _read_page_header(buf: bytes, pos: int):
    """One PageHeader at ``pos``: native C parse (meta_parse.cpp, the
    per-page host hot path — ~100 µs of python thrift per page otherwise)
    with the python engine as fallback and fuzz-parity oracle."""
    from . import native

    res = native.page_header(buf, pos)
    if res is None:
        return read_struct(PageHeader, buf, pos)
    if isinstance(res, int):
        raise ThriftError(
            _NATIVE_THRIFT_ERRORS.get(res, f"thrift parse error {res}")
        )
    return res


def walk_pages(buf: bytes, total_values: int) -> list[PageSlice]:
    """Parse page headers until the chunk's declared value count is consumed.

    Mirrors readPages (chunk_reader.go:182-263): iterate thrift PageHeaders and
    their payloads; dictionary pages don't count toward the value total.
    """
    pages: list[PageSlice] = []
    pos = 0
    seen_values = 0
    seen_dict = False
    n = len(buf)
    while seen_values < total_values:
        if pos >= n:
            raise ParquetError(
                f"chunk exhausted at {seen_values}/{total_values} values"
            )
        try:
            header, pos = _read_page_header(buf, pos)
        except ThriftError as e:
            raise ParquetError(f"corrupt page header: {e}") from e
        if header.compressed_page_size is None or header.compressed_page_size < 0:
            raise ParquetError(
                f"invalid compressed page size {header.compressed_page_size}"
            )
        if header.uncompressed_page_size is None or header.uncompressed_page_size < 0:
            raise ParquetError(
                f"invalid uncompressed page size {header.uncompressed_page_size}"
            )
        end = pos + header.compressed_page_size
        if end > n:
            raise ParquetError("page payload extends past chunk end")
        ptype = header.type
        if ptype == PageType.DICTIONARY_PAGE:
            if seen_dict or pages:
                # only one dict page, and only at the start (chunk_reader.go:196-199)
                raise ParquetError("unexpected extra dictionary page")
            if header.dictionary_page_header is None:
                raise ParquetError("dictionary page missing its header")
            seen_dict = True
        elif ptype == PageType.DATA_PAGE:
            if header.data_page_header is None:
                raise ParquetError("data page v1 missing its header")
            seen_values += header.data_page_header.num_values or 0
        elif ptype == PageType.DATA_PAGE_V2:
            if header.data_page_header_v2 is None:
                raise ParquetError("data page v2 missing its header")
            seen_values += header.data_page_header_v2.num_values or 0
        # INDEX_PAGE and unknown types: skip payload silently
        pages.append(PageSlice(header, pos, end))
        pos = end
    return pages


def _check_crc(header: PageHeader, payload: bytes, validate: bool) -> None:
    if not validate or header.crc is None:
        return
    actual = zlib.crc32(payload) & 0xFFFFFFFF
    if actual != header.crc & 0xFFFFFFFF:
        raise ParquetError(
            f"page CRC mismatch: header {header.crc & 0xFFFFFFFF:#x}, data {actual:#x}"
        )


def _byte_stream_split_decode(raw: bytes, ptype: Type, count: int, type_length: int):
    """BYTE_STREAM_SPLIT: K per-byte streams concatenated; de-interleave."""
    width = {
        Type.FLOAT: 4, Type.DOUBLE: 8, Type.INT32: 4, Type.INT64: 8,
    }.get(ptype, type_length)
    if width <= 0:
        raise ParquetError(f"BYTE_STREAM_SPLIT unsupported for {ptype!r}")
    need = count * width
    if len(raw) < need:
        raise ParquetError("BYTE_STREAM_SPLIT: truncated data")
    mat = np.frombuffer(raw, np.uint8, need).reshape(width, count).T.copy()
    flat = mat.reshape(-1)
    if ptype == Type.FLOAT:
        return flat.view("<f4").copy()
    if ptype == Type.DOUBLE:
        return flat.view("<f8").copy()
    if ptype == Type.INT32:
        return flat.view("<i4").copy()
    if ptype == Type.INT64:
        return flat.view("<i8").copy()
    offsets = np.arange(count + 1, dtype=np.int64) * width
    return ByteArrayData(offsets=offsets, heap=flat)


class ChunkDecoder:
    """Decodes one column chunk into a ColumnData.

    ``context`` carries the decode site's coordinates ({file, column,
    row_group, chunk_offset}) — every raise out of :meth:`decode` is
    annotated with them plus the failing page's ordinal and absolute byte
    offset (quarantine.error_context), so a CRC mismatch names WHERE at
    fleet scale instead of printing two hashes.
    """

    def __init__(
        self,
        leaf: SchemaNode,
        validate_crc: bool = False,
        alloc: Optional[AllocTracker] = None,
        context: Optional[dict] = None,
        dict_cache=None,
    ):
        self.leaf = leaf
        self.validate_crc = validate_crc
        self.alloc = alloc or AllocTracker(0)
        self.context = dict(context or {})
        if "column" not in self.context and leaf.path:
            self.context["column"] = ".".join(leaf.path)
        self.dictionary = None  # decoded dict values (np array or ByteArrayData)
        # read-through decoded-dictionary cache (serve.BoundDictCache duck
        # type): get(rg, column, kind) / put(rg, column, kind, value,
        # nbytes).  Keyed by this decoder's context coordinates — callers
        # without a row_group/column context never hit it.
        self.dict_cache = dict_cache

    # -- value decoding dispatch (getValuesDecoder, chunk_reader.go:106-159) --

    def _decode_values(self, enc: int, raw: bytes, count: int):
        ptype = self.leaf.physical_type
        tl = self.leaf.type_length
        enc = parse_encoding(enc)
        if enc == Encoding.PLAIN_DICTIONARY:
            enc = Encoding.RLE_DICTIONARY
        if enc == Encoding.PLAIN:
            return plain.decode(raw, ptype, count, tl)
        if enc == Encoding.RLE_DICTIONARY:
            if self.dictionary is None:
                raise ParquetError(
                    "dictionary-encoded page but no dictionary page seen"
                )
            if len(raw) < 1:
                raise ParquetError("dictionary page data truncated (missing width)")
            width = int(raw[0])
            if width > 32:
                raise ParquetError(f"dictionary index width {width} invalid")
            idx = rle.decode(raw[1:], width, count).astype(np.int64)
            dict_len = len(self.dictionary)
            if count and (idx.max(initial=0) >= dict_len):
                raise ParquetError(
                    f"dictionary index {int(idx.max())} out of range ({dict_len})"
                )
            if isinstance(self.dictionary, ByteArrayData):
                return self.dictionary.take(idx)
            return self.dictionary[idx]
        if enc == Encoding.DELTA_BINARY_PACKED:
            if ptype == Type.INT32:
                vals, _ = delta.decode(raw, bits=32)
            elif ptype == Type.INT64:
                vals, _ = delta.decode(raw, bits=64)
            else:
                raise ParquetError(f"DELTA_BINARY_PACKED invalid for {ptype!r}")
            if len(vals) < count:
                raise ParquetError(
                    f"delta stream yielded {len(vals)} of {count} values"
                )
            return vals[:count]
        if enc == Encoding.DELTA_LENGTH_BYTE_ARRAY:
            if ptype != Type.BYTE_ARRAY:
                raise ParquetError(f"DELTA_LENGTH_BYTE_ARRAY invalid for {ptype!r}")
            return ba_codec.decode_delta_length(raw, count)
        if enc == Encoding.DELTA_BYTE_ARRAY:
            if ptype != Type.BYTE_ARRAY:
                raise ParquetError(f"DELTA_BYTE_ARRAY invalid for {ptype!r}")
            return ba_codec.decode_delta(raw, count)
        if enc == Encoding.RLE:
            if ptype != Type.BOOLEAN:
                raise ParquetError(f"RLE value encoding invalid for {ptype!r}")
            vals, _ = rle.decode_prefixed(raw, 1, count)
            return vals.astype(bool)
        if enc == Encoding.BYTE_STREAM_SPLIT:
            return _byte_stream_split_decode(raw, ptype, count, tl)
        raise ParquetError(f"unsupported value encoding {enc.name} for {ptype!r}")

    # -- pages ----------------------------------------------------------------

    def _dict_cache_key(self):
        rg = self.context.get("row_group")
        col = self.context.get("column")
        if self.dict_cache is None or rg is None or col is None:
            return None
        # the CRC tier is part of the key: a validate_crc=True request
        # must never be served a dictionary a no-validation request
        # decoded without the integrity check it asked for
        return rg, col, f"host:v{1 if self.validate_crc else 0}"

    def _decode_dict_page(self, ps: PageSlice, buf: bytes, codec: int):
        # read-through seam: a dictionary this cache already decoded for
        # this (row group, column, CRC tier) of this file generation skips
        # the decompress + PLAIN decode entirely.  Decoded dictionaries are
        # shared read-only — every consumer below copies on take/index.
        ck = self._dict_cache_key()
        if ck is not None:
            hit = self.dict_cache.get(ck[0], ck[1], ck[2])
            if hit is not None:
                self.dictionary = hit
                return
        header = ps.header
        payload = buf[ps.payload_start : ps.payload_end]
        _check_crc(header, payload, self.validate_crc)
        self.alloc.register(header.uncompressed_page_size)
        raw = decompress_block(payload, codec, header.uncompressed_page_size)
        dh = header.dictionary_page_header
        enc = parse_encoding(dh.encoding, "dictionary page encoding")
        if enc not in (Encoding.PLAIN, Encoding.PLAIN_DICTIONARY):
            raise ParquetError(f"dictionary page encoding {enc.name} unsupported")
        count = dh.num_values or 0
        if count < 0:
            raise ParquetError(f"negative dictionary size {count}")
        self.dictionary = plain.decode(
            raw, self.leaf.physical_type, count, self.leaf.type_length
        )
        if ck is not None:
            d = self.dictionary
            nbytes = (int(d.offsets.nbytes + d.heap.nbytes)
                      if isinstance(d, ByteArrayData) else int(d.nbytes))
            self.dict_cache.put(ck[0], ck[1], ck[2], d, nbytes)

    def _decode_data_page_v1(self, ps: PageSlice, buf: bytes, codec: int):
        header = ps.header
        dh = header.data_page_header
        payload = buf[ps.payload_start : ps.payload_end]
        _check_crc(header, payload, self.validate_crc)
        self.alloc.register(header.uncompressed_page_size)
        raw = decompress_block(payload, codec, header.uncompressed_page_size)
        num_values = dh.num_values or 0
        if num_values < 0:
            raise ParquetError(f"negative page value count {num_values}")
        pos = 0
        max_rep, max_def = self.leaf.max_rep, self.leaf.max_def
        rlv = dlv = None
        if max_rep > 0:
            rlv, used = rle.decode_prefixed(
                raw[pos:], bitpack.bit_width(max_rep), num_values
            )
            pos += used
        if max_def > 0:
            dlv, used = rle.decode_prefixed(
                raw[pos:], bitpack.bit_width(max_def), num_values
            )
            pos += used
        # structural sanity tier (always on, O(1)): a level run table
        # yielding the wrong count means the page lies about itself
        if rlv is not None and len(rlv) != num_values:
            raise ParquetError(
                f"page declares {num_values} values, repetition levels "
                f"decode {len(rlv)}")
        if dlv is not None and len(dlv) != num_values:
            raise ParquetError(
                f"page declares {num_values} values, definition levels "
                f"decode {len(dlv)}")
        defined = int(np.count_nonzero(dlv == max_def)) if dlv is not None else num_values
        values = self._decode_values(dh.encoding, raw[pos:], defined)
        return values, dlv, rlv, num_values

    def _decode_data_page_v2(self, ps: PageSlice, buf: bytes, codec: int):
        header = ps.header
        dh = header.data_page_header_v2
        payload = buf[ps.payload_start : ps.payload_end]
        _check_crc(header, payload, self.validate_crc)
        num_values = dh.num_values or 0
        if num_values < 0:
            raise ParquetError(f"negative page value count {num_values}")
        rep_len = dh.repetition_levels_byte_length or 0
        def_len = dh.definition_levels_byte_length or 0
        if rep_len < 0 or def_len < 0 or rep_len + def_len > len(payload):
            raise ParquetError("v2 level lengths exceed page")
        max_rep, max_def = self.leaf.max_rep, self.leaf.max_def
        rlv = dlv = None
        if max_rep > 0:
            if rep_len == 0:
                raise ParquetError("v2 page missing repetition levels")
            rlv = rle.decode(
                payload[:rep_len], bitpack.bit_width(max_rep), num_values
            )
        if max_def > 0:
            dlv = rle.decode(
                payload[rep_len : rep_len + def_len],
                bitpack.bit_width(max_def),
                num_values,
            )
        if rlv is not None and len(rlv) != num_values:
            raise ParquetError(
                f"v2 page declares {num_values} values, repetition levels "
                f"decode {len(rlv)}")
        if dlv is not None and len(dlv) != num_values:
            raise ParquetError(
                f"v2 page declares {num_values} values, definition levels "
                f"decode {len(dlv)}")
        values_block = payload[rep_len + def_len :]
        uncompressed_values = (
            header.uncompressed_page_size - rep_len - def_len
        )
        self.alloc.register(max(uncompressed_values, 0))
        if dh.is_compressed is None or dh.is_compressed:
            raw = decompress_block(values_block, codec, uncompressed_values)
        else:
            raw = values_block
        if dh.num_nulls is not None and dlv is not None:
            declared_nulls = dh.num_nulls
            actual_nulls = int(np.count_nonzero(dlv != max_def))
            if declared_nulls != actual_nulls and max_rep == 0:
                raise ParquetError(
                    f"v2 page declares {declared_nulls} nulls, levels say {actual_nulls}"
                )
        defined = int(np.count_nonzero(dlv == max_def)) if dlv is not None else num_values
        values = self._decode_values(dh.encoding, raw, defined)
        return values, dlv, rlv, num_values

    # -- whole chunk -----------------------------------------------------------

    def decode(self, buf: bytes, codec: int, total_values: int) -> ColumnData:
        from .quarantine import error_context

        ctx = dict(self.context)
        chunk_offset = ctx.pop("chunk_offset", 0) or 0
        with error_context(**ctx):
            pages = walk_pages(buf, total_values)
        values_parts = []
        def_parts = []
        rep_parts = []
        slots = 0
        page_ordinal = 0  # data pages only (the quarantine record key)
        for ps in pages:
            pt = ps.header.type
            with error_context(
                    page=(page_ordinal if pt != PageType.DICTIONARY_PAGE
                          else None),
                    offset=chunk_offset + ps.payload_start, **ctx):
                if pt == PageType.DICTIONARY_PAGE:
                    self._decode_dict_page(ps, buf, codec)
                    continue
                if pt == PageType.DATA_PAGE:
                    v, d, r, n = self._decode_data_page_v1(ps, buf, codec)
                elif pt == PageType.DATA_PAGE_V2:
                    v, d, r, n = self._decode_data_page_v2(ps, buf, codec)
                else:
                    continue  # index/unknown pages: ignore
                page_ordinal += 1
            values_parts.append(v)
            slots += n
            if d is not None:
                def_parts.append(d)
            if r is not None:
                rep_parts.append(r)

        max_rep, max_def = self.leaf.max_rep, self.leaf.max_def
        values = _concat_values(values_parts)
        def_levels = (
            np.concatenate(def_parts).astype(np.int32) if def_parts else None
        )
        rep_levels = (
            np.concatenate(rep_parts).astype(np.int32) if rep_parts else None
        )
        with error_context(**ctx):
            if def_levels is not None and len(def_levels) != slots:
                raise ParquetError("definition level count mismatch")
            if rep_levels is not None and len(rep_levels) != slots:
                raise ParquetError("repetition level count mismatch")
        return ColumnData(
            values=values,
            def_levels=def_levels,
            rep_levels=rep_levels,
            max_def=max_def,
            max_rep=max_rep,
            num_leaf_slots=slots,
        )


def _concat_values(parts: list):
    if not parts:
        return np.zeros(0, dtype=np.int64)
    if len(parts) == 1:
        return parts[0]
    if isinstance(parts[0], ByteArrayData):
        offsets_parts = [parts[0].offsets]
        heap_parts = [parts[0].heap]
        base = int(parts[0].offsets[-1])
        for p in parts[1:]:
            offsets_parts.append(p.offsets[1:] + base)
            heap_parts.append(p.heap)
            base += int(p.offsets[-1])
        return ByteArrayData(
            offsets=np.concatenate(offsets_parts),
            heap=np.concatenate(heap_parts),
        )
    return np.concatenate(parts)


def validate_chunk_meta(chunk, leaf: SchemaNode):
    """Validate a ColumnChunk's embedded metadata; returns (md, start_offset).

    Mirrors readChunk's entry checks (chunk_reader.go:299-330): requires embedded
    ColumnMetaData (PARQUET-291: file_offset is unreliable), rejects external
    file_path chunks, verifies the physical type, and picks the dictionary page
    offset when present else the first data page.  Shared by the host and device
    chunk readers so both reject the same malformed files.
    """
    md = chunk.meta_data
    if md is None:
        raise ParquetError(
            "column chunk missing embedded metadata (external metadata unsupported)"
        )
    if chunk.file_path:
        raise ParquetError(
            f"column chunk data in external file {chunk.file_path!r} unsupported"
        )
    if md.type is not None and leaf.physical_type is not None:
        if md.type != int(leaf.physical_type):
            raise ParquetError(
                f"chunk type {md.type} does not match schema type {leaf.physical_type!r}"
            )
    if md.data_page_offset is None or md.data_page_offset < 0:
        raise ParquetError(f"invalid data page offset {md.data_page_offset}")
    offset = md.data_page_offset
    if md.dictionary_page_offset is not None and md.dictionary_page_offset >= 0:
        offset = min(offset, md.dictionary_page_offset)
    if md.total_compressed_size is None or md.total_compressed_size < 0:
        raise ParquetError(f"invalid chunk size {md.total_compressed_size}")
    if md.num_values is None or md.num_values < 0:
        raise ParquetError(f"invalid chunk value count {md.num_values}")
    return md, offset


def read_chunk(
    f: BinaryIO,
    chunk,
    leaf: SchemaNode,
    validate_crc: bool = False,
    alloc: Optional[AllocTracker] = None,
    context: Optional[dict] = None,
    dict_cache=None,
    meta: "Optional[tuple]" = None,
) -> ColumnData:
    """Read + decode one column chunk from an open file (readChunk parity).

    ``meta``: a pre-validated ``(md, offset)`` pair from
    :func:`validate_chunk_meta` (the scanplan chunk walk yields them) —
    callers that already walked the footer skip the second validation."""
    from .iostore import require_full
    from .quarantine import error_context

    md, offset = meta if meta is not None else validate_chunk_meta(chunk, leaf)
    size = md.total_compressed_size
    if alloc is not None:
        alloc.register(size)
    ctx = dict(context or {})
    ctx.setdefault("chunk_offset", offset)
    with error_context(offset=offset,
                       **{k: v for k, v in ctx.items()
                          if k != "chunk_offset"}):
        f.seek(offset)
        buf = f.read(size)
        require_full(buf, offset, size,
                     context=f"column {'.'.join(leaf.path)}")
    dec = ChunkDecoder(leaf, validate_crc=validate_crc, alloc=alloc,
                       context=ctx, dict_cache=dict_cache)
    return dec.decode(buf, md.codec, md.num_values)
