"""Record shredding: nested rows → per-leaf (values, def levels, rep levels).

Write-side Dremel, the inverse of assembly.py.  Equivalent of the reference's
recursiveAddColumnData/recursiveAddColumnNil (schema.go:837-891) + ColumnStore.add
(data_store.go:96-136), which walk one row at a time through interface dispatch;
here a row is shredded in one tree walk appending to per-leaf builders, and a
columnar fast path accepts whole arrays + validity masks without any per-row work.
"""

from __future__ import annotations

import datetime
from typing import Any, Optional

import numpy as np

from .column import ByteArrayData, ColumnData
from .footer import ParquetError
from .format import FieldRepetitionType as FRT, Type
from .logical import _is_list_node, _is_map_node, _repeated_group_is_element
from .schema.core import Schema, SchemaNode


class ShredError(ParquetError):
    pass


class LeafBuilder:
    """Accumulates one leaf column's slots across rows until a flush."""

    __slots__ = ("leaf", "values", "defs", "reps", "num_slots", "est_bytes")

    def __init__(self, leaf: SchemaNode):
        self.leaf = leaf
        self.values: list = []
        self.defs: list[int] = []
        self.reps: list[int] = []
        self.num_slots = 0
        self.est_bytes = 0

    def add_slot(self, d: int, r: int, value=None, present: bool = False):
        self.defs.append(d)
        self.reps.append(r)
        self.num_slots += 1
        if present:
            self.values.append(value)
            self.est_bytes += _value_size(self.leaf, value)
        self.est_bytes += 1  # levels

    def to_column_data(self) -> ColumnData:
        leaf = self.leaf
        vals = _coerce_values(self.values, leaf)
        defs = (
            np.asarray(self.defs, dtype=np.int32) if leaf.max_def > 0 else None
        )
        reps = (
            np.asarray(self.reps, dtype=np.int32) if leaf.max_rep > 0 else None
        )
        return ColumnData(
            values=vals,
            def_levels=defs,
            rep_levels=reps,
            max_def=leaf.max_def,
            max_rep=leaf.max_rep,
            num_leaf_slots=self.num_slots,
        )

    def reset(self):
        self.values = []
        self.defs = []
        self.reps = []
        self.num_slots = 0
        self.est_bytes = 0


def _value_size(leaf: SchemaNode, v) -> int:
    t = leaf.physical_type
    if t in (Type.INT32, Type.FLOAT):
        return 4
    if t in (Type.INT64, Type.DOUBLE):
        return 8
    if t == Type.INT96:
        return 12
    if t == Type.BOOLEAN:
        return 1
    try:
        return len(v) + 4
    except TypeError:
        return 8


def _coerce_leaf_value(v: Any, leaf: SchemaNode):
    """Validate/coerce one python value for a leaf (typedColumnStore.getValues
    parity — type errors raise rather than silently mangle)."""
    t = leaf.physical_type
    if t == Type.BOOLEAN:
        if not isinstance(v, (bool, np.bool_)):
            raise ShredError(f"column {leaf.flat_name()}: expected bool, got {type(v).__name__}")
        return bool(v)
    if t in (Type.INT32, Type.INT64):
        if isinstance(v, bool) or not isinstance(v, (int, np.integer)):
            raise ShredError(f"column {leaf.flat_name()}: expected int, got {type(v).__name__}")
        v = int(v)
        lim = 31 if t == Type.INT32 else 63
        if not -(1 << lim) <= v < (1 << lim):
            raise ShredError(f"column {leaf.flat_name()}: {v} out of {t.name} range")
        return v
    if t in (Type.FLOAT, Type.DOUBLE):
        if isinstance(v, (int, np.integer)) and not isinstance(v, bool):
            return float(v)
        if not isinstance(v, (float, np.floating)):
            raise ShredError(f"column {leaf.flat_name()}: expected float, got {type(v).__name__}")
        return float(v)
    if t == Type.INT96:
        if isinstance(v, (bytes, bytearray)) and len(v) == 12:
            return np.frombuffer(bytes(v), "<u4")
        arr = np.asarray(v)
        if arr.shape == (3,):
            return arr.astype("<u4")
        raise ShredError(f"column {leaf.flat_name()}: INT96 needs 12 bytes")
    if t in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
        if isinstance(v, str):
            v = v.encode("utf-8")
        elif isinstance(v, (bytes, bytearray, np.bytes_)):
            v = bytes(v)
        else:
            raise ShredError(
                f"column {leaf.flat_name()}: expected bytes/str, got {type(v).__name__}"
            )
        tl = leaf.type_length
        if t == Type.FIXED_LEN_BYTE_ARRAY and tl and len(v) != tl:
            raise ShredError(
                f"column {leaf.flat_name()}: FIXED[{tl}] got {len(v)} bytes"
            )
        return v
    raise ShredError(f"column {leaf.flat_name()}: unsupported type {t!r}")


def _coerce_values(vals: list, leaf: SchemaNode):
    t = leaf.physical_type
    if t == Type.INT32:
        return np.asarray(vals, dtype=np.int32)
    if t == Type.INT64:
        return np.asarray(vals, dtype=np.int64)
    if t == Type.FLOAT:
        return np.asarray(vals, dtype=np.float32)
    if t == Type.DOUBLE:
        return np.asarray(vals, dtype=np.float64)
    if t == Type.BOOLEAN:
        return np.asarray(vals, dtype=bool)
    if t == Type.INT96:
        if not vals:
            return np.zeros((0, 3), dtype="<u4")
        return np.stack(vals).astype("<u4")
    return ByteArrayData.from_list(vals)


class Shredder:
    """Shreds dict rows (raw physical shape or logical LIST/MAP shape) into
    per-leaf builders."""

    def __init__(self, schema: Schema):
        self.schema = schema
        self.builders = {l.path: LeafBuilder(l) for l in schema.leaves}
        self.num_rows = 0

    @property
    def est_bytes(self) -> int:
        return sum(b.est_bytes for b in self.builders.values())

    def add_row(self, row: dict) -> None:
        if not isinstance(row, dict):
            raise ShredError(f"row must be a dict, got {type(row).__name__}")
        self._shred_group(self.schema.root, row, 0, 0)
        self.num_rows += 1

    def _emit_empty(self, node: SchemaNode, d: int, r: int) -> None:
        """One null/absent slot for every leaf beneath node."""
        if node.is_leaf:
            self.builders[node.path].add_slot(d, r)
            return
        for c in node.children or []:
            self._emit_empty(c, d, r)

    def _shred_group(self, node: SchemaNode, value: dict, d: int, r: int) -> None:
        for child in node.children or []:
            v = value.get(child.name) if isinstance(value, dict) else None
            self._shred_node(child, v, d, r)

    def _shred_node(self, node: SchemaNode, value: Any, d: int, r: int) -> None:
        rep = node.repetition
        if rep == FRT.REPEATED:
            items = self._normalize_repeated(node, value)
            if not items:
                self._emit_empty(node, d, r)
                return
            for i, item in enumerate(items):
                ri = r if i == 0 else node.max_rep
                self._shred_instance(node, item, node.max_def, ri)
            return
        if value is None:
            if rep == FRT.REQUIRED:
                raise ShredError(
                    f"required column {node.flat_name() or node.name} is missing"
                )
            self._emit_empty(node, d, r)
            return
        nd = node.max_def if rep == FRT.OPTIONAL else d
        self._shred_instance(node, self._normalize_value(node, value), nd, r)

    def _shred_instance(self, node: SchemaNode, value: Any, d: int, r: int) -> None:
        if node.is_leaf:
            if value is None:
                # only reachable for a None element of a repeated leaf — the
                # format has no encoding for that (repeated == present)
                raise ShredError(
                    f"repeated column {node.flat_name()}: elements cannot be None"
                )
            cv = _coerce_leaf_value(value, node)
            self.builders[node.path].add_slot(d, r, cv, present=True)
            return
        if not isinstance(value, dict):
            raise ShredError(
                f"group {node.flat_name()}: expected dict, got {type(value).__name__}"
            )
        self._shred_group(node, value, d, r)

    # -- logical-shape acceptance (lists/dicts without physical wrappers) ------

    def _normalize_value(self, node: SchemaNode, value: Any) -> Any:
        """Accept logical python shapes for LIST/MAP columns: a plain list for a
        LIST group, a plain dict for a MAP group (mirrors what floor's
        marshalling does in the reference)."""
        if node.is_leaf or not isinstance(node.children, list) or not node.children:
            return value
        rep_group = node.children[0]
        if _is_list_node(node) and isinstance(value, list):
            if rep_group.is_leaf or _repeated_group_is_element(node.name, rep_group):
                return {rep_group.name: value}
            elem = rep_group.children[0]
            return {rep_group.name: [{elem.name: v} for v in value]}
        if _is_map_node(node) and isinstance(value, dict) and not (
            len(node.children) == 1
            and isinstance(value, dict)
            and set(value) <= {rep_group.name}
        ):
            kv = rep_group
            return {
                kv.name: [{"key": k, "value": v} for k, v in value.items()]
            }
        return value

    def _normalize_repeated(self, node: SchemaNode, value: Any) -> list:
        if value is None:
            return []
        if isinstance(value, list):
            return value
        if isinstance(value, np.ndarray):
            return value.tolist()
        raise ShredError(
            f"repeated column {node.flat_name()}: expected list, got {type(value).__name__}"
        )

    # -- output ---------------------------------------------------------------

    def harvest(self) -> tuple[dict[str, ColumnData], int]:
        """Returns (columns, row_count) and resets the builders."""
        out = {
            ".".join(path): b.to_column_data()
            for path, b in self.builders.items()
        }
        for b in self.builders.values():
            b.reset()
        n = self.num_rows
        self.num_rows = 0
        return out, n
