"""tpu-parquet: a TPU-native Apache Parquet framework.

A from-scratch, columnar, batch-oriented reimplementation of the capabilities of the
pure-Go reference (fraugster/parquet-go — see SURVEY.md): full Parquet read/write
(all 8 physical types, PLAIN / RLE-hybrid / dictionary / delta encodings, SNAPPY /
GZIP / ZSTD codecs, data pages v1+v2, CRC32, statistics, nested LIST/MAP schemas),
a textual schema-definition DSL, high-level object marshalling, and CLI tools —
with the hot decode paths running as vectorized JAX/XLA kernels on TPU and row
groups sharded across device meshes.
"""

__version__ = "0.1.0"

from .footer import ParquetError, read_file_metadata
from .format import (
    CompressionCodec,
    ConvertedType,
    Encoding,
    FieldRepetitionType,
    FileMetaData,
    LogicalType,
    PageType,
    SchemaElement,
    Type,
)

__all__ = [
    "ParquetError",
    "read_file_metadata",
    "FileMetaData",
    "SchemaElement",
    "Type",
    "ConvertedType",
    "LogicalType",
    "FieldRepetitionType",
    "Encoding",
    "CompressionCodec",
    "PageType",
]
