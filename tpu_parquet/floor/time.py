"""Time-of-day value type.

Equivalent of the reference's floor.Time (floor/time.go:10-146): nanoseconds since
midnight with an adjusted-to-UTC flag, converting to/from the TIME logical type's
MILLIS/MICROS/NANOS representations.  Interoperates with datetime.time.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass


def parse_iso_datetime(s: str) -> datetime.datetime:
    """ISO-8601 string → datetime, accepting a trailing 'Z' (shared by the
    floor marshaller and csv2parquet — one timestamp-string parser)."""
    return datetime.datetime.fromisoformat(s.strip().replace("Z", "+00:00"))


@dataclass(frozen=True, order=True)
class Time:
    nanoseconds: int  # since midnight
    utc: bool = True

    def __post_init__(self):
        if not 0 <= self.nanoseconds < 86_400_000_000_000:
            raise ValueError(f"time of day out of range: {self.nanoseconds}ns")

    # -- constructors (floor/time.go NewTime/TimeFrom* parity) ----------------

    @classmethod
    def from_parts(cls, hour: int, minute: int, second: int = 0, ns: int = 0,
                   utc: bool = True) -> "Time":
        if not (0 <= hour < 24 and 0 <= minute < 60 and 0 <= second < 60
                and 0 <= ns < 1_000_000_000):
            raise ValueError(f"invalid time {hour}:{minute}:{second}.{ns}")
        return cls(((hour * 60 + minute) * 60 + second) * 1_000_000_000 + ns, utc)

    @classmethod
    def from_nanoseconds(cls, ns: int, utc: bool = True) -> "Time":
        return cls(ns, utc)

    @classmethod
    def from_microseconds(cls, us: int, utc: bool = True) -> "Time":
        return cls(us * 1000, utc)

    @classmethod
    def from_milliseconds(cls, ms: int, utc: bool = True) -> "Time":
        return cls(ms * 1_000_000, utc)

    @classmethod
    def from_datetime_time(cls, t: datetime.time) -> "Time":
        return cls.from_parts(t.hour, t.minute, t.second, t.microsecond * 1000,
                              utc=t.tzinfo is not None)

    # -- accessors -------------------------------------------------------------

    @property
    def hour(self) -> int:
        return self.nanoseconds // 3_600_000_000_000

    @property
    def minute(self) -> int:
        return (self.nanoseconds // 60_000_000_000) % 60

    @property
    def second(self) -> int:
        return (self.nanoseconds // 1_000_000_000) % 60

    @property
    def nanosecond(self) -> int:
        return self.nanoseconds % 1_000_000_000

    def milliseconds(self) -> int:
        return self.nanoseconds // 1_000_000

    def microseconds(self) -> int:
        return self.nanoseconds // 1000

    def to_datetime_time(self) -> datetime.time:
        return datetime.time(
            self.hour, self.minute, self.second, self.nanosecond // 1000,
            tzinfo=datetime.timezone.utc if self.utc else None,
        )

    def __str__(self):
        base = f"{self.hour:02d}:{self.minute:02d}:{self.second:02d}"
        if self.nanosecond:
            base += f".{self.nanosecond:09d}".rstrip("0")
        return base + ("Z" if self.utc else "")
