"""Logical-type value conversion for the high-level object API.

Equivalent of the conversion logic inside the reference's floor reflection
marshaller/unmarshaller (floor/writer.go:81-454 decodeValue/decodeMap/...,
floor/reader.go:120-448 fillValue/fillMap/...): python-typed values
(datetime, date, time, uuid.UUID, Decimal, str) ⇄ physical parquet values,
driven by each leaf's logical/converted type annotations.
"""

from __future__ import annotations

import datetime
import decimal
import functools
import uuid as uuid_mod
from typing import Any, Callable, Optional

from ..footer import ParquetError
from ..format import ConvertedType, Type
from ..int96 import datetime_to_int96, int96_to_datetime
from ..schema.core import SchemaNode
from .time import Time, parse_iso_datetime

_EPOCH_DATE = datetime.date(1970, 1, 1)
_UTC = datetime.timezone.utc
_EPOCH_DT = datetime.datetime(1970, 1, 1, tzinfo=_UTC)


def _datetime_to_epoch_ns(v: datetime.datetime) -> int:
    """Exact integer epoch-nanoseconds (timedelta arithmetic — no float
    truncation, correct for pre-epoch times)."""
    if v.tzinfo is None:
        v = v.replace(tzinfo=_UTC)
    delta = v - _EPOCH_DT
    return (delta.days * 86_400 + delta.seconds) * 1_000_000_000 + delta.microseconds * 1000


class MarshalError(ParquetError):
    pass


def _ts_unit_ns(leaf: SchemaNode) -> Optional[int]:
    """ns per tick for TIMESTAMP leaves, None if not a timestamp."""
    lt = leaf.logical_type
    if lt is not None and lt.TIMESTAMP is not None:
        u = lt.TIMESTAMP.unit.which()
        return {"MILLIS": 1_000_000, "MICROS": 1_000, "NANOS": 1}[u]
    conv = leaf.converted_type
    if conv == ConvertedType.TIMESTAMP_MILLIS:
        return 1_000_000
    if conv == ConvertedType.TIMESTAMP_MICROS:
        return 1_000
    return None


def _time_unit_ns(leaf: SchemaNode) -> Optional[int]:
    lt = leaf.logical_type
    if lt is not None and lt.TIME is not None:
        u = lt.TIME.unit.which()
        return {"MILLIS": 1_000_000, "MICROS": 1_000, "NANOS": 1}[u]
    conv = leaf.converted_type
    if conv == ConvertedType.TIME_MILLIS:
        return 1_000_000
    if conv == ConvertedType.TIME_MICROS:
        return 1_000
    return None


def _is_utc(leaf: SchemaNode, which: str) -> bool:
    lt = leaf.logical_type
    if lt is None:
        return True
    member = getattr(lt, which, None)
    return bool(member.isAdjustedToUTC) if member is not None else True


def _is_date(leaf) -> bool:
    lt = leaf.logical_type
    return (lt is not None and lt.DATE is not None) or (
        leaf.converted_type == ConvertedType.DATE
    )


def _is_uuid(leaf) -> bool:
    lt = leaf.logical_type
    return lt is not None and lt.UUID is not None


def _is_decimal(leaf) -> bool:
    lt = leaf.logical_type
    return (lt is not None and lt.DECIMAL is not None) or (
        leaf.converted_type == ConvertedType.DECIMAL
    )


def _decimal_scale(leaf) -> int:
    lt = leaf.logical_type
    if lt is not None and lt.DECIMAL is not None:
        return lt.DECIMAL.scale or 0
    return leaf.element.scale or 0


# ---------------------------------------------------------------------------
# python → physical (write side)
# ---------------------------------------------------------------------------

def _parse_time_string(v: str) -> datetime.datetime:
    """Best-effort string → datetime (floor/writer.go:256 dateparse.ParseAny
    parity, scoped to ISO-8601 and unix-time digit strings)."""
    s = v.strip()
    try:
        body = s[1:] if s.startswith("-") else s
        if body.isdigit():
            return _unix_heuristic_dt(int(s))
        dt = parse_iso_datetime(s)
    except (ValueError, MarshalError) as e:
        raise MarshalError(f"cannot parse {v!r} as a timestamp") from e
    return dt if dt.tzinfo else dt.replace(tzinfo=_UTC)


@functools.lru_cache(maxsize=1)
def _unix_digit_refs() -> tuple:
    """Digit counts of 'now' per unit, cached per process (the counts next
    change in 2033 — per-value now() calls would dominate bulk writes)."""
    now_s = int(datetime.datetime.now(tz=_UTC).timestamp())
    return tuple(
        (ns_per_tick, len(str(now_s * mult)))
        for ns_per_tick, mult in (
            (1_000_000_000, 1), (1_000_000, 1_000),
            (1_000, 1_000_000), (1, 1_000_000_000),
        )
    )


def _unix_heuristic_dt(i: int) -> datetime.datetime:
    """Digit-count unix-time interpretation — seconds, then millis, micros,
    nanos.  Exact decodeUnixTime parity (floor/writer.go:317-340): the
    reference compares DIGIT COUNTS against now's per-unit digit counts
    ('since 99% of the time these are timestamps and are <= now this is a
    fairly safe bet' — its words), not magnitudes."""
    digits = len(str(abs(i))) if i else 1
    for ns_per_tick, ref_digits in _unix_digit_refs():
        if digits <= ref_digits:
            return _EPOCH_DT + datetime.timedelta(
                microseconds=i * ns_per_tick // 1_000
            )
    raise MarshalError(f"INT96 value {i} is not a plausible unix time")


def to_physical(leaf: SchemaNode, v: Any) -> Any:
    if v is None:
        return None
    t = leaf.physical_type

    unit = _ts_unit_ns(leaf)
    if unit is not None and isinstance(v, str):
        v = _parse_time_string(v)
    if unit is not None and isinstance(v, datetime.datetime):
        return _datetime_to_epoch_ns(v) // unit
    if t == Type.INT96:
        if isinstance(v, str):
            v = _parse_time_string(v)
        elif isinstance(v, int) and not isinstance(v, bool):
            v = _unix_heuristic_dt(v)
        if isinstance(v, datetime.datetime):
            return datetime_to_int96(v)
    if _is_date(leaf) and isinstance(v, datetime.date) and not isinstance(
        v, datetime.datetime
    ):
        return (v - _EPOCH_DATE).days
    tunit = _time_unit_ns(leaf)
    if tunit is not None:
        if isinstance(v, datetime.time):
            v = Time.from_datetime_time(v)
        if isinstance(v, Time):
            return v.nanoseconds // tunit
    if _is_uuid(leaf):
        if isinstance(v, uuid_mod.UUID):
            return v.bytes
        if isinstance(v, (bytes, bytearray)) and len(v) == 16:
            return bytes(v)
        raise MarshalError(f"column {leaf.flat_name()}: UUID needs uuid or 16 bytes")
    if _is_decimal(leaf) and isinstance(v, decimal.Decimal):
        scale = _decimal_scale(leaf)
        unscaled = int(v.scaleb(scale).to_integral_value(decimal.ROUND_HALF_EVEN))
        if t in (Type.INT32, Type.INT64):
            return unscaled
        if t in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
            length = leaf.type_length if t == Type.FIXED_LEN_BYTE_ARRAY else max(
                (unscaled.bit_length() + 8) // 8, 1
            )
            return unscaled.to_bytes(length, "big", signed=True)
    return v


# ---------------------------------------------------------------------------
# physical → python (read side)
# ---------------------------------------------------------------------------

def from_physical(leaf: SchemaNode, v: Any) -> Any:
    if v is None:
        return None
    t = leaf.physical_type

    unit = _ts_unit_ns(leaf)
    if unit is not None and isinstance(v, int):
        ns = v * unit
        dt = datetime.datetime.fromtimestamp(ns // 1_000_000_000, tz=_UTC)
        dt = dt.replace(microsecond=(ns // 1000) % 1_000_000)
        if not _is_utc(leaf, "TIMESTAMP"):
            dt = dt.replace(tzinfo=None)
        return dt
    if t == Type.INT96:
        if isinstance(v, (bytes, bytearray)):
            return int96_to_datetime(v)
        if isinstance(v, (list, tuple)) and len(v) == 3:
            # row assembly materializes INT96 as [lo, hi, julian_day] uint32s
            return int96_to_datetime(
                b"".join(int(x).to_bytes(4, "little") for x in v)
            )
    if _is_date(leaf) and isinstance(v, int):
        return _EPOCH_DATE + datetime.timedelta(days=v)
    tunit = _time_unit_ns(leaf)
    if tunit is not None and isinstance(v, int):
        return Time(v * tunit, utc=_is_utc(leaf, "TIME"))
    if _is_uuid(leaf) and isinstance(v, (bytes, bytearray)):
        return uuid_mod.UUID(bytes=bytes(v))
    if _is_decimal(leaf):
        scale = _decimal_scale(leaf)
        if isinstance(v, int):
            return decimal.Decimal(v).scaleb(-scale)
        if isinstance(v, (bytes, bytearray)):
            unscaled = int.from_bytes(bytes(v), "big", signed=True)
            return decimal.Decimal(unscaled).scaleb(-scale)
    return v


# ---------------------------------------------------------------------------
# recursive row conversion along the schema (logical shapes)
# ---------------------------------------------------------------------------

def convert_row(node: SchemaNode, row: dict, fn: Callable) -> dict:
    """Apply fn(leaf, value) to every leaf of a logical-shape row."""
    out = {}
    for child in node.children or []:
        name = child.name
        if not isinstance(row, dict) or name not in row:
            continue
        out[name] = _convert_value(child, row[name], fn)
    return out


def _convert_value(node: SchemaNode, v: Any, fn: Callable) -> Any:
    if v is None:
        return None
    from ..logical import _is_list_node, _is_map_node

    if node.is_leaf:
        return fn(node, v)
    if _is_list_node(node) and isinstance(v, list) and node.children:
        from ..logical import _repeated_group_is_element

        rep = node.children[0]
        if not rep.is_leaf and _repeated_group_is_element(node.name, rep):
            # legacy 2-level list: the repeated group IS the element struct
            return [_convert_value_instance(rep, item, fn) for item in v]
        elem = rep.children[0] if (not rep.is_leaf and rep.children) else rep
        return [_convert_value(elem, item, fn) for item in v]
    if _is_map_node(node) and isinstance(v, dict) and node.children:
        kv = node.children[0]
        key_node = kv.child("key") if not kv.is_leaf else None
        val_node = kv.child("value") if not kv.is_leaf else None
        return {
            (_convert_value(key_node, k, fn) if key_node else k):
            (_convert_value(val_node, w, fn) if val_node else w)
            for k, w in v.items()
        }
    if node.repetition.name == "REPEATED" and isinstance(v, list):
        return [_convert_value_instance(node, item, fn) for item in v]
    return _convert_value_instance(node, v, fn)


def _convert_value_instance(node: SchemaNode, v: Any, fn: Callable) -> Any:
    if node.is_leaf:
        return fn(node, v)
    if isinstance(v, dict):
        return convert_row(node, v, fn)
    return v
