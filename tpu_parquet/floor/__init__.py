"""floor: the high-level object API.

Equivalent of the reference's floor package (floor/reader.go, floor/writer.go,
floor/interfaces/): read and write typed Python objects — dataclasses or dicts —
with logical-type conversion (datetime ⇄ TIMESTAMP, date ⇄ DATE, Time ⇄ TIME,
uuid ⇄ FIXED(16), Decimal ⇄ DECIMAL, INT96 julian timestamps) layered on the
low-level FileReader/FileWriter.

Custom marshalling hooks mirror the Marshaller/Unmarshaller interfaces
(floor/interfaces/marshaller.go:7-9, unmarshaller.go:15-17): an object with a
``to_parquet_row()`` method controls its own encoding; a class with a
``from_parquet_row(row)`` classmethod controls decoding.
"""

from __future__ import annotations

import dataclasses
import os
import typing
from typing import Any, Iterable, Optional, Type as PyType, Union

from ..footer import ParquetError
from ..logical import unwrap_row
from ..reader import FileReader
from ..schema.autoschema import schema_from_type
from ..schema.core import Schema
from ..writer import FileWriter
from .marshal import MarshalError, convert_row, from_physical, to_physical
from .time import Time

__all__ = ["Reader", "Writer", "Time", "MarshalError", "open_reader", "open_writer"]


class Writer:
    """High-level writer (floor.Writer parity: NewFileWriter + Write,
    floor/writer.go:20-70)."""

    def __init__(self, sink, schema: Optional[Schema] = None,
                 obj_type: Optional[PyType] = None, **writer_options):
        if schema is None:
            if obj_type is None:
                raise ParquetError("floor.Writer needs a schema or an obj_type")
            schema = schema_from_type(obj_type)
        self.schema = schema
        self._w = FileWriter(sink, schema, **writer_options)

    def write(self, obj: Any) -> None:
        """Write one object: Marshaller hook, dataclass, or dict."""
        if hasattr(obj, "to_parquet_row"):
            row = obj.to_parquet_row()
        elif dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            row = _dataclass_to_row(obj)
        elif isinstance(obj, dict):
            row = obj
        else:
            raise MarshalError(
                f"cannot marshal {type(obj).__name__}: expected dataclass, dict, "
                f"or an object with to_parquet_row()"
            )
        physical = convert_row(self.schema.root, row, to_physical)
        self._w.write_row(physical)

    def write_many(self, objs: Iterable[Any]) -> None:
        for o in objs:
            self.write(o)

    def flush_row_group(self, **kw) -> None:
        self._w.flush_row_group(**kw)

    def close(self) -> None:
        self._w.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.close()
        return False


class Reader:
    """High-level reader (floor.Reader parity: Next/Scan, floor/reader.go:18-94)."""

    def __init__(self, source, obj_type: Optional[PyType] = None, **reader_options):
        self._r = FileReader(source, **reader_options)
        self.schema = self._r.schema
        self.obj_type = obj_type
        self._iter = None

    # iterator of converted logical rows
    def __iter__(self):
        for raw in self._r.iter_rows():
            logical = unwrap_row(self.schema, raw)
            yield convert_row(self.schema.root, logical, from_physical)

    def scan_all(self, obj_type: Optional[PyType] = None) -> list:
        """All rows as obj_type instances (Scan parity)."""
        cls = obj_type or self.obj_type
        return [self._construct(cls, row) for row in self]

    def _construct(self, cls, row: dict):
        if cls is None or cls is dict:
            return row
        if hasattr(cls, "from_parquet_row"):
            return cls.from_parquet_row(row)
        if dataclasses.is_dataclass(cls):
            return _row_to_dataclass(cls, row)
        raise MarshalError(
            f"cannot unmarshal into {cls!r}: expected dataclass, dict, or a class "
            f"with from_parquet_row()"
        )

    def close(self):
        self._r.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def num_rows(self) -> int:
        return self._r.num_rows

    @property
    def metadata(self):
        return self._r.metadata


def _dataclass_to_row(obj) -> dict:
    """Shallow per-field conversion (field names lowercased like floor's
    fieldNameFunc unless the dataclass declares metadata={'parquet': name})."""
    out = {}
    for f in dataclasses.fields(obj):
        name = f.metadata.get("parquet", f.name.lower())
        v = getattr(obj, f.name)
        out[name] = _obj_to_plain(v)
    return out


def _obj_to_plain(v):
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return _dataclass_to_row(v)
    if isinstance(v, list):
        return [_obj_to_plain(x) for x in v]
    if isinstance(v, dict):
        return {k: _obj_to_plain(x) for k, x in v.items()}
    return v


def _row_to_dataclass(cls, row: dict):
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        name = f.metadata.get("parquet", f.name.lower())
        if name not in row:
            continue
        v = row[name]
        hint = hints.get(f.name)
        kwargs[f.name] = _plain_to_obj(hint, v)
    return cls(**kwargs)


def _plain_to_obj(hint, v):
    if v is None:
        return None
    origin = typing.get_origin(hint)
    if origin is typing.Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if len(args) == 1:
            return _plain_to_obj(args[0], v)
        return v
    if origin in (list, typing.List) and isinstance(v, list):
        (elem,) = typing.get_args(hint) or (None,)
        return [_plain_to_obj(elem, x) for x in v]
    if origin in (dict, typing.Dict) and isinstance(v, dict):
        args = typing.get_args(hint) or (None, None)
        return {_plain_to_obj(args[0], k): _plain_to_obj(args[1], x) for k, x in v.items()}
    if hint is not None and dataclasses.is_dataclass(hint) and isinstance(v, dict):
        return _row_to_dataclass(hint, v)
    import datetime as _dt

    if hint is _dt.time and isinstance(v, Time):
        return v.to_datetime_time()
    return v


def open_reader(source, obj_type=None, **kw) -> Reader:
    """NewFileReader parity."""
    return Reader(source, obj_type=obj_type, **kw)


def open_writer(sink, schema=None, obj_type=None, **kw) -> Writer:
    """NewFileWriter parity."""
    return Writer(sink, schema=schema, obj_type=obj_type, **kw)
