"""Schema-guided nested row construction/access without defining a class.

The builder-style object API of the reference's floor layer
(floor/interfaces/marshaller.go:7-208 ``MarshalObject``/``MarshalElement``/
``MarshalList``/``MarshalMap``; unmarshaller.go:15-310 for the read side):
programmatic construction and traversal of nested parquet rows guided by the
schema, covering shapes Python dicts alone get wrong — the LIST wrapper
(``{"list": [{"element": v}]}``), its Athena compatibility naming
(``bag``/``array_element``, the marshaller.go:100-109 special case), and the
MAP ``key_value`` pair groups.

Pythonic surface instead of the Go interface pair: ``RowBuilder`` produces
the raw row dict a ``FileWriter.write_row`` expects; ``RowView`` wraps a raw
row from ``FileReader.iter_rows`` with field access that raises
``FieldNotPresent`` (unmarshaller.go's ``ErrFieldNotPresent``) instead of
silently yielding None.

    b = RowBuilder(schema)
    b.field("name").group().field("first").set(b"Hans")
    lst = b.field("tags").list()
    lst.add().set(b"a"); lst.add().set(b"b")
    m = b.field("attrs").map()
    k, v = m.add(); k.set(b"k"); v.set(1)
    writer.write_row(b.data)

    view = RowView(row, schema)
    view.field("name").group().field("first").bytes()   # b"Hans"
    [e.bytes() for e in view.field("tags").list()]
    {k.bytes(): v.int64() for k, v in view.field("attrs").map()}
"""

from __future__ import annotations

from typing import Any, Optional

from ..footer import ParquetError
from ..schema.core import SchemaNode


class FieldNotPresent(ParquetError, KeyError):
    """Requested field is absent from the row (ErrFieldNotPresent parity)."""


def _child(node: Optional[SchemaNode], name: str) -> Optional[SchemaNode]:
    for c in (node.children or ()) if node is not None else ():
        if c.name == name:
            return c
    return None


def _list_names(node: Optional[SchemaNode]) -> tuple[str, str]:
    """(wrapper, element) names for a LIST group under ``node`` — standard
    ``list``/``element`` unless the schema uses the Athena ``bag``/
    ``array_element`` shape (marshaller.go:100-109)."""
    if _child(node, "bag") is not None:
        return "bag", "array_element"
    return "list", "element"


class RowBuilder:
    """Builds the raw nested row dict for ``FileWriter.write_row``."""

    def __init__(self, schema: Optional[SchemaNode] = None,
                 _data: Optional[dict] = None):
        self._node = schema
        self._data = {} if _data is None else _data

    @property
    def data(self) -> dict:
        """The built raw row (live — further field() calls keep mutating)."""
        return self._data

    def field(self, name: str) -> "ElementBuilder":
        return ElementBuilder(self._data, name, _child(self._node, name))


class ElementBuilder:
    def __init__(self, data: dict, name: str, node: Optional[SchemaNode]):
        self._data = data
        self._name = name
        self._node = node

    def set(self, value: Any) -> None:
        """Scalar value (int/float/bool/bytes/str — whatever the writer's
        marshal layer accepts for the leaf)."""
        self._data[self._name] = value

    def group(self) -> RowBuilder:
        obj = self._data.setdefault(self._name, {})
        return RowBuilder(self._node, _data=obj)

    def list(self) -> "ListBuilder":
        wrapper, elem = _list_names(self._node)
        lst = self._data.setdefault(self._name, {}).setdefault(wrapper, [])
        rep = _child(self._node, wrapper)
        return ListBuilder(lst, elem, _child(rep, elem))

    def map(self) -> "MapBuilder":
        pairs = self._data.setdefault(self._name, {}).setdefault(
            "key_value", [])
        return MapBuilder(pairs, _child(self._node, "key_value"))


class ListBuilder:
    def __init__(self, items: list, elem_name: str,
                 node: Optional[SchemaNode]):
        self._items = items
        self._elem = elem_name
        self._node = node

    def add(self) -> ElementBuilder:
        entry: dict = {}
        self._items.append(entry)
        return ElementBuilder(entry, self._elem, self._node)


class MapBuilder:
    def __init__(self, pairs: list, node: Optional[SchemaNode]):
        self._pairs = pairs
        self._node = node

    def add(self) -> tuple[ElementBuilder, ElementBuilder]:
        entry: dict = {}
        self._pairs.append(entry)
        return (ElementBuilder(entry, "key", _child(self._node, "key")),
                ElementBuilder(entry, "value", _child(self._node, "value")))


# ---------------------------------------------------------------------------
# read side (unmarshaller.go parity)
# ---------------------------------------------------------------------------


class RowView:
    """Typed access into a raw row dict from ``FileReader.iter_rows``."""

    def __init__(self, row: dict, schema: Optional[SchemaNode] = None):
        self._row = row
        self._node = schema

    @property
    def data(self) -> dict:
        return self._row

    def field(self, name: str) -> "ElementView":
        if name not in self._row:
            raise FieldNotPresent(name)
        return ElementView(self._row[name], _child(self._node, name), name)


class ElementView:
    def __init__(self, value: Any, node: Optional[SchemaNode], name: str):
        self._v = value
        self._node = node
        self._name = name

    def value(self) -> Any:
        return self._v

    def _typed(self, types, what: str):
        if not isinstance(self._v, types):
            raise ParquetError(
                f"field {self._name!r} is {type(self._v).__name__}, "
                f"not {what}")
        return self._v

    def int32(self) -> int:
        return int(self._typed((int,), "an int"))

    def int64(self) -> int:
        return int(self._typed((int,), "an int"))

    def float32(self) -> float:
        return float(self._typed((int, float), "a float"))

    def float64(self) -> float:
        return float(self._typed((int, float), "a float"))

    def bool(self) -> bool:
        return self._typed((bool,), "a bool")

    def bytes(self) -> bytes:
        v = self._typed((bytes, bytearray, str), "a byte array")
        return v.encode() if isinstance(v, str) else bytes(v)

    def group(self) -> RowView:
        return RowView(self._typed((dict,), "a group"), self._node)

    def list(self):
        """Iterate element views of a LIST field (either naming shape)."""
        d = self._typed((dict,), "a LIST group")
        wrapper, elem = _list_names(self._node)
        if wrapper not in d and "list" in d:
            wrapper, elem = "list", "element"
        items = d.get(wrapper)
        if items is None:
            raise ParquetError(f"field {self._name!r} is not a LIST group")
        rep = _child(self._node, wrapper)
        node = _child(rep, elem)
        for entry in items:
            if elem not in entry:
                raise FieldNotPresent(f"{self._name}.{elem}")
            yield ElementView(entry[elem], node, elem)

    def map(self):
        """Iterate (key_view, value_view) pairs of a MAP field."""
        d = self._typed((dict,), "a MAP group")
        pairs = d.get("key_value")
        if pairs is None:
            raise ParquetError(f"field {self._name!r} is not a MAP group")
        kv = _child(self._node, "key_value")
        kn, vn = _child(kv, "key"), _child(kv, "value")
        for entry in pairs:
            if "key" not in entry:
                raise FieldNotPresent(f"{self._name}.key")
            yield (ElementView(entry["key"], kn, "key"),
                   ElementView(entry.get("value"), vn, "value"))
