"""Columnar data containers.

The reference is row-oriented and boxes every value (`[]interface{}`, data_store.go);
this framework keeps decoded data columnar: fixed-width columns are flat numpy/jax
arrays, variable-length BYTE_ARRAY columns are an (offsets, heap) pair — the
ragged-on-TPU representation SURVEY.md §7.4.2 calls for.  Nulls and nesting are
carried as definition/repetition level arrays next to the dense values.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np


@dataclass
class ByteArrayData:
    """Ragged bytes: value i is heap[offsets[i]:offsets[i+1]].

    ``offsets`` has length n+1, dtype int64; ``heap`` is a flat uint8 buffer.
    """

    offsets: np.ndarray
    heap: np.ndarray

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, i: int) -> bytes:
        return self.heap[self.offsets[i] : self.offsets[i + 1]].tobytes()

    def to_list(self) -> list:
        off = self.offsets
        heap = self.heap.tobytes()
        return [heap[off[i] : off[i + 1]] for i in range(len(self))]

    @classmethod
    def from_list(cls, items: list) -> "ByteArrayData":
        lens = np.fromiter((len(x) for x in items), dtype=np.int64, count=len(items))
        offsets = np.zeros(len(items) + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        heap = np.frombuffer(b"".join(bytes(x) for x in items), dtype=np.uint8)
        return cls(offsets=offsets, heap=heap)

    def take(self, indices: np.ndarray) -> "ByteArrayData":
        """Gather rows by index (dictionary expansion)."""
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        lens = self.offsets[1:] - self.offsets[:-1]
        sel_lens = lens[idx]
        new_off = np.zeros(len(idx) + 1, dtype=np.int64)
        np.cumsum(sel_lens, out=new_off[1:])
        total = int(new_off[-1])
        starts = self.offsets[idx]
        if total == 0:
            return ByteArrayData(new_off, np.zeros(0, dtype=np.uint8))
        # native memcpy-per-row gather: the single hottest host-decode
        # transform on dictionary-string files, and — unlike the numpy
        # repeat+arange formulation below — it releases the GIL, so the
        # prefetch pipeline's worker threads overlap through it
        from . import native

        if int(idx.min()) >= 0:  # negative (python-wrap) indices: numpy path
            off = np.ascontiguousarray(self.offsets, dtype=np.int64)
            heap = np.ascontiguousarray(self.heap)
            out_heap = np.empty(total, dtype=np.uint8)
            if native.ragged_take(off, heap, idx, new_off, out_heap):
                return ByteArrayData(new_off, out_heap)
        # numpy fallback: position j in output belongs to row
        # r = searchsorted(new_off, j, 'right')-1, via repeat + arange
        reps = sel_lens
        row_of = np.repeat(np.arange(len(idx), dtype=np.int64), reps)
        within = np.arange(total, dtype=np.int64) - np.repeat(new_off[:-1], reps)
        src = starts[row_of] + within
        return ByteArrayData(new_off, self.heap[src])

    def __eq__(self, other):
        if not isinstance(other, ByteArrayData):
            return NotImplemented
        return np.array_equal(self.offsets, other.offsets) and np.array_equal(
            self.heap, other.heap
        )


@dataclass
class ColumnData:
    """One column chunk's decoded leaf data (dense values + levels).

    ``values`` holds only the *defined* leaf values (len = number of slots whose
    def level == max_def); ``def_levels``/``rep_levels`` have one entry per leaf
    slot (len = num_values from the page headers).  For flat required columns the
    level arrays are None and values are one-per-row.
    """

    values: "np.ndarray | ByteArrayData"
    def_levels: Optional[np.ndarray] = None
    rep_levels: Optional[np.ndarray] = None
    max_def: int = 0
    max_rep: int = 0
    num_leaf_slots: int = 0  # total slots including nulls/empties

    def __post_init__(self):
        if self.num_leaf_slots == 0:
            self.num_leaf_slots = (
                len(self.def_levels) if self.def_levels is not None else len(self.values)
            )

    @property
    def num_defined(self) -> int:
        return len(self.values)

    def validity(self) -> np.ndarray:
        """Boolean mask over leaf slots: slot holds a real value."""
        if self.def_levels is None:
            return np.ones(self.num_leaf_slots, dtype=bool)
        return self.def_levels == self.max_def
