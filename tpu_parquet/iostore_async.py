"""Async fetch engine: hundreds of in-flight ranges without hundreds of threads.

The thread-per-range prefetch pool is the scaling ceiling for high-latency
object stores (ROADMAP direction 3): every in-flight range costs an OS
thread, and ``prefetch_map`` caps its pool at the machine's cores — so a
cloud-scale scan that wants *hundreds* of overlapped 50ms fetches gets a
handful.  This module multiplexes them all on ONE event-loop thread:

- :class:`FetchEngine` — a daemon thread (``tpq-fetch``) running an asyncio
  loop.  ``submit(store, offset, size, scan=...)`` returns a
  ``concurrent.futures.Future`` immediately; the loop drives up to
  ``TPQ_IO_INFLIGHT`` (default 256) concurrent fetches, each one the FULL
  :meth:`~tpu_parquet.iostore.GenericRangeStore.read_range` discipline
  reimplemented as a coroutine — per-request deadlines, bounded retries
  with decorrelated-jitter backoff spending the per-scan
  :class:`~tpu_parquet.iostore.RetryBudget`, short/torn-read detection
  with verified re-reads, EOF classification, and tail-latency hedging
  (``TPQ_IO_HEDGE_MS``/auto p90, first success wins, losers reaped and
  accounted) — bit-identical behavior on every store counter and error
  message, asserted by the fault-matrix tests.
- Stores opt in with one coroutine:
  :meth:`~tpu_parquet.iostore.GenericRangeStore._fetch_once_async` (the
  async twin of ``_fetch_once``); ``ByteStore.supports_async`` flips
  automatically when a subclass provides it.  ``LocalStore`` never routes
  here — its ``os.pread`` path stays zero-overhead.
- :class:`~tpu_parquet.iostore.CoalescedFetcher` grows an engine mode: a
  row group's spans (and lone ranges) all go in flight at construction;
  ``pipeline.prefetch_map`` grows a ``feed`` that keeps pulling work while
  the engine has free slots — ``prefetch=K`` bounds DECODE parallelism,
  in-flight IO is bounded by the engine cap and the memory budget.
- Cancellation wakes in-flight fetches: each submitted range races its
  scan's :class:`~tpu_parquet.resilience.CancelToken` (via ``on_cancel``
  posting to the loop), so a cancelled request's futures resolve with the
  request's TYPED verdict instead of waiting out a stalled transport.

Observability: :class:`EngineStats` carries the in-flight gauge/peak/cap,
a queue-wait histogram (submit → slot), and monotonic ``progress()``
counters for a watchdog heartbeat lane; the engine registers as a flight
source so a hang dump names the oldest in-flight range (the ``autopsy``
``network-stall`` contract), and :func:`fold_engine_stats` lands the
``io.engine`` registry subtree + the ``io.queue_wait`` histogram that the
``pq_tool doctor`` verdict ``io-concurrency-bound`` reads.

``TPQ_IO_ASYNC=0`` is the kill switch (every eligible store falls back to
the threaded path); ``TPQ_IO_INFLIGHT`` sizes the cap.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import threading
import time
import weakref

from .errors import (CancelledError, RetryExhaustedError, TransientIOError)
from .obs import LatencyHistogram, env_int, register_flight_source

__all__ = [
    "DEFAULT_INFLIGHT", "EngineStats", "FetchEngine",
    "default_engine_if_running", "engine_enabled", "engine_for_store",
    "fold_engine_stats", "get_default_engine", "shutdown_default_engine",
]

DEFAULT_INFLIGHT = 256

_engine_seq = itertools.count(1)


def engine_enabled() -> bool:
    """The routing switch: ``TPQ_IO_ASYNC=0`` kills the engine outright,
    ``TPQ_IO_INFLIGHT<=0`` likewise (a zero-slot engine could serve
    nothing).  Resolved per call so tests can flip the env per scan."""
    if os.environ.get("TPQ_IO_ASYNC", "1") == "0":
        return False
    return env_int("TPQ_IO_INFLIGHT", DEFAULT_INFLIGHT, lo=0) > 0


def engine_for_store(store) -> "FetchEngine | None":
    """Route one store: the shared default engine when the store carries
    the async primitive and the engine is enabled; None keeps the caller
    on the threaded path (LocalStore always lands here)."""
    if store is None or not getattr(store, "supports_async", False):
        return None
    if not engine_enabled():
        return None
    return get_default_engine()


class EngineStats:
    """The engine's own counters (thread-safe): submission/completion
    flows, the in-flight gauge + peak against the slot cap, queue-wait
    (submit → slot acquired — the backpressure signal the
    ``io-concurrency-bound`` doctor verdict reads) and in-slot fetch
    seconds, plus the point-in-time in-flight range table for flight
    dumps (``sample()`` names the OLDEST in-flight range, the
    ``network-stall`` autopsy contract ``IOStats.sample`` set)."""

    def __init__(self, inflight_cap: int):
        self._lock = threading.Lock()
        self.inflight_cap = int(inflight_cap)
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.inflight = 0
        self.inflight_peak = 0
        self.queue_wait_seconds = 0.0
        self.fetch_seconds = 0.0
        self.queue_wait_hist = LatencyHistogram()
        self._ranges: "dict[int, tuple[int, int, float]]" = {}
        self._seq = itertools.count(1)

    def note_submitted(self) -> None:
        with self._lock:
            self.submitted += 1

    def slot_acquired(self, wait_s: float) -> None:
        with self._lock:
            self.queue_wait_seconds += wait_s
            self.inflight += 1
            self.inflight_peak = max(self.inflight_peak, self.inflight)
        self.queue_wait_hist.record(wait_s)

    def note_done(self, ok: bool, had_slot: bool, fetch_s: float) -> None:
        with self._lock:
            if ok:
                self.completed += 1
            else:
                self.failed += 1
            if had_slot:
                self.inflight -= 1
                self.fetch_seconds += fetch_s

    def enter(self, offset: int, size: int) -> int:
        with self._lock:
            tok = next(self._seq)
            self._ranges[tok] = (offset, size, time.monotonic())
        return tok

    def exit(self, tok: int) -> None:
        with self._lock:
            self._ranges.pop(tok, None)

    def pending(self) -> int:
        """Submitted fetches not yet finished (queued + in flight) — the
        feed gate's backlog measure."""
        with self._lock:
            return self.submitted - self.completed - self.failed

    def progress(self) -> dict:
        """Monotonic counters only — the watchdog heartbeat contract (see
        ``IOStats.progress``): they freeze while every in-flight fetch is
        stalled and keep advancing while work completes."""
        with self._lock:
            return {
                "submitted": self.submitted,
                "finished": self.completed + self.failed,
            }

    def sample(self) -> dict:
        out = self.progress()
        with self._lock:
            out["inflight"] = self.inflight
            if self._ranges:
                now = time.monotonic()
                off, size, t0 = max(self._ranges.values(),
                                    key=lambda v: now - v[2])
                out["inflight_offset"] = off
                out["inflight_size"] = size
                out["inflight_age_s"] = round(now - t0, 3)
        return out

    def as_dict(self) -> dict:
        """The ``io.engine`` registry subtree: flows plus the gauge trio
        (``inflight``/``inflight_peak``/``inflight_cap`` — the generic
        merge maxes same-named keys across merged snapshots of one
        engine, which is exactly right for gauges of a shared engine)."""
        with self._lock:
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "inflight": self.inflight,
                "inflight_peak": self.inflight_peak,
                "inflight_cap": self.inflight_cap,
                "queue_wait_seconds": round(self.queue_wait_seconds, 6),
                "fetch_seconds": round(self.fetch_seconds, 6),
            }


class FetchEngine:
    """One event-loop thread multiplexing up to ``max_inflight`` range
    fetches.  ``submit`` is non-blocking and thread-safe; the returned
    ``concurrent.futures.Future`` resolves with the bytes, the same typed
    error the threaded ``read_range`` would raise, or ``CancelledError``
    when the engine is closed underneath it.  ``close()`` stops the loop,
    cancels whatever is still in flight (blocked waiters wake), and joins
    the thread — nothing for the bench leak gate to find."""

    def __init__(self, max_inflight: "int | None" = None, *,
                 name: str = "tpq-fetch"):
        if max_inflight is None:
            max_inflight = env_int("TPQ_IO_INFLIGHT", DEFAULT_INFLIGHT, lo=1)
        self.max_inflight = max(int(max_inflight), 1)
        self.stats = EngineStats(self.max_inflight)
        self._name = name
        self._lock = threading.Lock()
        self._loop: "asyncio.AbstractEventLoop | None" = None
        self._thread: "threading.Thread | None" = None
        self._ready = threading.Event()
        self._closed = False
        self._sem: "asyncio.Semaphore | None" = None
        # the "iostore" label prefix is the autopsy network-stall contract:
        # a dump reader scans iostore* samples for the oldest in-flight
        # range, and on the engine path THIS table is where it lives
        register_flight_source(f"iostore.engine[{next(_engine_seq)}]",
                               self.stats, "sample")

    # -- lifecycle ------------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._closed

    def _ensure_started(self) -> asyncio.AbstractEventLoop:
        with self._lock:
            if self._closed:
                raise RuntimeError("FetchEngine is closed")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True)
                self._thread.start()
        self._ready.wait()
        loop = self._loop
        if loop is None:  # pragma: no cover — loop thread died at startup
            raise RuntimeError("FetchEngine loop failed to start")
        return loop

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        # created on the loop thread: asyncio primitives bind their loop
        # on first await, and every await happens here
        self._sem = asyncio.Semaphore(self.max_inflight)
        self._loop = loop
        self._ready.set()
        try:
            loop.run_forever()
            # close() stopped the loop: cancel whatever is still in flight
            # so every blocked Future.result() waiter wakes promptly
            pending = asyncio.all_tasks(loop)
            for t in pending:
                t.cancel()
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
            # a task that settled exactly as the loop stopped has its
            # done-callbacks (hedge reaping, loser accounting) queued but
            # not yet run — drain the ready queue so no ledger entry is
            # lost; two beats cover callbacks scheduled by callbacks
            loop.run_until_complete(asyncio.sleep(0))
            loop.run_until_complete(asyncio.sleep(0))
        finally:
            asyncio.set_event_loop(None)
            loop.close()

    def close(self, timeout: float = 30.0) -> None:
        """Idempotent shutdown: stop the loop, reap in-flight fetches,
        join the thread."""
        with self._lock:
            thread = self._thread
            if not self._closed:
                self._closed = True
                loop = self._loop
                if loop is not None:
                    try:
                        loop.call_soon_threadsafe(loop.stop)
                    except RuntimeError:  # pragma: no cover — already dead
                        pass
        if thread is not None:
            thread.join(timeout)

    def want_more(self) -> bool:
        """Feed gate for ``pipeline.prefetch_map``: keep pulling work while
        the engine has free fetch slots."""
        return not self._closed and self.stats.pending() < self.max_inflight

    # -- submission -----------------------------------------------------------

    def submit(self, store, offset: int, size: int, scan=None,
               deadline: "float | None" = None):
        """Queue one range fetch; returns a ``concurrent.futures.Future``.
        ``scan``/``deadline`` carry exactly what ``read_range`` takes."""
        loop = self._ensure_started()
        self.stats.note_submitted()
        try:
            return asyncio.run_coroutine_threadsafe(
                self._fetch(store, int(offset), int(size), scan, deadline),
                loop)
        except RuntimeError:
            # lost the race with close(): account the submission as failed
            # so pending() reconciles, then surface the closed engine
            self.stats.note_done(False, False, 0.0)
            raise

    # -- the fetch coroutine --------------------------------------------------

    def _cancel_event(self, cancel) -> "asyncio.Event | None":
        """An asyncio.Event that fires when the scan's CancelToken flips —
        the bridge that lets a cross-thread ``cancel()`` wake this fetch
        mid-await.  Registered per fetch: the token's callback list is
        request-lived and cleared when it fires."""
        if cancel is None:
            return None
        ev = asyncio.Event()
        loop = self._loop
        evref = weakref.ref(ev)

        def _wake(_exc, _loop=loop, _evref=evref):
            e = _evref()
            if e is None:
                return
            try:
                _loop.call_soon_threadsafe(e.set)
            except RuntimeError:  # loop already closed: nothing to wake
                pass

        cancel.on_cancel(_wake)
        return ev

    async def _race(self, awaitable, ev, cancel):
        """Await ``awaitable`` unless the scan's cancel event fires first —
        in which case the in-flight work is cancelled (reaped, not leaked)
        and the request's TYPED verdict raises."""
        if ev is None:
            return await awaitable
        task = asyncio.ensure_future(awaitable)
        waiter = asyncio.ensure_future(ev.wait())
        try:
            await asyncio.wait({task, waiter},
                               return_when=asyncio.FIRST_COMPLETED)
        finally:
            waiter.cancel()
        if task.done():
            return task.result()
        task.cancel()
        try:
            await task
        except BaseException:  # noqa: BLE001 — the verdict outranks it
            pass
        cancel.check()
        raise CancelledError("scan cancelled")  # pragma: no cover — check raises

    async def _fetch(self, store, offset, size, scan, deadline):
        estats = self.stats
        if scan is None:
            scan = getattr(store, "_default_scan", None)
        cancel = scan.cancel if scan is not None else None
        trace = getattr(cancel, "trace", None) if cancel is not None else None
        # the request-trace span is recorded with add_timed AFTER the fact:
        # coroutines interleave on this one engine thread, so an open-span
        # context here would nest unrelated in-flight ranges into each other
        attempts: "list[dict] | None" = [] if trace is not None else None
        tr0 = time.perf_counter() if trace is not None else 0.0
        err_name = None
        ev = self._cancel_event(cancel)
        t0 = time.monotonic()
        ok = had_slot = False
        t_slot = t0
        try:
            await self._race(self._sem.acquire(), ev, cancel)
            t_slot = time.monotonic()
            estats.slot_acquired(t_slot - t0)
            had_slot = True
            try:
                buf = await self._read_range_async(
                    store, offset, size, scan, deadline, ev, cancel,
                    attempts_out=attempts)
                ok = True
                return buf
            finally:
                self._sem.release()
        except BaseException as e:
            err_name = type(e).__name__
            raise
        finally:
            estats.note_done(ok, had_slot, time.monotonic() - t_slot)
            if trace is not None:
                args = {"offset": offset, "size": size, "engine": True,
                        "queue_wait_ms": round(
                            max(t_slot - t0, 0.0) * 1e3, 3)}
                if attempts:
                    args["retries"] = len(attempts)
                    args["last_error"] = attempts[-1]["error"]
                if err_name is not None:
                    args["error"] = err_name
                trace.add_timed("fetch", tr0, time.perf_counter(), **args)

    async def _read_range_async(self, store, offset, size, scan, deadline,
                                ev, cancel, attempts_out: "list | None" = None):
        """The retry/deadline/backoff loop of
        ``GenericRangeStore.read_range``, as a coroutine.  Every branch,
        counter, and error message mirrors the threaded loop — the
        fault-matrix bit-identity tests hold the two together; a change
        to one must be checked against the other (iostore.py)."""
        cfg = store.config
        if cfg.deadline_s > 0:
            cfg_deadline = time.monotonic() + cfg.deadline_s
            deadline = (cfg_deadline if deadline is None
                        else min(deadline, cfg_deadline))
        if scan is not None and scan.deadline is not None:
            deadline = (scan.deadline if deadline is None
                        else min(deadline, scan.deadline))
        attempts: list[dict] = ([] if attempts_out is None else attempts_out)
        torn_prefix: "bytes | None" = None
        backoff = cfg.backoff_ms / 1e3
        stats = store.stats
        budget = scan.budget if scan is not None else None
        tok = self.stats.enter(offset, size)
        try:
            for attempt in range(cfg.retries + 1):
                if store._abort_exc is not None:
                    raise store._abort_exc
                if cancel is not None:
                    cancel.check()
                t0 = time.monotonic()
                try:
                    timeout = None
                    if deadline is not None:
                        timeout = deadline - t0
                        if timeout <= 0:
                            raise TransientIOError(
                                f"deadline exceeded before attempt "
                                f"{attempt} of range [{offset}, "
                                f"{offset + size})")
                    buf = await self._attempt(store, offset, size, timeout,
                                              ev, cancel)
                    if len(buf) == size and offset + size > store.size():
                        raise TransientIOError(
                            f"full-length read for range [{offset}, "
                            f"{offset + size}) past EOF at {store.size()}")
                    if len(buf) == size:
                        if torn_prefix is not None and not buf.startswith(
                                torn_prefix):
                            torn_prefix = None
                            raise TransientIOError(
                                f"re-read of range [{offset}, "
                                f"{offset + size}) does not match the torn "
                                f"attempt's prefix")
                        stats.add("reads")
                        stats.add("bytes_read", size)
                        return buf
                    if len(buf) > size:
                        raise TransientIOError(
                            f"overlong read: got {len(buf)} bytes for a "
                            f"{size}-byte range at {offset}")
                    if offset + len(buf) >= store.size():
                        stats.add("reads")
                        stats.add("bytes_read", len(buf))
                        return buf
                    stats.add("short_reads")
                    if len(buf) > (len(torn_prefix or b"")):
                        torn_prefix = bytes(buf)
                    raise TransientIOError(
                        f"short read: got {len(buf)} of {size} bytes at "
                        f"{offset} (torn read, not EOF)")
                except RetryExhaustedError:
                    raise
                except (TransientIOError, TimeoutError, OSError) as e:
                    if store._abort_exc is not None:
                        raise store._abort_exc from e
                    if cancel is not None:
                        cancel.check()
                    stats.add("transient_errors")
                    attempts.append({
                        "attempt": attempt,
                        "error": f"{type(e).__name__}: {e}",
                        "elapsed_ms": round(
                            (time.monotonic() - t0) * 1e3, 3),
                    })
                    if deadline is not None and time.monotonic() >= deadline:
                        stats.add("deadline_hits")
                        stats.add("exhausted")
                        raise RetryExhaustedError(
                            f"range [{offset}, {offset + size}) deadline "
                            f"exceeded after {attempt + 1} attempt(s)",
                            attempts=attempts, offset=offset, size=size,
                        ) from e
                    if attempt >= cfg.retries:
                        stats.add("exhausted")
                        raise RetryExhaustedError(
                            f"range [{offset}, {offset + size}) failed "
                            f"after {attempt + 1} attempt(s): {e}",
                            attempts=attempts, offset=offset, size=size,
                        ) from e
                    if budget is not None and not budget.spend():
                        stats.add("exhausted")
                        raise RetryExhaustedError(
                            f"range [{offset}, {offset + size}): per-scan "
                            f"retry budget "
                            f"({budget.max_retries}) exhausted",
                            attempts=attempts, offset=offset, size=size,
                        ) from e
                    if backoff > 0:
                        with store._rng_lock:
                            backoff = min(
                                store._rng.uniform(cfg.backoff_ms / 1e3,
                                                   backoff * 3),
                                cfg.backoff_ms / 1e3 * 64)
                        if deadline is not None:
                            backoff = min(
                                backoff,
                                max(deadline - time.monotonic(), 0.0))
                        attempts[-1]["backoff_ms"] = round(backoff * 1e3, 3)
                        stats.add("retries")
                        stats.add("backoff_seconds", backoff)
                        await self._race(asyncio.sleep(backoff), ev, cancel)
                    else:
                        stats.add("retries")
            raise AssertionError("unreachable: the retry loop always "
                                 "returns or raises")  # pragma: no cover
        finally:
            self.stats.exit(tok)

    async def _attempt(self, store, offset, size, timeout, ev, cancel):
        """One attempt, hedged when the store has a hedge delay (the async
        twin of ``GenericRangeStore._fetch``); the direct call otherwise.
        Hedge duplicates are asyncio tasks, not threads, but spend the
        SAME store-side semaphore/cap and counters as the threaded racers
        — both paths share one hedging budget on a shared store."""
        delay = store._hedge_delay_s()
        if delay is None or \
                store._hedges_outstanding >= store.config.hedge_max:
            t0 = time.monotonic()
            buf = await self._race(
                store._fetch_once_async(offset, size, timeout), ev, cancel)
            store.stats.fetch_hist.record(time.monotonic() - t0)
            return buf
        return await self._hedged(store, offset, size, timeout, delay,
                                  ev, cancel)

    async def _hedged(self, store, offset, size, timeout, delay, ev, cancel):
        stats = store.stats
        loop = asyncio.get_running_loop()

        async def one():
            t0 = time.monotonic()
            buf = await store._fetch_once_async(offset, size, timeout)
            stats.fetch_hist.record(time.monotonic() - t0)
            return buf

        racers: "list[tuple[str, asyncio.Task]]" = [
            ("primary", loop.create_task(one()))]
        done, _ = await asyncio.wait({racers[0][1]}, timeout=delay)
        if not done and store._hedge_sem.acquire(blocking=False):
            with store._hedge_lock:
                store._hedges_outstanding += 1
            stats.add("hedges_issued")
            hedge = loop.create_task(one())

            def _hedge_done(_t):
                # the duplicate's cap slot frees when IT finishes, win or
                # lose — the same contract the threaded racer keeps
                with store._hedge_lock:
                    store._hedges_outstanding -= 1
                store._hedge_sem.release()

            hedge.add_done_callback(_hedge_done)
            racers.append(("hedge", hedge))
        pending = {t for _r, t in racers}
        errors: list = []
        while pending:
            wait_for = set(pending)
            waiter = None
            if ev is not None:
                waiter = asyncio.ensure_future(ev.wait())
                wait_for.add(waiter)
            done, _ = await asyncio.wait(
                wait_for, return_when=asyncio.FIRST_COMPLETED)
            if waiter is not None:
                waiter.cancel()
                if waiter in done and not (done & pending):
                    # the scan was cancelled mid-race: reap both racers,
                    # then raise the request's typed verdict
                    for t in pending:
                        t.cancel()
                    await asyncio.gather(*pending, return_exceptions=True)
                    cancel.check()
                    raise CancelledError("scan cancelled")  # pragma: no cover
            for role, t in racers:
                if t not in pending or not t.done():
                    continue
                pending.discard(t)
                try:
                    buf = t.result()
                except BaseException as e:  # noqa: BLE001 — settled below
                    errors.append(e)
                    continue
                # first SUCCESS wins; the loser drains in the background
                # with its bytes accounted and its payload verified —
                # exactly _FetchRace.settle's contract
                if role == "hedge":
                    stats.add("hedges_won")
                for _r2, t2 in racers:
                    if t2 in pending:
                        self._reap_loser(t2, buf, stats)
                return buf
        raise errors[0]

    @staticmethod
    def _reap_loser(task, winner_buf, stats) -> None:
        def _done(t):
            if t.cancelled():
                return
            if t.exception() is not None:
                return  # loser failure: the winner already settled the race
            buf = t.result()
            stats.add("hedges_wasted_bytes", len(buf))
            if buf != winner_buf:
                stats.add("hedge_mismatches")

        task.add_done_callback(_done)


# ---------------------------------------------------------------------------
# the shared default engine
# ---------------------------------------------------------------------------

_default_lock = threading.Lock()
_default_engine: "FetchEngine | None" = None


def get_default_engine() -> FetchEngine:
    """The process-wide engine every routed store shares (lazily started;
    one loop thread serves every scan).  A closed default is replaced."""
    global _default_engine
    with _default_lock:
        if _default_engine is None or _default_engine.closed:
            _default_engine = FetchEngine()
        return _default_engine


def default_engine_if_running() -> "FetchEngine | None":
    """The default engine ONLY if one is live — obs folds call this so a
    registry snapshot never spawns an engine thread just to report
    zeros."""
    eng = _default_engine
    if eng is None or eng.closed:
        return None
    return eng


def shutdown_default_engine(timeout: float = 30.0) -> None:
    """Close and drop the default engine (tests + the bench leak gate call
    this; the next routed store lazily starts a fresh one)."""
    global _default_engine
    with _default_lock:
        eng, _default_engine = _default_engine, None
    if eng is not None:
        eng.close(timeout)


def fold_engine_stats(reg) -> None:
    """Fold the live default engine into a :class:`~tpu_parquet.obs
    .StatsRegistry`: the ``io.engine`` subtree plus the ``io.queue_wait``
    histogram.  No-op when no engine ever ran (local scans carry no
    engine keys — the golden-key contract)."""
    eng = default_engine_if_running()
    if eng is None or eng.stats.submitted == 0:
        return
    reg.add_io({"engine": eng.stats.as_dict()})
    reg.histogram("io.queue_wait").merge_from(eng.stats.queue_wait_hist)
