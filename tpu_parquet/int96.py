"""INT96 legacy timestamp conversions.

Equivalent of the reference's int96_time.go:33-56 (`Int96ToTime`/`TimeToInt96`):
the 12-byte INT96 layout is 8 bytes little-endian nanoseconds-within-day followed
by 4 bytes little-endian Julian day number.  Vectorized over (n, 3) uint32
matrices (the decode representation from kernels/plain.py).
"""

from __future__ import annotations

import datetime

import numpy as np

JULIAN_UNIX_EPOCH = 2440588  # Julian day number of 1970-01-01
NS_PER_DAY = 86_400_000_000_000


def int96_to_ns_epoch(arr: np.ndarray) -> np.ndarray:
    """(n, 3) uint32 INT96 → int64 nanoseconds since unix epoch."""
    a = np.asarray(arr, dtype=np.uint32).reshape(-1, 3)
    nanos = a[:, 0].astype(np.uint64) | (a[:, 1].astype(np.uint64) << np.uint64(32))
    days = a[:, 2].astype(np.int64) - JULIAN_UNIX_EPOCH
    return days * NS_PER_DAY + nanos.astype(np.int64)


def ns_epoch_to_int96(ns: np.ndarray) -> np.ndarray:
    """int64 nanoseconds since unix epoch → (n, 3) uint32 INT96.

    Like the reference (int96_time.go IsAfterUnixEpoch gate), only post-epoch
    times are representable; negative inputs raise.
    """
    ns = np.asarray(ns, dtype=np.int64)
    if np.any(ns < 0):
        raise ValueError("INT96 conversion only supports times at/after the unix epoch")
    days, rem = np.divmod(ns, NS_PER_DAY)
    out = np.empty((len(ns), 3), dtype=np.uint32)
    rem_u = rem.astype(np.uint64)
    out[:, 0] = (rem_u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    out[:, 1] = (rem_u >> np.uint64(32)).astype(np.uint32)
    out[:, 2] = (days + JULIAN_UNIX_EPOCH).astype(np.uint32)
    return out


def int96_to_datetime(v) -> datetime.datetime:
    """One INT96 value (12 bytes or (3,) uint32) → aware UTC datetime."""
    if isinstance(v, (bytes, bytearray)):
        v = np.frombuffer(bytes(v), "<u4")
    ns = int(int96_to_ns_epoch(np.asarray(v).reshape(1, 3))[0])
    return datetime.datetime.fromtimestamp(
        ns // 1_000_000_000, tz=datetime.timezone.utc
    ).replace(microsecond=(ns // 1000) % 1_000_000)


def datetime_to_int96(dt: datetime.datetime) -> bytes:
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    delta = dt - datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
    ns = (delta.days * 86_400 + delta.seconds) * 1_000_000_000 + delta.microseconds * 1000
    return ns_epoch_to_int96(np.array([ns]))[0].astype("<u4").tobytes()
