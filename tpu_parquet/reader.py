"""FileReader: the low-level read API.

Equivalent of the reference's file_reader.go FileReader — options (column projection,
CRC validation, memory budget, externally-supplied metadata), row-group cursor
(seek/skip/preload), and metadata accessors — but columnar-first: the primary API
returns decoded column arrays per row group (`read_row_group` / `read_all`); the
row-map iteration of the reference (`NextRow`, file_reader.go:258-273) is provided
on top by tpu_parquet.assembly.
"""

from __future__ import annotations

import io
import os
from typing import BinaryIO, Iterable, Optional, Sequence, Union

import numpy as np

from .alloc import AllocTracker, InFlightBudget
from .chunk_decode import ChunkDecoder, read_chunk, validate_chunk_meta
from .column import ByteArrayData, ColumnData
from .errors import DataIntegrityError
from .footer import ParquetError, read_file_metadata
from .format import FileMetaData, Type
from .iostore import CoalescedFetcher, require_full, resolve_store
from .iostore_async import engine_for_store
from .pipeline import PipelineStats, SharedReader, prefetch_map
from .schema.core import Schema, SchemaNode


class _ChunkFailed:
    """In-band marker for a quarantined chunk riding the ordered prefetch
    stream (the stream must keep flowing — a raise would kill the pool).
    Carries the annotated exception; the CONSUMER notes exactly one
    quarantine record per failed unit (the first failing chunk in column
    order), so the ledger is identical at every prefetch depth."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class FileReader:
    """Low-level parquet reader over a seekable byte source.

    Options mirror file_reader.go:65-149: ``columns`` (projection),
    ``validate_crc``, ``max_memory`` (WithMaximumMemorySize), ``metadata``
    (WithFileMetaData).

    ``prefetch=K`` (K > 0) turns the group/chunk iteration APIs into an
    overlapped pipeline: a bounded pool of K threads runs IO + CRC +
    decompression + decode for upcoming chunks — flattened ACROSS row-group
    boundaries, so the pipeline never drains between groups — while the
    consumer assembles finished groups.  Output is bit-identical to the
    sequential path.  Memory semantics shift from the sequential path's
    per-row-group AllocTracker raise to per-CHUNK bomb enforcement plus a
    blocking in-flight cap over the same ``max_memory`` (backpressure
    instead of an error when only concurrency, not any single chunk,
    exceeds the budget).  ``pipeline_stats()`` exposes per-stage timing.
    """

    def __init__(
        self,
        source: Union[str, os.PathLike, BinaryIO, bytes],
        columns: Optional[Iterable[Union[str, Sequence[str]]]] = None,
        validate_crc=None,
        max_memory: int = 0,
        metadata: Optional[FileMetaData] = None,
        row_filter=None,
        prefetch: int = 0,
        trace=None,
        store=None,
        on_data_error=None,
        quarantine=None,
        plan=None,
        dict_cache=None,
        result_cache=None,
        cancel=None,
    ):
        from .obs import resolve_tracer
        from .quarantine import Quarantine, resolve_validate

        # span tracer (obs.py): None = the TPQ_TRACE process tracer; a path
        # = per-reader tracer written (with the registry) at close()
        self._tracer, self._owns_tracer = resolve_tracer(trace)
        if isinstance(source, (str, os.PathLike)):
            self._f: BinaryIO = open(source, "rb")
            self._owns_file = True
            self._source_name = os.fspath(source)
        elif isinstance(source, (bytes, bytearray, memoryview)):
            self._f = io.BytesIO(bytes(source))
            self._owns_file = False
            self._source_name = "<memory>"
        else:
            self._f = source
            self._owns_file = False
            self._source_name = getattr(source, "name", None) or "<stream>"
        # data-error containment (quarantine.py): ``on_data_error`` picks
        # the policy (raise | skip_unit | skip_file, TPQ_ON_DATA_ERROR);
        # ``quarantine=`` shares one engine across readers (scan_files,
        # DeviceFileReader's host half) so the budget and ledger are global
        self.quarantine = (quarantine if quarantine is not None
                           else Quarantine(on_data_error))
        # per-request lifecycle token (resilience.CancelToken): its
        # deadline/cancellation is checked at every unit boundary and
        # rides the scan token into every store read — the serve tier's
        # deadline-propagation contract
        self._cancel = cancel
        validate_crc = resolve_validate(validate_crc)
        try:
            self.metadata = (metadata if metadata is not None
                             else read_file_metadata(self._f))
            # the IO backend every chunk byte enters through (iostore.py):
            # LocalStore by default (zero-overhead pread), a
            # GenericRangeStore for fault-tolerant/remote reads.  A factory
            # callable gets this reader's open file; an instance is the
            # caller's (single-file use, caller owns/closes it).
            self._owns_store = store is None or callable(store)
            self._store = resolve_store(self._f, store)
            self._sr = SharedReader(self._f, store=self._store)
            self.schema = Schema.from_file_metadata(self.metadata)
            self._preloaded: Optional[dict[str, ColumnData]] = None
            if columns is not None:
                self.set_selected_columns(columns)
            self.validate_crc = validate_crc
            self.alloc = AllocTracker(max_memory)
            self.prefetch = int(prefetch)
            self._pipe_stats = PipelineStats(prefetch=self.prefetch,
                                             budget_bytes=int(max_memory),
                                             tracer=self._tracer)
            self._current_row_group = 0
            self._preloaded: Optional[dict[str, ColumnData]] = None
            # statistics-based row-group pruning (predicate pushdown): groups
            # whose footer stats prove the predicate can never match are
            # skipped by the iteration APIs — their bytes are never read
            self.row_filter = row_filter
            # decoded-dictionary read-through cache (serve.BoundDictCache
            # duck type); threaded into every ChunkDecoder below
            self._dict_cache = dict_cache
            # decoded column-chunk result cache (serve.BoundResultCache
            # duck type, bound to this file generation + the HOST decode
            # signature): a cached (row group, column) unit skips its IO +
            # decompress + decode entirely; misses decode once under
            # single-flight and publish for every concurrent waiter.
            # Served values are shared READ-ONLY.  An adapter whose
            # signature doesn't match THIS reader's decode shape is
            # dropped, not adopted: a device-signed one would publish
            # host ColumnData where jax arrays are expected, and one
            # signed for a different CRC tier would let a
            # validate_crc=True request adopt unvalidated decodes.
            if result_cache is not None:
                sig = getattr(result_cache, "sig", None) or ()
                want = ("host", "v1" if validate_crc else "v0")
                if tuple(sig[:2]) != want:
                    result_cache = None
            self._result_cache = result_cache
            from .scanplan import build_scan_plan, predicate_fingerprint

            fp = predicate_fingerprint(row_filter)
            cols_sig = tuple(sorted(
                ".".join(l.path) for l in self.schema.selected_leaves()))
            fp_match = ((row_filter is None and plan is not None
                         and plan.filter_fp is None)
                        or (fp is not None and plan is not None
                            and plan.filter_fp == fp))
            if plan is not None and fp_match and plan.columns == cols_sig:
                # replay a cached ScanPlan (scanplan.py): the group-pruning
                # verdict is adopted, never recomputed; a plan whose
                # projection or filter doesn't match falls through to a
                # fresh build rather than a wrong replay
                self._plan = plan
                self._rg_keep = (list(plan.rg_keep)
                                 if plan.rg_keep is not None else None)
            else:
                if row_filter is not None:
                    from .predicate import prune_row_groups

                    self._rg_keep = prune_row_groups(
                        self.metadata, self.schema, row_filter)
                else:
                    self._rg_keep = None
                self._plan = build_scan_plan(
                    self.metadata, self.schema, row_filter=row_filter,
                    filter_fp=fp, rg_keep=self._rg_keep)
        except BaseException:
            # a constructor failure (bad footer, bad projection, bad filter)
            # must not leak the fd this reader opened
            if self._owns_file:
                self._f.close()
            raise

    def set_selected_columns(self, columns) -> None:
        """Re-project mid-read (SetSelectedColumns parity, schema.go:347-367):
        subsequent row-group reads decode only these columns, seeking past the
        rest.  ``None`` restores all columns.  Clears any preloaded group.
        Validates BEFORE applying: a failed call leaves the selection as it
        was (an applied-then-raised empty selection would make later reads
        silently return {})."""
        from .scanplan import apply_selection

        apply_selection(self.schema, columns)
        self._preloaded = None
        # the plan IR is projection-scoped: re-projecting rebuilds it (a
        # cheap footer walk) so its chunk slices and byte estimates always
        # describe the CURRENT selection.  During __init__ the first plan
        # has not been built yet — the constructor builds it right after.
        if hasattr(self, "_plan"):
            from .scanplan import build_scan_plan

            self._plan = build_scan_plan(self.metadata, self.schema,
                                         row_filter=self.row_filter,
                                         rg_keep=self._rg_keep)

    def row_group_selected(self, index: int) -> bool:
        """False when ``row_filter`` proves row group ``index`` cannot match."""
        return self._rg_keep is None or self._rg_keep[index]

    @property
    def num_selected_rows(self) -> int:
        """Total rows in the row groups that survive ``row_filter`` — the
        count the iteration APIs will actually yield (``num_rows`` stays the
        footer total; pruning is group-granular, so surviving groups may
        still contain rows the predicate rejects)."""
        if self._rg_keep is None:
            return self.metadata.num_rows
        return sum(
            rg.num_rows for rg, keep in
            zip(self.metadata.row_groups, self._rg_keep) if keep
        )

    # -- context management ---------------------------------------------------

    def close(self):
        if getattr(self, "_owns_store", False):
            self._store.close()
        if self._owns_file:
            self._f.close()
        if self._owns_tracer:
            self._tracer.write(registry=self.obs_registry())
            self._owns_tracer = False

    def obs_registry(self):
        """This reader's unified metrics tree (obs.StatsRegistry): the
        pipeline's per-stage sums + histograms, the alloc peak, and the IO
        backend's retry/coalescing counters when the store keeps any."""
        from .obs import StatsRegistry

        reg = StatsRegistry()
        reg.add_pipeline(self._pipe_stats)
        reg.note_alloc_peak(self.alloc)
        if self._store.stats is not None:
            reg.add_io(self._store.stats)
        if getattr(self._store, "supports_async", False):
            # the io.engine subtree + io.queue_wait histogram (the doctor's
            # io-concurrency-bound evidence); no-op when no engine ran
            from .iostore_async import fold_engine_stats

            fold_engine_stats(reg)
        if len(self.quarantine.log) or self.quarantine.units_skipped:
            reg.add_data_errors(self.quarantine)
        return reg

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- metadata accessors (file_reader.go parity) ---------------------------

    @property
    def num_rows(self) -> int:
        return self.metadata.num_rows

    @property
    def num_row_groups(self) -> int:
        return len(self.metadata.row_groups)

    def row_group_num_rows(self, index: int) -> int:
        return self.metadata.row_groups[index].num_rows

    @property
    def created_by(self) -> Optional[str]:
        return self.metadata.created_by

    def key_value_metadata(self) -> dict:
        return {
            kv.key: kv.value for kv in (self.metadata.key_value_metadata or [])
        }

    def columns(self) -> list[SchemaNode]:
        return self.schema.selected_leaves()

    # -- columnar reads --------------------------------------------------------

    def pipeline_stats(self) -> PipelineStats:
        """Per-stage timing of the last/current prefetch pipeline
        (io / decompress / stall / peak in-flight); zeros when ``prefetch``
        was never used.  See pipeline.PipelineStats.overlap_efficiency."""
        return self._pipe_stats

    def _decode_row_groups(self, indices, k: int, contain: bool = True):
        """Chunk-granular overlapped decode (the prefetch pipeline).

        Work items are (row group, chunk) pairs FLATTENED across ``indices``
        — row-group lookahead falls out of the flattening: the K-deep window
        spans group boundaries, so worker threads keep decoding the next
        group's chunks while a finished group is assembled and yielded.
        Yields ``(index, {dotted_path: ColumnData})`` in ``indices`` order;
        per-group missing-column checks match read_row_group exactly.

        Memory: every worker chunk gets its own AllocTracker(max_memory)
        (the per-chunk decompression-bomb guard), and cross-chunk in-flight
        bytes are bounded by an InFlightBudget over the same budget —
        backpressure in the submitting thread, never a raise for a file the
        sequential path would accept chunk by chunk.

        device_reader._chunk_feed mirrors this flatten/regroup protocol
        (different payloads); a change here should be checked against it.
        """
        stats = PipelineStats(prefetch=k, budget_bytes=self.alloc.max_size,
                              tracer=self._tracer)
        self._pipe_stats = stats
        budget = InFlightBudget(self.alloc.max_size)
        sr = self._sr
        store = self._store
        # fresh per-scan retry budget + coalescing state, scoped to THIS
        # scan's token (a store shared between concurrent requests never
        # shares budgets); the request deadline/cancel rides it into every
        # read_range
        scan_tok = store.begin_scan(cancel=self._cancel)
        sr.set_scan(scan_tok)
        # async fetch engine routing (iostore_async): eligible stores put
        # a whole row group's ranges in flight on the engine's event loop
        # — prefetch=k keeps bounding DECODE parallelism, in-flight IO is
        # bounded by TPQ_IO_INFLIGHT; None = the threaded/pread path
        eng = engine_for_store(store)
        q = self.quarantine
        contain = contain and q.contains
        if contain:
            q.begin_scan(len(indices) if hasattr(indices, "__len__") else None)
        pending: dict[int, dict] = {}  # rg index -> regrouping slot

        def gen_items():
            # runs in the CONSUMER thread as the window refills, so the
            # schema-selection snapshot keeps sequential semantics
            for i in indices:
                rg = self.metadata.row_groups[i]
                by_path = {l.path: l for l in self.schema.selected_leaves()}
                items = []
                for chunk in rg.columns or []:
                    md = chunk.meta_data
                    if md is None or md.path_in_schema is None:
                        raise ParquetError("column chunk missing metadata/path")
                    path = tuple(md.path_in_schema)
                    leaf = by_path.get(path)
                    if leaf is None:
                        continue  # unselected: never read its bytes
                    items.append([i, path, chunk, leaf, None])
                # range coalescing (iostore.py): adjacent chunk reads of
                # this group merge into fewer, larger, individually-
                # retryable fetches — only for stores that ask for it
                # (remote/fault-injecting; the local path pays nothing,
                # not even the range collection below)
                use_coalesce = (store.prefers_coalescing
                                and not (scan_tok.coalesce_disabled
                                         if scan_tok is not None
                                         else store.coalesce_disabled)
                                and len(items) > 1)
                fetch_items = items
                if eng is not None and rc is not None and items:
                    # engine mode submits IO at PLAN time, so the result
                    # cache must be probed here, not at decode time: a
                    # warm unit's bytes are never fetched (the zero-store-
                    # read warm-scan contract).  Evicted-between-probe-and-
                    # decode units fall back to a plain single-range read.
                    fetch_items = [
                        c for c in items
                        if not rc.has_group(c[0], [".".join(c[1])])]
                if fetch_items and (use_coalesce or eng is not None):
                    ranges = []
                    for it in fetch_items:
                        _md, offset = validate_chunk_meta(it[2], it[3])
                        ranges.append((offset, _md.total_compressed_size))
                    # engine mode submits the group's fetches NOW (merged
                    # spans, or single ranges once the ladder disables
                    # merging) — decode catches up through the futures
                    fetcher = CoalescedFetcher(store, ranges, scan=scan_tok,
                                               engine=eng,
                                               coalesce=use_coalesce)
                    for it in fetch_items:
                        it[4] = fetcher
                pending[i] = {
                    "expect": {".".join(p) for p in by_path},
                    "todo": max(len(items), 1),
                    "out": {},
                }
                if not items:
                    # sentinel so an empty group still finalizes in order
                    items.append([i, None, None, None, None])
                yield from map(tuple, items)

        def chunk_cost(item):
            _i, _path, chunk, _leaf, _fetcher = item
            if chunk is None:
                return 0
            md = chunk.meta_data
            comp = max(md.total_compressed_size or 0, 0)
            return comp + max(md.total_uncompressed_size or 0, comp)

        rc = self._result_cache

        def decode_item(item):
            i, path, chunk, leaf, fetcher = item
            if chunk is None:
                return i, None, None
            name = ".".join(path)
            ctx = {"file": self._source_name, "row_group": i, "column": name}

            def decode_chunk():
                md, offset = validate_chunk_meta(chunk, leaf)
                alloc = AllocTracker(self.alloc.max_size)
                alloc.register(md.total_compressed_size)
                with stats.timed("io"):
                    buf = (fetcher.read(offset, md.total_compressed_size)
                           if fetcher is not None
                           else sr.pread(offset, md.total_compressed_size))
                require_full(buf, offset, md.total_compressed_size,
                             context=f"column {name}")
                with stats.timed("decompress"):
                    dec = ChunkDecoder(leaf, validate_crc=self.validate_crc,
                                       alloc=alloc,
                                       context={**ctx,
                                                "chunk_offset": offset},
                                       dict_cache=self._dict_cache)
                    return dec.decode(buf, md.codec, md.num_values)

            try:
                if rc is not None:
                    # decoded-result seam (serve/result_cache.py): a warm
                    # unit is returned without touching the store; a cold
                    # one decodes ONCE (single-flight across every
                    # concurrent scan of this file generation) and
                    # publishes.  Failed decodes are never published.
                    cd = rc.get_or_build(i, name,
                                         _cache_build(decode_chunk))
                else:
                    cd = decode_chunk()
            except ParquetError as e:
                # containment seam (quarantine.py): under a skip policy the
                # failure becomes a marker + a poisoned unit instead of an
                # aborted scan; the CONSUMER notes the record (once per
                # unit, ordered — so the ledger matches prefetch=0 exactly)
                if not contain or isinstance(e, DataIntegrityError):
                    raise
                return i, name, _ChunkFailed(e)
            stats.count_chunk()
            return i, name, cd

        stats.touch_wall()
        for i, name, cd in prefetch_map(gen_items(), decode_item, k,
                                        budget=budget, cost=chunk_cost,
                                        stats=stats, cancel=self._cancel,
                                        feed=eng):
            slot = pending[i]
            if name is not None:
                if isinstance(cd, _ChunkFailed):
                    slot.setdefault("failed", cd)
                else:
                    slot["out"][name] = cd
            slot["todo"] -= 1
            if slot["todo"] == 0:
                del pending[i]
                failed = slot.get("failed")
                if failed is not None:
                    # a quarantined unit: ONE record (the first failing
                    # chunk), nothing yielded, the skip accounted;
                    # skip_file on a single-file reader ends the scan here.
                    # note() raises DataIntegrityError on budget exhaustion.
                    q.note(failed.exc, file=self._source_name, row_group=i)
                    rg = self.metadata.row_groups[i]
                    q.note_unit_skipped(int(rg.num_rows or 0))
                    if q.policy == "skip_file":
                        # collateral: the file's remaining groups are
                        # accounted (results yield in order, so none of
                        # them has been yielded yet)
                        q.note_file_skipped()
                        pos = list(indices).index(i)
                        for j in list(indices)[pos + 1:]:
                            q.note_unit_skipped(int(
                                self.metadata.row_groups[j].num_rows or 0))
                        break
                    continue
                missing = slot["expect"] - set(slot["out"])
                if missing:
                    raise ParquetError(
                        f"row group {i} missing columns {sorted(missing)}"
                    )
                stats.count_row_group()
                stats.note_peak(budget)
                stats.touch_wall()
                yield i, slot["out"]
        stats.touch_wall()

    def read_row_group(self, index: int,
                       prefetch: Optional[int] = None) -> dict[str, ColumnData]:
        """Decode all selected column chunks of one row group.

        Returns {dotted_column_path: ColumnData}.  This is the TPU work unit:
        each chunk is one contiguous IO + one batch decode.  With
        ``prefetch`` > 0 (argument, else the reader's setting) the group's
        chunks decode concurrently on the pipeline pool.
        """
        if not 0 <= index < self.num_row_groups:
            raise IndexError(f"row group {index} of {self.num_row_groups}")
        k = self.prefetch if prefetch is None else int(prefetch)
        if k > 0:
            # contain=False: an EXPLICITLY requested group must raise, not
            # silently skip itself (the iteration APIs own the skip policy)
            for _i, out in self._decode_row_groups([index], k,
                                                   contain=False):
                return out
        rg = self.metadata.row_groups[index]
        self.alloc.reset()
        leaves = self.schema.selected_leaves()
        by_path = {l.path: l for l in leaves}
        out: dict[str, ColumnData] = {}
        # every byte enters through the store, sequential path included —
        # the fault-tolerance (and fault-injection) contract covers
        # prefetch=0 bit-identically.  begin_scan here means the "scan"
        # unit on this path is one row group (a looser retry-budget bound
        # than the pipelined whole-iteration scan, but bounded) — and a
        # watchdog abort from a previous scan never poisons this one.
        self._sr.set_scan(self._store.begin_scan(cancel=self._cancel))
        f = self._sr.as_file()
        # the one shared footer walk (scanplan.py): unselected chunks'
        # bytes are never read (skipChunk parity)
        from .scanplan import row_group_chunks

        rc = self._result_cache
        for path, leaf, chunk, md, offset in row_group_chunks(rg, by_path):
            if self._cancel is not None:
                self._cancel.check()  # unit boundary: stop issuing new IO
            name = ".".join(path)

            def decode_chunk(chunk=chunk, leaf=leaf, md=md, offset=offset):
                return read_chunk(
                    f, chunk, leaf,
                    validate_crc=self.validate_crc, alloc=self.alloc,
                    context={"file": self._source_name, "row_group": index},
                    dict_cache=self._dict_cache, meta=(md, offset),
                )

            if rc is not None:
                # decoded-result seam, sequential path: same contract as
                # the pipelined one (see _decode_row_groups)
                out[name] = rc.get_or_build(index, name,
                                            _cache_build(decode_chunk))
            else:
                out[name] = decode_chunk()
        missing = set(".".join(p) for p in by_path) - set(out)
        if missing:
            raise ParquetError(f"row group {index} missing columns {sorted(missing)}")
        return out

    def iter_row_groups(self, prefetch: Optional[int] = None):
        k = self.prefetch if prefetch is None else int(prefetch)
        selected = [i for i in range(self.num_row_groups)
                    if self.row_group_selected(i)]  # pruned: bytes never read
        if k > 0:
            for _i, out in self._decode_row_groups(selected, k):
                yield out
            return
        q = self.quarantine
        q.begin_scan(len(selected))
        for i in selected:
            if not q.contains:
                yield self.read_row_group(i, prefetch=0)
                continue
            try:
                out = self.read_row_group(i, prefetch=0)
            except ParquetError as e:
                # containment (quarantine.py): the unit is recorded and
                # skipped; a budget-exhausted DataIntegrityError aborts
                if isinstance(e, DataIntegrityError):
                    raise
                q.note(e, file=self._source_name, row_group=i)
                q.note_unit_skipped(
                    int(self.metadata.row_groups[i].num_rows or 0))
                if q.policy == "skip_file":
                    q.note_file_skipped()
                    for j in selected[selected.index(i) + 1:]:
                        q.note_unit_skipped(int(
                            self.metadata.row_groups[j].num_rows or 0))
                    return
                continue
            yield out

    def read_all(self, prefetch: Optional[int] = None) -> dict[str, ColumnData]:
        """Concatenate all row groups' columns (convenience for small files).

        ``prefetch`` overrides the reader's pipeline depth for this call
        (0 forces the sequential path, K > 0 the overlapped one)."""
        groups = list(self.iter_row_groups(prefetch=prefetch))
        if not groups:
            return {
                ".".join(l.path): ColumnData(
                    values=np.zeros(0, dtype=np.int64),
                    max_def=l.max_def, max_rep=l.max_rep,
                )
                for l in self.schema.selected_leaves()
            }
        if len(groups) == 1:
            return groups[0]
        out = {}
        for key in groups[0]:
            out[key] = _concat_column_data([g[key] for g in groups])
        return out

    # -- row-group cursor (SeekToRowGroup/SkipRowGroup/PreLoad parity) ---------

    def seek_to_row_group(self, index: int) -> None:
        if not 0 <= index < self.num_row_groups:
            raise IndexError(f"row group {index} of {self.num_row_groups}")
        if index != self._current_row_group:
            self._preloaded = None
        self._current_row_group = index

    def skip_row_group(self) -> None:
        if self._current_row_group >= self.num_row_groups:
            raise IndexError("already past the last row group")
        self._current_row_group += 1
        self._preloaded = None

    def preload(self) -> dict[str, ColumnData]:
        """Decode the cursor's row group now and cache it (PreLoad parity,
        file_reader.go:280-288).  Row iteration consumes this cache."""
        if self._current_row_group >= self.num_row_groups:
            raise IndexError("no row group to preload")
        if self._preloaded is None:
            self._preloaded = self.read_row_group(self._current_row_group)
        return self._preloaded

    def current_row_group(self):
        if self._current_row_group >= self.num_row_groups:
            raise IndexError("cursor past the last row group")
        return self.metadata.row_groups[self._current_row_group]

    # -- row-oriented API (NextRow parity) -------------------------------------

    def iter_rows(self):
        """Iterate raw nested dict rows (reference NextRow semantics)."""
        from .assembly import RowIterator

        return RowIterator(self)

    def iter_rows_logical(self):
        """Iterate rows with LIST/MAP wrappers unwrapped to python lists/dicts."""
        from .logical import unwrap_row

        for row in self.iter_rows():
            yield unwrap_row(self.schema, row)

    def __iter__(self):
        return self.iter_rows()

    # -- python-value conversion ----------------------------------------------

    def read_pylist(self) -> dict[str, list]:
        """Flat columns as Python lists with None for nulls (testing/CLI aid)."""
        out = {}
        for name, cd in self.read_all().items():
            leaf = self.schema.leaf_by_path(tuple(name.split(".")))
            out[name] = column_to_pylist(cd, leaf)
        return out


def _cache_build(decode):
    """Adapt a no-arg chunk decode to the result cache's get_or_build
    contract (``build() -> (value, nbytes)``)."""
    def build():
        from .serve.result_cache import column_nbytes

        cd = decode()
        return cd, column_nbytes(cd)
    return build


def _concat_column_data(parts: list[ColumnData]) -> ColumnData:
    first = parts[0]

    def cat_opt(attr):
        arrs = [getattr(p, attr) for p in parts]
        if any(a is None for a in arrs):
            return None
        return np.concatenate(arrs)

    if isinstance(first.values, ByteArrayData):
        offsets = [first.values.offsets]
        heaps = [first.values.heap]
        base = int(first.values.offsets[-1])
        for p in parts[1:]:
            offsets.append(p.values.offsets[1:] + base)
            heaps.append(p.values.heap)
            base += int(p.values.offsets[-1])
        values = ByteArrayData(np.concatenate(offsets), np.concatenate(heaps))
    else:
        values = np.concatenate([p.values for p in parts])
    return ColumnData(
        values=values,
        def_levels=cat_opt("def_levels"),
        rep_levels=cat_opt("rep_levels"),
        max_def=first.max_def,
        max_rep=first.max_rep,
        num_leaf_slots=sum(p.num_leaf_slots for p in parts),
    )


def column_to_pylist(cd: ColumnData, leaf: Optional[SchemaNode] = None) -> list:
    """Flat (max_rep==0) column → Python list with None in null slots.

    BYTE_ARRAY becomes str when the column is logically UTF8, else bytes.
    """
    if cd.max_rep > 0:
        raise ParquetError("column_to_pylist only handles flat columns")
    from .assembly import materialize_leaf_values

    vals = materialize_leaf_values(leaf, cd) if leaf is not None else (
        cd.values.to_list() if isinstance(cd.values, ByteArrayData) else cd.values.tolist()
    )
    if cd.def_levels is None:
        return vals
    out = [None] * cd.num_leaf_slots
    vi = 0
    mask = cd.validity()
    for i in range(cd.num_leaf_slots):
        if mask[i]:
            out[i] = vals[vi]
            vi += 1
    return out
