"""Device-path chunk decoding: host structure parse → XLA bulk decode.

This is the TPU twin of chunk_decode.py.  The host walks page headers and the
sequential, metadata-sized parts of each encoding (run headers, delta block
headers) with NumPy; the bulky transforms run as jitted XLA programs from
jax_kernels.py over the raw page bytes staged to device memory.  Decoded columns
are jax Arrays that stay on device (SURVEY.md §7.1 design stance).

Shapes are static per (geometry) so XLA executables are cached across pages:
run tables are padded to power-of-two buckets, byte buffers to 64-byte multiples.
The first page of a new geometry pays a compile; every later page of the same
shape reuses it — the pipelining SURVEY.md §7.4.7 names as the real perf lever.

Encoding coverage mirrors chunk_reader.go:106-159 where the transform is
parallelizable; inherently sequential byte-level paths (PLAIN BYTE_ARRAY length
walking, DELTA_BYTE_ARRAY prefix stitching) parse on host and ship (offsets, heap)
to device, per SURVEY.md §7.4.2/§7.4.4.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import jax_kernels as K
from .jax_kernels import scoped_x64
from .column import ByteArrayData
from .compress import decompress_block
from .footer import ParquetError
from .format import Encoding, PageType, Type, parse_encoding
from .kernels import bitpack, rle
from .kernels.rle import RLEError, _read_uvarint
from .kernels import delta as delta_host
from .kernels.delta import DeltaError
from .chunk_decode import PageSlice, validate_chunk_meta, walk_pages, _check_crc
from .schema.core import SchemaNode

__all__ = [
    "DeviceColumnData",
    "DeviceChunkDecoder",
    "parse_hybrid_meta",
    "parse_delta_meta",
    "decode_hybrid_device",
    "decode_delta_device",
    "pad_buffer",
]

_SLACK = 16  # extract_bits worst-case gather overrun (9 bytes) + alignment


def _bucket(n: int, floor: int = 8) -> int:
    """Round up to a power of two (>= floor) to bound the jit cache."""
    b = floor
    while b < n:
        b <<= 1
    return b


def _bucket_bytes(n: int, floor: int = 64) -> int:
    """Round a byte-buffer size up to 8 steps per power-of-two octave.

    Value buffers are the dominant host→device transfer; pure power-of-two
    padding wastes up to 2x tunnel bandwidth on them (an 80 MB chunk would
    ship as 128 MB).  Eight sizes per octave caps the waste at 12.5% while
    still bounding the number of distinct executable shapes.
    """
    b = _bucket(n, floor)
    if b <= floor:
        return b
    step = b >> 3
    return ((n + step - 1) // step) * step


def _bucket_count(n: int) -> int:
    """Bucket a value count: 8 steps per power-of-two octave (<= 12.5% pad).

    The decode kernels take their output size as a *static* shape, so every
    distinct count otherwise compiles a fresh executable — and over a tunneled
    backend each remote compile costs tens of seconds, dominating first-open
    wall clock (the row groups of one file rarely share exact value counts).
    Decoding into the bucketed size (tail lanes masked or sliced off on host)
    collapses that diversity to <= 8 shapes per octave per kernel family.
    """
    return _bucket_bytes(max(n, 1), 8)


def pad_buffer(raw: bytes | np.ndarray) -> jax.Array:
    """Stage a byte buffer on device, padded so bit-extract gathers stay in bounds."""
    arr = np.frombuffer(raw, dtype=np.uint8) if isinstance(raw, (bytes, bytearray, memoryview)) else raw
    n = len(arr)
    padded = _bucket_bytes(n + _SLACK, 64)
    out = np.empty(padded, dtype=np.uint8)
    out[:n] = arr
    out[n:] = 0
    return jnp.asarray(out)


# ---------------------------------------------------------------------------
# RLE/bit-packed hybrid: host run-header parse → device expansion
# ---------------------------------------------------------------------------

@dataclass
class HybridMeta:
    """Padded per-run tables for jax_kernels.expand_rle_hybrid."""

    run_ends: np.ndarray       # int64[R] cumulative counts (padded: repeat last)
    run_is_rle: np.ndarray     # bool[R]
    run_values: np.ndarray     # uint32[R]
    run_bit_starts: np.ndarray  # int64[R] payload bit start minus start*width
    count: int
    consumed: int              # bytes consumed from the stream
    n_runs: int = 0            # real (unpadded) run count
    max_value: Optional[int] = None  # stream max (native walk only, on request)
    eq_count: Optional[int] = None   # values == eq_target (native walk only)


from .native import NATIVE_ERRORS as _NATIVE_ERRORS


def parse_hybrid_meta(
    buf: bytes, width: int, count: int, pos: int = 0, end: Optional[int] = None,
    compute_max: bool = False, eq_target: Optional[int] = None,
) -> HybridMeta:
    """Walk run headers only (no payload unpacking) — cheap, O(runs) bytes.

    Mirrors the header walk of hybrid_decoder.go:115-165 but records (kind, span,
    payload offset) instead of decoding; the payload stays untouched for the
    device kernel.  ``end`` bounds the stream (v1 length prefix): runs may not
    extend past it, matching the host decoder's size validation.

    ``compute_max`` additionally reports the stream's maximum value when the
    native walk is available (``max_value``; None otherwise) — dictionary
    callers use it to range-check indices on host with zero device syncs.
    ``eq_target`` likewise reports ``eq_count``, the number of stream values
    equal to the target — def-level callers pass max_def and get the page's
    defined count without ever materializing the decoded levels.

    The walk itself runs in C when the native library is available
    (native/meta_parse.cpp, identical semantics); this Python loop is the
    reference implementation and the no-toolchain fallback.
    """
    if width < 0 or width > 32:
        raise RLEError(f"invalid hybrid bit width {width} for device path")
    n = len(buf) if end is None else min(end, len(buf))
    if count > 0:
        got = _native_hybrid_meta(buf, n, pos, width, count, compute_max,
                                  eq_target)
        if got is not None:
            return got
    return _parse_hybrid_meta_py(buf, width, count, pos, n)


def _native_hybrid_meta(buf, n, pos, width, count, compute_max=False,
                        eq_target=None) -> Optional[HybridMeta]:
    from . import native

    res = native.hybrid_meta_retry(buf, n, pos, width, count,
                                   want_max=compute_max, eq_target=eq_target)
    if res is None:
        return None
    if isinstance(res, int):
        if res == -10:  # cap retry exhausted: let the Python walk diagnose
            return None
        raise RLEError(_NATIVE_ERRORS.get(res, f"hybrid parse error {res}"))
    n_runs, consumed, ends, kinds, vals, starts, max_value, eq_count = res
    rp = _bucket(max(n_runs, 1))
    run_ends = np.full(rp, count, dtype=np.int64)
    run_is_rle = np.zeros(rp, dtype=bool)
    run_values = np.zeros(rp, dtype=np.uint32)
    run_bit_starts = np.zeros(rp, dtype=np.int64)
    run_ends[:n_runs] = ends
    run_is_rle[:n_runs] = kinds.astype(bool)
    run_values[:n_runs] = vals
    run_bit_starts[:n_runs] = starts
    return HybridMeta(
        run_ends, run_is_rle, run_values, run_bit_starts, count, consumed,
        n_runs=n_runs, max_value=max_value, eq_count=eq_count,
    )


def _parse_hybrid_meta_py(
    buf: bytes, width: int, count: int, pos: int, n: int
) -> HybridMeta:
    ends, kinds, vals, starts = [], [], [], []
    total = 0
    value_bytes = (width + 7) // 8
    while total < count:
        if pos >= n:
            raise RLEError(f"hybrid stream exhausted: wanted {count}, got {total}")
        h, pos = _read_uvarint(buf, pos)
        if h & 1:
            groups = h >> 1
            nvals = groups * 8
            if nvals == 0:
                continue
            nbytes = groups * width
            if pos + nbytes > n:
                raise RLEError("truncated bit-packed run")
            take = min(nvals, count - total)
            kinds.append(False)
            vals.append(0)
            starts.append(pos * 8 - total * width)
            pos += nbytes
            total += take
        else:
            repeats = h >> 1
            if repeats == 0:
                continue
            repeats = min(repeats, count - total)
            if pos + value_bytes > n:
                raise RLEError("truncated RLE run value")
            v = int.from_bytes(buf[pos : pos + value_bytes], "little") if value_bytes else 0
            pos += value_bytes
            kinds.append(True)
            vals.append(v & 0xFFFFFFFF)
            starts.append(0)
            total += repeats
        ends.append(total)

    r = max(len(ends), 1)
    rp = _bucket(r)
    run_ends = np.full(rp, count, dtype=np.int64)
    run_is_rle = np.zeros(rp, dtype=bool)
    run_values = np.zeros(rp, dtype=np.uint32)
    run_bit_starts = np.zeros(rp, dtype=np.int64)
    if ends:
        run_ends[: len(ends)] = ends
        run_is_rle[: len(ends)] = kinds
        run_values[: len(ends)] = vals
        run_bit_starts[: len(ends)] = starts
    else:  # count == 0 never reaches here; defensive
        run_is_rle[0] = True
    return HybridMeta(
        run_ends, run_is_rle, run_values, run_bit_starts, count, pos,
        n_runs=len(ends),
    )


@functools.partial(jax.jit, static_argnames=("width", "count"))
def _hybrid_jit(buf, run_ends, run_is_rle, run_values, run_bit_starts, n_valid,
                *, width, count):
    """``count`` is the (possibly bucketed) static output size; ``n_valid`` is
    the traced real count — tail lanes beyond it are zeroed."""
    return K.expand_rle_hybrid(
        buf, run_ends, run_is_rle, run_values, run_bit_starts, width, count,
        n_valid=n_valid,
    )


@functools.partial(jax.jit, static_argnames=("max_width", "count"))
def _hybrid_vw_jit(buf, run_ends, run_is_rle, run_values, run_bit_starts,
                   run_widths, n_valid, *, max_width, count):
    """Variable-width hybrid expansion (per-run widths — multi-page dict
    chunks whose index width grows as the dictionary fills)."""
    return K.expand_rle_hybrid_vw(
        buf, run_ends, run_is_rle, run_values, run_bit_starts, run_widths,
        max_width, count, n_valid=n_valid,
    )


@scoped_x64
def decode_hybrid_device(buf_dev: jax.Array, meta: HybridMeta, width: int) -> jax.Array:
    return _hybrid_jit(
        buf_dev,
        jnp.asarray(meta.run_ends),
        jnp.asarray(meta.run_is_rle),
        jnp.asarray(meta.run_values),
        jnp.asarray(meta.run_bit_starts),
        np.int64(meta.count),
        width=width,
        count=meta.count,
    )


# ---------------------------------------------------------------------------
# DELTA_BINARY_PACKED: host block-header parse → device extract + cumsum
# ---------------------------------------------------------------------------

@dataclass
class DeltaMeta:
    first_value: int
    mini_bit_starts: np.ndarray  # int64[M] (padded: repeat last with width 0)
    mini_widths: np.ndarray      # int32[M]
    mini_min_delta: np.ndarray   # uint64[M] per-miniblock (block min repeated)
    values_per_mini: int
    count: int
    consumed: int


def _meta_from_headers(hdrs) -> DeltaMeta:
    """Bucket-pad a kernels.delta.parse_headers result into a DeltaMeta."""
    first, starts, widths, mins, values_per_mini, total, consumed = hdrs
    n = len(starts)
    mp = _bucket(max(n, 1))
    bs = np.zeros(mp, dtype=np.int64)
    ws = np.zeros(mp, dtype=np.int32)
    md = np.zeros(mp, dtype=np.uint64)
    if n:
        bs[:n] = starts
        ws[:n] = widths
        md[:n] = mins
        bs[n:] = starts[-1]
    return DeltaMeta(first, bs, ws, md, values_per_mini, total, consumed)


def parse_delta_meta(buf: bytes, bits: int, pos: int = 0) -> DeltaMeta:
    """Walk DELTA_BINARY_PACKED headers, recording per-miniblock geometry.

    The payload bytes are never touched: only the varint headers and the
    bit-width byte vectors are read (deltabp_decoder.go:38-103 structure).
    The walk itself lives in kernels.delta.parse_headers (native C with a
    Python reference fallback — one source of truth for host and device
    paths); this wrapper only adds the bucketed table padding.  ``bits`` is
    kept for API stability: widths up to 64 are accepted even for 32-bit
    columns (wrap-mod-2^32 parity with the Go reference).
    """
    return _meta_from_headers(delta_host.parse_headers(buf, pos))


def _native_delta_meta(buf: bytes, pos: int) -> Optional[DeltaMeta]:
    """Native-walk-only variant (fuzz parity oracle — see fuzz.py)."""
    hdrs = delta_host.native_headers(buf, pos)
    return None if hdrs is None else _meta_from_headers(hdrs)


def _parse_delta_meta_py(buf: bytes, bits: int, pos: int = 0) -> DeltaMeta:
    """Python-walk-only variant (fuzz parity oracle — see fuzz.py)."""
    return _meta_from_headers(delta_host.python_headers(buf, pos))


@functools.partial(
    jax.jit, static_argnames=("values_per_mini", "count", "bits", "max_width")
)
def _delta_jit(
    buf, first, starts, widths, mins, *, values_per_mini, count, bits, max_width
):
    return K.delta_reconstruct(
        buf, first, starts, widths, mins, values_per_mini, count, bits, max_width
    )


@scoped_x64
def decode_delta_device(buf_dev: jax.Array, meta: DeltaMeta, bits: int) -> jax.Array:
    return _delta_jit(
        buf_dev,
        jnp.asarray(meta.first_value, dtype=jnp.int64),
        jnp.asarray(meta.mini_bit_starts),
        jnp.asarray(meta.mini_widths),
        jnp.asarray(meta.mini_min_delta),
        values_per_mini=meta.values_per_mini,
        count=meta.count,
        bits=bits,
        max_width=max(int(meta.mini_widths.max(initial=0)), 1),
    )


# ---------------------------------------------------------------------------
# Whole-chunk device decoder
# ---------------------------------------------------------------------------

_PTYPE_TO_NAME = {
    Type.INT32: "int32",
    Type.INT64: "int64",
    Type.FLOAT: "float32",
    Type.DOUBLE: "float64",
}


@dataclass
class ParsedDataPage:
    """Host-parsed data page: decompressed bytes + levels + defined count.

    The shared front half of both device decode paths (page-at-a-time
    DeviceChunkDecoder and the batched device_reader): CRC, decompression,
    host level decode, num_nulls validation.
    """

    raw: bytes            # decompressed page bytes (value stream at value_pos)
    value_pos: int
    num_values: int
    defined: int
    encoding: int
    def_levels: Optional[np.ndarray] = None
    rep_levels: Optional[np.ndarray] = None
    # raw RLE/bit-packed level streams as (source_buffer, start, size): the
    # batched reader stages THESE (run-dominated, tiny) and expands them on
    # device, instead of shipping the host-decoded uint32 arrays (4 bytes per
    # leaf slot per level — the dominant transfer on nested files)
    def_stream: Optional[tuple] = None
    rep_stream: Optional[tuple] = None
    # def-stream run tables from the decode_levels=False walk (native eq-count
    # gives `defined` without materializing levels); reused by _plan_levels
    def_meta: Optional["HybridMeta"] = None
    # lazily-decompressed value stream: (compressed_payload, codec, ulen).
    # Set by parse_data_page(lazy_decompress=True) on pages eligible for
    # device-side snappy expansion (PLAIN values, levels outside the
    # compressed region); then ``raw`` is b"" until materialize().  Consumers
    # that need host bytes call materialize(); the device-snappy planner
    # ships the compressed payload instead.
    comp: Optional[tuple] = None

    def materialize(self) -> bytes:
        if self.comp is not None:
            self.peek()
            self.comp = None
        return self.raw

    def peek(self) -> bytes:
        """Decompressed bytes WITHOUT dropping the compressed payload.

        The byte-array ship routes need both: the host walks length
        prefixes over the decompressed stream, but the LINK still carries
        the compressed payload (device-side expansion).  ``materialize()``
        keeps its drop-the-payload semantics for routes that commit to
        host bytes.
        """
        if self.comp is not None and len(self.raw) == 0:
            payload, codec, ulen = self.comp
            self.raw = decompress_block(payload, codec, ulen)
        return self.raw


def parse_data_page(
    ps: PageSlice, buf: bytes, codec: int, leaf: SchemaNode,
    validate_crc: bool = False, alloc=None, decode_levels: bool = True,
    lazy_decompress: bool = False,
) -> ParsedDataPage:
    """Parse one v1/v2 data page on host (no device work).

    With ``decode_levels=False`` (the batched reader) neither level array is
    host-decoded: rep streams are only *located* (the v1 length prefix gives
    the span without decoding), and def streams are header-walked with the
    native eq-counter (meta_parse.cpp want_eq) so the defined-value count —
    which gates every static decode shape — comes straight off the run walk;
    the run tables are kept on the page for the device-side expansion.
    Without the native library the def levels fall back to a host decode
    (the count has to come from somewhere).  The device-side
    *reconstruction* from levels (validity scatter, row starts) runs as
    prefix scans in jax_kernels.
    """
    header = ps.header
    payload = buf[ps.payload_start : ps.payload_end]
    _check_crc(header, payload, validate_crc)
    if alloc is not None:
        # register the REAL decompressed size before materializing it — the
        # chunk-level metadata totals are attacker-controlled and optional
        alloc.register(max(header.uncompressed_page_size or 0, 0))
    max_rep, max_def = leaf.max_rep, leaf.max_def
    if header.type == PageType.DATA_PAGE:
        dh = header.data_page_header
        num_values = dh.num_values or 0
        if num_values < 0:
            raise ParquetError(f"negative page value count {num_values}")
        if (lazy_decompress and max_rep == 0 and max_def == 0
                and parse_encoding(dh.encoding) == Encoding.PLAIN):
            # no levels inside the compressed region: the whole payload is
            # the PLAIN value stream — keep it compressed for device-side
            # expansion (materialize() restores the host bytes on demand)
            return ParsedDataPage(
                raw=b"", value_pos=0, num_values=num_values,
                defined=num_values, encoding=dh.encoding,
                comp=(payload, codec, max(header.uncompressed_page_size or 0,
                                          0)),
            )
        raw = decompress_block(payload, codec, header.uncompressed_page_size)
        pos = 0
        rlv = dlv = None
        rsp = dsp = None
        def_meta = None

        def _prefixed_span(p0):
            """v1 length prefix: locate the stream without decoding it."""
            if len(raw) - p0 < 4:
                raise ParquetError("truncated level stream length prefix")
            size = int.from_bytes(raw[p0 : p0 + 4], "little")
            if p0 + 4 + size > len(raw):
                raise ParquetError(f"level stream length {size} exceeds page")
            return size

        if max_rep > 0:
            if decode_levels:
                rlv, used = rle.decode_prefixed(
                    raw[pos:], bitpack.bit_width(max_rep), num_values
                )
            else:
                used = 4 + _prefixed_span(pos)
            rsp = (raw, pos + 4, used - 4)  # hybrid payload past the u32 size
            pos += used
        if max_def > 0:
            w = bitpack.bit_width(max_def)
            if decode_levels:
                dlv, used = rle.decode_prefixed(raw[pos:], w, num_values)
            else:
                size = _prefixed_span(pos)
                used = 4 + size
                def_meta = parse_hybrid_meta(
                    raw, w, num_values, pos=pos + 4, end=pos + 4 + size,
                    eq_target=max_def,
                )
                if def_meta.eq_count is None:  # no native walk: must decode
                    dlv, _ = rle.decode_prefixed(raw[pos:], w, num_values)
            dsp = (raw, pos + 4, used - 4)
            pos += used
        if def_meta is not None and def_meta.eq_count is not None:
            defined = def_meta.eq_count
        elif dlv is not None:
            defined = int(np.count_nonzero(dlv == max_def))
        else:
            defined = num_values
        return ParsedDataPage(
            raw=raw, value_pos=pos, num_values=num_values, defined=defined,
            encoding=dh.encoding, def_levels=dlv, rep_levels=rlv,
            def_stream=dsp, rep_stream=rsp, def_meta=def_meta,
        )

    dh = header.data_page_header_v2
    num_values = dh.num_values or 0
    if num_values < 0:
        raise ParquetError(f"negative page value count {num_values}")
    rep_len = dh.repetition_levels_byte_length or 0
    def_len = dh.definition_levels_byte_length or 0
    if rep_len < 0 or def_len < 0 or rep_len + def_len > len(payload):
        raise ParquetError("v2 level lengths exceed page")
    rlv = dlv = None
    rsp = dsp = None
    def_meta = None
    if max_rep > 0:
        if rep_len == 0:
            raise ParquetError("v2 page missing repetition levels")
        if decode_levels:
            rlv = rle.decode(payload[:rep_len], bitpack.bit_width(max_rep),
                             num_values)
        rsp = (payload, 0, rep_len)
    if max_def > 0:
        w = bitpack.bit_width(max_def)
        if decode_levels:
            dlv = rle.decode(
                payload[rep_len : rep_len + def_len], w, num_values
            )
        else:
            def_meta = parse_hybrid_meta(
                payload, w, num_values, pos=rep_len,
                end=rep_len + def_len, eq_target=max_def,
            )
            if def_meta.eq_count is None:  # no native walk: must decode
                dlv = rle.decode(
                    payload[rep_len : rep_len + def_len], w, num_values
                )
        dsp = (payload, rep_len, def_len)
    if def_meta is not None and def_meta.eq_count is not None:
        defined = def_meta.eq_count
    elif dlv is not None:
        defined = int(np.count_nonzero(dlv == max_def))
    else:
        defined = num_values
    if dh.num_nulls is not None and max_def > 0 and max_rep == 0:
        actual_nulls = num_values - defined
        if dh.num_nulls != actual_nulls:
            raise ParquetError(
                f"v2 page declares {dh.num_nulls} nulls, levels say {actual_nulls}"
            )
    values_block = payload[rep_len + def_len :]
    uncompressed_values = header.uncompressed_page_size - rep_len - def_len
    comp = None
    if dh.is_compressed is None or dh.is_compressed:
        if (lazy_decompress
                and parse_encoding(dh.encoding) == Encoding.PLAIN):
            # v2 keeps levels OUTSIDE the compressed region, so the value
            # block can stay compressed for device-side expansion
            raw, comp = b"", (values_block, codec,
                              max(uncompressed_values, 0))
        else:
            raw = decompress_block(values_block, codec, uncompressed_values)
    else:
        raw = values_block
    return ParsedDataPage(
        raw=raw, value_pos=0, num_values=num_values, defined=defined,
        encoding=dh.encoding, def_levels=dlv, rep_levels=rlv,
        def_stream=dsp, rep_stream=rsp, def_meta=def_meta, comp=comp,
    )


def host_decode_dictionary(raw: bytes, leaf: SchemaNode, encoding: int, count: int):
    """Decode a dictionary page's values on host.

    Returns ByteArrayData for ragged dictionaries, else (u8_rows, dtype_name, n)
    — the byte-row staging form dict_gather_bytes consumes.
    """
    from .kernels import plain as plain_host

    enc = parse_encoding(encoding, "dictionary page encoding")
    if enc not in (Encoding.PLAIN, Encoding.PLAIN_DICTIONARY):
        raise ParquetError(f"dictionary page encoding {enc.name} unsupported")
    if count < 0:
        raise ParquetError(f"negative dictionary size {count}")
    decoded = plain_host.decode(raw, leaf.physical_type, count, leaf.type_length)
    if isinstance(decoded, ByteArrayData):
        return decoded
    arr = np.ascontiguousarray(decoded)
    n = len(arr)
    row_bytes = (arr.nbytes // n) if n else arr.dtype.itemsize
    base = arr.dtype.name if arr.ndim == 1 else "uint32"  # INT96: (n,3) u32
    u8 = (
        arr.view(np.uint8).reshape(n, row_bytes)
        if n else np.zeros((0, row_bytes), dtype=np.uint8)
    )
    return u8, base, n


# The value stream starts at a page-dependent byte offset inside the staged
# page buffer; the offset is a *traced* scalar so one executable serves every
# page of the same (dtype, count) geometry — no recompile, no re-staging.

@functools.partial(jax.jit, static_argnames=("dtype", "count"))
def _plain_jit(buf, off, *, dtype, count):
    nbytes = 8 if dtype in ("int64", "float64") else 4
    raw = jax.lax.dynamic_slice(buf, (off,), (count * nbytes,))
    return K.plain_decode_fixed(raw, dtype, count)


@functools.partial(jax.jit, static_argnames=("k", "count"))
def _plain_rows_jit(buf, off, *, k, count):
    """PLAIN INT96 rows: 12-byte rows bitcast to little-endian u32[count, 3]
    (the host decoder's layout)."""
    raw = jax.lax.dynamic_slice(buf, (off,), (count * k,))
    return jax.lax.bitcast_convert_type(
        raw.reshape(count, k // 4, 4), jnp.uint32
    ).reshape(count, k // 4)


@functools.partial(jax.jit, static_argnames=("k", "count"))
def _plain_flba_jit(buf, off, *, k, count):
    """PLAIN FIXED_LEN_BYTE_ARRAY: uniform (offsets, heap) ragged form —
    the host decoder's representation (kernels/plain.py FLBA)."""
    heap = jax.lax.dynamic_slice(buf, (off,), (count * k,))
    offsets = jnp.arange(count + 1, dtype=jnp.int64) * k
    return offsets, heap


@functools.partial(jax.jit, static_argnames=("dtype", "count"))
def _bss_jit(buf, off, *, dtype, count):
    nbytes = 8 if dtype in ("int64", "float64") else 4
    raw = jax.lax.dynamic_slice(buf, (off,), (count * nbytes,))
    return K.byte_stream_split_decode(raw, dtype, count)


@functools.partial(jax.jit, static_argnames=("count",))
def _bool_plain_jit(buf, off, *, count):
    bit_pos = off.astype(jnp.int64) * 8 + jnp.arange(count, dtype=jnp.int64)
    return K.extract_bits(buf, bit_pos, 1, 1).astype(jnp.bool_)


@functools.partial(jax.jit, static_argnames=("dtype",))
def _dict_gather_bytes_jit(dict_u8, indices, *, dtype):
    return K.dict_gather_bytes(dict_u8, indices, dtype)


@functools.partial(jax.jit, static_argnames=("k", "itemsize"))
def _dict_rows_jit(buf, base, *, k, itemsize):
    """Cut a dictionary's (k, itemsize) u8 rows out of the staged buffer.

    The dictionary bytes ride the one row-group transfer instead of a
    separate jnp.asarray per chunk (each such transfer costs a fixed
    ~50-100ms tunnel round trip); this on-device slice is an async dispatch.
    ``k`` is bucketed, and the caller MUST stage the dictionary with a
    zero-filled reserve covering k*itemsize (stager.add(..., reserve=...)):
    on the deferred range-check path, clamped out-of-range indices DO gather
    the tail rows before validation resolves, and they must read as zeros —
    never a neighboring chunk's staged bytes (see device_reader._finish_dict).
    """
    return jax.lax.dynamic_slice(buf, (base,), (k * itemsize,)).reshape(
        k, itemsize
    )


@functools.partial(jax.jit, static_argnames=("out_heap_size",))
def _ragged_take_jit(offsets, heap, indices, *, out_heap_size):
    return K.ragged_take(offsets, heap, indices, out_heap_size)


# Eager (non-jit) ops are poison on a tunneled TPU backend: the FIRST dispatch
# of every distinct eager op/shape pays a full XLA compile (~0.7-3s measured on
# axon), so even a handful of stray jnp.max / slice / concatenate calls in the
# decode path dwarfs the actual decode.  Everything below keeps those tail ops
# inside jit; np scalars and np.zeros feed jit/device_put directly so no eager
# broadcast is ever dispatched.

_max_jit = jax.jit(jnp.max)


@jax.jit
def _concat_jit(parts):
    return jnp.concatenate(parts)


@jax.jit
def _concat_ragged_jit(offs, heaps):
    """Concatenate per-page (offsets, heap) pairs into one ragged column.

    Offsets are rebased by the running heap length entirely on device — no
    host sync on the per-page heap sizes.
    """
    out_offs = [offs[0]]
    base = offs[0][-1]
    for o in offs[1:]:
        out_offs.append(o[1:] + base)
        base = base + o[-1]
    return jnp.concatenate(out_offs), jnp.concatenate(heaps)


@functools.partial(jax.jit, static_argnames=("size",))
def _slice_jit(x, *, size):
    return x[:size]


@jax.jit
def _stack_jit(xs):
    # stack deferred-check scalars on device so syncing them costs ONE host
    # transfer: the tunneled backend charges a full round trip per transfer,
    # and jax.device_get fetches list leaves one by one
    return jnp.stack(xs)


@dataclass
class DeviceColumnData:
    """Decoded column chunk resident on device.

    Fixed-width: ``values`` is a jax Array of the defined values.  BYTE_ARRAY:
    ``offsets``/``heap`` hold the ragged representation on device instead.
    Levels (when present) are device uint32 arrays, one per leaf slot.
    """

    values: Optional[jax.Array] = None
    offsets: Optional[jax.Array] = None
    heap: Optional[jax.Array] = None
    def_levels: Optional[jax.Array] = None
    rep_levels: Optional[jax.Array] = None
    max_def: int = 0
    max_rep: int = 0
    num_leaf_slots: int = 0
    # logical dtype when the device representation differs: DOUBLE columns are
    # uint32[n,2] word pairs on device (TPU f64 emulation rounds real f64 data —
    # see jax_kernels.plain_decode_fixed) and only become f64 on the host.
    value_dtype: Optional[str] = None
    # Number of REAL defined values; device arrays may be padded past it to a
    # bucketed static shape (executable sharing across chunks — _bucket_count).
    # None means the arrays are exact.  Level arrays may likewise be padded
    # past num_leaf_slots.  A jitted consumer *wants* the bucketed shapes (it
    # recompiles per shape); host materialization slices the padding off.
    n_values: Optional[int] = None

    @property
    def num_values(self) -> int:
        """Real defined-value count (excludes bucketing pad and nulls)."""
        if self.n_values is not None:
            return self.n_values
        if self.values is not None:
            return int(self.values.shape[0])
        if self.offsets is not None:
            return max(int(self.offsets.shape[0]) - 1, 0)
        return 0

    def validity(self) -> jax.Array:
        if self.def_levels is None:
            return jnp.ones(self.num_leaf_slots, dtype=bool)
        # def_levels may be bucket-padded; tail lanes are garbage, so the
        # mask must stop at the real slot count
        return K.levels_to_validity(
            self.def_levels, self.max_def
        )[: self.num_leaf_slots]

    def levels_to_host(self):
        """(def_levels, rep_levels) as exact host arrays (padding sliced)."""
        n = self.num_leaf_slots
        d = None if self.def_levels is None else np.asarray(self.def_levels)[:n]
        r = None if self.rep_levels is None else np.asarray(self.rep_levels)[:n]
        return d, r

    def to_host(self) -> "ByteArrayData | np.ndarray":
        n = self.num_values
        if self.offsets is not None:
            off = np.asarray(self.offsets)[: n + 1]
            heap = np.asarray(self.heap)
            if len(off) and heap.nbytes > off[-1]:
                heap = heap[: off[-1]]  # drop bucketed staging padding
            return ByteArrayData(offsets=off, heap=heap)
        vals = np.asarray(self.values)[:n]
        if self.value_dtype == "float64" and vals.ndim == 2:
            return np.ascontiguousarray(vals).view("<f8").reshape(len(vals))
        return vals


class DeviceChunkDecoder:
    """Decode one column chunk into device-resident arrays.

    Mirrors chunk_decode.ChunkDecoder page-for-page; falls back to the host
    kernels only for the sequential byte-array paths (PLAIN/DELTA BYTE_ARRAY
    value streams), shipping their (offsets, heap) results to device.
    """

    def __init__(self, leaf: SchemaNode, validate_crc: bool = False,
                 context: "dict | None" = None):
        self.leaf = leaf
        self.validate_crc = validate_crc
        self.context = dict(context or {})
        self.dict_u8: Optional[jax.Array] = None           # fixed-width dict, u8 rows
        self.dict_dtype: Optional[str] = None              # target dtype name
        self.dict_len: int = 0
        self.dict_offsets: Optional[jax.Array] = None      # ragged dict
        self.dict_heap: Optional[jax.Array] = None
        self._dict_host_offsets: Optional[np.ndarray] = None
        self._idx_maxima: list = []  # per-page device max dict index, checked per chunk

    # -- dictionary ----------------------------------------------------------

    def _decode_dict_page(self, ps: PageSlice, buf: bytes, codec: int) -> None:
        header = ps.header
        payload = buf[ps.payload_start : ps.payload_end]
        _check_crc(header, payload, self.validate_crc)
        raw = decompress_block(payload, codec, header.uncompressed_page_size)
        dh = header.dictionary_page_header
        decoded = host_decode_dictionary(
            raw, self.leaf, dh.encoding, dh.num_values or 0
        )
        if isinstance(decoded, ByteArrayData):
            self._dict_host_offsets = decoded.offsets
            self.dict_offsets = jnp.asarray(decoded.offsets)
            self.dict_heap = jnp.asarray(decoded.heap)
            self.dict_len = len(decoded)
        else:
            # raw byte rows: gathers must move bits verbatim, and u8[...,k]→wide
            # bitcasts are the only ones TPU's X64 pass supports
            u8, base, n = decoded
            self.dict_u8 = jnp.asarray(u8)
            self.dict_dtype = base
            self.dict_len = n

    # -- values --------------------------------------------------------------

    def _decode_values_device(self, enc: int, raw: bytes, pos: int, count: int):
        """Decode the value stream at byte offset ``pos`` of page bytes ``raw``.

        Returns (values_array, offsets, heap) — exactly one representation set.
        ``raw`` is staged to device at most once; all kernels address into it
        with byte/bit offsets instead of re-staging slices.
        """
        ptype = self.leaf.physical_type
        avail = len(raw) - pos
        enc = parse_encoding(enc)
        if enc == Encoding.PLAIN_DICTIONARY:
            enc = Encoding.RLE_DICTIONARY

        if enc == Encoding.PLAIN:
            if ptype == Type.BOOLEAN:
                need = (count + 7) // 8
                if avail < need:
                    raise ParquetError(f"PLAIN BOOLEAN truncated: {avail} < {need}")
                return (
                    _bool_plain_jit(
                        pad_buffer(raw), np.int64(pos), count=count
                    ),
                    None,
                    None,
                )
            name = _PTYPE_TO_NAME.get(ptype)
            if name is not None:
                need = count * np.dtype(name).itemsize
                if avail < need:
                    raise ParquetError(f"PLAIN data truncated: {avail} < {need}")
                return (
                    _plain_jit(pad_buffer(raw), np.int64(pos), dtype=name, count=count),
                    None,
                    None,
                )
            # INT96 / BYTE_ARRAY / FIXED: host parse, device-stage result
            from .kernels import plain as plain_host

            decoded = plain_host.decode(raw[pos:], ptype, count, self.leaf.type_length)
            if isinstance(decoded, ByteArrayData):
                return None, jnp.asarray(decoded.offsets), jnp.asarray(decoded.heap)
            return jnp.asarray(decoded), None, None

        if enc == Encoding.RLE_DICTIONARY:
            if self.dict_u8 is None and self.dict_offsets is None:
                raise ParquetError("dictionary-encoded page but no dictionary page seen")
            if avail < 1:
                raise ParquetError("dictionary page data truncated (missing width)")
            width = int(raw[pos])
            if width > 32:
                raise ParquetError(f"dictionary index width {width} invalid")
            meta = parse_hybrid_meta(raw, width, count, pos=pos + 1,
                                     compute_max=True)
            idx = decode_hybrid_device(pad_buffer(raw), meta, width)
            if self.dict_u8 is not None:
                if count and self.dict_len == 0:
                    raise ParquetError("dictionary indices with empty dictionary")
                # range check: on host when the native walk reported the max;
                # otherwise deferred to the end of the chunk (decode()) as one
                # on-device max + one sync
                if count and meta.max_value is not None:
                    if meta.max_value >= self.dict_len:
                        raise ParquetError(
                            f"dictionary index {meta.max_value} out of range "
                            f"({self.dict_len})"
                        )
                elif count:
                    self._idx_maxima.append(_max_jit(idx))
                return (
                    _dict_gather_bytes_jit(self.dict_u8, idx, dtype=self.dict_dtype),
                    None,
                    None,
                )
            # ragged dictionary: need output heap size on host
            host_idx = np.asarray(idx, dtype=np.int64)
            off = self._dict_host_offsets
            if count and host_idx.max(initial=0) >= len(off) - 1:
                raise ParquetError(
                    f"dictionary index {int(host_idx.max())} out of range ({len(off) - 1})"
                )
            out_heap = int((off[host_idx + 1] - off[host_idx]).sum())
            new_off, new_heap = _ragged_take_jit(
                self.dict_offsets, self.dict_heap, idx,
                out_heap_size=_bucket_bytes(max(out_heap, 1), 64),
            )
            if not out_heap:
                return None, new_off, jnp.asarray(np.zeros(0, dtype=np.uint8))
            return None, new_off, _slice_jit(new_heap, size=out_heap)

        if enc == Encoding.DELTA_BINARY_PACKED:
            bits = 32 if ptype == Type.INT32 else 64
            if ptype not in (Type.INT32, Type.INT64):
                raise ParquetError(f"DELTA_BINARY_PACKED invalid for {ptype!r}")
            meta = parse_delta_meta(raw, bits, pos=pos)
            if meta.count < count:
                raise ParquetError(f"delta stream yielded {meta.count} of {count} values")
            vals = decode_delta_device(pad_buffer(raw), meta, bits)
            if meta.count == count:
                return vals, None, None
            return _slice_jit(vals, size=count), None, None

        if enc == Encoding.BYTE_STREAM_SPLIT:
            name = _PTYPE_TO_NAME.get(ptype)
            if name is None:
                # FIXED_LEN_BYTE_ARRAY etc.: host decode, stage the result
                # (same fallback pattern as the sequential byte-array paths)
                from .chunk_decode import _byte_stream_split_decode

                decoded = _byte_stream_split_decode(
                    raw[pos:], ptype, count, self.leaf.type_length
                )
                if isinstance(decoded, ByteArrayData):
                    return None, jnp.asarray(decoded.offsets), jnp.asarray(decoded.heap)
                return jnp.asarray(decoded), None, None
            need = count * np.dtype(name).itemsize
            if avail < need:
                raise ParquetError(f"BYTE_STREAM_SPLIT truncated: {avail} < {need}")
            return (
                _bss_jit(pad_buffer(raw), np.int64(pos), dtype=name, count=count),
                None,
                None,
            )

        if enc == Encoding.RLE:
            if ptype != Type.BOOLEAN:
                raise ParquetError(f"RLE value encoding invalid for {ptype!r}")
            if avail < 4:
                raise ParquetError("truncated boolean RLE stream")
            size = int.from_bytes(raw[pos : pos + 4], "little")
            if pos + 4 + size > len(raw):
                raise ParquetError(f"boolean RLE length {size} exceeds page")
            meta = parse_hybrid_meta(raw, 1, count, pos=pos + 4, end=pos + 4 + size)
            vals = decode_hybrid_device(pad_buffer(raw), meta, 1)
            return vals.astype(jnp.bool_), None, None

        # DELTA_LENGTH_BYTE_ARRAY / DELTA_BYTE_ARRAY: host decode, stage result
        from .kernels import bytearray as ba_host

        if enc == Encoding.DELTA_LENGTH_BYTE_ARRAY:
            d = ba_host.decode_delta_length(raw[pos:], count)
            return None, jnp.asarray(d.offsets), jnp.asarray(d.heap)
        if enc == Encoding.DELTA_BYTE_ARRAY:
            d = ba_host.decode_delta(raw[pos:], count)
            return None, jnp.asarray(d.offsets), jnp.asarray(d.heap)
        raise ParquetError(f"unsupported value encoding {enc.name} for {ptype!r}")

    # -- pages ---------------------------------------------------------------

    def _decode_data_page(self, ps: PageSlice, buf: bytes, codec: int):
        """Shared host parse (parse_data_page) + device value decode."""
        p = parse_data_page(ps, buf, codec, self.leaf, self.validate_crc)
        v, off, heap = self._decode_values_device(
            p.encoding, p.raw, p.value_pos, p.defined
        )
        dlv = jnp.asarray(p.def_levels) if p.def_levels is not None else None
        rlv = jnp.asarray(p.rep_levels) if p.rep_levels is not None else None
        return v, off, heap, dlv, rlv, p.num_values

    # -- chunk ---------------------------------------------------------------

    @scoped_x64
    def decode(self, buf: bytes, codec: int, total_values: int) -> DeviceColumnData:
        from .quarantine import error_context

        ctx = dict(self.context)
        if "column" not in ctx and self.leaf.path:
            ctx["column"] = ".".join(self.leaf.path)
        # absolute file offsets in the records, matching the host paths'
        # (a ledger offset an operator seeks to must be the page's, not a
        # chunk-relative one)
        chunk_offset = ctx.pop("chunk_offset", 0) or 0
        with error_context(**ctx):
            pages = walk_pages(buf, total_values)
        vals_parts, off_parts, heap_parts = [], [], []
        def_parts, rep_parts = [], []
        slots = 0
        page_ordinal = 0
        self._idx_maxima = []
        for ps in pages:
            pt = ps.header.type
            if pt == PageType.DICTIONARY_PAGE:
                with error_context(offset=chunk_offset + ps.payload_start,
                                   **ctx):
                    self._decode_dict_page(ps, buf, codec)
                continue
            if pt in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2):
                with error_context(page=page_ordinal,
                                   offset=chunk_offset + ps.payload_start,
                                   **ctx):
                    v, off, heap, d, r, n = self._decode_data_page(
                        ps, buf, codec)
                page_ordinal += 1
            else:
                continue
            slots += n
            if v is not None:
                vals_parts.append(v)
            else:
                off_parts.append(off)
                heap_parts.append(heap)
            if d is not None:
                def_parts.append(d)
            if r is not None:
                rep_parts.append(r)

        if self._idx_maxima:
            mx = int(np.asarray(_stack_jit(self._idx_maxima)).max())
            if mx >= self.dict_len:
                raise ParquetError(
                    f"dictionary index {mx} out of range ({self.dict_len})"
                )

        out = DeviceColumnData(
            max_def=self.leaf.max_def,
            max_rep=self.leaf.max_rep,
            num_leaf_slots=slots,
            value_dtype=(
                "float64" if self.leaf.physical_type == Type.DOUBLE else None
            ),
        )
        if off_parts:
            if len(off_parts) == 1:
                out.offsets, out.heap = off_parts[0], heap_parts[0]
            else:
                out.offsets, out.heap = _concat_ragged_jit(off_parts, heap_parts)
        elif vals_parts:
            out.values = (
                vals_parts[0] if len(vals_parts) == 1 else _concat_jit(vals_parts)
            )
        else:
            out.values = jnp.asarray(np.zeros(0, dtype=np.int64))
        if def_parts:
            out.def_levels = (
                def_parts[0] if len(def_parts) == 1 else _concat_jit(def_parts)
            )
        if rep_parts:
            out.rep_levels = (
                rep_parts[0] if len(rep_parts) == 1 else _concat_jit(rep_parts)
            )
        return out


@scoped_x64
def read_chunk_device(
    f, chunk, leaf: SchemaNode, validate_crc: bool = False
) -> DeviceColumnData:
    """Device twin of chunk_decode.read_chunk (same seek/size/meta discipline)."""
    md, offset = validate_chunk_meta(chunk, leaf)
    f.seek(offset)
    buf = f.read(md.total_compressed_size)
    if len(buf) != md.total_compressed_size:
        raise ParquetError(
            f"chunk truncated: wanted {md.total_compressed_size} bytes at {offset}, "
            f"got {len(buf)}"
        )
    dec = DeviceChunkDecoder(leaf, validate_crc=validate_crc,
                             context={"chunk_offset": offset})
    return dec.decode(buf, md.codec, md.num_values)
