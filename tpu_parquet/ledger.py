"""Versioned run ledger + noise-aware bench diff (observability over runs).

PR 4 made one run attributable (spans, histograms, one registry tree); this
module makes the *trajectory* machine-checkable.  Three pieces, stdlib-only
like obs.py:

- **Ledger** — an append-only ``ledger.jsonl`` of run records: each line is
  the full bench record (per-config metrics, rep lists, registry trees)
  wrapped with a schema version, timestamp, git revision, and an environment
  fingerprint (every ``TPQ_*``/``BENCH_*`` knob that changes what a number
  means — two runs with different ``TPQ_LINK_MBPS`` are different
  experiments, and the ledger says so).  ``bench.py`` appends automatically.

- **Noise-aware diff** — :func:`diff` compares two run records per config
  and metric, with the tolerance band derived from the REP VARIANCE both
  records already carry (``device_windows_s``, ``host_reps_s``, ...): a
  delta is only a regression/improvement when it leaves ``max(z * combined
  rel-MAD, floor)``.  Flagged regressions are *attributed*: the registry
  stage whose seconds moved the most is named next to the metric
  (:func:`attribute_stages`) — "lineitem16 device throughput -52%, the
  decompress lane grew 2.1x" instead of a bare red number.

- **Gate** — :func:`check` is the CI form: only regressions, with a wider
  default floor (``DEFAULT_CHECK_FLOOR``) so weather-prone boxes gate on
  2x-class regressions, not 5% drifts.  ``bench.py --check-against
  BASELINE.json`` exits nonzero through it; ``pq_tool bench diff A B`` /
  ``bench history`` are the human surfaces.

Records compare only when their config's ``rows`` match — a smoke run
against a full-scale baseline yields "incomparable", never a fake 100x
regression.
"""

from __future__ import annotations

import json
import os
import platform
import subprocess
import time

__all__ = [
    "LEDGER_VERSION", "append", "attribute_stages", "check", "default_path",
    "diff", "env_fingerprint", "format_diff", "format_history", "git_rev",
    "is_ref", "load_side", "make_record", "read", "rel_noise",
]

# version of the ledger line schema; bumped when a field changes meaning so
# `bench history` / `bench diff` can refuse records they'd misread
LEDGER_VERSION = 1

# the environment that changes what a bench number MEANS: route/link knobs,
# sampling shape, and the backend.  Recorded per run so a diff across a knob
# flip is visibly a different experiment.
_ENV_KEYS = (
    "TPQ_LINK_MBPS", "TPQ_FORCE_ROUTE", "TPQ_TRACE", "TPQ_SAMPLE_MS",
    "TPQ_DEVICE_SNAPPY", "TPQ_COMPILE_CACHE", "TPQ_FUSE_RG", "TPQ_FUSE",
    "TPQ_PALLAS",
    "TPQ_DEFER_DICT_CHECK", "TPQ_DEVICE_MBPS", "TPQ_DEVICE_TIMING",
    "TPQ_XPROF", "TPQ_SERVE_CONCURRENCY", "TPQ_SERVE_QUEUE",
    "TPQ_PLAN_CACHE_MB", "TPQ_RESULT_CACHE_MB", "TPQ_RESULT_CACHE_HBM_MB",
    "TPQ_SERVE_BROWNOUT", "TPQ_IO_HEDGE_MS",
    "TPQ_SERVE_FAIR", "TPQ_SERVE_TENANTS", "TPQ_STREAM_BUFFER_BATCHES",
    "TPQ_WRITE_CRC", "TPQ_WRITE_WORKERS",
    "TPQ_IO_HEDGE_MAX", "TPQ_IO_INFLIGHT", "TPQ_IO_ASYNC",
    "TPQ_CIRCUIT_FAILS", "TPQ_CIRCUIT_WINDOW_S",
    "TPQ_CIRCUIT_COOLDOWN_S",
    "TPQ_TRACE_TAIL", "TPQ_TRACE_RING", "TPQ_TRACE_SPANS",
    "TPQ_TRACE_SLOW_Q", "TPQ_METRICS_DUMP",
    "TPQ_OBS_SPOOL", "TPQ_OBS_SPOOL_S", "TPQ_OBS_SPOOL_KEEP",
    "TPQ_OBS_STALE_S", "TPQ_SERVE_STREAM_YIELD",
    "BENCH_SCALE", "BENCH_DEVICE_REPS",
    "BENCH_BASELINE_REPS", "BENCH_RESAMPLE", "BENCH_CONFIGS",
    "JAX_PLATFORMS",
)

# gated per-config metrics -> (rep-list key for the noise bound, direction).
# direction +1: higher is better.  The rep lists are the raw per-rep SECONDS
# bench.py already banks in every artifact; a metric whose reps are absent
# falls back to the floor alone.
_METRICS = {
    "device_rows_per_sec": ("device_windows_s", 1),
    "device_mb_per_sec": ("device_windows_s", 1),
    "host_rows_per_sec": ("host_reps_s", 1),
    "pyarrow_rows_per_sec": ("pyarrow_reps_s", 1),
    "device_vs_host": ("device_windows_s", 1),
    "device_vs_host_pipeline": ("device_windows_s", 1),
    "prefetch0_rows_per_sec": ("prefetch0_reps_s", 1),
    "prefetch4_rows_per_sec": ("prefetch4_reps_s", 1),
    "pipeline_speedup": ("prefetch4_reps_s", 1),
    "loader_speedup": ("prefetch4_reps_s", 1),
    "scan_files_rows_per_sec": ("scan_files_reps_s", 1),
    # byte counts are deterministic functions of the code + file: any move
    # is real, the floor alone bounds them; fewer shipped bytes is better
    "link_bytes_ratio": (None, -1),
}

DEFAULT_NOISE_Z = 3.0
DEFAULT_DIFF_FLOOR = 0.10   # human diff: show 10%+ moves beyond noise
DEFAULT_CHECK_FLOOR = 0.30  # CI gate: 2x-class regressions, not drift

# registry stage seconds the attribution ranks (the obs pipeline tree)
_STAGE_KEYS = (
    "io_seconds", "decompress_seconds", "recompress_seconds",
    "stage_seconds", "dispatch_seconds", "finalize_seconds", "stall_seconds",
)


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------

def env_fingerprint() -> dict:
    fp = {
        "python": platform.python_version(),
        "platform": platform.platform(),
    }
    for k in _ENV_KEYS:
        v = os.environ.get(k)
        if v is not None:
            fp[k] = v
    # whether Pallas kernels (the fused decode megakernels included) ran
    # compiled (native Mosaic) or through the interpreter: an
    # interpret-mode device number is bit-identical but NOT a kernel
    # measurement, and a banked run must say which it was.  Best-effort:
    # a ledger read on a jax-less host still fingerprints the rest.
    try:
        from .pallas_kernels import pallas_mode

        fp["pallas_mode"] = pallas_mode()
    except Exception:  # noqa: BLE001 — fingerprinting never raises
        pass
    return fp


def git_rev(cwd: "str | None" = None) -> "str | None":
    """Best-effort short revision of the running tree (None outside git)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)))
    except (OSError, subprocess.SubprocessError):
        return None
    rev = out.stdout.strip()
    return rev if out.returncode == 0 and rev else None


def make_record(bench_record: dict, ts: "float | None" = None) -> dict:
    """Wrap one bench result tree as a versioned ledger record."""
    rec = {
        "ledger_version": LEDGER_VERSION,
        "ts": round(time.time() if ts is None else float(ts), 3),
        "git_rev": git_rev(),
        "env": env_fingerprint(),
    }
    rec.update(bench_record)
    return rec


def append(path: str, record: dict) -> int:
    """Append one record (one compact JSON line); returns its 0-based
    sequence number.  Missing parent directories are created — same
    contract as ``Tracer.write`` (no late FileNotFoundError after the run
    already happened).

    The record and its newline go down in ONE ``write`` call, and a torn
    tail left by a writer that died mid-append (bytes after the last
    newline) is truncated away first — that record was never durably
    written, and gluing the new line onto it would poison the whole
    ledger for every later ``read``.
    """
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    seq = 0
    if os.path.exists(path):
        with open(path, "r+b") as f:
            data = f.read()
            if data and not data.endswith(b"\n"):
                # in-place truncate of JUST the torn bytes — a rewrite
                # (open "wb" + write-back) would hold the whole ledger
                # hostage to a crash mid-rewrite, destroying the durable
                # records the repair exists to protect
                data = data[: data.rfind(b"\n") + 1]
                f.truncate(len(data))
        seq = sum(1 for line in data.splitlines() if line.strip())
    with open(path, "a") as f:
        f.write(json.dumps(record, separators=(",", ":"), sort_keys=True)
                + "\n")
    return seq


def read(path: str) -> "list[dict]":
    """All records of a ledger.  A torn TAIL (a final line without its
    newline — a writer died mid-append) is skipped: the intact records
    must stay readable.  Corruption anywhere else is fatal — silently
    dropping a mid-file record would shift every ``#N`` address."""
    with open(path) as f:
        text = f.read()
    ends_complete = text.endswith("\n")
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    out = []
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError as e:
            if i == len(lines) - 1 and not ends_complete:
                break  # torn tail: never durably written
            raise ValueError(
                f"{path}:{i + 1}: corrupt ledger line ({e})") from None
    return out


def default_path() -> str:
    """The default ledger the bare refs resolve against: ``TPQ_LEDGER``
    when set, else ``ledger.jsonl`` in the working directory (the same
    name bench.py appends to next to its artifact)."""
    return os.environ.get("TPQ_LEDGER") or "ledger.jsonl"


def is_ref(spec: str) -> bool:
    """True when ``spec`` is a ledger reference rather than a plain
    artifact path: ``latest``, ``latest#N``, ``#N``, ``*.jsonl``, or
    ``*.jsonl#N`` — the forms ``load_side`` resolves through a ledger."""
    path, _, _idx = spec.partition("#")
    return path in ("", "latest") or path.endswith(".jsonl")


def load_side(spec: str) -> dict:
    """Resolve one side of a diff/check to a run record.

    Accepted forms: a bench artifact ``*.json`` (read whole), a ledger
    ``*.jsonl`` (its LAST record), ``ledger.jsonl#N`` (record N; negative
    counts from the end, so ``#-2`` is the previous run), and the default-
    ledger shorthands ``latest`` (last record of :func:`default_path`),
    ``latest#N``, and bare ``#N`` — so post-mortems (`pq_tool doctor
    latest`) never require remembering artifact paths.
    """
    path, _, idx = spec.partition("#")
    if path in ("", "latest"):
        path = default_path()
    if idx or path.endswith(".jsonl"):
        records = read(path)
        if not records:
            raise ValueError(f"{path}: empty ledger")
        i = int(idx) if idx else -1
        try:
            return records[i]
        except IndexError:
            raise ValueError(
                f"{path}: no record #{i} (have {len(records)})") from None
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a run record (top level not an object)")
    return doc


# ---------------------------------------------------------------------------
# noise model
# ---------------------------------------------------------------------------

def _median(xs):
    xs = sorted(xs)
    m = len(xs) // 2
    return xs[m] if len(xs) % 2 else 0.5 * (xs[m - 1] + xs[m])


def _reps(cfg: dict, key: "str | None") -> "list[float]":
    """Flatten a config's rep list (``device_windows_s`` nests windows)."""
    if key is None:
        return []
    v = cfg.get(key)
    if not isinstance(v, list):
        return []
    flat: list = []
    for x in v:
        if isinstance(x, list):
            flat.extend(x)
        else:
            flat.append(x)
    return [float(t) for t in flat
            if isinstance(t, (int, float)) and t > 0]


def rel_noise(reps: "list[float]") -> float:
    """Relative rep-to-rep noise of one sample list.

    n >= 4: normal-consistent relative MAD (robust to the one rep a context
    switch ate).  n in {2, 3}: half-range over the median — MAD under-reads
    badly at tiny n.  n < 2: 0.0 (no information; the caller's floor is the
    only band).
    """
    if len(reps) < 2:
        return 0.0
    med = _median(reps)
    if med <= 0:
        return 0.0
    if len(reps) < 4:
        return (max(reps) - min(reps)) / (2.0 * med)
    mad = _median([abs(x - med) for x in reps])
    return 1.4826 * mad / med


# ---------------------------------------------------------------------------
# diff / attribution / gate
# ---------------------------------------------------------------------------

def attribute_stages(cfg_a: dict, cfg_b: dict) -> "dict | None":
    """Name the registry stage whose seconds grew the most from a to b.

    Reads each config's embedded registry tree (``obs.pipeline`` plus the
    per-route device completion seconds of the ``obs.device`` section, as
    ``device:<route>`` pseudo-stages — so a regression can be pinned to a
    SPECIFIC device route, not just "dispatch grew"); the stage with the
    largest absolute second growth is the attribution a flagged regression
    carries.  Records predating the device section simply contribute no
    device pseudo-stages (graceful n/a, never a KeyError).  None when
    neither side embedded a registry, or when no stage grew at all (a
    shrinking stage can't explain a regression — attributing the
    least-shrinking one would mislead).
    """
    oa = cfg_a.get("obs") or {}
    ob = cfg_b.get("obs") or {}
    pa = oa.get("pipeline") or {}
    pb = ob.get("pipeline") or {}
    moves = {}
    for k in _STAGE_KEYS:
        sa = float(pa.get(k) or 0.0)
        sb = float(pb.get(k) or 0.0)
        if sa or sb:
            moves[k] = (sa, sb)
    da = (oa.get("device") or {}).get("routes") or {}
    db = (ob.get("device") or {}).get("routes") or {}
    for r in set(da) | set(db):
        sa = float((da.get(r) or {}).get("device_seconds") or 0.0)
        sb = float((db.get(r) or {}).get("device_seconds") or 0.0)
        if sa or sb:
            moves[f"device:{r}_seconds"] = (sa, sb)
    if not moves:
        return None
    stage = max(moves, key=lambda k: moves[k][1] - moves[k][0])
    sa, sb = moves[stage]
    if sb <= sa:
        # no stage grew: the registry can't explain this regression (a
        # machine/env change, or reps the registry never saw) — naming the
        # least-shrinking stage would mislead, so attribute nothing
        return None
    return {
        "stage": stage[: -len("_seconds")],
        "a_seconds": round(sa, 6),
        "b_seconds": round(sb, 6),
        "moved_seconds": round(sb - sa, 6),
        "ratio": round(sb / sa, 3) if sa else None,
    }


def diff(a: dict, b: dict, z: float = DEFAULT_NOISE_Z,
         floor: float = DEFAULT_DIFF_FLOOR) -> dict:
    """Per-metric deltas of run ``b`` against run ``a`` with noise bounds.

    For each config present in both records with MATCHING ``rows`` and each
    gated metric: ``ratio = b/a``; the band is ``max(z * sqrt(na^2 + nb^2),
    floor)`` over the two sides' :func:`rel_noise`.  Outside the band in
    the bad direction -> a regression entry carrying the stage attribution;
    the good direction -> an improvement; inside -> within_noise.
    Configs whose ``rows`` differ are listed as incomparable (a smoke run
    against a full-scale baseline is a different experiment).
    """
    out = {
        "metrics": {},
        "regressions": [],
        "improvements": [],
        "incomparable": [],
        "compared": 0,
        "noise_z": z,
        "floor": floor,
    }
    acfgs = a.get("configs")
    bcfgs = b.get("configs")
    if not isinstance(acfgs, dict) or not isinstance(bcfgs, dict):
        return out
    for name in sorted(set(acfgs) & set(bcfgs)):
        ca, cb = acfgs[name], bcfgs[name]
        if not isinstance(ca, dict) or not isinstance(cb, dict):
            continue
        if ca.get("rows") != cb.get("rows"):
            out["incomparable"].append({
                "config": name,
                "reason": f"rows {ca.get('rows')} != {cb.get('rows')}",
            })
            continue
        for key, (rep_key, direction) in _METRICS.items():
            va, vb = ca.get(key), cb.get(key)
            if (not isinstance(va, (int, float)) or isinstance(va, bool)
                    or not isinstance(vb, (int, float)) or not va):
                continue
            na = rel_noise(_reps(ca, rep_key))
            nb = rel_noise(_reps(cb, rep_key))
            bound = max(z * (na * na + nb * nb) ** 0.5, floor)
            ratio = vb / va
            signed = (ratio - 1.0) * direction  # negative = worse
            entry = {
                "config": name, "metric": key, "a": va, "b": vb,
                "ratio": round(ratio, 4), "noise_bound": round(bound, 4),
                "direction": direction,
            }
            out["compared"] += 1
            if signed < -bound:
                entry["verdict"] = "regression"
                entry["attribution"] = attribute_stages(ca, cb)
                out["regressions"].append(entry)
            elif signed > bound:
                entry["verdict"] = "improvement"
                out["improvements"].append(entry)
            else:
                entry["verdict"] = "within_noise"
            out["metrics"][f"{name}.{key}"] = entry
    return out


def check(baseline: dict, current: dict, z: float = DEFAULT_NOISE_Z,
          floor: float = DEFAULT_CHECK_FLOOR) -> "list[dict]":
    """The CI regression gate: flagged regressions of ``current`` vs
    ``baseline`` at the gate floor (improvements never fail a build)."""
    return diff(baseline, current, z=z, floor=floor)["regressions"]


# ---------------------------------------------------------------------------
# rendering (the pq_tool bench backends)
# ---------------------------------------------------------------------------

def _fmt_val(v: float) -> str:
    return f"{v:.4g}" if isinstance(v, float) and abs(v) < 1e4 else f"{v:,.0f}"


def format_diff(d: dict, a_label: str = "A", b_label: str = "B") -> str:
    lines = [f"bench diff: {a_label} -> {b_label}  "
             f"({d['compared']} comparable metrics, noise z={d['noise_z']:g}, "
             f"floor {100 * d['floor']:.0f}%)"]
    for verdict, entries in (("REGRESSION", d["regressions"]),
                             ("improvement", d["improvements"])):
        for e in entries:
            line = (f"  {verdict}  {e['config']}.{e['metric']}: "
                    f"{_fmt_val(e['a'])} -> {_fmt_val(e['b'])} "
                    f"({100 * (e['ratio'] - 1):+.1f}%, "
                    f"bound ±{100 * e['noise_bound']:.1f}%)")
            att = e.get("attribution")
            if att:
                grown = (f"{att['ratio']:.2f}x" if att["ratio"] is not None
                         else f"+{att['moved_seconds']:.3f}s")
                line += f"  <- {att['stage']} stage moved {grown}"
            lines.append(line)
    if not d["regressions"] and not d["improvements"]:
        lines.append("  all metrics within noise bounds")
    for inc in d["incomparable"]:
        lines.append(f"  incomparable  {inc['config']}: {inc['reason']}")
    return "\n".join(lines) + "\n"


def format_history(records: "list[dict]", path: str, start: int = 0) -> str:
    lines = [f"ledger: {path}  ({len(records)} runs)"]
    for i, r in enumerate(records, start):
        ts = r.get("ts")
        when = (time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))
                if isinstance(ts, (int, float)) else "-")
        rev = r.get("git_rev") or "-"
        value = r.get("value")
        vs = r.get("vs_baseline")
        lines.append(
            f"  #{i}  {when}  {rev:<12}  {r.get('metric', '?')}="
            f"{_fmt_val(value) if isinstance(value, (int, float)) else '?'} "
            f"{r.get('unit', '')}  vs_baseline="
            f"{vs if vs is not None else '-'}")
    return "\n".join(lines) + "\n"
