"""Memory-budget enforcement for adversarial files.

Equivalent of the reference's allocTracker (alloc.go:10-89): decoders register the
sizes of buffers they are about to materialize (decompressed pages, value arrays);
exceeding the configured budget raises instead of OOMing on decompression bombs.
Python has no finalizer-based decrement need here because tracking is scoped to a
single read operation and reset per row group.
"""

from __future__ import annotations

import threading
import time
import weakref

# live-instance registries for the flight recorder (obs.FlightRecorder):
# a hang dump must show every budget's waiters and every tracker's
# watermark WITHOUT the dumping thread knowing who created them.  WeakSets:
# registration never extends a lifetime (per-chunk AllocTrackers are
# created by the thousand and must stay collectable).
_LIVE_BUDGETS: "weakref.WeakSet[InFlightBudget]" = weakref.WeakSet()
_LIVE_TRACKERS: "weakref.WeakSet[AllocTracker]" = weakref.WeakSet()


def budget_snapshots() -> "list[dict]":
    """Consistent snapshots of every live :class:`InFlightBudget` (the
    flight recorder's backpressure section; see obs.FlightRecorder)."""
    return [b.snapshot() for b in list(_LIVE_BUDGETS)]


def tracker_snapshots() -> "list[dict]":
    """``{in_use, peak, max_size}`` of every live :class:`AllocTracker`
    with a nonzero watermark (idle per-chunk trackers carry no signal)."""
    out = []
    for t in list(_LIVE_TRACKERS):
        in_use, peak = t.snapshot()
        dev_in_use, dev_peak = t.device_snapshot()
        if in_use or peak or dev_in_use or dev_peak:
            out.append({"in_use": in_use, "peak": peak,
                        "max_size": t.max_size,
                        "device_in_use": dev_in_use,
                        "device_peak": dev_peak})
    return out


class MemoryBudgetExceeded(MemoryError):
    def __init__(self, requested: int, total: int, budget: int):
        super().__init__(
            f"memory budget exceeded: allocating {requested} bytes would bring the "
            f"total to {total} of a {budget}-byte budget (suspected corrupt or "
            f"malicious file)"
        )
        self.requested = requested
        self.total = total
        self.budget = budget


class AllocTracker:
    """Running byte counter with a hard cap (0 = unlimited).

    Alongside the HOST ledger (decompressed pages, value arrays) the
    tracker carries a DEVICE-bytes ledger: staged HBM buffers register at
    dispatch (:meth:`register_device`) and release on donation/finalize —
    the residency accounting behind the ``device_bytes`` sampler track and
    the flight dump's tracker section.  The device ledger is pure
    bookkeeping: it never raises against ``max_size`` (HBM exhaustion is
    the runtime's error to report, and the budget models host memory).
    """

    def __init__(self, max_size: int = 0):
        self.max_size = int(max_size)
        self.total = 0
        self.peak = 0  # high-water mark (obs.StatsRegistry reports it)
        self.device_total = 0  # staged HBM bytes currently resident
        self.device_peak = 0   # HBM residency high-water mark
        self._lock = threading.Lock()
        _LIVE_TRACKERS.add(self)

    def register(self, nbytes: int) -> None:
        # the high-water mark is tracked even without a cap — the default
        # max_size=0 configuration is exactly the one obs.StatsRegistry
        # reports peaks for; only the budget CHECK is conditional
        with self._lock:
            self.total += int(nbytes)
            if self.total > self.peak:
                self.peak = self.total
            if 0 < self.max_size < self.total:
                raise MemoryBudgetExceeded(int(nbytes), self.total, self.max_size)

    def register_transient(self, nbytes: int) -> None:
        """Account a short-lived buffer against the cap without holding it.

        The ship planner's link recompression (ship.py ROUTE_RECOMPRESS)
        materializes a compressed COPY of each value stream alongside the
        decompressed original; the copy must fit the budget at its peak
        (raise-don't-OOM contract) but is handed to the stager and released
        as the originals are, so holding it registered would double-count
        the chunk for the rest of the row-group window.
        """
        self.register(nbytes)
        self.release(nbytes)

    def release(self, nbytes: int) -> None:
        with self._lock:
            self.total -= int(nbytes)

    def reset(self) -> None:
        with self._lock:
            self.total = 0

    def register_device(self, nbytes: int) -> None:
        """Account staged HBM bytes (a row-group buffer at dispatch).
        Never raises — see the class docstring's device-ledger contract."""
        with self._lock:
            self.device_total += int(nbytes)
            if self.device_total > self.device_peak:
                self.device_peak = self.device_total

    def release_device(self, nbytes: int) -> None:
        """Release staged HBM bytes (donation consumed them, or finalize
        proved every kernel that reads them has completed)."""
        with self._lock:
            self.device_total -= int(nbytes)

    def snapshot(self) -> "tuple[int, int]":
        """Consistent ``(in_use, peak)`` pair for the obs.Sampler's
        watermark track (reading the attributes separately can pair a new
        total with a stale peak mid-register)."""
        with self._lock:
            return self.total, self.peak

    def device_snapshot(self) -> "tuple[int, int]":
        """Consistent ``(device_in_use, device_peak)`` pair — the HBM
        residency twin of :meth:`snapshot`."""
        with self._lock:
            return self.device_total, self.device_peak


class InFlightBudget:
    """Bounded in-flight bytes with *backpressure* instead of an exception.

    The prefetch pipeline (tpu_parquet/pipeline.py) holds several chunks'
    decompressed bytes concurrently; raising (AllocTracker semantics) would
    turn a legal file into an error just because the pipeline ran ahead.
    Instead ``acquire`` BLOCKS the submitting thread until enough in-flight
    bytes drain — the pipeline degrades toward sequential under memory
    pressure rather than OOMing or failing.

    A single item larger than the whole budget is admitted alone (charged at
    the budget cap, after the pipeline has fully drained): per-chunk
    decompression-bomb enforcement stays AllocTracker's job, this class only
    bounds cross-chunk concurrency.  ``max_bytes <= 0`` disables all gating.

    ``peak`` records the high-water mark of concurrently held bytes so tests
    (and bench.py) can assert the bound was honored.
    """

    def __init__(self, max_bytes: int = 0):
        self.max_bytes = int(max_bytes)
        self.held = 0
        self.peak = 0
        self._cv = threading.Condition()
        # hang observability (obs.FlightRecorder / obs.Watchdog): who is
        # blocked in acquire() right now, and since when — the single most
        # diagnostic fact about a wedged pipeline
        self._waiting: dict[int, float] = {}  # thread ident -> wait start
        self._abort: "BaseException | None" = None
        _LIVE_BUDGETS.add(self)

    def _charge(self, nbytes: int) -> int:
        n = int(nbytes)
        if self.max_bytes > 0:
            n = min(n, self.max_bytes)
        return max(n, 0)

    def _fits(self, n: int) -> bool:
        return self.held == 0 or self.held + n <= self.max_bytes

    def try_acquire(self, nbytes: int) -> bool:
        """Non-blocking acquire; False when the bytes must wait their turn."""
        if self.max_bytes <= 0:
            return True
        n = self._charge(nbytes)
        with self._cv:
            if not self._fits(n):
                return False
            self.held += n
            self.peak = max(self.peak, self.held)
            return True

    def acquire(self, nbytes: int, cancel=None) -> None:
        """Block until ``nbytes`` fit under the cap, then take them.

        While blocked, the waiter is visible in :meth:`snapshot` (waiter
        count + longest wait age).  An :meth:`abort` delivered by the
        watchdog wakes every waiter and raises the abort exception here —
        the graceful-degradation exit from a wedge that would otherwise
        block forever.  ``cancel`` (a
        :class:`~tpu_parquet.resilience.CancelToken`) turns the wait into
        a sliced one so a cancelled or deadline-expired request raises its
        typed verdict instead of waiting out someone else's drain.
        """
        if self.max_bytes <= 0:
            return
        n = self._charge(nbytes)
        tid = threading.get_ident()
        with self._cv:
            started = None
            try:
                while not self._fits(n):
                    if self._abort is not None:
                        raise self._abort
                    if cancel is not None:
                        cancel.check()
                    if started is None:
                        started = time.monotonic()
                        self._waiting[tid] = started
                    self._cv.wait(0.05 if cancel is not None else None)
            finally:
                if started is not None:
                    self._waiting.pop(tid, None)
            self.held += n
            self.peak = max(self.peak, self.held)

    def abort(self, exc: BaseException) -> None:
        """Poison the budget: every current and future blocking
        :meth:`acquire` raises ``exc``.  Called by the watchdog's
        raise-policy hook so a submitter wedged on backpressure surfaces
        :class:`~tpu_parquet.errors.HangError` instead of hanging."""
        with self._cv:
            self._abort = exc
            self._cv.notify_all()

    def release(self, nbytes: int) -> None:
        if self.max_bytes <= 0:
            return
        n = self._charge(nbytes)
        with self._cv:
            self.held -= n
            self._cv.notify_all()

    def snapshot(self) -> dict:
        """Consistent backpressure snapshot for the obs.Sampler track and
        the flight recorder: held/peak bytes plus ``waiters`` (threads
        blocked in :meth:`acquire` now) and ``longest_wait_s`` (the oldest
        waiter's age — a growing value with a frozen ``held`` IS a wedge)."""
        with self._cv:
            now = time.monotonic()
            waits = [now - t0 for t0 in self._waiting.values()]
            return {
                "held": self.held,
                "peak": self.peak,
                "max_bytes": self.max_bytes,
                "waiters": len(waits),
                "longest_wait_s": round(max(waits), 6) if waits else 0.0,
            }
