"""Memory-budget enforcement for adversarial files.

Equivalent of the reference's allocTracker (alloc.go:10-89): decoders register the
sizes of buffers they are about to materialize (decompressed pages, value arrays);
exceeding the configured budget raises instead of OOMing on decompression bombs.
Python has no finalizer-based decrement need here because tracking is scoped to a
single read operation and reset per row group.
"""

from __future__ import annotations

import threading


class MemoryBudgetExceeded(MemoryError):
    def __init__(self, requested: int, total: int, budget: int):
        super().__init__(
            f"memory budget exceeded: allocating {requested} bytes would bring the "
            f"total to {total} of a {budget}-byte budget (suspected corrupt or "
            f"malicious file)"
        )
        self.requested = requested
        self.total = total
        self.budget = budget


class AllocTracker:
    """Running byte counter with a hard cap (0 = unlimited)."""

    def __init__(self, max_size: int = 0):
        self.max_size = int(max_size)
        self.total = 0
        self._lock = threading.Lock()

    def register(self, nbytes: int) -> None:
        if self.max_size <= 0:
            return
        with self._lock:
            self.total += int(nbytes)
            if self.total > self.max_size:
                raise MemoryBudgetExceeded(int(nbytes), self.total, self.max_size)

    def release(self, nbytes: int) -> None:
        if self.max_size <= 0:
            return
        with self._lock:
            self.total -= int(nbytes)

    def reset(self) -> None:
        with self._lock:
            self.total = 0
