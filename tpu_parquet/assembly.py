"""Dremel record assembly: columnar (values + def/rep levels) → nested rows.

Equivalent of the reference's read-side record assembly (schema.go:216-312
getData/getNextData + data_store.go:262-309 ColumnStore.get), which walks one value
at a time.  Here records are assembled from whole decoded column chunks:

- flat schemas (no repeated fields) take a fully vectorized path;
- nested schemas use a recursive span-splitting assembler over the schema tree,
  driven by the level semantics: a slot's definition level is the depth of the
  deepest present optional/repeated node on the path, and its repetition level r
  means "this slot starts a new element of the depth-r repeated list" (r=0 starts
  a new record).

Rows are plain dicts mirroring the schema: groups → dicts, repeated nodes → lists,
null optionals → None (present in the dict, unlike the reference which omits nil
keys — a deliberate, documented difference for ergonomic Python).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .column import ByteArrayData, ColumnData
from .footer import ParquetError
from .format import FieldRepetitionType
from .logical import is_string_leaf
from .schema.core import Schema, SchemaNode


class _LeafState:
    """Per-leaf decoded arrays + python-value materialization."""

    __slots__ = ("cd", "defs", "reps", "vals", "val_idx", "record_starts")

    def __init__(self, leaf: SchemaNode, cd: ColumnData):
        self.cd = cd
        n = cd.num_leaf_slots
        self.defs = (
            cd.def_levels
            if cd.def_levels is not None
            else np.full(n, cd.max_def, dtype=np.int32)
        )
        self.reps = (
            cd.rep_levels
            if cd.rep_levels is not None
            else np.zeros(n, dtype=np.int32)
        )
        if isinstance(cd.values, ByteArrayData):
            vals = cd.values.to_list()
            if is_string_leaf(leaf):
                vals = [v.decode("utf-8", errors="replace") for v in vals]
            self.vals = vals
        else:
            self.vals = cd.values.tolist()
        # slot -> index into vals (valid only where defs == max_def)
        defined = self.defs == cd.max_def
        self.val_idx = np.cumsum(defined) - 1
        self.record_starts = np.flatnonzero(self.reps == 0)


def assemble_rows(
    schema: Schema,
    columns: dict[str, ColumnData],
    start: int = 0,
    count: Optional[int] = None,
) -> list[dict]:
    """Assemble rows [start, start+count) of one row group's decoded columns."""
    leaves = [l for l in schema.selected_leaves() if ".".join(l.path) in columns]
    if not leaves:
        return []
    states = {l.path: _LeafState(l, columns[".".join(l.path)]) for l in leaves}

    nrecords = len(next(iter(states.values())).record_starts)
    for path, st in states.items():
        if len(st.record_starts) != nrecords:
            raise ParquetError(
                f"column {'.'.join(path)} has {len(st.record_starts)} records, "
                f"expected {nrecords}"
            )
    if count is None:
        count = nrecords - start
    end = min(start + count, nrecords)
    if start < 0 or start > nrecords:
        raise IndexError(f"record {start} of {nrecords}")

    if all(l.max_rep == 0 and len(l.path) == 1 for l in leaves):
        return _assemble_flat(schema, leaves, states, start, end)

    rows = []
    for rec in range(start, end):
        spans = {}
        for path, st in states.items():
            s = int(st.record_starts[rec])
            e = (
                int(st.record_starts[rec + 1])
                if rec + 1 < nrecords
                else len(st.defs)
            )
            spans[path] = (s, e)
        rows.append(_assemble_group(schema.root, states, spans, is_root=True))
    return rows


def _assemble_flat(schema, leaves, states, start, end):
    """Vectorized path: every column is a top-level scalar."""
    cols = {}
    for l in leaves:
        st = states[l.path]
        name = l.path[0]
        if st.cd.def_levels is None:
            cols[name] = st.vals[start:end]
        else:
            defined = st.defs == st.cd.max_def
            out = [None] * (end - start)
            vi = st.val_idx
            vals = st.vals
            for i in range(start, end):
                if defined[i]:
                    out[i - start] = vals[vi[i]]
            cols[name] = out
    names = [l.path[0] for l in leaves]
    return [
        {name: cols[name][i] for name in names} for i in range(end - start)
    ]


def _first_def(states, spans, node) -> int:
    """Definition level of the first slot of this node instance."""
    for path, (s, _e) in spans.items():
        if path[: len(node.path)] == node.path:
            return int(states[path].defs[s])
    raise ParquetError(f"no leaf spans under {'.'.join(node.path)}")


def _assemble_node(node: SchemaNode, states, spans):
    """Assemble one schema node given leaf spans covering one parent instance."""
    rep = node.repetition
    if rep == FieldRepetitionType.REPEATED:
        if _first_def(states, spans, node) < node.max_def:
            return []  # zero elements
        # split each leaf's span at slots where rep == node.max_rep
        k = node.max_rep
        elements = None
        split_spans: list[dict] = []
        for path, (s, e) in spans.items():
            if path[: len(node.path)] != node.path:
                continue
            reps = states[path].reps
            bounds = [s] + [
                int(i) for i in range(s + 1, e) if reps[i] == k
            ] + [e]
            segs = list(zip(bounds[:-1], bounds[1:]))
            if elements is None:
                elements = len(segs)
                split_spans = [dict() for _ in range(elements)]
            elif len(segs) != elements:
                raise ParquetError(
                    f"repeated group {'.'.join(node.path)}: sibling columns "
                    f"disagree on element count ({len(segs)} vs {elements})"
                )
            for i, seg in enumerate(segs):
                split_spans[i][path] = seg
        return [_instance_value(node, states, sp) for sp in split_spans]
    if rep == FieldRepetitionType.OPTIONAL:
        if _first_def(states, spans, node) < node.max_def:
            return None
    return _instance_value(node, states, spans)


def _instance_value(node: SchemaNode, states, spans):
    """Value of one present instance of node (scalar or dict of children)."""
    if node.is_leaf:
        (path, (s, _e)) = next(
            (p, sp) for p, sp in spans.items() if p == node.path
        )
        st = states[path]
        return st.vals[int(st.val_idx[s])]
    return _assemble_group(node, states, spans, is_root=False)


def _assemble_group(node: SchemaNode, states, spans, is_root: bool):
    out = {}
    for child in node.children or []:
        child_spans = {
            p: sp for p, sp in spans.items() if p[: len(child.path)] == child.path
        }
        if not child_spans:
            continue  # unselected subtree
        out[child.name] = _assemble_node(child, states, child_spans)
    return out


class RowIterator:
    """Row-at-a-time cursor over a FileReader (NextRow parity,
    file_reader.go:258-273): decodes row groups lazily via the reader's
    preload cache and yields assembled dict rows."""

    def __init__(self, reader):
        self.reader = reader
        self._rows: list[dict] = []
        self._pos = 0
        self._group = 0

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        while self._pos >= len(self._rows):
            if self._group >= self.reader.num_row_groups:
                raise StopIteration
            self.reader.seek_to_row_group(self._group)
            cols = self.reader.preload()
            self._rows = assemble_rows(self.reader.schema, cols)
            self._pos = 0
            self._group += 1
        row = self._rows[self._pos]
        self._pos += 1
        return row
