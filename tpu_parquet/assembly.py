"""Dremel record assembly: columnar (values + def/rep levels) → nested rows.

Equivalent of the reference's read-side record assembly (schema.go:216-312
getData/getNextData + data_store.go:262-309 ColumnStore.get), which walks one value
at a time.  Here records are assembled from whole decoded column chunks:

- flat schemas (no repeated fields) take a fully vectorized path;
- nested schemas use a recursive span-splitting assembler over the schema tree,
  driven by the level semantics: a slot's definition level is the depth of the
  deepest present optional/repeated node on the path, and its repetition level r
  means "this slot starts a new element of the depth-r repeated list" (r=0 starts
  a new record).

Rows are plain dicts mirroring the schema: groups → dicts, repeated nodes → lists,
null optionals → None (present in the dict, unlike the reference which omits nil
keys — a deliberate, documented difference for ergonomic Python).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .column import ByteArrayData, ColumnData
from .footer import ParquetError
from .format import FieldRepetitionType
from .logical import is_string_leaf
from .schema.core import Schema, SchemaNode


def materialize_leaf_values(leaf: SchemaNode, cd: ColumnData, lo: int = 0,
                            hi: Optional[int] = None) -> list:
    """Python values for the defined slots in value-index window [lo, hi).

    The single canonical values→pylist conversion (UTF-8 decode for string
    leaves), shared by row assembly and the reader's columnar pylist API.
    """
    if isinstance(cd.values, ByteArrayData):
        ba = cd.values
        n = len(ba)
        hi = n if hi is None else hi
        if lo >= hi:
            return []
        base = int(ba.offsets[lo])
        heap = ba.heap[base : int(ba.offsets[hi])].tobytes()  # window only
        off = ba.offsets
        vals = [heap[off[i] - base : off[i + 1] - base] for i in range(lo, hi)]
        if is_string_leaf(leaf):
            vals = [v.decode("utf-8", errors="replace") for v in vals]
        return vals
    arr = cd.values[lo:] if hi is None else cd.values[lo:hi]
    return arr.tolist()


class _LeafState:
    """Per-leaf decoded arrays + lazily-windowed python-value materialization."""

    __slots__ = ("leaf", "cd", "defs", "reps", "vals", "val_idx", "record_starts",
                 "_val_base")

    def __init__(self, leaf: SchemaNode, cd: ColumnData):
        self.leaf = leaf
        self.cd = cd
        n = cd.num_leaf_slots
        self.defs = (
            cd.def_levels
            if cd.def_levels is not None
            else np.full(n, cd.max_def, dtype=np.int32)
        )
        self.reps = (
            cd.rep_levels
            if cd.rep_levels is not None
            else np.zeros(n, dtype=np.int32)
        )
        self.vals: Optional[list] = None
        self._val_base = 0
        # slot -> index into the full defined-value sequence
        defined = self.defs == cd.max_def
        self.val_idx = np.cumsum(defined) - 1
        self.record_starts = np.flatnonzero(self.reps == 0)

    def materialize(self, slot_lo: int, slot_hi: int) -> None:
        """Convert only the defined values inside the slot window to python."""
        vlo = int(self.val_idx[slot_lo - 1]) + 1 if slot_lo > 0 else 0
        vhi = int(self.val_idx[slot_hi - 1]) + 1 if slot_hi > 0 else 0
        self._val_base = vlo
        self.vals = materialize_leaf_values(self.leaf, self.cd, vlo, vhi)

    def value_at(self, slot: int):
        return self.vals[int(self.val_idx[slot]) - self._val_base]


def assemble_rows(
    schema: Schema,
    columns: dict[str, ColumnData],
    start: int = 0,
    count: Optional[int] = None,
) -> list[dict]:
    """Assemble rows [start, start+count) of one row group's decoded columns."""
    leaves = [l for l in schema.selected_leaves() if ".".join(l.path) in columns]
    if not leaves:
        return []
    states = {l.path: _LeafState(l, columns[".".join(l.path)]) for l in leaves}

    nrecords = len(next(iter(states.values())).record_starts)
    for path, st in states.items():
        if len(st.record_starts) != nrecords:
            raise ParquetError(
                f"column {'.'.join(path)} has {len(st.record_starts)} records, "
                f"expected {nrecords}"
            )
    if count is None:
        count = nrecords - start
    end = min(start + count, nrecords)
    if start < 0 or start > nrecords:
        raise IndexError(f"record {start} of {nrecords}")

    # materialize python values only for the requested record window
    for st in states.values():
        slot_lo = int(st.record_starts[start]) if start < nrecords else len(st.defs)
        slot_hi = int(st.record_starts[end]) if end < nrecords else len(st.defs)
        st.materialize(slot_lo, slot_hi)

    if all(l.max_rep == 0 and len(l.path) == 1 for l in leaves):
        return _assemble_flat(schema, leaves, states, start, end)

    rows = []
    for rec in range(start, end):
        spans = {}
        for path, st in states.items():
            s = int(st.record_starts[rec])
            e = (
                int(st.record_starts[rec + 1])
                if rec + 1 < nrecords
                else len(st.defs)
            )
            spans[path] = (s, e)
        rows.append(_assemble_group(schema.root, states, spans, is_root=True))
    return rows


def _assemble_flat(schema, leaves, states, start, end):
    """Vectorized path: every column is a top-level scalar."""
    cols = {}
    for l in leaves:
        st = states[l.path]
        name = l.path[0]
        if st.cd.def_levels is None:
            cols[name] = st.vals[start - st._val_base : end - st._val_base]
        else:
            defined = st.defs == st.cd.max_def
            out = [None] * (end - start)
            for i in range(start, end):
                if defined[i]:
                    out[i - start] = st.value_at(i)
            cols[name] = out
    names = [l.path[0] for l in leaves]
    return [
        {name: cols[name][i] for name in names} for i in range(end - start)
    ]


def _first_def(states, spans, node) -> int:
    """Definition level of the first slot of this node instance."""
    for path, (s, _e) in spans.items():
        if path[: len(node.path)] == node.path:
            return int(states[path].defs[s])
    raise ParquetError(f"no leaf spans under {'.'.join(node.path)}")


def _assemble_node(node: SchemaNode, states, spans):
    """Assemble one schema node given leaf spans covering one parent instance."""
    rep = node.repetition
    if rep == FieldRepetitionType.REPEATED:
        if _first_def(states, spans, node) < node.max_def:
            return []  # zero elements
        # split each leaf's span at slots where rep == node.max_rep
        k = node.max_rep
        elements = None
        split_spans: list[dict] = []
        for path, (s, e) in spans.items():
            if path[: len(node.path)] != node.path:
                continue
            reps = states[path].reps
            bounds = [s] + [
                int(i) for i in range(s + 1, e) if reps[i] == k
            ] + [e]
            segs = list(zip(bounds[:-1], bounds[1:]))
            if elements is None:
                elements = len(segs)
                split_spans = [dict() for _ in range(elements)]
            elif len(segs) != elements:
                raise ParquetError(
                    f"repeated group {'.'.join(node.path)}: sibling columns "
                    f"disagree on element count ({len(segs)} vs {elements})"
                )
            for i, seg in enumerate(segs):
                split_spans[i][path] = seg
        return [_instance_value(node, states, sp) for sp in split_spans]
    if rep == FieldRepetitionType.OPTIONAL:
        if _first_def(states, spans, node) < node.max_def:
            return None
    return _instance_value(node, states, spans)


def _instance_value(node: SchemaNode, states, spans):
    """Value of one present instance of node (scalar or dict of children)."""
    if node.is_leaf:
        (path, (s, _e)) = next(
            (p, sp) for p, sp in spans.items() if p == node.path
        )
        st = states[path]
        return st.value_at(s)
    return _assemble_group(node, states, spans, is_root=False)


def _assemble_group(node: SchemaNode, states, spans, is_root: bool):
    out = {}
    for child in node.children or []:
        child_spans = {
            p: sp for p, sp in spans.items() if p[: len(child.path)] == child.path
        }
        if not child_spans:
            continue  # unselected subtree
        out[child.name] = _assemble_node(child, states, child_spans)
    return out


class RowIterator:
    """Row-at-a-time cursor over a FileReader (NextRow parity,
    file_reader.go:258-273 + advanceIfNeeded): starts from the reader's
    current row-group cursor (so seek_to_row_group/skip_row_group are honored,
    like the reference), consumes the preload cache when it matches, and never
    mutates the reader's cursor itself."""

    def __init__(self, reader):
        self.reader = reader
        self._rows: list[dict] = []
        self._pos = 0
        self._group = reader._current_row_group

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        while self._pos >= len(self._rows):
            if self._group >= self.reader.num_row_groups:
                raise StopIteration
            if not self.reader.row_group_selected(self._group):
                self._group += 1  # pruned by row_filter: skip without IO
                continue
            if (
                self.reader._current_row_group == self._group
                and self.reader._preloaded is not None
            ):
                cols = self.reader._preloaded
            else:
                cols = self.reader.read_row_group(self._group)
            self._rows = assemble_rows(self.reader.schema, cols)
            self._pos = 0
            self._group += 1
        row = self._rows[self._pos]
        self._pos += 1
        return row
