"""Mutation fuzzing for the host-side parse surface.

The reference fuzzes four surfaces with go-fuzz (reader_fuzz.go:12-31,
hybrid_fuzz.go:12-35, deltabp_fuzz.go:10-25, types_fuzz.go) and replays every
crasher as a regression test (fuzz_test.go:11-28).  The contract here is the
same, adapted to Python: feeding ANY bytes to a target may raise
``ParquetError`` (the unified malformed-input error, errors.py) or return
normally — any other exception, a hang, or a crash is a finding.  The native
C walkers are additionally held to *differential* parity: where both the C
and the pure-Python walk accept an input, their outputs must match, and they
must agree on rejection.

Run:  ``python -m tpu_parquet.fuzz --runs 20000 [--target all] [--seed 0]``
Crashers are minimized (greedy chunk deletion) and written to
``tests/fuzz_corpus/<target>-<sha>`` for check-in; ``tests/test_fuzz.py``
replays the corpus and runs a deterministic smoke batch in CI.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys

import numpy as np

from .errors import ParquetError

__all__ = ["TARGETS", "run_fuzz", "minimize", "mutate"]

_CORPUS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "fuzz_corpus",
)


# ---------------------------------------------------------------------------
# targets: bytes -> None (raise ParquetError for malformed input, nothing else)
# ---------------------------------------------------------------------------

def fuzz_file_reader(data: bytes) -> None:
    """Whole-file surface: footer thrift → schema → pages → rows
    (FuzzFileReader, reader_fuzz.go:12-31)."""
    from .reader import FileReader

    try:
        r = FileReader(io.BytesIO(data))
    except ParquetError:
        return
    try:
        for _ in r.iter_rows():
            pass
    except ParquetError:
        pass
    finally:
        r.close()


def fuzz_thrift(data: bytes) -> None:
    """Bare compact-protocol struct decode (the fuzz_test.go:11-28 bombs
    attack exactly this layer)."""
    from .format import FileMetaData
    from .thrift import read_struct

    try:
        read_struct(FileMetaData, data)
    except ParquetError:
        pass


def fuzz_hybrid(data: bytes) -> None:
    """RLE/bit-packed hybrid: host decode + native/Python walk parity
    (FuzzHybrid, hybrid_fuzz.go:12-35)."""
    from . import jax_decode as jd
    from .kernels import rle

    if not data:
        return
    width = data[0] % 33
    count = (data[1] if len(data) > 1 else 0) % 512
    payload = data[2:]
    try:
        rle.decode(payload, width, count)
    except ParquetError:
        pass
    _walk_parity(
        lambda: jd._native_hybrid_meta(payload, len(payload), 0, width, count, True)
        if count else None,
        lambda: jd._parse_hybrid_meta_py(payload, width, count, 0, len(payload)),
        ("run_ends", "run_is_rle", "run_values", "run_bit_starts"),
        note=f"hybrid width={width} count={count}",
    )


def fuzz_delta(data: bytes) -> None:
    """DELTA_BINARY_PACKED: host decode + native/Python walk parity
    (FuzzDelta, deltabp_fuzz.go:10-25)."""
    from . import jax_decode as jd
    from .kernels import delta

    if not data:
        return
    bits = 32 if data[0] & 1 else 64
    payload = data[1:]
    try:
        delta.decode(payload, bits=bits)
    except ParquetError:
        pass
    _walk_parity(
        lambda: jd._native_delta_meta(payload, 0),
        lambda: jd._parse_delta_meta_py(payload, bits, 0),
        ("mini_bit_starts", "mini_widths", "mini_min_delta"),
        note=f"delta bits={bits}",
    )


def _walk_parity(native_fn, py_fn, array_fields, note=""):
    try:
        a = native_fn()
    except ParquetError:
        a = ParquetError
    try:
        b = py_fn()
    except ParquetError:
        b = ParquetError
    if a is None:  # native library unavailable / skipped
        return
    if (a is ParquetError) != (b is ParquetError):
        raise AssertionError(
            f"native/python rejection mismatch ({note}): "
            f"native={'reject' if a is ParquetError else 'accept'} "
            f"python={'reject' if b is ParquetError else 'accept'}"
        )
    if a is ParquetError:
        return
    for f in array_fields:
        av, bv = getattr(a, f), getattr(b, f)
        if not np.array_equal(av, bv):
            raise AssertionError(f"native/python {f} mismatch ({note})")
    if a.consumed != b.consumed:
        raise AssertionError(f"native/python consumed mismatch ({note})")


def fuzz_plain(data: bytes) -> None:
    """Per-type PLAIN decoders (FuzzBooleanPlain & friends, types_fuzz.go)."""
    from .format import Type
    from .kernels import plain

    if len(data) < 2:
        return
    types = [Type.BOOLEAN, Type.INT32, Type.INT64, Type.INT96, Type.FLOAT,
             Type.DOUBLE, Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY]
    ptype = types[data[0] % len(types)]
    count = data[1] % 256
    try:
        plain.decode(data[2:], ptype, count, type_length=5)
    except ParquetError:
        pass


def fuzz_schema_dsl(data: bytes) -> None:
    """Schema-definition parser (schemaParser.recover surface,
    schema_parser.go:285-298)."""
    from .schema.dsl import parse_schema_definition

    try:
        parse_schema_definition(data.decode("utf-8", errors="replace"))
    except ParquetError:
        pass


def _force_cpu_jax() -> None:
    """Pin JAX to CPU before the first backend query (the axon site hook
    pins the platform via env early, so the config update is load-bearing —
    same pattern as tests/conftest.py).  Fuzzing must never burn TPU time."""
    import jax

    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — already initialized on CPU
        pass


def fuzz_device_reader(data: bytes) -> None:
    """Differential: batched device decoder vs host reader on the same bytes.

    The staging/bucketing/fused-dispatch logic (device_reader.py) is attack
    surface none of the host targets touch.  Contract: the two paths must
    agree on acceptance, and for accepted files every column's values and
    def levels must match bit for bit.  Runs on the CPU backend (the XLA
    decode path; set TPQ_PALLAS=1 to fuzz the Pallas interpreter route).
    """
    _force_cpu_jax()
    from .device_reader import DeviceFileReader
    from .reader import FileReader

    try:
        host_cols: dict = {}
        with FileReader(io.BytesIO(data)) as r:
            for rg in r.iter_row_groups():
                for k, v in rg.items():
                    host_cols.setdefault(k, []).append(v)
        host_err = None
    except ParquetError as e:
        host_err = e
    try:
        dev_cols: dict = {}
        with DeviceFileReader(io.BytesIO(data)) as r:
            for rg in r.iter_row_groups():
                for k, v in rg.items():
                    dev_cols.setdefault(k, []).append(v)
        dev_err = None
    except ParquetError as e:
        dev_err = e
    if (host_err is None) != (dev_err is None):
        h = repr(host_err) if host_err else "accept"
        d = repr(dev_err) if dev_err else "accept"
        raise AssertionError(f"host/device acceptance mismatch: host={h} device={d}")
    if host_err is not None:
        return
    if set(host_cols) != set(dev_cols):
        raise AssertionError(
            f"column sets differ: {sorted(host_cols)} vs {sorted(dev_cols)}"
        )
    from .column import ByteArrayData

    for k, hlist in host_cols.items():
        dlist = dev_cols[k]
        if len(hlist) != len(dlist):
            raise AssertionError(
                f"row group count differs in {k}: {len(hlist)} vs {len(dlist)}"
            )
        for h, d in zip(hlist, dlist):
            dh = d.to_host()
            hv = h.values
            if isinstance(hv, ByteArrayData):
                if not (np.array_equal(hv.offsets, dh.offsets)
                        and np.array_equal(hv.heap, dh.heap)):
                    raise AssertionError(f"byte-array values differ in {k}")
            elif not np.array_equal(np.asarray(hv), np.asarray(dh)):
                raise AssertionError(f"values differ in {k}")
            dd, dr = d.levels_to_host()
            for name, hl, dl in (("def", h.def_levels, dd),
                                 ("rep", h.rep_levels, dr)):
                if (hl is None) != (dl is None) or (
                    hl is not None and not np.array_equal(hl, dl)
                ):
                    raise AssertionError(f"{name} levels differ in {k}")


def fuzz_page_header(data: bytes) -> None:
    """Native vs python PageHeader parse parity (the C parser replicates
    thrift.py's compact-protocol semantics byte for byte — same
    accept/reject set, same consumed length, same extracted fields,
    INCLUDING each data page header's Statistics sub-struct)."""
    from . import native
    from .format import PageHeader
    from .thrift import ThriftError, read_struct

    res = native.page_header(data, 0)
    if res is None:
        return  # no native library: nothing to differentiate
    try:
        py, py_end = read_struct(PageHeader, data, 0)
    except ThriftError:
        py = ThriftError
    if isinstance(res, int):
        if py is not ThriftError:
            raise AssertionError(
                f"native rejected ({res}) where python accepted"
            )
        return
    if py is ThriftError:
        raise AssertionError("native accepted where python rejected")
    c, c_end = res
    if c_end != py_end:
        raise AssertionError(f"consumed mismatch: {c_end} != {py_end}")
    if c != py:
        raise AssertionError(f"field mismatch: {c!r} != {py!r}")


def fuzz_snappy(data: bytes) -> None:
    """Native vs pure-Python raw-snappy differential: identical accept/reject
    set and identical output bytes (the C fast paths — blind 16-byte literal
    stores, 8-byte stride copies — must be invisible)."""
    from . import native
    from .compress import CompressionError, _py_snappy_decompress

    if not native.available():
        return
    try:
        want = _py_snappy_decompress(data, max_size=1 << 22)
        py_ok = True
    except CompressionError:
        py_ok = False
    try:
        got = native.snappy_decompress(data, max_size=1 << 22)
        c_ok = True
    except (ValueError, RuntimeError):
        c_ok = False
    if py_ok != c_ok:
        raise AssertionError(f"snappy acceptance mismatch: py={py_ok} c={c_ok}")
    if py_ok and bytes(got) != want:
        raise AssertionError("snappy output mismatch")


def fuzz_snappy_plan(data: bytes) -> None:
    """Device-snappy PLANNER differential (the round-4 native surface the
    compressed-page shipping path trusts): ``tpq_snappy_plan``'s op tables,
    resolved sequentially on host with the device resolver's copy semantics
    (out[dst+j] = out[dst - off + (j % off)]), must reproduce
    ``tpq_snappy_decompress`` byte for byte — and the two must agree on the
    accept/reject set."""
    from . import native

    if not native.available():
        return
    try:
        out = native.snappy_decompress(data, max_size=1 << 20)
        dec_ok = True
    except (ValueError, RuntimeError):
        dec_ok = False
    plan = native.snappy_plan(data, len(out) if dec_ok else (1 << 20))
    if plan is None:
        return
    plan_ok = not isinstance(plan, int)
    if plan_ok != dec_ok:
        raise AssertionError(
            f"plan/decompress acceptance mismatch: plan={plan} dec={dec_ok}")
    if not dec_ok:
        return
    dst_end, op_src, is_lit, depth = plan
    res = np.zeros(len(out), dtype=np.uint8)
    src = np.frombuffer(data, dtype=np.uint8)
    pos = 0
    for e, s, lit in zip(dst_end, op_src, is_lit):
        e = int(e)
        n = e - pos
        if lit:
            res[pos:e] = src[int(s) : int(s) + n]
        else:
            off = int(s)
            # a plan op with off=0 or off>pos is itself a planner bug (the
            # decompressor rejects those streams); assert rather than let
            # numpy negative-index wraparound mask it against zero tails
            if not 1 <= off <= pos:
                raise AssertionError(f"plan copy offset {off} at pos {pos}")
            # device copy semantics: j-th byte reads dst_start - off + j%off
            idx = pos - off + (np.arange(n) % off)
            res[pos:e] = res[idx]
        pos = e
    if depth < 0 or pos != len(out):
        raise AssertionError(f"plan shape bad: end={pos} depth={depth}")
    if res.tobytes() != bytes(out):
        raise AssertionError("plan resolution diverges from decompress")


def fuzz_snappy_ops(data: bytes) -> None:
    """Fuzz target #13: hostile compressed streams against the op-table ship
    planner (the surface every compressed-shipping route trusts — ship.py).

    Beyond fuzz_snappy_plan's host-resolver output differential, this target
    asserts the STRUCTURAL invariants the device resolver
    (jax_kernels.snappy_resolve) assumes of every ACCEPTED plan:

    - ``dst_end`` strictly increasing, ending exactly at the stream's
      declared output size (monotonicity is what searchsorted needs);
    - literal sources within the compressed payload;
    - copy offsets ``1 <= off <= dst_start`` (overlapping RLE-style copies
      included — the mod-form source math relies on it);
    - chain depth within [0, n_ops] and op count within the n/2+2 bound
      (the cap-retry path in native.snappy_plan);
    - a DECLARED-SIZE LIE (first fuzz byte perturbs the expect argument)
      must be rejected exactly like the decompressor's bomb guard.

    Any violated invariant would make the device expansion read garbage
    silently — the resolver has no bounds it can raise from.
    """
    from . import native

    if not native.available() or len(data) < 1:
        return
    bias = data[0] % 5 - 2  # perturb the declared size by -2..+2
    payload = data[1:]
    try:
        out = native.snappy_decompress(payload, max_size=1 << 20)
        ulen = len(out)
        dec_ok = True
    except (ValueError, RuntimeError):
        ulen = 1 << 10
        dec_ok = False
    expect = max(ulen + bias, 0)  # clamped: what the planner is actually told
    plan = native.snappy_plan(payload, expect)
    plan_ok = not isinstance(plan, int) and plan is not None
    # a negative bias on an empty stream clamps back to the true size — the
    # planner legitimately accepts that call, so the oracle must too
    want_ok = dec_ok and expect == ulen
    if plan_ok != want_ok:
        raise AssertionError(
            f"plan acceptance mismatch: plan_ok={plan_ok} dec_ok={dec_ok} "
            f"bias={bias}")
    if not plan_ok:
        return
    dst_end, op_src, is_lit, depth = plan
    n_ops = len(dst_end)
    if n_ops > len(payload) // 2 + 2:
        raise AssertionError(f"op count {n_ops} above the n/2+2 bound")
    if not 0 <= depth <= max(n_ops, 1):
        raise AssertionError(f"chain depth {depth} outside [0, {n_ops}]")
    pos = 0
    for e, s, lit in zip(dst_end, op_src, is_lit):
        e, s = int(e), int(s)
        if e <= pos:
            raise AssertionError(f"dst_end not increasing at {pos}: {e}")
        run = e - pos
        if lit:
            if s < 0 or s + run > len(payload):
                raise AssertionError(
                    f"literal source [{s}, {s + run}) outside payload")
        else:
            if not 1 <= s <= pos:
                raise AssertionError(f"copy offset {s} at pos {pos}")
        pos = e
    if pos != ulen:
        raise AssertionError(f"plan output {pos} != declared {ulen}")


def fuzz_narrow(data: bytes) -> None:
    """Narrow-int transcode differential (the round-4 transfer-cut path):
    minmax + k-byte truncate + widen-and-rebias must reconstruct the source
    values exactly, for both widths, at every alignment the planner uses."""
    from . import native
    from .device_reader import _narrow_max_k, _span_bytes

    if not native.available() or len(data) < 8:
        return
    for width, dt in ((8, np.int64), (4, np.int32)):
        n = len(data) // width
        if n == 0:
            continue
        vals = np.frombuffer(data[: n * width], dtype=dt)
        mm = native.int_minmax(data, 0, n, width)
        mn, mx = int(vals.min()), int(vals.max())
        if mm != (mn, mx):
            raise AssertionError(f"minmax mismatch w{width}: {mm} != {(mn, mx)}")
        k = _span_bytes(mn, mx)
        if k > _narrow_max_k(width):
            continue  # planner would decline; nothing to transcode
        out = np.empty(n * k, dtype=np.uint8)
        assert native.int_truncate(data, 0, n, width, mn, k, out)
        # widen: little-endian k-byte rows -> u64 -> + bias -> dtype wrap
        rows = out.reshape(n, k).astype(np.uint64)
        acc = np.zeros(n, dtype=np.uint64)
        for b in range(k):
            acc |= rows[:, b] << np.uint64(8 * b)
        got = (acc + np.uint64(mn % (1 << 64))).astype(np.uint64).astype(dt)
        if not np.array_equal(got, vals):
            raise AssertionError(f"narrow roundtrip diverges (w{width}, k={k})")


_FUZZ_LOADER = None


def _loader_for_fuzz():
    """A tiny two-row-group DataLoader over a temp file, built once.

    The restore surface is pure cursor math, so one canned loader covers it;
    mutated states that survive unpack mostly die on the config fingerprint,
    and the few that are genuinely compatible drive a real one-batch pull.
    """
    global _FUZZ_LOADER
    if _FUZZ_LOADER is None:
        import tempfile

        from .data import DataLoader
        from .format import CompressionCodec, FieldRepetitionType as FRT, Type
        from .schema.core import build_schema, data_column
        from .writer import FileWriter

        path = os.path.join(tempfile.mkdtemp(prefix="tpq_fuzz_loader_"),
                            "tiny.parquet")
        schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
        rng = np.random.default_rng(0)
        with FileWriter(path, schema,
                        codec=CompressionCodec.UNCOMPRESSED) as w:
            for _ in range(2):
                w.write_columns({"v": rng.integers(0, 1 << 30, 60)})
                w.flush_row_group()
        _FUZZ_LOADER = DataLoader(path, 16, shuffle=True, seed=7,
                                  shuffle_window=32)
    return _FUZZ_LOADER


def fuzz_loader_state(data: bytes) -> None:
    """Checkpoint-blob surface (data/checkpoint.py): ANY bytes must either
    unpack+restore cleanly or raise a tpu_parquet.errors type — truncated,
    bit-flipped, and version-bumped blobs must never crash or silently
    mis-seek the loader."""
    _force_cpu_jax()  # DataLoader's shard planning imports jax
    from .data import checkpoint as ck

    try:
        st = ck.unpack_state(data)
    except ParquetError:
        return
    # accepted: the state must round-trip the pack/unpack pair exactly
    st2 = ck.unpack_state(ck.pack_state(st))
    if st2 != st:
        raise AssertionError(f"state round-trip diverges: {st} != {st2}")
    loader = _loader_for_fuzz()
    pristine = loader.state()  # FULL reset below, seed included: a seed
    # adopted from one input must never leak into the next input's run, or
    # corpus replays of a single crasher stop reproducing
    try:
        loader.restore(st)
    except ParquetError:
        return
    try:
        # a state the loader ADOPTED must be iterable: a crash (or a yielded
        # batch of the wrong shape) here is a mis-seek the validator missed
        batch = next(iter(loader), None)
        if batch is not None and len(batch["v"]) != loader.batch_size:
            raise AssertionError(f"restored batch shape {len(batch['v'])}")
    finally:
        loader.restore(pristine)


def fuzz_io_ranges(data: bytes) -> None:
    """Fuzz target #14: the range-coalescing planner + a store that lies.

    Blob layout: byte 0 picks the gap threshold, byte 1 the span cap, byte
    2 the store's lie mode, then 5-byte records (3-byte offset, 2-byte
    size) describe the ranges.  Invariants of ``plan_coalesced`` (the
    surface every coalesced fetch trusts):

    - deterministic: two plans over the same inputs are identical;
    - covering: every nonzero input range lands in exactly one group, with
      multiplicity, and inside its group's span;
    - bounded: groups are sorted and disjoint, no group bridges a hole
      wider than the gap threshold, and a group merged across HOLES never
      exceeds the span cap (only overlap-forced merges may — disjointness
      outranks the cap);

    then every member is read through a :class:`CoalescedFetcher` over a
    deterministic store whose span responses may lie about size (short or
    overlong): each read must either return the exact true bytes (the
    degradation ladder recovered via single-range fetches) or raise an
    IOError-rooted retry error — never crash, never silently return wrong
    bytes.
    """
    from .errors import RetryExhaustedError, TransientIOError
    from .iostore import (CoalescedFetcher, GenericRangeStore, IOConfig,
                          plan_coalesced)

    if len(data) < 3:
        return
    gap = [0, 1, 16, 256, 1 << 16][data[0] % 5]
    max_span = [128, 1 << 12, 1 << 20][data[1] % 3]
    lie_mode = data[2] % 3  # 0 honest, 1 short, 2 overlong
    payload = data[3:]
    ranges = []
    for i in range(0, len(payload) - 4, 5):
        off = int.from_bytes(payload[i : i + 3], "little")
        size = int.from_bytes(payload[i + 3 : i + 5], "little")
        ranges.append((off, size))
    if len(ranges) > 64:
        ranges = ranges[:64]

    plan = plan_coalesced(ranges, gap, max_span)
    again = plan_coalesced(list(reversed(ranges)), gap, max_span)
    if [g.key() for g in plan] != [g.key() for g in again]:
        raise AssertionError("coalescing plan is input-order dependent")
    want = {}
    for off, size in ranges:
        if size > 0:
            want[(off, size)] = want.get((off, size), 0) + 1
    got = {}
    prev_end = None
    for g in plan:
        if prev_end is not None and g.offset < prev_end:
            raise AssertionError("groups overlap or are unsorted")
        prev_end = g.offset + g.size
        ends = sorted((o, o + s) for (o, s) in g.members)
        if ends[0][0] != g.offset or max(e for _o, e in ends) != prev_end:
            raise AssertionError("group span does not hug its members")
        cover_end = None
        has_overlap = False
        for o, e in ends:
            if cover_end is not None:
                if o - cover_end > gap:
                    raise AssertionError(
                        f"group bridges a hole wider than {gap}")
                has_overlap = has_overlap or o < cover_end
            cover_end = e if cover_end is None else max(cover_end, e)
        if len(g.members) > 1 and g.size > max_span and not has_overlap:
            raise AssertionError(f"merged span {g.size} exceeds cap {max_span}")
        for m, n in g.members.items():
            if not (g.offset <= m[0] and m[0] + m[1] <= prev_end):
                raise AssertionError("member outside its group span")
            got[m] = got.get(m, 0) + n
    if got != want:
        raise AssertionError(f"coverage broken: {got} != {want}")

    # a store that lies about coalesced-span sizes must degrade, not corrupt
    file_size = 1 << 18
    member_max = max((s for _o, s in want), default=0)

    class _LyingStore(GenericRangeStore):
        def size(self):
            return file_size

        def _fetch_once(self, offset, size, timeout):
            true = bytes((offset + j) % 251 for j in range(
                min(size, max(file_size - offset, 0))))
            if lie_mode == 0 or size <= member_max:
                return true  # honest (single-member reads always are)
            if lie_mode == 1:
                return true[: size // 2]  # short, not at EOF
            return true + b"\x00" * 7  # overlong

    store = _LyingStore(config=IOConfig(retries=1, backoff_ms=0,
                                        retry_budget=0, coalesce_gap=gap))
    fetcher = CoalescedFetcher(store, list(want), gap=gap, max_span=max_span)
    for off, size in want:
        if off >= file_size:
            continue  # fully past EOF: short returns are legitimate
        expect = bytes((off + j) % 251
                       for j in range(min(size, file_size - off)))
        try:
            buf = fetcher.read(off, size)
        except (RetryExhaustedError, TransientIOError):
            continue  # clean failure is an accepted outcome
        if bytes(buf) != expect:
            raise AssertionError(
                f"lying store corrupted range [{off}, {off + size})")


_PAGE_CORRUPT_BASE = None


def _page_corrupt_base():
    """A small CRC'd 2-column × 3-row-group parquet image + oracle, built
    once: (file bytes, per-row-group byte spans, clean per-group decodes).
    The spans let the target tell which row groups a blob's flips touched —
    the untouched ones are the wrong-data oracle."""
    global _PAGE_CORRUPT_BASE
    if _PAGE_CORRUPT_BASE is None:
        import io as _io

        from .chunk_decode import validate_chunk_meta
        from .footer import read_file_metadata
        from .format import CompressionCodec, FieldRepetitionType as FRT, Type
        from .reader import FileReader
        from .schema.core import Schema, build_schema, data_column
        from .writer import FileWriter

        rng = np.random.default_rng(5)
        sink = _io.BytesIO()
        schema = build_schema([
            data_column("a", Type.INT64, FRT.REQUIRED),
            data_column("b", Type.INT32, FRT.REQUIRED),
        ])
        with FileWriter(sink, schema, codec=CompressionCodec.SNAPPY,
                        write_crc=True) as w:
            for _ in range(3):
                w.write_columns({
                    "a": rng.integers(0, 1 << 40, 150),
                    "b": rng.integers(0, 1 << 20, 150).astype(np.int32),
                })
                w.flush_row_group()
        whole = sink.getvalue()
        md = read_file_metadata(_io.BytesIO(whole))
        fschema = Schema.from_file_metadata(md)
        leaves = {l.path: l for l in fschema.leaves}
        spans = []
        for rg in md.row_groups:
            lo, hi = 1 << 62, 0
            for cc in rg.columns:
                cmd, off = validate_chunk_meta(
                    cc, leaves[tuple(cc.meta_data.path_in_schema)])
                lo = min(lo, off)
                hi = max(hi, off + cmd.total_compressed_size)
            spans.append((lo, hi))
        clean = []
        with FileReader(whole) as r:
            for i in range(r.num_row_groups):
                clean.append({k: np.asarray(v.values)
                              for k, v in r.read_row_group(i).items()})
        _PAGE_CORRUPT_BASE = (whole, spans, clean)
    return _PAGE_CORRUPT_BASE


def fuzz_page_corrupt(data: bytes) -> None:
    """Fuzz target #15: crafted page corruption through the policy engine.

    Blob layout: byte 0 picks the error policy, byte 1 the validate mode,
    byte 2 the budget, byte 3 the prefetch depth; then 4-byte records
    (3-byte position, 1-byte xor mask) flip bytes of the DATA region of a
    small CRC'd file (the footer is left alone — the footer's own fuzz
    surface is the file_reader target).  Invariants:

    - no hang, no unclassified crash: every outcome is a clean read, a
      ``ParquetError``-rooted raise (``DataIntegrityError`` included), or
      a clean skip — the crash oracle (run_fuzz) enforces the type;
    - no wrong data: row groups whose byte span is UNTOUCHED decode
      bit-identically to the clean image, under every policy;
    - exact accounting: under a skip policy, every quarantine record names
      a row group whose span was actually touched — nothing else is ever
      quarantined.
    """
    from .errors import DataIntegrityError
    from .quarantine import ErrorBudget, Quarantine
    from .reader import FileReader

    if len(data) < 8:
        return
    whole, spans, clean = _page_corrupt_base()
    policy = ("raise", "skip_unit", "skip_file")[data[0] % 3]
    validate = (None, False)[data[1] % 2]
    tiny_budget = data[2] % 4 == 0
    prefetch = (0, 2)[data[3] % 2]
    payload = data[4:]
    data_lo = min(lo for lo, _hi in spans)
    data_hi = max(hi for _lo, hi in spans)
    buf = bytearray(whole)
    touched: set[int] = set()
    n_flips = 0
    for i in range(0, len(payload) - 3, 4):
        if n_flips >= 32:
            break
        pos = data_lo + (int.from_bytes(payload[i : i + 3], "little")
                         % (data_hi - data_lo))
        xor = payload[i + 3] or 0xFF
        buf[pos] ^= xor
        n_flips += 1
        for gi, (lo, hi) in enumerate(spans):
            if lo <= pos < hi:
                touched.add(gi)
    q = Quarantine(policy, budget=(ErrorBudget(1, 1.0) if tiny_budget
                                   else ErrorBudget()))
    try:
        with FileReader(bytes(buf), validate_crc=validate,
                        prefetch=prefetch, quarantine=q) as r:
            list(r.iter_row_groups())
    except DataIntegrityError as e:
        if not touched:
            raise AssertionError(
                "budget exhausted with no touched row group")
        for rec in e.records:
            if rec.get("row_group") not in touched:
                raise AssertionError(
                    f"quarantined untouched row group {rec}")
        return
    except ParquetError:
        return  # classified raise: the accepted failure mode
    for rec in q.log.snapshot():
        if rec.get("row_group") not in touched:
            raise AssertionError(f"quarantined untouched row group {rec}")
    # untouched row groups must decode bit-identically on a fresh reader
    with FileReader(bytes(buf)) as r:
        for gi in range(r.num_row_groups):
            if gi in touched:
                continue
            out = r.read_row_group(gi, prefetch=0)
            for k, want in clean[gi].items():
                got = np.asarray(out[k].values)
                if got.shape != want.shape or not np.array_equal(got, want):
                    raise AssertionError(
                        f"untouched row group {gi} column {k} diverged")


def crafted_page_corrupt_blobs() -> "list[bytes]":
    """Hand-crafted ``page_corrupt`` inputs (and corpus blobs): one flip in
    a CRC-covered payload (skip_unit), a page-header flip (raise), a
    dictionary/zero-region multi-flip (skip_file), budget exhaustion under
    a tiny budget, and a validate-off single flip (the sanity tier alone)."""
    whole, spans, _clean = _page_corrupt_base()
    data_lo = min(lo for lo, _hi in spans)
    data_hi = max(hi for _lo, hi in spans)

    def rec(pos, xor):
        return (pos - data_lo).to_bytes(3, "little") + bytes([xor])

    mid0 = (spans[0][0] + spans[0][1]) // 2
    mid1 = (spans[1][0] + spans[1][1]) // 2
    mid2 = (spans[2][0] + spans[2][1]) // 2
    return [
        # one payload flip, skip_unit, default validate+budget, prefetch 2
        bytes([1, 0, 1, 1]) + rec(mid1, 0x40),
        # page-header-ish flip right at a span start, raise policy
        bytes([0, 0, 1, 0]) + rec(spans[2][0] + 2, 0xFF),
        # multi-flip across two groups, skip_file
        bytes([2, 0, 1, 1]) + rec(mid0, 0x10) + rec(mid2, 0x20),
        # budget exhaustion: tiny budget, flips in every group
        bytes([1, 0, 0, 0]) + rec(mid0, 0x01) + rec(mid1, 0x02)
        + rec(mid2, 0x04),
        # validate off: only the structural sanity tier stands
        bytes([1, 1, 1, 0]) + rec(mid1, 0x80),
    ]


def fuzz_scan_plan(data: bytes) -> None:
    """Fuzz target #16: ScanPlan IR blob adoption (scanplan.py).

    The serve layer caches serialized plans and replays them across
    requests, so a plan blob is an INPUT like a footer is: deserialize must
    either raise ParquetError or yield a plan whose serialize→deserialize
    round-trip is byte-stable, whose cache key survives the trip (the
    PlanCache's correctness invariant — a round-tripped plan must land on
    the same cache slot), and whose memo/costing surfaces never crash on
    arbitrary coordinates."""
    from .scanplan import ScanPlan

    try:
        p = ScanPlan.deserialize(data)
    except ParquetError:
        return
    blob = p.serialize()
    q = ScanPlan.deserialize(blob)  # our own output must always readopt
    assert q.cache_key() == p.cache_key(), "cache key broke round-trip"
    assert q.serialize() == blob, "serialize not stable across round-trip"
    # the replay surfaces a reader would hit — never a crash, any input
    assert p.estimated_bytes() >= 0
    p.selected_ordinals()
    for rgp in p.row_groups[:8]:
        p.pruning_hint(rgp.ordinal)
        for c in rgp.chunks[:8]:
            p.route_hint(rgp.ordinal, c.column)


def crafted_scan_plan_blobs() -> "list[bytes]":
    """Hand-crafted ``scan_plan`` inputs (and corpus blobs): truncated and
    lying plans around a small valid one."""
    from .scanplan import ChunkPlan, RowGroupPlan, ScanPlan

    plan = ScanPlan(
        file_key=("file", "/tmp/x.parquet", 4096, 1234567890),
        columns=("a", "s"), filter_fp=None, rg_keep=[True, False],
        row_groups=[
            RowGroupPlan(0, 100, [ChunkPlan("a", 4, 800, 1600, 1, 100),
                                  ChunkPlan("s", 804, 900, 2000, 1, 100)]),
            RowGroupPlan(1, 50, [ChunkPlan("a", 1704, 400, 800, 1, 50)]),
        ])
    plan.note_route(0, "a", "device_snappy", "snappy_resolve")
    plan.note_pruning(1, {("a",): {0, 2}}, 30)
    good = plan.serialize()
    lying_route = good.replace(b"device_snappy", b"warp_teleportx")
    neg_offset = good.replace(b'"offset":4,', b'"offset":-4,')
    dup_ordinal = good.replace(b'"ordinal":1}', b'"ordinal":0}')
    # non-string family: must be the typed rejection, never a TypeError
    # out of the frozenset membership test
    bad_family = good.replace(b'"snappy_resolve"]', b"[1714]]")
    assert (lying_route != good and neg_offset != good
            and dup_ordinal != good and bad_family != good)
    return [
        good,
        good[:17],                      # truncated mid-body
        b"TPQX" + good[4:],             # bad magic
        b"TPQP\xff" + good[5:],         # unknown version
        lying_route,
        neg_offset,
        dup_ordinal,
        bad_family,
        b"TPQP\x01" + b'{"row_groups":"no"}',
    ]


def fuzz_chaos_schedule(data: bytes) -> None:
    """Fuzz target #17: chaos-schedule blob adoption + planner invariants
    (resilience.py).

    A chaos schedule is a TEST plan that drives fault injection over live
    services, so a hostile blob must never become a hostile test run:
    ``from_blob`` either raises ParquetError or yields a schedule whose
    invariants hold (phases sorted + disjoint, every stall bounded — no
    schedule may encode an unbounded stall), whose round-trip is exact
    (``from_blob(to_blob(s)) == s``, bytes stable), and whose phase lookup
    never crashes on arbitrary ordinals.  Seeded GENERATION must be
    deterministic too: same seed, same schedule, byte for byte."""
    from .resilience import MAX_CHAOS_STALL_S, ChaosSchedule

    try:
        s = ChaosSchedule.from_blob(data)
    except ParquetError:
        s = None
    if s is not None:
        blob = s.to_blob()
        q = ChaosSchedule.from_blob(blob)  # our own output must readopt
        assert q == s, "schedule broke round-trip"
        assert q.to_blob() == blob, "to_blob not stable across round-trip"
        prev_end = None
        for p in s.phases:
            assert p.end > p.start
            assert prev_end is None or p.start >= prev_end, "overlap"
            assert not (p.kind == "stall"
                        and p.stall_s > MAX_CHAOS_STALL_S), "unbounded stall"
            prev_end = p.end
        # phase lookup over arbitrary coordinates — never a crash
        for ordinal in (0, 1, 17, 1 << 20):
            s.phase_at(ordinal, file_index=ordinal % 3 - 1)
    # seeded generation: deterministic and self-adopting for ANY params
    seed = int.from_bytes(data[:4], "little") if len(data) >= 4 else len(data)
    n = data[4] % 9 if len(data) > 4 else 4
    files = (data[5] % 4) + 1 if len(data) > 5 else 1
    g1 = ChaosSchedule.generate(seed, n_phases=n, horizon=128, files=files)
    g2 = ChaosSchedule.generate(seed, n_phases=n, horizon=128, files=files)
    assert g1 == g2, "generate() is not deterministic"
    assert ChaosSchedule.from_blob(g1.to_blob()) == g1


def crafted_chaos_blobs() -> "list[bytes]":
    """Hand-crafted ``chaos_schedule`` inputs (and corpus blobs): a valid
    generated schedule plus the hostile shapes adoption must reject."""
    import struct as _struct

    from .resilience import ChaosSchedule

    good = ChaosSchedule.generate(7, n_phases=4, horizon=128, files=3) \
        .to_blob()
    head = good[:11]

    def phase(start, end, kind, intensity=1, fidx=0, stall=0.25):
        return _struct.pack("<IIBBIf", start, end, kind, intensity, fidx,
                            stall)

    def blob(*phases):
        return (b"TPQC\x01" + _struct.pack("<IH", 7, len(phases))
                + b"".join(phases))

    return [
        good,
        good[:9],                        # truncated header
        b"TPQX" + good[4:],              # bad magic
        b"TPQC\xff" + good[5:],          # unknown version
        head + b"\x00" * 7,              # length lies about phase count
        blob(phase(10, 5, 0)),           # end <= start
        blob(phase(0, 10, 0), phase(5, 20, 1)),   # overlapping phases
        blob(phase(0, 10, 9)),           # unknown kind
        blob(phase(0, 10, 0, stall=60.0)),        # unbounded stall
        blob(phase(0, 10, 0, intensity=0)),       # zero intensity
        blob(phase(0, 10, 0, stall=float("nan"))),  # NaN smuggle
    ]


def fuzz_fused_plan(data: bytes) -> None:
    """Fuzz target #18: fused-route planner invariants (ship.py).

    The fused megakernel rows ride the same cost table as every other
    route, so a hostile fact set must never break the table's contracts:

    - a fused row is present ⇔ fusion is enabled AND the facts are
      fused-eligible (``ship.fused_eligible`` — the ONE predicate the
      planner, the device_reader builders, and this target share) AND the
      unfused twin is priced feasible;
    - a fused row never counts the unfused chain's inter-stage HBM term:
      its device cost is the single output-sized pass, <= the twin's
      device cost, and strictly below ``unfused_device_costs`` (the
      spill-inclusive prediction the fusion-win verdict compares against);
    - at equal modeled cost the fused variant outranks its twin — and a
      costlier fused row never jumps the queue;
    - a FORCED fused route on ineligible facts degrades (plan returns
      ``[force, plain]`` and the cost table simply has no fused entry —
      the builder falls through with a counter), never a crash;
    - ``parse_route`` on arbitrary junk warns and returns None, never
      raises (the TPQ_FORCE_ROUTE mid-scan degradation contract).
    """
    from .ship import (
        FUSED_ROUTES, ROUTE_PLAIN as _PLAIN, ROUTES, UNFUSED_OF, ChunkFacts,
        ShipPlanner, fused_eligible, parse_route,
    )

    if len(data) < 14:
        data = data + b"\x00" * (14 - len(data))
    flags = data[0]
    fuse = bool(flags & 1)
    force = (ROUTES[(flags >> 2) % len(ROUTES)] if flags & 2 else None)
    logical = int.from_bytes(data[1:7], "little") % (1 << 33)
    width = (0, 4, 8, 12)[data[7] % 4]
    narrow_k = data[8] % 9
    bits = data[9]
    comp_bytes = int.from_bytes(data[10:14], "little") % (1 << 30)
    f = ChunkFacts(
        logical=logical, width=width, narrow_k=narrow_k,
        narrow_possible=bool(bits & 1), comp_bytes=comp_bytes,
        native=bool(bits & 2), host_bytes_ready=bool(bits & 4),
        flat=bool(bits & 8),
    )
    p = ShipPlanner(link_mbps=1.0 + (data[7] % 97) * 13.0, force=force,
                    fuse=fuse, device_mbps=1.0 + (data[8] % 89) * 11.0)
    order, costs = p.plan(f)  # never raises, whatever the facts
    assert _PLAIN in costs, "plain anchor missing"
    eligible = set(fused_eligible(f))
    for fr in FUSED_ROUTES:
        present = fr in costs
        expected = fuse and fr in eligible and UNFUSED_OF[fr] in costs
        assert present == expected, (fr, present, expected, f)
        if present:
            dev = p.device_costs(f, routes=costs)
            unf = p.unfused_device_costs(f, routes=costs)
            assert dev[fr] <= dev[UNFUSED_OF[fr]] + 1e-12 or \
                UNFUSED_OF[fr] == _PLAIN, (fr, dev)
            assert unf[fr] > dev[fr] - 1e-18, (fr, unf, dev)
            twin = UNFUSED_OF[fr]
            if (force is None and twin in costs
                    and abs(costs[fr] - costs[twin]) < 1e-15):
                assert order.index(fr) < order.index(twin), order
    if force is not None:
        assert order[0] == force and order[-1] == _PLAIN
        # forced-fused on an ineligible stream: no fused cost row, and the
        # infallible plain tail is still there to degrade to
        if force in FUSED_ROUTES and force not in costs:
            assert _PLAIN in order
    # env-validation degradation: junk never raises (candidates are a
    # FIXED set — warn_env_once keys on the value, and a per-blob random
    # string would grow its dedup set without bound over a long campaign)
    junk = ("", "warp", "fusedplain", "FUSED_PLAIN", " plain ",
            *ROUTES)[data[1] % (5 + len(ROUTES))]
    assert parse_route(junk) in (None, *ROUTES)


def crafted_fused_plan_blobs() -> "list[bytes]":
    """Hand-crafted ``fused_plan`` inputs (and corpus blobs): each hits a
    distinct planner branch — fused-on eligible, fused-off, non-flat,
    width-ineligible, forced-fused-ineligible, zero logical, huge facts."""

    def blob(flags, logical, width_sel, k, bits, comp):
        return (bytes([flags]) + logical.to_bytes(6, "little")
                + bytes([width_sel, k, bits]) + comp.to_bytes(4, "little"))

    return [
        blob(1, 8 << 20, 2, 3, 0b1011, 0),        # fuse on, flat int64
        blob(0, 8 << 20, 2, 3, 0b1011, 0),        # fuse off: no fused rows
        blob(1, 8 << 20, 2, 3, 0b0011, 0),        # not flat: ineligible
        blob(1, 8 << 20, 0, 0, 0b1011, 0),        # width 0 (byte array)
        # forced fused_narrow_snappy (index of it in ROUTES) on a float
        # column that can never narrow — degrade path
        blob(2 | 1 | (6 << 2), 8 << 20, 1, 0, 0b1010, 0),
        blob(1, 0, 2, 3, 0b1011, 0),              # zero logical
        blob(3 | (5 << 2), (1 << 33) - 1, 2, 8, 0b1111, (1 << 30) - 1),
    ]


def fuzz_result_cache(data: bytes) -> None:
    """Fuzz target #19: tiered result-cache invariants under arbitrary op
    streams (serve/result_cache.py).

    The input is an op stream (4 bytes per op: opcode, file, row group,
    size) driving a SMALL two-tier ResultCache through puts, gets,
    generation bumps, dictionary traffic, and single-flight builds.  The
    hard invariants hold after EVERY op:

    - the per-tier byte bound is never exceeded (recomputed from the
      entries, compared to the ledger — not trusted from the counters);
    - the device-tier ledger reconciles with the AllocTracker's
      ``device_snapshot`` at all times (the HBM residency accounting);
    - a generation bump always invalidates: once a newer generation of a
      file is cached, NO entry of an older generation is ever served;
    - single-flight never double-builds: a ``get_or_build`` whose key is
      already published must not invoke its builder;
    - key round-trip: the (file key, rg, column, sig) tuple that stored a
      value retrieves exactly that value while it stays resident.
    """
    from .serve.result_cache import ResultCache

    if len(data) < 2:
        return
    host_cap = (data[0] % 64 + 1) * 16          # 16..1024 bytes
    dev_cap = (data[1] % 64) * 16               # 0 = device tier off
    rc = ResultCache(max_bytes=host_cap, hbm_bytes=dev_cap,
                     chunks_enabled=True)
    gens: dict[int, int] = {}

    def fkey(f: int) -> tuple:
        g = gens.setdefault(f, 0)
        return ("file", f"f{f}", 64 + g, g)

    def check_invariants() -> None:
        with rc._lock:
            by_tier = {"host": 0, "device": 0}
            by_count = {"host": 0, "device": 0}
            by_tenant: dict = {}
            for (_v, n, t, ten) in rc._entries.values():
                by_tier[t] += n
                by_count[t] += 1
                if ten is not None:
                    by_tenant[ten] = by_tenant.get(ten, 0) + n
            # the per-tenant byte ledger (QoS cache shares) reconciles
            # with the entries — drift here silently breaks share caps
            ledger = {}
            for t in ("host", "device"):
                for ten, n in rc._tenant_bytes[t].items():
                    ledger[ten] = ledger.get(ten, 0) + n
            assert by_tenant == ledger, "tenant byte ledger drift"
            for t, total in by_tier.items():
                assert total == rc._bytes[t], "byte ledger drift"
                # the per-tier recency index tracks the value map exactly
                assert by_count[t] == len(rc._lru[t]), "LRU index drift"
                cap = rc._caps[t]
                if cap > 0:
                    assert total <= cap, f"{t} byte bound exceeded"
                else:
                    assert total == 0, "entries admitted to a 0-cap tier"
        dev_in_use, _peak = rc.tracker.device_snapshot()
        assert dev_in_use == rc._bytes["device"], "HBM ledger drift"

    pos = 2
    while pos + 4 <= len(data):
        op, f, rg, size = (data[pos], data[pos + 1] % 4, data[pos + 2] % 4,
                           data[pos + 3])
        pos += 4
        col = f"c{(op >> 4) % 3}"
        dev = bool(op & 0x08) and dev_cap > 0
        sig = (("dev", "v1", None, None, False) if dev else ("host", "v1"))
        tier = "device" if dev else "host"
        full = ResultCache.chunk_key(fkey(f), rg, col, sig)
        kind = op % 5
        if kind == 0:
            val = b"x" * max(size, 1)
            if rc.put(full, val, max(size, 1), tier):
                assert rc.get(full) is val, "key round-trip broke"
        elif kind == 1:
            rc.get(full)
        elif kind == 2:
            # generation bump: cache a unit under the NEW generation, then
            # prove the old generation can never be served again
            old = full
            gens[f] = gens.get(f, 0) + 1
            rc.put(ResultCache.chunk_key(fkey(f), 0, "c0", ("host", "v1")),
                   b"g", 1, "host")
            assert rc.get(old) is None, "stale generation served after bump"
        elif kind == 3:
            calls = []

            def build(n=max(size, 1)):
                calls.append(1)
                return b"b" * n, n

            rc.get_or_build(full, build, tier)
            first = len(calls)
            rc.get_or_build(full, build, tier)
            if first == 1 and rc.contains_all([full]):
                assert len(calls) == 1, "single-flight double-built"
        else:
            dk = ResultCache.dict_key(fkey(f), rg, col, "host:v1")
            rc.put(dk, b"d" * max(size, 1), max(size, 1), "host")
            rc.get(dk)
        check_invariants()
    rc.counters()  # reporting must never crash on any reachable state
    rc.progress()


def crafted_result_cache_blobs() -> "list[bytes]":
    """Hand-crafted ``result_cache`` op streams (and corpus blobs): the
    shapes a hot serve tier actually produces plus the hostile ones."""

    def ops(*quads):
        return bytes(b for q in quads for b in q)

    tiny = bytes([0, 4])      # 16B host cap, 64B device cap
    roomy = bytes([63, 63])   # 1024B host, 1008B device
    # opcodes: kind = op % 5 (0 put, 1 get, 2 gen-bump, 3 build, 4 dict);
    # op & 0x08 selects the device tier; bits 4-5 pick the column
    PUT, GET, BUMP, BUILD, DICT = 0, 1, 2, 3, 4
    PUT_DEV, BUILD_DEV = 40, 8  # 40 % 5 == 0 & bit3; 8 % 5 == 3 & bit3

    return [
        # eviction pressure: puts far past the 16B host cap
        tiny + ops(*[(PUT, 0, i % 4, 12) for i in range(12)]),
        # generation churn: put / bump / put / bump on one file
        roomy + ops((PUT, 1, 0, 32), (BUMP, 1, 0, 0), (PUT, 1, 1, 32),
                    (BUMP, 1, 1, 0), (GET, 1, 0, 0)),
        # single-flight + dict traffic interleaved on both tiers
        roomy + ops((BUILD, 0, 0, 64), (DICT, 0, 0, 24),
                    (BUILD_DEV, 0, 1, 64), (BUILD, 0, 0, 64),
                    (DICT, 0, 0, 24)),
        # oversized values: every put must reject, bounds hold
        tiny + ops((PUT, 2, 0, 255), (PUT_DEV, 2, 1, 255), (GET, 2, 0, 0)),
        # device-tier pressure with the host tier idle
        bytes([0, 2]) + ops(*[(PUT_DEV, 3, i % 4, 30) for i in range(8)]),
    ]


def _mini_shard_blob(seed: int = 0, rows: int = 64,
                     kv: "dict | None" = None) -> bytes:
    """One valid single-row-group shard file (the footer_merge seed)."""
    from .format import FieldRepetitionType as FRT, Type
    from .schema.core import build_schema, data_column
    from .write.sharded import encode_row_group

    rng = np.random.default_rng(seed)
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("b", Type.DOUBLE, FRT.REQUIRED),
    ])
    blob, _meta = encode_row_group(
        schema,
        {"a": rng.integers(0, 1000, rows).astype(np.int64),
         "b": rng.random(rows)},
        write_crc=True, kv_metadata=kv)
    return blob


def _frame_merge_parts(parts: "list[tuple[bytes, int]]") -> bytes:
    """Frame (footer_thrift, declared_file_size) pairs as one fuzz blob."""
    out = [bytes([len(parts)])]
    for thrift_bytes, size in parts:
        out.append(len(thrift_bytes).to_bytes(4, "little"))
        out.append(thrift_bytes)
        out.append(int(size).to_bytes(8, "little"))
    return b"".join(out)


def _shard_footer_thrift(blob: bytes) -> bytes:
    flen = int.from_bytes(blob[-8:-4], "little")
    return blob[-8 - flen : -8]


def fuzz_footer_merge(data: bytes) -> None:
    """Fuzz target #20: the write-side footer merge (write/merge.py).

    Input framing: ``[count u8][per part: u32 thrift_len, footer thrift
    bytes, u64 declared file size]``.  Each footer deserializes (or the
    blob is rejected); :func:`~tpu_parquet.write.merge_footers` over the
    parts must either raise ParquetError (truncated/lying/overlapping/
    mismatched shard footers — the typed rejections) or produce a merged
    footer holding the merge invariants: row counts and row-group counts
    sum, shard order is preserved with globally renumbered ordinals, and
    the relocated spans tile the output data segment contiguously from
    the head magic with every chunk offset inside its span."""
    from .format import FileMetaData
    from .scanplan import row_group_byte_span
    from .schema.core import Schema
    from .thrift import ThriftError, deserialize
    from .write.merge import merge_footers

    if len(data) < 1:
        return  # empty merge blob: rejected framing
    count = data[0]
    if not 1 <= count <= 4:
        return  # part count out of range
    pos = 1
    parts = []
    for _ in range(count):
        if pos + 4 > len(data):
            return  # truncated part header
        tlen = int.from_bytes(data[pos : pos + 4], "little")
        pos += 4
        if tlen > len(data) - pos or tlen > (1 << 20):
            return  # part thrift length lies
        try:
            meta = deserialize(FileMetaData, data[pos : pos + tlen])
        except ThriftError:
            return  # bad part footer thrift: rejected
        pos += tlen
        if pos + 8 > len(data):
            return  # truncated part size
        size = int.from_bytes(data[pos : pos + 8], "little")
        pos += 8
        if size > (1 << 40):
            return  # part size lies
        parts.append((meta, size))
    try:
        merged, spans = merge_footers(parts)
    except ParquetError:
        return
    # -- merge invariants (reject was the only other legal outcome) --------
    in_rgs = sum(len(m.row_groups or []) for m, _s in parts)
    in_rows = sum(int(rg.num_rows or 0) for m, _s in parts
                  for rg in (m.row_groups or []))
    assert len(merged.row_groups) == in_rgs, "row-group count not preserved"
    assert len(spans) == in_rgs, "span per row group"
    assert int(merged.num_rows) == in_rows, "row count not preserved"
    assert [rg.ordinal for rg in merged.row_groups] == list(range(in_rgs)), \
        "ordinals not renumbered sequentially"
    schema = Schema.from_file_metadata(merged)
    leaves = {l.path: l for l in schema.leaves}
    pos_out = 4  # spans tile the data segment contiguously from the magic
    order = []
    for rg, (idx, start, end) in zip(merged.row_groups, spans):
        lo, hi = row_group_byte_span(rg, leaves)
        assert lo == pos_out, f"relocated span starts at {lo}, not {pos_out}"
        assert hi - lo == end - start, "relocated span length changed"
        pos_out = pos_out + (end - start)
        order.append(idx)
    assert order == sorted(order), "shard order not preserved"


def crafted_footer_merge_blobs() -> "list[bytes]":
    """Hand-crafted ``footer_merge`` inputs (and corpus blobs): two valid
    shards, then the typed-rejection shapes — truncated footer thrift, a
    declared size that amputates the data segment (lying/truncated
    shard), a footer whose num_rows disagrees with its groups, a schema
    mismatch between shards, and self-overlapping row groups."""
    import copy as _copy

    from .thrift import serialize as _ser

    b1 = _mini_shard_blob(seed=1)
    b2 = _mini_shard_blob(seed=2, rows=32)
    t1, t2 = _shard_footer_thrift(b1), _shard_footer_thrift(b2)
    good = _frame_merge_parts([(t1, len(b1)), (t2, len(b2))])
    # truncated thrift: merge must reject, not crash
    truncated = _frame_merge_parts([(t1[: len(t1) // 2], len(b1))])
    # lying size: the declared file is smaller than the chunk spans need
    amputated = _frame_merge_parts([(t1, 64), (t2, len(b2))])
    # lying num_rows: footer total disagrees with the row groups' sum
    from .format import FileMetaData
    from .thrift import deserialize as _deser

    lying = _deser(FileMetaData, t1)
    lying.num_rows = int(lying.num_rows or 0) + 7
    lying_rows = _frame_merge_parts([(_ser(lying), len(b1))])
    # schema mismatch: shard 2 claims a different column name
    other = _deser(FileMetaData, t2)
    for se in other.schema or []:
        if se.name == "b":
            se.name = "zz"
    mismatch = _frame_merge_parts([(t1, len(b1)), (_ser(other), len(b2))])
    # overlapping row groups: one group duplicated at the same offsets
    dup = _deser(FileMetaData, t1)
    dup.row_groups = [dup.row_groups[0], _copy.deepcopy(dup.row_groups[0])]
    dup.num_rows = 2 * int(dup.row_groups[0].num_rows or 0)
    overlap = _frame_merge_parts([(_ser(dup), len(b1))])
    # single valid shard with kv metadata (the kv-union path)
    b3 = _mini_shard_blob(seed=3, kv={"origin": "fuzz"})
    single = _frame_merge_parts([(_shard_footer_thrift(b3), len(b3))])
    return [good, truncated, amputated, lying_rows, mismatch, overlap,
            single]


def fuzz_stream_cursor(data: bytes) -> None:
    """Streaming-scan cursor surface (serve/stream.py): ANY bytes must
    either unpack to a validated cursor state or raise a tpu_parquet.errors
    type — truncated, bit-flipped, and version-bumped blobs must never
    crash or silently seek a resumed stream.  Accepted cursors must
    round-trip the pack/unpack pair exactly, self-match the compatibility
    fingerprint, and REFUSE a perturbed request digest (the rail that
    keeps a cursor from resuming a different stream)."""
    from .errors import CheckpointError
    from .serve import stream as sc

    try:
        st = sc.unpack_cursor(data)
    except ParquetError:
        return
    st2 = sc.unpack_cursor(sc.pack_cursor(st))
    if st2 != st:
        raise AssertionError(f"cursor round-trip diverges: {st} != {st2}")
    fp = {k: st[k] for k in sc._FINGERPRINT}
    sc.check_cursor_compatible(st, fp)  # self-match must pass
    lying = dict(fp)
    d = str(st["request_digest"])
    lying["request_digest"] = ("0" if d[:1] != "0" else "1") + d[1:]
    try:
        sc.check_cursor_compatible(st, lying)
    except CheckpointError:
        return
    raise AssertionError("cursor accepted a mismatched request digest")


def crafted_stream_cursor_blobs() -> "list[bytes]":
    """Hand-crafted ``stream_cursor`` inputs (and corpus blobs): two valid
    cursors (fresh and mid-stream), then the typed-rejection shapes —
    truncation, bad magic, a bumped version, a ``rows_done`` off the
    batch-boundary rail, ``path_index`` past ``n_paths``, a
    bool-typed int field, and a malformed digest."""
    import json as _json

    from .serve import stream as sc

    def blob(**over):
        st = {"version": sc.CURSOR_VERSION, "batch_rows": 128, "n_paths": 2,
              "path_index": 0, "rows_done": 0, "batches_emitted": 0,
              "device": False, "request_digest": "deadbeefcafe0123"}
        st.update(over)
        payload = _json.dumps(st, sort_keys=True,
                              separators=(",", ":")).encode()
        return (sc.CURSOR_MAGIC
                + int(st.get("version", 1)).to_bytes(2, "big") + payload)

    good = sc.pack_cursor(sc.unpack_cursor(blob()))
    mid = blob(path_index=1, rows_done=384, batches_emitted=3)
    return [
        good, mid,
        good[: len(good) // 2],              # truncated payload
        b"TPQX" + good[4:],                  # bad magic
        blob(version=sc.CURSOR_VERSION + 1),  # unknown version
        blob(rows_done=100),                 # off the batch-boundary rail
        blob(path_index=3),                  # past n_paths
        blob(rows_done=True),                # bool masquerading as int
        blob(request_digest="nope"),         # digest too short
    ]


def fuzz_fetch_engine(data: bytes) -> None:
    """Async fetch-engine op-stream interpreter (iostore_async.py): the
    blob picks the in-flight cap, hedge/fault plan, and an op stream of
    submits / collects / a cancel against a tiny in-memory store.
    Whatever the stream does, the engine's ledger must hold: the in-flight
    gauge never exceeds the cap even transiently, submitted reconciles
    with completed+failed once every future resolves, hedge losers are
    always reaped, cancellation wakes every waiter with a typed verdict,
    and ``close()`` leaves no engine thread behind.  Successful reads must
    return the store's true bytes; failures must be the typed iostore
    verdicts — anything else is a finding."""
    import threading as _threading

    from .errors import (
        CancelledError, DeadlineExceededError, RetryExhaustedError,
        TransientIOError,
    )
    from .iostore import GenericRangeStore, IOConfig, RetryBudget, ScanToken
    from .iostore_async import FetchEngine
    from .resilience import CancelToken

    if len(data) < 2:
        return
    cap = 1 + data[0] % 8
    flags = data[1]
    file_size = 4096
    plan = list(data[2:26])  # per-attempt fault codes, popped in order
    ops = data[26:74]
    lock = _threading.Lock()

    class _Store(GenericRangeStore):
        def size(self):
            return file_size

        async def _fetch_once_async(self, offset, size, timeout):
            import asyncio as _asyncio

            with lock:
                code = (plan.pop(0) % 8) if plan else 0
            if code == 5:
                raise TransientIOError(f"injected fault (code {code})")
            if code == 6:
                await _asyncio.sleep(0.002)  # slow leg: hedge bait
            n = max(min(size, file_size - offset), 0)
            true = bytes((offset + j) % 251 for j in range(n))
            if code == 7 and n > 1:
                return true[: n // 2]  # torn prefix (verified re-read)
            return true

    store = _Store(config=IOConfig(
        retries=3, backoff_ms=0.05, retry_budget=0,
        hedge_ms=(1.0 if flags & 1 else 0.0), deadline_s=10.0))
    cancel = CancelToken()
    scan = ScanToken(budget=RetryBudget(6 if flags & 2 else 0),
                     cancel=cancel)
    eng = FetchEngine(max_inflight=cap, name="tpq-fetch-fuzz")
    outstanding: "list[tuple]" = []

    def collect(fut, off, sz):
        try:
            buf = fut.result(timeout=10.0)
        except (RetryExhaustedError, TransientIOError, CancelledError,
                DeadlineExceededError):
            return
        n = max(min(sz, file_size - off), 0)
        if bytes(buf) != bytes((off + j) % 251 for j in range(n)):
            raise AssertionError(
                f"engine corrupted range [{off}, {off + sz})")

    cancelled = False
    try:
        for b in ops:
            op, arg = b >> 5, b & 31
            if op == 6:
                if outstanding:
                    collect(*outstanding.pop(0))
                continue
            if op == 7:
                cancel.cancel()
                cancelled = True
                continue
            off = (arg * 173) % (file_size + 64)  # may cross or pass EOF
            sz = 1 + (b * 37) % 200
            fut = eng.submit(store, off, sz, scan=scan)
            if eng.stats.inflight > cap:
                raise AssertionError(
                    f"in-flight gauge {eng.stats.inflight} exceeded the "
                    f"cap {cap}")
            outstanding.append((fut, off, sz))
        while outstanding:
            collect(*outstanding.pop(0))
    finally:
        eng.close()
    st = eng.stats
    if st.inflight != 0:
        raise AssertionError(f"in-flight gauge leaked: {st.inflight}")
    if st.inflight_peak > cap:
        raise AssertionError(
            f"in-flight peak {st.inflight_peak} exceeded the cap {cap}")
    if st.completed + st.failed != st.submitted:
        raise AssertionError(
            f"ledger does not reconcile: {st.submitted} submitted != "
            f"{st.completed} completed + {st.failed} failed"
            f" (cancelled={cancelled})")
    if store._hedges_outstanding != 0:
        raise AssertionError(
            f"{store._hedges_outstanding} hedge loser(s) never reaped")
    for t in _threading.enumerate():
        if t.name.startswith("tpq-fetch-fuzz"):
            raise AssertionError("engine thread leaked after close()")


def crafted_fetch_engine_blobs() -> "list[bytes]":
    """Hand-crafted ``fetch_engine`` inputs (and corpus blobs): a deep
    clean burst through a cap-1 engine (every submit queues for the one
    slot), a fault-heavy hedged plan (transient + slow + torn legs racing
    duplicates), a cancel dropped mid-burst with waiters parked on slots,
    a retry-budget-capped scan under pure transient pressure, and an
    interleaved submit/collect stream across EOF."""
    SUB, COLLECT, CANCEL = 0 << 5, 6 << 5, 7 << 5

    def blob(cap_byte, flags, plan, ops):
        return (bytes([cap_byte, flags])
                + bytes(plan[:24]).ljust(24, b"\x00") + bytes(ops))

    deep = blob(0, 0, [], [SUB | (i % 32) for i in range(32)])
    hedged = blob(7, 1, [6, 5, 7, 6, 6, 5, 7, 6] * 3,
                  [SUB | (i % 32) for i in range(16)])
    cancel_mid = blob(0, 0, [6] * 8,
                      [SUB | (i % 32) for i in range(8)] + [CANCEL]
                      + [SUB | 3, SUB | 9] + [COLLECT] * 10)
    budget = blob(3, 2, [5] * 24, [SUB | (i % 32) for i in range(8)])
    interleave = blob(2, 3, [5, 6, 7, 0, 5, 6],
                      [SUB | 31, SUB | 30, COLLECT, SUB | 1, COLLECT,
                       SUB | 29, COLLECT, COLLECT, COLLECT])
    return [deep, hedged, cancel_mid, budget, interleave]


def fuzz_request_trace(data: bytes) -> None:
    """Request-tracing op-stream interpreter (obs.py, ISSUE 19): the blob
    picks the tail sampler's 1-in-N rate, ring size, worker-thread count,
    and per-trace span cap, then drives randomized span open / close /
    error-close / annotate / flag / early-finish ops across threads on
    shared ``RequestTrace`` trees offered to one ``TailSampler``.
    Whatever the stream does: every finished tree is well-nested (a span's
    parent index is always smaller than its own, no null durations after
    ``finish``), the span cap bounds the tree with drops counted, trace
    ids never collide, the export ring honours its byte bound with a
    ledger-consistent retained/evicted count, every retained trace is
    fetchable by id, and every histogram exemplar's raw value re-derives
    the bucket it is stored under — anything else is a finding."""
    import threading as _threading
    import time as _time

    from .obs import LatencyHistogram, RequestTrace, TailSampler

    if len(data) < 6:
        return
    one_in_n = 1 + data[0] % 4
    ring = 4096 + (data[1] & 7) * 1024
    nthreads = 1 + data[2] % 3
    max_spans = 4 + data[3] % 29
    ntraces = 1 + data[4] % 6
    ops = data[5:133]
    sampler = TailSampler(one_in_n=one_in_n, ring_bytes=ring, slow_q=0.95)
    hist = LatencyHistogram()
    ids = []
    for ti in range(ntraces):
        tr = RequestTrace(max_spans=max_spans)
        ids.append(tr.trace_id)

        def run(ops_slice, _tr=tr):
            open_spans = []  # deliberately may leave some open: finish()
            for b in ops_slice:  # must close the orphans
                op, arg = b >> 5, b & 31
                if op in (0, 1):
                    s = _tr.span(f"s{arg}", arg=arg)
                    s.__enter__()
                    open_spans.append(s)
                elif op == 2:
                    if open_spans:
                        open_spans.pop().__exit__(None, None, None)
                elif op == 3:
                    if open_spans:
                        e = ValueError("boom")
                        open_spans.pop().__exit__(ValueError, e, None)
                elif op == 4:
                    t = _time.perf_counter()
                    _tr.add_timed(f"t{arg}", t, t + arg * 1e-6, n=arg)
                elif op == 5:
                    _tr.annotate(bytes=arg)
                elif op == 6:
                    if arg % 3 == 0:
                        _tr.mark_error(ValueError(f"e{arg}"))
                    else:
                        _tr.set_flag(("deadline", "shed")[arg % 2])
                else:
                    _tr.finish()  # racing early finish must stay safe

        threads = [_threading.Thread(target=run, args=(ops[t::nthreads],))
                   for t in range(nthreads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tr.finish()
        if len(tr.spans) > max_spans:
            raise AssertionError(
                f"span cap {max_spans} breached: {len(tr.spans)} spans")
        if tr.dropped and len(tr.spans) != max_spans:
            raise AssertionError(
                f"{tr.dropped} drops counted below the cap "
                f"({len(tr.spans)}/{max_spans} spans)")
        for i, s in enumerate(tr.spans):
            if not (s[3] == -1 or 0 <= s[3] < i):
                raise AssertionError(
                    f"tree not well-nested: span {i} has parent {s[3]}")
            if s[2] is None or s[2] < 0.0:
                raise AssertionError(
                    f"span {i} duration {s[2]!r} after finish()")
        # deterministic synthetic durations spread offers across buckets
        dur = 1e-4 * (ti + 1) + len(tr.spans) * 1e-6
        retained = sampler.offer(tr, duration_s=dur)
        hist.record(dur, exemplar=tr.trace_id if retained else None)
        if retained and sampler.get(tr.trace_id) is None \
                and sampler.counters()["evicted"] == 0:
            raise AssertionError(
                f"retained trace {tr.trace_id} not fetchable by id")
    if len(set(ids)) != len(ids):
        raise AssertionError(f"trace ids collided: {ids}")
    c = sampler.counters()
    if c["retained_bytes"] > c["ring_capacity_bytes"]:
        raise AssertionError(f"export ring over its byte bound: {c}")
    docs = sampler.traces()
    if len(docs) != c["retained"] - c["evicted"]:
        raise AssertionError(
            f"ring ledger does not reconcile: {len(docs)} held vs {c}")
    for doc in docs:
        if sampler.get(doc["trace_id"]) != doc:
            raise AssertionError(
                f"get({doc['trace_id']}) diverged from the ring entry")
    for idx, ex in hist.exemplars.items():
        if LatencyHistogram.bucket_index(ex[1]) != idx:
            raise AssertionError(
                f"exemplar {ex} stored under bucket {idx} but its value "
                f"re-derives bucket {LatencyHistogram.bucket_index(ex[1])}")


def crafted_request_trace_blobs() -> "list[bytes]":
    """Hand-crafted ``request_trace`` inputs (and corpus blobs): a deep
    open chain against a tiny span cap (counted drops + orphan close on
    finish), an interleaved open/error-close/flag storm across 3 threads,
    a retain-all sampler on the smallest ring (eviction churn under the
    byte bound), an early-finish race with ops still arriving, and a
    bucket-spreading run that exercises the exemplar map."""
    OPEN, CLOSE, ERRC, TIMED, ANN, FLAG, FIN = (
        0 << 5, 2 << 5, 3 << 5, 4 << 5, 5 << 5, 6 << 5, 7 << 5)
    deep = bytes([0, 7, 0, 0, 0]) + bytes(
        [OPEN | (i % 32) for i in range(40)])
    storm = bytes([0, 7, 2, 12, 2]) + bytes(
        [OPEN | 1, OPEN | 2, ERRC | 0, CLOSE | 0, TIMED | 9, ANN | 3,
         OPEN | 4, FLAG | 3, CLOSE | 0] * 6)
    churn = bytes([0, 0, 0, 28, 5]) + bytes(
        [(OPEN | (i % 32)) if i % 3 else (TIMED | (i % 32))
         for i in range(64)])
    early = bytes([0, 0, 1, 10, 1]) + bytes(
        [OPEN | 5, FIN, OPEN | 6, TIMED | 2, CLOSE, FIN, OPEN | 7,
         ANN | 1, CLOSE])
    spread = bytes([0, 3, 1, 20, 5]) + bytes(
        [TIMED | (1 + i % 31) for i in range(32)] + [OPEN | 9, CLOSE])
    return [deep, storm, churn, early, spread]


def fuzz_fleet_snapshot(data: bytes) -> None:
    """Fleet-spool op-stream interpreter (obs_fleet.py, ISSUE 20): the
    blob picks the member count, per-member retained generations, and
    staleness threshold, then drives counter bumps / gauge raises /
    histogram records / ``publish_once`` / torn-file injection /
    dead-member injection / full aggregation scans against one spool
    directory.  Whatever the stream does: fleet counters reconcile
    EXACTLY with the sum of each member's last-published model, gauges
    (``workers``) merge as the max, merged histogram counts equal the
    published sum and every exemplar's raw value re-derives its bucket,
    torn/truncated/garbage files are counted rejected (exactly) and are
    never fatal, injected dead members always read stale, per-member
    heartbeats are monotonic across generations, and pruning never
    retains more than ``keep`` generations — anything else is a finding.
    """
    import json
    import shutil as _shutil
    import tempfile as _tempfile
    import time

    from .obs import LatencyHistogram, StatsRegistry
    from .obs_fleet import FleetAggregator, SpoolWriter

    if len(data) < 4:
        return
    n_members = 1 + data[0] % 4
    keep = 1 + data[1] % 3
    stale_s = 0.5 + (data[2] & 3)
    ops = data[3:131]
    tmp = _tempfile.mkdtemp(prefix="tpq-fuzz-spool-")
    try:
        members = []
        for m in range(n_members):
            reg = StatsRegistry()
            members.append({
                "reg": reg,
                "w": SpoolWriter(reg, role=("serve", "loader", "writer")[
                    m % 3], spool_dir=tmp, keep=keep,
                    host=f"h{m % 2}", pid=1000 + m),
                "rows": 0, "workers": 0, "hist": 0,
                "pub": None, "hb": -1.0,
            })
        agg = FleetAggregator(spool_dir=tmp, stale_s=stale_s)
        garbage = dead = 0

        def check_scan():
            snap = agg.scan()
            if snap["rejected"] != garbage:
                raise AssertionError(
                    f"{garbage} garbage file(s) written but "
                    f"{snap['rejected']} rejected")
            pubs = [mm["pub"] for mm in members if mm["pub"] is not None]
            live = len(pubs)
            if len(snap["processes"]) != live + dead:
                raise AssertionError(
                    f"{live} live + {dead} dead member(s) but "
                    f"{len(snap['processes'])} in the fleet snapshot")
            wr = (snap["registry"].get("write") or {})
            want_rows = sum(p["rows"] for p in pubs)
            if int(wr.get("rows", 0)) != want_rows:
                raise AssertionError(
                    f"fleet write.rows {wr.get('rows')} != published sum "
                    f"{want_rows}")
            want_workers = max((p["workers"] for p in pubs), default=0)
            if int(wr.get("workers", 0)) != want_workers:
                raise AssertionError(
                    f"fleet write.workers {wr.get('workers')} != published "
                    f"max {want_workers}")
            hd = (snap["registry"].get("histograms") or {}).get(
                "serve.request") or {}
            want_n = sum(p["hist"] for p in pubs)
            if int(hd.get("count", 0)) != want_n:
                raise AssertionError(
                    f"fleet histogram count {hd.get('count')} != published "
                    f"sum {want_n}")
            for bi, ex in (hd.get("exemplars") or {}).items():
                if LatencyHistogram.bucket_index(float(ex[1])) != int(bi):
                    raise AssertionError(
                        f"merged exemplar {ex} under bucket {bi} re-derives "
                        f"{LatencyHistogram.bucket_index(float(ex[1]))}")
            for key, p in snap["processes"].items():
                if key.startswith("dead") and not p["stale"]:
                    raise AssertionError(
                        f"injected dead member {key} not flagged stale: {p}")

        for i, b in enumerate(ops):
            op, arg = b >> 5, b & 31
            mem = members[arg % n_members]
            if op in (0, 1):
                mem["reg"].add_write({"rows": arg + 1})
                mem["rows"] += arg + 1
            elif op == 2:
                mem["reg"].add_write({"workers": arg})
                mem["workers"] = max(mem["workers"], arg)
            elif op == 3:
                mem["reg"].histogram("serve.request").record(
                    (arg + 1) * 1e-4, exemplar=f"t-{arg}-{i}")
                mem["hist"] += 1
            elif op == 4:
                path = mem["w"].publish_once()
                if path is None:
                    raise AssertionError(
                        f"publish_once failed with a live spool dir "
                        f"({mem['w'].dropped} dropped)")
                with open(path) as f:
                    doc = json.load(f)
                if doc["heartbeat_ts"] < mem["hb"]:
                    raise AssertionError(
                        f"heartbeat went backwards: {doc['heartbeat_ts']} "
                        f"after {mem['hb']}")
                mem["hb"] = doc["heartbeat_ts"]
                mem["pub"] = {"rows": mem["rows"],
                              "workers": mem["workers"],
                              "hist": mem["hist"]}
            elif op == 5:
                kind = arg % 3
                blob = (b"{torn" if kind == 0
                        else b"[1, 2, 3]" if kind == 1
                        else json.dumps({"spool_version": 999, "host": "x",
                                         "pid": 1, "seq": 1,
                                         "heartbeat_ts": 0,
                                         "registry": {}}).encode())
                with open(os.path.join(tmp, f"zz-garbage-{i}.json"),
                          "wb") as f:
                    f.write(blob)
                garbage += 1
            elif op == 6:
                doc = {"spool_version": 1, "host": f"dead{i}", "pid": 9000,
                       "role": "loader", "seq": 1,
                       "heartbeat_ts": time.time() - 3600.0,
                       "registry": StatsRegistry().as_dict(), "traces": []}
                with open(os.path.join(tmp, f"dead{i}-9000.00000001.json"),
                          "w") as f:
                    json.dump(doc, f)
                dead += 1
            else:
                check_scan()
        check_scan()
        for mem in members:
            prefix = f"{mem['w']._member}."
            mine = [fn for fn in os.listdir(tmp) if fn.startswith(prefix)
                    and fn.endswith(".json")]
            if len(mine) > keep:
                raise AssertionError(
                    f"prune kept {len(mine)} generation(s) of "
                    f"{mem['w']._member}, cap {keep}: {sorted(mine)}")
    finally:
        _shutil.rmtree(tmp, ignore_errors=True)


def crafted_fleet_snapshot_blobs() -> "list[bytes]":
    """Hand-crafted ``fleet_snapshot`` inputs (and corpus blobs): a
    publish/scan cadence across 4 members, a garbage storm against one
    publishing member, a keep=1 prune churn with gauge raises, a
    dead-member graveyard, and a histogram/exemplar spread — each ends in
    a full-invariant aggregation scan."""
    BUMP, GAUGE, HIST, PUB, TORN, DEAD, SCAN = (
        0 << 5, 2 << 5, 3 << 5, 4 << 5, 5 << 5, 6 << 5, 7 << 5)
    cadence = bytes([3, 1, 1]) + bytes(
        b for i in range(8)
        for b in (BUMP | (i % 4), HIST | (i % 4), PUB | (i % 4), SCAN))
    storm = bytes([0, 1, 0]) + bytes(
        b for i in range(10)
        for b in (BUMP | 0, TORN | (i % 3), PUB | 0, SCAN))
    churn = bytes([0, 0, 2]) + bytes(
        b for i in range(12)
        for b in (GAUGE | (i % 8), BUMP | 0, PUB | 0)) + bytes([SCAN])
    graveyard = bytes([1, 1, 3]) + bytes(
        b for i in range(6) for b in (DEAD | 0, PUB | 0)) + bytes(
        [SCAN, DEAD | 0, SCAN])
    spread = bytes([2, 2, 0]) + bytes(
        b for i in range(20) for b in (HIST | (i % 32 & 31), PUB | (i % 2))
    ) + bytes([SCAN])
    return [cadence, storm, churn, graveyard, spread]


TARGETS = {
    "file_reader": fuzz_file_reader,
    "thrift": fuzz_thrift,
    "hybrid": fuzz_hybrid,
    "delta": fuzz_delta,
    "plain": fuzz_plain,
    "schema_dsl": fuzz_schema_dsl,
    "device_reader": fuzz_device_reader,
    "page_header": fuzz_page_header,
    "snappy": fuzz_snappy,
    "snappy_plan": fuzz_snappy_plan,
    "snappy_ops": fuzz_snappy_ops,
    "narrow": fuzz_narrow,
    "loader_state": fuzz_loader_state,
    "io_ranges": fuzz_io_ranges,
    "page_corrupt": fuzz_page_corrupt,
    "scan_plan": fuzz_scan_plan,
    "chaos_schedule": fuzz_chaos_schedule,
    "fused_plan": fuzz_fused_plan,
    "result_cache": fuzz_result_cache,
    "footer_merge": fuzz_footer_merge,
    "stream_cursor": fuzz_stream_cursor,
    "fetch_engine": fuzz_fetch_engine,
    "request_trace": fuzz_request_trace,
    "fleet_snapshot": fuzz_fleet_snapshot,
}


def crafted_io_range_blobs() -> "list[bytes]":
    """Hand-crafted ``io_ranges`` inputs (and corpus blobs): the planner
    shapes a real footer produces plus the hostile ones it doesn't."""

    def rec(off, size):
        return off.to_bytes(3, "little") + size.to_bytes(2, "little")

    # adjacent column chunks with small header gaps (the real row-group
    # shape coalescing exists for), generous gap + span
    adjacent = bytes([4, 2, 0]) + b"".join(
        rec(o, 1000) for o in range(64, 16064, 1040))
    # duplicate + overlapping ranges (a re-read of a dict page overlaps its
    # chunk), short-lie mode
    overlap = bytes([2, 2, 1]) + rec(100, 500) + rec(100, 500) + \
        rec(300, 800) + rec(2000, 100)
    # span-cap pressure: members that would merge but for the 128-byte cap,
    # overlong-lie mode
    capped = bytes([1, 0, 2]) + b"".join(rec(o, 100) for o in range(0, 1200, 101))
    # zero-size + EOF-straddling + past-EOF ranges, zero gap
    edges = bytes([0, 1, 1]) + rec(50, 0) + rec((1 << 18) - 40, 200) + \
        rec(1 << 18, 100) + rec(10, 7)
    return [adjacent, overlap, capped, edges]


# ---------------------------------------------------------------------------
# seeds + mutation
# ---------------------------------------------------------------------------

def _uvarint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def crafted_snappy_streams() -> "list[bytes]":
    """Hand-crafted raw-snappy streams for the snappy_ops target (and its
    checked-in corpus blobs): the hostile shapes the op-table planner must
    survive — no compressor in this repo emits them, so only crafting
    covers them."""
    # deep offset-1 overlap chain: 1 literal byte then 50 copies each
    # reading the bytes the PREVIOUS copy just wrote (max chain depth ~50,
    # the pointer-doubling resolver's worst shape per op count)
    deep = bytearray(_uvarint(1 + 50 * 60))
    deep += b"\x00x"  # literal len 1: 'x'
    for _ in range(50):
        deep += bytes([((60 - 1) << 2) | 2, 1, 0])  # kind-2 copy len 60 off 1
    # out-of-range copy: offset 5 with only 1 output byte written — the
    # decompressor rejects; the planner must reject identically
    oor = _uvarint(5) + b"\x00x" + bytes([((4 - 1) << 2) | 2, 5, 0])
    # kind-3 copy (4-byte little-endian offset, > 64 KiB back): a tag no
    # in-tree compressor emits
    lit = (bytes(range(256)) * 274)[:70000]
    big = bytearray(_uvarint(70064))
    big += bytes([62 << 2]) + (70000 - 1).to_bytes(3, "little") + lit
    big += bytes([((64 - 1) << 2) | 3]) + (65540).to_bytes(4, "little")
    # op-count pressure: 2000 one-byte literals — far past the planner's
    # starting table cap (max(n/32, 64)), forcing the ERR_CAP retry path
    many = bytearray(_uvarint(2000))
    for i in range(2000):
        many += bytes([0x00, i & 0xFF])
    return [bytes(deep), oor, bytes(big), bytes(many)]


def _seed_inputs(target: str) -> list[bytes]:
    """Valid inputs for the target, built in-process (corpus seeds)."""
    rng = np.random.default_rng(0)
    if target in ("file_reader", "thrift", "device_reader"):
        import io as _io

        from .format import (
            CompressionCodec, FieldRepetitionType as FRT, Type,
        )
        from .schema.core import build_schema, data_column
        from .writer import FileWriter

        sink = _io.BytesIO()
        schema = build_schema([
            data_column("a", Type.INT64, FRT.REQUIRED),
            data_column("b", Type.BYTE_ARRAY, FRT.OPTIONAL),
        ])
        with FileWriter(sink, schema, codec=CompressionCodec.SNAPPY) as w:
            from .column import ByteArrayData, ColumnData

            vals = [b"x", None, b"yz", b"", None, b"abc"] * 4
            heap = b"".join(v or b"" for v in vals)
            offs = np.cumsum([0] + [len(v or b"") for v in vals])
            dl = np.array([0 if v is None else 1 for v in vals], np.uint32)
            w.write_columns({
                "a": rng.integers(-(1 << 50), 1 << 50, len(vals)),
                "b": ColumnData(
                    values=ByteArrayData(
                        offsets=offs[np.r_[0, 1 + np.flatnonzero(dl)]],
                        heap=np.frombuffer(heap, np.uint8).copy(),
                    ),
                    def_levels=dl, max_def=1,
                ),
            })
        whole = sink.getvalue()
        if target == "thrift":
            # footer thrift bytes only (between data end and trailing len+magic)
            flen = int.from_bytes(whole[-8:-4], "little")
            return [whole[-8 - flen : -8]]
        if target == "device_reader":
            # second seed: PLAIN (non-dictionary) strings — the device-side
            # lengths/heap-compaction path has no dict analogue
            sink2 = _io.BytesIO()
            schema2 = build_schema([
                data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED),
            ])
            from .column import ByteArrayData, ColumnData

            svals = [b"alpha", b"", b"bb", b"gamma-gamma", b"x"] * 8
            with FileWriter(sink2, schema2, codec=CompressionCodec.SNAPPY,
                            use_dictionary=False) as w2:
                w2.write_columns({"s": ColumnData(values=ByteArrayData(
                    offsets=np.cumsum([0] + [len(v) for v in svals]),
                    heap=np.frombuffer(b"".join(svals), np.uint8).copy(),
                ))})
            return [whole, sink2.getvalue()]
        return [whole]
    if target == "hybrid":
        from .kernels import rle

        vals = rng.integers(0, 8, 300, dtype=np.uint64)
        enc = rle.encode(vals, 3)
        return [bytes([3, 300 % 256]) + enc]
    if target == "delta":
        from .kernels import delta

        vals = np.cumsum(rng.integers(-50, 50, 300)).astype(np.int64)
        return [b"\x00" + delta.encode(vals, bits=64)]
    if target == "plain":
        return [bytes([6, 20]) + b"".join(
            len(s).to_bytes(4, "little") + s
            for s in (b"alpha", b"", b"beta") * 7
        )]
    if target == "page_header":
        from .format import (
            DataPageHeader, DataPageHeaderV2, DictionaryPageHeader, PageHeader,
        )
        from .thrift import write_struct

        v1 = PageHeader(
            type=0, uncompressed_page_size=1000, compressed_page_size=600,
            crc=123456, data_page_header=DataPageHeader(
                num_values=300, encoding=3, definition_level_encoding=3,
                repetition_level_encoding=3,
            ),
        )
        v2 = PageHeader(
            type=3, uncompressed_page_size=2048, compressed_page_size=900,
            data_page_header_v2=DataPageHeaderV2(
                num_values=128, num_nulls=5, num_rows=100, encoding=8,
                definition_levels_byte_length=17,
                repetition_levels_byte_length=0, is_compressed=True,
            ),
        )
        d = PageHeader(
            type=2, uncompressed_page_size=64, compressed_page_size=64,
            dictionary_page_header=DictionaryPageHeader(
                num_values=16, encoding=0, is_sorted=False,
            ),
        )
        return [write_struct(x) for x in (v1, v2, d)]
    if target == "schema_dsl":
        return [b"message m { required int64 a; optional group l (LIST) "
                b"{ repeated group list { optional binary element (STRING); } } }"]
    if target in ("snappy", "snappy_plan"):
        from . import native
        from .compress import _py_snappy_compress

        comp = (native.snappy_compress if native.available()
                else _py_snappy_compress)
        # bytes() each seed: native compress returns a uint8 array, and
        # mutate()'s truthiness/slicing assumes bytes semantics
        return [bytes(comp(x)) for x in (
            b"the quick brown fox " * 40,            # literal+copy mix
            bytes(rng.integers(0, 4, 600).astype(np.uint8)),
            b"\x00" * 3000,                          # deep RLE-style chains
            b"ab" * 2000,                            # offset-2 overlap copies
            b"",
        )]
    if target == "snappy_ops":
        return [b"\x02" + s for s in crafted_snappy_streams()] + [
            # declared-size lie: bias +1 on a valid stream must reject
            b"\x03" + crafted_snappy_streams()[0],
        ]
    if target == "io_ranges":
        return crafted_io_range_blobs()
    if target == "page_corrupt":
        return crafted_page_corrupt_blobs()
    if target == "scan_plan":
        return crafted_scan_plan_blobs()
    if target == "chaos_schedule":
        return crafted_chaos_blobs()
    if target == "fused_plan":
        return crafted_fused_plan_blobs()
    if target == "result_cache":
        return crafted_result_cache_blobs()
    if target == "footer_merge":
        return crafted_footer_merge_blobs()
    if target == "stream_cursor":
        return crafted_stream_cursor_blobs()
    if target == "fetch_engine":
        return crafted_fetch_engine_blobs()
    if target == "request_trace":
        return crafted_request_trace_blobs()
    if target == "fleet_snapshot":
        return crafted_fleet_snapshot_blobs()
    if target == "loader_state":
        from .data import checkpoint as ck

        _force_cpu_jax()
        loader = _loader_for_fuzz()
        fresh = loader.state_blob()
        mid = dict(loader.state())
        mid.update(epoch=2, rows_taken=2 * loader.batch_size)
        return [fresh, ck.pack_state(mid)]
    if target == "narrow":
        return [
            rng.integers(500, 1500, 64).astype(np.int64).tobytes(),
            (rng.integers(-40, 40, 64) * 1000).astype(np.int64).tobytes(),
            rng.integers(0, 200, 64).astype(np.int32).tobytes(),
            np.full(32, -(1 << 62), dtype=np.int64).tobytes(),
        ]
    raise KeyError(target)


def mutate(data: bytes, rng: np.random.Generator) -> bytes:
    """go-fuzz-style byte mutations: flips, splices, truncation, duplication."""
    if not data:
        return bytes(rng.integers(0, 256, rng.integers(1, 64), dtype=np.uint8))
    buf = bytearray(data)
    for _ in range(int(rng.integers(1, 8))):
        if not buf:
            break
        op = rng.integers(0, 6)
        i = int(rng.integers(0, len(buf)))
        if op == 0:      # bit flip
            buf[i] ^= 1 << int(rng.integers(0, 8))
        elif op == 1:    # random byte
            buf[i] = int(rng.integers(0, 256))
        elif op == 2 and len(buf) > 1:   # truncate tail
            del buf[i:]
        elif op == 3:    # insert random run
            ins = bytes(rng.integers(0, 256, int(rng.integers(1, 16)), dtype=np.uint8))
            buf[i:i] = ins
        elif op == 4:    # duplicate a chunk
            j = int(rng.integers(0, len(buf)))
            lo, hi = min(i, j), max(i, j)
            buf[lo:lo] = buf[lo:hi][:64]
        elif op == 5:    # interesting values
            magic = rng.choice([0x00, 0xFF, 0x7F, 0x80, 0x01])
            buf[i] = int(magic)
        if len(buf) > 1 << 16:
            del buf[1 << 16 :]
    return bytes(buf)


def minimize(target_fn, data: bytes, max_rounds: int = 200) -> bytes:
    """Greedy chunk-deletion minimization preserving the crash."""
    def crashes(b: bytes) -> bool:
        try:
            target_fn(b)
            return False
        except ParquetError:
            return False
        except Exception:
            return True

    if not crashes(data):
        return data
    cur = data
    step = max(len(cur) // 2, 1)
    rounds = 0
    while step > 0 and rounds < max_rounds:
        i = 0
        shrunk = False
        while i < len(cur) and rounds < max_rounds:
            cand = cur[:i] + cur[i + step :]
            rounds += 1
            if cand != cur and crashes(cand):
                cur = cand
                shrunk = True
            else:
                i += step
        if not shrunk:
            step //= 2
    return cur


def run_fuzz(target: str, runs: int, seed: int = 0, save_crashers: bool = True):
    """Fuzz one target; returns list of (minimized_input, exception_repr)."""
    fn = TARGETS[target]
    rng = np.random.default_rng(seed)
    corpus = _seed_inputs(target)
    crashers = []
    for it in range(runs):
        base = corpus[int(rng.integers(0, len(corpus)))]
        data = mutate(base, rng)
        try:
            fn(data)
            if len(corpus) < 64 and rng.random() < 0.02:
                corpus.append(data)  # coverage-ish: keep accepted mutants
        except ParquetError:
            pass
        except Exception as e:  # noqa: BLE001 — the whole point
            small = minimize(fn, data)
            crashers.append((small, repr(e)))
            if save_crashers:
                os.makedirs(_CORPUS_DIR, exist_ok=True)
                name = f"{target}-{hashlib.sha256(small).hexdigest()[:12]}"
                with open(os.path.join(_CORPUS_DIR, name), "wb") as f:
                    f.write(small)
            print(f"[{target}] iter {it}: CRASH {e!r} "
                  f"({len(data)}B → {len(small)}B)", file=sys.stderr)
    return crashers


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--target", default="all", choices=["all", *TARGETS])
    ap.add_argument("--runs", type=int, default=5000)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    names = list(TARGETS) if args.target == "all" else [args.target]
    total = 0
    for name in names:
        found = run_fuzz(name, args.runs, seed=args.seed)
        print(f"{name}: {args.runs} runs, {len(found)} crashers", file=sys.stderr)
        total += len(found)
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
