"""CLI tools: pq_tool (cat/head/meta/schema/rowcount/split) and csv2parquet.

Equivalents of the reference's cmd/parquet-tool (cobra CLI, cmd/parquet-tool/
cmds/*.go) and cmd/csv2parquet (cmd/csv2parquet/main.go:24-435).
"""
