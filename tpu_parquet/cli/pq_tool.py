"""pq-tool: inspect and manipulate parquet files.

Command parity with the reference's parquet-tool (cmd/parquet-tool/cmds/):

    cat       print all records              (cat.go:14-27)
    head      print the first N records      (head.go:17-30)
    meta      flat schema + per-column R/D levels + row group info (meta.go)
    schema    print the textual schema definition  (schema.go:16-37)
    rowcount  number of rows from the footer       (rowcount.go:16-37)
    stats     per-row-group column min/max/null_count (beyond the reference)
    split     re-shard into parts of at most a given size (split.go:31-117)
    trace     summarize a TPQ_TRACE run (per-stage p50/p95 incl. the
              device.<route> completion lanes, overlap efficiency, stall
              attribution, ship-route prediction error)
    doctor    rule-based bottleneck attribution of a traced run (link- vs
              host-decompress- vs stall- vs device-resolve- vs h2d-bound,
              naming the dominant device route/kernel), with the
              recalibrated TPQ_LINK_MBPS / TPQ_DEVICE_MBPS when the routes
              disagree with the ship planner's cost model
    autopsy   post-mortem of a flight-recorder dump (the watchdog's or
              TPQ_DUMP_SIGNAL's hang/crash snapshot): stalled lane,
              blocked-thread classification, probable cause
    bench     run-ledger tools: `bench diff A B` (per-metric deltas with
              noise bounds from rep variance + stage attribution) and
              `bench history LEDGER` (one line per recorded run)

trace/doctor/bench-diff arguments may be ledger refs — `latest`, `#N`,
`ledger.jsonl#N` (default ledger: TPQ_LEDGER or ./ledger.jsonl) — so a
post-mortem never requires remembering an artifact path.

cat/head/rowcount take --filter "a > 5 and b == 'x'" for statistics-based
row-group pruning (tpu_parquet.predicate).

Usage: python -m tpu_parquet.cli.pq_tool <command> [options] <file>
"""

from __future__ import annotations

import argparse
import base64
import datetime
import decimal
import json
import sys
import uuid

from ..floor.time import Time
from ..footer import ParquetError
from ..format import CompressionCodec, Type
from ..logical import unwrap_row
from ..reader import FileReader
from ..schema.dsl import schema_to_string
from ..writer import FileWriter


def _json_default(v):
    if isinstance(v, (bytes, bytearray)):
        try:
            return bytes(v).decode("utf-8")
        except UnicodeDecodeError:
            return base64.b64encode(bytes(v)).decode("ascii")
    if isinstance(v, (datetime.datetime, datetime.date, Time)):
        return str(v)
    if isinstance(v, (decimal.Decimal, uuid.UUID)):
        return str(v)
    return repr(v)


def _row_filter(args):
    if getattr(args, "filter", None) is None:
        return None
    from ..predicate import parse_filter

    return parse_filter(args.filter)


def cmd_cat(args, out=sys.stdout) -> int:
    """Shared handler for cat and head (identical modulo the -n default)."""
    from ..floor import Reader

    count = 0
    with Reader(args.file, row_filter=_row_filter(args)) as r:
        for row in r:
            if args.n is not None and count >= args.n:
                break
            out.write(json.dumps(row, default=_json_default) + "\n")
            count += 1
    return 0


def cmd_meta(args, out=sys.stdout) -> int:
    with FileReader(args.file) as r:
        meta = r.metadata
        out.write(f"file: {args.file}\n")
        out.write(f"created by: {meta.created_by}\n")
        out.write(f"rows: {meta.num_rows}\n")
        out.write(f"row groups: {len(meta.row_groups)}\n")
        kv = r.key_value_metadata()
        if kv:
            out.write("key-value metadata:\n")
            for k, v in sorted(kv.items()):
                if k == "ARROW:schema":
                    v = "(arrow schema blob)"
                out.write(f"  {k} = {v}\n")
        out.write("columns:\n")
        name_w = max((len(l.flat_name()) for l in r.schema.leaves), default=4)
        for leaf in r.schema.leaves:
            t = leaf.physical_type
            tname = t.name if t is not None else "group"  # BOOLEAN is enum 0
            out.write(
                f"  {leaf.flat_name():<{name_w}}  type={tname:<22} "
                f"R={leaf.max_rep} D={leaf.max_def}\n"
            )
        for i, rg in enumerate(meta.row_groups):
            out.write(
                f"row group {i}: rows={rg.num_rows} "
                f"bytes={rg.total_byte_size}\n"
            )
            for chunk in rg.columns or []:
                md = chunk.meta_data
                if md is None:
                    continue
                codec = CompressionCodec(md.codec).name
                out.write(
                    f"  {'.'.join(md.path_in_schema):<{name_w}}  "
                    f"values={md.num_values} codec={codec} "
                    f"compressed={md.total_compressed_size} "
                    f"uncompressed={md.total_uncompressed_size}\n"
                )
    return 0


def cmd_schema(args, out=sys.stdout) -> int:
    with FileReader(args.file) as r:
        out.write(schema_to_string(r.schema))
    return 0


def cmd_rowcount(args, out=sys.stdout) -> int:
    with FileReader(args.file, row_filter=_row_filter(args)) as r:
        # surviving groups' total; equals num_rows when no filter is set
        out.write(f"{r.num_selected_rows}\n")
    return 0


def cmd_stats(args, out=sys.stdout) -> int:
    """Per-row-group, per-column statistics (the pruning evidence)."""
    from ..predicate import chunk_stats_range

    def fmt(v):
        if v is None:
            return "-"
        if isinstance(v, bytes):
            try:
                return repr(v.decode("utf-8"))
            except UnicodeDecodeError:
                return v.hex()
        return str(v)

    with FileReader(args.file) as r:
        leaves = {".".join(l.path): l for l in r.schema.leaves}
        name_w = max((len(n) for n in leaves), default=4)
        for i, rg in enumerate(r.metadata.row_groups):
            out.write(f"row group {i}: rows={rg.num_rows}\n")
            for chunk in rg.columns or []:
                md = chunk.meta_data
                if md is None or not md.path_in_schema:
                    continue
                name = ".".join(md.path_in_schema)
                leaf = leaves.get(name)
                if leaf is None:
                    continue
                mn, mx, nulls, _, _ = chunk_stats_range(md, leaf.element)
                out.write(
                    f"  {name:<{name_w}}  min={fmt(mn)} max={fmt(mx)} "
                    f"nulls={fmt(nulls)}\n"
                )
    return 0


def _load_doc(spec: str):
    """Load a command argument to a JSON document: a plain file path, or a
    ledger reference (``latest``, ``#N``, ``ledger.jsonl[#N]`` — see
    ledger.load_side), so post-mortems address runs the way ``bench diff``
    already does instead of remembering artifact paths."""
    from .. import ledger

    if ledger.is_ref(spec):
        return ledger.load_side(spec)
    try:
        with open(spec) as f:
            return json.load(f)
    except json.JSONDecodeError as e:
        raise ValueError(f"{spec}: not JSON ({e})") from None


def _fmt_span_args(args_d: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in args_d.items())


def _write_span_tree(out, tr: dict, indent: str = "") -> None:
    """Emit one trace document's span tree (indentation is nesting)."""
    spans = tr.get("spans") or []
    children: dict = {}
    for i, s in enumerate(spans):
        children.setdefault(s.get("parent", -1), []).append(i)

    def emit(idx: int, depth: int) -> None:
        s = spans[idx]
        d = s.get("dur_s")
        line = (f"{indent}  {'  ' * depth}"
                f"{s.get('name', '?'):<{18 - 2 * min(depth, 6)}} "
                f"@{s.get('t_s', 0) * 1e3:>9.3f}ms "
                + (f"{d * 1e3:>9.3f}ms" if d is not None else f"{'?':>11}"))
        extra = _fmt_span_args(s.get("args") or {})
        out.write(line + (f"  {extra}" if extra else "") + "\n")
        for c in children.get(idx, ()):
            emit(c, depth + 1)

    for root in children.get(-1, ()):
        emit(root, 0)


def _spool_trace_docs(spool_dir: str) -> list:
    """Every trace document published into a fleet spool (each member's
    freshest generation), for stitched multi-process rendering."""
    from ..obs_fleet import FleetAggregator

    snap = FleetAggregator(spool_dir=spool_dir).scan()
    return [t for t in snap.get("traces") or () if isinstance(t, dict)]


def cmd_trace_request(args, out=sys.stdout) -> int:
    """``pq_tool trace --request <id> <dump>``: print one retained
    request's span tree from a tail-sampler dump
    (:meth:`~tpu_parquet.serve.ScanService.trace_dump` /
    ``TailSampler.dump`` output) — indentation is nesting, each line a
    span's start offset, duration, and annotations (retry counts, hedge
    outcomes, cache hits), so a bad exemplar percentile reads as a story:
    which range fetch stalled, which probe missed, where the time went.

    With ``--spool DIR`` the fleet spool's trace docs join the pool (the
    dump file becomes optional) and children that adopted the request's
    exported trace context — loader iterations, ``write_sharded`` encode
    passes, any process that called ``adopt_context`` — render stitched
    under it, labelled ``[host:pid]``: one request, every process."""
    traces: list = []
    label = args.file or ""
    if args.file:
        doc = _load_doc(args.file)
        if isinstance(doc, dict) and isinstance(doc.get("traces"), list):
            traces = [t for t in doc["traces"] if isinstance(t, dict)]
        elif isinstance(doc, dict) and "trace_id" in doc:
            traces = [doc]
        else:
            out.write(f"pq-tool trace: {args.file}: not a trace dump "
                      f"(expected the ScanService.trace_dump / "
                      f"TailSampler.dump format)\n")
            return 1
    spool = getattr(args, "spool", None)
    if spool:
        traces.extend(_spool_trace_docs(spool))
        label = f"{label} + spool {spool}" if label else f"spool {spool}"
    if not args.file and not spool:
        out.write("pq-tool trace: --request needs a dump file and/or "
                  "--spool DIR\n")
        return 1
    want = args.request
    match = [t for t in traces if t.get("trace_id") == want]
    if not match:  # prefix match: ids are long, tails are what users copy
        match = [t for t in traces
                 if str(t.get("trace_id", "")).startswith(want)]
    if not match:
        ids = ", ".join(str(t.get("trace_id")) for t in traces[-8:])
        out.write(f"pq-tool trace: {label}: no retained trace "
                  f"{want!r} ({len(traces)} retained"
                  + (f"; most recent: {ids}" if ids else "")
                  + ") — it may have been evicted (raise TPQ_TRACE_RING) "
                    "or never retained (raise sampling: TPQ_TRACE_TAIL)\n")
        return 1
    tr = match[0]
    dur = tr.get("duration_s")
    origin = (f" [{tr['host']}:{tr['pid']}]"
              if tr.get("host") and tr.get("pid") else "")
    out.write(f"trace {tr.get('trace_id')}{origin}: "
              + (f"{dur * 1e3:.2f}ms" if dur is not None else "?")
              + (f", dropped {tr['dropped']} span(s)"
                 if tr.get("dropped") else "")
              + (f", flags [{', '.join(tr['flags'])}]"
                 if tr.get("flags") else "")
              + "\n")
    err = tr.get("error")
    if err:
        out.write(f"error: {err.get('type')}: {err.get('message')}\n")
    out.write("spans:\n")
    _write_span_tree(out, tr)
    from ..obs_fleet import stitch_traces

    stitched = stitch_traces(traces, str(tr.get("trace_id")))
    for ch in (stitched or {}).get("children") or ():
        cdur = ch.get("duration_s")
        out.write(f"  child [{ch.get('host', '?')}:{ch.get('pid', '?')}] "
                  f"trace {ch.get('trace_id')}: "
                  + (f"{cdur * 1e3:.2f}ms" if cdur is not None else "?")
                  + "\n")
        _write_span_tree(out, ch, indent="    ")
    return 0


def cmd_trace(args, out=sys.stdout) -> int:
    """Render a Chrome trace-event JSON (a ``TPQ_TRACE`` run) as the
    per-stage latency / overlap / stall / route-prediction report — the
    trace made useful without a browser (obs.trace_summary does the math;
    Perfetto / chrome://tracing load the same file for the timeline).

    Also accepts ledger refs (``latest``, ``#N``): the record's env names
    the run's ``TPQ_TRACE`` base, and the per-config artifact
    ``<base>.<config>.json`` (``--config``, default the record's first
    config) is summarized in its place.

    ``--request <trace_id>`` switches modes: the argument is a tail-sampler
    dump and the named retained REQUEST trace prints as a span tree."""
    from ..obs import trace_summary

    if getattr(args, "request", None):
        return cmd_trace_request(args, out)
    if not args.file:
        out.write("pq-tool trace: FILE is required (it is optional only "
                  "with --request --spool)\n")
        return 2
    doc = _load_doc(args.file)
    label = args.file
    if isinstance(doc, dict) and "traceEvents" not in doc and "configs" in doc:
        # a bench/ledger record: resolve its per-config trace artifact
        base = (doc.get("env") or {}).get("TPQ_TRACE")
        if not base:
            out.write(f"pq-tool trace: {args.file}: run was recorded "
                      f"without TPQ_TRACE — no trace artifact to "
                      f"summarize (re-run with TPQ_TRACE=<base>)\n")
            return 1
        cfgs = doc.get("configs") or {}
        cfg = getattr(args, "config", None) or next(iter(cfgs), None)
        if not cfg:
            out.write(f"pq-tool trace: {args.file}: record has no configs\n")
            return 1
        label = f"{base}.{cfg}.json"
        try:
            with open(label) as f:
                doc = json.load(f)
        except FileNotFoundError:
            out.write(f"pq-tool trace: {args.file}: trace artifact "
                      f"{label} not found (moved or cleaned?)\n")
            return 1
        except json.JSONDecodeError as e:
            raise ValueError(f"{label}: not JSON ({e})") from None
    args = argparse.Namespace(**{**vars(args), "file": label})
    s = trace_summary(doc)
    if not s["stages"]:
        # zero spans: the run recorded nothing to summarize — one-line
        # diagnosis, not a table of zeros (or a traceback downstream)
        out.write(f"pq-tool trace: {args.file}: no spans recorded — was the "
                  f"tracer enabled for the run (TPQ_TRACE / trace=)?\n")
        return 1
    out.write(f"trace: {args.file}\n")
    out.write(f"events: {s['events']}  threads: {s['threads']}  "
              f"wall: {s['wall_seconds']:.3f}s\n")
    if s["stages"]:
        name_w = max(max(len(n) for n in s["stages"]), 5)
        out.write(f"{'stage':<{name_w}} {'count':>7} {'total_s':>9} "
                  f"{'p50_ms':>9} {'p95_ms':>9} {'max_ms':>9}\n")
        for name, st in s["stages"].items():
            out.write(
                f"{name:<{name_w}} {st['count']:>7} "
                f"{st['total_seconds']:>9.3f} "
                f"{st['p50_seconds'] * 1e3:>9.3f} "
                f"{st['p95_seconds'] * 1e3:>9.3f} "
                f"{st['max_seconds'] * 1e3:>9.3f}\n")
    out.write(f"overlap efficiency: {s['busy_seconds']:.3f}s busy / "
              f"{s['wall_seconds']:.3f}s wall = "
              f"{s['overlap_efficiency']:.3f}\n")
    out.write(f"stall: {s['stall_seconds']:.3f}s "
              f"({100 * s['stall_share']:.1f}% of wall)\n")
    if s["routes"]:
        out.write(f"ship routes (measured link "
                  f"{s['link_bytes_per_sec'] / 1e6:.1f} MB/s):\n")
        name_w = max(max(len(n) for n in s["routes"]), 5)
        out.write(f"  {'route':<{name_w}} {'streams':>7} {'shipped_mb':>11} "
                  f"{'predicted_s':>12} {'measured_s':>11} {'error':>7}\n")
        for name, r in s["routes"].items():
            meas = r.get("measured_seconds")
            err = r.get("error_ratio")
            out.write(
                f"  {name:<{name_w}} {r['streams']:>7} "
                f"{r['shipped_bytes'] / 1e6:>11.2f} "
                f"{r['predicted_seconds']:>12.4f} "
                + (f"{meas:>11.4f} " if meas is not None else f"{'-':>11} ")
                + (f"{err:>7.2f}" if err is not None else f"{'-':>7}")
                + "\n")
    reg = s.get("registry")
    if not reg:
        # the span tables above still printed; the nonzero exit tells
        # scripts the artifact is registry-less (an atexit-written process
        # trace, or a hand-built one) so `doctor`/`bench diff` can't use it
        out.write(f"pq-tool trace: {args.file}: no embedded registry — "
                  f"write the trace via a reader-owned trace= path (or "
                  f"Tracer.write(registry=...))\n")
        return 1
    pipe = reg.get("pipeline") or {}
    out.write(
        f"embedded registry: obs_version={reg.get('obs_version')} "
        f"chunks={pipe.get('chunks')} "
        f"busy={pipe.get('busy_seconds')}s "
        f"stall={pipe.get('stall_seconds')}s\n")
    return 0


def _load_registry_tree(path, config=None):
    """Resolve a doctor argument to one registry tree.

    Accepts a trace-event document (uses the embedded registry), a bare
    registry tree (``obs_version`` at top level), a bench artifact
    (``configs``: picks ``--config`` or the first config embedding an
    ``obs`` tree), or a ledger record / ledger ref (``latest``, ``#N``,
    ``ledger.jsonl[#N]``).  Returns ``(tree, None)`` or ``(None,
    diagnosis)``.
    """
    doc = _load_doc(path)
    if not isinstance(doc, dict):
        return None, "top level is not an object"
    if "traceEvents" in doc:
        tree = (doc.get("otherData") or {}).get("registry")
        if not tree:
            return None, ("trace has no embedded registry — write it via a "
                          "reader-owned trace= path")
        return tree, None
    if "obs_version" in doc:
        return doc, None
    cfgs = doc.get("configs")
    if isinstance(cfgs, dict):
        names = ([config] if config else
                 [n for n, c in cfgs.items()
                  if isinstance(c, dict) and isinstance(c.get("obs"), dict)])
        for n in names:
            c = cfgs.get(n)
            if isinstance(c, dict) and isinstance(c.get("obs"), dict):
                return c["obs"], None
        return None, (f"config {config!r} has no embedded obs registry"
                      if config else "no config embeds an obs registry")
    return None, "not a trace, registry tree, or bench artifact"


def cmd_doctor(args, out=sys.stdout) -> int:
    """Rule-based bottleneck attribution: which lane bounds this run (link /
    host decompress / stall / device resolve), how sure, and — when the
    measured routes disagree with the ship planner's cost model — the
    recalibrated ``TPQ_LINK_MBPS`` to re-run with.  obs.doctor_registry
    does the math; this renders the verdict."""
    from ..obs import doctor_registry

    tree, why = _load_registry_tree(args.file, getattr(args, "config", None))
    if tree is None:
        out.write(f"pq-tool doctor: {args.file}: {why}\n")
        return 1
    rep = doctor_registry(tree)
    if rep is None:
        out.write(f"pq-tool doctor: {args.file}: registry has no lane "
                  f"seconds to attribute (nothing was decoded?)\n")
        return 1
    out.write(f"doctor: {args.file}\n")
    lanes = rep.get("lanes")
    if lanes:
        out.write("lanes: " + "  ".join(
            f"{k}={lanes[k]:.3f}s"
            for k in sorted(lanes, key=lambda k: -lanes[k])) + "\n")
        out.write(f"verdict: {rep['verdict']} "
                  f"({100 * rep['dominant_share']:.0f}% of lane seconds)\n")
    rm = rep.get("route_model")
    if rm:
        err = rm.get("error_ratio")
        if err is None:
            out.write("route model: chosen routes never measured "
                      "(measured_s null — no staging seconds recorded)\n")
        else:
            side = ("optimistic" if err > 1 else "pessimistic")
            out.write(
                f"route model: predicted {rm['predicted_seconds']:.4f}s, "
                f"measured {rm['measured_seconds']:.4f}s "
                f"(error {err:.2f}x {side}; planner assumed "
                f"{rm['planner_link_mbps'] or '?'} MB/s, measured "
                f"{rm['measured_link_mbps'] or '?'} MB/s)\n")
    recal = rep.get("recalibrate_link_mbps")
    if recal is not None:
        out.write(f"recalibrate: re-run with TPQ_LINK_MBPS={recal:g} "
                  f"(the measured staging rate) to align the planner\n")
    dv = rep.get("device")
    if dv:
        err = dv.get("error_ratio")
        out.write(
            f"device: dominant route {dv['dominant_route']!r}"
            + (f" (kernel {dv['dominant_kernel']})"
               if dv.get("dominant_kernel") else "")
            + f", measured {dv['measured_seconds']:.4f}s"
            + (f", predicted {dv['predicted_seconds']:.4f}s "
               f"(error {err:.2f}x)" if err is not None
               else ", prediction n/a")
            + "\n")
        drecal = rep.get("recalibrate_device_mbps")
        if drecal is not None:
            out.write(f"recalibrate: re-run with TPQ_DEVICE_MBPS={drecal:g} "
                      f"(the measured device-resolve rate) to align the "
                      f"planner's device lane\n")
        fw = rep.get("fusion_win")
        if fw:
            out.write(
                f"fusion-win: {fw['route']!r} measured "
                f"{fw['measured_seconds']:.6f}s vs unfused chain prediction "
                f"{fw['unfused_predicted_seconds']:.6f}s "
                f"({fw['speedup']:.2f}x) — the fused megakernel beats the "
                f"staged chain; keep TPQ_FUSE on for this workload\n")
    else:
        # records predating the device registry section (or runs with
        # TPQ_DEVICE_TIMING=0): explicitly n/a, never a KeyError
        out.write("device: n/a (no device section — record predates device "
                  "timing, or TPQ_DEVICE_TIMING=0)\n")
    ct = rep.get("cache")
    if ct:
        top = ct.get("top_evict_file")
        knob = ct.get("budget_knob") or "TPQ_RESULT_CACHE_MB"
        out.write(f"cache-thrash: {ct['tier']} tier churning "
                  f"({ct['evictions']} evictions at "
                  f"{100 * ct['hit_rate']:.0f}% hit rate"
                  + (f"; top evictor {top} x{ct['top_evict_count']}"
                     if top else "")
                  + f") — the working set exceeds "
                    f"{ct['capacity_bytes']} bytes; raise {knob} or shard "
                    f"the hot set\n")
    co = rep.get("circuit_open")
    if co:
        out.write(f"circuit-open: {', '.join(co['files']) or '?'} "
                  f"({co['opened']} trip(s), {co['fast_fails']} fast-fail(s)"
                  f") — the named file keeps failing; inspect or replace "
                  f"it, healthy traffic is unaffected\n")
    ov = rep.get("overload")
    if ov:
        sheds = ov.get("sheds") or {}
        hint = ov.get("retry_after_hint_s")
        out.write(f"overload: {ov['rejected']} rejected, "
                  f"{sheds.get('low', 0)}+{sheds.get('normal', 0)} shed"
                  + (f"; offender '{ov['offending_tenant']}' "
                     f"(demand {ov['offender_demand']})"
                     if ov.get("offending_tenant") else "")
                  + (f"; victims {', '.join(ov['victims'])}"
                     if ov.get("victims") else "")
                  + (f"; retry-after {hint:g}s" if hint else "")
                  + f" — {ov['advice']}\n")
    sb = rep.get("slo_burn")
    if sb:
        out.write(f"slo-burn: tenant {sb['tenant']!r} p99 "
                  f"{sb['p99_ms']:.2f}ms vs slo {sb['slo_p99_ms']:g}ms "
                  f"({sb['burn_ratio']:.1f}x), offending bucket le "
                  f"{sb['bucket_le_s'] * 1e3:g}ms"
                  + (f", exemplar trace {sb['exemplar_trace']}"
                     if sb.get("exemplar_trace") else "")
                  + (f" ({sb['exemplar_value_s'] * 1e3:.2f}ms)"
                     if sb.get("exemplar_value_s") is not None else "")
                  + f" — {sb['advice']}\n")
        burning = sb.get("burning_tenants") or []
        if len(burning) > 1:
            out.write(f"slo-burn: {len(burning)} tenants over target "
                      f"({', '.join(burning)}); worst burn shown\n")
    hg = rep.get("hedge")
    if hg:
        out.write(f"hedge-ineffective: {hg['won']}/{hg['issued']} hedges "
                  f"won ({100 * hg['win_rate']:.0f}%) for "
                  f"{hg['wasted_bytes']} wasted bytes — the hedge delay "
                  f"sits below the real p90; raise TPQ_IO_HEDGE_MS or let "
                  f"auto re-learn\n")
    ioc = rep.get("io_concurrency")
    if ioc:
        out.write(f"io-concurrency-bound: in-flight peak "
                  f"{ioc['inflight_peak']}/{ioc['inflight_cap']} cap, "
                  f"slot queue-wait {ioc['queue_wait_seconds']:.3f}s vs "
                  f"fetch {ioc['fetch_seconds']:.3f}s — {ioc['advice']}\n")
    wrt = rep.get("write")
    if wrt:
        wl = wrt["lanes"]
        out.write("write: " + "  ".join(
            f"{k}={wl[k]:.3f}s"
            for k in sorted(wl, key=lambda k: -wl[k])) + "\n")
        out.write(f"write verdict: {wrt['verdict']} "
                  f"({100 * wrt['dominant_share']:.0f}% of write lane "
                  f"seconds; {wrt['rows_per_sec']:.0f} rows/s, "
                  f"{wrt['bytes_per_sec'] / 1e6:.1f} MB/s)\n")
    return 0


def cmd_metrics(args, out=sys.stdout) -> int:
    """Live metrics plumbing over registry snapshots (the JSON trees
    ``TPQ_METRICS_DUMP`` writes, or any input ``doctor`` accepts):

    - one snapshot: render the OpenMetrics text exposition (counters,
      gauges, ``_bucket``/``_sum``/``_count`` histogram families with
      trace-id exemplars) — what a scraper would ingest;
    - two snapshots: the numeric counter deltas A → B;
    - ``--watch``: poll the snapshot file and print deltas as they land
      (``--count`` bounds the polls for scripting);
    - ``--spool DIR``: aggregate a fleet spool instead of reading a file
      and render the fleet exposition, every per-process series labelled
      ``host``/``pid``/``role`` — one scrape, the whole fleet."""
    from ..obs import diff_registry_trees, render_openmetrics

    def load(spec):
        tree, why = _load_registry_tree(spec, getattr(args, "config", None))
        if tree is None:
            raise ValueError(f"{spec}: {why}")
        return tree

    spool = getattr(args, "spool", None)
    if spool:
        from ..obs_fleet import FleetAggregator, render_fleet_openmetrics

        snap = FleetAggregator(spool_dir=spool).scan()
        if not snap["processes"]:
            out.write(f"pq-tool metrics: {spool}: no spool members\n")
            return 1
        out.write(render_fleet_openmetrics(snap))
        return 0
    if not getattr(args, "file", None):
        out.write("pq-tool metrics: FILE is required (it is optional only "
                  "with --spool DIR)\n")
        return 2

    def write_diff(old, new, indent="  "):
        d = diff_registry_trees(old, new)
        if not d:
            out.write(f"{indent}(no numeric changes)\n")
            return
        w = max(len(p) for p in d)
        for path in sorted(d):
            o, n, delta = d[path]
            out.write(f"{indent}{path:<{w}}  {o:g} -> {n:g}  ({delta:+g})\n")

    if getattr(args, "watch", False):
        import time as _time

        interval = max(float(args.interval), 0.01)
        out.write(f"metrics: watching {args.file} "
                  f"(interval {interval:g}s"
                  + (f", {args.count} poll(s)" if args.count else "")
                  + ")\n")
        prev = None
        polls = 0
        while args.count is None or polls < args.count:
            if polls:
                _time.sleep(interval)
            polls += 1
            try:
                tree = load(args.file)
            except (OSError, ValueError):
                continue  # dumper mid-replace or not written yet: next poll
            if prev is not None and tree != prev:
                out.write(f"poll {polls}:\n")
                write_diff(prev, tree)
            prev = tree
        return 0
    try:
        if getattr(args, "file2", None):
            old, new = load(args.file), load(args.file2)
            out.write(f"metrics diff: {args.file} -> {args.file2}\n")
            write_diff(old, new)
            return 0
        out.write(render_openmetrics(load(args.file)))
        return 0
    except (OSError, ValueError) as e:
        out.write(f"pq-tool metrics: {e}\n")
        return 1


def cmd_autopsy(args, out=sys.stdout) -> int:
    """Post-mortem of a flight-recorder dump (a hang/crash snapshot written
    by the watchdog, ``TPQ_DUMP_SIGNAL``, a worker crash, or the explicit
    API): which lane stopped advancing first, which threads are blocked on
    which lock/queue, the longest budget-wait age, each thread's last
    recorded event, and a one-line probable cause — the ``doctor`` verdict
    style, for runs that never finished (obs.autopsy_dump does the math)."""
    from ..obs import autopsy_dump

    doc = _load_doc(args.file)
    try:
        rep = autopsy_dump(doc)
    except ValueError as e:
        out.write(f"pq-tool autopsy: {args.file}: {e}\n")
        return 1
    out.write(f"autopsy: {args.file} (reason: {rep['reason']}, "
              f"pid {rep['pid']})\n")
    ages = rep["ages"]
    if rep["stalled_first"] is not None:
        out.write(f"stalled first: {rep['stalled_first']} "
                  f"(no advance for {ages.get(rep['stalled_first'], '?')}s "
                  f"of a {rep['hang_s']}s deadline)\n")
    if ages:
        worst = sorted(ages.items(), key=lambda kv: -kv[1])[:6]
        out.write("lane ages: " + "  ".join(
            f"{k}={v:g}s" for k, v in worst) + "\n")
    threads = rep["threads"]
    if threads:
        name_w = max(max(len(t["name"]) for t in threads.values()), 6)
        out.write("threads:\n")
        for _tid, t in sorted(threads.items(),
                              key=lambda kv: kv[1]["name"]):
            last = t["last_event"]
            tail = (f"  last: {last['name']} {last['age_s']:g}s ago"
                    if last else "")
            dead = "" if t["alive"] else "  [DEAD]"
            out.write(f"  {t['name']:<{name_w}}  {t['class']}{dead}{tail}\n")
    b = rep.get("budget")
    if b:
        out.write(f"budget: {b['waiters']} waiter(s), longest wait "
                  f"{b['longest_wait_s']:g}s\n")
    io = rep.get("io")
    if io:
        out.write(f"io: range at offset {io['offset']} ({io['size']} bytes) "
                  f"in flight for {io['age_s']:g}s\n")
    sv = rep.get("serve")
    if sv:
        stuck = sv.get("stuck_request")
        tail = (f"; stuck request #{stuck['id']} over {stuck['path']!r} "
                f"({stuck['age_s']:g}s in flight)" if stuck else "")
        out.write(f"serve: {sv.get('in_flight', 0)} in flight, queue depth "
                  f"{sv.get('queue_depth', 0)}{tail}\n")
        for c in sv.get("circuit_open") or []:
            out.write(f"circuit: OPEN for {c['file']!r} (next probe in "
                      f"{c.get('retry_after_s', '?')}s)\n")
    de = rep.get("data_errors")
    if de:
        first = de.get("first") or {}
        where = (f" — first bad: file {first.get('file')!r} column "
                 f"{first.get('column')!r} row_group "
                 f"{first.get('row_group')} page {first.get('page')}"
                 if first else "")
        out.write(f"data: {de['errors']} quarantined error(s){where}\n")
    err = rep.get("error")
    if err:
        out.write(f"error: {err.get('type')}: {err.get('message')}\n")
    out.write(f"verdict: {rep['verdict']}\n")
    out.write(f"probable cause: {rep['probable_cause']}\n")
    return 0


def cmd_serve_stats(args, out=sys.stdout) -> int:
    """Summarize a scan service run's ``serve`` registry section: request/
    rejection counters, queue depth, plan-cache hit rates, and the
    per-request latency SLO table (p50/p95 from the ``serve.*``
    histograms).  Accepts the same inputs as ``doctor`` (registry tree,
    trace artifact, bench artifact, ledger ref) plus flight dumps."""
    from ..obs import LatencyHistogram

    tree, why = _load_registry_tree(args.file, getattr(args, "config", None))
    if tree is None:
        doc = _load_doc(args.file)
        if isinstance(doc, dict) and isinstance(doc.get("registry"), dict):
            tree, why = doc["registry"], None  # a flight dump's snapshot
    if tree is None:
        out.write(f"pq-tool serve-stats: {args.file}: {why}\n")
        return 1
    sv = tree.get("serve")
    if not isinstance(sv, dict):
        out.write(f"pq-tool serve-stats: {args.file}: registry has no "
                  f"`serve` section (run was not served through a "
                  f"ScanService)\n")
        return 1
    out.write(f"serve-stats: {args.file}\n")
    out.write(f"requests: {sv.get('submitted', 0)} submitted, "
              f"{sv.get('completed', 0)} completed, "
              f"{sv.get('rejected', 0)} rejected (overload), "
              f"{sv.get('failed', 0)} failed\n")
    out.write(f"queue: depth peak {sv.get('queue_depth_peak', 0)}, "
              f"total wait {float(sv.get('queue_wait_seconds', 0)):.4f}s, "
              f"total exec {float(sv.get('exec_seconds', 0)):.4f}s\n")
    sheds = sv.get("sheds") or {}
    dl, cn = sv.get("deadline_exceeded", 0), sv.get("cancelled", 0)
    if any(sheds.values()) or dl or cn:
        out.write(f"lifecycle: {dl} deadline-exceeded, {cn} cancelled, "
                  f"shed {sheds.get('low', 0)} low / "
                  f"{sheds.get('normal', 0)} normal priority (brownout)\n")
    if sv.get("retry_after_hint_s"):
        out.write(f"overload: last retry-after hint "
                  f"{float(sv['retry_after_hint_s']):.3f}s (callers should "
                  f"back off at least this long)\n")
    if sv.get("stream_sessions"):
        out.write(f"streaming: {sv.get('stream_sessions', 0)} session(s), "
                  f"{sv.get('stream_batches', 0)} batch(es) emitted\n")
    circ = sv.get("circuit") or {}
    if any(v for k, v in circ.items() if k != "open_files"):
        files = circ.get("open_files") or []
        out.write(f"circuit: {circ.get('open_now', 0)} open now"
                  + (f" ({', '.join(str(f) for f in files)})" if files
                     else "")
                  + f", {circ.get('opened', 0)} opened / "
                    f"{circ.get('reopened', 0)} reopened / "
                    f"{circ.get('closed', 0)} closed, "
                    f"{circ.get('fast_fails', 0)} fast-fails\n")
    io_sec = tree.get("io") or {}
    if io_sec.get("hedges_issued"):
        issued = int(io_sec.get("hedges_issued", 0))
        won = int(io_sec.get("hedges_won", 0))
        out.write(f"hedges: {issued} issued, {won} won "
                  f"({100 * won / issued:.0f}%), "
                  f"{io_sec.get('hedges_wasted_bytes', 0)} wasted bytes, "
                  f"{io_sec.get('hedge_mismatches', 0)} mismatches\n")
    cache = sv.get("cache") or {}
    if cache:
        def rate(kind):
            h = int(cache.get(f"{kind}_hits", 0))
            m = int(cache.get(f"{kind}_misses", 0))
            return f"{kind} {h}/{h + m}" + (
                f" ({100 * h / (h + m):.0f}%)" if h + m else "")

        out.write("cache hits: " + "  ".join(
            rate(k) for k in ("footer", "plan", "dict"))
            + f"  [{cache.get('held_bytes', 0)} B held, "
              f"{cache.get('evictions', 0)} evicted, "
              f"{cache.get('invalidations', 0)} invalidated]\n")
    rcache = tree.get("cache") or {}
    for tier in ("host", "device"):
        tc = rcache.get(tier)
        if not isinstance(tc, dict):
            continue
        h, m = int(tc.get("hits", 0)), int(tc.get("misses", 0))
        if not (h + m or tc.get("entries")):
            continue
        out.write(
            f"result cache [{tier}]: {h}/{h + m} hits"
            + (f" ({100 * h / (h + m):.0f}%)" if h + m else "")
            + f", {tc.get('held_bytes', 0)}/{tc.get('capacity_bytes', 0)} B"
              f" held, {tc.get('entries', 0)} entries, "
              f"{tc.get('evictions', 0)} evicted, "
              f"{tc.get('invalidations', 0)} invalidated\n")
    if rcache.get("single_flight_waits"):
        out.write(f"result cache: {rcache['single_flight_waits']} "
                  f"single-flight wait(s) (concurrent first-touches "
                  f"served by one decode)\n")
    hists = tree.get("histograms") or {}
    tenants = {n: t for n, t in (sv.get("tenants") or {}).items()
               if isinstance(t, dict)}
    # one-tenant registries are the untenanted default — the table only
    # earns its lines when QoS is actually partitioning the service
    if len(tenants) > 1 or any(t.get("rejected") or t.get("sheds", {}).get(
            "low") or t.get("sheds", {}).get("normal")
            for t in tenants.values()):
        out.write("tenants:\n")
        out.write(f"  {'name':<16}{'weight':>7}{'submit':>8}{'done':>7}"
                  f"{'reject':>8}{'shed':>6}{'cacheB':>10}{'p99':>12}\n")
        for name in sorted(tenants):
            t = tenants[name]
            tsheds = t.get("sheds") or {}
            shed = int(tsheds.get("low", 0)) + int(tsheds.get("normal", 0))
            hd = hists.get(f"serve.tenant.{name}")
            if isinstance(hd, dict):
                q99 = LatencyHistogram.from_dict(hd).quantile(0.99) * 1e3
                p99 = f"{q99:>10.2f}ms"
            else:
                p99 = f"{'-':>12}"
            slo_ms = t.get("slo_p99_ms")
            ddl = t.get("deadline_s")
            out.write(f"  {name:<16}{t.get('weight', 1):>7}"
                      f"{t.get('submitted', 0):>8}{t.get('completed', 0):>7}"
                      f"{t.get('rejected', 0):>8}{shed:>6}"
                      f"{t.get('cache_held_bytes', 0):>10}{p99}"
                      + (f"  (slo {float(slo_ms):g}ms)" if slo_ms else "")
                      + (f"  (deadline {float(ddl):g}s)" if ddl else "")
                      + "\n")
    slo = [(name.split(".", 1)[1], LatencyHistogram.from_dict(hd))
           for name, hd in sorted(hists.items())
           if name.startswith("serve.")]
    if slo:
        out.write("latency (per request):\n")
        out.write(f"  {'lane':<12}{'count':>7}{'p50':>12}{'p95':>12}"
                  f"{'p99':>12}{'max':>12}\n")
        for lane, h in slo:
            out.write(f"  {lane:<12}{h.count:>7}"
                      f"{h.quantile(0.5) * 1e3:>10.2f}ms"
                      f"{h.quantile(0.95) * 1e3:>10.2f}ms"
                      f"{h.quantile(0.99) * 1e3:>10.2f}ms"
                      f"{h.max_seconds * 1e3:>10.2f}ms\n")
    # exemplar rows: the percentile-to-trace link — each populated bucket's
    # most recent RETAINED trace id (pq_tool trace --request fetches it)
    ex_rows = []
    for name, hd in sorted(hists.items()):
        if not name.startswith("serve.") or not isinstance(hd, dict):
            continue
        for b, ex in sorted((hd.get("exemplars") or {}).items(),
                            key=lambda kv: int(kv[0])):
            ex_rows.append((name.split(".", 1)[1], int(b), ex))
    if ex_rows:
        out.write("exemplars (bucket -> retained trace):\n")
        for lane, b, ex in ex_rows:
            le = LatencyHistogram.bucket_upper_seconds(b) * 1e3
            out.write(f"  {lane:<16} le={le:g}ms  {ex[0]}  "
                      f"({float(ex[1]) * 1e3:.3f}ms)\n")
    trc = sv.get("trace") or {}
    if trc.get("offered"):
        out.write(f"tracing: {trc.get('offered', 0)} offered, "
                  f"{trc.get('retained', 0)} retained, "
                  f"{trc.get('evicted', 0)} evicted, "
                  f"{trc.get('retained_bytes', 0)}/"
                  f"{trc.get('ring_capacity_bytes', 0)} B ring\n")
    return 0


def _render_fleet_top(snap, out) -> int:
    """One ``pq_tool top`` frame from a :meth:`FleetAggregator.scan`
    snapshot: per-process lanes/queue/cache table, the merged tenant
    table, then active fleet verdicts."""
    from ..obs import LatencyHistogram
    from ..obs_fleet import doctor_fleet, process_lanes

    procs = snap.get("processes") or {}
    if not procs:
        out.write(f"pq-tool top: {snap.get('spool_dir')}: no spool members "
                  f"yet — processes publish once TPQ_OBS_SPOOL points here\n")
        return 1
    stale_n = sum(1 for p in procs.values() if p.get("stale"))
    out.write(f"fleet top — {snap.get('spool_dir')} — {len(procs)} "
              f"process(es), {stale_n} stale, "
              f"{snap.get('rejected', 0)} rejected file(s)\n")
    name_w = max(max(len(n) for n in procs), 7) + 2
    out.write(f"{'process':<{name_w}}{'role':<8}{'hb':>8}{'queue':>7}"
              f"{'cache%':>8}{'lane_s':>9}  dominant lane\n")
    for name in sorted(procs):
        p = procs[name]
        tree = p.get("registry") or {}
        lanes = {k: v for k, v in process_lanes(tree).items() if v > 0}
        total = sum(lanes.values())
        sv = tree.get("serve") or {}
        cache = sv.get("cache") or {}
        hits = sum(int(cache.get(f"{k}_hits", 0))
                   for k in ("footer", "plan", "dict"))
        miss = sum(int(cache.get(f"{k}_misses", 0))
                   for k in ("footer", "plan", "dict"))
        rate = f"{100 * hits / (hits + miss):.0f}" if hits + miss else "-"
        age = p.get("heartbeat_age_s")
        hb = ("STALE" if p.get("stale")
              else f"{age:.1f}s" if age is not None else "?")
        dom = max(lanes, key=lanes.get) if lanes else None
        out.write(f"{name:<{name_w}}{p.get('role', '?'):<8}{hb:>8}"
                  f"{sv.get('queue_depth', 0):>7}{rate:>8}{total:>9.3f}  "
                  + (f"{dom} ({lanes[dom]:.3f}s)" if dom else "-") + "\n")
    merged = snap.get("registry") or {}
    msv = merged.get("serve") or {}
    tenants = {n: t for n, t in (msv.get("tenants") or {}).items()
               if isinstance(t, dict)}
    if tenants:
        hists = merged.get("histograms") or {}
        out.write("tenants (fleet-merged):\n")
        out.write(f"  {'name':<16}{'weight':>7}{'submit':>8}{'done':>7}"
                  f"{'reject':>8}{'p99':>12}\n")
        for name in sorted(tenants):
            t = tenants[name]
            hd = hists.get(f"serve.tenant.{name}")
            if isinstance(hd, dict):
                q99 = LatencyHistogram.from_dict(hd).quantile(0.99) * 1e3
                p99 = f"{q99:>10.2f}ms"
            else:
                p99 = f"{'-':>12}"
            out.write(f"  {name:<16}{t.get('weight', 1):>7}"
                      f"{t.get('submitted', 0):>8}{t.get('completed', 0):>7}"
                      f"{t.get('rejected', 0):>8}{p99}\n")
    rep = doctor_fleet(snap)
    verdicts = (rep or {}).get("verdicts") or []
    if not verdicts:
        out.write("verdicts: none\n")
        return 0
    out.write("verdicts:\n")
    for v in verdicts:
        kind = v.get("verdict")
        if kind == "straggler":
            out.write(f"  straggler: {v.get('process')} ({v.get('role')}) — "
                      f"dominant lane {v.get('dominant_lane')}, "
                      f"{float(v.get('deviation', 0)):.2f}x the fleet "
                      f"median lane-seconds\n")
        elif kind == "dead-process":
            out.write(f"  dead-process: {v.get('process')} "
                      f"({v.get('role')}) — heartbeat "
                      f"{float(v.get('heartbeat_age_s', 0)):.1f}s old "
                      f"(stale after {float(v.get('stale_after_s', 0)):g}s)\n")
        elif kind == "slo-burn":
            out.write(f"  slo-burn: tenant {v.get('tenant')} p99 "
                      f"{float(v.get('p99_ms', 0)):.1f}ms over its "
                      f"{float(v.get('slo_p99_ms', 0)):g}ms budget "
                      f"(x{float(v.get('burn_ratio', 0)):.2f}"
                      + (f"; exemplar {v['exemplar_trace']} retained by "
                         f"{v.get('exemplar_process') or '?'}"
                         if v.get("exemplar_trace") else "")
                      + ")\n")
        else:
            out.write(f"  {kind}: {v.get('advice', v)}\n")
    return 0


def cmd_top(args, out=sys.stdout) -> int:
    """``pq_tool top <spool_dir>``: the live fleet dashboard — every
    process publishing into a ``TPQ_OBS_SPOOL`` directory on one screen
    (throughput lanes, queue depths, cache hit rates, merged tenant
    table, active ``straggler``/``dead-process``/``slo-burn`` verdicts),
    refreshed in place with plain ANSI.  ``--once`` renders a single
    frame and exits (tests/CI); ``--count`` bounds the refresh loop."""
    import time as _time

    from ..obs_fleet import FleetAggregator

    agg = FleetAggregator(spool_dir=args.spool,
                          stale_s=getattr(args, "stale", None))
    if args.once:
        return _render_fleet_top(agg.scan(), out)
    polls = 0
    rc = 0
    try:
        while args.count is None or polls < args.count:
            if polls:
                _time.sleep(max(float(args.interval), 0.05))
            polls += 1
            out.write("\x1b[2J\x1b[H")  # clear + home — the whole protocol
            rc = _render_fleet_top(agg.scan(), out)
            if hasattr(out, "flush"):
                out.flush()
    except KeyboardInterrupt:
        pass
    return rc


def cmd_quarantine(args, out=sys.stdout) -> int:
    """Summarize a run's quarantine ledger (the JSONL ``TPQ_QUARANTINE_LOG``
    wrote, one record per contained data error): totals, per-file /
    per-column / per-error-class breakdowns, and the first bad
    (file, column, page) — the fleet-scale view of a degraded run."""
    from ..quarantine import summarize_quarantine_log

    records = []
    try:
        with open(args.file) as f:
            for ln, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError as e:
                    out.write(f"pq-tool quarantine: {args.file}:{ln}: "
                              f"bad record: {e}\n")
                    return 1
    except OSError as e:
        out.write(f"pq-tool quarantine: {args.file}: {e}\n")
        return 1
    rep = summarize_quarantine_log(records)
    if not rep["records"]:
        out.write(f"quarantine: {args.file}: no records — the run "
                  f"contained no data errors\n")
        return 0
    out.write(f"quarantine: {args.file}: {rep['records']} record(s) "
              f"across {rep['files']} file(s)\n")
    first = rep["first"] or {}
    out.write(f"first bad: file {first.get('file')!r} column "
              f"{first.get('column')!r} row_group {first.get('row_group')} "
              f"page {first.get('page')} ({first.get('error')}: "
              f"{str(first.get('message'))[:120]})\n")
    for title, key in (("by file", "by_file"), ("by column", "by_column"),
                       ("by error", "by_class")):
        rows = rep[key]
        if rows:
            out.write(f"{title}:\n")
            for name, n in list(rows.items())[:12]:
                out.write(f"  {n:>6}  {name}\n")
            if len(rows) > 12:
                out.write(f"  ... and {len(rows) - 12} more\n")
    return 0


def cmd_bench_diff(args, out=sys.stdout) -> int:
    """Noise-aware comparison of two recorded runs (ledger entries or bench
    artifacts); exits 1 when a metric regressed beyond its noise bound."""
    from .. import ledger

    a = ledger.load_side(args.a)
    b = ledger.load_side(args.b)
    d = ledger.diff(a, b, floor=args.floor)
    out.write(ledger.format_diff(d, args.a, args.b))
    return 1 if d["regressions"] else 0


def cmd_bench_history(args, out=sys.stdout) -> int:
    from .. import ledger

    records = ledger.read(args.ledger)
    start = 0
    if args.n and len(records) > args.n:
        out.write(f"(showing last {args.n} of {len(records)} runs)\n")
        start = len(records) - args.n
        records = records[start:]
    out.write(ledger.format_history(records, args.ledger, start=start))
    return 0


def parse_human_size(s: str) -> int:
    """'100MB', '1GiB', '4096' → bytes (helpers.go:10-40 parity)."""
    s = s.strip()
    units = {
        "": 1, "B": 1,
        "KB": 1000, "MB": 1000**2, "GB": 1000**3, "TB": 1000**4,
        "KIB": 1024, "MIB": 1024**2, "GIB": 1024**3, "TIB": 1024**4,
        "K": 1024, "M": 1024**2, "G": 1024**3,
    }
    num = s
    unit = ""
    for i, ch in enumerate(s):
        if not (ch.isdigit() or ch == "."):
            num, unit = s[:i], s[i:]
            break
    try:
        value = float(num)
        mult = units[unit.strip().upper()]
    except (ValueError, KeyError):
        raise ValueError(f"cannot parse size {s!r}") from None
    return int(value * mult)


def cmd_split(args, out=sys.stdout) -> int:
    max_size = parse_human_size(args.size)
    src = args.file
    with FileReader(src) as r:
        schema = r.schema
        part = 0
        writer = None
        written = 0

        def open_part():
            nonlocal writer, part, written
            path = args.output_pattern.format(part)
            writer = FileWriter(path, schema, codec=args_codec)
            out.write(f"writing {path}\n")
            part += 1
            written = 0
            return writer

        args_codec = getattr(CompressionCodec, args.codec.upper())
        writer = None
        for raw in r.iter_rows():
            if writer is not None and (
                writer.current_file_size + writer.current_row_group_size >= max_size
            ):
                writer.close()
                writer = None
            if writer is None:
                writer = open_part()  # opened lazily: no empty trailing parts
            writer.write_row(raw)
        if writer is None:
            writer = open_part()  # empty input still produces one valid file
        writer.close()
    return 0


def cmd_merge(args, out=sys.stdout) -> int:
    """Footer-merge N parquet files into one: row groups relocated with
    corrected offsets, data bytes copied untouched (CRCs ride along),
    atomic publish.  The write-side inverse of ``split``."""
    from ..write import WriteStats, merge_files

    st = WriteStats()
    meta = merge_files(args.output, args.inputs, stats=st)
    out.write(f"merged {len(args.inputs)} file(s) -> {args.output}: "
              f"{meta.num_rows} rows in {len(meta.row_groups)} row "
              f"group(s), {st.bytes_written} bytes\n")
    return 0


def cmd_compact(args, out=sys.stdout) -> int:
    """Compact a dataset (manifest dir or file list) into few large files:
    codec re-planned through the ship planner, CRCs always written,
    atomic manifest publish with a generation bump."""
    from ..write import compact

    rep = compact(
        args.dataset if len(args.dataset) > 1 else args.dataset[0],
        out=args.out,
        target_file_bytes=parse_human_size(args.target_size),
        workers=args.workers,
        remove_inputs=args.remove_inputs,
    )
    d = rep.as_dict()
    out.write(
        f"compacted {d['files_before']} file(s) ({d['bytes_before']} B, "
        f"{d['row_groups_before']} row groups) -> {d['files_after']} "
        f"file(s) ({d['bytes_after']} B, {d['row_groups_after']} row "
        f"groups), {d['rows']} rows\n")
    out.write(
        f"link bytes (ship-planner model): {d['link_bytes_before']} -> "
        f"{d['link_bytes_after']} (ratio {d['link_bytes_ratio']:.3f})\n")
    if rep.manifest_path:
        out.write(f"published: {rep.manifest_path} "
                  f"(generation {rep.generation})\n")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pq-tool", description="Inspect and manipulate parquet files"
    )
    sub = p.add_subparsers(dest="command", required=True)

    FILTER_HELP = ("row-group pruning predicate, e.g. \"a > 5 and b == 'x'\" "
                   "(skips groups whose stats cannot match)")
    c = sub.add_parser("cat", help="print all records as JSON lines")
    c.add_argument("-n", type=int, default=None, help="limit record count")
    c.add_argument("--filter", default=None, help=FILTER_HELP)
    c.add_argument("file")
    c.set_defaults(func=cmd_cat)

    h = sub.add_parser("head", help="print the first N records")
    h.add_argument("-n", type=int, default=5)
    h.add_argument("--filter", default=None, help=FILTER_HELP)
    h.add_argument("file")
    h.set_defaults(func=cmd_cat)

    m = sub.add_parser("meta", help="print file metadata")
    m.add_argument("file")
    m.set_defaults(func=cmd_meta)

    s = sub.add_parser("schema", help="print the schema definition")
    s.add_argument("file")
    s.set_defaults(func=cmd_schema)

    rc = sub.add_parser("rowcount", help="print the number of rows")
    rc.add_argument("--filter", default=None,
                    help=FILTER_HELP + "; prints surviving groups' row total")
    rc.add_argument("file")
    rc.set_defaults(func=cmd_rowcount)

    st = sub.add_parser("stats",
                        help="per-row-group column min/max/null statistics")
    st.add_argument("file")
    st.set_defaults(func=cmd_stats)

    tr = sub.add_parser(
        "trace", help="summarize a TPQ_TRACE run (Chrome trace-event JSON, "
                      "or a ledger ref: latest, #N, ledger.jsonl#N)")
    tr.add_argument("file", nargs="?", default=None,
                    help="trace/dump file (optional with --request --spool)")
    tr.add_argument("--config", default=None,
                    help="ledger-ref input: which config's trace artifact "
                         "to summarize (default: the record's first)")
    tr.add_argument("--request", default=None, metavar="TRACE_ID",
                    help="FILE is a tail-sampler dump (ScanService."
                         "trace_dump): print the named retained request's "
                         "span tree (prefix match accepted)")
    tr.add_argument("--spool", default=None, metavar="DIR",
                    help="--request: also pool the fleet spool's trace docs "
                         "(TPQ_OBS_SPOOL dir) and render child-process "
                         "traces stitched under the request")
    tr.set_defaults(func=cmd_trace)

    dr = sub.add_parser(
        "doctor",
        help="bottleneck attribution of a traced run (trace / registry / "
             "bench artifact / ledger ref: latest, #N, ledger.jsonl#N) "
             "+ TPQ_LINK_MBPS recalibration")
    dr.add_argument("file")
    dr.add_argument("--config", default=None,
                    help="bench-artifact input: which config's registry to "
                         "diagnose (default: first with an obs tree)")
    dr.set_defaults(func=cmd_doctor)

    au = sub.add_parser(
        "autopsy",
        help="post-mortem of a flight-recorder dump (hang/crash snapshot): "
             "stalled lane, blocked-thread classes, probable cause")
    au.add_argument("file")
    au.set_defaults(func=cmd_autopsy)

    qa = sub.add_parser(
        "quarantine",
        help="summarize a quarantine ledger (TPQ_QUARANTINE_LOG JSONL)")
    qa.add_argument("file", help="quarantine JSONL path")
    qa.set_defaults(func=cmd_quarantine)

    ss = sub.add_parser(
        "serve-stats",
        help="summarize a ScanService run's `serve` registry section: "
             "queue depth, cache hit rates, per-request p50/p95 SLO table")
    ss.add_argument("file", help="registry/trace/bench artifact, flight "
                                 "dump, or ledger ref")
    ss.add_argument("--config", default=None,
                    help="bench-artifact input: which config's registry to "
                         "summarize")
    ss.set_defaults(func=cmd_serve_stats)

    tp = sub.add_parser(
        "top",
        help="live fleet dashboard over a TPQ_OBS_SPOOL directory: "
             "per-process lanes/queues/caches, tenant table, verdicts")
    tp.add_argument("spool", help="fleet spool directory (TPQ_OBS_SPOOL)")
    tp.add_argument("--once", action="store_true",
                    help="render one frame and exit (tests/CI)")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="refresh interval seconds (default 2)")
    tp.add_argument("--count", type=int, default=None,
                    help="stop after N refreshes (default: forever)")
    tp.add_argument("--stale", type=float, default=None,
                    help="heartbeat staleness threshold seconds "
                         "(default TPQ_OBS_STALE_S / 10)")
    tp.set_defaults(func=cmd_top)

    mt = sub.add_parser(
        "metrics",
        help="OpenMetrics exposition of a registry snapshot "
             "(TPQ_METRICS_DUMP output); two snapshots diff; --watch polls")
    mt.add_argument("file", nargs="?", default=None,
                    help="registry snapshot JSON, trace/bench artifact, or "
                         "ledger ref (optional with --spool)")
    mt.add_argument("--spool", default=None, metavar="DIR",
                    help="render the aggregated fleet spool instead: every "
                         "per-process series labelled host/pid/role")
    mt.add_argument("file2", nargs="?", default=None,
                    help="second snapshot: print numeric counter deltas "
                         "FILE -> FILE2 instead of rendering")
    mt.add_argument("--config", default=None,
                    help="bench-artifact input: which config's registry to "
                         "render")
    mt.add_argument("--watch", action="store_true",
                    help="poll FILE, printing counter deltas as they land")
    mt.add_argument("--interval", type=float, default=2.0,
                    help="--watch poll interval seconds (default 2)")
    mt.add_argument("--count", type=int, default=None,
                    help="--watch: stop after N polls (default: forever)")
    mt.set_defaults(func=cmd_metrics)

    be = sub.add_parser(
        "bench", help="run-ledger tools: compare and list recorded runs")
    bsub = be.add_subparsers(dest="bench_command", required=True)
    bd = bsub.add_parser(
        "diff",
        help="per-metric deltas A -> B with noise bounds from rep variance; "
             "exit 1 on a regression beyond noise")
    bd.add_argument("a", help="bench artifact .json, ledger .jsonl (last "
                              "run), or ledger.jsonl#N")
    bd.add_argument("b", help="same forms as A")
    bd.add_argument("--floor", type=float, default=0.10,
                    help="minimum relative band when reps carry no noise "
                         "information (default 0.10)")
    bd.set_defaults(func=cmd_bench_diff)
    bh = bsub.add_parser("history", help="one line per recorded run")
    bh.add_argument("ledger", help="ledger.jsonl path")
    bh.add_argument("-n", type=int, default=20, help="show the last N runs")
    bh.set_defaults(func=cmd_bench_history)

    sp = sub.add_parser("split", help="split into files of at most SIZE bytes")
    sp.add_argument("--size", required=True, help="max part size, e.g. 100MB")
    sp.add_argument(
        "--output-pattern", default="part_{}.parquet",
        help="output filename pattern with {} for the part number",
    )
    sp.add_argument("--codec", default="snappy",
                    choices=["uncompressed", "snappy", "gzip", "zstd"])
    sp.add_argument("file")
    sp.set_defaults(func=cmd_split)

    mg = sub.add_parser(
        "merge", help="footer-merge N parquet files into one (no re-encode)")
    mg.add_argument("output")
    mg.add_argument("inputs", nargs="+")
    mg.set_defaults(func=cmd_merge)

    cp = sub.add_parser(
        "compact",
        help="compact a dataset into few large files (manifest publish, "
             "ship-planner codec replanning, CRCs always on)")
    cp.add_argument("dataset", nargs="+",
                    help="manifest dir/file, or a list of parquet files")
    cp.add_argument("--out", default=None,
                    help="output directory (default: the dataset's own)")
    cp.add_argument("--target-size", default="128MB",
                    help="target output file size, e.g. 512MB")
    cp.add_argument("--workers", type=int, default=None,
                    help="encode workers (default TPQ_WRITE_WORKERS)")
    cp.add_argument("--remove-inputs", action="store_true",
                    help="unlink superseded members after the manifest flip")
    cp.set_defaults(func=cmd_compact)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.func(args)
    except (ParquetError, ValueError, OSError) as e:
        print(f"pq-tool: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
