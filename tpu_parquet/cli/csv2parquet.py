"""csv2parquet: convert CSV files to parquet with type hints.

Equivalent of the reference's cmd/csv2parquet (main.go:24-435): derives a schema
from the CSV header, with ``--type-hints col=type,...`` overrides (deriveSchema
:154, createColumn :188, per-type handlers :367-434).

Usage:
    python -m tpu_parquet.cli.csv2parquet --input data.csv --output data.parquet \
        [--type-hints "id=int64,price=double,ok=boolean"] [--codec snappy] \
        [--delimiter ,] [--wrap optional]

Supported hint types: boolean, int32, int64, float, double, string (default),
byte_array, timestamp (RFC3339/ISO), date (YYYY-MM-DD), json.
"""

from __future__ import annotations

import argparse
import csv
import datetime
import json
import sys

from ..footer import ParquetError
from ..format import (
    CompressionCodec,
    ConvertedType,
    FieldRepetitionType as FRT,
    LogicalType,
    StringType,
    TimestampType,
    TimeUnit,
    Type,
)
from ..schema.core import ColumnParameters, SchemaNode, build_schema, data_column
from ..schema.dsl import schema_to_string

_HANDLERS = {}


def _handler(name):
    def reg(fn):
        _HANDLERS[name] = fn
        return fn
    return reg


@_handler("boolean")
def _h_bool(s: str):
    low = s.strip().lower()
    if low in ("true", "t", "1", "yes", "y"):
        return True
    if low in ("false", "f", "0", "no", "n"):
        return False
    raise ValueError(f"cannot parse boolean {s!r}")


@_handler("int32")
@_handler("int64")
def _h_int(s: str):
    return int(s.strip())


@_handler("float")
@_handler("double")
def _h_float(s: str):
    return float(s.strip())


@_handler("string")
def _h_str(s: str):
    return s


@_handler("byte_array")
def _h_bytes(s: str):
    return s.encode("utf-8")


@_handler("json")
def _h_json(s: str):
    json.loads(s)  # validate
    return s


@_handler("timestamp")
def _h_ts(s: str):
    from ..floor.time import parse_iso_datetime

    return parse_iso_datetime(s)


@_handler("date")
def _h_date(s: str):
    return datetime.date.fromisoformat(s.strip())


def column_for_type(name: str, typ: str, repetition: FRT) -> SchemaNode:
    if typ == "boolean":
        return data_column(name, Type.BOOLEAN, repetition)
    if typ == "int32":
        return data_column(name, Type.INT32, repetition)
    if typ == "int64":
        return data_column(name, Type.INT64, repetition)
    if typ == "float":
        return data_column(name, Type.FLOAT, repetition)
    if typ == "double":
        return data_column(name, Type.DOUBLE, repetition)
    if typ == "byte_array":
        return data_column(name, Type.BYTE_ARRAY, repetition)
    if typ == "json":
        return data_column(
            name, Type.BYTE_ARRAY, repetition,
            ColumnParameters(converted_type=ConvertedType.JSON),
        )
    if typ == "string":
        return data_column(
            name, Type.BYTE_ARRAY, repetition,
            ColumnParameters(
                logical_type=LogicalType(STRING=StringType()),
                converted_type=ConvertedType.UTF8,
            ),
        )
    if typ == "timestamp":
        return data_column(
            name, Type.INT64, repetition,
            ColumnParameters(
                logical_type=LogicalType(
                    TIMESTAMP=TimestampType(isAdjustedToUTC=True, unit=TimeUnit.nanos())
                )
            ),
        )
    if typ == "date":
        return data_column(
            name, Type.INT32, repetition,
            ColumnParameters(converted_type=ConvertedType.DATE),
        )
    raise ValueError(f"unknown type hint {typ!r}")


def parse_type_hints(s: str) -> dict[str, str]:
    """'a=int64,b=double' → {'a': 'int64', 'b': 'double'} (main.go:72-90)."""
    out = {}
    if not s:
        return out
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"invalid type hint {part!r} (want col=type)")
        col, typ = part.split("=", 1)
        typ = typ.strip().lower()
        if typ not in _HANDLERS:
            raise ValueError(
                f"unknown type {typ!r} in hint for {col!r}; "
                f"valid: {sorted(_HANDLERS)}"
            )
        out[col.strip()] = typ
    return out


def derive_schema(header: list[str], hints: dict[str, str], wrap: str):
    for col in hints:
        if col not in header:
            raise ValueError(f"type hint for unknown column {col!r}")
    rep = FRT.OPTIONAL if wrap == "optional" else FRT.REQUIRED
    cols = []
    types = []
    for name in header:
        typ = hints.get(name, "string")
        types.append(typ)
        cols.append(column_for_type(name, typ, rep))
    return build_schema(cols, root_name="csv"), types


def convert(
    input_path: str,
    output_path: str,
    type_hints: dict[str, str],
    codec: int = CompressionCodec.SNAPPY,
    delimiter: str = ",",
    wrap: str = "required",
    creator: str = "csv2parquet",
    out=sys.stdout,
) -> int:
    """Returns the number of rows written."""
    with open(input_path, newline="") as f:
        reader = csv.reader(f, delimiter=delimiter)
        try:
            header = next(reader)
        except StopIteration:
            raise ValueError("empty CSV input") from None
        schema, types = derive_schema(header, type_hints, wrap)
        handlers = [_HANDLERS[t] for t in types]
        n = 0
        # floor.Writer performs the logical conversions (timestamp/date -> ints)
        from ..floor import Writer as FloorWriter

        with FloorWriter(
            output_path, schema=schema, codec=codec, created_by=creator
        ) as w:
            for lineno, record in enumerate(reader, 2):
                if len(record) != len(header):
                    raise ValueError(
                        f"line {lineno}: {len(record)} fields, expected {len(header)}"
                    )
                row = {}
                for name, h, raw in zip(header, handlers, record):
                    if raw == "" and wrap == "optional":
                        row[name] = None
                        continue
                    try:
                        row[name] = h(raw)
                    except ValueError as e:
                        raise ValueError(f"line {lineno}, column {name!r}: {e}") from None
                w.write(row)
                n += 1
    return n


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="csv2parquet", description="Convert CSV to parquet"
    )
    p.add_argument("--input", "-i", required=True)
    p.add_argument("--output", "-o", required=True)
    p.add_argument("--type-hints", default="", help="col=type,col=type,...")
    p.add_argument("--delimiter", default=",")
    p.add_argument("--codec", default="snappy",
                   choices=["uncompressed", "snappy", "gzip", "zstd"])
    p.add_argument("--wrap", default="required", choices=["required", "optional"],
                   help="optional: empty CSV fields become NULL")
    p.add_argument("--creator", default="csv2parquet")
    p.add_argument("--print-schema", action="store_true")
    args = p.parse_args(argv)
    try:
        if len(args.delimiter) != 1:
            raise ValueError(
                f"delimiter must be a single character, got {args.delimiter!r}"
            )
        hints = parse_type_hints(args.type_hints)
        if args.print_schema:
            with open(args.input, newline="") as f:
                try:
                    header = next(csv.reader(f, delimiter=args.delimiter))
                except StopIteration:
                    raise ValueError("empty CSV input") from None
            schema, _ = derive_schema(header, hints, args.wrap)
            sys.stdout.write(schema_to_string(schema))
            return 0
        codec = getattr(CompressionCodec, args.codec.upper())
        n = convert(args.input, args.output, hints, codec=codec,
                    delimiter=args.delimiter, wrap=args.wrap,
                    creator=args.creator)
        print(f"wrote {n} rows to {args.output}")
        return 0
    except (ParquetError, ValueError, OSError) as e:
        print(f"csv2parquet: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
