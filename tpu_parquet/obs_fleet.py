"""Fleet observability: cross-process metrics spool, aggregation, and
straggler attribution (ISSUE 20).

Every layer below this one — :class:`~tpu_parquet.obs.StatsRegistry`, the
ledger, the flight recorder, request tracing — sees exactly ONE process.
Production is a *fleet*: N loader/writer/serve processes per host, M
hosts.  This module is the seam between the two:

- :class:`SpoolWriter` rides the ``MetricsDumper`` discipline to publish
  versioned per-process snapshots ``{host, pid, role, seq, heartbeat_ts,
  registry tree, tail-sampled trace docs}`` into a shared spool directory
  (``TPQ_OBS_SPOOL``; default off).  One file per process generation,
  written tmp + ``os.replace`` so a reader never sees a torn snapshot;
  older generations are pruned to ``TPQ_OBS_SPOOL_KEEP``.

- :class:`FleetAggregator` scans the spool and folds every member's
  registry through the existing ``merge_dict`` paths into ONE fleet
  snapshot: counters reconcile exactly with the per-process sum, gauges
  take the max (``_MERGE_MAXED``), histograms add bucket-wise.  Torn,
  truncated, stale, or version-skewed files are counted and skipped,
  never fatal — a half-written snapshot is normal operation, not an
  error.

- :func:`doctor_fleet` turns the snapshot into verdicts the single-process
  doctor cannot reach: ``straggler`` (the process whose lane-seconds total
  sits outside the fleet's rel-MAD deviation band — named by host:pid,
  dominant lane, and deviation ratio), ``dead-process`` (heartbeat older
  than ``TPQ_OBS_STALE_S``), and the fleet-level ``slo-burn`` (the merged
  tree's worst tenant, with the exemplar attributed to the process whose
  histogram retained it).

- :func:`render_fleet_openmetrics` labels every per-process series with
  ``host``/``pid``/``role`` so one scrape shows the whole fleet.

- :func:`stitch_traces` / :func:`ambient_request_trace` carry a request's
  identity across OS-process seams: the parent exports
  ``RequestTrace.trace_context()`` (JSON via ``TPQ_TRACE_CONTEXT``), the
  child adopts it, and the aggregated view re-parents the child's spans
  under the originating request — ``pq_tool trace --request`` renders one
  multi-process tree.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .ledger import rel_noise
from .obs import (
    LatencyHistogram, RequestTrace, StatsRegistry, TailSampler, _om_escape,
    _om_name, _om_num, current_request_trace, doctor_registry, env_float,
    env_int, fleet_host, set_request_trace, warn_env_once,
)

__all__ = [
    "FLEET_VERSION", "SPOOL_VERSION", "FleetAggregator", "SpoolWriter",
    "ambient_request_trace", "doctor_fleet", "render_fleet_openmetrics",
    "resolve_spool_dir", "stitch_traces",
]

# version of the per-process spool document (`SpoolWriter` output)
SPOOL_VERSION = 1
# version of the aggregated fleet snapshot (`FleetAggregator.scan` output)
FLEET_VERSION = 1

# straggler detection: a process fires only when the fleet has enough
# members for a median to mean anything, and its lane-seconds total sits
# past BAND_K fleet-noise bands (rel-MAD, the ledger's discipline) over
# the median — with an absolute floor so a near-zero-noise fleet doesn't
# flag a 1% wobble
STRAGGLER_MIN_PROCS = 3
STRAGGLER_BAND_K = 3.0
STRAGGLER_FLOOR = 0.5


def resolve_spool_dir(spec: "str | None" = None) -> "str | None":
    """The spool directory (default: ``TPQ_OBS_SPOOL``), or ``None`` when
    fleet observability is off."""
    raw = os.environ.get("TPQ_OBS_SPOOL", "") if spec is None else spec
    return raw or None


def _member_name(host: str, pid: int, role: str) -> str:
    """A filesystem-safe spool-member prefix for ``host:pid:role``.  The
    role is part of the identity: one process may run several armed entry
    points (a job that ``write_sharded``s then ``DataLoader``s), and two
    writers sharing a prefix would ``os.replace``/prune each other's
    generations."""
    def safe(s):
        return "".join(ch if (ch.isascii() and (ch.isalnum() or ch in "-_."))
                       else "_" for ch in str(s))
    return f"{safe(host) or 'localhost'}-{int(pid)}-{safe(role) or 'unknown'}"


class SpoolWriter:
    """Daemon thread publishing this process's observability snapshot into
    the fleet spool directory (``TPQ_OBS_SPOOL``; inert when unset).

    ``source`` is a :class:`StatsRegistry`, a zero-arg callable returning
    one (or an ``as_dict`` tree), or a plain tree; ``sampler`` is a
    :class:`TailSampler`, a
    zero-arg callable returning trace documents, or ``None``.  Each tick
    writes one versioned generation file ``<host>-<pid>-<role>.<seq>.json``
    atomically (tmp + ``os.replace``) and prunes this member's older
    generations down to ``TPQ_OBS_SPOOL_KEEP``.  Lifecycle discipline
    matches :class:`~tpu_parquet.obs.MetricsDumper`: ``stop()`` publishes
    a final generation and joins, a failing source or write is counted,
    never raised.  ``host``/``pid`` overrides exist for tests and the
    fuzz harness (simulated fleets in one process).
    """

    def __init__(self, source, role: str, sampler=None,
                 spool_dir: "str | None" = None,
                 interval_s: "float | None" = None,
                 keep: "int | None" = None,
                 host: "str | None" = None, pid: "int | None" = None):
        self.source = source
        self.role = str(role)
        self.sampler = sampler
        self.spool_dir = (resolve_spool_dir() if spool_dir is None
                          else (spool_dir or None))
        self.interval_s = (env_float("TPQ_OBS_SPOOL_S", 1.0, lo=0.05)
                           if interval_s is None else float(interval_s))
        self.keep = (env_int("TPQ_OBS_SPOOL_KEEP", 2, lo=1)
                     if keep is None else max(int(keep), 1))
        self.host = str(host) if host is not None else fleet_host()
        self.pid = int(pid) if pid is not None else os.getpid()
        self._member = _member_name(self.host, self.pid, self.role)
        self._seq = 0
        self._last_hb = 0.0
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.written = 0
        self.dropped = 0

    @property
    def enabled(self) -> bool:
        return self.spool_dir is not None and self.interval_s > 0

    def start(self) -> "SpoolWriter":
        if not self.enabled or self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"tpq-spool-{self.role}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent; joins the spool thread (no leak, bench-gated)."""
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=10.0)
        self._thread = None

    def __enter__(self) -> "SpoolWriter":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _run(self) -> None:
        while True:
            stopping = self._stop.wait(self.interval_s)
            self.publish_once()
            if stopping:
                return

    def _trace_docs(self) -> list:
        if self.sampler is None:
            return []
        if isinstance(self.sampler, TailSampler):
            return self.sampler.traces()
        return list(self.sampler() or [])

    def publish_once(self) -> "str | None":
        """Publish one snapshot generation; returns its path (``None``
        when disabled or the publish failed — failures never raise)."""
        if self.spool_dir is None:
            return None
        try:
            tree = self.source
            if callable(tree) and not isinstance(tree, StatsRegistry):
                tree = tree()
            if isinstance(tree, StatsRegistry):
                tree = tree.as_dict()
            # heartbeat is monotonic per member even if the wall clock
            # steps backwards (the fuzz harness checks)
            self._last_hb = max(time.time(), self._last_hb)
            self._seq += 1
            doc = {
                "spool_version": SPOOL_VERSION,
                "host": self.host,
                "pid": self.pid,
                "role": self.role,
                "seq": self._seq,
                "heartbeat_ts": self._last_hb,
                "registry": tree,
                "traces": self._trace_docs(),
            }
            os.makedirs(self.spool_dir, exist_ok=True)
            path = os.path.join(self.spool_dir,
                                f"{self._member}.{self._seq:08d}.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=repr)
                f.write("\n")
            os.replace(tmp, path)
            self.written += 1
            self._prune()
            return path
        except Exception:  # noqa: BLE001 — observability never takes the run down
            self.dropped += 1
            return None

    def _prune(self) -> None:
        """Drop this member's generations beyond the newest ``keep``."""
        prefix = f"{self._member}."
        mine = sorted(fn for fn in os.listdir(self.spool_dir)
                      if fn.startswith(prefix) and fn.endswith(".json"))
        for fn in mine[:-self.keep]:
            try:
                os.remove(os.path.join(self.spool_dir, fn))
            except OSError:
                pass  # a concurrent aggregator/pruner got there first


def _valid_spool_doc(doc) -> bool:
    return (isinstance(doc, dict)
            and doc.get("spool_version") == SPOOL_VERSION
            and isinstance(doc.get("host"), str) and doc["host"]
            and isinstance(doc.get("pid"), int)
            and isinstance(doc.get("seq"), int) and doc["seq"] > 0
            and isinstance(doc.get("heartbeat_ts"), (int, float))
            and isinstance(doc.get("registry"), dict))


class FleetAggregator:
    """Scan a spool directory and fold every member's latest snapshot into
    one versioned fleet snapshot.

    Per member (``host:pid:role``) only the highest-``seq`` readable
    document counts; lower generations are ``stale_skipped``; members
    sharing a ``host:pid`` (one process, several armed entry points) fold
    into one process entry.  Torn / truncated /
    non-JSON / version-skewed files are ``rejected`` — counted, never
    fatal (a writer mid-``os.replace`` is normal).  The merged registry
    reconciles exactly with the per-process trees by construction:
    counters add, ``_MERGE_MAXED`` gauges max, histograms add bucket-wise
    (the fuzz target and the 3-process e2e test hold it to "exactly").
    """

    def __init__(self, spool_dir: "str | None" = None,
                 stale_s: "float | None" = None):
        self.spool_dir = (resolve_spool_dir() if spool_dir is None
                          else (spool_dir or None))
        self.stale_s = (env_float("TPQ_OBS_STALE_S", 10.0, lo=0.1)
                        if stale_s is None else float(stale_s))

    def scan(self, now: "float | None" = None) -> dict:
        """One aggregation pass; returns the fleet snapshot dict (empty
        fleet when the spool is unset/missing, never raises)."""
        now = time.time() if now is None else float(now)
        files_scanned = rejected = stale_skipped = 0
        latest: dict = {}  # (host, pid) -> doc
        try:
            names = sorted(os.listdir(self.spool_dir or ""))
        except OSError:
            names = []
        for fn in names:
            if not fn.endswith(".json"):
                continue
            files_scanned += 1
            try:
                with open(os.path.join(self.spool_dir, fn)) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                rejected += 1
                continue
            if not _valid_spool_doc(doc):
                rejected += 1
                continue
            key = (doc["host"], doc["pid"], str(doc.get("role") or "unknown"))
            prev = latest.get(key)
            if prev is None:
                latest[key] = doc
            elif doc["seq"] > prev["seq"]:
                latest[key] = doc
                stale_skipped += 1
            else:
                stale_skipped += 1
        merged = StatsRegistry()
        processes: dict = {}
        traces: list = []
        for (host, pid, role), doc in sorted(latest.items()):
            try:
                merged.merge_dict(doc["registry"])
            except (ValueError, TypeError, AttributeError):
                rejected += 1
                continue
            hb = float(doc["heartbeat_ts"])
            pkey = f"{host}:{pid}"
            prev = processes.get(pkey)
            if prev is None:
                processes[pkey] = {
                    "role": role,
                    "seq": doc["seq"],
                    "heartbeat_ts": hb,
                    "registry": doc["registry"],
                }
            else:
                # one OS process, several armed entry points (e.g. a job
                # that write_sharded's then DataLoader's): one process
                # entry, roles joined, registries folded, newest heartbeat
                roles = set(prev["role"].split("+")) | {role}
                prev["role"] = "+".join(sorted(roles))
                prev["seq"] = max(prev["seq"], doc["seq"])
                prev["heartbeat_ts"] = max(prev["heartbeat_ts"], hb)
                fold = StatsRegistry()
                fold.merge_dict(prev["registry"])
                fold.merge_dict(doc["registry"])
                prev["registry"] = fold.as_dict()
            for td in doc.get("traces") or []:
                if isinstance(td, dict) and td.get("trace_id"):
                    traces.append(td)
        for p in processes.values():
            age = max(now - p["heartbeat_ts"], 0.0)
            p["heartbeat_ts"] = round(p["heartbeat_ts"], 3)
            p["heartbeat_age_s"] = round(age, 3)
            p["stale"] = age > self.stale_s
        return {
            "fleet_version": FLEET_VERSION,
            "generated_unix": round(now, 3),
            "spool_dir": self.spool_dir,
            "stale_after_s": self.stale_s,
            "processes": processes,
            "registry": merged.as_dict(),
            "traces": traces,
            "files_scanned": files_scanned,
            "rejected": rejected,
            "stale_skipped": stale_skipped,
        }


# ---------------------------------------------------------------------------
# fleet diagnosis: straggler / dead-process / fleet slo-burn
# ---------------------------------------------------------------------------

def _num(d, k) -> float:
    v = d.get(k) if isinstance(d, dict) else None
    return float(v) if isinstance(v, (int, float)) else 0.0


def process_lanes(tree: dict) -> dict:
    """Per-process lane seconds — the same lane extraction the
    single-process doctor attributes on, plus the write lanes, so a
    straggling writer and a straggling decoder are both nameable."""
    if not isinstance(tree, dict):
        return {}
    pipe = tree.get("pipeline") or {}
    reader = tree.get("reader") or {}
    dev = tree.get("device")
    dev = dev if isinstance(dev, dict) else {}
    serve = tree.get("serve")
    serve = serve if isinstance(serve, dict) else {}
    host = (_num(pipe, "io_seconds") + _num(pipe, "decompress_seconds")
            + _num(pipe, "recompress_seconds"))
    if host == 0.0:
        host = _num(reader, "host_seconds")
    dev_resolve = sum(_num(c, "device_seconds")
                      for c in (dev.get("routes") or {}).values()
                      if isinstance(c, dict))
    lanes = {
        "link": _num(pipe, "stage_seconds"),
        "host_decompress": host,
        "device_resolve": dev_resolve or (_num(pipe, "dispatch_seconds")
                                          + _num(pipe, "finalize_seconds")),
        "h2d": _num(dev.get("h2d") or {}, "device_seconds"),
        "stall": _num(pipe, "stall_seconds"),
        "admission": _num(serve, "queue_wait_seconds"),
    }
    wr = tree.get("write")
    wr = wr if isinstance(wr, dict) else {}
    for s in ("encode", "compress", "flush", "merge", "compact"):
        lanes[f"write_{s}"] = _num(wr, f"{s}_seconds")
    lanes["write_stall"] = _num(wr, "stall_seconds")
    return {k: v for k, v in lanes.items()}


def _median(xs: "list[float]") -> float:
    s = sorted(xs)
    n = len(s)
    if not n:
        return 0.0
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _straggler_block(processes: dict) -> "dict | None":
    totals = {}
    lanes_by = {}
    for key, p in processes.items():
        if p.get("stale"):
            continue  # a dead process is its own verdict, not a straggler
        lanes = process_lanes(p.get("registry") or {})
        total = sum(lanes.values())
        if total > 0:
            totals[key] = total
            lanes_by[key] = lanes
    if len(totals) < STRAGGLER_MIN_PROCS:
        return None
    worst = max(totals, key=lambda k: (totals[k], k))
    # leave-one-out: the candidate's own total must not define the fleet's
    # noise band (at small n the half-range estimator would let one extreme
    # straggler inflate the band past its own deviation and never fire)
    rest = [v for k, v in totals.items() if k != worst]
    med = _median(rest)
    if med <= 0:
        return None
    band = rel_noise(rest)
    ratio = totals[worst] / med
    bar = 1.0 + max(STRAGGLER_BAND_K * band, STRAGGLER_FLOOR)
    if ratio <= bar:
        return None
    lanes = lanes_by[worst]
    dominant = max(lanes, key=lambda k: (lanes[k], k))
    return {
        "verdict": "straggler",
        "process": worst,
        "role": (processes[worst] or {}).get("role", "unknown"),
        "dominant_lane": dominant,
        "deviation": round(ratio, 3),
        "band": round(band, 4),
        "total_lane_s": round(totals[worst], 6),
        "median_lane_s": round(med, 6),
        "lanes": {k: round(v, 6) for k, v in lanes.items() if v > 0},
        "advice": (
            f"process {worst} carries {ratio:.2f}x the fleet-median lane "
            f"seconds (band {band:.3f}); its dominant lane is "
            f"'{dominant}' — diagnose THAT process: pq_tool doctor on its "
            f"own snapshot, or pq_tool trace --request on a trace it "
            f"retained"),
    }


def _dead_blocks(processes: dict, stale_s: float) -> "list[dict]":
    out = []
    for key, p in sorted(processes.items()):
        if not p.get("stale"):
            continue
        out.append({
            "verdict": "dead-process",
            "process": key,
            "role": p.get("role", "unknown"),
            "heartbeat_age_s": p.get("heartbeat_age_s", 0.0),
            "stale_after_s": round(float(stale_s), 3),
            "advice": (
                f"process {key} ({p.get('role', 'unknown')}) last "
                f"heartbeat {p.get('heartbeat_age_s', 0.0):g}s ago "
                f"(> {stale_s:g}s): restart it or prune its spool entry; "
                f"its counters still ride the fleet totals"),
        })
    return out


def _owning_process(processes: dict, trace_id: str) -> "str | None":
    """The fleet member whose snapshot retained ``trace_id`` — first as a
    histogram exemplar (the slo-burn linkage), then among its trace docs."""
    if not trace_id:
        return None
    for key, p in sorted(processes.items()):
        hists = (p.get("registry") or {}).get("histograms") or {}
        for hd in hists.values():
            for ex in (hd.get("exemplars") or {}).values():
                if isinstance(ex, (list, tuple)) and ex \
                        and str(ex[0]) == trace_id:
                    return key
    return None


def doctor_fleet(snapshot: dict) -> "dict | None":
    """Fleet-level diagnosis over a :meth:`FleetAggregator.scan` snapshot.

    Returns ``{"verdicts": [...], "doctor": <merged-tree doctor report>}``
    — or ``None`` when the fleet produced no evidence at all.  Verdicts:
    ``straggler``, one ``dead-process`` per stale member, and the merged
    tree's ``slo-burn`` annotated with ``exemplar_process`` (which member
    retained the exemplar trace).  The merged-tree doctor report rides
    along so the fleet view never says less than the single-process one.
    """
    if not isinstance(snapshot, dict):
        return None
    processes = snapshot.get("processes") or {}
    verdicts: list = []
    strag = _straggler_block(processes)
    if strag:
        verdicts.append(strag)
    verdicts.extend(_dead_blocks(
        processes, float(snapshot.get("stale_after_s") or 0.0)))
    report = doctor_registry(snapshot.get("registry") or {})
    burn = (report or {}).get("slo_burn")
    if isinstance(burn, dict):
        burn = dict(burn)
        owner = _owning_process(processes, burn.get("exemplar_trace") or "")
        burn["exemplar_process"] = owner
        if owner:
            burn["advice"] = (burn.get("advice", "")
                              + f"; the exemplar was retained by {owner}")
        verdicts.append(burn)
    if not verdicts and report is None:
        return None
    return {"verdicts": verdicts, "doctor": report}


# ---------------------------------------------------------------------------
# fleet OpenMetrics: host/pid/role-labelled exposition
# ---------------------------------------------------------------------------

def _om_labels(host: str, pid: int, role: str, extra: str = "") -> str:
    base = (f'host="{_om_escape(host)}",pid="{int(pid)}",'
            f'role="{_om_escape(role)}"')
    return f"{{{base}{',' + extra if extra else ''}}}"


def _om_walk_labelled(lines: list, prefix: tuple, tree: dict,
                      labels: str, typed: set) -> None:
    for k, v in sorted(tree.items()):
        if isinstance(v, dict):
            _om_walk_labelled(lines, prefix + (k,), v, labels, typed)
        elif isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        else:
            name = _om_name("tpq", *prefix, k)
            if name not in typed:
                typed.add(name)
                lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{labels} {_om_num(v)}")


def render_fleet_openmetrics(snapshot: dict) -> str:
    """Render a fleet snapshot as an OpenMetrics exposition where every
    per-process series carries ``host``/``pid``/``role`` labels — one
    scrape, the whole fleet — followed by the per-member heartbeat ages.
    Ends with ``# EOF``.
    """
    if not isinstance(snapshot, dict):
        raise ValueError("not a fleet snapshot")
    lines: list[str] = []
    typed: set = set()
    for key, p in sorted((snapshot.get("processes") or {}).items()):
        host, _, pid = key.rpartition(":")
        try:
            pid_i = int(pid)
        except ValueError:
            continue
        role = str(p.get("role") or "unknown")
        labels = _om_labels(host, pid_i, role)
        tree = p.get("registry") or {}
        for section in ("pipeline", "reader", "loader", "io", "data_errors",
                        "device", "serve", "cache", "write", "alloc"):
            sub = tree.get(section)
            if isinstance(sub, dict):
                sub = dict(sub)
                sub.pop("ship_feedback", None)
                _om_walk_labelled(lines, (section,), sub, labels, typed)
        for hname, hd in sorted((tree.get("histograms") or {}).items()):
            if not isinstance(hd, dict):
                continue
            name = _om_name("tpq", hname, "seconds")
            if name not in typed:
                typed.add(name)
                lines.append(f"# TYPE {name} histogram")
            exemplars = hd.get("exemplars") or {}
            cum = 0
            for i in sorted(int(k) for k in (hd.get("buckets") or {})):
                cum += int(hd["buckets"][str(i)])
                le = LatencyHistogram.bucket_upper_seconds(i)
                lab = _om_labels(host, pid_i, role, f'le="{le!r}"')
                line = f"{name}_bucket{lab} {cum}"
                ex = exemplars.get(str(i))
                if isinstance(ex, (list, tuple)) and len(ex) == 2:
                    line += (f' # {{trace_id="{_om_escape(ex[0])}"}}'
                             f" {float(ex[1])!r}")
                lines.append(line)
            lab = _om_labels(host, pid_i, role, 'le="+Inf"')
            lines.append(f"{name}_bucket{lab} {int(hd.get('count', 0))}")
            lines.append(f"{name}_sum{labels} "
                         f"{float(hd.get('sum_seconds', 0.0))!r}")
            lines.append(f"{name}_count{labels} {int(hd.get('count', 0))}")
        hb = _om_name("tpq", "fleet", "heartbeat_age_seconds")
        if hb not in typed:
            typed.add(hb)
            lines.append(f"# TYPE {hb} gauge")
        lines.append(f"{hb}{labels} "
                     f"{float(p.get('heartbeat_age_s') or 0.0)!r}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# cross-process trace stitching
# ---------------------------------------------------------------------------

def stitch_traces(docs: "list[dict]", trace_id: str) -> "dict | None":
    """Assemble one multi-process view of a request from retained trace
    documents: the root (the doc whose own ``trace_id`` matches) plus
    every child doc whose ``origin.trace_id`` points at it (adopted via
    :meth:`RequestTrace.adopt_context` in another process).  Children sort
    by ``(host, pid, trace_id)``.  Returns ``None`` when neither a root
    nor any child matches.
    """
    root = None
    children = []
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        if doc.get("trace_id") == trace_id:
            # highest-information copy wins: a later spool generation of
            # the same doc simply replaces the earlier one
            root = doc
        elif (doc.get("origin") or {}).get("trace_id") == trace_id:
            children.append(doc)
    if root is None and not children:
        return None
    seen = set()
    uniq = []
    for d in sorted(children,
                    key=lambda d: (str(d.get("host") or ""),
                                   int(d.get("pid") or 0),
                                   str(d.get("trace_id") or ""))):
        tid = d.get("trace_id")
        if tid in seen:
            continue  # the same child republished across generations
        seen.add(tid)
        uniq.append(d)
    return {"trace_id": trace_id, "root": root, "children": uniq}


def ambient_request_trace() -> "RequestTrace | None":
    """The request trace this work should record into: the thread's
    current one when set, else one adopted from the ``TPQ_TRACE_CONTEXT``
    env blob a parent process exported (installed thread-locally so
    nested code finds it).  ``None`` when neither exists; a malformed
    blob degrades via ``warn_env_once``, never raises."""
    tr = current_request_trace()
    if tr is not None:
        return tr
    raw = os.environ.get("TPQ_TRACE_CONTEXT", "")
    if not raw:
        return None
    try:
        tr = RequestTrace.adopt_context(json.loads(raw))
    except (ValueError, TypeError):
        warn_env_once("TPQ_TRACE_CONTEXT", raw, None)
        return None
    set_request_trace(tr)
    return tr
