"""Pallas TPU kernels for the hot bit-level decode primitives.

The XLA decode kernels (jax_kernels.py) express bit extraction as per-value
byte *gathers* — fully general (arbitrary per-value positions/widths), which
the RLE-hybrid and delta paths need.  But the single hottest primitive —
fixed-width unpack of an 8-value-aligned stream (the reference's 98 generated
``unpack8intXX_N`` functions, bitbacking32.go/bitpacking64.go) — has an
affine access pattern Pallas can exploit: a tile of 8 values occupies exactly
``width`` contiguous bytes, so every byte a lane needs is a STATIC column of
a (groups, width) byte matrix.  The kernel below is pure strided loads +
shifts + ors: no gathers, no dynamic indexing, VMEM-resident.

Layout: values [g*8+j] live in row g of the (G, width) byte matrix; value j's
bits start at static bit ``j*width`` of the row, so the unroll over j∈[0,8)
bakes byte offsets and shifts into the instruction stream — the same
specialization trick as the reference's generated Go, but one parameterized
kernel instead of 98 source functions, and 8×128 lanes per VPU op instead of
one value per iteration.

On non-TPU backends (CPU tests) the kernel runs through the Pallas
interpreter; ``unpack_bits`` in jax_kernels.py remains the default path until
`use_pallas=True` callers opt in (bench.py A/Bs the two).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["unpack_bits_pallas", "unpack_bp_groups", "bp_groups_pad",
           "build_planes", "pallas_available"]

_GROUPS_PER_TILE = 1024  # 8192 values per grid step; (1024,) = one 8x128 tile


def pallas_available() -> bool:
    """True when the current default backend can run Mosaic TPU kernels."""
    try:
        plat = jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        return False
    return plat in ("tpu", "axon")


def _unpack_kernel(width: int, in_ref, out_ref):
    """One tile: (width, G) byte PLANES -> (G, 8) values.

    Plane b holds byte b of every group's packed row (host transposes once).
    Leading-dim static indexing `in_ref[k, :]` is the layout Mosaic lowers
    cleanly — strided u8 column slices of a (G, width) tile miscompile
    (verified on v5e: the `<<16` term of 3-byte accumulations silently
    drops for ~1/4 of the lanes).

    Static unroll over the 8 values of a group: value j's field starts at bit
    j*width of its row, i.e. byte j*width//8 with shift j*width%8 — all
    Python ints at trace time, so the loop emits straight-line vector code.
    """
    mask = jnp.uint32((1 << width) - 1 if width < 32 else 0xFFFFFFFF)
    for j in range(8):
        start = (j * width) // 8
        shift = (j * width) % 8
        end = (j * width + width - 1) // 8  # inclusive last byte
        acc = in_ref[start, :].astype(jnp.uint32)
        for k in range(start + 1, min(end, start + 3) + 1):
            acc = acc | (in_ref[k, :].astype(jnp.uint32)
                         << jnp.uint32(8 * (k - start)))
        val = acc if shift == 0 else acc >> jnp.uint32(shift)
        if end - start + 1 > 4:  # 5-byte span (width>25, shift>0): straggler
            val = val | (in_ref[start + 4, :].astype(jnp.uint32)
                         << jnp.uint32(32 - shift))
        out_ref[:, j] = val & mask


def _unpack_call(planes, width: int, groups: int, interpret: bool):
    """The one pallas_call site: (width, groups) byte planes -> u32[groups, 8].

    The BlockSpec layout here IS the Mosaic miscompile workaround documented
    on _unpack_kernel (leading-dim plane indexing, never strided u8 column
    slices) — both jit entry points share it so they can't drift apart.
    """
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        functools.partial(_unpack_kernel, width),
        out_shape=jax.ShapeDtypeStruct((groups, 8), jnp.uint32),
        grid=(groups // _GROUPS_PER_TILE,),
        in_specs=[pl.BlockSpec((width, _GROUPS_PER_TILE), lambda t: (0, t))],
        out_specs=pl.BlockSpec((_GROUPS_PER_TILE, 8), lambda t: (t, 0)),
        interpret=interpret,
    )(planes)


@functools.partial(jax.jit, static_argnames=("width", "count", "interpret"))
def _unpack_pallas_jit(planes, *, width, count, interpret):
    groups = planes.shape[1]
    return _unpack_call(planes, width, groups, interpret).reshape(-1)[:count]


def build_planes(buf, width: int, count: int) -> jax.Array:
    """Stage packed bytes as the kernel's (width, padded_groups) byte planes.

    Pads to whole 8-value groups and whole tiles, then transposes once so
    plane k holds byte k of every group's row (the layout the kernel's
    leading-dim indexing needs — see _unpack_kernel).
    """
    groups = -(-count // 8)                    # ceil: values -> 8-value groups
    gpad = -(-max(groups, 1) // _GROUPS_PER_TILE) * _GROUPS_PER_TILE
    need = gpad * width
    if isinstance(buf, jax.Array):
        n = buf.shape[0]
        flat = buf[:need] if n >= need else jnp.pad(buf, (0, need - n))
        return flat.reshape(gpad, width).T
    host = np.asarray(buf)
    padded = np.zeros(need, dtype=np.uint8)
    padded[: min(len(host), need)] = host[:need]
    return jnp.asarray(np.ascontiguousarray(padded.reshape(gpad, width).T))


def bp_groups_pad(groups: int) -> int:
    """Pad a group count to a whole number of kernel tiles (bucketed first so
    the (width, groups_pad) executable set stays bounded across chunks)."""
    from .jax_decode import _bucket_count

    b = _bucket_count(max(groups, 1))
    return -(-b // _GROUPS_PER_TILE) * _GROUPS_PER_TILE


@functools.partial(
    jax.jit, static_argnames=("width", "groups_pad", "interpret")
)
def _bp_groups_jit(buf, bp_base, *, width, groups_pad, interpret):
    bp = jax.lax.dynamic_slice(buf, (bp_base,), (groups_pad * width,))
    planes = bp.reshape(groups_pad, width).T
    return _unpack_call(planes, width, groups_pad, interpret).reshape(-1)


def unpack_bp_groups(buf_dev, bp_base: int, width: int, groups_pad: int,
                     interpret: bool = False):
    """Unpack ``groups_pad`` 8-value groups of ``width``-bit values starting
    at byte ``bp_base`` of the staged device buffer.

    The production entry point the batched reader routes hybrid bit-packed
    runs through: BP payloads are staged *contiguously* (group-aligned, a
    structural property of the RLE/BP hybrid format — every BP run is whole
    8-value groups starting on a byte boundary), so the unpack is the exact
    fixed-width affine case this kernel exists for — no gathers at all.
    Returns uint32[groups_pad * 8]; bytes past the real payload decode to
    garbage values that callers never select (combine masks by run table).

    ``groups_pad`` must come from :func:`bp_groups_pad`.  Traced with x64
    disabled regardless of ambient context (the decode paths run under
    scoped_x64, but Mosaic refuses i64 grid index maps — see the NOTE on
    :func:`unpack_bits_pallas`); the uint32 result is x64-agnostic.
    """
    if groups_pad % _GROUPS_PER_TILE:
        raise ValueError(f"groups_pad {groups_pad} not a multiple of "
                         f"{_GROUPS_PER_TILE}")
    if isinstance(bp_base, (int, np.integer)):
        bp_base = np.int32(bp_base)  # traced callers pass their own i32
    from .jax_kernels import enable_x64

    # tpq.unpack name scope: the TPQ_XPROF device timeline attributes the
    # Pallas unpack to the same kernel family as the XLA fallback path
    with enable_x64(False), jax.named_scope("tpq.unpack"):
        return _bp_groups_jit(buf_dev, bp_base, width=width,
                              groups_pad=groups_pad,
                              interpret=bool(interpret))


def unpack_bits_pallas(buf, width: int, count: int, interpret: bool | None = None):
    # NOTE: deliberately NOT under scoped_x64 — the kernel is pure uint32 and
    # an x64 trace makes the grid index maps emit i64, which Mosaic refuses
    # to legalize ("func.return (i32, i64)").
    """Fixed-width LSB-first unpack via the Pallas tile kernel.

    ``buf`` uint8[...] packed bytes (numpy or jax); ``count`` values out.
    Drop-in for jax_kernels.unpack_bits on width 1..32.  ``interpret`` forces
    the Pallas interpreter (auto: on for non-TPU backends so CPU tests run).
    """
    if not 1 <= width <= 32:
        raise ValueError(f"unpack_bits_pallas supports widths 1..32, got {width}")
    if interpret is None:
        interpret = not pallas_available()
    planes = build_planes(buf, width, count)
    with jax.named_scope("tpq.unpack"):
        return _unpack_pallas_jit(planes, width=width, count=count,
                                  interpret=bool(interpret))
