"""Pallas TPU kernels for the hot bit-level decode primitives.

The XLA decode kernels (jax_kernels.py) express bit extraction as per-value
byte *gathers* — fully general (arbitrary per-value positions/widths), which
the RLE-hybrid and delta paths need.  But the single hottest primitive —
fixed-width unpack of an 8-value-aligned stream (the reference's 98 generated
``unpack8intXX_N`` functions, bitbacking32.go/bitpacking64.go) — has an
affine access pattern Pallas can exploit: a tile of 8 values occupies exactly
``width`` contiguous bytes, so every byte a lane needs is a STATIC column of
a (groups, width) byte matrix.  The kernel below is pure strided loads +
shifts + ors: no gathers, no dynamic indexing, VMEM-resident.

Layout: values [g*8+j] live in row g of the (G, width) byte matrix; value j's
bits start at static bit ``j*width`` of the row, so the unroll over j∈[0,8)
bakes byte offsets and shifts into the instruction stream — the same
specialization trick as the reference's generated Go, but one parameterized
kernel instead of 98 source functions, and 8×128 lanes per VPU op instead of
one value per iteration.

On non-TPU backends (CPU tests) the kernel runs through the Pallas
interpreter; ``unpack_bits`` in jax_kernels.py remains the default path until
`use_pallas=True` callers opt in (bench.py A/Bs the two).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["unpack_bits_pallas", "unpack_bp_groups", "bp_groups_pad",
           "build_planes", "pallas_available", "pallas_mode",
           "resolve_interpret", "fused_plain_words", "fused_narrow_words",
           "fused_count_pad", "fused_narrow_count_pad"]

_GROUPS_PER_TILE = 1024  # 8192 values per grid step; (1024,) = one 8x128 tile

# probed once per process (satellite of ISSUE 13): the backend platform
# cannot change under a live process, and the old per-call probe showed up
# as jax.devices() churn on the dispatch hot path once every fused plan
# asked it.  None = not probed yet.
_AVAILABLE: "bool | None" = None


def pallas_available() -> bool:
    """True when the current default backend can run Mosaic TPU kernels
    (cached after the first probe; ``_reset_available_cache`` un-caches for
    tests that flip backends)."""
    global _AVAILABLE
    if _AVAILABLE is None:
        try:
            plat = jax.devices()[0].platform
        except Exception:  # noqa: BLE001
            plat = None
        _AVAILABLE = plat in ("tpu", "axon")
    return _AVAILABLE


def _reset_available_cache() -> None:
    global _AVAILABLE
    _AVAILABLE = None


def pallas_mode() -> str:
    """``"compiled"`` (native Mosaic) or ``"interpret"`` — how any Pallas
    kernel reached in this process actually runs.  Recorded in the ledger
    env fingerprint so a banked bench number carries whether its fused
    kernels were compiled or interpreted (an interpret-mode device time is
    not a measurement of the kernel)."""
    return "compiled" if pallas_available() else "interpret"


def resolve_interpret(interpret: "bool | None" = None) -> bool:
    """The ``interpret=`` every fused/pallas entry point resolves through:
    explicit wins; otherwise native Mosaic when available, else the Pallas
    interpreter with ONE process-wide breadcrumb (``warn_env_once`` — an
    interpreted fused kernel is bit-identical but a perf cliff, worth one
    line, never a failure)."""
    if interpret is not None:
        return bool(interpret)
    if pallas_available():
        return False
    from .obs import warn_env_once

    warn_env_once("TPQ_FUSE", "<no mosaic backend>",
                  "pallas interpret mode (bit-identical, not a measurement)")
    return True


def _unpack_kernel(width: int, in_ref, out_ref):
    """One tile: (width, G) byte PLANES -> (G, 8) values.

    Plane b holds byte b of every group's packed row (host transposes once).
    Leading-dim static indexing `in_ref[k, :]` is the layout Mosaic lowers
    cleanly — strided u8 column slices of a (G, width) tile miscompile
    (verified on v5e: the `<<16` term of 3-byte accumulations silently
    drops for ~1/4 of the lanes).

    Static unroll over the 8 values of a group: value j's field starts at bit
    j*width of its row, i.e. byte j*width//8 with shift j*width%8 — all
    Python ints at trace time, so the loop emits straight-line vector code.
    """
    mask = jnp.uint32((1 << width) - 1 if width < 32 else 0xFFFFFFFF)
    for j in range(8):
        start = (j * width) // 8
        shift = (j * width) % 8
        end = (j * width + width - 1) // 8  # inclusive last byte
        acc = in_ref[start, :].astype(jnp.uint32)
        for k in range(start + 1, min(end, start + 3) + 1):
            acc = acc | (in_ref[k, :].astype(jnp.uint32)
                         << jnp.uint32(8 * (k - start)))
        val = acc if shift == 0 else acc >> jnp.uint32(shift)
        if end - start + 1 > 4:  # 5-byte span (width>25, shift>0): straggler
            val = val | (in_ref[start + 4, :].astype(jnp.uint32)
                         << jnp.uint32(32 - shift))
        out_ref[:, j] = val & mask


def _unpack_call(planes, width: int, groups: int, interpret: bool):
    """The one pallas_call site: (width, groups) byte planes -> u32[groups, 8].

    The BlockSpec layout here IS the Mosaic miscompile workaround documented
    on _unpack_kernel (leading-dim plane indexing, never strided u8 column
    slices) — both jit entry points share it so they can't drift apart.
    """
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        functools.partial(_unpack_kernel, width),
        out_shape=jax.ShapeDtypeStruct((groups, 8), jnp.uint32),
        grid=(groups // _GROUPS_PER_TILE,),
        in_specs=[pl.BlockSpec((width, _GROUPS_PER_TILE), lambda t: (0, t))],
        out_specs=pl.BlockSpec((_GROUPS_PER_TILE, 8), lambda t: (t, 0)),
        interpret=interpret,
    )(planes)


@functools.partial(jax.jit, static_argnames=("width", "count", "interpret"))
def _unpack_pallas_jit(planes, *, width, count, interpret):
    groups = planes.shape[1]
    return _unpack_call(planes, width, groups, interpret).reshape(-1)[:count]


def build_planes(buf, width: int, count: int) -> jax.Array:
    """Stage packed bytes as the kernel's (width, padded_groups) byte planes.

    Pads to whole 8-value groups and whole tiles, then transposes once so
    plane k holds byte k of every group's row (the layout the kernel's
    leading-dim indexing needs — see _unpack_kernel).
    """
    groups = -(-count // 8)                    # ceil: values -> 8-value groups
    gpad = -(-max(groups, 1) // _GROUPS_PER_TILE) * _GROUPS_PER_TILE
    need = gpad * width
    if isinstance(buf, jax.Array):
        n = buf.shape[0]
        flat = buf[:need] if n >= need else jnp.pad(buf, (0, need - n))
        return flat.reshape(gpad, width).T
    host = np.asarray(buf)
    padded = np.zeros(need, dtype=np.uint8)
    padded[: min(len(host), need)] = host[:need]
    return jnp.asarray(np.ascontiguousarray(padded.reshape(gpad, width).T))


def bp_groups_pad(groups: int) -> int:
    """Pad a group count to a whole number of kernel tiles (bucketed first so
    the (width, groups_pad) executable set stays bounded across chunks)."""
    from .jax_decode import _bucket_count

    b = _bucket_count(max(groups, 1))
    return -(-b // _GROUPS_PER_TILE) * _GROUPS_PER_TILE


@functools.partial(
    jax.jit, static_argnames=("width", "groups_pad", "interpret")
)
def _bp_groups_jit(buf, bp_base, *, width, groups_pad, interpret):
    bp = jax.lax.dynamic_slice(buf, (bp_base,), (groups_pad * width,))
    planes = bp.reshape(groups_pad, width).T
    return _unpack_call(planes, width, groups_pad, interpret).reshape(-1)


def unpack_bp_groups(buf_dev, bp_base: int, width: int, groups_pad: int,
                     interpret: bool = False):
    """Unpack ``groups_pad`` 8-value groups of ``width``-bit values starting
    at byte ``bp_base`` of the staged device buffer.

    The production entry point the batched reader routes hybrid bit-packed
    runs through: BP payloads are staged *contiguously* (group-aligned, a
    structural property of the RLE/BP hybrid format — every BP run is whole
    8-value groups starting on a byte boundary), so the unpack is the exact
    fixed-width affine case this kernel exists for — no gathers at all.
    Returns uint32[groups_pad * 8]; bytes past the real payload decode to
    garbage values that callers never select (combine masks by run table).

    ``groups_pad`` must come from :func:`bp_groups_pad`.  Traced with x64
    disabled regardless of ambient context (the decode paths run under
    scoped_x64, but Mosaic refuses i64 grid index maps — see the NOTE on
    :func:`unpack_bits_pallas`); the uint32 result is x64-agnostic.
    """
    if groups_pad % _GROUPS_PER_TILE:
        raise ValueError(f"groups_pad {groups_pad} not a multiple of "
                         f"{_GROUPS_PER_TILE}")
    if isinstance(bp_base, (int, np.integer)):
        bp_base = np.int32(bp_base)  # traced callers pass their own i32
    from .jax_kernels import enable_x64

    # tpq.unpack name scope: the TPQ_XPROF device timeline attributes the
    # Pallas unpack to the same kernel family as the XLA fallback path
    with enable_x64(False), jax.named_scope("tpq.unpack"):
        return _bp_groups_jit(buf_dev, bp_base, width=width,
                              groups_pad=groups_pad,
                              interpret=bool(interpret))


def unpack_bits_pallas(buf, width: int, count: int, interpret: bool | None = None):
    # NOTE: deliberately NOT under scoped_x64 — the kernel is pure uint32 and
    # an x64 trace makes the grid index maps emit i64, which Mosaic refuses
    # to legalize ("func.return (i32, i64)").
    """Fixed-width LSB-first unpack via the Pallas tile kernel.

    ``buf`` uint8[...] packed bytes (numpy or jax); ``count`` values out.
    Drop-in for jax_kernels.unpack_bits on width 1..32.  ``interpret`` forces
    the Pallas interpreter (auto: on for non-TPU backends so CPU tests run).
    """
    if not 1 <= width <= 32:
        raise ValueError(f"unpack_bits_pallas supports widths 1..32, got {width}")
    if interpret is None:
        interpret = not pallas_available()
    planes = build_planes(buf, width, count)
    with jax.named_scope("tpq.unpack"):
        return _unpack_pallas_jit(planes, width=width, count=count,
                                  interpret=bool(interpret))


# ---------------------------------------------------------------------------
# fused decode megakernels (ISSUE 13 / ROADMAP direction 2): ONE pallas_call
# per ship route instead of the staged XLA chain.  The unfused routes run
# decompress-resolve → gather → widen → validity as separate XLA fusions
# with an HBM round trip between each stage; these kernels run the whole
# pipeline per value tile in VMEM and write the finished words once.
# Interpret mode (non-TPU backends) executes the SAME graph bit-identically,
# so tier-1 proves correctness on CPU; only compiled (Mosaic) runs are
# device-time measurements (pallas_mode in the ledger fingerprint records
# which one a banked run was).
# ---------------------------------------------------------------------------

_FUSED_TILE = 1024      # values per grid step, fused plain kernel
_FUSED_NS_TILE = 256    # values per grid step, fused narrow+snappy kernel
# fused narrow+snappy eligibility caps — kernel properties, shared by the
# device_reader builder and the bench/fuzz surfaces.  The op search is a
# per-tile broadcast compare over the whole (VMEM-resident) op table and
# the copy-chain chase is a static unroll, so streams beyond these bounds
# keep the unfused resolve path (pointer doubling scales, VMEM does not).
FUSED_MAX_OPS = 4096        # padded op-table rows held in VMEM per tile
FUSED_MAX_DEPTH = 16        # copy-chain depth unrolled in the kernel
FUSED_MAX_PAYLOAD = 4 << 20  # compressed payload bytes held in VMEM


def fused_count_pad(count: int) -> int:
    """Pad a value count to whole fused-plain tiles (bucketed first so the
    executable set stays bounded across chunks — same contract as
    :func:`bp_groups_pad`)."""
    from .jax_decode import _bucket_count

    b = _bucket_count(max(count, 1))
    return -(-b // _FUSED_TILE) * _FUSED_TILE


def fused_narrow_count_pad(count: int) -> int:
    """Tile padding for the fused narrow+snappy kernel."""
    from .jax_decode import _bucket_count

    b = _bucket_count(max(count, 1))
    return -(-b // _FUSED_NS_TILE) * _FUSED_NS_TILE


def _fused_plain_kernel(width, in_ref, nv_ref, out_ref):
    """One tile of the fused PLAIN fixed-width decode: (width, T) byte
    planes -> (T, width//4) finished u32 words, validity tail baked in.

    Same plane layout/indexing contract as :func:`_unpack_kernel` (leading-
    dim static plane reads — never strided u8 column slices).  The only
    dynamic input is ``nv`` (the real value count): lanes at or past it
    write zero words, which is the "validity" the unfused chain leaves to
    a separate tail-mask pass."""
    from jax.experimental import pallas as pl

    nv = nv_ref[0, 0]
    base = pl.program_id(0) * _FUSED_TILE
    pos = base + jax.lax.broadcasted_iota(jnp.int32, (_FUSED_TILE,), 0)
    keep = pos < nv
    for w in range(width // 4):
        acc = in_ref[4 * w, :].astype(jnp.uint32)
        for b in range(1, 4):
            acc = acc | (in_ref[4 * w + b, :].astype(jnp.uint32)
                         << jnp.uint32(8 * b))
        out_ref[:, w] = jnp.where(keep, acc, jnp.uint32(0))


@functools.partial(
    jax.jit, static_argnames=("width", "count_pad", "interpret")
)
def _fused_plain_jit(buf, vbase, nv, *, width, count_pad, interpret):
    from jax.experimental import pallas as pl

    raw = jax.lax.dynamic_slice(buf, (vbase,), (count_pad * width,))
    planes = raw.reshape(count_pad, width).T
    return pl.pallas_call(
        functools.partial(_fused_plain_kernel, width),
        out_shape=jax.ShapeDtypeStruct((count_pad, width // 4), jnp.uint32),
        grid=(count_pad // _FUSED_TILE,),
        in_specs=[
            pl.BlockSpec((width, _FUSED_TILE), lambda t: (0, t)),
            pl.BlockSpec((1, 1), lambda t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_FUSED_TILE, width // 4),
                               lambda t: (t, 0)),
        interpret=interpret,
    )(planes, nv.reshape(1, 1))


def fused_plain_words(buf_dev, vbase, n_valid, *, width: int,
                      count_pad: int, interpret: "bool | None" = None):
    """Fused PLAIN fixed-width decode: staged value bytes at ``vbase`` ->
    finished little-endian u32 words (``count_pad`` x ``width//4``), tail
    past ``n_valid`` zeroed — decode and validity in ONE device pass.

    ``count_pad`` must come from :func:`fused_count_pad`.  Traced x64-free
    (Mosaic refuses i64 grid index maps — see unpack_bits_pallas); callers
    bitcast the words to their value dtype under their own x64 scope.
    """
    if width not in (4, 8):
        raise ValueError(f"fused plain supports widths 4/8, got {width}")
    if count_pad % _FUSED_TILE:
        raise ValueError(f"count_pad {count_pad} not a multiple of "
                         f"{_FUSED_TILE}")
    interpret = resolve_interpret(interpret)
    if isinstance(vbase, (int, np.integer)):
        vbase = np.int32(vbase)
    if isinstance(n_valid, (int, np.integer)):
        n_valid = np.int32(n_valid)
    from .jax_kernels import enable_x64

    with enable_x64(False), jax.named_scope("tpq.fused"):
        return _fused_plain_jit(buf_dev, vbase, n_valid, width=width,
                                count_pad=count_pad,
                                interpret=bool(interpret))


def _fused_narrow_kernel(k, width, depth, out_pad, pay_ref, ends_ref,
                         asrc_ref, offs_ref, islit_ref, bias_ref, nv_ref,
                         out_ref):
    """One tile of the fused narrow+snappy decode: compressed payload +
    op tables -> finished biased u32 words, all in VMEM.

    Per output byte the snappy source resolves by a bounded copy-chain
    chase (``depth`` static unrolled rounds; the host's tag walk computed
    the exact max depth, so the unroll is exact, no loop carry): find the
    byte's op with a broadcast compare over the sorted op ends, literals
    read the payload directly, copies re-enter at their periodic source
    position — the same encoding :func:`jax_kernels.snappy_resolve`
    pointer-doubles over, chased per byte instead of materializing the
    output-space source map to HBM.  Widen (k little-endian bytes), re-bias
    (64-bit add as u32 word pairs with carry), and mask the validity tail —
    the whole unfused stage chain, one pass."""
    from jax.experimental import pallas as pl

    ends = ends_ref[:]
    asrc = asrc_ref[:]
    offs = offs_ref[:]
    islit = islit_ref[:]
    n_ops = ends.shape[0]
    nv = nv_ref[0, 0]
    base = pl.program_id(0) * _FUSED_NS_TILE
    vpos = base + jax.lax.broadcasted_iota(jnp.int32, (_FUSED_NS_TILE,), 0)
    keep = vpos < nv
    byte_vals = []
    # every scalar below is an EXPLICIT i32: the interpret-mode kernel
    # lowers inside the consumer's (x64-enabled) module, where a bare
    # Python int becomes a weak i64 constant that trips the lowering's
    # clip/minimum signatures (same discipline as _unpack_kernel's u32s)
    i32 = jnp.int32
    for b in range(k):
        p = jnp.clip(vpos * i32(k) + i32(b), i32(0), i32(out_pad - 1))
        src = jnp.zeros((_FUSED_NS_TILE,), jnp.int32)
        done = jnp.zeros((_FUSED_NS_TILE,), jnp.bool_)
        for _ in range(depth + 1):
            # searchsorted(ends, p, 'right') as a broadcast compare: the
            # padded table is VMEM-resident (FUSED_MAX_OPS cap), sorted,
            # fill = out_pad so padded positions land on padded literals
            op = jnp.minimum(
                jnp.sum((ends[None, :] <= p[:, None]).astype(jnp.int32),
                        axis=1),
                i32(n_ops - 1))
            start = jnp.where(op > i32(0),
                              ends[jnp.maximum(op - i32(1), i32(0))], i32(0))
            within = p - start
            lit = islit[op] != 0
            hit = jnp.logical_and(lit, jnp.logical_not(done))
            src = jnp.where(hit, asrc[op] + within, src)
            done = jnp.logical_or(done, lit)
            # copies re-enter at the periodic source (overlapping RLE-style
            # copies map straight past their own op — snappy_resolve's form)
            p = jnp.where(lit, p,
                          asrc[op] + within % jnp.maximum(offs[op], i32(1)))
        idx = jnp.clip(src, i32(0), i32(pay_ref.shape[0] - 1))
        byte_vals.append(pay_ref[idx].astype(jnp.uint32))
    lo = byte_vals[0]
    for b in range(1, min(k, 4)):
        lo = lo | (byte_vals[b] << jnp.uint32(8 * b))
    if width == 4:
        out_ref[:, 0] = jnp.where(keep, bias_ref[0, 0] + lo, jnp.uint32(0))
        return
    hi = jnp.zeros((_FUSED_NS_TILE,), jnp.uint32)
    for b in range(4, k):
        hi = hi | (byte_vals[b] << jnp.uint32(8 * (b - 4)))
    lo_sum = bias_ref[0, 0] + lo
    carry = (lo_sum < lo).astype(jnp.uint32)
    hi_sum = bias_ref[0, 1] + hi + carry
    out_ref[:, 0] = jnp.where(keep, lo_sum, jnp.uint32(0))
    out_ref[:, 1] = jnp.where(keep, hi_sum, jnp.uint32(0))


@functools.partial(
    jax.jit,
    static_argnames=("k", "width", "depth", "count_pad", "out_pad",
                     "interpret"),
)
def _fused_narrow_jit(payload, ends, asrc, offs, islit, bias2, nv, *, k,
                      width, depth, count_pad, out_pad, interpret):
    from jax.experimental import pallas as pl

    n_ops = ends.shape[0]
    ppad = payload.shape[0]
    words = width // 4
    whole = lambda n: pl.BlockSpec((n,), lambda t: (0,))  # noqa: E731
    return pl.pallas_call(
        functools.partial(_fused_narrow_kernel, k, width, depth, out_pad),
        out_shape=jax.ShapeDtypeStruct((count_pad, words), jnp.uint32),
        grid=(count_pad // _FUSED_NS_TILE,),
        in_specs=[
            whole(ppad), whole(n_ops), whole(n_ops), whole(n_ops),
            whole(n_ops),
            pl.BlockSpec((1, 2), lambda t: (0, 0)),
            pl.BlockSpec((1, 1), lambda t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_FUSED_NS_TILE, words), lambda t: (t, 0)),
        interpret=interpret,
    )(payload, ends, asrc, offs, islit, bias2, nv)


def fused_narrow_words(payload, ends, asrc, offs, islit, bias2, n_valid, *,
                       k: int, width: int, depth: int, count_pad: int,
                       out_pad: int, interpret: "bool | None" = None):
    """Fused narrow+snappy decode (ship.py ROUTE_FUSED_NARROW_SNAPPY):
    decompress-resolve, gather, widen, re-bias, and validity in ONE
    pallas pass over the compressed narrow transcode.

    ``payload`` u8[ppad] — the staged compressed bytes (VMEM-resident,
    FUSED_MAX_PAYLOAD cap); ``ends``/``asrc``/``offs``/``islit`` the
    padded op tables with PAYLOAD-RELATIVE literal sources (the fused
    builder packs its own tables — the staged-chain tables carry absolute
    staged coordinates); ``bias2`` u32[1, 2] little-endian (lo, hi) words
    of the narrow minimum; ``depth`` the exact max copy-chain depth from
    the host tag walk (FUSED_MAX_DEPTH cap).  Returns u32[count_pad,
    width//4] finished words, tail past ``n_valid`` zeroed; callers
    bitcast under their own x64 scope.  Traced x64-free (Mosaic i64 grid
    maps — see unpack_bits_pallas)."""
    if width not in (4, 8) or not 1 <= k <= width:
        raise ValueError(f"fused narrow: bad k={k}/width={width}")
    if count_pad % _FUSED_NS_TILE:
        raise ValueError(f"count_pad {count_pad} not a multiple of "
                         f"{_FUSED_NS_TILE}")
    if depth > FUSED_MAX_DEPTH:
        raise ValueError(f"depth {depth} over FUSED_MAX_DEPTH")
    interpret = resolve_interpret(interpret)
    if isinstance(n_valid, (int, np.integer)):
        n_valid = np.int32(n_valid)
    from .jax_kernels import enable_x64

    with enable_x64(False), jax.named_scope("tpq.fused"):
        return _fused_narrow_jit(
            payload, ends, asrc, offs, islit, bias2,
            jnp.asarray(n_valid, jnp.int32).reshape(1, 1), k=k, width=width,
            depth=depth, count_pad=count_pad, out_pad=out_pad,
            interpret=bool(interpret))
