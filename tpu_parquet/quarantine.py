"""Data-error containment: policy engine, error budget, quarantine ledger.

PR 7 made the *transport* fault-tolerant (iostore.py retries/deadlines);
this module is the *data* half: a single corrupt page, out-of-range
dictionary index, or truncated chunk used to raise a bare ``ParquetError``
and kill the whole scan — a multi-hour ``DataLoader`` epoch with it.  The
production-loader invariant (ROADMAP north star, directions 1/3/4) is that
one bad unit in a petabyte-scale file set degrades a run *with exact
accounting* instead of aborting it.  Three pieces:

- **Error policy** (``TPQ_ON_DATA_ERROR`` / ``on_data_error=`` on
  ``FileReader`` / ``DeviceFileReader`` / ``scan_files`` / ``DataLoader``):

  - ``raise``      the historical behavior (default) — first data error
    aborts the scan;
  - ``skip_unit``  quarantine the failing (file, row group) unit, keep
    scanning — readers skip the group, the loader drops the unit from the
    epoch stream *deterministically* (the skip is recorded in the
    checkpoint blob, so save→restore→iterate replays the identical batch
    stream including the skips);
  - ``skip_file``  quarantine the failing unit AND every later unit of the
    same file — for corruption patterns where one bad page predicts more.

- **Error budget** (``TPQ_DATA_ERROR_BUDGET``, ``"<count>"`` or
  ``"<count>,<fraction>"``): containment is bounded.  When the number of
  contained errors exceeds the absolute count, or the fraction of a scan's
  units, :class:`~tpu_parquet.errors.DataIntegrityError` aborts the scan
  carrying the full structured record list — a file set failing everywhere
  must fail loudly, not skip itself to an empty epoch.

- **Quarantine ledger** (:class:`QuarantineLog`): one structured record per
  failure — file, row group, column, page ordinal, byte offset, exception
  class, message — kept in memory, optionally appended to a JSONL file
  (``TPQ_QUARANTINE_LOG``), folded into ``obs.StatsRegistry`` as the
  ``data_errors`` section, sampled as a ``data_errors`` counter track, and
  summarized by ``pq_tool quarantine <log>``.

The context that makes a record useful at fleet scale (WHICH file, column,
row group, page) is attached to the exception itself as it unwinds:
:func:`error_context` annotates any ``ParquetError`` crossing it with the
decode site's coordinates (``exc.data_context``) and rewrites the message
once — so a bare CRC mismatch reads ``page CRC mismatch ... [file=...
column=... row_group=... page=...]`` wherever it lands.

Validation itself is promoted to a default-on cheap tier:
:func:`resolve_validate` resolves the readers' ``validate_crc=None``
default to ``TPQ_VALIDATE`` (default ``crc``: verify page CRCs *when the
writer recorded them* — files without CRCs pay one attribute check).  The
decode-time structural sanity checks (dict indices in range, level counts
vs ``num_values``, declared-vs-actual payload sizes) are always on.
"""

from __future__ import annotations

import json
import os
import threading
from contextlib import contextmanager
from typing import Optional

from .errors import DataIntegrityError, ParquetError

__all__ = [
    "ErrorBudget", "Quarantine", "QuarantineLog", "annotate_data_error",
    "corrupt_bytes", "error_context", "resolve_policy", "resolve_validate",
    "summarize_quarantine_log",
]

POLICIES = ("raise", "skip_unit", "skip_file")


def _warn_once(name: str, raw: str, fallback) -> None:
    from .obs import warn_env_once

    warn_env_once(name, raw, fallback)


def resolve_policy(policy=None) -> str:
    """Resolve an ``on_data_error=`` kwarg (strict) or the
    ``TPQ_ON_DATA_ERROR`` env (degrades to ``raise`` with one warning —
    an env typo must never change every reader construction into a raise,
    the TPQ_HANG_POLICY contract)."""
    if policy is not None:
        p = str(policy)
        if p not in POLICIES:
            raise ValueError(
                f"on_data_error must be one of {POLICIES}, got {policy!r}")
        return p
    raw = os.environ.get("TPQ_ON_DATA_ERROR", "")
    if not raw:
        return "raise"
    if raw not in POLICIES:
        _warn_once("TPQ_ON_DATA_ERROR", raw, "raise")
        return "raise"
    return raw


_VALIDATE_ON = ("crc", "on", "1", "true")
_VALIDATE_OFF = ("off", "0", "false", "none")


def resolve_validate(validate_crc=None) -> bool:
    """Resolve a reader's ``validate_crc`` option to a bool.

    ``None`` (the default everywhere since round 13) resolves through
    ``TPQ_VALIDATE``, whose default is ``crc`` — page CRCs are verified
    *when present* (files written without ``write_crc=True`` carry none
    and pay one attribute check per page).  Explicit ``False``/``"off"``
    opts out; ``True``/``"crc"`` forces the historical opt-in value.
    Kwarg strings are strict; a malformed env degrades to the default
    with one warning.
    """
    if validate_crc is None:
        raw = os.environ.get("TPQ_VALIDATE", "crc").lower()
        if raw in _VALIDATE_ON:
            return True
        if raw in _VALIDATE_OFF:
            return False
        _warn_once("TPQ_VALIDATE", raw, "crc")
        return True
    if isinstance(validate_crc, bool):
        return validate_crc
    v = str(validate_crc).lower()
    if v in _VALIDATE_ON:
        return True
    if v in _VALIDATE_OFF:
        return False
    raise ValueError(
        f"validate_crc must be a bool, 'crc', or 'off'; got {validate_crc!r}")


# ---------------------------------------------------------------------------
# exception context annotation
# ---------------------------------------------------------------------------

# record keys in report order; "error"/"message" are appended by note()
_CTX_KEYS = ("file", "column", "row_group", "page", "offset", "unit",
             "epoch")


def annotate_data_error(exc: BaseException, **ctx) -> BaseException:
    """Attach decode-site coordinates to ``exc`` and rewrite its message.

    Inner frames win: a field already present (set closer to the failure)
    is never overwritten by an outer, vaguer one.  The original message is
    kept on the exception and recomposed, so nesting N contexts yields ONE
    ``[file=... column=...]`` suffix, not N.
    """
    dc = getattr(exc, "data_context", None)
    if dc is None:
        dc = {}
        exc.data_context = dc
        exc._tpq_base_msg = str(exc)
    for k, v in ctx.items():
        if v is not None and k not in dc:
            dc[k] = v
    suffix = " ".join(f"{k}={dc[k]}" for k in _CTX_KEYS if k in dc)
    if suffix and exc.args:
        exc.args = (f"{exc._tpq_base_msg} [{suffix}]",) + exc.args[1:]
    return exc


@contextmanager
def error_context(**ctx):
    """Re-raise any ``ParquetError`` crossing this block annotated with
    ``ctx`` (see :func:`annotate_data_error`) — the one mechanism that puts
    file/column/row-group/page into every decode raise, CRC mismatches
    included, without threading strings through every kernel."""
    try:
        yield
    except ParquetError as e:
        raise annotate_data_error(e, **ctx)


# ---------------------------------------------------------------------------
# budget + ledger + the engine
# ---------------------------------------------------------------------------

class ErrorBudget:
    """Bounds on contained data errors per scan.

    ``max_errors`` is an absolute record count; ``max_fraction`` bounds
    records as a fraction of the scan's unit total (only enforced when the
    seam knows its total — multi-file streaming scans may not).  A scan
    exceeding either raises :class:`~tpu_parquet.errors.DataIntegrityError`
    from the containment seam, carrying the record list.
    """

    def __init__(self, max_errors: int = 64, max_fraction: float = 0.5):
        self.max_errors = int(max_errors)
        self.max_fraction = float(max_fraction)

    @classmethod
    def from_env(cls) -> "ErrorBudget":
        raw = os.environ.get("TPQ_DATA_ERROR_BUDGET", "")
        if not raw:
            return cls()
        parts = raw.replace(":", ",").split(",")
        try:
            max_errors = int(parts[0])
            max_fraction = float(parts[1]) if len(parts) > 1 else 0.5
            if max_errors < 0 or not 0.0 <= max_fraction <= 1.0:
                raise ValueError(raw)
        except (TypeError, ValueError):
            _warn_once("TPQ_DATA_ERROR_BUDGET", raw, "64,0.5")
            return cls()
        return cls(max_errors, max_fraction)

    def allowed(self, total_units: "int | None") -> int:
        """The record count a scan over ``total_units`` may reach.

        The fraction bound rounds UP: a 1-unit scan under the default
        0.5 fraction may still contain its one error (flooring to zero
        would make small scans un-containable under every skip policy —
        only an explicit ``max_fraction=0`` means "contain nothing").
        """
        import math

        cap = self.max_errors
        if total_units is not None and total_units > 0:
            cap = min(cap, math.ceil(self.max_fraction * total_units))
        return max(cap, 0)


class QuarantineLog:
    """Structured record per contained failure (thread-safe, append-only).

    Records are JSON-safe dicts: file, row_group, column, page, offset,
    error (exception class), message — plus whatever the seam adds (unit,
    epoch).  With a path (``TPQ_QUARANTINE_LOG`` or explicit) each record
    is ALSO appended to a JSONL file as it happens, so a crashed run's
    ledger survives for ``pq_tool quarantine``.
    """

    def __init__(self, path: "str | None" = None):
        self.path = (path if path is not None
                     else os.environ.get("TPQ_QUARANTINE_LOG") or None)
        self.records: list[dict] = []
        self._lock = threading.Lock()

    def append(self, record: dict) -> None:
        line = None
        if self.path:
            line = json.dumps(record, sort_keys=True, default=repr)
        with self._lock:
            self.records.append(record)
            if line is not None:
                parent = os.path.dirname(self.path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                with open(self.path, "a") as f:
                    f.write(line + "\n")

    def __len__(self) -> int:
        with self._lock:
            return len(self.records)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self.records)


_engine_seq = iter(range(1, 1 << 62))


class Quarantine:
    """The containment engine one scan surface shares: policy + budget +
    ledger + counters.

    Seams call :meth:`note` with the caught ``ParquetError`` (context
    already attached by :func:`error_context`); it appends the record,
    emits a flight-recorder instant, and raises ``DataIntegrityError``
    when the budget is exhausted.  :meth:`note_unit_skipped` /
    :meth:`note_file_skipped` account the *collateral* skips (units
    dropped by ``skip_file`` without their own failure) — accounting,
    never new records, so "every injected corruption appears in the log,
    nothing else does" holds exactly.

    Shareable: ``scan_files`` passes ONE engine to every per-file reader;
    a ``DeviceFileReader`` shares its engine with its host ``FileReader``.
    """

    def __init__(self, policy=None, budget: "ErrorBudget | None" = None,
                 log: "QuarantineLog | None" = None,
                 log_path: "str | None" = None):
        from .obs import register_flight_source

        self.policy = resolve_policy(policy)
        self.budget = budget if budget is not None else ErrorBudget.from_env()
        self.log = log if log is not None else QuarantineLog(log_path)
        self._lock = threading.Lock()
        self._scan_errors = 0
        self._scan_records: list[dict] = []
        self._total_units: "int | None" = None
        self.units_skipped = 0
        self.rows_skipped = 0
        self.files_skipped = 0
        self.by_class: dict[str, int] = {}
        # a wedge/crash dump must carry the quarantine state — including
        # the FIRST bad (file, column, page) for the autopsy verdict
        register_flight_source(f"quarantine[{next(_engine_seq)}]", self,
                               "sample")

    @property
    def contains(self) -> bool:
        """True when data errors are contained (any policy but ``raise``)."""
        return self.policy != "raise"

    def begin_scan(self, total_units: "int | None" = None) -> None:
        """Scan boundary: reset the per-scan budget accounting and (when
        known) pin the fraction denominator.  The cumulative ledger and
        skip counters survive — they are the run's history."""
        with self._lock:
            self._scan_errors = 0
            self._scan_records = []
            self._total_units = (int(total_units)
                                 if total_units is not None else None)

    def note(self, exc: BaseException, **ctx) -> dict:
        """Record one contained failure; raises ``DataIntegrityError`` when
        the scan's budget is exhausted.  ``ctx`` fills record fields the
        exception's own ``data_context`` did not already carry."""
        dc = dict(getattr(exc, "data_context", None) or {})
        for k, v in ctx.items():
            if v is not None and k not in dc:
                dc[k] = v
        record = {k: dc[k] for k in _CTX_KEYS if k in dc}
        record["error"] = type(exc).__name__
        record["message"] = str(exc)[:500]
        self.log.append(record)
        with self._lock:
            self._scan_errors += 1
            self._scan_records.append(record)
            errors, records = self._scan_errors, list(self._scan_records)
            total = self._total_units
            cls = record["error"]
            self.by_class[cls] = self.by_class.get(cls, 0) + 1
        from .obs import current_tracer

        tr = current_tracer()
        if tr.active:
            tr.instant("quarantine", **{k: v for k, v in record.items()
                                        if k != "message"})
        allowed = self.budget.allowed(total)
        if errors > allowed:
            raise DataIntegrityError(
                f"data-error budget exhausted: {errors} contained "
                f"error(s) exceed the allowed {allowed} "
                f"(TPQ_DATA_ERROR_BUDGET={self.budget.max_errors},"
                f"{self.budget.max_fraction:g}"
                + (f" over {total} units" if total is not None else "")
                + f"); last: {record['message']}",
                records=records,
            ) from exc
        return record

    def note_unit_skipped(self, rows: int = 0) -> None:
        with self._lock:
            self.units_skipped += 1
            self.rows_skipped += int(rows)

    def note_file_skipped(self) -> None:
        with self._lock:
            self.files_skipped += 1

    def progress(self) -> dict:
        """Monotonic counters for the ``data_errors`` sampler track."""
        with self._lock:
            return {
                "errors": len(self.log),
                "units_skipped": self.units_skipped,
                "rows_skipped": self.rows_skipped,
                "files_skipped": self.files_skipped,
            }

    def sample(self) -> dict:
        """Flight-source snapshot: the counters plus the first record —
        the (file, column, page) a data-corruption autopsy names."""
        out = self.progress()
        first = None
        recs = self.log.snapshot()
        if recs:
            first = recs[0]
        if first is not None:
            out["first"] = first
        return out

    def as_dict(self) -> dict:
        """The numeric ``data_errors`` section for ``obs.StatsRegistry``
        (counters only — multi-engine scans compose by addition; the
        record list lives in the log/JSONL, not the metrics tree)."""
        d = self.progress()
        with self._lock:
            d["by_class"] = dict(self.by_class)
        return d


# ---------------------------------------------------------------------------
# deterministic corruption (test/fault-injection helpers)
# ---------------------------------------------------------------------------

_M64 = (1 << 64) - 1

CORRUPT_MODES = ("bitflip", "zero", "truncate")


def _mix64(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def corrupt_bytes(data: bytes, mode: str, seed: int = 0) -> bytes:
    """Deterministically corrupt ``data`` — the shared payload mutator
    behind ``FaultSpec.corrupt`` and ``writer.corrupt_page``.

    Length-preserving by design: the corruption must ride THROUGH the
    transport layer (a short buffer would read as a torn fetch and be
    retried/re-classified as an IO fault) and be caught by the *integrity*
    tier.  Modes:

    - ``bitflip``   flip ``1 + len//512`` seeded bits (always changes);
    - ``zero``      zero a seeded span of up to half the payload;
    - ``truncate``  zero from a seeded point to the end (a truncated-then-
      padded page — the declared sizes stop matching the content).

    Pure in ``(data, mode, seed)``; key the seed per range (e.g.
    ``seed ^ offset``) for per-range determinism under concurrency.
    """
    if mode not in CORRUPT_MODES:
        raise ValueError(
            f"corrupt mode must be one of {CORRUPT_MODES}, got {mode!r}")
    n = len(data)
    if n == 0:
        return bytes(data)
    out = bytearray(data)
    h = _mix64((int(seed) & _M64) ^ 0xD6E8FEB86659FD93)
    if mode == "bitflip":
        for _ in range(1 + n // 512):
            h = _mix64(h)
            pos = h % n
            out[pos] ^= 1 << ((h >> 32) % 8)
    elif mode == "zero":
        h = _mix64(h)
        start = h % n
        length = 1 + (h >> 32) % (max(n // 2, 1))
        out[start : start + length] = b"\x00" * len(out[start : start + length])
    else:  # truncate
        h = _mix64(h)
        start = h % n
        out[start:] = b"\x00" * (n - start)
    return bytes(out)


# ---------------------------------------------------------------------------
# ledger summarization (the pq_tool quarantine backend)
# ---------------------------------------------------------------------------

def summarize_quarantine_log(records: list[dict]) -> dict:
    """Aggregate quarantine records into the report ``pq_tool quarantine``
    prints: totals, per-file / per-column / per-error-class counts, and
    the first record (the first bad file/column/page of the run)."""
    by_file: dict[str, int] = {}
    by_column: dict[str, int] = {}
    by_class: dict[str, int] = {}
    for r in records:
        if not isinstance(r, dict):
            continue
        by_file[str(r.get("file"))] = by_file.get(str(r.get("file")), 0) + 1
        if r.get("column") is not None:
            c = str(r["column"])
            by_column[c] = by_column.get(c, 0) + 1
        cls = str(r.get("error", "?"))
        by_class[cls] = by_class.get(cls, 0) + 1
    return {
        "records": len(records),
        "files": len(by_file),
        "by_file": dict(sorted(by_file.items(),
                               key=lambda kv: -kv[1])),
        "by_column": dict(sorted(by_column.items(),
                                 key=lambda kv: -kv[1])),
        "by_class": dict(sorted(by_class.items(),
                                key=lambda kv: -kv[1])),
        "first": (records[0] if records
                  and isinstance(records[0], dict) else None),
    }
