"""Schema layer: tree model, definition-language parser, validation, autoschema."""

from .core import (
    ColumnParameters,
    Schema,
    SchemaNode,
    SchemaError,
    data_column,
    group_column,
    list_column,
    map_column,
)

__all__ = [
    "Schema",
    "SchemaNode",
    "SchemaError",
    "ColumnParameters",
    "data_column",
    "group_column",
    "list_column",
    "map_column",
]
