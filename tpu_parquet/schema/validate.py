"""Schema validation: structural conventions + logical-type parameter checks.

Equivalent of the reference's Validate/ValidateStrict (schema_parser.go:724-1053):
LIST/MAP structural rules (with the Athena/Hive backward-compat shapes allowed in
lenient mode: ``bag``/``array_element`` naming, missing MAP value), DECIMAL
precision/scale vs physical type, INT bit widths, UUID/INTERVAL fixed lengths,
TIME/TIMESTAMP unit consistency, ENUM/JSON/UTF8 on binary only.
"""

from __future__ import annotations

from ..format import ConvertedType, FieldRepetitionType as FRT, Type
from .core import Schema, SchemaError, SchemaNode


class SchemaValidationError(SchemaError):
    pass


def validate(schema: Schema, strict: bool = False) -> None:
    """Raises SchemaValidationError on violations.  ``strict`` enforces the
    spec's exact LIST/MAP member naming (ValidateStrict parity); lenient mode
    accepts the compatibility shapes the reference tolerates."""
    root = schema.root
    if not root.children:
        raise SchemaValidationError("schema has no columns")
    for child in root.children:
        _validate_node(child, strict)


def validate_strict(schema: Schema) -> None:
    validate(schema, strict=True)


def _err(node: SchemaNode, msg: str) -> SchemaValidationError:
    return SchemaValidationError(f"column {node.flat_name() or node.name!r}: {msg}")


def _conv(node: SchemaNode):
    return node.converted_type


def _logical_which(node: SchemaNode):
    lt = node.logical_type
    return lt.which() if lt is not None else None


def _validate_node(node: SchemaNode, strict: bool) -> None:
    conv = _conv(node)
    which = _logical_which(node)

    if node.is_leaf:
        _validate_leaf(node, strict)
        return

    if conv == ConvertedType.LIST or which == "LIST":
        _validate_list(node, strict)
    elif conv == ConvertedType.MAP or which == "MAP":
        _validate_map(node, strict)
    for c in node.children or []:
        _validate_node(c, strict)


def _validate_list(node: SchemaNode, strict: bool) -> None:
    # spec: <rep> group name (LIST) { repeated group list { <element> } }
    if node.repetition == FRT.REPEATED:
        raise _err(node, "LIST group must not be repeated")
    if not node.children or len(node.children) != 1:
        raise _err(node, "LIST group must have exactly one child")
    rep_group = node.children[0]
    if rep_group.repetition != FRT.REPEATED:
        raise _err(node, "LIST child must be repeated")
    if strict:
        if rep_group.name != "list":
            raise _err(node, f"LIST child must be named 'list', got {rep_group.name!r}")
        if rep_group.is_leaf or len(rep_group.children) != 1:
            raise _err(node, "LIST repeated group must have exactly one child")
        if rep_group.children[0].name != "element":
            raise _err(
                node,
                f"LIST element must be named 'element', got {rep_group.children[0].name!r}",
            )
    else:
        # lenient: allow 2-level lists (repeated leaf/struct directly) and the
        # Athena 'bag'/'array_element' names (validateListLogicalType parity)
        if not rep_group.is_leaf and rep_group.children is not None and len(rep_group.children) == 0:
            raise _err(node, "LIST repeated group has no children")


def _validate_map(node: SchemaNode, strict: bool) -> None:
    # spec: <rep> group name (MAP) { repeated group key_value { key; value } }
    if node.repetition == FRT.REPEATED:
        raise _err(node, "MAP group must not be repeated")
    if not node.children or len(node.children) != 1:
        raise _err(node, "MAP group must have exactly one child")
    kv = node.children[0]
    if kv.repetition != FRT.REPEATED:
        raise _err(node, "MAP child must be repeated")
    if kv.is_leaf:
        raise _err(node, "MAP repeated child must be a group")
    names = [c.name for c in kv.children]
    if strict:
        if kv.name != "key_value":
            raise _err(node, f"MAP child must be named 'key_value', got {kv.name!r}")
        if names != ["key", "value"]:
            raise _err(node, f"MAP key_value must have key, value; got {names}")
    else:
        if "key" not in names:
            raise _err(node, "MAP key_value group is missing 'key'")
        if len(names) > 2:
            raise _err(node, f"MAP key_value has extra fields {names}")
    key = kv.child("key")
    if key is not None and key.repetition != FRT.REQUIRED:
        raise _err(node, "MAP key must be required")


_INT_CONV_WIDTHS = {
    ConvertedType.INT_8: (Type.INT32,), ConvertedType.INT_16: (Type.INT32,),
    ConvertedType.INT_32: (Type.INT32,), ConvertedType.INT_64: (Type.INT64,),
    ConvertedType.UINT_8: (Type.INT32,), ConvertedType.UINT_16: (Type.INT32,),
    ConvertedType.UINT_32: (Type.INT32,), ConvertedType.UINT_64: (Type.INT64,),
}


def _validate_leaf(node: SchemaNode, strict: bool) -> None:
    t = node.physical_type
    conv = _conv(node)
    which = _logical_which(node)
    lt = node.logical_type

    if t == Type.FIXED_LEN_BYTE_ARRAY and not node.type_length:
        raise _err(node, "FIXED_LEN_BYTE_ARRAY requires a length")

    if conv in (ConvertedType.UTF8, ConvertedType.ENUM, ConvertedType.JSON,
                ConvertedType.BSON) and t != Type.BYTE_ARRAY:
        raise _err(node, f"{conv.name} annotation requires binary, got {t.name}")
    if which in ("STRING", "ENUM", "JSON", "BSON") and t != Type.BYTE_ARRAY:
        raise _err(node, f"{which} logical type requires binary, got {t.name}")

    if conv in _INT_CONV_WIDTHS and t not in _INT_CONV_WIDTHS[conv]:
        raise _err(node, f"{conv.name} requires {_INT_CONV_WIDTHS[conv][0].name}")
    if which == "INTEGER":
        need = Type.INT64 if lt.INTEGER.bitWidth == 64 else Type.INT32
        if t != need:
            raise _err(node, f"INT({lt.INTEGER.bitWidth}) requires {need.name}")

    if conv == ConvertedType.DATE or which == "DATE":
        if t != Type.INT32:
            raise _err(node, "DATE requires int32")
    if conv == ConvertedType.TIME_MILLIS and t != Type.INT32:
        raise _err(node, "TIME_MILLIS requires int32")
    if conv == ConvertedType.TIME_MICROS and t != Type.INT64:
        raise _err(node, "TIME_MICROS requires int64")
    if conv in (ConvertedType.TIMESTAMP_MILLIS, ConvertedType.TIMESTAMP_MICROS):
        if t != Type.INT64:
            raise _err(node, f"{conv.name} requires int64")
    if which == "TIME":
        unit = lt.TIME.unit.which()
        need = Type.INT32 if unit == "MILLIS" else Type.INT64
        if t != need:
            raise _err(node, f"TIME({unit}) requires {need.name}")
    if which == "TIMESTAMP" and t != Type.INT64:
        raise _err(node, "TIMESTAMP requires int64")

    if which == "UUID":
        if t != Type.FIXED_LEN_BYTE_ARRAY or node.type_length != 16:
            raise _err(node, "UUID requires fixed_len_byte_array(16)")
    if conv == ConvertedType.INTERVAL:
        if t != Type.FIXED_LEN_BYTE_ARRAY or node.type_length != 12:
            raise _err(node, "INTERVAL requires fixed_len_byte_array(12)")

    if conv == ConvertedType.DECIMAL or which == "DECIMAL":
        precision = node.element.precision
        scale = node.element.scale
        if which == "DECIMAL":
            precision = lt.DECIMAL.precision
            scale = lt.DECIMAL.scale
        if precision is None or precision <= 0:
            raise _err(node, f"DECIMAL precision {precision} must be > 0")
        if scale is None or scale < 0 or scale > precision:
            raise _err(node, f"DECIMAL scale {scale} must be in [0, precision]")
        if t == Type.INT32 and precision > 9:
            raise _err(node, f"DECIMAL(int32) precision {precision} > 9")
        elif t == Type.INT64 and precision > 18:
            raise _err(node, f"DECIMAL(int64) precision {precision} > 18")
        elif t == Type.FIXED_LEN_BYTE_ARRAY:
            n = node.type_length
            max_digits = len(str(1 << (8 * n - 1))) - 1
            if precision > max_digits:
                raise _err(
                    node,
                    f"DECIMAL(fixed[{n}]) precision {precision} > {max_digits}",
                )
        elif t not in (Type.INT32, Type.INT64, Type.BYTE_ARRAY,
                       Type.FIXED_LEN_BYTE_ARRAY):
            raise _err(node, f"DECIMAL invalid on {t.name}")

    if conv == ConvertedType.MAP_KEY_VALUE and not strict:
        pass  # legacy annotation on leaf tolerated in lenient mode
