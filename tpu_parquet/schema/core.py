"""Schema tree: the Column hierarchy with def/rep level bookkeeping.

Equivalent of the reference's schema.go Column tree: a node per schema element,
max repetition/definition levels computed top-down (recursiveFix, schema.go:667-693),
flat-footer ⇄ tree conversion (readSchema/readColumnSchema/readGroupSchema,
schema.go:893-1015), column selection by path (schema.go:347-367), and the
LIST/MAP-convention constructors (schema.go:582-647).
"""

from __future__ import annotations

from ..errors import ParquetError

from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

from ..format import (
    ConvertedType,
    FieldRepetitionType,
    LogicalType,
    SchemaElement,
    Type,
)


class SchemaError(ParquetError):
    pass


@dataclass
class ColumnParameters:
    """Optional typing knobs for a column (ColumnParameters, schema.go parity)."""

    logical_type: Optional[LogicalType] = None
    converted_type: Optional[int] = None
    type_length: Optional[int] = None
    scale: Optional[int] = None
    precision: Optional[int] = None
    field_id: Optional[int] = None


class SchemaNode:
    """One node of the schema tree (reference `Column`, schema.go)."""

    __slots__ = (
        "element",
        "children",
        "parent",
        "max_def",
        "max_rep",
        "path",
        "leaf_index",
    )

    def __init__(self, element: SchemaElement, children: Optional[list] = None):
        self.element = element
        self.children: Optional[list[SchemaNode]] = children
        self.parent: Optional[SchemaNode] = None
        self.max_def = 0
        self.max_rep = 0
        self.path: tuple[str, ...] = ()
        self.leaf_index = -1

    # -- structure ----------------------------------------------------------

    @property
    def name(self) -> str:
        return self.element.name

    @property
    def is_leaf(self) -> bool:
        return self.children is None

    @property
    def repetition(self) -> FieldRepetitionType:
        rt = self.element.repetition_type
        try:
            return FieldRepetitionType(
                rt if rt is not None else FieldRepetitionType.REQUIRED
            )
        except ValueError:
            raise SchemaError(f"invalid repetition type {rt!r}") from None

    @property
    def physical_type(self) -> Optional[Type]:
        t = self.element.type
        if t is None:
            return None
        try:
            return Type(t)
        except ValueError:
            raise SchemaError(f"invalid physical type {t!r}") from None

    @property
    def type_length(self) -> int:
        return self.element.type_length or 0

    @property
    def converted_type(self) -> Optional[ConvertedType]:
        c = self.element.converted_type
        if c is None:
            return None
        try:
            return ConvertedType(c)
        except ValueError:
            raise SchemaError(f"invalid converted type {c!r}") from None

    @property
    def logical_type(self) -> Optional[LogicalType]:
        return self.element.logicalType

    def child(self, name: str) -> Optional["SchemaNode"]:
        if self.children is None:
            return None
        for c in self.children:
            if c.name == name:
                return c
        return None

    def flat_name(self) -> str:
        return ".".join(self.path)

    def __repr__(self):
        kind = (
            self.physical_type.name
            if self.is_leaf and self.physical_type is not None
            else "group"
        )
        return (
            f"SchemaNode({self.flat_name() or self.name!r}, {kind}, "
            f"{self.repetition.name}, maxR={self.max_rep}, maxD={self.max_def})"
        )


class Schema:
    """Schema tree + leaf registry (reference `schema` struct)."""

    def __init__(self, root: SchemaNode):
        self.root = root
        self.leaves: list[SchemaNode] = []
        self._selected: Optional[set[tuple[str, ...]]] = None
        self._fix()

    # -- construction -------------------------------------------------------

    @classmethod
    def from_file_metadata(cls, meta) -> "Schema":
        """Build the tree from the footer's flat element list (makeSchema,
        schema.go:1048-1079 + readSchema recursion)."""
        elems = meta.schema
        if not elems:
            raise SchemaError("empty schema")
        root_elem = elems[0]
        pos = 1

        def read_children(count: int) -> list[SchemaNode]:
            nonlocal pos
            out = []
            for _ in range(count):
                if pos >= len(elems):
                    raise SchemaError("schema element list shorter than num_children")
                e = elems[pos]
                pos += 1
                if not isinstance(e.name, str):
                    # a None/absent name breaks every path join downstream
                    # (readColumnSchema parity: "name is required")
                    raise SchemaError("schema element missing name")
                nc = e.num_children or 0
                if nc > 0:
                    node = SchemaNode(e, read_children(nc))
                else:
                    if e.type is None:
                        raise SchemaError(
                            f"leaf schema element {e.name!r} missing physical type"
                        )
                    node = SchemaNode(e, None)
                out.append(node)
            return out

        children = read_children(root_elem.num_children or 0)
        if pos != len(elems):
            raise SchemaError(
                f"schema has {len(elems) - pos} trailing elements beyond the tree"
            )
        root = SchemaNode(root_elem, children)
        return cls(root)

    def to_flat_elements(self) -> list[SchemaElement]:
        """Flatten back to the footer layout (depth-first preorder)."""
        out: list[SchemaElement] = []

        def visit(node: SchemaNode):
            e = node.element
            e.num_children = len(node.children) if node.children is not None else None
            out.append(e)
            for c in node.children or []:
                visit(c)

        visit(self.root)
        return out

    # -- level bookkeeping (recursiveFix, schema.go:667-693) ----------------

    def _fix(self):
        self.leaves = []

        def visit(node: SchemaNode, max_r: int, max_d: int, path: tuple[str, ...]):
            rep = node.repetition if node is not self.root else FieldRepetitionType.REQUIRED
            if node is not self.root:
                if rep == FieldRepetitionType.OPTIONAL:
                    max_d += 1
                elif rep == FieldRepetitionType.REPEATED:
                    max_d += 1
                    max_r += 1
                path = path + (node.name,)
            node.max_rep = max_r
            node.max_def = max_d
            node.path = path
            if node.is_leaf and node is not self.root:
                node.leaf_index = len(self.leaves)
                self.leaves.append(node)
            for c in node.children or []:
                c.parent = node
                visit(c, max_r, max_d, path)

        visit(self.root, 0, 0, ())

    # -- selection (SetSelectedColumns, schema.go:347-367) -------------------

    def set_selected(self, paths: Optional[Iterable[Sequence[str]]]) -> None:
        """Restrict decoding to the given column paths (None = all).

        A selected path selects the whole subtree under it.
        """
        if paths is None:
            self._selected = None
            return
        self._selected = {tuple(p) for p in paths}

    def is_selected(self, path: Sequence[str]) -> bool:
        if self._selected is None:
            return True
        path = tuple(path)
        for sel in self._selected:
            if path[: len(sel)] == sel or sel[: len(path)] == path:
                return True
        return False

    def selected_leaves(self) -> list[SchemaNode]:
        return [l for l in self.leaves if self.is_selected(l.path)]

    def selection_matches(self, paths) -> bool:
        """Would ``set_selected(paths)`` select at least one leaf?  Lets
        callers validate BEFORE mutating the live selection."""
        sel = {tuple(p) for p in paths}
        return any(
            l.path[: len(s)] == s or s[: len(l.path)] == l.path
            for l in self.leaves for s in sel
        )

    # -- lookup --------------------------------------------------------------

    def leaf_by_path(self, path: Sequence[str]) -> Optional[SchemaNode]:
        path = tuple(path)
        for l in self.leaves:
            if l.path == path:
                return l
        return None

    def node_by_path(self, path: Sequence[str]) -> Optional[SchemaNode]:
        node = self.root
        for part in path:
            node = node.child(part)
            if node is None:
                return None
        return node

    @property
    def num_columns(self) -> int:
        return len(self.leaves)

    def __repr__(self):
        return f"Schema({self.num_columns} leaf columns)"


# ---------------------------------------------------------------------------
# Programmatic constructors (NewDataColumn / NewListColumn / NewMapColumn,
# schema.go:570-647)
# ---------------------------------------------------------------------------

def _apply_params(e: SchemaElement, params: Optional[ColumnParameters]):
    if params is None:
        return
    if params.logical_type is not None:
        e.logicalType = params.logical_type
    if params.converted_type is not None:
        e.converted_type = int(params.converted_type)
    if params.type_length is not None:
        e.type_length = params.type_length
    if params.scale is not None:
        e.scale = params.scale
    if params.precision is not None:
        e.precision = params.precision
    if params.field_id is not None:
        e.field_id = params.field_id


def data_column(
    name: str,
    ptype: Type,
    repetition: FieldRepetitionType = FieldRepetitionType.REQUIRED,
    params: Optional[ColumnParameters] = None,
) -> SchemaNode:
    """A leaf data column (NewDataColumnWithParams semantics)."""
    e = SchemaElement(
        name=name, type=int(ptype), repetition_type=int(repetition)
    )
    _apply_params(e, params)
    if ptype == Type.FIXED_LEN_BYTE_ARRAY and not e.type_length:
        raise SchemaError("FIXED_LEN_BYTE_ARRAY requires type_length in params")
    return SchemaNode(e, None)


def group_column(
    name: str,
    children: list[SchemaNode],
    repetition: FieldRepetitionType = FieldRepetitionType.REQUIRED,
    params: Optional[ColumnParameters] = None,
) -> SchemaNode:
    e = SchemaElement(name=name, repetition_type=int(repetition))
    _apply_params(e, params)
    return SchemaNode(e, list(children))


def list_column(
    name: str,
    element: SchemaNode,
    repetition: FieldRepetitionType = FieldRepetitionType.OPTIONAL,
    params: Optional[ColumnParameters] = None,
) -> SchemaNode:
    """Spec-conventional LIST: <rep> group name (LIST) { repeated group list {
    <element> element } } (NewListColumn, schema.go:582-611)."""
    from ..format import ListType

    if element.name != "element":
        element.element.name = "element"
    lst = SchemaElement(
        name=name,
        repetition_type=int(repetition),
        converted_type=int(ConvertedType.LIST),
        logicalType=LogicalType(LIST=ListType()),
    )
    _apply_params(lst, params)
    inner = SchemaElement(
        name="list", repetition_type=int(FieldRepetitionType.REPEATED)
    )
    return SchemaNode(lst, [SchemaNode(inner, [element])])


def map_column(
    name: str,
    key: SchemaNode,
    value: SchemaNode,
    repetition: FieldRepetitionType = FieldRepetitionType.OPTIONAL,
    params: Optional[ColumnParameters] = None,
) -> SchemaNode:
    """Spec-conventional MAP: <rep> group name (MAP) { repeated group key_value {
    required <key>; <value> } } (NewMapColumn, schema.go:613-647)."""
    from ..format import MapType

    if key.repetition != FieldRepetitionType.REQUIRED:
        raise SchemaError("map key must be REQUIRED")
    key.element.name = "key"
    value.element.name = "value"
    mp = SchemaElement(
        name=name,
        repetition_type=int(repetition),
        converted_type=int(ConvertedType.MAP),
        logicalType=LogicalType(MAP=MapType()),
    )
    _apply_params(mp, params)
    kv = SchemaElement(
        name="key_value",
        repetition_type=int(FieldRepetitionType.REPEATED),
        converted_type=int(ConvertedType.MAP_KEY_VALUE),
    )
    return SchemaNode(mp, [SchemaNode(kv, [key, value])])


def build_schema(columns: list[SchemaNode], root_name: str = "msg") -> Schema:
    """Assemble a Schema from top-level columns."""
    root = SchemaNode(SchemaElement(name=root_name), list(columns))
    return Schema(root)
