"""Textual schema-definition language: parser + printer.

Same grammar as the reference (documented at parquetschema/schema_def.go:35-93 and
implemented by its lexer/parser in schema_parser.go:100-723):

    message ::= 'message' <identifier> '{' <column-definition>* '}'
    column  ::= ('required'|'optional'|'repeated')
                ( 'group' <id> [ '(' CONVERTED ')' ] '{' ... '}'
                | <type> <id> [ '(' LOGICAL ')' ] [ '=' <fieldid> ] ';' )
    type    ::= binary|boolean|float|double|int32|int64|int96
                |fixed_len_byte_array '(' N ')'

with parameterized logical annotations TIMESTAMP(unit,utc), TIME(unit,utc),
INT(bits,signed), DECIMAL(precision,scale), and the full converted-type name set.
The printer round-trips: parse(print(schema)) == schema.
"""

from __future__ import annotations

import re
from typing import Optional

from ..format import (
    ConvertedType,
    DateType,
    DecimalType,
    EnumType,
    FieldRepetitionType,
    IntType,
    JsonType,
    BsonType,
    ListType,
    LogicalType,
    MapType,
    SchemaElement,
    StringType,
    TimestampType,
    TimeType,
    TimeUnit,
    Type,
    UUIDType,
)
from .core import Schema, SchemaNode, SchemaError


class SchemaParseError(SchemaError):
    def __init__(self, msg: str, line: int = 0):
        super().__init__(f"line {line}: {msg}" if line else msg)
        self.line = line


_TYPES = {
    "binary": Type.BYTE_ARRAY,
    "boolean": Type.BOOLEAN,
    "float": Type.FLOAT,
    "double": Type.DOUBLE,
    "int32": Type.INT32,
    "int64": Type.INT64,
    "int96": Type.INT96,
    "fixed_len_byte_array": Type.FIXED_LEN_BYTE_ARRAY,
}
_TYPE_NAMES = {v: k for k, v in _TYPES.items()}

_TOKEN_RE = re.compile(r"[{}();,=]|[^\s{}();,=]+")


class _Lexer:
    """Tokens + line tracking (schemaLexer parity, schema_parser.go:100-263)."""

    def __init__(self, text: str):
        self.tokens: list[tuple[str, int]] = []
        for lineno, line in enumerate(text.splitlines(), 1):
            # strip #- and //-style comments (the reference has none, but they
            # cost nothing and schema files in the wild use them)
            for m in _TOKEN_RE.finditer(line.split("#")[0]):
                self.tokens.append((m.group(0), lineno))
        self.pos = 0

    def peek(self) -> Optional[str]:
        return self.tokens[self.pos][0] if self.pos < len(self.tokens) else None

    @property
    def line(self) -> int:
        i = min(self.pos, len(self.tokens) - 1)
        return self.tokens[i][1] if self.tokens else 0

    def next(self) -> str:
        if self.pos >= len(self.tokens):
            raise SchemaParseError("unexpected end of schema", self.line)
        tok, _ = self.tokens[self.pos]
        self.pos += 1
        return tok

    def expect(self, tok: str) -> None:
        got = self.next()
        if got != tok:
            raise SchemaParseError(f"expected {tok!r}, got {got!r}", self.line)


def parse_schema_definition(text: str) -> Schema:
    """ParseSchemaDefinition parity (schema_def.go:94)."""
    lx = _Lexer(text)
    lx.expect("message")
    name = lx.next()
    if name in ("{", "}", ";"):
        raise SchemaParseError(f"invalid message name {name!r}", lx.line)
    lx.expect("{")
    children = _parse_body(lx)
    lx.expect("}")
    if lx.peek() is not None:
        raise SchemaParseError(f"trailing content {lx.peek()!r}", lx.line)
    root = SchemaNode(SchemaElement(name=name), children)
    return Schema(root)


def _parse_body(lx: _Lexer) -> list[SchemaNode]:
    out = []
    while lx.peek() != "}":
        out.append(_parse_column(lx))
    return out


_REPETITIONS = {
    "required": FieldRepetitionType.REQUIRED,
    "optional": FieldRepetitionType.OPTIONAL,
    "repeated": FieldRepetitionType.REPEATED,
}


def _parse_column(lx: _Lexer) -> SchemaNode:
    rep_tok = lx.next()
    rep = _REPETITIONS.get(rep_tok)
    if rep is None:
        raise SchemaParseError(
            f"expected repetition (required/optional/repeated), got {rep_tok!r}",
            lx.line,
        )
    tok = lx.next()
    if tok == "group":
        name = lx.next()
        elem = SchemaElement(name=name, repetition_type=int(rep))
        if lx.peek() == "(":
            _parse_annotation(lx, elem, is_group=True)
        lx.expect("{")
        children = _parse_body(lx)
        lx.expect("}")
        if not children:
            raise SchemaParseError(f"group {name!r} has no children", lx.line)
        return SchemaNode(elem, children)
    # leaf field
    ptype = _TYPES.get(tok)
    if ptype is None:
        raise SchemaParseError(f"unknown type {tok!r}", lx.line)
    elem = SchemaElement(repetition_type=int(rep), type=int(ptype))
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        lx.expect("(")
        elem.type_length = _parse_int(lx)
        if elem.type_length <= 0:
            raise SchemaParseError(
                f"invalid fixed_len_byte_array length {elem.type_length}", lx.line
            )
        lx.expect(")")
    elem.name = lx.next()
    if elem.name in ("{", "}", ";", "(", ")"):
        raise SchemaParseError(f"invalid column name {elem.name!r}", lx.line)
    if lx.peek() == "(":
        _parse_annotation(lx, elem, is_group=False)
    if lx.peek() == "=":
        lx.next()
        elem.field_id = _parse_int(lx)
    lx.expect(";")
    return SchemaNode(elem, None)


def _parse_int(lx: _Lexer) -> int:
    tok = lx.next()
    try:
        return int(tok)
    except ValueError:
        raise SchemaParseError(f"expected number, got {tok!r}", lx.line) from None


def _parse_bool(lx: _Lexer) -> bool:
    tok = lx.next()
    if tok == "true":
        return True
    if tok == "false":
        return False
    raise SchemaParseError(f"expected true/false, got {tok!r}", lx.line)


_SIMPLE_CONVERTED = {e.name: e for e in ConvertedType}


def _parse_annotation(lx: _Lexer, elem: SchemaElement, is_group: bool) -> None:
    lx.expect("(")
    name = lx.next()
    lt = LogicalType()

    if name == "STRING":
        lt.STRING = StringType()
        elem.converted_type = int(ConvertedType.UTF8)
    elif name == "UTF8":
        lt.STRING = StringType()
        elem.converted_type = int(ConvertedType.UTF8)
    elif name == "DATE":
        lt.DATE = DateType()
        elem.converted_type = int(ConvertedType.DATE)
    elif name == "ENUM":
        lt.ENUM = EnumType()
        elem.converted_type = int(ConvertedType.ENUM)
    elif name == "JSON":
        lt.JSON = JsonType()
        elem.converted_type = int(ConvertedType.JSON)
    elif name == "BSON":
        lt.BSON = BsonType()
        elem.converted_type = int(ConvertedType.BSON)
    elif name == "UUID":
        lt.UUID = UUIDType()
    elif name == "LIST":
        lt.LIST = ListType()
        elem.converted_type = int(ConvertedType.LIST)
    elif name == "MAP":
        lt.MAP = MapType()
        elem.converted_type = int(ConvertedType.MAP)
    elif name == "MAP_KEY_VALUE":
        elem.converted_type = int(ConvertedType.MAP_KEY_VALUE)
        lt = None
    elif name in ("TIMESTAMP", "TIME"):
        lx.expect("(")
        unit_tok = lx.next()
        unit = {
            "MILLIS": TimeUnit.millis, "MICROS": TimeUnit.micros,
            "NANOS": TimeUnit.nanos,
        }.get(unit_tok)
        if unit is None:
            raise SchemaParseError(f"invalid time unit {unit_tok!r}", lx.line)
        lx.expect(",")
        utc = _parse_bool(lx)
        lx.expect(")")
        if name == "TIMESTAMP":
            lt.TIMESTAMP = TimestampType(isAdjustedToUTC=utc, unit=unit())
            elem.converted_type = {
                "MILLIS": int(ConvertedType.TIMESTAMP_MILLIS),
                "MICROS": int(ConvertedType.TIMESTAMP_MICROS),
            }.get(unit_tok)
        else:
            lt.TIME = TimeType(isAdjustedToUTC=utc, unit=unit())
            elem.converted_type = {
                "MILLIS": int(ConvertedType.TIME_MILLIS),
                "MICROS": int(ConvertedType.TIME_MICROS),
            }.get(unit_tok)
    elif name == "INT":
        lx.expect("(")
        bits = _parse_int(lx)
        if bits not in (8, 16, 32, 64):
            raise SchemaParseError(f"invalid INT bit width {bits}", lx.line)
        lx.expect(",")
        signed = _parse_bool(lx)
        lx.expect(")")
        lt.INTEGER = IntType(bitWidth=bits, isSigned=signed)
        elem.converted_type = int(
            ConvertedType[f"{'INT' if signed else 'UINT'}_{bits}"]
        )
    elif name == "DECIMAL":
        lx.expect("(")
        precision = _parse_int(lx)
        lx.expect(",")
        scale = _parse_int(lx)
        lx.expect(")")
        lt.DECIMAL = DecimalType(precision=precision, scale=scale)
        elem.converted_type = int(ConvertedType.DECIMAL)
        elem.precision = precision
        elem.scale = scale
    elif name in _SIMPLE_CONVERTED:
        # bare converted-type names (TIME_MILLIS, UINT_8, INTERVAL, ...)
        elem.converted_type = int(_SIMPLE_CONVERTED[name])
        lt = None
    else:
        raise SchemaParseError(f"unknown annotation {name!r}", lx.line)
    if lt is not None and lt.which() is not None:
        elem.logicalType = lt
    lx.expect(")")


# ---------------------------------------------------------------------------
# Printer (round-trippable String(), schema_def.go parity)
# ---------------------------------------------------------------------------

def _annotation_str(elem: SchemaElement) -> str:
    lt = elem.logicalType
    if lt is not None:
        which = lt.which()
        if which == "STRING":
            return " (STRING)"
        if which == "DATE":
            return " (DATE)"
        if which == "ENUM":
            return " (ENUM)"
        if which == "JSON":
            return " (JSON)"
        if which == "BSON":
            return " (BSON)"
        if which == "UUID":
            return " (UUID)"
        if which == "LIST":
            return " (LIST)"
        if which == "MAP":
            return " (MAP)"
        if which == "TIMESTAMP":
            t = lt.TIMESTAMP
            unit = t.unit.which()
            return f" (TIMESTAMP({unit},{'true' if t.isAdjustedToUTC else 'false'}))"
        if which == "TIME":
            t = lt.TIME
            unit = t.unit.which()
            return f" (TIME({unit},{'true' if t.isAdjustedToUTC else 'false'}))"
        if which == "INTEGER":
            i = lt.INTEGER
            return f" (INT({i.bitWidth},{'true' if i.isSigned else 'false'}))"
        if which == "DECIMAL":
            d = lt.DECIMAL
            return f" (DECIMAL({d.precision},{d.scale}))"
    if elem.converted_type is not None:
        conv = ConvertedType(elem.converted_type)
        if conv == ConvertedType.DECIMAL:
            # bare (DECIMAL) is unparseable; legacy columns carry p/s on the element
            return f" (DECIMAL({elem.precision or 0},{elem.scale or 0}))"
        return f" ({conv.name})"
    return ""


def schema_to_string(schema: Schema) -> str:
    lines = [f"message {schema.root.name} {{"]

    def visit(node: SchemaNode, indent: int):
        pad = "  " * indent
        rep = node.repetition.name.lower()
        if not node.is_leaf:
            lines.append(
                f"{pad}{rep} group {node.name}{_annotation_str(node.element)} {{"
            )
            for c in node.children:
                visit(c, indent + 1)
            lines.append(f"{pad}}}")
            return
        t = node.physical_type
        tname = _TYPE_NAMES[t]
        if t == Type.FIXED_LEN_BYTE_ARRAY:
            tname += f"({node.type_length})"
        fid = (
            f" = {node.element.field_id}" if node.element.field_id is not None else ""
        )
        lines.append(
            f"{pad}{rep} {tname} {node.name}{_annotation_str(node.element)}{fid};"
        )

    for c in schema.root.children or []:
        visit(c, 1)
    lines.append("}")
    return "\n".join(lines) + "\n"
