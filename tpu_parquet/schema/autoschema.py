"""Autoschema: generate a parquet schema from Python type annotations.

Equivalent of the reference's reflection-based autoschema (parquetschema/
autoschema/gen.go:17-398, Go struct → schema definition): here a dataclass (or
any class with type annotations) maps to a message schema —

    int → int64 INT(64,true)         bool → boolean
    float → double                   str → binary (STRING)
    bytes → binary                   datetime.datetime → int64 TIMESTAMP(NANOS)
    datetime.date → int32 (DATE)     datetime.time → int64 TIME(NANOS)
    uuid.UUID → fixed(16) (UUID)     Annotated fixed bytes → fixed(N)
    Optional[T] → optional           list[T] → LIST group
    dict[K,V] → MAP group            nested dataclass → group
    np.int32/float32/... → matching physical types

Field naming mirrors floor's rules (floor/fieldname.go:8-19): a ``parquet``
metadata key in dataclass field metadata overrides, else the lowercased name.
"""

from __future__ import annotations

import dataclasses
import datetime
import decimal as _decimal
import typing
import uuid as uuid_mod
from typing import Optional

import numpy as np

from ..format import (
    ConvertedType,
    DateType,
    FieldRepetitionType as FRT,
    IntType,
    LogicalType,
    SchemaElement,
    StringType,
    TimestampType,
    TimeType,
    TimeUnit,
    Type,
    UUIDType,
)
from .core import Schema, SchemaError, SchemaNode


class AutoSchemaError(SchemaError):
    pass


def schema_from_type(cls, root_name: str = "autoschema") -> Schema:
    """GenerateSchema parity: python class w/ annotations → Schema."""
    hints = typing.get_type_hints(cls, include_extras=True)
    if not hints:
        raise AutoSchemaError(f"{cls!r} has no type annotations")
    field_meta = {}
    if dataclasses.is_dataclass(cls):
        field_meta = {f.name: f.metadata for f in dataclasses.fields(cls)}
    children = []
    for name, hint in hints.items():
        pq_name = field_meta.get(name, {}).get("parquet", name.lower())
        children.append(_field_node(pq_name, hint))
    root = SchemaNode(SchemaElement(name=root_name), children)
    return Schema(root)


def _strip_optional(hint):
    origin = typing.get_origin(hint)
    if origin is typing.Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if len(args) == 1 and type(None) in typing.get_args(hint):
            return args[0], True
    return hint, False


def _field_node(name: str, hint, repetition: Optional[FRT] = None) -> SchemaNode:
    hint, optional = _strip_optional(hint)
    if repetition is None:
        repetition = FRT.OPTIONAL if optional else FRT.REQUIRED
    origin = typing.get_origin(hint)

    if origin in (list, typing.List):
        (elem_hint,) = typing.get_args(hint) or (int,)
        elem = _field_node("element", elem_hint)
        from ..format import ListType

        lst = SchemaElement(
            name=name,
            repetition_type=int(repetition),
            converted_type=int(ConvertedType.LIST),
            logicalType=LogicalType(LIST=ListType()),
        )
        inner = SchemaElement(name="list", repetition_type=int(FRT.REPEATED))
        return SchemaNode(lst, [SchemaNode(inner, [elem])])

    if origin in (dict, typing.Dict):
        args = typing.get_args(hint) or (str, int)
        key = _field_node("key", args[0], repetition=FRT.REQUIRED)
        value = _field_node("value", args[1])
        from ..format import MapType

        mp = SchemaElement(
            name=name,
            repetition_type=int(repetition),
            converted_type=int(ConvertedType.MAP),
            logicalType=LogicalType(MAP=MapType()),
        )
        kv = SchemaElement(
            name="key_value", repetition_type=int(FRT.REPEATED),
        )
        return SchemaNode(mp, [SchemaNode(kv, [key, value])])

    if dataclasses.is_dataclass(hint) or (
        isinstance(hint, type) and typing.get_type_hints(hint) and not _scalar(hint)
    ):
        sub = schema_from_type(hint, root_name=name)
        elem = SchemaElement(name=name, repetition_type=int(repetition))
        return SchemaNode(elem, sub.root.children)

    return _scalar_node(name, hint, repetition)


def _scalar(hint) -> bool:
    return hint in (
        int, float, str, bytes, bool,
        datetime.datetime, datetime.date, datetime.time, uuid_mod.UUID,
    ) or (isinstance(hint, type) and issubclass(hint, np.generic))


def _scalar_node(name: str, hint, repetition: FRT) -> SchemaNode:
    e = SchemaElement(name=name, repetition_type=int(repetition))
    if hint is bool or (isinstance(hint, type) and issubclass(hint, np.bool_)):
        e.type = int(Type.BOOLEAN)
    elif hint is int or (isinstance(hint, type) and issubclass(hint, np.int64)):
        e.type = int(Type.INT64)
        e.converted_type = int(ConvertedType.INT_64)
        e.logicalType = LogicalType(INTEGER=IntType(bitWidth=64, isSigned=True))
    elif isinstance(hint, type) and issubclass(hint, np.int32):
        e.type = int(Type.INT32)
        e.converted_type = int(ConvertedType.INT_32)
        e.logicalType = LogicalType(INTEGER=IntType(bitWidth=32, isSigned=True))
    elif isinstance(hint, type) and issubclass(hint, (np.uint32, np.uint64)):
        bits = 32 if issubclass(hint, np.uint32) else 64
        e.type = int(Type.INT32 if bits == 32 else Type.INT64)
        e.converted_type = int(ConvertedType[f"UINT_{bits}"])
        e.logicalType = LogicalType(INTEGER=IntType(bitWidth=bits, isSigned=False))
    elif isinstance(hint, type) and issubclass(hint, np.float32):
        e.type = int(Type.FLOAT)
    elif hint is float or (isinstance(hint, type) and issubclass(hint, np.floating)):
        e.type = int(Type.DOUBLE)
    elif hint is str:
        e.type = int(Type.BYTE_ARRAY)
        e.converted_type = int(ConvertedType.UTF8)
        e.logicalType = LogicalType(STRING=StringType())
    elif hint is bytes:
        e.type = int(Type.BYTE_ARRAY)
    elif hint is datetime.datetime:
        e.type = int(Type.INT64)
        e.logicalType = LogicalType(
            TIMESTAMP=TimestampType(isAdjustedToUTC=True, unit=TimeUnit.nanos())
        )
    elif hint is datetime.date:
        e.type = int(Type.INT32)
        e.converted_type = int(ConvertedType.DATE)
        e.logicalType = LogicalType(DATE=DateType())
    elif hint is datetime.time:
        e.type = int(Type.INT64)
        e.logicalType = LogicalType(
            TIME=TimeType(isAdjustedToUTC=True, unit=TimeUnit.nanos())
        )
    elif hint is uuid_mod.UUID:
        e.type = int(Type.FIXED_LEN_BYTE_ARRAY)
        e.type_length = 16
        e.logicalType = LogicalType(UUID=UUIDType())
    elif _decimal is not None and hint is _decimal.Decimal:
        # no precision/scale in the type: use the widest common default
        from ..format import DecimalType

        e.type = int(Type.BYTE_ARRAY)
        e.converted_type = int(ConvertedType.DECIMAL)
        e.precision, e.scale = 38, 18
        e.logicalType = LogicalType(DECIMAL=DecimalType(precision=38, scale=18))
    else:
        raise AutoSchemaError(f"field {name!r}: unsupported type {hint!r}")
    return SchemaNode(e, None)
