"""Logical-view conversion: raw nested rows ⇄ pythonic values.

The raw row model (assembly.py) mirrors the physical schema: LIST columns appear as
``{"list": [{"element": v}, ...]}`` and MAP columns as
``{"key_value": [{"key": k, "value": v}, ...]}`` — the same shape the reference's
row maps have, which its floor layer then unwraps (floor/interfaces/unmarshaller.go
LIST/MAP traversal, incl. Athena ``bag``/``array_element`` compatibility names).
This module is that unwrapping for dict rows: LIST → python list, MAP → python
dict-as-list-of-pairs (dict when keys are hashable), honoring the same
structural conventions.
"""

from __future__ import annotations

from typing import Any, Optional

from .format import ConvertedType
from .schema.core import SchemaNode


def is_string_leaf(leaf: SchemaNode) -> bool:
    """Leaf is logically a UTF-8 string (shared by row assembly and columnar
    pylist conversion so the two APIs can never disagree on str-vs-bytes)."""
    ct = leaf.converted_type
    lt = leaf.logical_type
    return ct in (ConvertedType.UTF8, ConvertedType.ENUM, ConvertedType.JSON) or (
        lt is not None and lt.which() in ("STRING", "ENUM", "JSON")
    )


def _repeated_group_is_element(lst_name: str, rep_group: SchemaNode) -> bool:
    """parquet-format LogicalTypes.md backward-compat rule: inside a LIST group,
    the repeated group is itself the element (2-level list of structs) when it
    has multiple fields, or is named ``array``, or ``<list-name>_tuple``."""
    if rep_group.children is None:
        return False
    if len(rep_group.children) != 1:
        return True
    return rep_group.name == "array" or rep_group.name == f"{lst_name}_tuple"


def _is_list_node(node: SchemaNode) -> bool:
    if node.is_leaf:
        return False
    ct = node.converted_type
    lt = node.logical_type
    return ct == ConvertedType.LIST or (lt is not None and lt.which() == "LIST")


def _is_map_node(node: SchemaNode) -> bool:
    if node.is_leaf:
        return False
    ct = node.converted_type
    lt = node.logical_type
    return ct in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE) or (
        lt is not None and lt.which() == "MAP"
    )


def unwrap_value(node: SchemaNode, value: Any) -> Any:
    """Convert one raw value for schema node into its logical python form."""
    if value is None:
        return None
    if node.is_leaf:
        return value
    if _is_list_node(node) and node.children:
        rep_group = node.children[0]
        if rep_group.is_leaf:
            # 2-level legacy list: repeated primitive directly
            return [unwrap_value(rep_group, v) for v in value.get(rep_group.name, [])]
        items = value.get(rep_group.name)
        if items is None:
            return []
        if _repeated_group_is_element(node.name, rep_group):
            # legacy 2-level list of structs: the repeated group IS the element
            return [unwrap_group(rep_group, item) for item in items]
        elem = rep_group.children[0]
        return [
            unwrap_value(elem, item.get(elem.name)) if isinstance(item, dict) else item
            for item in items
        ]
    if _is_map_node(node) and node.children:
        if isinstance(value, list):
            # legacy layout: MAP_KEY_VALUE annotates the repeated group itself;
            # `value` is already the list of {key,value} items
            return _pairs_to_map(node, value)
        kv = node.children[0]
        items = value.get(kv.name)
        if items is None:
            return {}
        return _pairs_to_map(kv, items)
    if isinstance(value, list):
        # plain repeated group/leaf (no LIST annotation)
        return [unwrap_group(node, v) if isinstance(v, dict) else v for v in value]
    return unwrap_group(node, value)


def _pairs_to_map(kv_node: SchemaNode, items: list):
    """{key,value} item dicts → python dict, or list of pairs when a key is
    unhashable (e.g. group-typed keys that unwrap to dicts)."""
    key_node = kv_node.child("key") if not kv_node.is_leaf else None
    val_node = kv_node.child("value") if not kv_node.is_leaf else None
    pairs = []
    for item in items:
        k = unwrap_value(key_node, item.get("key")) if key_node else item.get("key")
        v = unwrap_value(val_node, item.get("value")) if val_node else item.get("value")
        pairs.append((k, v))
    try:
        return dict(pairs)
    except TypeError:
        return pairs


def unwrap_group(node: SchemaNode, value: dict) -> dict:
    if not isinstance(value, dict):
        return value
    out = {}
    for child in node.children or []:
        if child.name in value:
            out[child.name] = unwrap_value(child, value[child.name])
    return out


def unwrap_row(schema, row: dict) -> dict:
    """Logical view of one raw row (schema is a tpu_parquet.schema.Schema)."""
    return unwrap_group(schema.root, row)
