"""Cost-based link-byte ship planner: choose HOW a chunk's bytes reach HBM.

The whole device reader is engineered around one scarce resource — the
host→device link (~hundreds of MB/s over the tunneled backend, vs GB/s for
every host-side pass that could shrink the payload).  Until this module the
"ship fewer bytes" decisions were scattered route gates inside
``device_reader._ChunkAssembler``: device-snappy only for PLAIN fixed-width
SNAPPY pages, narrow transcode only as its fallback, everything else shipped
fully decompressed.  This module centralizes the decision as an explicit
cost model over the five routes a chunk's value stream can take:

===============  ============================================================
route            what ships over the link
===============  ============================================================
plain            the decompressed host bytes, as-is
narrow           ``(v - min)`` truncated to k bytes/value (PLAIN INT only)
narrow_snappy    the narrow transcode, then snappy over the truncated bytes
device_snappy    the file's own snappy page payloads, decompressed on device
recompress       host re-compresses the stream to snappy, ships compressed
===============  ============================================================

Two FUSED variants (``fused_plain``, ``fused_narrow_snappy``) ship exactly
their twin's bytes but run the device half as one Pallas megakernel pass
(pallas_kernels): no inter-stage HBM spill term in the cost model, one
dispatch in the registry's ``device`` section.  Offered when ``TPQ_FUSE``
permits (default: exactly when the backend compiles Mosaic natively) and
the stream is fused-eligible (``fused_eligible``); at equal modeled cost
the planner prefers the fused variant.

Cost per route = host prep time + link time + device resolve time, each a
bytes/throughput term.  Link bandwidth comes from ``TPQ_LINK_MBPS`` when set
(bench.py exports its measured probe there); the host/device terms are
calibrated constants, overridable for experiments.  The model only ROUTES —
every route decodes bit-identically, so a mis-ranked route costs time, never
correctness.

``TPQ_FORCE_ROUTE=<route>`` pins the choice for deterministic CI and A/B
debugging; infeasible forces (narrow on a float column, device_snappy on a
gzip file) fall back to ``plain``.

Per-route decisions and shipped-byte counters surface in
``device_reader.ReaderStats`` (``ship_routes``, ``link_bytes_shipped``,
``link_bytes_logical``) and ride the bench artifact.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

ROUTE_PLAIN = "plain"
ROUTE_NARROW = "narrow"
ROUTE_NARROW_SNAPPY = "narrow_snappy"
ROUTE_DEVICE_SNAPPY = "device_snappy"
ROUTE_RECOMPRESS = "recompress"
# fused megakernel variants (pallas_kernels): the SAME bytes over the link
# as their unfused twin, but the device half runs as ONE Pallas pass
# (resolve → gather → widen → validity) instead of a chain of XLA calls
# with an HBM round trip between each stage
ROUTE_FUSED_PLAIN = "fused_plain"
ROUTE_FUSED_NARROW_SNAPPY = "fused_narrow_snappy"
# THE route-name registry: planner ranking, device_reader dispatch, the
# TPQ_FORCE_ROUTE validation, and the ScanPlan route memo all share this
# one table (parse_route below is the one env-validation entry point), so
# a fused name added here is automatically legal at every site.
ROUTES = (ROUTE_PLAIN, ROUTE_NARROW, ROUTE_NARROW_SNAPPY,
          ROUTE_DEVICE_SNAPPY, ROUTE_RECOMPRESS,
          ROUTE_FUSED_PLAIN, ROUTE_FUSED_NARROW_SNAPPY)
# fused route -> the unfused twin whose link bytes / host work it shares
UNFUSED_OF = {ROUTE_FUSED_PLAIN: ROUTE_PLAIN,
              ROUTE_FUSED_NARROW_SNAPPY: ROUTE_NARROW_SNAPPY}
FUSED_OF = {v: k for k, v in UNFUSED_OF.items()}
FUSED_ROUTES = tuple(UNFUSED_OF)

# link bandwidth the model assumes when TPQ_LINK_MBPS is absent: the tunneled
# TPU link's typical mid-weather rate from the bench probes (BENCH_r05 logs
# swing 93-1500 MB/s; 350 is the planning point the round-5 VERDICT used)
DEFAULT_LINK_MBPS = 350.0
# host-side throughputs (vectorized native passes; absolute values matter
# less than their RATIO to the link — every term here is GB/s-class while
# the link is hundreds of MB/s, which is the whole reason shrinking the
# payload wins)
HOST_TRANSCODE_MBPS = 2500.0   # min/max + truncating copy (native)
HOST_COMPRESS_MBPS = 1500.0    # native snappy_compress
HOST_DECOMPRESS_MBPS = 1400.0  # native snappy_decompress (lazy pages only)
# device-side op-table resolve (searchsorted + pointer-doubling gathers over
# the output space); HBM-bandwidth bound, charged per OUTPUT byte.
# TPQ_DEVICE_MBPS overrides it at planner construction — the device twin of
# TPQ_LINK_MBPS, fed back by `pq_tool doctor` when the measured per-route
# device lane (obs device timing) disagrees beyond DOCTOR_ERROR_BAND.
DEVICE_RESOLVE_MBPS = 3000.0
# a compressed route must beat plain shipping by at least this ratio or the
# builder falls through (the op tables + resolve cost eat thin wins)
SNAPPY_WORTH_RATIO = 0.92
# streams smaller than this never pay a recompression attempt: the op-table
# fixed overhead rivals the payload
MIN_COMPRESS_BYTES = 1 << 16
# assumed compression ratios used only for RANKING (the builder measures the
# real ratio and falls back when the estimate was wrong — a wrong guess
# costs one GB/s-class host pass on the overlapped pool, never link bytes)
EST_NARROW_SNAPPY_RATIO = 0.6  # narrow output: low-entropy residuals
EST_RECOMPRESS_RATIO = 0.5     # strings/dates/ids under snappy
# inter-stage HBM spill the UNFUSED decode chain pays beyond its resolve
# term: each extra XLA stage re-reads and re-writes the output-sized
# intermediate (PR 9's per-kernel device timing is what made this term
# attributable).  Used only for the fused-vs-unfused device prediction
# (`unfused_device_costs` → the doctor's `fusion-win` line), never for
# ranking the unfused routes against each other — their relative order is
# untouched by the fusion work.
HBM_SPILL_PASSES = 2


def parse_route(raw, *, source: str = "TPQ_FORCE_ROUTE") -> "str | None":
    """Validate a route name from the environment against the ONE registry
    (``ROUTES``).  Malformed values degrade — one ``warn_env_once`` line,
    then cost-ranked routing — instead of turning every reader
    construction (or a scan already in flight re-reading the env through
    ``default_planner``) into a raise.  Returns the canonical name or
    None."""
    v = (raw or "").strip()
    if not v:
        return None
    if v not in ROUTES:
        from .obs import warn_env_once

        warn_env_once(source, v, "cost-ranked routes (unforced)")
        return None
    return v


def fuse_enabled() -> bool:
    """Whether the planner offers fused megakernel routes (``TPQ_FUSE``).

    Same contract as ``TPQ_PALLAS``: unset → on exactly when the backend
    compiles Mosaic kernels natively (the fused graph is a perf feature,
    not a semantic one); ``1`` forces it on non-TPU backends through the
    Pallas interpreter (tier-1 exercises the fused graph bit-identically
    on CPU this way); ``0`` forces it off everywhere."""
    env = os.environ.get("TPQ_FUSE", "").strip()
    if env == "0":
        return False
    if env == "1":
        return True
    from .pallas_kernels import pallas_available

    return pallas_available()


@dataclass(frozen=True)
class ChunkFacts:
    """Everything the cost model needs to rank routes for one chunk.

    ``logical`` is the decompressed value-stream byte count (what ``plain``
    would ship); ``width`` the fixed value width (0 for byte-array/heap
    streams); ``narrow_k`` the stats-hinted narrow byte width when chunk
    Statistics prove the span fits (0 = unknown or infeasible);
    ``narrow_possible`` whether a narrow PROBE is allowed when no hint
    exists (int column + native library); ``comp_bytes`` the file's own
    snappy payload bytes available to ship as-is (0 = none);
    ``host_bytes_ready`` whether the decompressed host bytes already exist
    (dictionary tables, level-carrying pages) — when False and
    ``comp_bytes`` > 0, every host-bytes route additionally pays the
    decompress the lazy pages skipped.  ``flat`` whether the column is
    required and unrepeated (no def/rep level lanes) — the fused
    megakernel routes claim only flat streams, where "validity" is the
    tail mask the single pass bakes in.
    """

    logical: int
    width: int = 0
    narrow_k: int = 0
    narrow_possible: bool = False
    comp_bytes: int = 0
    native: bool = True
    host_bytes_ready: bool = False
    flat: bool = True


def fused_eligible(f: ChunkFacts) -> "tuple[str, ...]":
    """The fused routes these facts admit — the ONE eligibility predicate
    (planner pricing, device_reader dispatch, and the ``fused_plan`` fuzz
    invariants all call it, so the three sites cannot drift).  A fused row
    additionally requires its unfused twin to be priced feasible (the
    planner checks that; forced-fused on a stream whose twin build fails
    degrades in the builder with a counter, never a crash)."""
    if not f.flat or f.width not in (4, 8) or f.logical <= 0:
        return ()
    return (ROUTE_FUSED_PLAIN, ROUTE_FUSED_NARROW_SNAPPY)


class ShipPlanner:
    """Ranks ship routes by modeled wall cost; builders execute in order.

    One instance per reader (reads env at construction, so tests can flip
    ``TPQ_FORCE_ROUTE``/``TPQ_LINK_MBPS`` per reader); stateless after
    construction and safe to share across the prefetch pool's threads.
    """

    def __init__(self, link_mbps: "float | None" = None,
                 force: "str | None" = None,
                 device_mbps: "float | None" = None,
                 fuse: "bool | None" = None):
        from .obs import env_float

        if link_mbps is None:
            link_mbps = env_float("TPQ_LINK_MBPS", DEFAULT_LINK_MBPS)
        self.link_mbps = max(float(link_mbps), 1.0)
        if device_mbps is None:
            device_mbps = env_float("TPQ_DEVICE_MBPS", DEVICE_RESOLVE_MBPS)
        self.device_mbps = max(float(device_mbps), 1.0)
        if force is None:
            # env values degrade (parse_route: one warning, then unforced)
            # — an env typo must never raise mid-scan; an explicit force=
            # argument is a programming contract and still raises below
            force = parse_route(os.environ.get("TPQ_FORCE_ROUTE", ""))
        elif force not in ROUTES:
            raise ValueError(
                f"forced route {force!r} not one of {ROUTES}")
        self.force = force
        self.fuse = fuse_enabled() if fuse is None else bool(fuse)

    # -- cost terms (seconds) -------------------------------------------------

    @staticmethod
    def _t(nbytes: float, mbps: float) -> float:
        return nbytes / (mbps * 1e6)

    def _link(self, nbytes: float) -> float:
        return self._t(nbytes, self.link_mbps)

    def costs(self, f: ChunkFacts) -> dict:
        """Modeled seconds per FEASIBLE route (infeasible routes absent).

        Each route costs ``max(host lane, link lane, device lane)`` — the
        overlapped pipeline (prefetch pool + staging worker + async
        dispatch) runs host passes, transfers, and device resolves
        CONCURRENTLY, so steady-state cost is the bottleneck lane, not
        the sum.  The device lane (op-table resolve at HBM bandwidth) is
        almost never the bottleneck but keeps pathological op-heavy
        routes honest.

        ``plain`` is always present, so ``min(costs, key=costs.get)`` is
        total.  The narrow guess (no stats hint) only enters when no
        compressed payload exists — with one, the legacy hint contract
        applies: narrow claims the chunk only when Statistics prove the
        span, so a lying-stats file costs at most a wasted decompress.
        """
        L = float(f.logical)
        # every host-bytes route on a lazily-compressed chunk pays the
        # decompress the lazy parse skipped (the device_snappy route's
        # built-in win)
        mat = (self._t(L, HOST_DECOMPRESS_MBPS)
               if f.comp_bytes and not f.host_bytes_ready else 0.0)
        resolve = self._t(L, self.device_mbps)
        out = {ROUTE_PLAIN: max(mat, self._link(L))}
        if L <= 0:
            return out
        k = f.narrow_k
        if not k and f.narrow_possible and not f.comp_bytes:
            k = max(f.width // 2, 1)  # optimistic probe guess
        if k and f.width in (4, 8) and k < f.width:
            narrowed = L * k / f.width
            # the device lane: the widen/re-bias pass writes L output
            # bytes; narrow_snappy additionally resolves the compressed
            # stream over its narrowed output space first — strictly MORE
            # device work than bare narrow (device_costs mirrors these
            # terms exactly, so the calibration predictions and the
            # ranking model can never disagree about the same route)
            out[ROUTE_NARROW] = max(
                mat + self._t(L, HOST_TRANSCODE_MBPS),
                self._link(narrowed),
                self._t(L, self.device_mbps),
            )
            if f.native and narrowed >= MIN_COMPRESS_BYTES:
                out[ROUTE_NARROW_SNAPPY] = max(
                    mat + self._t(L, HOST_TRANSCODE_MBPS)
                    + self._t(narrowed, HOST_COMPRESS_MBPS),
                    self._link(narrowed * EST_NARROW_SNAPPY_RATIO),
                    self._t(L + narrowed, self.device_mbps),
                )
        if f.comp_bytes and f.native:
            out[ROUTE_DEVICE_SNAPPY] = max(
                self._link(float(f.comp_bytes)), resolve)
        if (not f.comp_bytes and f.native and L >= MIN_COMPRESS_BYTES):
            out[ROUTE_RECOMPRESS] = max(
                self._t(L, HOST_COMPRESS_MBPS),
                self._link(L * EST_RECOMPRESS_RATIO),
                resolve,
            )
        if self.fuse:
            # fused megakernel rows: SAME host prep and link bytes as the
            # unfused twin, device lane = one single-pass term (no
            # inter-stage HBM spill, one dispatch).  Priced only for
            # fused-eligible facts (fused_eligible); at equal modeled cost
            # the tie goes to the fused variant (plan() below) — strictly
            # fewer dispatches for the same bytes.
            for fr in fused_eligible(f):
                un = out.get(UNFUSED_OF[fr])
                if un is None:
                    continue
                if fr == ROUTE_FUSED_PLAIN:
                    out[fr] = max(mat, self._link(L), resolve)
                else:  # fused narrow+snappy: the host/link terms of the
                    # twin, minus its strictly-larger device term
                    narrowed = L * k / f.width
                    out[fr] = max(
                        mat + self._t(L, HOST_TRANSCODE_MBPS)
                        + self._t(narrowed, HOST_COMPRESS_MBPS),
                        self._link(narrowed * EST_NARROW_SNAPPY_RATIO),
                        resolve,
                    )
        return out

    def device_costs(self, f: ChunkFacts, routes=None) -> dict:
        """Modeled DEVICE-lane seconds per feasible route (keys match
        :meth:`costs`; pass ``routes`` — e.g. the cost table a
        :meth:`plan` call just returned — to skip re-running the
        feasibility walk).

        The device lane is what the per-route completion timing
        (``TPQ_DEVICE_TIMING``, device_reader) measures: kernel time from
        dispatch to ``block_until_ready``.  ``plain`` models ~0 (reshape +
        bitcast, no compute); the compressed routes charge the op-table
        resolve per OUTPUT byte at ``device_mbps``; ``narrow`` charges the
        widen/re-bias pass the same way.  These ride ReaderStats per route
        (``predicted_device_s``) so ``ship_feedback()`` can put them next
        to the measured device lane — the ``TPQ_DEVICE_MBPS`` calibration
        signal, exactly as the link lane calibrates ``TPQ_LINK_MBPS``.
        """
        c = routes if routes is not None else self.costs(f)
        L = float(f.logical)
        k = f.narrow_k
        if not k and f.narrow_possible and not f.comp_bytes:
            k = max(f.width // 2, 1)
        narrowed = L * k / f.width if (k and f.width) else L
        out = {}
        for r in c:
            if r == ROUTE_PLAIN:
                out[r] = 0.0
            elif r == ROUTE_NARROW_SNAPPY:
                # resolve over the narrowed stream + the widen to L: the
                # SAME term costs() uses — strictly more device work than
                # bare narrow, never less
                out[r] = self._t(L + narrowed, self.device_mbps)
            else:
                # narrow widen / snappy resolve — and BOTH fused routes:
                # the megakernel's device lane is one output-sized pass,
                # never the unfused chain's L + narrowed composite
                out[r] = self._t(L, self.device_mbps)
        return out

    def unfused_device_costs(self, f: ChunkFacts, routes=None) -> dict:
        """Per FUSED route: the modeled device seconds its UNFUSED twin's
        stage chain would pay for the same stream — the twin's
        :meth:`device_costs` term plus ``HBM_SPILL_PASSES`` output-sized
        inter-stage round trips.  Recorded on fused ship records
        (``predicted_unfused_device_s``) so the registry carries the
        prediction the measured fused lane has to beat — the doctor's
        ``fusion-win`` verdict is exactly that comparison.  Never used to
        rank the unfused routes against each other."""
        c = routes if routes is not None else self.costs(f)
        dev = self.device_costs(f, routes=c)
        spill = self._t(float(f.logical) * HBM_SPILL_PASSES,
                        self.device_mbps)
        return {r: dev.get(UNFUSED_OF[r], 0.0) + spill
                for r in c if r in UNFUSED_OF}

    def routes(self, f: ChunkFacts) -> list:
        """Ordered candidate routes, cheapest modeled cost first.

        Builders try them in order and fall through on infeasibility (op
        caps, i32 ceilings, a ratio the estimate got wrong); ``plain`` —
        the route that cannot fail — terminates the walk wherever it
        ranks, so entries after it are dead fallbacks.
        """
        return self.plan(f)[0]

    def plan(self, f: ChunkFacts) -> "tuple[list, dict]":
        """``(routes, costs)``: the ordered candidates of :meth:`routes`
        plus the modeled seconds per feasible route — builders keep the
        costs so the chosen route's *prediction* can ride the obs layer
        next to the measured lanes (TPQ_LINK_MBPS calibration feedback).
        A forced route that the model never priced (infeasible) simply has
        no entry; consumers treat a missing prediction as 0."""
        c = self.costs(f)
        if self.force is not None:
            order = ([self.force, ROUTE_PLAIN] if self.force != ROUTE_PLAIN
                     else [ROUTE_PLAIN])
            return order, c
        # equal-cost tie goes to the fused variant: same bytes, same host
        # work, ONE device dispatch instead of a stage chain (the common
        # fused_plain-vs-plain case on link-bound streams is exactly this
        # tie).  A fused row priced WORSE than its twin (slow device) still
        # ranks after it — the tie-rank only breaks equality.
        return sorted(c, key=lambda r: (c[r], r not in UNFUSED_OF,
                                        ROUTES.index(r))), c

    def decision_table(self, f: ChunkFacts) -> dict:
        """Route → modeled milliseconds (README/debug surface)."""
        return {r: round(t * 1e3, 3) for r, t in self.costs(f).items()}


def recalibrate_link_mbps(link_bytes_per_sec: float) -> "float | None":
    """The ``TPQ_LINK_MBPS`` value a measured staging rate says to re-run
    with (``pq_tool doctor``'s recalibration output): the observed link
    lane in MB/s, floored at the planner's own 1 MB/s clamp.  ``None``
    when nothing was measured — an unmeasured link must never overwrite a
    banked calibration with a guess."""
    if not link_bytes_per_sec or link_bytes_per_sec <= 0:
        return None
    return max(round(link_bytes_per_sec / 1e6, 1), 1.0)


def recalibrate_device_mbps(device_bytes_per_sec: float) -> "float | None":
    """The ``TPQ_DEVICE_MBPS`` value a measured device-resolve rate says to
    re-run with (the device twin of :func:`recalibrate_link_mbps`): logical
    output bytes through the measured per-route device seconds, in MB/s,
    floored at the planner's 1 MB/s clamp.  ``None`` when the device lane
    was never timed — an unmeasured device must never overwrite a banked
    calibration with a guess."""
    if not device_bytes_per_sec or device_bytes_per_sec <= 0:
        return None
    return max(round(device_bytes_per_sec / 1e6, 1), 1.0)


_default: "ShipPlanner | None" = None
_default_lock = threading.Lock()


def default_planner() -> ShipPlanner:
    """Process-wide planner for callers without a reader (decode_chunk_batched
    and the page-at-a-time paths).  Rebuilt when the routing env knobs change
    so monkeypatched tests see their override."""
    global _default
    key = (os.environ.get("TPQ_LINK_MBPS", ""),
           os.environ.get("TPQ_FORCE_ROUTE", ""),
           os.environ.get("TPQ_DEVICE_MBPS", ""),
           os.environ.get("TPQ_FUSE", ""))
    with _default_lock:
        if _default is None or getattr(_default, "_env_key", None) != key:
            _default = ShipPlanner()
            _default._env_key = key
        return _default
