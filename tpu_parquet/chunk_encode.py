"""Column-chunk encoding: page cutting, dictionary decision, page serialization.

Equivalent of the reference's chunk_writer.go (writeChunk :154-316, dictionary
decision :174-209, getValuesEncoder :80-128) + page_v1.go/page_v2.go/page_dict.go
write paths — batch-oriented: a chunk's values arrive as one ColumnData, pages are
cut at record boundaries targeting the max page size (default 1 MiB, matching
data_store.go:149-154), and the dictionary decision scans the whole chunk with the
reference's fallback threshold (> 32767 distinct values → plain, chunk_writer.go:
188-207 / type_dict.go:101-103).
"""

from __future__ import annotations

import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .column import ByteArrayData, ColumnData
from .compress import compress_block
from .footer import ParquetError
from .format import (
    ColumnChunk,
    ColumnMetaData,
    DataPageHeader,
    DataPageHeaderV2,
    DictionaryPageHeader,
    Encoding,
    KeyValue,
    PageHeader,
    PageType,
    Statistics,
    Type,
)
from .kernels import bitpack, bytearray as ba_codec, delta, plain, rle
from .schema.core import SchemaNode
from .stats import _lex_minmax, compute_statistics
from .thrift import serialize

MAX_DICT_SIZE = 32767  # MaxInt16, the reference's dictionary fallback threshold
DEFAULT_PAGE_SIZE = 1 << 20  # 1 MiB, data_store.go:149-154


@dataclass
class ChunkWriteResult:
    chunk: ColumnChunk
    total_compressed: int
    total_uncompressed: int


def _num_defined(cd: ColumnData) -> int:
    if cd.def_levels is None:
        return cd.num_leaf_slots
    return int(np.count_nonzero(cd.def_levels == cd.max_def))


def _values_slice(values, lo: int, hi: int):
    if isinstance(values, ByteArrayData):
        off = values.offsets[lo : hi + 1]
        heap = values.heap[off[0] : off[-1]]
        return ByteArrayData(offsets=off - off[0], heap=heap)
    return values[lo:hi]


def _unique_bytes_seq(values: ByteArrayData):
    """Sequential dict walk: O(heap) memory, bails at MAX_DICT_SIZE+1 distinct.
    The fallback for columns whose dominant length class would make the
    vectorized gather's transient memory excessive."""
    seen: dict = {}
    idx = np.empty(len(values), dtype=np.int64)
    for i, v in enumerate(values.to_list()):
        j = seen.get(v)
        if j is None:
            j = len(seen)
            if j >= MAX_DICT_SIZE:
                return None
            seen[v] = j
        idx[i] = j
    return ByteArrayData.from_list(list(seen)), idx


def _unique_rows(rows: np.ndarray):
    """(first_indices, inverse) for the distinct rows of a (m, L) u8 matrix.

    np.unique(axis=0) argsorts void-dtype rows — the single hottest writer
    cost on string columns (~80% of dict-encode time).  Instead: one
    vectorized FNV-1a pass gives a u64 hash per row, np.unique on the hashes
    sorts plain integers (~20x faster), and an exact vectorized compare of
    every row against its class representative guards correctness — any
    hash collision (never seen on real data, constructible adversarially)
    falls back to the sort-based path, so output never depends on hash
    quality.
    """
    m, ln = rows.shape
    if m <= 64 or ln > 512:
        # few rows, or very long values: the hash loop is one numpy op PER
        # BYTE COLUMN, so sort-based dedup (C over the whole matrix) wins
        _, first, inv = np.unique(rows, axis=0, return_index=True,
                                  return_inverse=True)
        return first, inv.reshape(-1)
    h = np.full(m, 14695981039346656037, dtype=np.uint64)
    fnv = np.uint64(1099511628211)
    for k in range(ln):
        h = (h ^ rows[:, k]) * fnv
    _, first, inv = np.unique(h, return_index=True, return_inverse=True)
    inv = inv.reshape(-1)
    if not (rows == rows[first[inv]]).all():
        _, first, inv = np.unique(rows, axis=0, return_index=True,
                                  return_inverse=True)
        inv = inv.reshape(-1)
    return first, inv


def _unique_bytes(values: ByteArrayData):
    """Vectorized first-appearance uniquing of a ragged byte column.

    Native path: one O(n) open-addressing hash pass (tpq_dict_build_bytes)
    at memory speed.  Fallback: values grouped by length; each group's bytes
    gather into a fixed (m, L) u8 matrix that _unique_rows dedups at C speed
    — no per-value Python loop.  Distinct ids are renumbered by global first
    appearance; both paths produce identical output.
    """
    from . import native

    res = native.dict_build(
        len(values), MAX_DICT_SIZE,
        offsets=np.ascontiguousarray(values.offsets, dtype=np.int64),
        heap=np.ascontiguousarray(values.heap),
    )
    if res is not None:
        if isinstance(res, int):
            return None  # distinct count exceeded MAX_DICT_SIZE
        firsts, inverse = res
        return values.take(firsts), inverse.astype(np.int64)
    off = np.asarray(values.offsets)
    heap = np.asarray(values.heap)
    n = len(values)
    lens = np.diff(off)
    idx_out = np.empty(n, dtype=np.int64)
    groups = []  # (global_first[int64[k]], sel, inv) per length class
    distinct = 0
    for length in np.unique(lens):
        sel = np.flatnonzero(lens == length)
        ln = int(length)
        # the gather materializes ~9x this class's heap bytes transiently
        # (int64 index matrix + row copy + unique's sort buffers); past a
        # sane cap, the O(heap)-memory sequential walk is the better deal
        if len(sel) * max(ln, 1) * 9 > 512 << 20:
            return _unique_bytes_seq(values)
        rows = heap[off[sel][:, None] + np.arange(ln, dtype=np.int64)]
        first, inv = _unique_rows(rows)
        distinct += len(first)
        if distinct > MAX_DICT_SIZE:
            return None  # early bail: don't unique the remaining classes
        groups.append((sel[first], sel, inv))
    all_first = np.concatenate([g[0] for g in groups])
    order = np.argsort(all_first, kind="stable")
    rank = np.empty(len(all_first), dtype=np.int64)
    rank[order] = np.arange(len(order))
    pos = 0
    for g_first, sel, inv in groups:
        idx_out[sel] = rank[pos : pos + len(g_first)][inv]
        pos += len(g_first)
    return values.take(all_first[order]), idx_out


def _unique_with_indices(values, ptype: Type):
    """(dict_values, indices) preserving first-appearance order, or None if the
    distinct count exceeds the reference's MaxInt16 threshold."""
    if isinstance(values, ByteArrayData):
        if len(values) == 0:
            return ByteArrayData.from_list([]), np.zeros(0, dtype=np.int64)
        return _unique_bytes(values)
    arr = np.asarray(values)
    if ptype == Type.INT96:
        return None  # no dictionary for int96 (reference parity)
    from . import native

    if len(arr) and arr.ndim == 1 and arr.dtype.kind in "iuf":
        # native O(n) hash pass; distinct bit patterns are distinct values
        # (same memcmp semantics as the unique-on-int-views fallback).
        # Object/other dtypes would memcmp POINTERS, so they keep np.unique.
        res = native.dict_build(
            len(arr), MAX_DICT_SIZE,
            data=np.ascontiguousarray(arr), width=arr.dtype.itemsize,
        )
        if res is not None:
            if isinstance(res, int):
                return None
            firsts, inverse = res
            return arr[firsts], inverse.astype(np.int64)
    view = arr.view(np.int32) if arr.dtype == np.float32 else (
        arr.view(np.int64) if arr.dtype == np.float64 else arr
    )
    uniq, first_idx, inv = np.unique(view, return_index=True, return_inverse=True)
    if len(uniq) > MAX_DICT_SIZE:
        return None
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty_like(order)
    rank[order] = np.arange(len(order))
    dict_vals = arr[np.sort(first_idx)]
    return dict_vals, rank[inv]


def _encode_values(values, leaf: SchemaNode, encoding: Encoding) -> bytes:
    ptype = leaf.physical_type
    if encoding == Encoding.PLAIN:
        # zero-copy uint8 view for fixed-width types (compressors and the
        # parts-based page writer take any buffer)
        return plain.encode_view(values, ptype, leaf.type_length)
    if encoding == Encoding.DELTA_BINARY_PACKED:
        if ptype == Type.INT32:
            return delta.encode(np.asarray(values), bits=32)
        if ptype == Type.INT64:
            return delta.encode(np.asarray(values), bits=64)
        raise ParquetError(f"DELTA_BINARY_PACKED invalid for {ptype!r}")
    if encoding == Encoding.DELTA_LENGTH_BYTE_ARRAY:
        if not isinstance(values, ByteArrayData):
            raise ParquetError("DELTA_LENGTH_BYTE_ARRAY needs byte arrays")
        return ba_codec.encode_delta_length(values)
    if encoding == Encoding.DELTA_BYTE_ARRAY:
        if not isinstance(values, ByteArrayData):
            raise ParquetError("DELTA_BYTE_ARRAY needs byte arrays")
        return ba_codec.encode_delta(values)
    if encoding == Encoding.RLE:
        if ptype != Type.BOOLEAN:
            raise ParquetError("RLE value encoding is boolean-only")
        return rle.encode_prefixed(np.asarray(values).astype(np.uint64), 1)
    if encoding == Encoding.BYTE_STREAM_SPLIT:
        arr = np.asarray(values)
        raw = plain.encode(arr, ptype, leaf.type_length)
        width = {Type.FLOAT: 4, Type.DOUBLE: 8, Type.INT32: 4, Type.INT64: 8}[ptype]
        mat = np.frombuffer(raw, np.uint8).reshape(-1, width)
        return mat.T.tobytes()
    raise ParquetError(f"unsupported write encoding {encoding!r}")


class ChunkEncoder:
    """Serializes one column chunk (dict decision + page cutting + headers)."""

    def __init__(
        self,
        leaf: SchemaNode,
        codec: int,
        page_size: int = DEFAULT_PAGE_SIZE,
        data_page_version: int = 1,
        use_dictionary: bool = True,
        write_crc: bool = False,
        encoding: Optional[Encoding] = None,
        write_statistics: bool = True,
        stats=None,
    ):
        self.leaf = leaf
        self.codec = codec
        self.page_size = page_size
        self.v2 = data_page_version == 2
        self.use_dictionary = use_dictionary
        self.write_crc = write_crc
        self.fallback_encoding = encoding or Encoding.PLAIN
        self.write_statistics = write_statistics
        # write-side lane attribution (write.WriteStats): write() books
        # its codec passes as `compress`, the sink write loop as `flush`,
        # and the remaining chunk wall as `encode` — the three lanes
        # pq_tool doctor needs to name a slow write's bottleneck
        self.stats = stats
        self._compress_s = 0.0
        # (min, max) bytes for dict-encoded BYTE_ARRAY page stats; set per
        # write() from the dictionary (O(distinct)), see _page_statistics
        self._dict_stat_bounds = None

    def _compress(self, raw) -> bytes:
        """compress_block with the codec pass booked into the `compress`
        write lane (one perf_counter pair per page when stats are on)."""
        if self.stats is None:
            return compress_block(raw, self.codec)
        t0 = time.perf_counter()
        out = compress_block(raw, self.codec)
        self._compress_s += time.perf_counter() - t0
        return out

    # -- page boundary selection ----------------------------------------------

    def _page_bounds(self, cd: ColumnData) -> list[tuple[int, int]]:
        """Split slots into pages at record boundaries targeting page_size."""
        n = cd.num_leaf_slots
        if n == 0:
            return [(0, 0)]
        if cd.rep_levels is not None:
            record_starts = np.flatnonzero(cd.rep_levels == 0)
        else:
            record_starts = None  # flat: every slot is a record boundary
        # estimated bytes/slot
        if isinstance(cd.values, ByteArrayData):
            per_slot = (int(cd.values.offsets[-1]) + 4 * len(cd.values)) / max(n, 1)
        else:
            per_slot = cd.values.dtype.itemsize if len(cd.values) else 4
        slots_per_page = max(int(self.page_size / max(per_slot, 0.125)), 1)
        bounds = []
        start = 0
        while start < n:
            target = start + slots_per_page
            if target >= n:
                bounds.append((start, n))
                break
            if record_starts is None:
                bounds.append((start, target))
                start = target
                continue
            # next record boundary at/after target
            i = int(np.searchsorted(record_starts, target))
            if i >= len(record_starts):
                bounds.append((start, n))
                break
            nxt = int(record_starts[i])
            if nxt == start:
                nxt = int(record_starts[i + 1]) if i + 1 < len(record_starts) else n
            bounds.append((start, nxt))
            start = nxt
        return bounds

    # -- serialization ---------------------------------------------------------

    def write(self, cd: ColumnData, sink, offset: int) -> ChunkWriteResult:
        """Serialize the chunk into sink (a writable), starting at file offset."""
        t_start = time.perf_counter() if self.stats is not None else 0.0
        self._compress_s = 0.0
        leaf = self.leaf
        ptype = leaf.physical_type
        # normalize the all-defined shorthand (def_levels=None with max_def>0)
        # that the rest of the codebase accepts
        if cd.max_def > 0 and cd.def_levels is None:
            cd = ColumnData(
                values=cd.values,
                def_levels=np.full(cd.num_leaf_slots, cd.max_def, dtype=np.int32),
                rep_levels=cd.rep_levels,
                max_def=cd.max_def, max_rep=cd.max_rep,
                num_leaf_slots=cd.num_leaf_slots,
            )
        if cd.max_rep > 0 and cd.rep_levels is None:
            cd = ColumnData(
                values=cd.values, def_levels=cd.def_levels,
                rep_levels=np.zeros(cd.num_leaf_slots, dtype=np.int32),
                max_def=cd.max_def, max_rep=cd.max_rep,
                num_leaf_slots=cd.num_leaf_slots,
            )
        # parts list, not a growing bytearray: the += growth copies plus the
        # final bytes() copy re-wrote a 16 MB row group ~2.5x over — ~40% of
        # a plain-int64 chunk write
        parts: list = []
        pos = 0

        dict_pair = None
        if self.use_dictionary and ptype != Type.BOOLEAN:
            dict_pair = _unique_with_indices(cd.values, ptype)
        use_dict = dict_pair is not None
        # dictionary-wide lexicographic bounds for BYTE_ARRAY page stats:
        # one O(distinct) pass here instead of O(values) per page
        self._dict_stat_bounds = None
        if (use_dict and self.write_statistics
                and ptype == Type.BYTE_ARRAY
                and isinstance(dict_pair[0], ByteArrayData)
                and len(dict_pair[0])):
            self._dict_stat_bounds = _lex_minmax(dict_pair[0])

        encodings: set[int] = set()
        encoding_used = Encoding.RLE_DICTIONARY if use_dict else self.fallback_encoding
        dict_page_offset = None
        data_page_offset = None
        chunk_stats: Optional[Statistics] = None
        total_uncompressed = 0

        if use_dict:
            dict_vals, indices = dict_pair
            raw = plain.encode(dict_vals, ptype, leaf.type_length)
            comp = self._compress(raw)
            ph = PageHeader(
                type=int(PageType.DICTIONARY_PAGE),
                uncompressed_page_size=len(raw),
                compressed_page_size=len(comp),
                dictionary_page_header=DictionaryPageHeader(
                    num_values=len(dict_vals), encoding=int(Encoding.PLAIN)
                ),
            )
            if self.write_crc:
                ph.crc = _crc_i32(comp)
            hdr = serialize(ph)
            dict_page_offset = offset + pos
            parts.append(hdr)
            parts.append(comp)
            pos += len(hdr) + len(comp)
            total_uncompressed += len(raw) + len(hdr)
            encodings.add(int(Encoding.PLAIN))

        # per-page writes
        page_stats_list: list = []
        bounds = self._page_bounds(cd)
        defined_prefix = (
            np.cumsum(cd.def_levels == cd.max_def)
            if cd.def_levels is not None
            else None
        )
        for lo, hi in bounds:
            if defined_prefix is not None:
                vlo = int(defined_prefix[lo - 1]) if lo > 0 else 0
                vhi = int(defined_prefix[hi - 1]) if hi > 0 else 0
            else:
                vlo, vhi = lo, hi
            if use_dict:
                page_payload = self._encode_dict_indices(
                    dict_pair[1][vlo:vhi], len(dict_pair[0])
                )
            else:
                page_payload = _encode_values(
                    _values_slice(cd.values, vlo, vhi), leaf, encoding_used
                )
            page_parts, hdr_len, raw_len, pstats = self._write_data_page(
                cd, lo, hi, vlo, vhi, page_payload, encoding_used
            )
            page_stats_list.append(pstats)
            if data_page_offset is None:
                data_page_offset = offset + pos
            parts.extend(page_parts)
            pos += sum(len(pp) for pp in page_parts)
            total_uncompressed += raw_len + hdr_len
            encodings.add(int(encoding_used))
        encodings.add(int(Encoding.RLE))  # level (and dict-index) encoding

        if self.write_statistics:
            n_slots = (len(cd.def_levels) if cd.def_levels is not None
                       else len(cd.values))
            # chunk stats == fold of the per-page stats already computed in
            # the page loop (min of mins, summed nulls) — a second full
            # min/max pass over the chunk doubled the stats cost
            chunk_stats = _fold_page_stats(
                page_stats_list, ptype, n_slots - len(cd.values))
            if chunk_stats is None:
                # pages carried no stats (booleans, INT96, non-dict byte
                # arrays, all-NaN float pages): one chunk-level pass.  Dict
                # chunks compute min/max over the DICTIONARY (identical by
                # definition — the lexicographic pass over n values was the
                # single hottest writer cost on low-cardinality strings)
                stat_values = dict_pair[0] if use_dict else cd.values
                chunk_stats = compute_statistics(
                    stat_values, ptype, null_count=n_slots - len(cd.values),
                )

        if self.stats is not None:
            t_flush = time.perf_counter()
            for part in parts:
                sink.write(part)
            flush_s = time.perf_counter() - t_flush
            # the chunk's three write lanes, partitioned exactly: codec
            # passes (compress), the sink write loop (flush), and the
            # remaining encode wall (dict build, page cutting, values,
            # headers) — doctor's slow-write attribution basis
            self.stats.add("compress", self._compress_s)
            self.stats.add("flush", flush_s)
            self.stats.add(
                "encode",
                max(time.perf_counter() - t_start
                    - self._compress_s - flush_s, 0.0))
        else:
            for part in parts:
                sink.write(part)

        md = ColumnMetaData(
            type=int(ptype),
            encodings=sorted(encodings),
            path_in_schema=list(leaf.path),
            codec=int(self.codec),
            num_values=cd.num_leaf_slots,
            total_uncompressed_size=total_uncompressed,
            total_compressed_size=pos,
            data_page_offset=data_page_offset if data_page_offset is not None else offset,
            dictionary_page_offset=dict_page_offset,
            statistics=chunk_stats if self.write_statistics else None,
        )
        chunk = ColumnChunk(file_offset=offset, meta_data=md)
        return ChunkWriteResult(
            chunk=chunk, total_compressed=pos,
            total_uncompressed=total_uncompressed,
        )

    def _encode_dict_indices(self, idx: np.ndarray, dict_len: int) -> bytes:
        width = bitpack.bit_width(max(dict_len - 1, 0))
        body = rle.encode(idx.astype(np.uint64), width)
        return bytes([width]) + body

    def _page_statistics(self, cd: ColumnData, lo, hi, vlo, vhi):
        """Per-page Statistics for fixed-width numeric pages (data_store.go:
        159-179 parity — the reference carries stats in every data page).
        Dict-encoded BYTE_ARRAY pages carry DICTIONARY-WIDE min/max bounds
        (set by write(): O(distinct) once per chunk, not O(values) per page
        — the per-page lexicographic pass was the writer's hottest path) and
        page-exact null counts; bounds wider than the page's actual values
        are sound for pruning readers.  Other ragged/boolean/INT96 pages
        skip stats."""
        if not self.write_statistics:
            return None
        if self.leaf.physical_type not in (Type.INT32, Type.INT64,
                                           Type.FLOAT, Type.DOUBLE):
            if self._dict_stat_bounds is not None and vhi > vlo:
                st = Statistics(null_count=(hi - lo) - (vhi - vlo))
                # dictionary-wide BOUNDS are only legal in min_value/max_value
                # (which permit non-occurring values); the deprecated min/max
                # fields imply actual page values and an ambiguous BYTE_ARRAY
                # sort order, so modern writers leave them unset here
                st.min_value = self._dict_stat_bounds[0]
                st.max_value = self._dict_stat_bounds[1]
                return st
            return None
        vals = cd.values[vlo:vhi]
        if len(vals) == 0:
            return None
        return compute_statistics(
            np.asarray(vals), self.leaf.physical_type,
            null_count=(hi - lo) - (vhi - vlo),
        )

    def _write_data_page(
        self, cd: ColumnData, lo, hi, vlo, vhi, payload, encoding
    ) -> tuple[list, int, int, "Optional[Statistics]"]:
        """Returns ([header, body parts...], header_len,
        uncompressed_payload_len, page_statistics).  Parts are bytes-like
        (the snappy path hands back uint8 arrays); callers append them to
        the chunk's parts list — concatenating here re-copied every page."""
        leaf = self.leaf
        num_values = hi - lo
        page_stats = self._page_statistics(cd, lo, hi, vlo, vhi)
        rep_bytes = b""
        def_bytes = b""
        if self.v2:
            if cd.max_rep > 0:
                rep_bytes = rle.encode(
                    cd.rep_levels[lo:hi].astype(np.uint64),
                    bitpack.bit_width(cd.max_rep),
                )
            if cd.max_def > 0:
                def_bytes = rle.encode(
                    cd.def_levels[lo:hi].astype(np.uint64),
                    bitpack.bit_width(cd.max_def),
                )
            comp = self._compress(payload)
            num_rows = (
                int(np.count_nonzero(cd.rep_levels[lo:hi] == 0))
                if cd.rep_levels is not None
                else num_values
            )
            header = PageHeader(
                type=int(PageType.DATA_PAGE_V2),
                uncompressed_page_size=len(rep_bytes) + len(def_bytes) + len(payload),
                compressed_page_size=len(rep_bytes) + len(def_bytes) + len(comp),
                data_page_header_v2=DataPageHeaderV2(
                    num_values=num_values,
                    num_nulls=num_values - (vhi - vlo),
                    num_rows=num_rows,
                    encoding=int(encoding),
                    definition_levels_byte_length=len(def_bytes),
                    repetition_levels_byte_length=len(rep_bytes),
                    is_compressed=True,
                    statistics=page_stats,
                ),
            )
            if self.write_crc:
                header.crc = _crc_i32(comp, zlib.crc32(def_bytes,
                                                       zlib.crc32(rep_bytes)))
            hdr = serialize(header)
            return ([hdr, rep_bytes, def_bytes, comp], len(hdr),
                    len(rep_bytes) + len(def_bytes) + len(payload),
                    page_stats)
        # v1: everything in one compressed block
        if cd.max_rep > 0:
            rep_bytes = rle.encode_prefixed(
                cd.rep_levels[lo:hi].astype(np.uint64),
                bitpack.bit_width(cd.max_rep),
            )
        if cd.max_def > 0:
            def_bytes = rle.encode_prefixed(
                cd.def_levels[lo:hi].astype(np.uint64),
                bitpack.bit_width(cd.max_def),
            )
        # flat required columns: compress the payload buffer directly (the
        # bytes concat would copy the whole page just to prepend nothing)
        if not rep_bytes and not def_bytes:
            raw = payload
        else:
            raw = rep_bytes + def_bytes + (
                payload if isinstance(payload, bytes) else bytes(payload))
        comp = self._compress(raw)
        header = PageHeader(
            type=int(PageType.DATA_PAGE),
            uncompressed_page_size=len(raw),
            compressed_page_size=len(comp),
            data_page_header=DataPageHeader(
                num_values=num_values,
                encoding=int(encoding),
                definition_level_encoding=int(Encoding.RLE),
                repetition_level_encoding=int(Encoding.RLE),
                statistics=page_stats,
            ),
        )
        if self.write_crc:
            header.crc = _crc_i32(comp)
        hdr = serialize(header)
        return [hdr, comp], len(hdr), len(raw), page_stats


def _fold_page_stats(plist, ptype: Type, null_count: int):
    """Chunk Statistics folded from per-page Statistics (numeric fixed
    types; None when any page lacks bounds — caller recomputes)."""
    fmts = {Type.INT32: "<i", Type.INT64: "<q",
            Type.FLOAT: "<f", Type.DOUBLE: "<d"}
    fmt = fmts.get(ptype)
    if fmt is None or not plist:
        return None
    if any(p is None or p.min_value is None or p.max_value is None
           for p in plist):
        return None
    mn = min(struct.unpack(fmt, p.min_value)[0] for p in plist)
    mx = max(struct.unpack(fmt, p.max_value)[0] for p in plist)
    st = Statistics(null_count=null_count)
    st.min = st.min_value = struct.pack(fmt, mn)
    st.max = st.max_value = struct.pack(fmt, mx)
    return st


def _crc_i32(data, start: int = 0) -> int:
    v = zlib.crc32(data, start) & 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v
