"""Footer merge: stitch N shard parquet files into one, data bytes untouched.

The reference's L4/L6 split (PAPER.md §1) ends with a file writer that owns
the footer while chunk writers own the bytes; this module is that seam at
dataset scale.  A shard's encoded row group is position-independent — page
headers carry no absolute offsets — so merging N shards is a *metadata*
operation: copy each row group's contiguous byte span into the output in
order and shift every footer offset by the relocation delta.  No re-encode,
no re-compress, no CRC recompute (the page CRCs ride along byte-identical).

Two layers, deliberately separated so the math is fuzzable without IO
(fuzz target #20 ``footer_merge``):

- :func:`merge_footers` — pure: ``[(FileMetaData, file_size), ...]`` in,
  ``(merged FileMetaData, copy spans)`` out.  Validates every shard footer
  through the SAME :func:`~tpu_parquet.scanplan.row_group_byte_span` walk
  the readers use, so a truncated or lying shard (chunk spans past EOF,
  overlapping row groups, ``num_rows`` disagreeing with its groups, a
  schema that doesn't match shard 0's) is rejected with a typed
  :class:`~tpu_parquet.errors.ParquetError` — never silently merged.
- :func:`merge_files` — the IO half: stream the spans (1 MiB blocks) and
  write the merged footer.  Used by ``pq_tool merge`` and the sharded
  writer's file layout.

Merged output invariants (held by construction, asserted by the fuzz
target): row count is the sum of the shards', row groups keep shard order
with globally renumbered ordinals, relocated chunk spans are ascending and
disjoint, and every relocated offset lands inside the output data segment.
"""

from __future__ import annotations

import copy
import os
from typing import BinaryIO, Union

from ..errors import ParquetError
from ..footer import FOOTER_TAIL, MAGIC, read_file_metadata, serialize_footer
from ..format import ColumnOrder, FileMetaData, KeyValue, TypeDefinedOrder
from ..scanplan import row_group_byte_span
from ..schema.core import Schema
from ..thrift import serialize

__all__ = ["merge_footers", "merge_files", "relocate_row_group",
           "validate_shard_footer"]

_COPY_BLOCK = 1 << 20


def _schema_sig(meta: FileMetaData) -> tuple:
    """Byte-stable signature of a footer's flat schema element list (thrift
    serialization per element — field-for-field equality, no name games)."""
    return tuple(serialize(se) for se in (meta.schema or []))


def validate_shard_footer(meta: FileMetaData, file_size: int,
                          *, label: str = "shard") -> list:
    """Validate one shard's footer for merging; returns its row groups'
    ``(row_group, (start, end))`` spans in footer order.

    Typed rejections (all :class:`ParquetError`): chunk spans that start
    before the head magic or run past the data segment (a truncated or
    lying shard), row groups whose spans overlap (double-counted bytes),
    and a footer ``num_rows`` that disagrees with its groups' sum.
    """
    schema = Schema.from_file_metadata(meta)
    leaves = {l.path: l for l in schema.leaves}
    data_end = int(file_size) - FOOTER_TAIL
    spans = []
    rows = 0
    for i, rg in enumerate(meta.row_groups or []):
        start, end = row_group_byte_span(rg, leaves)
        if start < len(MAGIC):
            raise ParquetError(
                f"{label}: row group {i} chunk span starts at {start}, "
                f"inside the head magic")
        if end > data_end:
            raise ParquetError(
                f"{label}: row group {i} chunk span ends at {end}, past "
                f"the data segment end {data_end} (truncated or lying "
                f"shard footer)")
        if int(rg.num_rows or 0) < 0:
            raise ParquetError(
                f"{label}: row group {i} has negative num_rows")
        rows += int(rg.num_rows or 0)
        spans.append((rg, (start, end)))
    ordered = sorted(s for _rg, s in spans)
    for (_s0, e0), (s1, _e1) in zip(ordered, ordered[1:]):
        if s1 < e0:
            raise ParquetError(
                f"{label}: row group byte spans overlap "
                f"([..{e0}) vs [{s1}..))")
    if meta.num_rows is not None and int(meta.num_rows) != rows:
        raise ParquetError(
            f"{label}: footer num_rows {meta.num_rows} != row-group sum "
            f"{rows} (lying shard footer)")
    return spans


def relocate_row_group(rg, delta: int, ordinal: int):
    """A deep copy of ``rg`` with every absolute file offset shifted by
    ``delta`` and the ordinal renumbered.  Page/column index and bloom
    filter offsets are CLEARED, not shifted — the merge copies only the
    row groups' chunk spans, so bytes those offsets point at are not in
    the output."""
    out = copy.deepcopy(rg)
    out.ordinal = ordinal
    if out.file_offset is not None:
        out.file_offset += delta
    for chunk in out.columns or []:
        if chunk.file_offset is not None:
            chunk.file_offset += delta
        chunk.offset_index_offset = None
        chunk.offset_index_length = None
        chunk.column_index_offset = None
        chunk.column_index_length = None
        md = chunk.meta_data
        if md is None:
            continue
        if md.data_page_offset is not None:
            md.data_page_offset += delta
        if md.dictionary_page_offset is not None:
            md.dictionary_page_offset += delta
        md.index_page_offset = None
        md.bloom_filter_offset = None
    return out


def merge_footers(parts, *, created_by=None, kv_metadata=None):
    """The pure footer-merge: ``parts`` is ``[(FileMetaData, file_size)]``.

    Returns ``(merged FileMetaData, spans)`` where ``spans`` is the copy
    plan ``[(part_index, src_start, src_end), ...]`` in output order —
    the caller lays the output down as ``MAGIC + spans' bytes + footer``.

    Every shard is validated (:func:`validate_shard_footer`); shards after
    the first must carry a byte-identical flat schema (a column added or
    retyped between shards is a merge error, not a cast).  ``created_by``
    defaults to the shards' common value when they agree, else the
    writer's own; key-value metadata is the union in part order (later
    shards win), overridable via ``kv_metadata``.
    """
    if not parts:
        raise ParquetError("merge needs at least one input file")
    sig0 = None
    merged_rgs = []
    spans = []
    kv: dict = {}
    creators = set()
    total_rows = 0
    pos = len(MAGIC)
    version = 1
    for idx, (meta, size) in enumerate(parts):
        if not isinstance(meta, FileMetaData):
            raise ParquetError(f"part {idx}: not a parquet footer")
        sig = _schema_sig(meta)
        if not sig:
            raise ParquetError(f"part {idx}: footer has no schema elements")
        if sig0 is None:
            sig0 = sig
        elif sig != sig0:
            raise ParquetError(
                f"part {idx}: schema does not match part 0's (merge "
                f"requires byte-identical flat schemas)")
        rg_spans = validate_shard_footer(meta, size, label=f"part {idx}")
        for rg, (start, end) in rg_spans:
            delta = pos - start
            merged_rgs.append(relocate_row_group(rg, delta,
                                                 len(merged_rgs)))
            spans.append((idx, start, end))
            pos += end - start
            total_rows += int(rg.num_rows or 0)
        for pair in meta.key_value_metadata or []:
            kv[pair.key] = pair.value
        if meta.created_by:
            creators.add(meta.created_by)
        version = max(version, int(meta.version or 1))
    if kv_metadata:
        kv.update(kv_metadata)
    if created_by is None:
        from ..writer import DEFAULT_CREATED_BY

        created_by = (creators.pop() if len(creators) == 1
                      else DEFAULT_CREATED_BY)
    first_meta = parts[0][0]
    n_leaves = len(Schema.from_file_metadata(first_meta).leaves)
    merged = FileMetaData(
        version=version,
        schema=copy.deepcopy(first_meta.schema),
        num_rows=total_rows,
        row_groups=merged_rgs,
        created_by=created_by,
        key_value_metadata=[KeyValue(key=k, value=v)
                            for k, v in kv.items()] or None,
        column_orders=[ColumnOrder(TYPE_ORDER=TypeDefinedOrder())
                       for _ in range(n_leaves)],
    )
    return merged, spans


def _copy_span(src: BinaryIO, dst: BinaryIO, start: int, end: int) -> int:
    src.seek(start)
    left = end - start
    while left > 0:
        block = src.read(min(left, _COPY_BLOCK))
        if not block:
            raise ParquetError(
                f"short read copying span [{start}, {end}): file truncated "
                f"under the merge")
        dst.write(block)
        left -= len(block)
    return end - start


def merge_files(out: Union[str, os.PathLike], inputs, *, created_by=None,
                kv_metadata=None, stats=None) -> FileMetaData:
    """Merge ``inputs`` (paths) into one parquet file at ``out`` — data
    bytes relocated, never re-encoded; published atomically (temp +
    ``os.replace``).  Returns the merged footer.  ``stats`` (a
    :class:`~tpu_parquet.write.WriteStats`) books the wall into the
    ``merge`` lane."""
    from .stats import WriteStats

    st = stats if stats is not None else WriteStats()
    paths = [os.fspath(p) for p in inputs]
    if not paths:
        raise ParquetError("merge needs at least one input file")
    parts = []
    for p in paths:
        size = os.path.getsize(p)
        parts.append((read_file_metadata(p), size))
    with st.timed("merge", files=len(paths)):
        merged, spans = merge_footers(parts, created_by=created_by,
                                      kv_metadata=kv_metadata)
    out = os.fspath(out)
    tmp = f"{out}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as dst:
            dst.write(MAGIC)
            handles = {}
            try:
                with st.timed("flush"):
                    for idx, start, end in spans:
                        f = handles.get(idx)
                        if f is None:
                            f = handles[idx] = open(paths[idx], "rb")
                        _copy_span(f, dst, start, end)
                    dst.write(serialize_footer(merged))
                    dst.flush()
                    os.fsync(dst.fileno())
            finally:
                for f in handles.values():
                    f.close()
        os.replace(tmp, out)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    st.count_file(os.path.getsize(out))
    st.touch_wall()
    return merged
