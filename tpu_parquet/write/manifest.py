"""Versioned multi-file manifest: N parquet files published as ONE dataset.

The sharded writer's manifest layout and the compaction service both need a
commit point: a reader that opens the dataset mid-write (or mid-compaction)
must see either the previous complete file set or the next one — never a
half-renamed mixture.  The manifest is that commit point:

- a single JSON document (``tpq_manifest.json``) listing the member files
  with their row/byte/row-group counts, under a monotonically increasing
  **generation** number;
- written atomically (temp file in the same directory + ``fsync`` +
  ``os.replace``), so the flip from generation G to G+1 is one rename —
  POSIX guarantees readers see exactly one of the two documents;
- member files are themselves published by rename before the manifest
  flips, so every path a manifest references is complete the instant the
  manifest is visible.

Readers consume a manifest transparently: ``DataLoader(files=...)`` and
``scan_files(paths=...)`` accept a manifest path (or a directory holding
one) and expand it to the member list via :func:`expand_dataset` — one
dataset handle for the training job, however many files the writer cut.

The document is versioned and validated with the same strictness as the
loader checkpoint blob: wrong magic/version, non-monotonic or missing
fields, and absolute-path escapes are typed
:class:`~tpu_parquet.errors.ParquetError` rejections, never best-effort
parses.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Union

from ..errors import ParquetError

__all__ = ["Manifest", "ManifestEntry", "MANIFEST_NAME", "MANIFEST_VERSION",
           "write_manifest", "load_manifest", "find_manifest",
           "expand_dataset", "atomic_publish"]

MANIFEST_VERSION = 1
MANIFEST_MAGIC = "TPQM"
MANIFEST_NAME = "tpq_manifest.json"


@dataclass
class ManifestEntry:
    """One member file, path relative to the manifest's directory."""

    path: str
    rows: int
    nbytes: int
    row_groups: int

    def as_dict(self) -> dict:
        return {"path": self.path, "rows": self.rows,
                "bytes": self.nbytes, "row_groups": self.row_groups}


@dataclass
class Manifest:
    generation: int
    files: list = field(default_factory=list)  # [ManifestEntry]
    created_by: str = ""
    path: str = ""  # where it was loaded from / written to

    @property
    def total_rows(self) -> int:
        return sum(e.rows for e in self.files)

    @property
    def total_bytes(self) -> int:
        return sum(e.nbytes for e in self.files)

    def member_paths(self) -> list:
        """Member paths resolved against the manifest's own directory."""
        base = os.path.dirname(os.path.abspath(self.path))
        return [os.path.join(base, e.path) for e in self.files]

    def as_dict(self) -> dict:
        return {
            "magic": MANIFEST_MAGIC,
            "manifest_version": MANIFEST_VERSION,
            "generation": self.generation,
            "created_by": self.created_by,
            "total_rows": self.total_rows,
            "files": [e.as_dict() for e in self.files],
        }


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ParquetError(f"bad manifest: {msg}")


def load_manifest(path: Union[str, os.PathLike]) -> Manifest:
    """Load + validate a manifest document (the file itself, or a directory
    containing ``tpq_manifest.json``)."""
    path = os.fspath(path)
    if os.path.isdir(path):
        path = os.path.join(path, MANIFEST_NAME)
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise ParquetError(f"cannot read manifest {path!r}: {e}") from e
    except ValueError as e:
        raise ParquetError(f"manifest {path!r} is not JSON: {e}") from e
    _require(isinstance(doc, dict), "document is not an object")
    _require(doc.get("magic") == MANIFEST_MAGIC,
             f"magic {doc.get('magic')!r} != {MANIFEST_MAGIC!r}")
    _require(doc.get("manifest_version") == MANIFEST_VERSION,
             f"manifest_version {doc.get('manifest_version')!r} != "
             f"{MANIFEST_VERSION}")
    gen = doc.get("generation")
    _require(isinstance(gen, int) and gen >= 1,
             f"generation {gen!r} must be an int >= 1")
    files = doc.get("files")
    _require(isinstance(files, list) and files, "empty or missing file list")
    entries = []
    for i, e in enumerate(files):
        _require(isinstance(e, dict), f"files[{i}] is not an object")
        p = e.get("path")
        _require(isinstance(p, str) and p, f"files[{i}] missing path")
        _require(not os.path.isabs(p) and ".." not in p.split("/"),
                 f"files[{i}] path {p!r} escapes the dataset directory")
        for k in ("rows", "bytes", "row_groups"):
            v = e.get(k)
            _require(isinstance(v, int) and v >= 0,
                     f"files[{i}].{k} {v!r} must be a non-negative int")
        entries.append(ManifestEntry(path=p, rows=e["rows"],
                                     nbytes=e["bytes"],
                                     row_groups=e["row_groups"]))
    m = Manifest(generation=gen, files=entries,
                 created_by=str(doc.get("created_by") or ""), path=path)
    declared = doc.get("total_rows")
    if declared is not None:
        _require(declared == m.total_rows,
                 f"total_rows {declared} != member sum {m.total_rows}")
    return m


def atomic_publish(data: bytes, final_path: str) -> None:
    """Write ``data`` to ``final_path`` atomically: same-directory temp +
    ``fsync`` + ``os.replace`` — a reader sees the old document or the new
    one, never a torn one."""
    tmp = f"{final_path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final_path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_manifest(dirpath: Union[str, os.PathLike], entries,
                   generation: "int | None" = None,
                   created_by: str = "") -> Manifest:
    """Publish a manifest over ``entries`` in ``dirpath``, atomically.

    ``generation=None`` bumps the existing manifest's generation (1 for a
    fresh dataset) — the monotonic counter the plan/result caches key
    invalidation on.  An explicit ``generation`` must still move forward.
    """
    dirpath = os.fspath(dirpath)
    path = os.path.join(dirpath, MANIFEST_NAME)
    prev_gen = 0
    if os.path.exists(path):
        prev_gen = load_manifest(path).generation
    if generation is None:
        generation = prev_gen + 1
    elif generation <= prev_gen:
        raise ParquetError(
            f"manifest generation must advance: {generation} <= current "
            f"{prev_gen}")
    ents = []
    for e in entries:
        if isinstance(e, ManifestEntry):
            ents.append(e)
        else:  # a member path: stat it for the counts
            p = os.fspath(e)
            from ..footer import read_file_metadata

            md = read_file_metadata(p)
            ents.append(ManifestEntry(
                path=os.path.relpath(p, dirpath),
                rows=int(md.num_rows or 0),
                nbytes=os.path.getsize(p),
                row_groups=len(md.row_groups or [])))
    if not ents:
        raise ParquetError("manifest needs at least one member file")
    m = Manifest(generation=generation, files=ents,
                 created_by=created_by, path=path)
    doc = json.dumps(m.as_dict(), indent=1, sort_keys=True)
    atomic_publish(doc.encode("utf-8"), path)
    return m


def find_manifest(source) -> "str | None":
    """The manifest path ``source`` denotes, or None when it is a plain
    file/anything else: a path ending in the manifest name, or a directory
    containing one."""
    if not isinstance(source, (str, os.PathLike)):
        return None
    p = os.fspath(source)
    if os.path.basename(p) == MANIFEST_NAME and os.path.isfile(p):
        return p
    if os.path.isdir(p) and os.path.isfile(os.path.join(p, MANIFEST_NAME)):
        return os.path.join(p, MANIFEST_NAME)
    return None


def expand_dataset(files) -> "tuple[list, Manifest | None]":
    """Resolve a reader's ``files`` argument against the manifest contract:
    a manifest path (or a directory holding one) expands to its member
    list; a plain path or an iterable of paths passes through unchanged.
    Returns ``(paths, manifest_or_None)``."""
    if isinstance(files, (str, os.PathLike)):
        mp = find_manifest(files)
        if mp is not None:
            m = load_manifest(mp)
            return m.member_paths(), m
        return [os.fspath(files)], None
    out = []
    for f in files:
        mp = find_manifest(f)
        if mp is not None:
            m = load_manifest(mp)
            out.extend(m.member_paths())
        else:
            out.append(os.fspath(f))
    return out, None
