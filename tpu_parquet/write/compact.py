"""Compaction: many small files → few large, re-planned for cheap shipping.

A loader-output → transform → write-back workload leaves datasets shaped
like their producers: hundreds of small files with small row groups, each
paying footer/plan/open overhead per scan and defeating the ship planner's
per-chunk routes (tiny chunks never amortize an op table).  Compaction
rewrites such a dataset into few large files with large row groups:

- the output **codec is re-planned through the ship planner's cost table**
  (:class:`~tpu_parquet.ship.ShipPlanner`): per column, the modeled
  bottleneck-lane cost of shipping a snappy-paged file (the
  ``device_snappy`` route decompresses on device, shipping only the
  compressed bytes) is compared against shipping plain host bytes, using
  a measured compression-ratio sample of the actual data — so compacted
  output is cheap to ship back to the device, not just small on disk;
- **CRCs are always written** (``write_crc=True``, overriding even
  ``TPQ_WRITE_CRC=0``) so PR 8's default-on validation covers the output;
- publish is **atomic and generation-bumped**: members land by temp +
  ``os.replace``, the manifest flips last
  (:func:`~tpu_parquet.write.manifest.write_manifest`), and a
  :class:`~tpu_parquet.serve.PlanCache` passed in is notified of every
  replaced path — a reader or serve sweep running concurrently never
  sees a torn or stale dataset.

:class:`CompactionService` wraps the policy half: "compact when the
dataset has accumulated more than N undersized files", the run-once unit
a maintenance loop calls.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from ..errors import ParquetError
from ..footer import read_file_metadata
from ..format import CompressionCodec, Type
from ..schema.core import Schema
from ..ship import (ChunkFacts, EST_NARROW_SNAPPY_RATIO,
                    EST_RECOMPRESS_RATIO, ROUTE_DEVICE_SNAPPY, ROUTE_NARROW,
                    ROUTE_NARROW_SNAPPY, ROUTE_PLAIN, ROUTE_RECOMPRESS,
                    ShipPlanner, UNFUSED_OF)
from .manifest import expand_dataset
from .merge import _schema_sig
from .sharded import DEFAULT_TARGET_FILE_BYTES, write_sharded
from .stats import WriteStats

__all__ = ["compact", "CompactionReport", "CompactionService",
           "plan_codec", "modeled_link_bytes", "column_facts"]

_WIDTHS = {Type.INT32: 4, Type.INT64: 8, Type.FLOAT: 4, Type.DOUBLE: 8}
_SAMPLE_BYTES = 1 << 20


@dataclass
class CompactionReport:
    files_before: int = 0
    files_after: int = 0
    bytes_before: int = 0
    bytes_after: int = 0
    row_groups_before: int = 0
    row_groups_after: int = 0
    rows: int = 0
    codec: int = int(CompressionCodec.SNAPPY)
    link_bytes_before: int = 0
    link_bytes_after: int = 0
    manifest_path: "str | None" = None
    generation: "int | None" = None
    out_paths: list = field(default_factory=list)
    stats: "WriteStats | None" = None

    @property
    def link_bytes_ratio(self) -> float:
        """Planner-modeled shipped bytes, after/before — <1 means the
        compacted dataset is cheaper to put on the device link."""
        if not self.link_bytes_before:
            return 1.0
        return self.link_bytes_after / self.link_bytes_before

    def as_dict(self) -> dict:
        return {
            "files_before": self.files_before,
            "files_after": self.files_after,
            "bytes_before": self.bytes_before,
            "bytes_after": self.bytes_after,
            "row_groups_before": self.row_groups_before,
            "row_groups_after": self.row_groups_after,
            "rows": self.rows,
            "codec": self.codec,
            "link_bytes_before": self.link_bytes_before,
            "link_bytes_after": self.link_bytes_after,
            "link_bytes_ratio": round(self.link_bytes_ratio, 4),
            "generation": self.generation,
        }


# ---------------------------------------------------------------------------
# ship-planner replanning
# ---------------------------------------------------------------------------

def column_facts(metas, schema: Schema, leaf, *,
                 snappy_paged: bool) -> ChunkFacts:
    """Whole-DATASET ChunkFacts for one column, from the footers alone
    (``metas`` is one FileMetaData or a list of them): ``logical`` is the
    uncompressed value-stream total, ``comp_bytes`` the files' own
    compressed payload total when their pages are snappy (the
    ``device_snappy`` route's input)."""
    if not isinstance(metas, (list, tuple)):
        metas = [metas]
    logical = comp = 0
    path = leaf.path
    for meta in metas:
        for rg in meta.row_groups or []:
            for cc in rg.columns or []:
                md = cc.meta_data
                if md is None or tuple(md.path_in_schema or ()) != path:
                    continue
                logical += int(md.total_uncompressed_size or 0)
                comp += int(md.total_compressed_size or 0)
    width = _WIDTHS.get(leaf.physical_type, 0)
    return ChunkFacts(
        logical=logical,
        width=width,
        narrow_possible=width in (4, 8),
        comp_bytes=comp if snappy_paged else 0,
        host_bytes_ready=not snappy_paged,
        flat=(leaf.max_rep == 0 and leaf.max_def == 0),
    )


def modeled_link_bytes(planner: ShipPlanner, f: ChunkFacts) -> int:
    """The link bytes the planner's BEST route for ``f`` would ship — the
    per-route shipped-byte terms of :meth:`ShipPlanner.costs`, applied to
    the winning route (estimates where costs() estimates: the compressed
    routes use the same assumed ratios the ranking used)."""
    routes, _costs = planner.plan(f)
    best = UNFUSED_OF.get(routes[0], routes[0]) if routes else ROUTE_PLAIN
    L = float(f.logical)
    k = f.narrow_k
    if not k and f.narrow_possible and not f.comp_bytes:
        k = max(f.width // 2, 1)
    narrowed = L * k / f.width if (k and f.width) else L
    if best == ROUTE_NARROW:
        return int(narrowed)
    if best == ROUTE_NARROW_SNAPPY:
        return int(narrowed * EST_NARROW_SNAPPY_RATIO)
    if best == ROUTE_DEVICE_SNAPPY:
        return int(f.comp_bytes)
    if best == ROUTE_RECOMPRESS:
        return int(L * EST_RECOMPRESS_RATIO)
    return int(L)


def _sample_snappy_ratio(columns: dict) -> float:
    """Measured compression ratio over a bounded sample of the decoded
    first batch (the honest input to the codec decision — assumed ratios
    are for ranking, the codec choice gets real bytes)."""
    from ..column import ByteArrayData, ColumnData
    from ..compress import compress_block

    raw_total = comp_total = 0
    for v in columns.values():
        vals = v.values if hasattr(v, "values") else v
        if isinstance(vals, ByteArrayData):
            raw = bytes(vals.heap[:_SAMPLE_BYTES])
        elif hasattr(vals, "tobytes"):
            raw = vals.tobytes()[:_SAMPLE_BYTES]
        else:
            continue
        if not raw:
            continue
        try:
            comp = compress_block(raw, int(CompressionCodec.SNAPPY))
        except Exception:  # noqa: BLE001 — no snappy on this host
            return 1.0
        raw_total += len(raw)
        comp_total += len(comp)
    return (comp_total / raw_total) if raw_total else 1.0


def plan_codec(planner: ShipPlanner, metas, schema: Schema,
               ratio: float) -> "tuple[int, int, int]":
    """The compacted output's codec, re-planned through the ship cost
    table over the WHOLE dataset's footers (``metas``): per column,
    modeled bottleneck-lane seconds for a snappy-paged output
    (``comp_bytes`` = measured-ratio estimate) vs a plain one; the
    cheaper total wins.  Returns ``(codec, link_bytes_snappy,
    link_bytes_plain)`` — the modeled link bytes ride the report."""
    cost_snappy = cost_plain = 0.0
    link_snappy = link_plain = 0
    for leaf in schema.leaves:
        base = column_facts(metas, schema, leaf, snappy_paged=False)
        if base.logical <= 0:
            continue
        est_comp = max(int(base.logical * min(ratio, 1.0)), 1)
        fs = ChunkFacts(
            logical=base.logical, width=base.width,
            narrow_possible=base.narrow_possible, comp_bytes=est_comp,
            host_bytes_ready=False, flat=base.flat)
        cs, cp = planner.costs(fs), planner.costs(base)
        cost_snappy += min(cs.values())
        cost_plain += min(cp.values())
        link_snappy += modeled_link_bytes(planner, fs)
        link_plain += modeled_link_bytes(planner, base)
    codec = (int(CompressionCodec.SNAPPY) if cost_snappy <= cost_plain
             else int(CompressionCodec.UNCOMPRESSED))
    return codec, link_snappy, link_plain


# ---------------------------------------------------------------------------
# the compaction pass
# ---------------------------------------------------------------------------

def _batches(paths, target_rg_bytes, stats):
    """Re-batch the inputs' decoded row groups into target-sized output
    row groups (the column-layout half of replanning: many tiny groups
    in, few large groups out).  Decode runs in the consumer thread of the
    sharded writer's pool — encode overlaps it."""
    from ..reader import FileReader, _concat_column_data

    pending: "dict[str, list] | None" = None
    pending_bytes = 0

    def est_bytes(cols: dict) -> int:
        total = 0
        for cd in cols.values():
            vals = cd.values
            if hasattr(vals, "heap"):
                total += len(vals.heap) + 8 * len(vals)
            elif hasattr(vals, "nbytes"):
                total += int(vals.nbytes)
        return total

    def flush(parts: dict) -> dict:
        # ONE concat per output group: pairwise concatenation per input
        # group would copy the growing pending set O(G^2) times over —
        # exactly wrong for the many-tiny-groups workload compaction is for
        return {k: v[0] if len(v) == 1 else _concat_column_data(v)
                for k, v in parts.items()}

    for path in paths:
        with FileReader(path) as r:
            for gi in range(r.num_row_groups):
                with stats.timed("compact", file=os.path.basename(path),
                                 row_group=gi):
                    cols = r.read_row_group(gi)
                if pending is None:
                    pending = {k: [v] for k, v in cols.items()}
                else:
                    for k, v in cols.items():
                        pending[k].append(v)
                pending_bytes += est_bytes(cols)
                if pending_bytes >= target_rg_bytes:
                    yield flush(pending)
                    pending, pending_bytes = None, 0
    if pending is not None:
        yield flush(pending)


def compact(dataset, out=None, *, target_file_bytes: "int | None" = None,
            target_row_group_bytes: "int | None" = None, workers=None,
            planner: "ShipPlanner | None" = None, plan_cache=None,
            codec: "int | None" = None, remove_inputs: bool = False,
            stats: "WriteStats | None" = None) -> CompactionReport:
    """Compact ``dataset`` (a manifest path/directory, or an iterable of
    parquet paths) into few large files under ``out`` (default: the
    dataset's own directory), publishing a bumped-generation manifest.

    ``codec=None`` re-plans the output codec through ``planner``'s cost
    table (:func:`plan_codec`); CRCs are always written.  With
    ``remove_inputs=True`` superseded member files are unlinked AFTER the
    manifest flip (readers holding the previous manifest generation
    should be drained first — the default leaves them in place).
    ``plan_cache`` receives :meth:`~tpu_parquet.serve.PlanCache.
    note_mutation` for every path this pass replaces or removes.
    """
    paths, manifest = expand_dataset(dataset)
    if not paths:
        raise ParquetError("compact: empty dataset")
    if out is None:
        out = (os.path.dirname(manifest.path) if manifest is not None
               else os.path.dirname(os.path.abspath(paths[0])))
    out = os.fspath(out)
    if not os.path.isdir(out):
        raise ParquetError(f"compact: output {out!r} is not a directory")
    st = stats if stats is not None else WriteStats()
    st.touch_wall()
    target = int(target_file_bytes or DEFAULT_TARGET_FILE_BYTES)
    rg_target = int(target_row_group_bytes or min(target, 128 << 20))
    pl = planner if planner is not None else ShipPlanner()

    metas = [read_file_metadata(p) for p in paths]
    sig0 = _schema_sig(metas[0])
    for i, m in enumerate(metas[1:], 1):
        if _schema_sig(m) != sig0:
            raise ParquetError(
                f"compact: {paths[i]!r} schema does not match {paths[0]!r}")
    schema = Schema.from_file_metadata(metas[0])
    report = CompactionReport(stats=st)
    report.files_before = len(paths)
    report.bytes_before = sum(os.path.getsize(p) for p in paths)
    report.row_groups_before = sum(len(m.row_groups or []) for m in metas)

    # the planner's view of the INPUT dataset: best-route link bytes per
    # column per file, from the footers alone
    for m, p in zip(metas, paths):
        snappy_paged = all(
            int(cc.meta_data.codec or 0) == int(CompressionCodec.SNAPPY)
            for rg in (m.row_groups or []) for cc in (rg.columns or [])
            if cc.meta_data is not None)
        for leaf in schema.leaves:
            f = column_facts(m, schema, leaf, snappy_paged=snappy_paged)
            if f.logical > 0:
                report.link_bytes_before += modeled_link_bytes(pl, f)

    # codec replanning needs a measured ratio: decode the first group once
    # (cheap relative to the full pass, and the decode is re-used as the
    # sample only — the batch generator re-reads it through the reader)
    from ..reader import FileReader

    # the ratio sample comes from the first NON-EMPTY member (a valid
    # footer-only file contributes no groups and must not abort the pass)
    sample_path = next(
        (p for p, m in zip(paths, metas) if m.row_groups), None)
    if sample_path is None:
        raise ParquetError("compact: dataset has no row groups")
    with FileReader(sample_path) as r0:
        sample = r0.read_row_group(0)
    ratio = _sample_snappy_ratio(sample)
    if codec is None:
        # planned over the WHOLE dataset's footers (the first file alone
        # could be an unrepresentative runt); the ratio sample is bounded
        # to the first group by design — it feeds an estimate, the cost
        # table weighs it against every column's real byte totals
        codec, _ls, _lp = plan_codec(pl, metas, schema, ratio)
    report.codec = int(codec)

    # member names are generation-unique (write_sharded's default prefix),
    # so this pass never replaces a live generation's members — the
    # manifest flip is the only visible transition
    res = write_sharded(
        out, schema,
        _batches(paths, rg_target, st),
        workers=workers, layout="manifest", target_file_bytes=target,
        stats=st, plan_cache=plan_cache,
        codec=int(codec), write_crc=True,  # ALWAYS: the integrity tier
                                           # must cover compacted output
    )
    report.files_after = res.files
    report.bytes_after = res.bytes_written
    report.rows = res.rows
    report.row_groups_after = res.row_groups
    report.out_paths = list(res.paths)
    report.manifest_path = res.manifest_path
    report.generation = res.generation

    for p in res.paths:
        m = read_file_metadata(p)
        snappy_paged = int(codec) == int(CompressionCodec.SNAPPY)
        for leaf in schema.leaves:
            f = column_facts(m, schema, leaf, snappy_paged=snappy_paged)
            if f.logical > 0:
                report.link_bytes_after += modeled_link_bytes(pl, f)

    if remove_inputs:
        survivors = set(os.path.abspath(p) for p in res.paths)
        for p in paths:
            if os.path.abspath(p) in survivors:
                continue
            try:
                os.unlink(p)
            except OSError:
                continue
            if plan_cache is not None:
                plan_cache.note_mutation(p)
    st.touch_wall()
    return report


class CompactionService:
    """The policy half: compact a dataset when it has fragmented.

    ``run_once`` is the maintenance-loop unit: it inspects the dataset,
    and when more than ``max_small_files`` members are under
    ``min_file_bytes`` it runs one :func:`compact` pass (atomic publish,
    generation bump) and returns the report — else ``None``.  Stateless
    between calls; safe to run while readers and a serve tier sweep the
    same dataset (that concurrency is exactly the compaction contract)."""

    def __init__(self, *, min_file_bytes: int = 4 << 20,
                 max_small_files: int = 16, target_file_bytes=None,
                 workers=None, planner=None, plan_cache=None,
                 remove_inputs: bool = False):
        self.min_file_bytes = int(min_file_bytes)
        self.max_small_files = int(max_small_files)
        self.target_file_bytes = target_file_bytes
        self.workers = workers
        self.planner = planner
        self.plan_cache = plan_cache
        self.remove_inputs = remove_inputs

    def should_compact(self, dataset) -> bool:
        try:
            paths, _m = expand_dataset(dataset)
        except ParquetError:
            return False
        small = sum(1 for p in paths
                    if os.path.getsize(p) < self.min_file_bytes)
        return small > self.max_small_files

    def run_once(self, dataset, **kw) -> "CompactionReport | None":
        if not self.should_compact(dataset):
            return None
        return compact(
            dataset,
            target_file_bytes=self.target_file_bytes,
            workers=self.workers, planner=self.planner,
            plan_cache=self.plan_cache,
            remove_inputs=self.remove_inputs, **kw)
