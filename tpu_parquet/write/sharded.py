"""Sharded writer: N workers encode disjoint row groups, one footer merge.

The write-side mirror of the PR-1 read pipeline, shaped by the reference's
L4/L6 chunk-writer/file-writer split (PAPER.md §1): encoding a row group —
dictionary build, page cutting, value encoding, compression — is pure CPU
over private data, so N workers do it in parallel; laying the bytes into
the output file and owning the footer is inherently serial, so ONE
file-writer consumer does that.  The seam between them is a position-
independent encoded row group (a complete mini parquet blob), relocated
into place by the footer-merge machinery (:mod:`.merge`).

Mechanics ride the existing spine end to end:

- workers run on :func:`~tpu_parquet.pipeline.prefetch_map`'s bounded,
  ORDERED pool — results arrive in submission order, so the output file's
  row-group order is the input batch order at every worker count (the
  bit-faithfulness acceptance: N-worker output == the single-writer file);
- memory is bounded by :class:`~tpu_parquet.alloc.InFlightBudget`
  (``max_memory``): each batch's estimated bytes are acquired before
  submission and released as the file writer drains it — backpressure,
  not OOM, with stalls booked into :class:`~tpu_parquet.write.WriteStats`;
- every output is published atomically (same-directory temp + fsync +
  ``os.replace``), and the manifest layout flips its generation last, so
  a concurrent reader never sees a torn dataset;
- CRCs follow the ``TPQ_WRITE_CRC`` contract (default ON, mirroring the
  reader's default-on ``TPQ_VALIDATE``) so freshly written files are
  covered by the cheap integrity tier out of the box.

Layouts:

- ``"file"``  — one merged parquet file at ``out`` (row-group relocation
  with corrected offsets; byte-identical to a single ``FileWriter`` run
  over the same batches);
- ``"manifest"`` — ``out`` is a directory: members cut at
  ``target_file_bytes``, then a versioned manifest publish
  (:mod:`.manifest`) makes the set one dataset for ``scan_files`` /
  ``DataLoader``.
"""

from __future__ import annotations

import io
import os
from dataclasses import dataclass, field

from ..alloc import InFlightBudget
from ..column import ByteArrayData, ColumnData
from ..errors import ParquetError
from ..footer import MAGIC, read_file_metadata, serialize_footer
from ..format import ColumnOrder, FileMetaData, KeyValue, TypeDefinedOrder
from ..obs import env_int
from ..pipeline import prefetch_map
from .manifest import MANIFEST_NAME, write_manifest
from .merge import validate_shard_footer, relocate_row_group
from .stats import WriteStats

__all__ = ["write_sharded", "encode_row_group", "ShardedWriteResult",
           "resolve_write_workers", "DEFAULT_TARGET_FILE_BYTES"]

DEFAULT_TARGET_FILE_BYTES = 128 << 20


def resolve_write_workers(workers=None) -> int:
    """Worker count for the sharded encode pool: explicit argument, else
    ``TPQ_WRITE_WORKERS``, else ``min(cpu_count, 8)``."""
    if workers is not None:
        n = int(workers)
        if n < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        return n
    return env_int("TPQ_WRITE_WORKERS",
                   min(os.cpu_count() or 1, 8), lo=1)


@dataclass
class ShardedWriteResult:
    """What a sharded write produced: the published paths (one for the
    file layout), the manifest (manifest layout), and the totals."""

    paths: list = field(default_factory=list)
    manifest_path: "str | None" = None
    generation: "int | None" = None
    layout: str = "file"
    rows: int = 0
    row_groups: int = 0
    files: int = 0
    bytes_written: int = 0
    stats: "WriteStats | None" = None

    def as_dict(self) -> dict:
        return {
            "layout": self.layout, "rows": self.rows,
            "row_groups": self.row_groups, "files": self.files,
            "bytes_written": self.bytes_written,
            "generation": self.generation,
        }


def _batch_cost(batch: dict) -> int:
    """Estimated in-flight bytes of one batch: raw values + the encoded
    copy the worker materializes (budget accounting, never correctness)."""
    total = 0
    for v in batch.values():
        vals = v.values if isinstance(v, ColumnData) else v
        if isinstance(vals, ByteArrayData):
            total += int(vals.offsets[-1]) if len(vals) else 0
            total += 8 * len(vals)
        elif hasattr(vals, "nbytes"):
            total += int(vals.nbytes)
        else:
            total += 8 * len(vals)
        if isinstance(v, ColumnData):
            total += 8 * v.num_leaf_slots
    return 2 * total + 4096


def encode_row_group(schema, batch: dict, *, stats: "WriteStats | None" = None,
                     **writer_opts) -> "tuple[bytes, FileMetaData]":
    """Encode ONE batch as a complete position-independent parquet blob
    (magic + row group(s) + footer) — the sharded writer's work unit.

    Returns ``(blob, footer)``; the footer has been re-read from the blob
    through :func:`~tpu_parquet.footer.read_file_metadata`, so every
    worker's output passes the same validation a reader would apply
    before the merge trusts its offsets.
    """
    from ..writer import FileWriter

    buf = io.BytesIO()
    with FileWriter(buf, schema, stats=stats, **writer_opts) as w:
        w.write_columns(batch)
    blob = buf.getvalue()
    return blob, read_file_metadata(io.BytesIO(blob))


class _BudgetHooks:
    """The 3-method stats duck prefetch_map feeds (stall/peak/queue-depth),
    adapted onto WriteStats."""

    __slots__ = ("stats",)

    def __init__(self, stats: WriteStats):
        self.stats = stats

    def add_stall(self, seconds: float, t0=None) -> None:
        self.stats.add_stall(seconds)

    def note_peak(self, budget) -> None:
        pass

    def set_queue_depth(self, n: int) -> None:
        pass


class _FilePart:
    """One output file being laid down: MAGIC, relocated row-group spans,
    footer at close.  Writes to a same-directory temp; ``close()``
    publishes via ``os.replace`` (atomic) and returns the final size."""

    def __init__(self, final_path: str, schema, created_by: str,
                 kv_metadata: dict, stats: WriteStats):
        self.final_path = final_path
        self.tmp_path = f"{final_path}.tmp-{os.getpid()}"
        self.schema = schema
        self.created_by = created_by
        self.kv_metadata = dict(kv_metadata or {})
        self.stats = stats
        self._f = open(self.tmp_path, "wb")
        self._f.write(MAGIC)
        self.pos = len(MAGIC)
        self.row_groups: list = []
        self.rows = 0

    def append(self, blob: bytes, meta: FileMetaData) -> None:
        with self.stats.timed("merge"):
            spans = validate_shard_footer(meta, len(blob), label="shard")
        with self.stats.timed("flush", nbytes=len(blob)):
            for rg, (start, end) in spans:
                delta = self.pos - start
                self.row_groups.append(
                    relocate_row_group(rg, delta, len(self.row_groups)))
                self._f.write(blob[start:end])
                self.pos += end - start
                # row/row-group counting happened in the worker's
                # FileWriter (the encode side books the stats); the part
                # only books the file-level publish
                self.rows += int(rg.num_rows or 0)

    def close(self) -> int:
        meta = FileMetaData(
            version=1,
            schema=self.schema.to_flat_elements(),
            num_rows=self.rows,
            row_groups=self.row_groups,
            created_by=self.created_by,
            key_value_metadata=[KeyValue(key=k, value=v)
                                for k, v in self.kv_metadata.items()]
            or None,
            column_orders=[ColumnOrder(TYPE_ORDER=TypeDefinedOrder())
                           for _ in self.schema.leaves],
        )
        with self.stats.timed("flush"):
            self._f.write(serialize_footer(meta))
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
        os.replace(self.tmp_path, self.final_path)
        size = os.path.getsize(self.final_path)
        self.stats.count_file(size)
        return size

    def abort(self) -> None:
        try:
            self._f.close()
        finally:
            try:
                os.unlink(self.tmp_path)
            except OSError:
                pass


def write_sharded(out, schema, row_groups, *, workers=None, layout=None,
                  target_file_bytes: "int | None" = None,
                  max_memory: int = 0, member_prefix: "str | None" = None,
                  stats: "WriteStats | None" = None, plan_cache=None,
                  **writer_opts) -> ShardedWriteResult:
    """Write ``row_groups`` (an iterable of columnar batches — each batch
    becomes one output row group, ``FileWriter.write_columns`` shapes)
    through ``workers`` parallel encoders into ``out``.

    ``layout`` defaults to ``"manifest"`` when ``out`` is a directory,
    else ``"file"``.  ``writer_opts`` are the :class:`FileWriter` options
    (codec, page_size, write_crc, ...) applied identically by every
    worker; ``write_crc`` follows the ``TPQ_WRITE_CRC`` default-on
    contract.  ``plan_cache`` (a :class:`~tpu_parquet.serve.PlanCache`)
    is notified of every path this write REPLACES — the writer-driven
    generation bump that drops stale cached plans/results the moment the
    publish lands, instead of whenever the next footer open happens by.
    """
    from ..writer import DEFAULT_CREATED_BY, resolve_write_crc

    out = os.fspath(out)
    if layout is None:
        layout = "manifest" if os.path.isdir(out) else "file"
    if layout not in ("file", "manifest"):
        raise ValueError(f"layout must be 'file' or 'manifest', not {layout!r}")
    if layout == "manifest" and not os.path.isdir(out):
        raise ParquetError(f"manifest layout needs a directory, got {out!r}")
    n_workers = resolve_write_workers(workers)
    target = int(target_file_bytes or DEFAULT_TARGET_FILE_BYTES)
    generation = None
    if layout == "manifest":
        # the upcoming generation is fixed BEFORE any member lands so the
        # default member names are generation-unique: a re-write into a
        # live dataset directory must never os.replace the PREVIOUS
        # generation's members before the manifest flips — a reader
        # holding the old manifest would see a mixed-generation dataset
        from .manifest import load_manifest

        mpath = os.path.join(out, MANIFEST_NAME)
        prev_gen = (load_manifest(mpath).generation
                    if os.path.isfile(mpath) else 0)
        generation = prev_gen + 1
        if member_prefix is None:
            member_prefix = f"part-g{generation:04d}"
    elif member_prefix is None:
        member_prefix = "part"
    writer_opts = dict(writer_opts)
    writer_opts["write_crc"] = resolve_write_crc(writer_opts.get("write_crc"))
    created_by = writer_opts.get("created_by", DEFAULT_CREATED_BY)
    kv_metadata = writer_opts.get("kv_metadata") or {}
    st = stats if stats is not None else WriteStats()
    st.touch_wall()
    budget = InFlightBudget(max_memory)

    # fleet seam: adopt the originating request's trace context (if the
    # caller exported one across the process boundary) so encode spans
    # land in a child trace that stitches back under the parent, and arm
    # a writer-role spool snapshot (inert unless TPQ_OBS_SPOOL is set)
    from ..obs_fleet import SpoolWriter, ambient_request_trace

    tr = ambient_request_trace()

    def _spool_tree():
        from ..obs import StatsRegistry

        reg = StatsRegistry()
        reg.add_write(st)
        return reg

    spool = SpoolWriter(
        _spool_tree, role="writer",
        sampler=lambda: [tr.as_dict()] if tr is not None else [])

    def encode(batch):
        if tr is not None:
            with tr.span("encode", role="writer"):
                return encode_row_group(schema, batch, stats=st,
                                        **writer_opts)
        return encode_row_group(schema, batch, stats=st, **writer_opts)

    # prefetch == requested worker count, so the pool never exceeds it (a
    # deeper window would double the thread count behind the caller's
    # back); prefetch_map additionally caps the POOL at cpu_count (its
    # GIL-convoy guard) while keeping the window's lookahead — WriteStats
    # reports the EFFECTIVE pool size, never a count that didn't run
    st.workers = max(st.workers,
                     max(1, min(n_workers, os.cpu_count() or 1)))
    results = prefetch_map(
        row_groups, encode, prefetch=n_workers if n_workers > 1 else 0,
        budget=budget if max_memory else None,
        cost=_batch_cost if max_memory else None,
        stats=_BudgetHooks(st))

    res = ShardedWriteResult(layout=layout, stats=st)
    part: "_FilePart | None" = None
    member_paths: list = []
    replaced: list = []
    total_rows = total_rgs = 0

    def open_part(path: str) -> _FilePart:
        if os.path.exists(path):
            replaced.append(path)
        return _FilePart(path, schema, created_by, kv_metadata, st)

    spool.start()
    try:
        for blob, meta in results:
            if part is None:
                if layout == "file":
                    part = open_part(out)
                else:
                    path = os.path.join(
                        out, f"{member_prefix}-{len(member_paths):05d}"
                             ".parquet")
                    part = open_part(path)
            part.append(blob, meta)
            if layout == "manifest" and part.pos >= target:
                member_paths.append(part.final_path)
                total_rows += part.rows
                total_rgs += len(part.row_groups)
                part.close()
                part = None
        if part is None and layout == "file":
            raise ParquetError("write_sharded: no row groups to write")
        if part is not None:
            member_paths.append(part.final_path)
            total_rows += part.rows
            total_rgs += len(part.row_groups)
            part.close()
            part = None
    except BaseException:
        if part is not None:
            part.abort()
        raise
    finally:
        spool.stop()  # publishes a final generation, joins (no leak)

    res.paths = member_paths
    if layout == "manifest":
        if not member_paths:
            raise ParquetError("write_sharded: no row groups to write")
        m = write_manifest(out, member_paths, generation=generation,
                           created_by=created_by)
        res.manifest_path = os.path.join(out, MANIFEST_NAME)
        res.generation = m.generation
    if plan_cache is not None:
        for p in replaced:
            plan_cache.note_mutation(p)
    st.touch_wall()
    res.rows = total_rows
    res.row_groups = total_rgs
    res.files = len(member_paths)
    res.bytes_written = sum(os.path.getsize(p) for p in member_paths)
    return res
